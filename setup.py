"""Build a pip-installable wheel of the whole runtime (ref:
tools/pip/setup.py — the reference wheels libmxnet.so plus the python
package; here the native trio libmxtpu_io/_predict/_capi is built with
`make -C src` and bundled under ``mxnet_tpu/_native/``, where
``mxnet_tpu.libinfo.find_lib_path`` resolves it at runtime).

    python setup.py bdist_wheel          # or: pip wheel . --no-deps
    pip install dist/mxnet_tpu-*.whl
    python -c "import mxnet_tpu; print(mxnet_tpu.nd.ones((2,2)))"
"""
import os
import shutil
import subprocess

from setuptools import setup, find_packages
from setuptools.command.build_py import build_py

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "src")
NATIVE_LIBS = ["libmxtpu_io.so", "libmxtpu_predict.so", "libmxtpu_capi.so"]


class build_py_with_native(build_py):
    """Build the native libs and bundle them into the wheel."""

    def run(self):
        super().run()
        # make is incremental: always invoke it so a wheel rebuilt after
        # a src/*.cc edit never bundles stale binaries
        subprocess.run(["make", "-C", SRC], check=True)
        dest = os.path.join(self.build_lib, "mxnet_tpu", "_native")
        os.makedirs(dest, exist_ok=True)
        for n in NATIVE_LIBS:
            src_so = os.path.join(SRC, n)
            if os.path.exists(src_so):
                shutil.copy2(src_so, os.path.join(dest, n))


def _pkg_version():
    """Single source of truth: mxnet_tpu/__init__.py's __version__ (read
    textually — importing the package would pull in jax at build time)."""
    import re
    with open(os.path.join(HERE, "mxnet_tpu", "__init__.py")) as f:
        return re.search(r'__version__\s*=\s*"([^"]+)"', f.read()).group(1)


setup(
    name="mxnet_tpu",
    version=_pkg_version(),
    description="TPU-native deep learning framework with the MXNet API "
                "surface (JAX/XLA compute path, native C runtime)",
    packages=find_packages(include=["mxnet_tpu", "mxnet_tpu.*"]),
    python_requires=">=3.10",
    install_requires=["jax", "numpy"],
    cmdclass={"build_py": build_py_with_native},
    # wheels are platform-specific because of the bundled native libs
    has_ext_modules=lambda: True,
)
