"""Benchmark: ResNet-50 training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: 298.51 img/s — MXNet ResNet-50 training, batch 32 fp32, 1x V100
(BASELINE.md / docs/faq/perf.md:227-237).

TPU mapping decisions (the parts that matter for MFU):
- NHWC layout (MXTPU_BENCH_LAYOUT): channels-last is the native TPU conv
  layout — NCHW forces transposes around every convolution.
- bf16 compute (MXTPU_BENCH_DTYPE): the MXU-native dtype; f32 master
  weights (mixed precision) in SPMDTrainer.
- Fused multi-step dispatch: SPMDTrainer.run_steps scans K training steps
  inside ONE jitted program, so the ~100 ms per-execution relay/host
  overhead is paid once per K steps and XLA overlaps the weight update of
  step i with the forward of step i+1.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_IMGS_PER_SEC = 298.51


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _init_backend(timeout_s=900):
    """Initialize the JAX backend with a watchdog: if device discovery
    hangs (e.g. a wedged TPU tunnel), emit an error JSON instead of
    blocking the driver forever."""
    import threading
    result = {}

    def probe():
        try:
            import jax
            result["devices"] = jax.devices()
        except Exception as e:
            result["error"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in result:
        log(f"backend: {result['devices']}")
        return True
    err = result.get("error", f"backend init timed out after {timeout_s}s")
    print(json.dumps({"metric": "resnet50_train_imgs_per_sec", "value": 0.0,
                      "unit": "img/s", "vs_baseline": 0.0,
                      "error": str(err)[:200]}), flush=True)
    return False


def run(batch=256, k_steps=8, dtype=None, layout=None):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import SPMDTrainer

    if dtype is None:
        dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16")
    if layout is None:
        layout = os.environ.get("MXTPU_BENCH_LAYOUT", "NHWC")

    mx.random.seed(0)
    # space-to-depth stem (exact 7x7/2 reparametrization, MXU-efficient;
    # see SpaceToDepthStem + tests/test_model_zoo.py equivalence test)
    s2d = os.environ.get("MXTPU_BENCH_S2D", "1") != "0"
    net = resnet50_v1(layout=layout, stem_s2d=s2d)
    net.initialize(mx.init.Xavier())

    trainer = SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(),
                          mesh=None, optimizer="sgd",
                          optimizer_params={"learning_rate": 0.05,
                                            "momentum": 0.9},
                          dtype=jnp.bfloat16 if dtype == "bfloat16" else None)

    rs = np.random.RandomState(0)
    shape = ((k_steps, batch, 224, 224, 3) if layout == "NHWC"
             else (k_steps, batch, 3, 224, 224))
    # f32 input: it is resident on device once (the step casts to the
    # compute dtype inside the program, fused into the first conv)
    data = jnp.asarray(rs.rand(*shape).astype(np.float32))
    label = jnp.asarray(
        rs.randint(0, 1000, (k_steps, batch)).astype(np.float32))

    def sync(x):
        # Root-caused (r2): block_until_ready DOES wait on the axon relay
        # (measured ~120 ms for an 8k matmul, ~= compute + relay RTT); the
        # earlier "returns early" suspicion was relay round-trip latency
        # showing up in the subsequent fetch (~130 ms/op). A scalar fetch
        # is used here because the timed quantity must include losses
        # becoming host-visible, same as a real logging step.
        return float(np.asarray(x)[-1] if getattr(x, "ndim", 0) else x)

    log(f"compiling fused {k_steps}-step train program "
        f"(batch={batch}, {dtype}, {layout}) ...")
    t0 = time.time()
    loss_val = sync(trainer.run_steps(data, label))
    log(f"first dispatch (compile) took {time.time() - t0:.1f}s, "
        f"loss={loss_val:.3f}")
    t0 = time.time()
    sync(trainer.run_steps(data, label))
    est = (time.time() - t0) / k_steps
    # enough dispatches for a stable number within ~120s of measurement
    reps = max(1, min(5, int(120.0 / max(est * k_steps, 1e-3))))
    log(f"~{est * 1000:.1f} ms/step -> {reps} timed dispatches "
        f"of {k_steps} steps")

    t0 = time.perf_counter()
    for _ in range(reps - 1):
        trainer.run_steps(data, label)
    sync(trainer.run_steps(data, label))
    dt = time.perf_counter() - t0
    imgs_per_sec = batch * k_steps * reps / dt
    ms_step = dt / (k_steps * reps) * 1000
    # MFU accounting: ResNet-50 train ~= 3x fwd FLOPs ~= 12.3 GFLOP/img
    tflops = imgs_per_sec * 12.3e9 / 1e12
    log(f"{imgs_per_sec:.1f} img/s ({ms_step:.1f} ms/step, "
        f"~{tflops:.1f} TFLOP/s sustained)")
    return imgs_per_sec


def run_inference(batch=256, dtype=None, layout=None, reps=20):
    """Forward-only throughput (regenerates the README inference numbers:
    ref example/image-classification/benchmark_score.py)."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1

    if dtype is None:
        dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16")
    if layout is None:
        layout = os.environ.get("MXTPU_BENCH_LAYOUT", "NHWC")
    mx.random.seed(0)
    net = resnet50_v1(layout=layout,
                      stem_s2d=os.environ.get("MXTPU_BENCH_S2D", "1") != "0")
    net.initialize(mx.init.Xavier())
    net.hybridize()
    shape = ((batch, 224, 224, 3) if layout == "NHWC"
             else (batch, 3, 224, 224))
    cdt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    xf32 = mx.nd.from_jax(jnp.asarray(
        np.random.RandomState(0).rand(*shape).astype(np.float32)))
    net(xf32)  # materialize deferred-shape params before the dtype cast
    x = mx.nd.from_jax(xf32._data.astype(cdt))
    # params in compute dtype for inference
    for _, p in net.collect_params().items():
        if p._data is not None:
            p._data._rebind(p._data._data.astype(cdt))
    t0 = time.time()
    out = net(x)
    jax.block_until_ready(out._data)
    log(f"inference compile took {time.time() - t0:.1f}s")
    t0 = time.perf_counter()
    for _ in range(reps - 1):
        out = net(x)
    jax.block_until_ready(net(x)._data)
    dt = time.perf_counter() - t0
    ips = batch * reps / dt
    log(f"inference: {ips:.1f} img/s (batch {batch})")
    return ips


def _enable_compile_cache():
    """Persistent XLA compilation cache: full-graph ResNet-50 compiles
    take ~15 min through the tunnel; the cache cuts reruns to seconds."""
    from mxnet_tpu.util import enable_compile_cache
    if not enable_compile_cache():
        log("compile cache unavailable")


def main():
    if not _init_backend():
        os._exit(0)
    _enable_compile_cache()
    # batch x k_steps configs, largest first; smaller fallbacks cover
    # tighter-memory chips. k_steps amortizes dispatch overhead; batch
    # amortizes per-step fixed cost.
    # measured on one tunneled v5e chip (bf16 NHWC): 256x16 -> 2368 img/s,
    # 256x8 -> 2277, 512x8 -> 2169; chip's demonstrated matmul peak is
    # ~73 TFLOP/s, train sustains ~29 (=40% of practical peak)
    configs = os.environ.get("MXTPU_BENCH_CONFIGS",
                             "256x16,256x8,128x8,128x2")
    last_err = None
    for cfg in configs.split(","):
        batch, k = (int(v) for v in cfg.split("x"))
        try:
            value = run(batch=batch, k_steps=k)
            infer = None
            if os.environ.get("MXTPU_BENCH_INFERENCE", "1") != "0":
                try:
                    infer = round(run_inference(batch=batch), 2)
                except Exception as e:
                    log(f"inference bench failed: {e}")
            payload = {
                "metric": "resnet50_train_imgs_per_sec",
                "value": round(value, 2),
                "unit": "img/s",
                "vs_baseline": round(value / BASELINE_IMGS_PER_SEC, 3),
                "dtype": os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16"),
                "layout": os.environ.get("MXTPU_BENCH_LAYOUT", "NHWC"),
                "batch": batch,
                "fused_steps": k,
            }
            if infer:
                payload["inference_imgs_per_sec"] = infer
            print(json.dumps(payload))
            return
        except Exception as e:  # OOM or backend issue: try smaller config
            last_err = e
            log(f"config {cfg} failed: {e}")
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec",
        "value": 0.0,
        "unit": "img/s",
        "vs_baseline": 0.0,
        "error": str(last_err)[:200],
    }))


if __name__ == "__main__":
    main()
