"""Benchmark: ResNet-50 training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: 298.51 img/s — MXNet ResNet-50 training, batch 32 fp32, 1x V100
(BASELINE.md / docs/faq/perf.md:227-237).

TPU mapping decisions (the parts that matter for MFU):
- NHWC layout (MXTPU_BENCH_LAYOUT): channels-last is the native TPU conv
  layout — NCHW forces transposes around every convolution.
- bf16 compute (MXTPU_BENCH_DTYPE): the MXU-native dtype; f32 master
  weights (mixed precision) in SPMDTrainer.
- Fused multi-step dispatch: SPMDTrainer.run_steps scans K training steps
  inside ONE jitted program, so the ~100 ms per-execution relay/host
  overhead is paid once per K steps and XLA overlaps the weight update of
  step i with the forward of step i+1.
"""
import json
import os
import signal
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_IMGS_PER_SEC = 298.51
# global wall-clock default: must undercut the harness's own timeout with
# margin (BENCH_r02-r05 all died rc:124 with parsed:null because the old
# 2400 s default sat beyond it). Overridable via MXTPU_BENCH_DEADLINE_S.
DEFAULT_DEADLINE_S = 900.0


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _init_backend(timeout_s=900):
    """Initialize the JAX backend with a watchdog: if device discovery
    hangs (e.g. a wedged TPU tunnel), emit an error JSON instead of
    blocking the driver forever."""
    import threading
    result = {}

    def probe():
        try:
            import jax
            result["devices"] = jax.devices()
        except Exception as e:
            result["error"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in result:
        log(f"backend: {result['devices']}")
        return True
    err = result.get("error", f"backend init timed out after {timeout_s}s")
    print(json.dumps({"metric": "resnet50_train_imgs_per_sec", "value": 0.0,
                      "unit": "img/s", "vs_baseline": 0.0,
                      "error": str(err)[:200]}), flush=True)
    return False


def _smoke_net():
    """MXTPU_BENCH_MODEL=smoke (tests/test_bench_smoke.py): a 2-layer MLP
    that compiles in seconds on CPU, so a tiny MXTPU_BENCH_DEADLINE_S run
    still exercises the WHOLE artifact path — child subprocess, TRAIN_IPS/
    INFERENCE_IPS markers, probe EXTRA_ROWs, incremental headline JSON
    re-emission — without ResNet compile times. Shared by the train and
    inference children so both smoke models stay one model; the img/s it
    measures is meaningless as a perf signal. Returns (net, img_size)."""
    from mxnet_tpu.gluon import nn as gnn
    net = gnn.HybridSequential()  # SPMDTrainer needs a HybridBlock
    net.add(gnn.Dense(64, activation="relu"))
    net.add(gnn.Dense(1000))
    return net, 32


def run(batch=256, k_steps=8, dtype=None, layout=None, model=None):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo.vision import get_model, resnet50_v1
    from mxnet_tpu.parallel import SPMDTrainer

    if dtype is None:
        dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16")
    if layout is None:
        layout = os.environ.get("MXTPU_BENCH_LAYOUT", "NHWC")
    if model is None:
        model = os.environ.get("MXTPU_BENCH_MODEL", "resnet50_v1")

    mx.random.seed(0)
    img = 299 if "inception" in model else 224
    if model == "smoke":
        net, img = _smoke_net()
    elif model == "resnet50_v1":
        # space-to-depth stem (exact 7x7/2 reparametrization; see
        # SpaceToDepthStem + tests/test_model_zoo.py equivalence test)
        s2d = os.environ.get("MXTPU_BENCH_S2D", "1") != "0"
        net = resnet50_v1(layout=layout, stem_s2d=s2d)
    elif model.startswith("resnet"):
        net = get_model(model, layout=layout)
    else:
        layout = "NCHW"  # non-resnet zoo models are channel-first
        net = get_model(model)
    net.initialize(mx.init.Xavier())

    trainer = SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(),
                          mesh=None, optimizer="sgd",
                          optimizer_params={"learning_rate": 0.05,
                                            "momentum": 0.9},
                          dtype=jnp.bfloat16 if dtype == "bfloat16" else None)

    rs = np.random.RandomState(0)
    shape = ((k_steps, batch, img, img, 3) if layout == "NHWC"
             else (k_steps, batch, 3, img, img))
    # f32 input: it is resident on device once (the step casts to the
    # compute dtype inside the program, fused into the first conv)
    data = jnp.asarray(rs.rand(*shape).astype(np.float32))
    label = jnp.asarray(
        rs.randint(0, 1000, (k_steps, batch)).astype(np.float32))

    def sync(x):
        # Root-caused (r2): block_until_ready DOES wait on the axon relay
        # (measured ~120 ms for an 8k matmul, ~= compute + relay RTT); the
        # earlier "returns early" suspicion was relay round-trip latency
        # showing up in the subsequent fetch (~130 ms/op). A scalar fetch
        # is used here because the timed quantity must include losses
        # becoming host-visible, same as a real logging step.
        return float(np.asarray(x)[-1] if getattr(x, "ndim", 0) else x)

    log(f"compiling fused {k_steps}-step train program "
        f"(batch={batch}, {dtype}, {layout}) ...")
    t0 = time.time()
    loss_val = sync(trainer.run_steps(data, label))
    log(f"first dispatch (compile) took {time.time() - t0:.1f}s, "
        f"loss={loss_val:.3f}")
    t0 = time.time()
    sync(trainer.run_steps(data, label))
    est = (time.time() - t0) / k_steps
    # enough dispatches for a stable number within ~120s of measurement
    reps = max(1, min(5, int(120.0 / max(est * k_steps, 1e-3))))
    log(f"~{est * 1000:.1f} ms/step -> {reps} timed dispatches "
        f"of {k_steps} steps")

    t0 = time.perf_counter()
    for _ in range(reps - 1):
        trainer.run_steps(data, label)
    sync(trainer.run_steps(data, label))
    dt = time.perf_counter() - t0
    imgs_per_sec = batch * k_steps * reps / dt
    ms_step = dt / (k_steps * reps) * 1000
    # MFU accounting: ResNet-50 train ~= 3x fwd FLOPs ~= 12.3 GFLOP/img
    tflops = imgs_per_sec * 12.3e9 / 1e12
    log(f"{imgs_per_sec:.1f} img/s ({ms_step:.1f} ms/step, "
        f"~{tflops:.1f} TFLOP/s sustained)")
    return imgs_per_sec


def run_inference(batch=256, dtype=None, layout=None, k_batches=8, reps=3,
                  model=None, int8=None):
    """Forward-only throughput (regenerates the README inference numbers:
    ref example/image-classification/benchmark_score.py).

    Like training, K forward batches are fused into ONE scanned XLA
    program so the ~100 ms tunneled-dispatch overhead is amortized — the
    per-dispatch serving pattern would measure the relay, not the chip.
    MXTPU_BENCH_MODEL selects the architecture (resnet50_v1 default;
    resnet152_v1 / inceptionv3 / vgg16 / alexnet cover the other
    BASELINE.md rows — NCHW-only zoo models fall back to that layout).

    MXTPU_BENCH_INT8=1: calibrated int8 path — BN folded into convs,
    weights int8 per-channel, activations int8 between layers
    (contrib.quantization.quantize_net). The v5e MXU runs int8 conv at
    ~1.5x bf16 FLOPs and inter-layer activations at half the HBM bytes."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.cached_op import make_scan_forward
    from mxnet_tpu.gluon.model_zoo.vision import get_model, resnet50_v1

    if dtype is None:
        dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16")
    if layout is None:
        layout = os.environ.get("MXTPU_BENCH_LAYOUT", "NHWC")
    if model is None:
        model = os.environ.get("MXTPU_BENCH_MODEL", "resnet50_v1")
    mx.random.seed(0)
    img = 299 if "inception" in model else 224
    if model == "smoke":
        net, img = _smoke_net()
    elif model == "resnet50_v1":
        net = resnet50_v1(layout=layout,
                          stem_s2d=os.environ.get("MXTPU_BENCH_S2D",
                                                  "1") != "0")
    elif model.startswith("resnet"):
        net = get_model(model, layout=layout)
    else:
        layout = "NCHW"  # non-resnet zoo models are channel-first
        net = get_model(model)
    net.initialize(mx.init.Xavier())
    shape = ((batch, img, img, 3) if layout == "NHWC"
             else (batch, 3, img, img))
    cdt = jnp.bfloat16 if dtype == "bfloat16" else jnp.float32
    rs = np.random.RandomState(0)
    # materialize deferred-shape params on the HOST cpu device (fast; no
    # tunnel compile), then push the cast params to the accelerator — the
    # scanned program below is then the only remote compile
    small = (2,) + shape[1:]
    cpu = jax.devices("cpu")[0]
    with jax.default_device(cpu):
        net(mx.nd.from_jax(jnp.asarray(rs.rand(*small).astype(np.float32),
                                       device=cpu)))
    if int8 is None:
        int8 = os.environ.get("MXTPU_BENCH_INT8", "0") != "0"
    if int8:
        # fold + calibrate + rewrite ON HOST (eager per-block calls would
        # each pay the ~100 ms relay RTT on the accelerator)
        from mxnet_tpu.contrib.quantization import quantize_net
        with jax.default_device(cpu):
            calib = [jnp.asarray(
                rs.rand(*small).astype(np.float32) * 2 - 1, device=cpu)
                for _ in range(4)]
            t0 = time.time()
            net = quantize_net(net, [mx.nd.from_jax(c) for c in calib])
            log(f"quantize_net (fold+calibrate+rewrite) took "
                f"{time.time() - t0:.1f}s")
    accel = jax.devices()[0]
    # quantized blocks keep int8 weights + f32 scales/biases (tiny; the
    # dequant epilogue multiplies in f32 registers anyway) — but every
    # OTHER float param (excluded/non-quantized layers) still follows the
    # compute-dtype policy, so a partially-quantized net doesn't run
    # f32-weight x bf16-activation convs
    qids = set()
    if int8:
        from mxnet_tpu.contrib.quantization import (_QuantizedLayer,
                                                    _walk_blocks)
        for _, _, blk in _walk_blocks(net):
            if isinstance(blk, _QuantizedLayer):
                qids.update(id(p) for _, p in blk.collect_params().items())
    for _, p in net.collect_params().items():
        if p._data is not None:
            a = p._data._data
            if a.dtype == jnp.float32 and id(p) not in qids:
                a = a.astype(cdt)
            p._data._rebind(jax.device_put(a, accel))

    # cast to the compute dtype ON HOST (ml_dtypes): halves tunnel bytes
    # and avoids double residency of f32+bf16 copies on the chip
    host = rs.rand(k_batches, *shape).astype(np.float32)
    if dtype == "bfloat16":
        import ml_dtypes
        host = host.astype(ml_dtypes.bfloat16)
    xs = jax.device_put(jnp.asarray(host), accel)
    fwd_k = make_scan_forward(net)
    t0 = time.time()
    jax.block_until_ready(fwd_k(xs)._data)
    log(f"inference compile took {time.time() - t0:.1f}s")
    t0 = time.perf_counter()
    for _ in range(reps - 1):
        fwd_k(xs)
    jax.block_until_ready(fwd_k(xs)._data)
    dt = time.perf_counter() - t0
    ips = batch * k_batches * reps / dt
    log(f"inference[{model}]: {ips:.1f} img/s (batch {batch}, "
        f"{k_batches} fused)")
    return ips


def _serve_model():
    """Small shape-polymorphic CNN (conv -> global pool -> dense): cheap
    enough to serve on CPU in CI, conv-shaped enough that img/s means
    something on a real chip."""
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    mx.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3))
    net.add(gluon.nn.GlobalAvgPool2D())
    net.add(gluon.nn.Flatten())
    net.add(gluon.nn.Dense(10, in_units=8))
    net.initialize(mx.init.Xavier())
    with mx.autograd.pause():
        net(nd.ones((1, 3, 32, 32)))
    return net


def _serve_closed_loop_rps(server, item, seconds=1.0, clients=4):
    """Capacity probe: closed-loop clients hammer predict() to find the
    saturation throughput the offered-load points are scaled from."""
    import threading
    stop = time.perf_counter() + seconds
    counts = [0] * clients

    def worker(i):
        while time.perf_counter() < stop:
            try:
                server.predict(item, timeout=10)
                counts[i] += 1
            except Exception:
                pass

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return sum(counts) / seconds


def _serve_load_point(server, item, rate_rps, duration_s):
    """Open-loop offered load at ``rate_rps`` for ``duration_s``; returns
    the point's latency percentiles + achieved throughput."""
    from mxnet_tpu.serving import ServingError
    server.reset_metrics()
    futs, rejected = [], 0
    n = max(2, int(rate_rps * duration_s))
    t0 = time.perf_counter()
    for i in range(n):
        nxt = t0 + i / rate_rps
        now = time.perf_counter()
        if nxt > now:
            time.sleep(nxt - now)
        try:
            futs.append(server.submit(item))
        except ServingError:
            rejected += 1
    for f in futs:
        try:
            f.result(timeout=30)
        except ServingError:
            rejected += 1
        except Exception as e:
            # a stuck/errored future must cost one sample, not the whole
            # row — the bench's contract is "always ship a number"
            log(f"serve load point: dropped result ({e})")
            rejected += 1
    dt = time.perf_counter() - t0
    j = server.metrics_json()
    lat = j["latency_ms"]["total"]
    return {
        "offered_rps": round(rate_rps, 1),
        "duration_s": round(dt, 2),
        "throughput_rps": round(j["responses_total"] / dt, 1),
        "p50_ms": lat["p50"], "p95_ms": lat["p95"], "p99_ms": lat["p99"],
        "rejected": rejected,
        "batches": j["batches_total"],
        "mean_batch": j["batch_size"]["mean"],
    }


def run_serve():
    """The `serve` row: dynamic-batching ModelServer under an offered-load
    sweep (>=2 points scaled off a measured capacity probe). One JSON
    line: p50/p95/p99 end-to-end latency + achieved img/s per point.
    Respects MXTPU_BENCH_DEADLINE_S like every other row."""
    import numpy as np
    if not _init_backend():
        return
    _enable_compile_cache()
    from mxnet_tpu.serving import ModelServer
    shape = (3, 32, 32)
    # batching knobs come from the declared MXTPU_SERVE_* env defaults —
    # one source of truth with a default-configured ModelServer
    net = _serve_model()
    server = ModelServer(net, bucket_shapes=[shape],
                         name="bench_cnn32")
    server.start()
    t0 = time.time()
    compiles = server.warmup()
    log(f"serve warmup: {compiles} signatures compiled "
        f"in {time.time() - t0:.1f}s")
    rs = np.random.RandomState(0)
    item = rs.rand(*shape).astype(np.float32)
    # floor at 0.5s: a warmup that ate the whole deadline budget must not
    # drive the probe window to <= 0 (negative/div-zero capacity)
    cap = _serve_closed_loop_rps(server, item,
                                 seconds=min(2.0, max(0.5,
                                                      _budget_left() / 8)))
    log(f"serve capacity probe: {cap:.0f} req/s closed-loop")
    fractions = [float(v) for v in os.environ.get(
        "MXTPU_BENCH_SERVE_LOADS", "0.5,0.8").split(",")]
    per_point_s = float(os.environ.get("MXTPU_BENCH_SERVE_SECONDS", "5"))
    points = []
    for frac in fractions:
        budget = _budget_left() - 30
        if budget < 1.0 and points:
            log(f"serve: budget exhausted after {len(points)} points")
            break
        rate = max(1.0, cap * frac)
        pt = _serve_load_point(server, item, rate,
                               min(per_point_s, max(1.0, budget)))
        pt["load_fraction"] = frac
        log(f"serve @{frac:.0%} capacity ({rate:.0f} rps offered): "
            f"p50={pt['p50_ms']}ms p99={pt['p99_ms']}ms "
            f"-> {pt['throughput_rps']} img/s")
        points.append(pt)
    top = points[-1]
    # the row ships BEFORE the drain: a wedged worker making stop() time
    # out must not throw away already-measured points
    payload = {
        "metric": "serve_p99_latency_ms",
        "value": top["p99_ms"],
        "unit": "ms",
        "imgs_per_sec": top["throughput_rps"],
        "capacity_rps": round(cap, 1),
        "compiled_signatures": compiles,
        "max_batch": server.max_batch_size,
        "points": points,
    }
    print(json.dumps(payload), flush=True)
    try:
        server.stop(drain=True)
    except Exception as e:
        log(f"serve: drain after row emission failed: {e}")
    if os.environ.get("MXTPU_BENCH_SERVE_COLD_START", "1") != "0":
        # registry cold-start probe rides after the load sweep; the row
        # above already shipped, so a probe failure costs nothing — a
        # success re-emits the extended row (the incremental convention)
        try:
            extra = _serve_cold_start_probe(net, shape)
            if extra:
                payload.update(extra)
                print(json.dumps(payload), flush=True)
        except Exception as e:
            log(f"serve cold-start probe abandoned: {e}")


def run_serve_cold(registry_root, model):
    """Child mode for the cold-start probe: fresh process, resolve the
    model from the registry, warm (honoring MXTPU_COMPILE_CACHE), serve
    ONE request. Emits 'SERVE_COLD {json}' with first_response_s plus the
    telemetry compile counters — the zero-compile-cold-start evidence."""
    t0 = time.perf_counter()
    if not _init_backend():
        return
    import numpy as np
    from mxnet_tpu.serving import FleetServer, ModelRegistry
    from mxnet_tpu.telemetry import default_registry
    default_registry()  # install XLA compile listeners BEFORE any compile
    server = FleetServer(ModelRegistry(registry_root), model,
                         workers=1).start()
    shape = sorted(server._table.bucket_shapes)[0]
    server.predict(np.zeros(shape, server.dtype), timeout=120)
    first = time.perf_counter() - t0
    j = default_registry().render_json()
    print("SERVE_COLD " + json.dumps({
        "first_response_s": round(first, 3),
        "xla_compiles": j.get("mxtpu_xla_compile_total", 0),
        "xla_compile_s": round(j.get("mxtpu_xla_compile_seconds_total",
                                     0.0), 3),
        "xla_cache_hits": j.get("mxtpu_xla_cache_hits_total", 0),
    }), flush=True)
    server.stop(drain=True)


def _serve_cold_start_probe(net, shape):
    """cold_start_s / warm_start_s for the serve row: publish the serve
    model to a scratch registry, then cold-start it in two fresh
    processes — first with an EMPTY persistent compile cache (pays the
    full XLA bill and populates the cache), then against the populated
    cache (the fleet's restart path: compiles become disk reads)."""
    import shutil
    import tempfile
    tmp = tempfile.mkdtemp(prefix="bench_serve_registry_")
    try:
        return _serve_cold_start_children(net, shape, tmp)
    finally:
        # the scratch registry + populated compile cache are tens of MB;
        # a long-lived bench host must not accumulate one per run
        shutil.rmtree(tmp, ignore_errors=True)


def _serve_cold_start_children(net, shape, tmp):
    import subprocess
    out = {}
    from mxnet_tpu.serving import ModelRegistry
    ModelRegistry(os.path.join(tmp, "registry")).publish(
        "bench_cnn32", net=net,
        signature={"bucket_shapes": [list(shape)], "dtype": "float32"})
    cache_dir = os.path.join(tmp, "compile_cache")
    # cold child = empty cache (full XLA bill; populates the cache on the
    # way), warm child = same model against the populated cache — the
    # replica-restart path. The delta IS the compile tax a registry-driven
    # fleet stops paying.
    for label, cache in (("cold_start", cache_dir),
                         ("warm_start", cache_dir)):
        budget = _budget_left() - 20
        if budget < 30:
            log(f"serve {label}: skipped ({_budget_left():.0f}s budget "
                "left)")
            break
        env = dict(os.environ, MXTPU_COMPILE_CACHE=cache)
        try:
            res = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "serve-cold",
                 os.path.join(tmp, "registry"), "bench_cnn32"],
                capture_output=True, text=True, timeout=budget, env=env)
        except subprocess.TimeoutExpired:
            log(f"serve {label}: child timed out")
            if label == "cold_start":
                break  # a 'warm' run after a partial cold pass would
            continue   # report cold compiles as the warm number
        row = None
        for line in (res.stdout or "").splitlines():
            if line.startswith("SERVE_COLD "):
                try:
                    row = json.loads(line[len("SERVE_COLD "):])
                except ValueError:
                    pass
        if row is None:
            log(f"serve {label}: child rc={res.returncode}: "
                f"{(res.stderr or '')[-300:]}")
            if label == "cold_start":
                break  # warm is only defined relative to a completed cold
            continue
        log(f"serve {label}: first response in {row['first_response_s']}s "
            f"({row['xla_compiles']} compiles, {row['xla_compile_s']}s; "
            f"{row['xla_cache_hits']} cache hits)")
        if label == "cold_start":
            out["cold_start_s"] = row["first_response_s"]
            out["cold_start_compile_s"] = row["xla_compile_s"]
        elif label == "warm_start":
            out["warm_start_s"] = row["first_response_s"]
            out["warm_start_compile_s"] = row["xla_compile_s"]
    return out


def _enable_compile_cache():
    """Persistent XLA compilation cache: full-graph ResNet-50 compiles
    take ~15 min through the tunnel; the cache cuts reruns to seconds."""
    from mxnet_tpu.util import enable_compile_cache
    if not enable_compile_cache():
        log("compile cache unavailable")


def _dispatch_probe(n_params=50):
    """Per-step optimizer-dispatch counts with aggregation on vs off.

    A 50-tensor synthetic parameter set (the regime the aggregated path
    targets: many small tensors) is stepped once per mode through the
    gluon Trainer; `last_update_dispatches` counts compiled-call launches
    — O(buckets) aggregated, O(params) per-param. Recorded into the
    headline JSON so the trajectory catches launch-count regressions."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import nd, gluon
    from mxnet_tpu.optimizer import grouped as _grouped

    rs = np.random.RandomState(0)

    def one_mode(agg):
        os.environ["MXTPU_OPTIMIZER_AGGREGATION"] = str(agg)
        try:
            params = []
            for j in range(n_params):
                p = gluon.Parameter(f"bench_p{j}", shape=(16, 4))
                p.initialize(mx.init.Constant(0.0))
                p.set_data(nd.array(rs.randn(16, 4).astype(np.float32)))
                params.append(p)
            tr = gluon.Trainer(params, "sgd",
                               {"learning_rate": 0.1, "momentum": 0.9},
                               kvstore=None)
            for p in params:
                p._grad._rebind(nd.array(
                    rs.randn(16, 4).astype(np.float32))._data)
                p._fresh_grad = True
            tr.step(32)
            return tr.last_update_dispatches
        finally:
            os.environ.pop("MXTPU_OPTIMIZER_AGGREGATION", None)

    agg_size = _grouped.aggregation_size()
    aggregated = one_mode(agg_size if agg_size > 0 else 4)
    per_param = one_mode(0)
    return {"params": n_params, "agg_size": agg_size,
            "aggregated_dispatches": aggregated,
            "per_param_dispatches": per_param,
            "dispatch_reduction": round(per_param / max(1, aggregated), 2)}


def _step_breakdown_probe(steps=4, batch=64):
    """Segment shares of a short instrumented FitLoop run (telemetry
    subsystem): where does the step time go — data_wait / h2d / compute /
    optimizer / comm — folded into the headline JSON so the segment
    shares become part of the perf trajectory (an input pipeline
    regression shows up as a data_wait share jump even when img/s only
    drifts)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, io as mxio, telemetry
    from mxnet_tpu.fit import FitLoop
    from mxnet_tpu.io.staging import DeviceStagingIter

    rs = np.random.RandomState(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(64, activation="relu"), gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    data = rs.randn(steps * batch, 32).astype(np.float32)
    label = rs.randint(0, 8, (steps * batch,)).astype(np.float32)
    train_iter = DeviceStagingIter(
        mxio.NDArrayIter(data, label, batch_size=batch))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    was_on = telemetry.tracer.enabled  # MXTPU_PROFILE may have it on
    telemetry.enable()
    try:
        loop = FitLoop(net, trainer, loss_fn, train_iter, ckpt_dir=None)
        result = loop.fit(epochs=1)
    finally:
        if not was_on:
            telemetry.disable()
    summary = result.step_breakdown or {}
    return {"steps": summary.get("steps", 0),
            "mean_step_s": summary.get("mean_step_s", 0.0),
            "shares": summary.get("shares", {}),
            "accounted_frac": summary.get("accounted_frac", 0.0),
            "diagnoses": summary.get("diagnoses", [])[:3]}


def _autotune_probe(steps=30, batch=32, width=64, n_layers=6):
    """The `autotune` row: does the telemetry-driven tuner actually move
    the needle it watches? A deliberately comm-heavy FitLoop (kv_slow
    chaos injects a deterministic per-collective wire delay, so the comm
    segment dominates even on a laptop CPU run) is trained twice —
    untuned, then with MXTPU_AUTOTUNE on — and the row records the
    chosen knobs plus the before/after exclusive comm-segment share, so
    the perf trajectory catches a tuner that stops choosing (or a chosen
    knob that stops helping)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, io as mxio
    from mxnet_tpu import kvstore as kvs
    from mxnet_tpu.contrib import chaos
    from mxnet_tpu.fit import FitLoop

    def seg_share(recs, *names):
        wall = sum(r.get("wall", 0.0) for r in recs)
        c = sum(r.get(n, 0.0) for n in names for r in recs)
        return round(c / wall, 4) if wall > 0 else 0.0

    def one_run(autotune_spec):
        mx.random.seed(0)
        rs = np.random.RandomState(0)
        net = gluon.nn.Sequential()
        for _ in range(n_layers):  # several grads -> several buckets
            net.add(gluon.nn.Dense(width, activation="relu"))
        net.add(gluon.nn.Dense(8))
        net.initialize(mx.init.Xavier())
        data = rs.randn(steps * batch, width).astype(np.float32)
        label = rs.randint(0, 8, (steps * batch,)).astype(np.float32)
        it = mxio.NDArrayIter(data, label, batch_size=batch)
        # an explicit store OBJECT: the "device" string degrades to no
        # store at all on a 1-device host (direct updates add nothing),
        # and with no store there are no collectives to slow down, hide,
        # or tune — the whole probe would measure an empty comm segment
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.01},
                                kvstore=kvs.create("device"))
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        old = os.environ.get("MXTPU_AUTOTUNE")
        if autotune_spec is None:
            os.environ.pop("MXTPU_AUTOTUNE", None)
        else:
            os.environ["MXTPU_AUTOTUNE"] = autotune_spec
        chaos.install("kv_slow@3")  # 3 ms per collective, every attempt
        try:
            result = FitLoop(net, trainer, loss_fn, it,
                             ckpt_dir=None).fit(epochs=1)
        finally:
            chaos.uninstall()
            if old is None:
                os.environ.pop("MXTPU_AUTOTUNE", None)
            else:
                os.environ["MXTPU_AUTOTUNE"] = old
        return result

    before = one_run(None)
    after = one_run("on,probe=2,warmup=1")
    report = after.tuning_report or {}
    recs = (after.step_breakdown or {}).get("per_step", [])
    locked_at = report.get("locked_at_step")
    # post-lock steps only: probing deliberately visits bad configs, and
    # the row's claim is about the configuration the tuner LOCKED. The
    # lock fires at the END of step `locked_at` (that step still ran
    # under the final candidate's knobs) — the locked config owns
    # locked_at+1 onward. `is not None`, not truthiness: a lock at step
    # 0 (nothing to vary) still counts, and never-locked keeps all steps
    post = recs[locked_at + 1:] if locked_at is not None else recs
    pre = (before.step_breakdown or {}).get("per_step", [])
    return {
        "steps": steps,
        "status": report.get("status"),
        "locked_at_step": locked_at,
        "baseline": report.get("baseline", {}),
        "chosen": report.get("chosen", {}),
        # exposed comm = the post-backward barrier segment the overlap
        # scheduler exists to hide; the overlapped share is reported
        # alongside so the hidden time stays visible
        "comm_share_before": seg_share(pre, "comm"),
        "comm_share_after": seg_share(post, "comm"),
        "comm_overlapped_share_after": seg_share(post, "comm_overlapped"),
        "probe_candidates": len(report.get("candidates", [])),
    }


def _memory_probe(steps=4, batch=32, width=64):
    """The `memory` row: device-byte attribution of a small train model —
    params / grads / optimizer-state / f32-masters / grad-bucket bytes
    from the live ledger (exact by construction), per-program temp bytes
    from the static XLA memory_analysis, and the per-step ledger peak —
    the numbers a ZeRO-1 sharded-optimizer change will be graded on
    (optimizer+masters bytes must drop ~Nx, everything else flat)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, io as mxio
    from mxnet_tpu import kvstore as kvs
    from mxnet_tpu.fit import FitLoop
    from mxnet_tpu.io.staging import DeviceStagingIter
    from mxnet_tpu.optimizer import grouped
    from mxnet_tpu.telemetry import memory as mem

    import gc
    gc.collect()  # earlier probes' cyclic garbage must die BEFORE the
    # baseline, or its ledger bytes subtract from this probe's deltas
    led = mem.ledger()
    base = {c: led.live_bytes(c) for c in mem.CATEGORIES}
    mx.random.seed(0)
    rs = np.random.RandomState(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(width, activation="relu"),
            gluon.nn.Dense(width, activation="relu"),
            gluon.nn.Dense(8))
    net.initialize(mx.init.Xavier())
    data = rs.randn(steps * batch, width).astype(np.float32)
    label = rs.randint(0, 8, (steps * batch,)).astype(np.float32)
    it = DeviceStagingIter(mxio.NDArrayIter(data, label, batch_size=batch))
    # explicit store object so the _gbkt bucket path runs on a 1-device
    # host (the "device" string degrades to no store — see _autotune_probe)
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 1e-3},
                            kvstore=kvs.create("device"))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    result = FitLoop(net, trainer, loss_fn, it, ckpt_dir=None).fit(epochs=1)
    # masters: one aggregated multi_precision step over bf16 params (a
    # full low-precision FitLoop is not what this row measures)
    mp_params = [gluon.Parameter(f"membench_mp{i}", shape=(width,),
                                 dtype="bfloat16") for i in range(4)]
    for p in mp_params:
        p.initialize(mx.init.One())
    mp_tr = gluon.Trainer(mp_params, "adam",
                          {"learning_rate": 1e-3, "multi_precision": True},
                          kvstore=None)
    for p in mp_params:
        p.grad()._rebind(mx.nd.ones(p.shape, dtype="bfloat16")._data)
        p._fresh_grad = True
    mp_tr.update(1)
    progs = grouped.program_memory()
    delta = {c: led.live_bytes(c) - base[c] for c in mem.CATEGORIES}
    mem_sum = result.memory or {}
    return {
        "params_bytes": delta["params"],
        "grads_bytes": delta["grads"],
        "optimizer_bytes": delta["optimizer"],
        "masters_bytes": delta["masters"],
        "grad_bucket_bytes": delta["grad_buckets"],
        "program_temp_bytes": sum(int(s.get("temp_bytes", 0))
                                  for s in progs.values()),
        "programs": len(progs),
        "step_peak_bytes": int(mem_sum.get("peak_bytes", 0)),
        "live_total_bytes": led.live_bytes(),
    }


def _zero_probe(steps=3, width=64, n_params=8, world=4):
    """The `zero` row: ledger-measured `optimizer`+`masters` bytes and
    step time, unsharded vs ``MXTPU_ZERO=1`` at ``world`` simulated ranks
    — the mp-Adam probe the ZeRO-1 subsystem is graded on. Equal-sized
    bf16 params make the greedy partition exact, so the per-rank bytes
    must land at 1/world of the unsharded baseline (the ledger is exact
    by construction on CPU)."""
    import gc
    import time

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu import kvstore as kvs
    from mxnet_tpu.telemetry import memory as mem

    led = mem.ledger()
    saved = {k: os.environ.get(k) for k in ("MXTPU_ZERO",
                                            "MXTPU_ZERO_WORLD")}

    def one(zero):
        for k in saved:
            os.environ.pop(k, None)
        if zero:
            os.environ["MXTPU_ZERO"] = "1"
            os.environ["MXTPU_ZERO_WORLD"] = str(world)
        gc.collect()  # earlier probes' garbage must not skew the deltas
        tag = "zbz" if zero else "zbu"
        rs = np.random.RandomState(0)
        params = []
        for i in range(n_params):
            p = gluon.Parameter(f"{tag}{i}", shape=(width, width),
                                dtype="bfloat16")
            p.initialize(mx.init.One())
            params.append(p)
        tr = gluon.Trainer(params, "adam",
                           {"learning_rate": 1e-3,
                            "multi_precision": True},
                           kvstore=kvs.create("device"))

        def setg():
            for p in params:
                g = nd.array(rs.randn(width, width).astype(np.float32))
                p._grad._rebind(g.astype("bfloat16")._data)
                p._fresh_grad = True

        setg()
        tr.step(4)  # compile + state creation outside the timed window
        t0 = time.perf_counter()
        for _ in range(steps):
            setg()
            tr.step(4)
        step_ms = (time.perf_counter() - t0) / steps * 1e3
        # shard-aware owners make per-rank bytes a queryable prefix
        total = sum(
            led.live_bytes(c, owner_prefix=pref) for c, pref in
            (("optimizer", f"state:{tag}"), ("masters", f"master:{tag}")))
        rank0 = None
        if zero:
            total = sum(
                led.live_bytes(c, owner_prefix=f"{o}:zr{r}/{world}:{tag}")
                for r in range(world)
                for c, o in (("optimizer", "state"), ("masters",
                                                     "master")))
            rank0 = sum(
                led.live_bytes(c, owner_prefix=f"{o}:zr0/{world}:{tag}")
                for c, o in (("optimizer", "state"), ("masters",
                                                     "master")))
        row = {"opt_masters_bytes": int(total), "step_ms": step_ms,
               "rank0_bytes": rank0,
               "collectives": (tr.last_reduce_scatter_collectives +
                               tr.last_allgather_collectives) if zero
               else tr.last_allreduce_collectives}
        return row

    try:
        unsharded = one(False)
        sharded = one(True)
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v
    ratio = (sharded["rank0_bytes"] / unsharded["opt_masters_bytes"]
             if unsharded["opt_masters_bytes"] else 0.0)
    return {
        "world": world,
        "unsharded_opt_masters_bytes": unsharded["opt_masters_bytes"],
        "zero_total_opt_masters_bytes": sharded["opt_masters_bytes"],
        "zero_rank0_opt_masters_bytes": sharded["rank0_bytes"],
        "rank0_share": round(ratio, 4),
        "step_ms_unsharded": round(unsharded["step_ms"], 2),
        "step_ms_zero": round(sharded["step_ms"], 2),
        "zero_collectives_per_step": sharded["collectives"],
    }


def _zero_overlap_probe(steps=8, batch=16, width=32, world=2):
    """The `zero_overlap` row: overlapped vs barrier ZeRO-1 on the same
    non-hybridized FitLoop workload — with ``MXTPU_COMM_OVERLAP=on`` the
    grad-finality reduce-scatter and the allgather prefetch move the
    collective launches into the ``comm_overlapped`` breakdown segment,
    so the EXPOSED ``comm`` share of step time must strictly drop while
    MFU holds (the attribution move is what the overlap work is graded
    on; the trajectory itself is bitwise-pinned by tests/test_zero_overlap
    .py). Tiny ``MXTPU_GRAD_BUCKET_MB`` forces several ragged buckets so
    the tiled psum_scatter path and per-bucket launches are exercised."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, io as mxio
    from mxnet_tpu import kvstore as kvs
    from mxnet_tpu.fit import FitLoop

    saved = {k: os.environ.get(k) for k in
             ("MXTPU_ZERO", "MXTPU_ZERO_WORLD", "MXTPU_COMM_OVERLAP",
              "MXTPU_GRAD_BUCKET_MB", "MXTPU_OPTIMIZER_AGGREGATION",
              "MXTPU_EFFICIENCY")}

    def one(overlap):
        os.environ["MXTPU_ZERO"] = "1"
        os.environ["MXTPU_ZERO_WORLD"] = str(world)
        os.environ["MXTPU_COMM_OVERLAP"] = "on" if overlap else "off"
        # ~0.002 MB buckets -> several ragged buckets per step, so the
        # per-bucket launch points (not one monolithic flat) are measured
        os.environ["MXTPU_GRAD_BUCKET_MB"] = "0.002"
        os.environ["MXTPU_OPTIMIZER_AGGREGATION"] = "8"
        os.environ["MXTPU_EFFICIENCY"] = "on"
        mx.random.seed(0)
        rs = np.random.RandomState(0)
        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(width, activation="relu"),
                gluon.nn.Dense(8))
        net.initialize(mx.init.Xavier())
        # NOT hybridized: the tape backward fires per-grad finality
        # hooks; a whole-graph CachedOp backward would degrade to the
        # finalize barrier and measure nothing
        data = rs.randn(steps * batch, width).astype(np.float32)
        label = rs.randn(steps * batch, 8).astype(np.float32)
        it = mxio.NDArrayIter(data, label, batch_size=batch)
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-3},
                           kvstore=kvs.create("device"))
        loop = FitLoop(net, tr, lambda out, y: ((out - y) ** 2).mean(),
                       it, ckpt_dir=None)
        res = loop.fit(epochs=1)
        bd = res.step_breakdown or {}
        shares = bd.get("shares") or {}
        eff = res.efficiency or {}
        return {
            "step_ms": round(float(bd.get("mean_step_s", 0.0)) * 1e3, 3),
            "comm_share": float(shares.get("comm", 0.0)),
            "comm_overlapped_share": float(
                shares.get("comm_overlapped", 0.0)),
            "mfu": float(eff.get("mfu", 0.0)),
            "collectives": (tr.last_reduce_scatter_collectives +
                            tr.last_allgather_collectives),
        }

    try:
        one(False), one(True)              # warm both legs' programs
        barrier = one(False)
        overlapped = one(True)
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v
    return {
        "world": world,
        "step_ms_barrier": barrier["step_ms"],
        "step_ms_overlap": overlapped["step_ms"],
        "exposed_comm_share_barrier": barrier["comm_share"],
        "exposed_comm_share_overlap": overlapped["comm_share"],
        "comm_overlapped_share": overlapped["comm_overlapped_share"],
        "total_comm_share_overlap": round(
            overlapped["comm_share"] +
            overlapped["comm_overlapped_share"], 4),
        "mfu_barrier": barrier["mfu"],
        "mfu_overlap": overlapped["mfu"],
        "collectives_per_step": overlapped["collectives"],
    }


def _megastep_probe(steps=8, batch=16, width=32):
    """The `megastep` row: ``MXTPU_MEGASTEP=on`` vs the composed path on
    the same non-hybridized FitLoop workload. The fused leg traces
    forward + backward + sentinel + grouped update into ONE jitted
    donated-buffer program, so a warm step is a single dispatch; the row
    carries warm steps/s and MFU for both legs plus the two structural
    pins: ``parity`` (the loss trajectories are bitwise EQUAL — the
    fused program is the composed step's kernels minus the dispatches,
    see tests/test_megastep.py for the full 6-optimizer matrix) and
    ``unattributed_dispatches == 0`` (the one noted program resolves its
    own cost). steps/s uses the warm median: the fused leg pays one cold
    trace per fresh net, and the median is the number the knob is sold
    on."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, io as mxio
    from mxnet_tpu.fit import FitLoop
    from mxnet_tpu.telemetry import efficiency as eff

    saved = {k: os.environ.get(k) for k in
             ("MXTPU_MEGASTEP", "MXTPU_OPTIMIZER_AGGREGATION",
              "MXTPU_EFFICIENCY", "MXTPU_ZERO", "MXTPU_ZERO_WORLD",
              "MXTPU_COMM_OVERLAP")}

    def one(mega):
        os.environ["MXTPU_MEGASTEP"] = "on" if mega else "off"
        os.environ["MXTPU_OPTIMIZER_AGGREGATION"] = "8"
        os.environ["MXTPU_EFFICIENCY"] = "on"
        for k in ("MXTPU_ZERO", "MXTPU_ZERO_WORLD", "MXTPU_COMM_OVERLAP"):
            os.environ.pop(k, None)
        mx.random.seed(0)
        rs = np.random.RandomState(0)
        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(width, activation="relu"),
                gluon.nn.Dense(8))
        net.initialize(mx.init.Xavier())
        data = rs.randn(steps * batch, width).astype(np.float32)
        label = rs.randn(steps * batch, 8).astype(np.float32)
        it = mxio.NDArrayIter(data, label, batch_size=batch)
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-3})
        loop = FitLoop(net, tr, lambda out, y: ((out - y) ** 2).mean(),
                       it, ckpt_dir=None)
        res = loop.fit(epochs=1)
        bd = res.step_breakdown or {}
        e = res.efficiency or {}
        warm = sorted(rec.get("wall", 0.0)
                      for rec in (bd.get("per_step") or [])[1:])
        p50_s = warm[len(warm) // 2] if warm else 0.0
        recs = [r for r in eff.rollup().recent if r.get("step", 0) >= 1]
        return {
            "losses": list(res.losses),
            "p50_s": p50_s,
            "mfu": float(e.get("mfu", 0.0)),
            "flops_per_step": float(e.get("flops_per_step", 0.0)),
            "unattributed": int(e.get("unattributed_dispatches", -1)),
            "warm_dispatches": (max(r.get("dispatches", 0) for r in recs)
                                if recs else -1),
        }

    try:
        one(False), one(True)              # warm both legs' programs
        composed = one(False)
        fused = one(True)
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v
    return {
        "parity": composed["losses"] == fused["losses"],
        "steps_per_s_composed": round(
            1.0 / composed["p50_s"], 2) if composed["p50_s"] else 0.0,
        "steps_per_s_megastep": round(
            1.0 / fused["p50_s"], 2) if fused["p50_s"] else 0.0,
        "mfu_composed": composed["mfu"],
        "mfu_megastep": fused["mfu"],
        "flops_per_step_composed": composed["flops_per_step"],
        "flops_per_step_megastep": fused["flops_per_step"],
        "warm_dispatches_per_step": fused["warm_dispatches"],
        "unattributed_dispatches": fused["unattributed"],
    }


def _comm_health_probe(steps=3, width=32, n_params=8, world=4):
    """The `comm_health` row: the collective-observability plane over a
    simulated N-rank ZeRO run — ledger depth, max cross-rank collective
    skew (0 in simulation: one process plays every rank on one clock)
    and the watchdog count, which MUST be 0 on a clean run (a fired
    watchdog here means the plane false-positives on healthy traffic)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, nd
    from mxnet_tpu import kvstore as kvs
    from mxnet_tpu.telemetry import collective as coll

    saved = {k: os.environ.get(k) for k in
             ("MXTPU_ZERO", "MXTPU_ZERO_WORLD", "MXTPU_COLL_HEALTH",
              "MXTPU_COLL_TIMEOUT_S")}
    os.environ["MXTPU_ZERO"] = "1"
    os.environ["MXTPU_ZERO_WORLD"] = str(world)
    os.environ["MXTPU_COLL_HEALTH"] = "1"
    # arm the watchdog with a generous timeout: the row proves clean
    # traffic fires ZERO flight records WITH the watchdog running
    os.environ["MXTPU_COLL_TIMEOUT_S"] = "30"
    fired_before = coll.ledger.watchdog_fired
    depth_before = coll.ledger.depth()
    try:
        rs = np.random.RandomState(0)
        params = []
        for i in range(n_params):
            p = gluon.Parameter(f"ch{i}", shape=(width, width))
            p.initialize(mx.init.One())
            params.append(p)
        tr = gluon.Trainer(params, "adam", {"learning_rate": 1e-3},
                           kvstore=kvs.create("device"))
        for _ in range(steps):
            for p in params:
                g = nd.array(rs.randn(width, width).astype(np.float32))
                p._grad._rebind(g._data)
                p._fresh_grad = True
            tr.step(4)
        health = coll.health_check(tr._kvstore)
        collectives = (tr.last_reduce_scatter_collectives +
                       tr.last_allgather_collectives)
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v
    return {
        "world": world,
        "max_coll_skew_ms": round(float(health["max_skew_ms"]), 3),
        "straggler_rank": health["straggler_rank"],
        "desync": health["desync"],
        "ledger_depth": coll.ledger.depth() - depth_before,
        "watchdog_fired": coll.ledger.watchdog_fired - fired_before,
        "collectives_per_step": collectives,
    }


def _numerics_probe(steps=6, batch=32, width=64):
    """The `numerics` row: the in-graph tensor-stats plane over a short
    instrumented FitLoop — the global gradient norm and update ratio a
    transformer recipe is graded on, the sampled-step overhead vs the
    plane off (stats are extra outputs of the same bucket programs, so
    this should be noise), and the provenance drill: an injected
    nan_grad step must fire the non-finite forensics dump EXACTLY once
    and name the poisoned parameter."""
    import tempfile
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, io as mxio
    from mxnet_tpu import kvstore as kvs
    from mxnet_tpu.contrib import chaos
    from mxnet_tpu.fit import FitLoop

    dump_dir = tempfile.mkdtemp(prefix="bench_numerics_")
    saved = {k: os.environ.get(k) for k in
             ("MXTPU_NUMERICS", "MXTPU_MEM_DUMP_DIR", "MXTPU_CHAOS")}
    for k in saved:
        os.environ.pop(k, None)
    os.environ["MXTPU_MEM_DUMP_DIR"] = dump_dir

    def run(spec, chaos_spec=None):
        os.environ.pop("MXTPU_NUMERICS", None)
        if spec:
            os.environ["MXTPU_NUMERICS"] = spec
        if chaos_spec:
            chaos.install(chaos_spec)
        try:
            mx.random.seed(0)
            rs = np.random.RandomState(0)
            net = gluon.nn.Sequential()
            net.add(gluon.nn.Dense(width, activation="relu"),
                    gluon.nn.Dense(8))
            net.initialize(mx.init.Xavier())
            data = rs.randn(steps * batch, width).astype(np.float32)
            label = rs.randint(0, 8, (steps * batch,)).astype(np.float32)
            it = mxio.NDArrayIter(data, label, batch_size=batch)
            tr = gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 1e-3},
                               kvstore=kvs.create("device"))
            loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
            loop = FitLoop(net, tr, loss_fn, it, ckpt_dir=None,
                           collect_breakdown=False)
            t0 = time.perf_counter()
            result = loop.fit(epochs=1)
            return result, (time.perf_counter() - t0) / steps * 1e3
        finally:
            if chaos_spec:
                chaos.uninstall()

    try:
        run(None)                      # warm the stats-free programs
        _, off_ms = run(None)
        run("on")                      # warm the stats-emitting variants
        res_on, on_ms = run("on")
        res_chaos, _ = run("on", chaos_spec="nan_grad@2")
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v
    num = res_on.numerics or {}
    chaos_num = res_chaos.numerics or {}
    overhead = ((on_ms - off_ms) / off_ms * 100.0) if off_ms > 0 else 0.0
    return {
        "grad_norm": round(float(num.get("grad_norm", 0.0)), 6),
        "update_ratio": round(float(num.get("update_ratio", 0.0)), 8),
        "samples": int(num.get("samples", 0)),
        "step_ms_off": round(off_ms, 2),
        "step_ms_on": round(on_ms, 2),
        "sampled_overhead_pct": round(overhead, 1),
        "provenance_dumps": len(chaos_num.get("dumps", [])),
        "culprit": (chaos_num.get("culprits") or [None])[0],
        "nonfinite_steps": chaos_num.get("nonfinite_steps", []),
        "loss_scale_events": len(chaos_num.get("loss_scale_events", [])),
    }


def _elastic_probe(resize_at=3, from_world=2, to_world=3):
    """The `elastic` row: simulated resize mid-run (parallel/elastic.py)
    — a world-``from_world`` ZeRO run is killed by chaos
    ``resize@K:to_world`` (final verified checkpoint + resumable exit,
    asserted), resumed at world ``to_world`` under MXTPU_ELASTIC=on, and
    graded on the resume wall seconds plus a post-resize
    trajectory-match verdict against an always-at-``to_world`` run —
    the ROADMAP acceptance bar, re-measured with every artifact."""
    import shutil
    import tempfile
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import fit as fit_mod, gluon, io as mxio
    from mxnet_tpu import kvstore as kvs
    from mxnet_tpu.contrib import chaos

    saved = {k: os.environ.get(k) for k in
             ("MXTPU_ZERO", "MXTPU_ZERO_WORLD", "MXTPU_ELASTIC",
              "MXTPU_OPTIMIZER_AGGREGATION", "MXTPU_CHAOS")}
    for k in saved:
        os.environ.pop(k, None)
    tmp = tempfile.mkdtemp(prefix="bench_elastic_")

    def build(world, ck, elastic_on=False):
        os.environ["MXTPU_OPTIMIZER_AGGREGATION"] = "8"
        os.environ["MXTPU_ZERO"] = "1"
        os.environ["MXTPU_ZERO_WORLD"] = str(world)
        os.environ.pop("MXTPU_ELASTIC", None)
        if elastic_on:
            os.environ["MXTPU_ELASTIC"] = "on"
        mx.random.seed(0)
        net = gluon.nn.Dense(2, in_units=3)
        net.initialize(mx.init.Constant(0.5))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           kvstore=kvs.create("local"))
        rs = np.random.RandomState(0)
        it = mxio.NDArrayIter(rs.rand(24, 3).astype(np.float32),
                              rs.rand(24, 2).astype(np.float32),
                              batch_size=4, shuffle=True, seed=7)
        loss = lambda o, y: ((o - y) ** 2).mean()
        return net, fit_mod.FitLoop(net, tr, loss, it, ckpt_dir=ck,
                                    ckpt_every=100, async_ckpt=False,
                                    heartbeat=False, seed=7)

    try:
        _, ref = build(to_world, os.path.join(tmp, "ref"))
        res_ref = ref.fit(epochs=2)
        ck = os.path.join(tmp, "ck")
        chaos.install(f"resize@{resize_at}:{to_world}")
        _, killed = build(from_world, ck)
        resumable = False
        try:
            killed.fit(epochs=2)
        except SystemExit as e:
            resumable = (e.code == fit_mod.resumable_exit_code())
        chaos.uninstall()
        t0 = time.perf_counter()
        _, resumed = build(to_world, ck, elastic_on=True)
        res_b = resumed.fit(epochs=2)
        resume_s = time.perf_counter() - t0
        match = bool(
            res_b.resumed_from == resize_at and
            len(res_b.losses) == len(res_ref.losses) - resize_at and
            np.allclose(res_b.losses, res_ref.losses[resize_at:],
                        rtol=1e-6))
    finally:
        chaos.uninstall()
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "from_world": from_world,
        "to_world": to_world,
        "resize_step": resize_at,
        "resumable_exit": resumable,
        "resume_s": round(resume_s, 3),
        "post_resize_steps": int(res_b.step - resize_at),
        "trajectory_match": match,
    }


def _selfheal_probe(port=12770):
    """The `selfheal` row: a REAL supervised 2-worker fleet
    (tools/launch.py --supervise + parallel/supervisor.py) with one
    scripted rank kill — the supervisor must auto-shrink to 1, auto-grow
    back to 2 when the spot capacity model recovers, and finish with
    zero human intervention. Graded on the supervisor's own summary
    (restart/grow counts, relaunch wall seconds) plus the union/
    trajectory contract vs an in-process never-failed run — the ROADMAP
    self-healing acceptance bar, re-measured with every artifact."""
    import glob
    import shutil
    import subprocess
    import sys as _sys
    import tempfile
    import numpy as np

    root = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="bench_selfheal_")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one cpu device per worker process
    env.update({
        "JAX_PLATFORMS": "cpu",
        "MXTPU_ZERO": "1",
        "MXTPU_OPTIMIZER_AGGREGATION": "8",
        "SELFHEAL_OUT_DIR": tmp,
        "SELFHEAL_TARGET": "2",
        "SELFHEAL_STEP_SLEEP_MS": "300",
        "SELFHEAL_EVENTS": json.dumps(
            {"0": {"kind": "kill", "rank": 1, "offset": 2}}),
    })
    env.pop("MXTPU_CHAOS", None)
    t0 = time.perf_counter()
    try:
        proc = subprocess.run(
            [_sys.executable, os.path.join(root, "tools", "launch.py"),
             "-n", "2", "--launcher", "local",
             "--coordinator", f"127.0.0.1:{port}",
             "--supervise", "--supervise-grace", "3",
             "--supervise-recovery", "2",
             "--supervise-ckpt", os.path.join(tmp, "ckpt_r0"),
             "--supervise-dir", tmp,
             _sys.executable,
             os.path.join(root, "tests", "dist", "selfheal_worker.py")],
            capture_output=True, text=True, cwd=root,
            timeout=max(60, min(180, _budget_left() - 30)),
            env=env)
        total_s = time.perf_counter() - t0
        if proc.returncode != 0:
            raise RuntimeError(
                f"supervised run rc={proc.returncode}: "
                f"{(proc.stdout + proc.stderr)[-500:]}")
        text = proc.stdout + proc.stderr
        summary = json.loads(
            text.split("SUPERVISOR_SUMMARY ", 1)[1].split("\n", 1)[0])

        # relaunch latencies from the supervisor's generation log:
        # incident DETECTED -> shrunken fleet spawned, and grow
        # DECIDED -> grown fleet spawned (both include the drain grace
        # — that is the real time-to-back-in-business)
        gens = summary["gen_log"]
        shrink_s = grow_s = None
        for prev, cur in zip(gens, gens[1:]):
            if prev.get("t_decide") is None:
                continue
            gap = cur["t_start"] - prev["t_decide"]
            if prev["outcome"] == "incident" and shrink_s is None:
                shrink_s = gap
            if prev["outcome"] == "grow" and grow_s is None:
                grow_s = gap

        # union + trajectory contract vs an in-process never-failed run
        _sys.path.insert(0, os.path.join(root, "tests", "dist"))
        try:
            import selfheal_worker as sw
        finally:
            _sys.path.pop(0)
        saved = {k: os.environ.get(k) for k in
                 ("MXTPU_ZERO", "MXTPU_ZERO_WORLD", "MXTPU_ELASTIC")}
        for k in saved:
            os.environ.pop(k, None)
        try:
            import mxnet_tpu as mx
            from mxnet_tpu import fit as fit_mod, gluon, io as mxio
            X, Y = sw.make_data()
            mx.random.seed(0)
            net = gluon.nn.Dense(1, in_units=3)
            net.initialize(mx.init.Constant(0.25))
            trn = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": 0.05, "momentum": 0.9},
                                kvstore=None)
            it = mxio.NDArrayIter(X, Y, batch_size=sw.G, shuffle=True,
                                  seed=sw.SEED)
            ref = fit_mod.FitLoop(
                net, trn, lambda o, y: ((o - y) ** 2).sum(), it,
                ckpt_dir=None, heartbeat=False, seed=sw.SEED).fit(
                    epochs=sw.EPOCHS, batch_size=sw.G)
            ref_stream = []
            rit = mxio.NDArrayIter(X, Y, batch_size=sw.G, shuffle=True,
                                   seed=sw.SEED)
            for ep in range(sw.EPOCHS):
                rit.set_epoch(ep)
                for bt in rit:
                    ref_stream += sw.batch_ids(bt.data[0].asnumpy())
        finally:
            for k, v in saved.items():
                os.environ.pop(k, None)
                if v is not None:
                    os.environ[k] = v
        consumed, per_step = [], {}
        for path in glob.glob(os.path.join(tmp, "steps_r*_g*.jsonl")):
            with open(path) as f:
                for line in f:
                    rec = json.loads(line)
                    consumed += rec["ids"]
                    per_step[rec["step"]] = \
                        per_step.get(rec["step"], 0.0) + rec["loss"]
        union_ok = sorted(consumed) == sorted(ref_stream)
        steps = sorted(per_step)
        match = bool(
            union_ok and steps == list(range(len(ref.losses))) and
            np.allclose([per_step[s] for s in steps], ref.losses,
                        rtol=1e-4, atol=1e-6))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "restarts": int(summary["restarts"]),
        "grows": int(summary["grows"]),
        "final_world": int(summary["final_world"]),
        "generations": int(summary["generations"]),
        "shrink_s": round(shrink_s, 3) if shrink_s is not None else None,
        "grow_s": round(grow_s, 3) if grow_s is not None else None,
        "total_s": round(total_s, 3),
        "union_ok": union_ok,
        "trajectory_match": match,
    }


def _efficiency_probe(steps=6, batch=32, width=64):
    """The `efficiency` row: the MFU/goodput plane over a warmed
    smoke-MLP FitLoop — nonzero MFU from the XLA cost-model FLOPs of the
    programs actually dispatched (hybridized forward + backward, grouped
    optimizer buckets, the fused finiteness reduction), samples/s
    goodput, the top per-program FLOP movers, and the persistent run
    report round-trip (written, parsed, manifest-verified) — the
    artifact tools/run_compare.py grades regressions against."""
    import tempfile
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import fault, gluon, io as mxio
    from mxnet_tpu.fit import FitLoop
    from mxnet_tpu.telemetry import run_report as rrmod

    report_dir = tempfile.mkdtemp(prefix="bench_efficiency_")
    saved = {k: os.environ.get(k) for k in
             ("MXTPU_EFFICIENCY", "MXTPU_RUN_REPORT_DIR",
              "MXTPU_DEVICE_PEAK")}
    for k in saved:
        os.environ.pop(k, None)

    def run():
        mx.random.seed(0)
        rs = np.random.RandomState(0)
        net = gluon.nn.Sequential()
        net.add(gluon.nn.Dense(width, activation="relu"),
                gluon.nn.Dense(8))
        net.initialize(mx.init.Xavier())
        net.hybridize()  # whole-graph programs = full FLOP attribution
        data = rs.randn(steps * batch, width).astype(np.float32)
        label = rs.randint(0, 8, (steps * batch,)).astype(np.float32)
        it = mxio.NDArrayIter(data, label, batch_size=batch)
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 1e-3}, kvstore=None)
        loop = FitLoop(net, tr, gluon.loss.SoftmaxCrossEntropyLoss(),
                       it, ckpt_dir=None)
        return loop.fit(epochs=1)

    try:
        run()                              # warm the compiled programs
        os.environ["MXTPU_EFFICIENCY"] = "on"
        os.environ["MXTPU_RUN_REPORT_DIR"] = report_dir
        result = run()
    finally:
        for k, v in saved.items():
            os.environ.pop(k, None)
            if v is not None:
                os.environ[k] = v
    eff = result.efficiency or {}
    report_ok = False
    report_steps = 0
    if result.run_report:
        try:
            rep = rrmod.load_run_report(result.run_report)
            fault.verify_manifest(report_dir, required=True)
            report_ok = True
            report_steps = int(rep["run"]["steps"])
        except Exception as e:
            log(f"efficiency probe: report verify failed: {e}")
    top = [[p["label"], p["flops"]]
           for p in eff.get("per_program", [])[:3]]
    return {
        "mfu": float(eff.get("mfu", 0.0)),
        "estimate": bool(eff.get("estimate", True)),
        "roofline": eff.get("roofline"),
        "samples_per_s": round(float(eff.get("samples_per_s", 0.0)), 2),
        "flops_per_step": float(eff.get("flops_per_step", 0.0)),
        "unattributed_dispatches": int(
            eff.get("unattributed_dispatches", -1)),
        "top_programs": top,
        "run_report": result.run_report,
        "report_ok": report_ok,
        "report_steps": report_steps,
    }


def _read_fleet_ready(proc, timeout):
    """Block until a spawned fleet replica prints its READY json line
    (tests/dist/fleet_worker.py); raises on death/timeout."""
    import threading
    info = {}
    done = threading.Event()

    def _read():
        for line in proc.stdout:
            if line.startswith("FLEET_REPLICA_READY "):
                try:
                    info.update(json.loads(line.split(" ", 1)[1]))
                except ValueError:
                    pass
                done.set()
                return
        done.set()

    threading.Thread(target=_read, daemon=True).start()
    if not done.wait(timeout) or "port" not in info:
        raise RuntimeError(f"fleet replica not ready after {timeout:.0f}s "
                           f"(rc={proc.poll()})")
    return info


def _fleet_closed_loop(router, item, seconds, clients=4):
    """Closed-loop QPS + client-observed latency through the router."""
    import threading
    stop = time.perf_counter() + seconds
    counts = [0] * clients
    lats, errs = [], []
    lock = threading.Lock()

    def worker(i):
        while time.perf_counter() < stop:
            t0 = time.perf_counter()
            try:
                router.predict(item, timeout=60)
                counts[i] += 1
                with lock:
                    lats.append((time.perf_counter() - t0) * 1000.0)
            except Exception as e:
                with lock:
                    errs.append(type(e).__name__)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(clients)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    dt = time.perf_counter() - t0
    lats.sort()
    pct = lambda q: round(lats[min(len(lats) - 1,  # noqa: E731
                                   int(q * len(lats)))], 3) if lats else None
    return {"qps": round(sum(counts) / dt, 1), "n": sum(counts),
            "p50_ms": pct(0.50), "p99_ms": pct(0.99),
            "errors": len(errs)}


def _fleet_probe():
    """The `fleet` row: a REAL 2-process serving fleet behind the
    least-loaded router (serving/router.py) — aggregate QPS and p99
    with a chaos `replica_kill` firing mid-run (`dropped_requests` MUST
    be 0: the router retries the corpse's un-acked requests on the
    survivor), scale-up cold-start wall seconds with 0 XLA compiles
    (published AOT bundle + shared compile cache), and dense-vs-int8
    per-replica QPS for the registry-published `fold_batchnorm` +
    `quantize_net` variant — the ROADMAP item 3 acceptance bar,
    re-measured with every artifact."""
    import shutil
    import signal as _signal
    import subprocess
    import sys as _sys
    import tempfile
    import numpy as np
    from mxnet_tpu import nd
    from mxnet_tpu.contrib import chaos
    from mxnet_tpu.contrib.quantization import quantize_net
    from mxnet_tpu.serving import FleetRouter, ModelRegistry

    root = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="bench_fleet_")
    shape = (3, 32, 32)
    procs = []

    def spawn(model, publish_aot=False):
        env = dict(os.environ)
        env.pop("MXTPU_CHAOS", None)  # the plan lives in the ROUTER
        env.update({"JAX_PLATFORMS": "cpu",  # replicas must not fight
                    #                          over a single-owner TPU
                    "FLEET_REGISTRY": os.path.join(tmp, "registry"),
                    "FLEET_MODEL": model,
                    "FLEET_PUBLISH_AOT": "1" if publish_aot else "0",
                    "MXTPU_COMPILE_CACHE": os.path.join(tmp, "cache")})
        p = subprocess.Popen(
            [_sys.executable,
             os.path.join(root, "tests", "dist", "fleet_worker.py")],
            stdout=subprocess.PIPE, text=True, bufsize=1, env=env)
        procs.append(p)
        info = _read_fleet_ready(
            p, timeout=max(30, min(120, _budget_left() - 20)))
        return p, info

    router = None
    try:
        reg = ModelRegistry(os.path.join(tmp, "registry"))
        sig = {"bucket_shapes": [list(shape)], "dtype": "float32"}
        reg.publish("bench_cnn32", net=_serve_model(), signature=sig)
        # the int8 per-replica throughput variant: fold_batchnorm +
        # calibrated int8 rewrite, published as its own registry model
        rs = np.random.RandomState(0)
        calib = [nd.from_jax(rs.rand(8, *shape).astype(np.float32))]
        qnet = quantize_net(_serve_model(), calib)
        reg.publish("bench_cnn32_int8", net=qnet, signature=sig)

        p1, i1 = spawn("bench_cnn32", publish_aot=True)
        p2, i2 = spawn("bench_cnn32")
        router = FleetRouter(heartbeat_ms=100)
        router.add_replica("r1", ("127.0.0.1", i1["port"]), pid=i1["pid"])
        router.add_replica("r2", ("127.0.0.1", i2["port"]), pid=i2["pid"])
        router.set_kill_hook(
            lambda name: os.kill(
                {"r1": i1["pid"], "r2": i2["pid"]}[name], _signal.SIGKILL))
        item = rs.rand(*shape).astype(np.float32)
        router.predict(item, timeout=60)  # one warm round trip each side

        # churn phase: kill one replica (chaos grammar) mid closed-loop
        chaos.install("replica_kill@25")
        churn_s = min(4.0, max(1.5, _budget_left() / 20))
        point = _fleet_closed_loop(router, item, churn_s)
        chaos.uninstall()
        killed = [n for n, s in router.states().items()
                  if not s["healthy"]]
        dropped = point["errors"]

        # scale-up: third replica must cold-start with ZERO compiles
        t0 = time.perf_counter()
        p3, i3 = spawn("bench_cnn32")
        router.add_replica("r3", ("127.0.0.1", i3["port"]),
                           pid=i3["pid"])
        router.predict(item, timeout=60)
        scaleup_s = time.perf_counter() - t0

        # per-replica dense vs int8 closed-loop (each behind its own
        # single-replica router: replica-level throughput, no fan-out)
        per_s = min(2.0, max(0.8, _budget_left() / 30))
        dense_router = FleetRouter(heartbeat_ms=200)
        dense_router.add_replica("d", ("127.0.0.1", i3["port"]))
        dense_point = _fleet_closed_loop(dense_router, item, per_s)
        dense_router.close()
        p4, i4 = spawn("bench_cnn32_int8")
        int8_router = FleetRouter(heartbeat_ms=200)
        int8_router.add_replica("q", ("127.0.0.1", i4["port"]))
        int8_point = _fleet_closed_loop(int8_router, item, per_s)
        int8_router.close()

        router.stop_fleet(drain=True)
        return {
            "replicas": 2,
            "aggregate_qps": point["qps"],
            "requests": point["n"],
            "p50_ms": point["p50_ms"],
            "p99_ms": point["p99_ms"],
            "killed": len(killed),
            "dropped_requests": dropped,
            "scaleup_s": round(scaleup_s, 3),
            "scaleup_compiles": int(i3.get("xla_compiles", -1)),
            "scaleup_aot_loaded": int(
                (i3.get("warm") or {}).get("aot_loaded", 0)),
            "dense_qps": dense_point["qps"],
            "int8_qps": int8_point["qps"],
        }
    finally:
        try:
            chaos.uninstall()
        except Exception:
            pass
        if router is not None:
            router.close()
        for p in procs:
            if p.poll() is None:
                p.kill()
        shutil.rmtree(tmp, ignore_errors=True)


def _recsys_probe(rows=256, dim=16, world=4, batch=128, steps=8):
    """The `recsys` row: the sparse embedding plane's two-tower numbers
    (parallel/embedding_plane.py). Train: warm mask-packed row-sparse
    steps against the ``world``-way row-sharded table -> examples/s, and
    the per-rank ledger bytes vs a world=1 baseline trained the same way
    (Adam state is lazy per rank, so every rank is touched first — the
    pin is per_rank == unsharded // world EXACTLY, the ledger is exact
    on CPU). Serve: the table + a small tower publish as one registry
    version (serving/lookup.py) and a 2-replica LookupFleet answers a
    closed loop -> lookup_qps."""
    import shutil
    import tempfile
    import time

    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu import optimizer as opt_mod
    from mxnet_tpu.parallel.embedding_plane import EmbeddingPlane
    from mxnet_tpu.serving import LookupFleet, ModelRegistry
    from mxnet_tpu.serving.lookup import publish_embedding

    saved = os.environ.get("MXTPU_SPARSE_PLANE")
    os.environ["MXTPU_SPARSE_PLANE"] = "on"
    tmp = tempfile.mkdtemp(prefix="bench_recsys_")
    planes = []
    try:
        rs = np.random.RandomState(0)
        grads = rs.randn(batch, dim).astype(np.float32) * 0.1

        def make(w, name):
            p = EmbeddingPlane(name, rows=rows, dim=dim, world=w,
                               optimizer=opt_mod.Adam(learning_rate=0.05))
            planes.append(p)
            # touch every row once: all ranks materialize their lazy
            # Adam state, and warm compiles leave the timed window
            p.step(np.arange(rows),
                   rs.randn(rows, dim).astype(np.float32) * 0.1)
            p.step(rs.randint(0, rows, batch), grads)
            return p

        base = make(1, "bench_recsys_base")       # the unsharded ledger
        plane = make(world, "bench_recsys")
        t0 = time.perf_counter()
        for _ in range(steps):
            plane.step(rs.randint(0, rows, batch), grads)
        examples_per_s = steps * batch / max(time.perf_counter() - t0,
                                             1e-9)
        unsharded = base.rank_bytes(0)
        per_rank = [plane.rank_bytes(r) for r in range(world)]

        # serve the trained table: one published version, 2 replicas
        tower = nn.Dense(1, in_units=dim)
        tower.initialize(mx.init.Xavier())
        with autograd.pause():
            tower(plane.lookup(np.arange(4)))
        reg = ModelRegistry(os.path.join(tmp, "registry"))
        version = publish_embedding(
            reg, "bench_recsys", plane, tower,
            signature={"bucket_shapes": [[dim]], "dtype": "float32"})
        fleet = LookupFleet(reg, "bench_recsys", replicas=2,
                            version=version)
        serve_s = min(1.0, max(0.4, _budget_left() / 60))
        deadline = time.perf_counter() + serve_s
        while time.perf_counter() < deadline:
            fleet.lookup(rs.randint(0, rows, 32))
        m = fleet.metrics_json()
        return {
            "world": world,
            "rows": rows,
            "dim": dim,
            "examples_per_s": round(examples_per_s, 1),
            "unsharded_embedding_bytes": int(unsharded),
            "per_rank_embedding_bytes": [int(b) for b in per_rank],
            "replicas": m["replicas"],
            "lookup_requests": m["requests"],
            "lookup_qps": round(m["lookup_qps"], 1),
        }
    finally:
        for p in planes:
            try:
                p.close()
            except Exception:
                pass
        shutil.rmtree(tmp, ignore_errors=True)
        os.environ.pop("MXTPU_SPARSE_PLANE", None)
        if saved is not None:
            os.environ["MXTPU_SPARSE_PLANE"] = saved


def _run_child(mode, args_rest):
    if not _init_backend():
        os._exit(1)
    _enable_compile_cache()
    if mode == "--inference-only":
        print(f"INFERENCE_IPS {run_inference(batch=int(args_rest[0])):.2f}",
              flush=True)
    else:
        batch, k = int(args_rest[0]), int(args_rest[1])
        print(f"TRAIN_IPS {run(batch=batch, k_steps=k):.2f}", flush=True)
        if os.environ.get("MXTPU_BENCH_DISPATCH_PROBE", "1") != "0":
            try:
                probe = _dispatch_probe()
                print("EXTRA_ROW " + json.dumps({"update_dispatch": probe}),
                      flush=True)
            except Exception as e:
                # the probe is an optional row: must never cost TRAIN_IPS
                log(f"dispatch probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_STEP_BREAKDOWN", "1") != "0":
            try:
                bd = _step_breakdown_probe()
                print("EXTRA_ROW " + json.dumps({"step_breakdown": bd}),
                      flush=True)
            except Exception as e:
                log(f"step breakdown probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_AUTOTUNE", "1") != "0":
            try:
                at = _autotune_probe()
                print("EXTRA_ROW " + json.dumps({"autotune": at}),
                      flush=True)
            except Exception as e:
                log(f"autotune probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_MEMORY", "1") != "0":
            try:
                mrow = _memory_probe()
                print("EXTRA_ROW " + json.dumps({"memory": mrow}),
                      flush=True)
            except Exception as e:
                log(f"memory probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_ZERO", "1") != "0":
            try:
                zrow = _zero_probe()
                print("EXTRA_ROW " + json.dumps({"zero": zrow}),
                      flush=True)
            except Exception as e:
                log(f"zero probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_ZERO_OVERLAP", "1") != "0":
            try:
                zorow = _zero_overlap_probe()
                print("EXTRA_ROW " + json.dumps({"zero_overlap": zorow}),
                      flush=True)
            except Exception as e:
                log(f"zero overlap probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_MEGASTEP", "1") != "0":
            try:
                msrow = _megastep_probe()
                print("EXTRA_ROW " + json.dumps({"megastep": msrow}),
                      flush=True)
            except Exception as e:
                log(f"megastep probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_COMM_HEALTH", "1") != "0":
            try:
                crow = _comm_health_probe()
                print("EXTRA_ROW " + json.dumps({"comm_health": crow}),
                      flush=True)
            except Exception as e:
                log(f"comm health probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_NUMERICS", "1") != "0":
            try:
                nrow = _numerics_probe()
                print("EXTRA_ROW " + json.dumps({"numerics": nrow}),
                      flush=True)
            except Exception as e:
                log(f"numerics probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_EFFICIENCY", "1") != "0":
            try:
                erow = _efficiency_probe()
                print("EXTRA_ROW " + json.dumps({"efficiency": erow}),
                      flush=True)
            except Exception as e:
                log(f"efficiency probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_ELASTIC", "1") != "0":
            try:
                elrow = _elastic_probe()
                print("EXTRA_ROW " + json.dumps({"elastic": elrow}),
                      flush=True)
            except Exception as e:
                log(f"elastic probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_SELFHEAL", "1") != "0":
            try:
                shrow = _selfheal_probe()
                print("EXTRA_ROW " + json.dumps({"selfheal": shrow}),
                      flush=True)
            except Exception as e:
                log(f"selfheal probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_FLEET", "1") != "0":
            try:
                flrow = _fleet_probe()
                print("EXTRA_ROW " + json.dumps({"fleet": flrow}),
                      flush=True)
            except Exception as e:
                log(f"fleet probe failed: {e}")
        if os.environ.get("MXTPU_BENCH_RECSYS", "1") != "0":
            try:
                rrow = _recsys_probe()
                print("EXTRA_ROW " + json.dumps({"recsys": rrow}),
                      flush=True)
            except Exception as e:
                log(f"recsys probe failed: {e}")


# global wall-clock budget: the driver kills the whole bench at some
# hard limit (BENCH_r05 was rc:124 with NO number because the rows ran
# open-loop) — every child timeout is sized from what actually remains
MIN_CHILD_S = 120          # don't bother launching a child below this
_DEADLINE = [None]
_HEADLINE_SHIPPED = [False]
_EXTRAS = {}               # side-channel rows parsed from child stdout


def _emit_on_signal(signum, frame):
    """SIGTERM/SIGINT (the harness pulling the plug): a truncated run must
    still parse. If the headline already shipped, stdout already holds a
    good JSON line — just exit cleanly; otherwise emit an error row NOW.
    os._exit, not sys.exit: unwinding would block on an in-flight child
    (subprocess.run waits for it on non-timeout exceptions) and the
    harness's kill -9 would land before any JSON did."""
    if not _HEADLINE_SHIPPED[0]:
        print(json.dumps({
            "metric": "resnet50_train_imgs_per_sec",
            "value": 0.0,
            "unit": "img/s",
            "vs_baseline": 0.0,
            "error": f"terminated by signal {signum} before the train row "
                     f"landed ({_budget_left():.0f}s of budget left)",
        }), flush=True)
    sys.stdout.flush()
    os._exit(0)


def _budget_left():
    if _DEADLINE[0] is None:
        return float("inf")
    return _DEADLINE[0] - time.time()


def _scan_child_stdout(stdout, marker):
    """Harvest a child's stdout: stash every EXTRA_ROW side-channel line
    into _EXTRAS (e.g. the update-dispatch probe) and return the marker's
    value, or None. Applied to complete AND timeout-truncated stdout, so
    rows that printed before a stall are never lost."""
    value = None
    for line in stdout.splitlines():
        if line.startswith("EXTRA_ROW "):
            try:
                _EXTRAS.update(json.loads(line[len("EXTRA_ROW "):]))
            except ValueError:
                pass
        elif line.startswith(marker + " ") and value is None:
            try:
                value = float(line.split()[1])
            except (IndexError, ValueError):
                pass
    return value


def _subprocess_metric(mode, args_list, marker, timeout_s=2100,
                       env_extra=None):
    """Run a measurement in an isolated child (a crash — e.g. a SIGILL
    from relay-compiled AOT cache artifacts — must not kill the bench);
    retry once with the compile cache disabled if the child dies. Each
    attempt's timeout is clipped to the remaining global budget."""
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    for attempt, cache_extra in ((0, {}), (1, {"MXTPU_COMPILE_CACHE": "0"})):
        attempt_s = min(float(timeout_s), _budget_left() - 30)
        if attempt_s < MIN_CHILD_S:
            log(f"{marker} skipped (attempt {attempt}): "
                f"{_budget_left():.0f}s of budget left")
            return None
        env = dict(os.environ, **(env_extra or {}), **cache_extra)
        try:
            res = subprocess.run(
                [sys.executable, os.path.abspath(__file__), mode,
                 *[str(a) for a in args_list]],
                capture_output=True, text=True, timeout=attempt_s,
                cwd=here, env=env)
        except subprocess.TimeoutExpired as e:
            # the child may have printed its rows BEFORE stalling (e.g.
            # TRAIN_IPS + the probe landed, then teardown hung): salvage
            # the partial stdout instead of discarding measurements we
            # already paid for
            partial = e.stdout or b""
            if isinstance(partial, bytes):
                partial = partial.decode("utf-8", "replace")
            value = _scan_child_stdout(partial, marker)
            if value is not None:
                log(f"{marker} child timed out AFTER printing its row "
                    f"(attempt {attempt}): salvaged")
                return value
            log(f"{marker} child timed out (attempt {attempt})")
            return None  # a longer recompile will not beat the timeout
        value = _scan_child_stdout(res.stdout, marker)
        if value is not None:
            return value
        for line in res.stdout.splitlines():
            if line.startswith("{") and '"error"' in line:
                # backend init failed in the child — fatal for every
                # config; surface the real cause and stop retrying.
                # NEVER after the headline shipped: a late error row for
                # the same metric would contradict the good number
                if _HEADLINE_SHIPPED[0]:
                    log(f"{marker} child backend error (headline already "
                        f"shipped): {line[:200]}")
                    return None
                print(line, flush=True)
                raise SystemExit(0)
        log(f"{marker} child rc={res.returncode} (attempt {attempt}): "
            f"{(res.stderr or '')[-300:]}")
        if res.returncode >= 0:
            # python-level failure (OOM raise, bad config): the cache-off
            # retry only helps signal deaths from poisoned AOT cache
            # artifacts (SIGILL/SIGSEGV)
            return None
    return None


def main():
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        # serving row is self-deadlined like the train rows; it runs
        # in-process (tiny model — a crash here has nothing to protect)
        _DEADLINE[0] = time.time() + float(
            os.environ.get("MXTPU_BENCH_DEADLINE_S", DEFAULT_DEADLINE_S))
        run_serve()
        return
    if len(sys.argv) > 1 and sys.argv[1] == "serve-cold":
        # fresh-process cold-start child of the serve row's probe
        _DEADLINE[0] = time.time() + float(
            os.environ.get("MXTPU_BENCH_DEADLINE_S", DEFAULT_DEADLINE_S))
        run_serve_cold(sys.argv[2], sys.argv[3])
        return
    if len(sys.argv) > 1 and sys.argv[1] in ("--inference-only",
                                             "--train-only"):
        if len(sys.argv) < 3:
            log("usage: bench.py --train-only <batch> <k> | "
                "--inference-only <batch>")
            os._exit(2)
        _run_child(sys.argv[1], sys.argv[2:])
        return
    # children own the backend; the parent stays jax-free so a child
    # crash can never take the JSON emission with it.
    # MXTPU_BENCH_DEADLINE_S: global wall-clock budget. The headline
    # JSON line ships the moment the train row lands and is RE-EMITTED
    # after every optional row that lands (incremental extended lines) —
    # a run truncated at any point still parses to the newest complete
    # payload. SIGTERM/SIGINT emit an error row if nothing shipped yet.
    # BENCH_r02-r05's failure mode (rc:124, no number: the old 2400 s
    # default outlived the harness timeout) is structurally impossible:
    # the default deadline undercuts the harness budget and every child
    # timeout is clipped to what remains.
    _DEADLINE[0] = time.time() + float(
        os.environ.get("MXTPU_BENCH_DEADLINE_S", DEFAULT_DEADLINE_S))
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, _emit_on_signal)
        except (ValueError, OSError):
            pass
    # batch x k_steps configs, largest first; smaller fallbacks cover
    # tighter-memory chips. k_steps amortizes dispatch overhead; batch
    # amortizes per-step fixed cost.
    # measured on one tunneled v5e chip (bf16 NHWC, round 3): 256x16 ->
    # 2494 img/s, 512x8 -> 2255 (bigger batch loses: same bytes/img,
    # worse pipelining) — ~30 TFLOP/s sustained vs the chip's ~73 TFLOP/s
    # matmul peak: HBM-bandwidth-bound; see README perf ledger
    configs = os.environ.get("MXTPU_BENCH_CONFIGS",
                             "256x16,256x8,128x8,128x2")
    last_err = None
    for cfg in configs.split(","):
        batch, k = (int(v) for v in cfg.split("x"))
        try:
            value = _subprocess_metric("--train-only", [batch, k],
                                       "TRAIN_IPS")
            if value is None:
                raise RuntimeError(f"train child failed for {cfg}")
            payload = {
                "metric": "resnet50_train_imgs_per_sec",
                "value": round(value, 2),
                "unit": "img/s",
                "vs_baseline": round(value / BASELINE_IMGS_PER_SEC, 3),
                "dtype": os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16"),
                "layout": os.environ.get("MXTPU_BENCH_LAYOUT", "NHWC"),
                "batch": batch,
                "fused_steps": k,
            }
            if "update_dispatch" in _EXTRAS:
                # the dispatch probe rode along in the train child: the
                # per-step compiled-call launch counts with aggregation
                # on vs off, so the trajectory catches a regression in
                # launch count, not just img/s
                payload["update_dispatch"] = _EXTRAS["update_dispatch"]
            if "step_breakdown" in _EXTRAS:
                # telemetry step-time shares from the same child: an
                # input-pipeline or comm regression shows up as a segment
                # share shift even when img/s only drifts
                payload["step_breakdown"] = _EXTRAS["step_breakdown"]
            if "autotune" in _EXTRAS:
                # the self-tuning loop's evidence: chosen knobs + the
                # before/after comm-segment share on a comm-heavy config
                payload["autotune"] = _EXTRAS["autotune"]
            if "memory" in _EXTRAS:
                # device-byte attribution (live ledger + per-program
                # temp bytes + per-step peak): the number ZeRO-1-class
                # memory work is graded on
                payload["memory"] = _EXTRAS["memory"]
            if "zero" in _EXTRAS:
                # the ZeRO-1 evidence: per-rank optimizer+masters bytes
                # vs the unsharded baseline (mp-Adam at simulated N
                # ranks) and the step-time cost of the sharded plane
                payload["zero"] = _EXTRAS["zero"]
            if "zero_overlap" in _EXTRAS:
                # the overlapped-ZeRO evidence: exposed comm share of
                # step time strictly below the barrier plane's with the
                # moved launches visible under comm_overlapped, MFU held
                payload["zero_overlap"] = _EXTRAS["zero_overlap"]
            if "megastep" in _EXTRAS:
                # the one-program-step evidence: warm steps/s + MFU for
                # the fused vs composed legs, bitwise loss parity, and a
                # fully attributed single dispatch per warm step
                payload["megastep"] = _EXTRAS["megastep"]
            if "comm_health" in _EXTRAS:
                # the comm-observability evidence: collective-ledger
                # depth, cross-rank skew and a zero watchdog count on a
                # clean simulated N-rank ZeRO run
                payload["comm_health"] = _EXTRAS["comm_health"]
            if "numerics" in _EXTRAS:
                # the numerics-plane evidence: global grad norm + update
                # ratio from the in-graph stats, sampled-step overhead
                # vs the plane off, and the provenance drill firing
                # exactly once under an injected nan_grad
                payload["numerics"] = _EXTRAS["numerics"]
            if "efficiency" in _EXTRAS:
                # the efficiency-plane evidence: nonzero MFU + samples/s
                # goodput from the cost-model FLOPs of the dispatched
                # programs, the top per-program movers, and the run
                # report round-trip (the run_compare regression artifact)
                payload["efficiency"] = _EXTRAS["efficiency"]
            if "elastic" in _EXTRAS:
                # the elastic-training evidence: a simulated mid-run
                # resize (chaos resize@K, resumable exit) resumed at a
                # different world — resume wall seconds and the
                # post-resize trajectory-match verdict
                payload["elastic"] = _EXTRAS["elastic"]
            if "selfheal" in _EXTRAS:
                # the self-healing-fleet evidence: a real supervised
                # 2-worker run with an injected rank kill — restart/
                # grow counts, shrink/grow relaunch wall seconds, and
                # the union + trajectory verdict vs a never-failed run
                payload["selfheal"] = _EXTRAS["selfheal"]
            if "fleet" in _EXTRAS:
                # the serving-fleet evidence: a real 2-process fleet
                # behind the least-loaded router — aggregate QPS/p99
                # with a replica_kill mid-run (dropped_requests must be
                # 0), zero-compile scale-up wall seconds, and the
                # dense-vs-int8 per-replica throughput ratio
                payload["fleet"] = _EXTRAS["fleet"]
            if "recsys" in _EXTRAS:
                # the sparse-plane evidence: warm mask-packed row-sparse
                # examples/s against the 4-way row-sharded table, the
                # per-rank ledger bytes at exactly 1/world of the
                # unsharded baseline, and the closed-loop lookup_qps a
                # 2-replica LookupFleet serves from the published table
                payload["recsys"] = _EXTRAS["recsys"]
            # the train number is safe on stdout NOW; each optional row
            # that lands re-emits the extended line immediately, so a
            # truncated run keeps everything measured so far
            print(json.dumps(payload), flush=True)
            _HEADLINE_SHIPPED[0] = True
            try:
                if os.environ.get("MXTPU_BENCH_INFERENCE", "1") != "0":
                    infer = _subprocess_metric("--inference-only", [batch],
                                               "INFERENCE_IPS")
                    if infer:
                        payload["inference_imgs_per_sec"] = round(infer, 2)
                        print(json.dumps(payload), flush=True)
                if os.environ.get("MXTPU_BENCH_LOWBIT", "1") != "0":
                    # the round-4/5 low-precision levers, measured into
                    # the SAME artifact so results outlive commit
                    # messages: int8 calibrated inference (quantize_net)
                    # and int8 quantized-forward training
                    # (MXNET_CONV_COMPUTE) — docs/perf.md carries the
                    # accuracy evidence
                    if os.environ.get("MXTPU_BENCH_INFERENCE", "1") != "0":
                        i8 = _subprocess_metric(
                            "--inference-only", [batch], "INFERENCE_IPS",
                            env_extra={"MXTPU_BENCH_INT8": "1"})
                        if i8:
                            payload["inference_int8_imgs_per_sec"] = \
                                round(i8, 2)
                            print(json.dumps(payload), flush=True)
                    # int8-only: stacking fp8 residuals on top REGRESSES
                    # (2376 vs 2550 img/s measured r5 — the extra cast
                    # kernels break fusions); see docs/perf.md roofline
                    t8 = _subprocess_metric(
                        "--train-only", [batch, k], "TRAIN_IPS",
                        env_extra={"MXNET_CONV_COMPUTE": "int8",
                                   # probes already ran in the headline
                                   # train child; don't pay them twice —
                                   # and don't let the int8 child's
                                   # EXTRA_ROWs overwrite the headline
                                   # rows with int8-config numbers
                                   "MXTPU_BENCH_DISPATCH_PROBE": "0",
                                   "MXTPU_BENCH_STEP_BREAKDOWN": "0",
                                   "MXTPU_BENCH_AUTOTUNE": "0",
                                   "MXTPU_BENCH_MEMORY": "0",
                                   "MXTPU_BENCH_ZERO": "0",
                                   "MXTPU_BENCH_ZERO_OVERLAP": "0",
                                   "MXTPU_BENCH_MEGASTEP": "0",
                                   "MXTPU_BENCH_COMM_HEALTH": "0",
                                   "MXTPU_BENCH_NUMERICS": "0",
                                   "MXTPU_BENCH_EFFICIENCY": "0",
                                   "MXTPU_BENCH_ELASTIC": "0",
                                   "MXTPU_BENCH_SELFHEAL": "0",
                                   "MXTPU_BENCH_FLEET": "0",
                                   "MXTPU_BENCH_RECSYS": "0"})
                    if t8:
                        payload["train_int8_imgs_per_sec"] = round(t8, 2)
                        print(json.dumps(payload), flush=True)
            except Exception as e:
                # optional rows must NEVER cost us the shipped headline:
                # no config retry (a second headline), no error JSON
                log(f"optional rows abandoned: {e}")
            return
        except Exception as e:  # OOM or backend issue: try smaller config
            last_err = e
            log(f"config {cfg} failed: {e}")
        if _budget_left() < MIN_CHILD_S + 30:
            last_err = last_err or RuntimeError(
                "bench deadline exhausted before any train row")
            break
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec",
        "value": 0.0,
        "unit": "img/s",
        "vs_baseline": 0.0,
        "error": str(last_err)[:200],
    }))


if __name__ == "__main__":
    main()
