"""Benchmark: ResNet-50 training throughput on one chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Baseline: 298.51 img/s — MXNet ResNet-50 training, batch 32 fp32, 1x V100
(BASELINE.md / docs/faq/perf.md:227-237). The whole train step (fwd+bwd+SGD
momentum update) is one fused XLA program; SPMDTrainer pins parameters to
the accelerator backend up front (CPU-committed args would silently run
the jit on host). Compute dtype from MXTPU_BENCH_DTYPE (default bfloat16 —
the MXU-native dtype; measured 1065 img/s at batch 256 vs 576 f32).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

BASELINE_IMGS_PER_SEC = 298.51


def log(msg):
    print(msg, file=sys.stderr, flush=True)


def _init_backend(timeout_s=900):
    """Initialize the JAX backend with a watchdog: if device discovery
    hangs (e.g. a wedged TPU tunnel), emit an error JSON instead of
    blocking the driver forever."""
    import threading
    result = {}

    def probe():
        try:
            import jax
            result["devices"] = jax.devices()
        except Exception as e:
            result["error"] = e

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout_s)
    if "devices" in result:
        log(f"backend: {result['devices']}")
        return True
    err = result.get("error", f"backend init timed out after {timeout_s}s")
    print(json.dumps({"metric": "resnet50_train_imgs_per_sec", "value": 0.0,
                      "unit": "img/s", "vs_baseline": 0.0,
                      "error": str(err)[:200]}), flush=True)
    return False


def run(batch=128, warmup=1, iters=None, dtype=None):
    import numpy as np
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn, loss as gloss
    from mxnet_tpu.gluon.model_zoo.vision import resnet50_v1
    from mxnet_tpu.parallel import SPMDTrainer
    from mxnet_tpu import nd

    # bf16 default: the MXU-native dtype (the earlier "bf16 slow on the
    # relay" measurement was an artifact of CPU-committed parameters
    # pulling the jit onto the host backend — fixed in SPMDTrainer).
    if dtype is None:
        dtype = os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16")

    mx.random.seed(0)
    net = resnet50_v1()
    net.initialize(mx.init.Xavier())

    trainer = SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(),
                          mesh=None, optimizer="sgd",
                          optimizer_params={"learning_rate": 0.05,
                                            "momentum": 0.9},
                          dtype=jnp.bfloat16 if dtype == "bfloat16" else None)

    rs = np.random.RandomState(0)
    data = jnp.asarray(rs.randn(batch, 3, 224, 224).astype(np.float32))
    label = jnp.asarray(rs.randint(0, 1000, batch).astype(np.float32))

    def sync(loss):
        # on the tunneled backend block_until_ready can return before the
        # device finishes; fetching the scalar is the only true sync
        return float(loss)

    log(f"compiling train step (batch={batch}, {dtype}) ...")
    t0 = time.time()
    loss_val = sync(trainer.step(data, label))
    log(f"first step (compile) took {time.time() - t0:.1f}s, "
        f"loss={loss_val:.3f}")
    t0 = time.time()
    for _ in range(warmup):
        sync(trainer.step(data, label))
    step_est = (time.time() - t0) / max(warmup, 1)
    if iters is None:
        # enough steps for a stable number, capped at ~180s of measurement
        # (floor 2 keeps multi-minute steps from blowing the time budget)
        iters = max(2 if step_est > 120 else 3,
                    min(10, int(180.0 / max(step_est, 1e-3))))
    log(f"~{step_est:.2f}s/step -> {iters} timed iters")

    t0 = time.perf_counter()
    for _ in range(iters - 1):
        trainer.step(data, label)
    sync(trainer.step(data, label))
    dt = time.perf_counter() - t0
    imgs_per_sec = batch * iters / dt
    log(f"{imgs_per_sec:.1f} img/s over {iters} steps "
        f"({dt / iters * 1000:.1f} ms/step)")
    return imgs_per_sec


def _enable_compile_cache():
    """Persistent XLA compilation cache: full-graph ResNet-50 compiles
    take ~15 min through the tunnel; the cache cuts reruns to seconds."""
    from mxnet_tpu.util import enable_compile_cache
    if not enable_compile_cache():
        log("compile cache unavailable")


def main():
    if not _init_backend():
        os._exit(0)
    _enable_compile_cache()
    # batch 512 first: the ~100ms per-execution relay overhead amortizes
    # with batch size (measured 1406 img/s @512, 1065 @256, 690 @128,
    # bf16); smaller fallbacks cover tighter-memory chips
    batches = [int(b) for b in
               os.environ.get("MXTPU_BENCH_BATCHES", "512,256,128").split(",")]
    last_err = None
    for batch in batches:
        try:
            value = run(batch=batch)
            print(json.dumps({
                "metric": "resnet50_train_imgs_per_sec",
                "value": round(value, 2),
                "unit": "img/s",
                "vs_baseline": round(value / BASELINE_IMGS_PER_SEC, 3),
                "dtype": os.environ.get("MXTPU_BENCH_DTYPE", "bfloat16"),
                "batch": batch,
            }))
            return
        except Exception as e:  # OOM or backend issue: try smaller batch
            last_err = e
            log(f"batch {batch} failed: {e}")
    print(json.dumps({
        "metric": "resnet50_train_imgs_per_sec",
        "value": 0.0,
        "unit": "img/s",
        "vs_baseline": 0.0,
        "error": str(last_err)[:200],
    }))


if __name__ == "__main__":
    main()
