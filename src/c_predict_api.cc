// Standalone C predict API over exported models.
//
// Reference: include/mxnet/c_predict_api.h (MXPredCreate/SetInput/Forward/
// GetOutput/Free + MXNDList*) and src/c_api/c_predict_api.cc.
//
// TPU-native design: the reference builds a GraphExecutor in-process; here
// the executor IS the Python/JAX runtime (XLA owns compilation), so this
// library embeds CPython and drives mxnet_tpu.c_predict.Predictor. The C
// surface — signatures, shape indptr encoding, float32 buffers, last-error
// contract — matches the reference so existing c_predict_api consumers
// port by relinking. Works both standalone (initializes the interpreter;
// set MXTPU_HOME to the repo/package root) and when loaded into an
// already-running Python process (pytest/ctypes: uses PyGILState).
//
// Build: make -C src  (libmxtpu_predict.so, links libpython3.12)

#include <Python.h>

#include <cstring>
#include <mutex>
#include <string>
#include <vector>

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *PredictorHandle;
typedef void *NDListHandle;

#define MXTPU_API extern "C" __attribute__((visibility("default")))

namespace {

thread_local std::string g_last_error;

void set_error(const std::string &msg) { g_last_error = msg; }

// ---------------------------------------------------------------------------
// interpreter lifecycle
// ---------------------------------------------------------------------------

std::once_flag g_init_flag;
bool g_we_initialized = false;

void ensure_interpreter() {
  std::call_once(g_init_flag, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      g_we_initialized = true;
      // release the GIL acquired by Py_Initialize so ScopedGIL below
      // can manage it uniformly
      PyEval_SaveThread();
    }
  });
}

class ScopedGIL {
 public:
  ScopedGIL() : state_(PyGILState_Ensure()) {}
  ~ScopedGIL() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

// fetch the python exception as a string and clear it
std::string py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

PyObject *get_predict_module() {
  const char *home = getenv("MXTPU_HOME");
  if (home != nullptr) {
    PyObject *sys_path = PySys_GetObject("path");  // borrowed
    if (sys_path != nullptr) {
      PyObject *p = PyUnicode_FromString(home);
      bool found = false;
      for (Py_ssize_t i = 0; i < PyList_Size(sys_path); ++i) {
        PyObject *item = PyList_GetItem(sys_path, i);
        if (item && PyUnicode_Compare(item, p) == 0) { found = true; break; }
      }
      if (!found) PyList_Insert(sys_path, 0, p);
      Py_DECREF(p);
    }
  }
  return PyImport_ImportModule("mxnet_tpu.c_predict");
}

struct PredictorObj {
  PyObject *pred;  // mxnet_tpu.c_predict.Predictor
  // per-handle shape storage: valid until the next MXPred call on THIS
  // handle (the reference keeps out_shapes inside PredictorObj likewise)
  std::vector<mx_uint> shape_buf;
};

struct NDListObj {
  PyObject *names;   // list[str]
  PyObject *arrays;  // list[np.ndarray float32 C-contiguous]
  std::vector<mx_uint> shape_buf;
};

// call a method returning a new reference; nullptr on python error
PyObject *call_method(PyObject *obj, const char *name, PyObject *args) {
  PyObject *fn = PyObject_GetAttrString(obj, name);
  if (!fn) return nullptr;
  PyObject *out = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  return out;
}

}  // namespace

MXTPU_API const char *MXGetLastError() { return g_last_error.c_str(); }

MXTPU_API int MXPredCreatePartialOut(
    const char *symbol_json_str, const void *param_bytes, int param_size,
    int dev_type, int dev_id, mx_uint num_input_nodes,
    const char **input_keys, const mx_uint *input_shape_indptr,
    const mx_uint *input_shape_data, mx_uint num_output_nodes,
    const char **output_keys, PredictorHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *mod = get_predict_module();
  if (!mod) { set_error(py_error()); return -1; }

  PyObject *keys = PyList_New(num_input_nodes);
  PyObject *shapes = PyList_New(num_input_nodes);
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(input_keys[i]));
    mx_uint lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *shape = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(shape, j - lo, PyLong_FromUnsignedLong(
          input_shape_data[j]));
    PyList_SetItem(shapes, i, shape);
  }
  PyObject *outputs = Py_None;
  Py_INCREF(Py_None);
  if (num_output_nodes > 0) {
    Py_DECREF(Py_None);
    outputs = PyList_New(num_output_nodes);
    for (mx_uint i = 0; i < num_output_nodes; ++i)
      PyList_SetItem(outputs, i, PyUnicode_FromString(output_keys[i]));
  }
  PyObject *params = PyBytes_FromStringAndSize(
      static_cast<const char *>(param_bytes), param_size);
  PyObject *cls = PyObject_GetAttrString(mod, "Predictor");
  Py_DECREF(mod);
  PyObject *args = Py_BuildValue("(sOiiOOO)", symbol_json_str, params,
                                 dev_type, dev_id, keys, shapes, outputs);
  Py_DECREF(params);
  Py_DECREF(keys);
  Py_DECREF(shapes);
  Py_DECREF(outputs);
  if (!cls || !args) {
    Py_XDECREF(cls);
    Py_XDECREF(args);
    set_error(py_error());
    return -1;
  }
  PyObject *pred = PyObject_CallObject(cls, args);
  Py_DECREF(cls);
  Py_DECREF(args);
  if (!pred) { set_error(py_error()); return -1; }
  auto *h = new PredictorObj{};
  h->pred = pred;
  *out = h;
  return 0;
}

MXTPU_API int MXPredCreate(const char *symbol_json_str,
                           const void *param_bytes, int param_size,
                           int dev_type, int dev_id,
                           mx_uint num_input_nodes, const char **input_keys,
                           const mx_uint *input_shape_indptr,
                           const mx_uint *input_shape_data,
                           PredictorHandle *out) {
  return MXPredCreatePartialOut(symbol_json_str, param_bytes, param_size,
                                dev_type, dev_id, num_input_nodes,
                                input_keys, input_shape_indptr,
                                input_shape_data, 0, nullptr, out);
}

MXTPU_API int MXPredSetInput(PredictorHandle handle, const char *key,
                             const mx_float *data, mx_uint size) {
  ScopedGIL gil;
  auto *h = static_cast<PredictorObj *>(handle);
  // hand the buffer over as bytes; python reshapes to the declared shape
  PyObject *mod = PyImport_ImportModule("numpy");
  if (!mod) { set_error(py_error()); return -1; }
  PyObject *frombuffer = PyObject_GetAttrString(mod, "frombuffer");
  Py_DECREF(mod);
  PyObject *buf = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(data), size * sizeof(mx_float));
  PyObject *args = Py_BuildValue("(O)", buf);
  PyObject *kw = Py_BuildValue("{s:s}", "dtype", "float32");
  PyObject *arr = PyObject_Call(frombuffer, args, kw);
  Py_DECREF(frombuffer);
  Py_DECREF(buf);
  Py_DECREF(args);
  Py_DECREF(kw);
  if (!arr) { set_error(py_error()); return -1; }
  PyObject *cargs = Py_BuildValue("(sO)", key, arr);
  Py_DECREF(arr);
  PyObject *res = call_method(h->pred, "set_input", cargs);
  Py_DECREF(cargs);
  if (!res) { set_error(py_error()); return -1; }
  Py_DECREF(res);
  return 0;
}

MXTPU_API int MXPredForward(PredictorHandle handle) {
  ScopedGIL gil;
  auto *h = static_cast<PredictorObj *>(handle);
  PyObject *res = call_method(h->pred, "forward", nullptr);
  if (!res) { set_error(py_error()); return -1; }
  Py_DECREF(res);
  return 0;
}

// the reference's PartialForward steps the graph node by node; whole-graph
// XLA execution has no per-node stepping, so one step == full forward
MXTPU_API int MXPredPartialForward(PredictorHandle handle, int step,
                                   int *step_left) {
  int rc = MXPredForward(handle);
  if (step_left) *step_left = 0;
  return rc;
}

MXTPU_API int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                                   mx_uint **shape_data,
                                   mx_uint *shape_ndim) {
  ScopedGIL gil;
  auto *h = static_cast<PredictorObj *>(handle);
  PyObject *args = Py_BuildValue("(I)", index);
  PyObject *res = call_method(h->pred, "get_output_shape", args);
  Py_DECREF(args);
  if (!res) { set_error(py_error()); return -1; }
  Py_ssize_t n = PyList_Size(res);
  h->shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    h->shape_buf[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyList_GetItem(res, i)));
  Py_DECREF(res);
  *shape_data = h->shape_buf.data();
  *shape_ndim = static_cast<mx_uint>(n);
  return 0;
}

MXTPU_API int MXPredGetOutput(PredictorHandle handle, mx_uint index,
                              mx_float *data, mx_uint size) {
  ScopedGIL gil;
  auto *h = static_cast<PredictorObj *>(handle);
  PyObject *args = Py_BuildValue("(I)", index);
  PyObject *arr = call_method(h->pred, "get_output", args);
  Py_DECREF(args);
  if (!arr) { set_error(py_error()); return -1; }
  PyObject *tobytes = call_method(arr, "tobytes", nullptr);
  Py_DECREF(arr);
  if (!tobytes) { set_error(py_error()); return -1; }
  char *buf = nullptr;
  Py_ssize_t len = 0;
  PyBytes_AsStringAndSize(tobytes, &buf, &len);
  if (static_cast<size_t>(len) != size * sizeof(mx_float)) {
    Py_DECREF(tobytes);
    set_error("output size mismatch: have " + std::to_string(len / 4) +
              " elements, caller asked for " + std::to_string(size));
    return -1;
  }
  std::memcpy(data, buf, len);
  Py_DECREF(tobytes);
  return 0;
}

MXTPU_API int MXPredReshape(mx_uint num_input_nodes, const char **input_keys,
                            const mx_uint *input_shape_indptr,
                            const mx_uint *input_shape_data,
                            PredictorHandle handle, PredictorHandle *out) {
  ScopedGIL gil;
  auto *h = static_cast<PredictorObj *>(handle);
  PyObject *keys = PyList_New(num_input_nodes);
  PyObject *shapes = PyList_New(num_input_nodes);
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(keys, i, PyUnicode_FromString(input_keys[i]));
    mx_uint lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject *shape = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(shape, j - lo,
                     PyLong_FromUnsignedLong(input_shape_data[j]));
    PyList_SetItem(shapes, i, shape);
  }
  PyObject *args = Py_BuildValue("(OO)", keys, shapes);
  Py_DECREF(keys);
  Py_DECREF(shapes);
  // reference returns a NEW handle sharing weights; the original stays
  // usable with its own shapes
  PyObject *clone = call_method(h->pred, "reshaped", args);
  Py_DECREF(args);
  if (!clone) { set_error(py_error()); return -1; }
  auto *nh = new PredictorObj{};
  nh->pred = clone;
  *out = nh;
  return 0;
}

MXTPU_API int MXPredFree(PredictorHandle handle) {
  ScopedGIL gil;
  auto *h = static_cast<PredictorObj *>(handle);
  Py_XDECREF(h->pred);
  delete h;
  return 0;
}

// ---------------------------------------------------------------------------
// MXNDList*: read a saved NDArray map (ref: c_predict_api.h:252-277)
// ---------------------------------------------------------------------------

MXTPU_API int MXNDListCreate(const char *nd_file_bytes, int nd_file_size,
                             NDListHandle *out, mx_uint *out_length) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *mod = get_predict_module();
  if (!mod) { set_error(py_error()); return -1; }
  PyObject *fn = PyObject_GetAttrString(mod, "load_ndlist");
  Py_DECREF(mod);
  PyObject *bytes = PyBytes_FromStringAndSize(nd_file_bytes, nd_file_size);
  PyObject *args = Py_BuildValue("(O)", bytes);
  Py_DECREF(bytes);
  PyObject *res = fn ? PyObject_CallObject(fn, args) : nullptr;
  Py_XDECREF(fn);
  Py_DECREF(args);
  if (!res) { set_error(py_error()); return -1; }
  PyObject *names = PySequence_GetItem(res, 0);
  PyObject *arrays = PySequence_GetItem(res, 1);
  Py_DECREF(res);
  auto *h = new NDListObj{};
  h->names = names;
  h->arrays = arrays;
  *out = h;
  *out_length = static_cast<mx_uint>(PyList_Size(names));
  return 0;
}

MXTPU_API int MXNDListGet(NDListHandle handle, mx_uint index,
                          const char **out_key, const mx_float **out_data,
                          const mx_uint **out_shape, mx_uint *out_ndim) {
  ScopedGIL gil;
  auto *h = static_cast<NDListObj *>(handle);
  if (index >= static_cast<mx_uint>(PyList_Size(h->names))) {
    set_error("MXNDListGet: index out of range");
    return -1;
  }
  *out_key = PyUnicode_AsUTF8(PyList_GetItem(h->names, index));
  PyObject *arr = PyList_GetItem(h->arrays, index);  // borrowed
  // ensure float32 C-contiguous via numpy (stored that way by load_ndlist)
  PyObject *iface = PyObject_GetAttrString(arr, "ctypes");
  PyObject *dataptr = iface ? PyObject_GetAttrString(iface, "data") : nullptr;
  Py_XDECREF(iface);
  if (!dataptr) { set_error(py_error()); return -1; }
  *out_data = reinterpret_cast<const mx_float *>(PyLong_AsSize_t(dataptr));
  Py_DECREF(dataptr);
  PyObject *shape = PyObject_GetAttrString(arr, "shape");
  if (!shape) { set_error(py_error()); return -1; }
  Py_ssize_t n = PyTuple_Size(shape);
  h->shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    h->shape_buf[i] = static_cast<mx_uint>(
        PyLong_AsUnsignedLong(PyTuple_GetItem(shape, i)));
  Py_DECREF(shape);
  *out_shape = h->shape_buf.data();
  *out_ndim = static_cast<mx_uint>(n);
  return 0;
}

MXTPU_API int MXNDListFree(NDListHandle handle) {
  ScopedGIL gil;
  auto *h = static_cast<NDListObj *>(handle);
  Py_XDECREF(h->names);
  Py_XDECREF(h->arrays);
  delete h;
  return 0;
}
