// General C API: NDArray lifecycle, operator invocation, symbol
// composition, executor, autograd, kvstore.
//
// Reference: include/mxnet/c_api.h (198 functions) + src/c_api/*.cc.
// TPU-native design: like c_predict_api.cc, the runtime IS the
// Python/JAX stack, so this library embeds CPython and drives
// mxnet_tpu.c_api_bridge. Handles crossing the boundary are PyObject*
// (ref-counted via MXNDArrayFree etc.); signatures, shape encodings,
// last-error contract and return-code conventions match the reference so
// existing c_api consumers (and future language bindings) port by
// relinking.
//
// Build: make -C src  (libmxtpu_capi.so, links libpython3.12)

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <vector>

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;
typedef void *KVStoreHandle;

#define MXTPU_API extern "C" __attribute__((visibility("default")))

namespace {

thread_local std::string g_last_error;
void set_error(const std::string &msg) { g_last_error = msg; }

std::once_flag g_init_flag;

void ensure_interpreter() {
  std::call_once(g_init_flag, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();
    }
  });
}

class ScopedGIL {
 public:
  ScopedGIL() : state_(PyGILState_Ensure()) {}
  ~ScopedGIL() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

std::string py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

PyObject *bridge() {
  const char *home = getenv("MXTPU_HOME");
  if (home != nullptr) {
    PyObject *sys_path = PySys_GetObject("path");
    if (sys_path != nullptr) {
      PyObject *p = PyUnicode_FromString(home);
      bool found = false;
      for (Py_ssize_t i = 0; i < PyList_Size(sys_path); ++i) {
        PyObject *item = PyList_GetItem(sys_path, i);
        if (item && PyUnicode_Compare(item, p) == 0) { found = true; break; }
      }
      if (!found) PyList_Insert(sys_path, 0, p);
      Py_DECREF(p);
    }
  }
  return PyImport_ImportModule("mxnet_tpu.c_api_bridge");
}

// call bridge.<name>(*args); steals nothing, returns new ref or nullptr
PyObject *call(const char *name, PyObject *args) {
  PyObject *mod = bridge();
  if (!mod) return nullptr;
  PyObject *fn = PyObject_GetAttrString(mod, name);
  Py_DECREF(mod);
  if (!fn) return nullptr;
  PyObject *out = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  return out;
}

PyObject *uint_list(const mx_uint *data, mx_uint n) {
  PyObject *lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyLong_FromUnsignedLong(data[i]));
  return lst;
}

PyObject *str_list(const char **data, mx_uint n) {
  PyObject *lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyUnicode_FromString(data[i]));
  return lst;
}

PyObject *handle_list(void *const *handles, mx_uint n) {
  PyObject *lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i) {
    PyObject *o = handles[i] ? static_cast<PyObject *>(handles[i])
                             : Py_None;
    Py_INCREF(o);
    PyList_SetItem(lst, i, o);
  }
  return lst;
}

// per-thread string/shape storage for pointer-returning getters (the
// reference stores these in thread-local Ret entries likewise)
thread_local std::vector<std::string> g_str_store;
thread_local std::vector<const char *> g_cstr_store;
thread_local std::vector<mx_uint> g_shape_store;
thread_local std::vector<void *> g_handle_store;

int fill_strs(PyObject *lst, mx_uint *out_n, const char ***out) {
  Py_ssize_t n = PyList_Size(lst);
  g_str_store.clear();
  g_cstr_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char *c = PyUnicode_AsUTF8(PyList_GetItem(lst, i));
    g_str_store.emplace_back(c ? c : "");
  }
  for (auto &s : g_str_store) g_cstr_store.push_back(s.c_str());
  *out_n = static_cast<mx_uint>(n);
  *out = g_cstr_store.data();
  return 0;
}

int fill_handles(PyObject *lst, mx_uint *out_n, NDArrayHandle **out) {
  Py_ssize_t n = PyList_Size(lst);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(lst, i);
    Py_INCREF(o);  // caller owns via MXNDArrayFree
    g_handle_store.push_back(o);
  }
  *out_n = static_cast<mx_uint>(n);
  *out = g_handle_store.data();
  return 0;
}

}  // namespace

// ---------------------------------------------------------------------------
// misc
// ---------------------------------------------------------------------------

MXTPU_API const char *MXGetLastError() { return g_last_error.c_str(); }

MXTPU_API int MXGetVersion(int *out) {
  *out = 10500;
  return 0;
}

MXTPU_API int MXRandomSeed(int seed) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(i)", seed);
  PyObject *r = call("random_seed", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayWaitAll() {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *r = call("ndarray_wait_all", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

// ---------------------------------------------------------------------------
// NDArray
// ---------------------------------------------------------------------------

MXTPU_API int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim,
                                int dev_type, int dev_id,
                                int delay_alloc, int dtype,
                                NDArrayHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *shp = uint_list(shape, ndim);
  PyObject *args = Py_BuildValue("(Oiii)", shp, dtype, dev_type, dev_id);
  Py_DECREF(shp);
  PyObject *r = call("ndarray_create", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim,
                              int dev_type, int dev_id, int delay_alloc,
                              NDArrayHandle *out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc, 0,
                           out);
}

MXTPU_API int MXNDArrayCreateNone(NDArrayHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *r = call("ndarray_create_none", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXNDArrayFree(NDArrayHandle handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

MXTPU_API int MXNDArraySyncCopyFromCPU(NDArrayHandle handle,
                                       const void *data, size_t size) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(OKK)", static_cast<PyObject *>(handle),
                                 (unsigned long long)(uintptr_t)data,
                                 (unsigned long long)size);
  PyObject *r = call("ndarray_sync_copy_from_cpu", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data,
                                     size_t size) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(OKK)", static_cast<PyObject *>(handle),
                                 (unsigned long long)(uintptr_t)data,
                                 (unsigned long long)size);
  PyObject *r = call("ndarray_sync_copy_to_cpu", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                                const mx_uint **out_pdata) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(handle));
  PyObject *r = call("ndarray_shape", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_ssize_t n = PyList_Size(r);
  g_shape_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i)
    g_shape_store.push_back(
        (mx_uint)PyLong_AsUnsignedLong(PyList_GetItem(r, i)));
  Py_DECREF(r);
  *out_dim = static_cast<mx_uint>(n);
  *out_pdata = g_shape_store.data();
  return 0;
}

MXTPU_API int MXNDArrayGetDType(NDArrayHandle handle, int *out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(handle));
  PyObject *r = call("ndarray_dtype", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArraySlice(NDArrayHandle handle, mx_uint begin,
                             mx_uint end, NDArrayHandle *out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(OII)", static_cast<PyObject *>(handle),
                                 begin, end);
  PyObject *r = call("ndarray_slice", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXNDArrayAt(NDArrayHandle handle, mx_uint idx,
                          NDArrayHandle *out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(OI)", static_cast<PyObject *>(handle),
                                 idx);
  PyObject *r = call("ndarray_at", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXNDArrayReshape(NDArrayHandle handle, int ndim,
                               const int *dims, NDArrayHandle *out) {
  ScopedGIL gil;
  PyObject *shp = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyList_SetItem(shp, i, PyLong_FromLong(dims[i]));
  PyObject *args = Py_BuildValue("(ON)", static_cast<PyObject *>(handle),
                                 shp);
  PyObject *r = call("ndarray_reshape", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXNDArraySave(const char *fname, mx_uint num_args,
                            NDArrayHandle *args_h, const char **keys) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *arrays = handle_list(args_h, num_args);
  PyObject *names = keys ? str_list(keys, num_args) : PyList_New(0);
  PyObject *args = Py_BuildValue("(sNN)", fname, arrays, names);
  PyObject *r = call("ndarray_save", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                            NDArrayHandle **out_arr,
                            mx_uint *out_name_size,
                            const char ***out_names) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(s)", fname);
  PyObject *r = call("ndarray_load", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  PyObject *names = PyTuple_GetItem(r, 0);
  PyObject *arrays = PyTuple_GetItem(r, 1);
  fill_strs(names, out_name_size, out_names);
  fill_handles(arrays, out_size, out_arr);
  Py_DECREF(r);
  return 0;
}

// ---------------------------------------------------------------------------
// operators
// ---------------------------------------------------------------------------

MXTPU_API int MXListAllOpNames(mx_uint *out_size, const char ***out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *r = call("list_all_op_names", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  fill_strs(r, out_size, out);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXImperativeInvoke(const char *op_name, int num_inputs,
                                 NDArrayHandle *inputs, int *num_outputs,
                                 NDArrayHandle **outputs, int num_params,
                                 const char **param_keys,
                                 const char **param_vals) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *ins = handle_list(inputs, num_inputs);
  PyObject *keys = str_list(param_keys, num_params);
  PyObject *vals = str_list(param_vals, num_params);
  // reference contract: *num_outputs > 0 means the caller preallocated
  // output arrays — results are written into them in place
  bool prealloc = *num_outputs > 0 && *outputs != nullptr;
  PyObject *outs = prealloc ? handle_list(*outputs, *num_outputs)
                            : (Py_INCREF(Py_None), Py_None);
  PyObject *args = Py_BuildValue("(sNNNN)", op_name, ins, keys, vals,
                                 outs);
  PyObject *r = call("imperative_invoke", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  if (!prealloc) {
    mx_uint n = 0;
    fill_handles(r, &n, outputs);
    *num_outputs = static_cast<int>(n);
  }
  Py_DECREF(r);
  return 0;
}

// ---------------------------------------------------------------------------
// symbol
// ---------------------------------------------------------------------------

MXTPU_API int MXSymbolCreateVariable(const char *name, SymbolHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(s)", name);
  PyObject *r = call("symbol_create_variable", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolFree(SymbolHandle handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

MXTPU_API int MXSymbolCreateAtomicSymbol(const char *op_name,
                                         mx_uint num_param,
                                         const char **keys,
                                         const char **vals,
                                         SymbolHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *k = str_list(keys, num_param);
  PyObject *v = str_list(vals, num_param);
  PyObject *empty1 = PyList_New(0);
  PyObject *empty2 = PyList_New(0);
  PyObject *args = Py_BuildValue("(sNNNNs)", op_name, k, v, empty1,
                                 empty2, "");
  PyObject *r = call("symbol_create_atomic", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

// compose an atomic symbol with inputs: the CreateAtomicSymbol+Compose
// two-step every reference language binding uses. keys == NULL is
// positional; non-NULL keys compose by argument NAME (the bridge matches
// them against the op's declared input slots, ref: nnvm Symbol::Compose
// kwargs path).
MXTPU_API int MXSymbolCompose(SymbolHandle sym, const char *name,
                              mx_uint num_args, const char **keys,
                              SymbolHandle *args_h) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *ins = handle_list(args_h, num_args);
  PyObject *names = keys == nullptr
                        ? (Py_INCREF(Py_None), Py_None)
                        : str_list(keys, num_args);
  PyObject *args = Py_BuildValue("(OsNN)", static_cast<PyObject *>(sym),
                                 name ? name : "", ins, names);
  PyObject *r = call("symbol_compose", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXSymbolCreateAtomicSymbolEx(const char *op_name,
                                           mx_uint num_param,
                                           const char **keys,
                                           const char **vals,
                                           mx_uint num_inputs,
                                           SymbolHandle *inputs,
                                           const char *name,
                                           SymbolHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *k = str_list(keys, num_param);
  PyObject *v = str_list(vals, num_param);
  PyObject *ins = handle_list(inputs, num_inputs);
  PyObject *in_names = PyList_New(0);
  PyObject *args = Py_BuildValue("(sNNNNs)", op_name, k, v, ins, in_names,
                                 name ? name : "");
  PyObject *r = call("symbol_create_atomic", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(s)", json);
  PyObject *r = call("symbol_from_json", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolSaveToJSON(SymbolHandle sym, const char **out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(sym));
  PyObject *r = call("symbol_to_json", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  g_str_store.clear();
  const char *c = PyUnicode_AsUTF8(r);
  g_str_store.emplace_back(c ? c : "");
  Py_DECREF(r);
  *out = g_str_store.back().c_str();
  return 0;
}

static int list_via(const char *fn, SymbolHandle sym, mx_uint *out_size,
                    const char ***out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(sym));
  PyObject *r = call(fn, args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  fill_strs(r, out_size, out);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXSymbolListArguments(SymbolHandle sym, mx_uint *out_size,
                                    const char ***out) {
  return list_via("symbol_list_arguments", sym, out_size, out);
}

MXTPU_API int MXSymbolListOutputs(SymbolHandle sym, mx_uint *out_size,
                                  const char ***out) {
  return list_via("symbol_list_outputs", sym, out_size, out);
}

MXTPU_API int MXSymbolListAuxiliaryStates(SymbolHandle sym,
                                          mx_uint *out_size,
                                          const char ***out) {
  return list_via("symbol_list_aux", sym, out_size, out);
}

namespace {
// thread-local CSR-style shape storage for MXSymbolInferShape (the
// reference's per-thread MXAPIThreadLocalEntry layout)
struct ShapeSet {
  std::vector<mx_uint> ndim;
  std::vector<std::vector<mx_uint>> shapes;
  std::vector<const mx_uint *> ptrs;
};
thread_local ShapeSet g_in_shapes, g_out_shapes, g_aux_shapes;

void fill_shapeset(PyObject *list_of_shapes, ShapeSet *ss, mx_uint *size,
                   const mx_uint **ndim_out,
                   const mx_uint ***data_out) {
  Py_ssize_t n = PyList_Size(list_of_shapes);
  ss->ndim.clear();
  ss->shapes.assign(n, {});
  ss->ptrs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *shp = PyList_GetItem(list_of_shapes, i);
    Py_ssize_t d = PySequence_Size(shp);
    ss->ndim.push_back(static_cast<mx_uint>(d));
    for (Py_ssize_t j = 0; j < d; ++j) {
      PyObject *v = PySequence_GetItem(shp, j);
      ss->shapes[i].push_back((mx_uint)PyLong_AsUnsignedLong(v));
      Py_DECREF(v);
    }
  }
  for (auto &s : ss->shapes) ss->ptrs.push_back(s.data());
  *size = static_cast<mx_uint>(n);
  *ndim_out = ss->ndim.data();
  *data_out = ss->ptrs.data();
}
}  // namespace

MXTPU_API int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                                 const char **keys,
                                 const mx_uint *arg_ind_ptr,
                                 const mx_uint *arg_shape_data,
                                 mx_uint *in_shape_size,
                                 const mx_uint **in_shape_ndim,
                                 const mx_uint ***in_shape_data,
                                 mx_uint *out_shape_size,
                                 const mx_uint **out_shape_ndim,
                                 const mx_uint ***out_shape_data,
                                 mx_uint *aux_shape_size,
                                 const mx_uint **aux_shape_ndim,
                                 const mx_uint ***aux_shape_data,
                                 int *complete) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *names = str_list(keys, num_args);
  PyObject *shapes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    mx_uint lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject *shp = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(shp, j - lo,
                     PyLong_FromUnsignedLong(arg_shape_data[j]));
    PyList_SetItem(shapes, i, shp);
  }
  PyObject *args = Py_BuildValue("(ONN)", static_cast<PyObject *>(sym),
                                 names, shapes);
  PyObject *r = call("symbol_infer_shape", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  fill_shapeset(PyTuple_GetItem(r, 0), &g_in_shapes, in_shape_size,
                in_shape_ndim, in_shape_data);
  fill_shapeset(PyTuple_GetItem(r, 1), &g_out_shapes, out_shape_size,
                out_shape_ndim, out_shape_data);
  fill_shapeset(PyTuple_GetItem(r, 2), &g_aux_shapes, aux_shape_size,
                aux_shape_ndim, aux_shape_data);
  if (complete)
    *complete = (int)PyLong_AsLong(PyTuple_GetItem(r, 3));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXSymbolGetAtomicSymbolInfo(const char *op_name,
                                          const char **name,
                                          const char **description,
                                          const char **signature) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(s)", op_name);
  PyObject *r = call("symbol_get_atomic_symbol_info", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  g_str_store.clear();
  for (int i = 0; i < 3; ++i) {
    const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(r, i));
    g_str_store.emplace_back(c ? c : "");
  }
  Py_DECREF(r);
  *name = g_str_store[0].c_str();
  *description = g_str_store[1].c_str();
  *signature = g_str_store[2].c_str();
  return 0;
}

// ---------------------------------------------------------------------------
// executor
// ---------------------------------------------------------------------------

MXTPU_API int MXExecutorBind(SymbolHandle sym, mx_uint num_args,
                             const char **arg_names, NDArrayHandle *args_h,
                             mx_uint num_grads, const char **grad_names,
                             NDArrayHandle *grads_h, mx_uint num_aux,
                             const char **aux_names, NDArrayHandle *aux_h,
                             ExecutorHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *a = handle_list(args_h, num_args);
  PyObject *an = str_list(arg_names, num_args);
  PyObject *g = num_grads ? handle_list(grads_h, num_grads)
                          : PyList_New(0);
  PyObject *gn = num_grads ? str_list(grad_names, num_grads)
                           : PyList_New(0);
  PyObject *x = num_aux ? handle_list(aux_h, num_aux) : PyList_New(0);
  PyObject *xn = num_aux ? str_list(aux_names, num_aux) : PyList_New(0);
  PyObject *args = Py_BuildValue("(ONNNNNN)",
                                 static_cast<PyObject *>(sym), a, an, g,
                                 gn, x, xn);
  PyObject *r = call("executor_bind", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXExecutorFree(ExecutorHandle handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

MXTPU_API int MXExecutorForward(ExecutorHandle handle, int is_train) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(Oi)", static_cast<PyObject *>(handle),
                                 is_train);
  PyObject *r = call("executor_forward", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXExecutorBackward(ExecutorHandle handle, mx_uint num_grads,
                                 NDArrayHandle *grads_h) {
  ScopedGIL gil;
  PyObject *g = num_grads ? handle_list(grads_h, num_grads)
                          : PyList_New(0);
  PyObject *args = Py_BuildValue("(ON)", static_cast<PyObject *>(handle),
                                 g);
  PyObject *r = call("executor_backward", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size,
                                NDArrayHandle **out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(handle));
  PyObject *r = call("executor_outputs", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  fill_handles(r, out_size, out);
  Py_DECREF(r);
  return 0;
}

// ---------------------------------------------------------------------------
// autograd
// ---------------------------------------------------------------------------

MXTPU_API int MXAutogradSetIsRecording(int is_recording, int *prev) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(i)", is_recording);
  PyObject *r = call("autograd_set_recording", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  if (prev) *prev = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXAutogradSetIsTraining(int is_training, int *prev) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(i)", is_training);
  PyObject *r = call("autograd_set_training", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  if (prev) *prev = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXAutogradMarkVariables(mx_uint num, NDArrayHandle *vars) {
  ScopedGIL gil;
  PyObject *lst = handle_list(vars, num);
  PyObject *args = Py_BuildValue("(N)", lst);
  PyObject *r = call("autograd_mark_variables", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXAutogradBackward(mx_uint num, NDArrayHandle *outputs,
                                 NDArrayHandle *head_grads,
                                 int retain_graph) {
  ScopedGIL gil;
  PyObject *lst = handle_list(outputs, num);
  PyObject *heads = head_grads ? handle_list(head_grads, num)
                               : (Py_INCREF(Py_None), Py_None);
  PyObject *args = Py_BuildValue("(NNi)", lst, heads, retain_graph);
  PyObject *r = call("autograd_backward", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(handle));
  PyObject *r = call("autograd_get_grad", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

// ---------------------------------------------------------------------------
// kvstore
// ---------------------------------------------------------------------------

MXTPU_API int MXKVStoreCreate(const char *type, KVStoreHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(s)", type ? type : "local");
  PyObject *r = call("kvstore_create", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXKVStoreFree(KVStoreHandle handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

static int kv_op(const char *fn, KVStoreHandle kv, mx_uint num,
                 const char **keys, NDArrayHandle *vals) {
  ScopedGIL gil;
  PyObject *k = str_list(keys, num);
  PyObject *v = handle_list(vals, num);
  PyObject *args = Py_BuildValue("(ONN)", static_cast<PyObject *>(kv), k,
                                 v);
  PyObject *r = call(fn, args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStoreInitEx(KVStoreHandle kv, mx_uint num,
                              const char **keys, NDArrayHandle *vals) {
  return kv_op("kvstore_init", kv, num, keys, vals);
}

MXTPU_API int MXKVStorePushEx(KVStoreHandle kv, mx_uint num,
                              const char **keys, NDArrayHandle *vals,
                              int priority) {
  return kv_op("kvstore_push", kv, num, keys, vals);
}

MXTPU_API int MXKVStorePullEx(KVStoreHandle kv, mx_uint num,
                              const char **keys, NDArrayHandle *outs,
                              int priority) {
  return kv_op("kvstore_pull", kv, num, keys, outs);
}

MXTPU_API int MXKVStoreGetRank(KVStoreHandle kv, int *rank) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(kv));
  PyObject *r = call("kvstore_rank", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *rank = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStoreGetGroupSize(KVStoreHandle kv, int *size) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(kv));
  PyObject *r = call("kvstore_size", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *size = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

// ===========================================================================
// Round-3 C API expansion: symbol depth, DataIter, RecordIO, profiler,
// CachedOp, sparse NDArray, SimpleBind/Reshape/monitor, kvstore
// updater/server surface, legacy Function API, quantization, RTC.
// Signatures follow include/mxnet/c_api.h so existing consumers relink.
// ===========================================================================

namespace {

thread_local std::string g_str_single;
thread_local std::vector<uint64_t> g_u64_store;
thread_local std::vector<int> g_int_store;

// terse return-marshalers: every bridge call funnels through one of these
int rv(PyObject *r) {            // void return
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

int rh(PyObject *r, void **out) {  // handle return (ownership to caller)
  if (!r) { set_error(py_error()); return -1; }
  if (r == Py_None) { Py_DECREF(r); *out = nullptr; return 0; }
  *out = r;
  return 0;
}

int ri(PyObject *r, int *out) {
  if (!r) { set_error(py_error()); return -1; }
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

int rs(PyObject *r, const char **out) {  // single string (own TLS slot)
  if (!r) { set_error(py_error()); return -1; }
  const char *c = PyUnicode_AsUTF8(r);
  g_str_single = c ? c : "";
  Py_DECREF(r);
  *out = g_str_single.c_str();
  return 0;
}

int rsl(PyObject *r, mx_uint *out_n, const char ***out) {  // string list
  if (!r) { set_error(py_error()); return -1; }
  fill_strs(r, out_n, out);
  Py_DECREF(r);
  return 0;
}

int rhl(PyObject *r, mx_uint *out_n, NDArrayHandle **out) {  // handle list
  if (!r) { set_error(py_error()); return -1; }
  fill_handles(r, out_n, out);
  Py_DECREF(r);
  return 0;
}

PyObject *int_list(const int *data, mx_uint n) {
  PyObject *lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyLong_FromLong(data[i]));
  return lst;
}

// CSR-encoded shape args (MXSymbolInferShape wire format) -> list of lists
PyObject *csr_shapes(mx_uint num, const mx_uint *ind, const mx_uint *data) {
  PyObject *out = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i) {
    mx_uint lo = ind[i], hi = ind[i + 1];
    PyObject *shp = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(shp, j - lo, PyLong_FromUnsignedLong(data[j]));
    PyList_SetItem(out, i, shp);
  }
  return out;
}

}  // namespace

#define PREP ensure_interpreter(); ScopedGIL gil
#define H(x) static_cast<PyObject *>(x)

namespace {
// query each handle's real storage type through the bridge (the round-3
// sparse dispatch means outputs are no longer always dense)
int fill_stypes(NDArrayHandle *handles, int n, const int **out_stypes) {
  g_int_store.assign(n > 0 ? n : 0, 0);
  for (int i = 0; i < n; ++i) {
    if (!handles[i]) continue;
    PyObject *a = Py_BuildValue("(O)", H(handles[i]));
    PyObject *r = call("ndarray_get_storage_type", a);
    Py_DECREF(a);
    if (!r) { set_error(py_error()); return -1; }
    g_int_store[i] = (int)PyLong_AsLong(r);
    Py_DECREF(r);
  }
  *out_stypes = g_int_store.data();
  return 0;
}
}  // namespace

// ---------------------------------------------------------------------------
// symbol depth
// ---------------------------------------------------------------------------

MXTPU_API int MXSymbolCopy(SymbolHandle sym, SymbolHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(sym));
  PyObject *r = call("symbol_copy", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXSymbolCreateFromFile(const char *fname, SymbolHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(s)", fname);
  PyObject *r = call("symbol_from_file", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXSymbolSaveToFile(SymbolHandle sym, const char *fname) {
  PREP;
  PyObject *a = Py_BuildValue("(Os)", H(sym), fname);
  PyObject *r = call("symbol_save_to_file", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXSymbolCreateGroup(mx_uint num, SymbolHandle *syms,
                                  SymbolHandle *out) {
  PREP;
  PyObject *lst = handle_list(syms, num);
  PyObject *a = Py_BuildValue("(N)", lst);
  PyObject *r = call("symbol_create_group", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXSymbolPrint(SymbolHandle sym, const char **out_str) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(sym));
  PyObject *r = call("symbol_print", a); Py_DECREF(a);
  return rs(r, out_str);
}

static int sym_str_success(const char *fn, SymbolHandle sym,
                           const char *key, const char **out, int *success) {
  PREP;
  PyObject *a = key ? Py_BuildValue("(Os)", H(sym), key)
                    : Py_BuildValue("(O)", H(sym));
  PyObject *r = call(fn, a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(r, 0));
  g_str_single = c ? c : "";
  *success = (int)PyLong_AsLong(PyTuple_GetItem(r, 1));
  Py_DECREF(r);
  *out = *success ? g_str_single.c_str() : nullptr;
  return 0;
}

MXTPU_API int MXSymbolGetName(SymbolHandle sym, const char **out,
                              int *success) {
  return sym_str_success("symbol_get_name", sym, nullptr, out, success);
}

MXTPU_API int MXSymbolGetAttr(SymbolHandle sym, const char *key,
                              const char **out, int *success) {
  return sym_str_success("symbol_get_attr", sym, key, out, success);
}

MXTPU_API int MXSymbolSetAttr(SymbolHandle sym, const char *key,
                              const char *value) {
  PREP;
  PyObject *a = Py_BuildValue("(Oss)", H(sym), key, value);
  PyObject *r = call("symbol_set_attr", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXSymbolListAttr(SymbolHandle sym, mx_uint *out_size,
                               const char ***out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(sym));
  PyObject *r = call("symbol_list_attr", a); Py_DECREF(a);
  if (rsl(r, out_size, out)) return -1;
  *out_size /= 2;  // reference reports PAIR count
  return 0;
}

MXTPU_API int MXSymbolListAttrShallow(SymbolHandle sym, mx_uint *out_size,
                                      const char ***out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(sym));
  PyObject *r = call("symbol_list_attr_shallow", a); Py_DECREF(a);
  if (rsl(r, out_size, out)) return -1;
  *out_size /= 2;
  return 0;
}

MXTPU_API int MXSymbolGetInternals(SymbolHandle sym, SymbolHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(sym));
  PyObject *r = call("symbol_get_internals", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXSymbolGetChildren(SymbolHandle sym, SymbolHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(sym));
  PyObject *r = call("symbol_get_children", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXSymbolGetOutput(SymbolHandle sym, mx_uint index,
                                SymbolHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(OI)", H(sym), index);
  PyObject *r = call("symbol_get_output", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXSymbolGetNumOutputs(SymbolHandle sym, mx_uint *output_count) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(sym));
  PyObject *r = call("symbol_get_num_outputs", a); Py_DECREF(a);
  int v = 0;
  if (ri(r, &v)) return -1;
  *output_count = (mx_uint)v;
  return 0;
}

MXTPU_API int MXSymbolGrad(SymbolHandle sym, mx_uint num_wrt,
                           const char **wrt, SymbolHandle *out) {
  PREP;
  PyObject *w = str_list(wrt, num_wrt);
  PyObject *a = Py_BuildValue("(ON)", H(sym), w);
  PyObject *r = call("symbol_grad", a); Py_DECREF(a);
  return rh(r, out);  // bridge raises: parity with reference LOG(FATAL)
}

MXTPU_API int MXSymbolCutSubgraph(SymbolHandle sym, SymbolHandle **inputs,
                                  int *input_size) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(sym));
  PyObject *r = call("symbol_cut_subgraph", a); Py_DECREF(a);
  mx_uint n = 0;
  if (rhl(r, &n, reinterpret_cast<NDArrayHandle **>(inputs))) return -1;
  *input_size = (int)n;
  return 0;
}

MXTPU_API int MXSymbolListAtomicSymbolCreators(mx_uint *out_size,
                                               void ***out_array) {
  PREP;
  PyObject *r = call("symbol_list_atomic_symbol_creators", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  // creator handles ARE interned python op-name strings
  Py_ssize_t n = PyList_Size(r);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(r, i);
    Py_INCREF(o);
    g_handle_store.push_back(o);
  }
  Py_DECREF(r);
  *out_size = (mx_uint)n;
  *out_array = g_handle_store.data();
  return 0;
}

MXTPU_API int MXSymbolGetAtomicSymbolName(void *creator, const char **name) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(creator));
  PyObject *r = call("symbol_get_atomic_symbol_name", a); Py_DECREF(a);
  return rs(r, name);
}

// shape-list TLS: three parallel stores (arg/out/aux), reused per call
thread_local std::vector<std::vector<mx_uint>> g_shape_lists[3];
thread_local std::vector<const mx_uint *> g_shape_ptrs[3];
thread_local std::vector<mx_uint> g_shape_ndims[3];

static void fill_shapes(PyObject *lst, int slot, mx_uint *out_n,
                        const mx_uint ***out_data, const mx_uint **out_ndim) {
  Py_ssize_t n = PyList_Size(lst);
  auto &lists = g_shape_lists[slot];
  auto &ptrs = g_shape_ptrs[slot];
  auto &ndims = g_shape_ndims[slot];
  lists.assign(n, {});
  ptrs.assign(n, nullptr);
  ndims.assign(n, 0);
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *shp = PyList_GetItem(lst, i);
    Py_ssize_t d = PyList_Size(shp);
    for (Py_ssize_t j = 0; j < d; ++j)
      lists[i].push_back(
          (mx_uint)PyLong_AsUnsignedLong(PyList_GetItem(shp, j)));
    ptrs[i] = lists[i].data();
    ndims[i] = (mx_uint)d;
  }
  *out_n = (mx_uint)n;
  *out_data = ptrs.data();
  *out_ndim = ndims.data();
}

static int infer_shape_common(const char *which, SymbolHandle sym,
                              mx_uint num_args, const char **keys,
                              const mx_uint *arg_ind_ptr,
                              const mx_uint *arg_shape_data, int partial,
                              mx_uint *in_n, const mx_uint **in_ndim,
                              const mx_uint ***in_data, mx_uint *out_n,
                              const mx_uint **out_ndim,
                              const mx_uint ***out_data, mx_uint *aux_n,
                              const mx_uint **aux_ndim,
                              const mx_uint ***aux_data, int *complete) {
  PREP;
  PyObject *k = str_list(keys, num_args);
  PyObject *shp = csr_shapes(num_args, arg_ind_ptr, arg_shape_data);
  PyObject *a = Py_BuildValue("(ONNi)", H(sym), k, shp, partial);
  PyObject *r = call(which, a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  fill_shapes(PyTuple_GetItem(r, 0), 0, in_n, in_data, in_ndim);
  fill_shapes(PyTuple_GetItem(r, 1), 1, out_n, out_data, out_ndim);
  fill_shapes(PyTuple_GetItem(r, 2), 2, aux_n, aux_data, aux_ndim);
  *complete = PyTuple_Size(r) > 3
      ? (int)PyLong_AsLong(PyTuple_GetItem(r, 3)) : 1;
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXSymbolInferShapePartial(
    SymbolHandle sym, mx_uint num_args, const char **keys,
    const mx_uint *arg_ind_ptr, const mx_uint *arg_shape_data,
    mx_uint *in_shape_size, const mx_uint **in_shape_ndim,
    const mx_uint ***in_shape_data, mx_uint *out_shape_size,
    const mx_uint **out_shape_ndim, const mx_uint ***out_shape_data,
    mx_uint *aux_shape_size, const mx_uint **aux_shape_ndim,
    const mx_uint ***aux_shape_data, int *complete) {
  return infer_shape_common("symbol_infer_shape_impl", sym, num_args, keys,
                            arg_ind_ptr, arg_shape_data, 1, in_shape_size,
                            in_shape_ndim, in_shape_data, out_shape_size,
                            out_shape_ndim, out_shape_data, aux_shape_size,
                            aux_shape_ndim, aux_shape_data, complete);
}

static int infer_type_common(SymbolHandle sym, mx_uint num_args,
                             const char **keys, const int *arg_type_data,
                             int partial, mx_uint *in_n, const int **in_t,
                             mx_uint *out_n, const int **out_t,
                             mx_uint *aux_n, const int **aux_t,
                             int *complete) {
  PREP;
  PyObject *k = str_list(keys, num_args);
  PyObject *t = int_list(arg_type_data, num_args);
  PyObject *a = Py_BuildValue("(ONNi)", H(sym), k, t, partial);
  PyObject *r = call("symbol_infer_type_impl", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  thread_local std::vector<int> stores[3];
  mx_uint *ns[3] = {in_n, out_n, aux_n};
  const int **outs[3] = {in_t, out_t, aux_t};
  for (int s = 0; s < 3; ++s) {
    PyObject *lst = PyTuple_GetItem(r, s);
    Py_ssize_t n = PyList_Size(lst);
    stores[s].assign(n, -1);
    for (Py_ssize_t i = 0; i < n; ++i)
      stores[s][i] = (int)PyLong_AsLong(PyList_GetItem(lst, i));
    *ns[s] = (mx_uint)n;
    *outs[s] = stores[s].data();
  }
  *complete = PyTuple_Size(r) > 3
      ? (int)PyLong_AsLong(PyTuple_GetItem(r, 3)) : 1;
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXSymbolInferType(SymbolHandle sym, mx_uint num_args,
                                const char **keys, const int *arg_type_data,
                                mx_uint *in_type_size, const int **in_type_data,
                                mx_uint *out_type_size,
                                const int **out_type_data,
                                mx_uint *aux_type_size,
                                const int **aux_type_data, int *complete) {
  return infer_type_common(sym, num_args, keys, arg_type_data, 0,
                           in_type_size, in_type_data, out_type_size,
                           out_type_data, aux_type_size, aux_type_data,
                           complete);
}

MXTPU_API int MXSymbolInferTypePartial(
    SymbolHandle sym, mx_uint num_args, const char **keys,
    const int *arg_type_data, mx_uint *in_type_size, const int **in_type_data,
    mx_uint *out_type_size, const int **out_type_data, mx_uint *aux_type_size,
    const int **aux_type_data, int *complete) {
  return infer_type_common(sym, num_args, keys, arg_type_data, 1,
                           in_type_size, in_type_data, out_type_size,
                           out_type_data, aux_type_size, aux_type_data,
                           complete);
}

// ---------------------------------------------------------------------------
// DataIter
// ---------------------------------------------------------------------------

MXTPU_API int MXListDataIters(mx_uint *out_size, void ***out_array) {
  PREP;
  PyObject *r = call("list_data_iters", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  Py_ssize_t n = PyList_Size(r);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(r, i);
    Py_INCREF(o);
    g_handle_store.push_back(o);  // creator handle = iterator-name string
  }
  Py_DECREF(r);
  *out_size = (mx_uint)n;
  *out_array = g_handle_store.data();
  return 0;
}

MXTPU_API int MXDataIterCreateIter(void *creator, mx_uint num_param,
                                   const char **keys, const char **vals,
                                   void **out) {
  PREP;
  PyObject *k = str_list(keys, num_param);
  PyObject *v = str_list(vals, num_param);
  PyObject *a = Py_BuildValue("(ONN)", H(creator), k, v);
  PyObject *r = call("data_iter_create", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXDataIterGetIterInfo(void *creator, const char **name,
                                    const char **description,
                                    mx_uint *num_args,
                                    const char ***arg_names,
                                    const char ***arg_type_infos,
                                    const char ***arg_descriptions) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(creator));
  PyObject *r = call("data_iter_get_info", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  thread_local std::string s_name, s_desc;
  const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(r, 0));
  s_name = c ? c : "";
  c = PyUnicode_AsUTF8(PyTuple_GetItem(r, 1));
  s_desc = c ? c : "";
  fill_strs(PyTuple_GetItem(r, 2), num_args, arg_names);
  Py_DECREF(r);
  *name = s_name.c_str();
  *description = s_desc.c_str();
  *arg_type_infos = *arg_names;     // typed metadata folded into names
  *arg_descriptions = *arg_names;
  return 0;
}

MXTPU_API int MXDataIterFree(void *handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(H(handle));
  return 0;
}

MXTPU_API int MXDataIterNext(void *handle, int *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("data_iter_next", a); Py_DECREF(a);
  return ri(r, out);
}

MXTPU_API int MXDataIterBeforeFirst(void *handle) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("data_iter_before_first", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXDataIterGetData(void *handle, NDArrayHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("data_iter_get_data", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXDataIterGetLabel(void *handle, NDArrayHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("data_iter_get_label", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXDataIterGetIndex(void *handle, uint64_t **out_index,
                                 uint64_t *out_size) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("data_iter_get_index", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  Py_ssize_t n = PyList_Size(r);
  g_u64_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i)
    g_u64_store.push_back(
        (uint64_t)PyLong_AsUnsignedLongLong(PyList_GetItem(r, i)));
  Py_DECREF(r);
  *out_index = g_u64_store.data();
  *out_size = (uint64_t)n;
  return 0;
}

MXTPU_API int MXDataIterGetPadNum(void *handle, int *pad) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("data_iter_get_pad_num", a); Py_DECREF(a);
  return ri(r, pad);
}

// ---------------------------------------------------------------------------
// RecordIO
// ---------------------------------------------------------------------------

MXTPU_API int MXRecordIOWriterCreate(const char *uri, void **out) {
  PREP;
  PyObject *a = Py_BuildValue("(s)", uri);
  PyObject *r = call("recordio_writer_create", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXRecordIOReaderCreate(const char *uri, void **out) {
  PREP;
  PyObject *a = Py_BuildValue("(s)", uri);
  PyObject *r = call("recordio_reader_create", a); Py_DECREF(a);
  return rh(r, out);
}

static int recordio_free(void *handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("recordio_close", a); Py_DECREF(a);
  Py_DECREF(H(handle));
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXRecordIOWriterFree(void *handle) {
  return recordio_free(handle);
}

MXTPU_API int MXRecordIOReaderFree(void *handle) {
  return recordio_free(handle);
}

MXTPU_API int MXRecordIOWriterWriteRecord(void *handle, const char *buf,
                                          size_t size) {
  PREP;
  PyObject *a = Py_BuildValue("(OKK)", H(handle),
                              (unsigned long long)(uintptr_t)buf,
                              (unsigned long long)size);
  PyObject *r = call("recordio_write_record", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXRecordIOReaderReadRecord(void *handle, char const **buf,
                                         size_t *size) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("recordio_read_record", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  if (r == Py_None) { Py_DECREF(r); *buf = nullptr; *size = 0; return 0; }
  *buf = (const char *)(uintptr_t)
      PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 0));
  *size = (size_t)PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 1));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXRecordIOReaderSeek(void *handle, size_t pos) {
  PREP;
  PyObject *a = Py_BuildValue("(OK)", H(handle), (unsigned long long)pos);
  PyObject *r = call("recordio_reader_seek", a); Py_DECREF(a);
  return rv(r);
}

static int recordio_tell(void *handle, size_t *pos) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("recordio_tell", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  *pos = (size_t)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXRecordIOWriterTell(void *handle, size_t *pos) {
  return recordio_tell(handle, pos);
}

MXTPU_API int MXRecordIOReaderTell(void *handle, size_t *pos) {
  return recordio_tell(handle, pos);
}

// ---------------------------------------------------------------------------
// profiler
// ---------------------------------------------------------------------------

MXTPU_API int MXSetProfilerConfig(int num_params, const char *const *keys,
                                  const char *const *vals) {
  PREP;
  PyObject *k = str_list(const_cast<const char **>(keys), num_params);
  PyObject *v = str_list(const_cast<const char **>(vals), num_params);
  PyObject *a = Py_BuildValue("(NN)", k, v);
  PyObject *r = call("profiler_set_config", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXSetProcessProfilerConfig(int num_params,
                                         const char *const *keys,
                                         const char *const *vals,
                                         KVStoreHandle kv) {
  (void)kv;  // no separate server processes to configure
  return MXSetProfilerConfig(num_params, keys, vals);
}

MXTPU_API int MXSetProfilerState(int state) {
  PREP;
  PyObject *a = Py_BuildValue("(i)", state);
  PyObject *r = call("profiler_set_state", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXSetProcessProfilerState(int state, int profile_process,
                                        KVStoreHandle kv) {
  (void)profile_process; (void)kv;
  return MXSetProfilerState(state);
}

MXTPU_API int MXDumpProfile(int finished) {
  PREP;
  PyObject *a = Py_BuildValue("(i)", finished);
  PyObject *r = call("profiler_dump", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXDumpProcessProfile(int finished, int profile_process,
                                   KVStoreHandle kv) {
  (void)profile_process; (void)kv;
  return MXDumpProfile(finished);
}

MXTPU_API int MXProfilePause(int paused) {
  PREP;
  PyObject *a = Py_BuildValue("(i)", paused);
  PyObject *r = call("profiler_pause", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXProcessProfilePause(int paused, int profile_process,
                                    KVStoreHandle kv) {
  (void)profile_process; (void)kv;
  return MXProfilePause(paused);
}

MXTPU_API int MXAggregateProfileStatsPrint(const char **out_str, int reset) {
  PREP;
  PyObject *a = Py_BuildValue("(i)", reset);
  PyObject *r = call("profiler_aggregate_stats", a); Py_DECREF(a);
  return rs(r, out_str);
}

MXTPU_API int MXProfileCreateDomain(const char *domain, void **out) {
  PREP;
  PyObject *a = Py_BuildValue("(s)", domain);
  PyObject *r = call("profile_create_domain", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXProfileCreateTask(void *domain, const char *name,
                                  void **out) {
  PREP;
  PyObject *a = Py_BuildValue("(Os)", H(domain), name);
  PyObject *r = call("profile_create_task", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXProfileCreateFrame(void *domain, const char *name,
                                   void **out) {
  PREP;
  PyObject *a = Py_BuildValue("(Os)", H(domain), name);
  PyObject *r = call("profile_create_frame", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXProfileCreateEvent(const char *name, void **out) {
  PREP;
  PyObject *a = Py_BuildValue("(s)", name);
  PyObject *r = call("profile_create_event", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXProfileCreateCounter(void *domain, const char *name,
                                     void **out) {
  PREP;
  PyObject *a = Py_BuildValue("(OsO)", H(domain), name, Py_None);
  PyObject *r = call("profile_create_counter", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXProfileDestroyHandle(void *handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(H(handle));
  return 0;
}

MXTPU_API int MXProfileDurationStart(void *handle) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("profile_duration_start", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXProfileDurationStop(void *handle) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("profile_duration_stop", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXProfileSetCounter(void *handle, uint64_t value) {
  PREP;
  PyObject *a = Py_BuildValue("(OK)", H(handle), (unsigned long long)value);
  PyObject *r = call("profile_set_counter", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXProfileAdjustCounter(void *handle, int64_t delta) {
  PREP;
  PyObject *a = Py_BuildValue("(OL)", H(handle), (long long)delta);
  PyObject *r = call("profile_adjust_counter", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXProfileSetMarker(void *domain, const char *name,
                                 const char *scope) {
  PREP;
  PyObject *a = Py_BuildValue("(Oss)", H(domain), name, scope);
  PyObject *r = call("profile_set_marker", a); Py_DECREF(a);
  return rv(r);
}

// ---------------------------------------------------------------------------
// CachedOp
// ---------------------------------------------------------------------------

MXTPU_API int MXCreateCachedOpEx(SymbolHandle sym, int num_flags,
                                 const char **keys, const char **vals,
                                 void **out) {
  PREP;
  PyObject *k = str_list(keys, num_flags);
  PyObject *v = str_list(vals, num_flags);
  PyObject *a = Py_BuildValue("(ONN)", H(sym), k, v);
  PyObject *r = call("cached_op_create", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXCreateCachedOp(SymbolHandle sym, void **out) {
  return MXCreateCachedOpEx(sym, 0, nullptr, nullptr, out);
}

MXTPU_API int MXFreeCachedOp(void *handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(H(handle));
  return 0;
}

MXTPU_API int MXInvokeCachedOp(void *handle, int num_inputs,
                               NDArrayHandle *inputs, int *num_outputs,
                               NDArrayHandle **outputs) {
  PREP;
  PyObject *ins = handle_list(inputs, num_inputs);
  PyObject *a = Py_BuildValue("(ON)", H(handle), ins);
  PyObject *r = call("cached_op_invoke", a); Py_DECREF(a);
  mx_uint n = 0;
  if (rhl(r, &n, outputs)) return -1;
  *num_outputs = (int)n;
  return 0;
}

MXTPU_API int MXInvokeCachedOpEx(void *handle, int num_inputs,
                                 NDArrayHandle *inputs, int *num_outputs,
                                 NDArrayHandle **outputs,
                                 const int **out_stypes) {
  if (MXInvokeCachedOp(handle, num_inputs, inputs, num_outputs, outputs))
    return -1;
  ScopedGIL gil;
  return fill_stypes(*outputs, *num_outputs, out_stypes);
}

// ---------------------------------------------------------------------------
// sparse NDArray
// ---------------------------------------------------------------------------

MXTPU_API int MXNDArrayCreateSparseEx(
    int storage_type, const mx_uint *shape, mx_uint ndim, int dev_type,
    int dev_id, int delay_alloc, int dtype, mx_uint num_aux, int *aux_type,
    mx_uint *aux_ndims, const mx_uint *aux_shape, NDArrayHandle *out) {
  (void)dev_type; (void)dev_id; (void)delay_alloc;
  (void)num_aux; (void)aux_type; (void)aux_ndims; (void)aux_shape;
  PREP;
  PyObject *shp = uint_list(shape, ndim);
  PyObject *a = Py_BuildValue("(iNi)", storage_type, shp, dtype);
  PyObject *r = call("ndarray_create_sparse", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXNDArrayGetStorageType(NDArrayHandle handle,
                                      int *out_storage_type) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_get_storage_type", a); Py_DECREF(a);
  return ri(r, out_storage_type);
}

MXTPU_API int MXNDArrayGetAuxNDArray(NDArrayHandle handle, mx_uint i,
                                     NDArrayHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(OI)", H(handle), i);
  PyObject *r = call("ndarray_get_aux_ndarray", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXNDArrayGetAuxType(NDArrayHandle handle, mx_uint i,
                                  int *out_type) {
  PREP;
  PyObject *a = Py_BuildValue("(OI)", H(handle), i);
  PyObject *r = call("ndarray_get_aux_type", a); Py_DECREF(a);
  return ri(r, out_type);
}

MXTPU_API int MXNDArrayGetDataNDArray(NDArrayHandle handle,
                                      NDArrayHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_get_data_ndarray", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXNDArraySyncCheckFormat(NDArrayHandle handle,
                                       const bool full_check) {
  PREP;
  PyObject *a = Py_BuildValue("(Oi)", H(handle), full_check ? 1 : 0);
  PyObject *r = call("ndarray_sync_check_format", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXNDArraySyncCopyFromNDArray(NDArrayHandle dst,
                                           const NDArrayHandle src,
                                           const int i) {
  PREP;
  PyObject *a = Py_BuildValue("(OOi)", H(dst), H(src), i);
  PyObject *r = call("ndarray_sync_copy_from_ndarray", a); Py_DECREF(a);
  return rv(r);
}

// ---------------------------------------------------------------------------
// NDArray depth
// ---------------------------------------------------------------------------

MXTPU_API int MXNDArrayWaitToRead(NDArrayHandle handle) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_wait_to_read", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXNDArrayWaitToWrite(NDArrayHandle handle) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_wait_to_write", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_detach", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXNDArrayGetContext(NDArrayHandle handle, int *out_dev_type,
                                  int *out_dev_id) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_get_context", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  *out_dev_type = (int)PyLong_AsLong(PyTuple_GetItem(r, 0));
  *out_dev_id = (int)PyLong_AsLong(PyTuple_GetItem(r, 1));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetData(NDArrayHandle handle, void **out_pdata) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_get_data_ptr", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  *out_pdata = (void *)(uintptr_t)PyLong_AsUnsignedLongLong(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetGradState(NDArrayHandle handle, int *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_get_grad_state", a); Py_DECREF(a);
  return ri(r, out);
}

MXTPU_API int MXNDArraySetGradState(NDArrayHandle handle, int state) {
  PREP;
  PyObject *a = Py_BuildValue("(Oi)", H(handle), state);
  PyObject *r = call("ndarray_set_grad_state", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXNDArrayReshape64(NDArrayHandle handle, int ndim,
                                 int64_t *dims, bool reverse,
                                 NDArrayHandle *out) {
  PREP;
  PyObject *shp = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyList_SetItem(shp, i, PyLong_FromLongLong(dims[i]));
  PyObject *a = Py_BuildValue("(ONi)", H(handle), shp, reverse ? 1 : 0);
  PyObject *r = call("ndarray_reshape64", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t *out_size,
                                    const char **out_buf) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_save_raw_bytes", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  *out_buf = (const char *)(uintptr_t)
      PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 0));
  *out_size = (size_t)PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 1));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayLoadFromRawBytes(const void *buf, size_t size,
                                        NDArrayHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(KK)", (unsigned long long)(uintptr_t)buf,
                              (unsigned long long)size);
  PyObject *r = call("ndarray_load_from_raw_bytes", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXNDArrayLoadFromBuffer(const void *buf, size_t size,
                                      mx_uint *out_size,
                                      NDArrayHandle **out_arr,
                                      mx_uint *out_name_size,
                                      const char ***out_names) {
  PREP;
  PyObject *a = Py_BuildValue("(KK)", (unsigned long long)(uintptr_t)buf,
                              (unsigned long long)size);
  PyObject *r = call("ndarray_load_from_buffer", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  fill_strs(PyTuple_GetItem(r, 0), out_name_size, out_names);
  fill_handles(PyTuple_GetItem(r, 1), out_size, out_arr);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetSharedMemHandle(NDArrayHandle handle,
                                          int *shared_pid, int *shared_id) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_get_shared_mem_handle", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  *shared_pid = (int)PyLong_AsLong(PyTuple_GetItem(r, 0));
  *shared_id = (int)PyLong_AsLong(PyTuple_GetItem(r, 1));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayCreateFromSharedMem(int shared_pid, int shared_id,
                                           const mx_uint *shape,
                                           mx_uint ndim, int dtype,
                                           NDArrayHandle *out) {
  PREP;
  PyObject *shp = uint_list(shape, ndim);
  PyObject *a = Py_BuildValue("(iiNis)", shared_pid, shared_id, shp, dtype,
                              "");
  PyObject *r = call("ndarray_create_from_shared_mem", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXNDArrayToDLPack(NDArrayHandle handle, void **out_dlpack) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("ndarray_to_dlpack", a); Py_DECREF(a);
  return rh(r, out_dlpack);
}

MXTPU_API int MXNDArrayFromDLPack(void *dlpack, NDArrayHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(dlpack));
  PyObject *r = call("ndarray_from_dlpack", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXNDArrayCallDLPackDeleter(void *dlpack) {
  if (!dlpack) return 0;
  ScopedGIL gil;
  Py_DECREF(H(dlpack));
  return 0;
}

// ---------------------------------------------------------------------------
// executor depth
// ---------------------------------------------------------------------------

MXTPU_API int MXExecutorSimpleBind(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const mx_uint num_g2c_keys, const char **g2c_keys,
    const int *g2c_dev_types, const int *g2c_dev_ids,
    const mx_uint provided_grad_req_list_len,
    const char **provided_grad_req_names,
    const char **provided_grad_req_types,
    const mx_uint num_provided_arg_shapes,
    const char **provided_arg_shape_names,
    const mx_uint *provided_arg_shape_data,
    const mx_uint *provided_arg_shape_idx,
    const mx_uint num_provided_arg_dtypes,
    const char **provided_arg_dtype_names, const int *provided_arg_dtypes,
    const mx_uint num_provided_arg_stypes,
    const char **provided_arg_stype_names, const int *provided_arg_stypes,
    const mx_uint num_shared_arg_names, const char **shared_arg_name_list,
    int *shared_buffer_len, const char **shared_buffer_name_list,
    NDArrayHandle *shared_buffer_handle_list,
    const char ***updated_shared_buffer_name_list,
    NDArrayHandle **updated_shared_buffer_handle_list,
    mx_uint *num_in_args, NDArrayHandle **in_args, NDArrayHandle **arg_grads,
    mx_uint *num_aux_states, NDArrayHandle **aux_states,
    ExecutorHandle shared_exec_handle, ExecutorHandle *out) {
  (void)dev_type; (void)dev_id; (void)num_g2c_keys; (void)g2c_keys;
  (void)g2c_dev_types; (void)g2c_dev_ids;
  (void)num_provided_arg_dtypes; (void)provided_arg_dtype_names;
  (void)provided_arg_dtypes; (void)num_provided_arg_stypes;
  (void)provided_arg_stype_names; (void)provided_arg_stypes;
  (void)num_shared_arg_names; (void)shared_arg_name_list;
  (void)shared_buffer_name_list; (void)shared_buffer_handle_list;
  (void)shared_exec_handle;
  PREP;
  const char *grad_req = provided_grad_req_list_len > 0
      ? provided_grad_req_types[0] : "write";
  PyObject *names = str_list(provided_arg_shape_names,
                             num_provided_arg_shapes);
  PyObject *shapes = csr_shapes(num_provided_arg_shapes,
                                provided_arg_shape_idx,
                                provided_arg_shape_data);
  PyObject *a = Py_BuildValue("(ONNs)", H(symbol_handle), names, shapes,
                              grad_req);
  PyObject *r = call("executor_simple_bind", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  // shared buffers pass through unchanged (XLA owns pooling)
  if (shared_buffer_len && *shared_buffer_len >= 0) {
    *updated_shared_buffer_name_list = nullptr;
    *updated_shared_buffer_handle_list = nullptr;
    *shared_buffer_len = 0;
  }
  thread_local std::vector<void *> arg_store, grad_store, aux_store;
  PyObject *ex = PyTuple_GetItem(r, 0);
  PyObject *args_l = PyTuple_GetItem(r, 1);
  PyObject *grads_l = PyTuple_GetItem(r, 2);
  PyObject *aux_l = PyTuple_GetItem(r, 3);
  auto fill = [](PyObject *lst, std::vector<void *> &store) {
    store.clear();
    for (Py_ssize_t i = 0; i < PyList_Size(lst); ++i) {
      PyObject *o = PyList_GetItem(lst, i);
      if (o == Py_None) { store.push_back(nullptr); continue; }
      Py_INCREF(o);
      store.push_back(o);
    }
  };
  fill(args_l, arg_store);
  fill(grads_l, grad_store);
  fill(aux_l, aux_store);
  *num_in_args = (mx_uint)arg_store.size();
  *in_args = arg_store.data();
  *arg_grads = grad_store.empty() ? nullptr : grad_store.data();
  *num_aux_states = (mx_uint)aux_store.size();
  *aux_states = aux_store.data();
  Py_INCREF(ex);
  *out = ex;
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXExecutorReshape(
    int partial_shaping, int allow_up_sizing, int dev_type, int dev_id,
    mx_uint num_map_keys, const char **map_keys, const int *map_dev_types,
    const int *map_dev_ids, const mx_uint num_provided_arg_shapes,
    const char **provided_arg_shape_names,
    const mx_uint *provided_arg_shape_data,
    const mx_uint *provided_arg_shape_idx, mx_uint *num_in_args,
    NDArrayHandle **in_args, NDArrayHandle **arg_grads,
    mx_uint *num_aux_states, NDArrayHandle **aux_states,
    ExecutorHandle shared_exec, ExecutorHandle *out) {
  (void)partial_shaping; (void)allow_up_sizing; (void)dev_type;
  (void)dev_id; (void)num_map_keys; (void)map_keys; (void)map_dev_types;
  (void)map_dev_ids; (void)shared_exec;
  PREP;
  if (!shared_exec) {
    set_error("MXExecutorReshape: shared_exec handle required");
    return -1;
  }
  PyObject *names = str_list(provided_arg_shape_names,
                             num_provided_arg_shapes);
  PyObject *shapes = csr_shapes(num_provided_arg_shapes,
                                provided_arg_shape_idx,
                                provided_arg_shape_data);
  PyObject *a = Py_BuildValue("(ONN)", H(shared_exec), names, shapes);
  PyObject *r = call("executor_reshape", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  thread_local std::vector<void *> arg_store, aux_store;
  auto fill = [](PyObject *lst, std::vector<void *> &store) {
    store.clear();
    for (Py_ssize_t i = 0; i < PyList_Size(lst); ++i) {
      PyObject *o = PyList_GetItem(lst, i);
      Py_INCREF(o);
      store.push_back(o);
    }
  };
  fill(PyTuple_GetItem(r, 1), arg_store);
  fill(PyTuple_GetItem(r, 2), aux_store);
  *num_in_args = (mx_uint)arg_store.size();
  *in_args = arg_store.data();
  *arg_grads = nullptr;
  *num_aux_states = (mx_uint)aux_store.size();
  *aux_states = aux_store.data();
  PyObject *ex = PyTuple_GetItem(r, 0);
  Py_INCREF(ex);
  *out = ex;
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXExecutorBindX(SymbolHandle sym, int dev_type, int dev_id,
                              mx_uint num_map_keys, const char **map_keys,
                              const int *map_dev_types,
                              const int *map_dev_ids, mx_uint len,
                              NDArrayHandle *in_args,
                              NDArrayHandle *arg_grad_store,
                              mx_uint *grad_req_type, mx_uint aux_len,
                              NDArrayHandle *aux_states,
                              ExecutorHandle *out) {
  (void)dev_type; (void)dev_id; (void)num_map_keys; (void)map_keys;
  (void)map_dev_types; (void)map_dev_ids; (void)grad_req_type;
  PREP;
  // name-align positional arrays against the symbol's argument list
  PyObject *a0 = Py_BuildValue("(O)", H(sym));
  PyObject *arg_names_obj = call("symbol_list_arguments", a0);
  Py_DECREF(a0);
  if (!arg_names_obj) { set_error(py_error()); return -1; }
  PyObject *aux0 = Py_BuildValue("(O)", H(sym));
  PyObject *aux_names_obj = call("symbol_list_aux", aux0);
  Py_DECREF(aux0);
  if (!aux_names_obj) {
    Py_DECREF(arg_names_obj);
    set_error(py_error());
    return -1;
  }
  PyObject *args_l = handle_list(in_args, len);
  PyObject *grads_l = arg_grad_store ? handle_list(arg_grad_store, len)
                                     : PyList_New(0);
  PyObject *aux_l = handle_list(aux_states, aux_len);
  PyObject *a = Py_BuildValue("(ONONONO)", H(sym), args_l, arg_names_obj,
                              grads_l, arg_names_obj, aux_l,
                              aux_names_obj);
  // note: Py_BuildValue 'O' increfs arg_names_obj for each use
  PyObject *r = call("executor_bind", a);
  Py_DECREF(a);
  Py_DECREF(arg_names_obj);
  Py_DECREF(aux_names_obj);
  return rh(r, out);
}

MXTPU_API int MXExecutorBindEX(SymbolHandle sym, int dev_type, int dev_id,
                               mx_uint num_map_keys, const char **map_keys,
                               const int *map_dev_types,
                               const int *map_dev_ids, mx_uint len,
                               NDArrayHandle *in_args,
                               NDArrayHandle *arg_grad_store,
                               mx_uint *grad_req_type, mx_uint aux_len,
                               NDArrayHandle *aux_states,
                               ExecutorHandle shared_exec,
                               ExecutorHandle *out) {
  (void)shared_exec;  // XLA owns cross-executor memory sharing
  return MXExecutorBindX(sym, dev_type, dev_id, num_map_keys, map_keys,
                         map_dev_types, map_dev_ids, len, in_args,
                         arg_grad_store, grad_req_type, aux_len, aux_states,
                         out);
}

MXTPU_API int MXExecutorBackwardEx(ExecutorHandle handle, mx_uint len,
                                   NDArrayHandle *head_grads, int is_train) {
  PREP;
  PyObject *grads = len ? handle_list(head_grads, len)
                        : (Py_INCREF(Py_None), Py_None);
  PyObject *a = Py_BuildValue("(ONi)", H(handle), grads, is_train);
  PyObject *r = call("executor_backward_ex", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXExecutorPrint(ExecutorHandle handle, const char **out_str) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("executor_print", a); Py_DECREF(a);
  return rs(r, out_str);
}

MXTPU_API int MXExecutorGetOptimizedSymbol(ExecutorHandle handle,
                                           SymbolHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("executor_get_optimized_symbol", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                           void (*callback)(const char *,
                                                            NDArrayHandle,
                                                            void *),
                                           void *callback_handle) {
  PREP;
  PyObject *a = Py_BuildValue("(OKKi)", H(handle),
                              (unsigned long long)(uintptr_t)callback,
                              (unsigned long long)(uintptr_t)callback_handle,
                              0);
  PyObject *r = call("executor_set_monitor_callback", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXExecutorSetMonitorCallbackEX(ExecutorHandle handle,
                                             void (*callback)(const char *,
                                                              NDArrayHandle,
                                                              void *),
                                             void *callback_handle,
                                             bool monitor_all) {
  PREP;
  PyObject *a = Py_BuildValue("(OKKi)", H(handle),
                              (unsigned long long)(uintptr_t)callback,
                              (unsigned long long)(uintptr_t)callback_handle,
                              monitor_all ? 1 : 0);
  PyObject *r = call("executor_set_monitor_callback", a); Py_DECREF(a);
  return rv(r);
}

// ---------------------------------------------------------------------------
// autograd depth + imperative Ex
// ---------------------------------------------------------------------------

MXTPU_API int MXAutogradIsRecording(bool *curr) {
  PREP;
  PyObject *r = call("autograd_is_recording", nullptr);
  int v = 0;
  if (ri(r, &v)) return -1;
  *curr = v != 0;
  return 0;
}

MXTPU_API int MXAutogradIsTraining(bool *curr) {
  PREP;
  PyObject *r = call("autograd_is_training", nullptr);
  int v = 0;
  if (ri(r, &v)) return -1;
  *curr = v != 0;
  return 0;
}

MXTPU_API int MXAutogradComputeGradient(mx_uint num_output,
                                        NDArrayHandle *output_handles) {
  PREP;
  PyObject *outs = handle_list(output_handles, num_output);
  PyObject *a = Py_BuildValue("(NOi)", outs, Py_None, 0);
  PyObject *r = call("autograd_backward", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXAutogradBackwardEx(mx_uint num_output,
                                   NDArrayHandle *output_handles,
                                   NDArrayHandle *ograd_handles,
                                   mx_uint num_variables,
                                   NDArrayHandle *var_handles,
                                   int retain_graph, int create_graph,
                                   int is_train,
                                   NDArrayHandle **grad_handles,
                                   int **grad_stypes) {
  PREP;
  PyObject *outs = handle_list(output_handles, num_output);
  PyObject *ograds = ograd_handles && ograd_handles[0]
      ? handle_list(ograd_handles, num_output)
      : (Py_INCREF(Py_None), Py_None);
  PyObject *vars = num_variables
      ? handle_list(var_handles, num_variables)
      : (Py_INCREF(Py_None), Py_None);
  PyObject *a = Py_BuildValue("(NNNiii)", outs, ograds, vars, retain_graph,
                              create_graph, is_train);
  PyObject *r = call("autograd_backward_ex", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  if (r == Py_None || !num_variables) {
    Py_DECREF(r);
    if (grad_handles) *grad_handles = nullptr;
    if (grad_stypes) *grad_stypes = nullptr;
    return 0;
  }
  mx_uint n = 0;
  if (fill_handles(r, &n, grad_handles)) { Py_DECREF(r); return -1; }
  Py_DECREF(r);
  if (grad_stypes) {
    const int *st = nullptr;
    if (fill_stypes(*grad_handles, (int)n, &st)) return -1;
    *grad_stypes = const_cast<int *>(st);
  }
  return 0;
}

MXTPU_API int MXAutogradGetSymbol(NDArrayHandle handle, SymbolHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(handle));
  PyObject *r = call("autograd_get_symbol", a); Py_DECREF(a);
  return rh(r, out);
}


MXTPU_API int MXImperativeInvokeEx(const char *op_name, int num_inputs,
                                   NDArrayHandle *inputs, int *num_outputs,
                                   NDArrayHandle **outputs, int num_params,
                                   const char **param_keys,
                                   const char **param_vals,
                                   const int **out_stypes) {
  if (MXImperativeInvoke(op_name, num_inputs, inputs, num_outputs, outputs,
                         num_params, param_keys, param_vals))
    return -1;
  ScopedGIL gil;
  return fill_stypes(*outputs, *num_outputs, out_stypes);
}

// ---------------------------------------------------------------------------
// kvstore depth
// ---------------------------------------------------------------------------

namespace {
// int-keyed wrappers: stringify keys into TLS storage and reuse kv_op
int kv_int_op(const char *fn, KVStoreHandle kv, mx_uint num,
              const int *keys, NDArrayHandle *vals) {
  thread_local std::vector<std::string> key_strs;
  thread_local std::vector<const char *> key_ptrs;
  key_strs.clear();
  key_ptrs.clear();
  for (mx_uint i = 0; i < num; ++i)
    key_strs.push_back(std::to_string(keys[i]));
  for (auto &s : key_strs) key_ptrs.push_back(s.c_str());
  return kv_op(fn, kv, num, key_ptrs.data(), vals);
}
}  // namespace

MXTPU_API int MXKVStoreInit(KVStoreHandle kv, mx_uint num, const int *keys,
                            NDArrayHandle *vals) {
  return kv_int_op("kvstore_init", kv, num, keys, vals);
}

MXTPU_API int MXKVStorePush(KVStoreHandle kv, mx_uint num, const int *keys,
                            NDArrayHandle *vals, int priority) {
  (void)priority;
  return kv_int_op("kvstore_push", kv, num, keys, vals);
}

MXTPU_API int MXKVStorePull(KVStoreHandle kv, mx_uint num, const int *keys,
                            NDArrayHandle *outs, int priority) {
  (void)priority;
  return kv_int_op("kvstore_pull", kv, num, keys, outs);
}

static int kv_pull_sparse(KVStoreHandle kv, mx_uint num, PyObject *keys,
                          NDArrayHandle *vals, int ignore_sparse) {
  ScopedGIL gil;
  PyObject *v = handle_list(vals, num);
  PyObject *a = Py_BuildValue("(ONNi)", H(kv), keys, v, ignore_sparse);
  PyObject *r = call("kvstore_pull_with_sparse", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXKVStorePullWithSparse(KVStoreHandle kv, mx_uint num,
                                      const int *keys, NDArrayHandle *vals,
                                      int priority, bool ignore_sparse) {
  (void)priority;
  ScopedGIL gil;
  PyObject *k = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i)
    PyList_SetItem(k, i, PyUnicode_FromString(
        std::to_string(keys[i]).c_str()));
  return kv_pull_sparse(kv, num, k, vals, ignore_sparse ? 1 : 0);
}

MXTPU_API int MXKVStorePullWithSparseEx(KVStoreHandle kv, mx_uint num,
                                        const char **keys,
                                        NDArrayHandle *vals, int priority,
                                        bool ignore_sparse) {
  (void)priority;
  ScopedGIL gil;
  return kv_pull_sparse(kv, num, str_list(keys, num), vals,
                        ignore_sparse ? 1 : 0);
}

static int kv_pull_rsp(KVStoreHandle kv, mx_uint num, PyObject *keys,
                       NDArrayHandle *vals, const NDArrayHandle *row_ids) {
  ScopedGIL gil;
  PyObject *v = handle_list(vals, num);
  PyObject *r_ids = handle_list(const_cast<NDArrayHandle *>(row_ids), num);
  PyObject *a = Py_BuildValue("(ONNN)", H(kv), keys, v, r_ids);
  PyObject *r = call("kvstore_pull_row_sparse", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXKVStorePullRowSparse(KVStoreHandle kv, mx_uint num,
                                     const int *keys, NDArrayHandle *vals,
                                     const NDArrayHandle *row_ids,
                                     int priority) {
  (void)priority;
  ScopedGIL gil;
  PyObject *k = PyList_New(num);
  for (mx_uint i = 0; i < num; ++i)
    PyList_SetItem(k, i, PyUnicode_FromString(
        std::to_string(keys[i]).c_str()));
  return kv_pull_rsp(kv, num, k, vals, row_ids);
}

MXTPU_API int MXKVStorePullRowSparseEx(KVStoreHandle kv, mx_uint num,
                                       const char **keys,
                                       NDArrayHandle *vals,
                                       const NDArrayHandle *row_ids,
                                       int priority) {
  (void)priority;
  ScopedGIL gil;
  return kv_pull_rsp(kv, num, str_list(keys, num), vals, row_ids);
}

MXTPU_API int MXKVStoreSetUpdater(KVStoreHandle kv,
                                  void (*updater)(int, NDArrayHandle,
                                                  NDArrayHandle, void *),
                                  void *updater_handle) {
  PREP;
  PyObject *a = Py_BuildValue("(OKK)", H(kv),
                              (unsigned long long)(uintptr_t)updater,
                              (unsigned long long)(uintptr_t)updater_handle);
  PyObject *r = call("kvstore_set_updater", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXKVStoreSetUpdaterEx(KVStoreHandle kv,
                                    void (*updater)(int, NDArrayHandle,
                                                    NDArrayHandle, void *),
                                    void (*str_updater)(const char *,
                                                        NDArrayHandle,
                                                        NDArrayHandle,
                                                        void *),
                                    void *updater_handle) {
  (void)updater;
  PREP;
  PyObject *a = Py_BuildValue("(OKK)", H(kv),
                              (unsigned long long)(uintptr_t)str_updater,
                              (unsigned long long)(uintptr_t)updater_handle);
  PyObject *r = call("kvstore_set_updater_str", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXKVStoreBarrier(KVStoreHandle kv) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(kv));
  PyObject *r = call("kvstore_barrier", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXKVStoreGetType(KVStoreHandle kv, const char **type) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(kv));
  PyObject *r = call("kvstore_get_type", a); Py_DECREF(a);
  return rs(r, type);
}

static int kv_role(int which, int *ret) {
  PREP;
  PyObject *r = call("kvstore_role_flags", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  *ret = (int)PyLong_AsLong(PyTuple_GetItem(r, which));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStoreIsWorkerNode(int *ret) { return kv_role(0, ret); }
MXTPU_API int MXKVStoreIsServerNode(int *ret) { return kv_role(1, ret); }
MXTPU_API int MXKVStoreIsSchedulerNode(int *ret) { return kv_role(2, ret); }

MXTPU_API int MXKVStoreRunServer(KVStoreHandle kv,
                                 void (*controller)(int, const char *,
                                                    void *),
                                 void *controller_handle) {
  (void)controller; (void)controller_handle;
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(kv));
  PyObject *r = call("kvstore_run_server", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXKVStoreSendCommmandToServers(KVStoreHandle kv, int cmd_id,
                                             const char *cmd_body) {
  PREP;
  PyObject *a = Py_BuildValue("(Ois)", H(kv), cmd_id, cmd_body);
  PyObject *r = call("kvstore_send_command", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXKVStoreGetNumDeadNode(KVStoreHandle kv, const int node_id,
                                      int *number, const int timeout_sec) {
  (void)timeout_sec;
  PREP;
  PyObject *a = Py_BuildValue("(Oi)", H(kv), node_id);
  PyObject *r = call("kvstore_get_num_dead_node", a); Py_DECREF(a);
  return ri(r, number);
}

MXTPU_API int MXKVStoreSetBarrierBeforeExit(KVStoreHandle kv,
                                            const int barrier_before_exit) {
  PREP;
  PyObject *a = Py_BuildValue("(Oi)", H(kv), barrier_before_exit);
  PyObject *r = call("kvstore_set_barrier_before_exit", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXKVStoreSetGradientCompression(KVStoreHandle kv,
                                              mx_uint num_params,
                                              const char **keys,
                                              const char **vals) {
  PREP;
  PyObject *k = str_list(keys, num_params);
  PyObject *v = str_list(vals, num_params);
  PyObject *a = Py_BuildValue("(ONN)", H(kv), k, v);
  PyObject *r = call("kvstore_set_gradient_compression", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXInitPSEnv(mx_uint num_vars, const char **keys,
                          const char **vals) {
  PREP;
  PyObject *k = str_list(keys, num_vars);
  PyObject *v = str_list(vals, num_vars);
  PyObject *a = Py_BuildValue("(NN)", k, v);
  PyObject *r = call("init_ps_env", a); Py_DECREF(a);
  return rv(r);
}

// ---------------------------------------------------------------------------
// misc + legacy Function API + quantization + RTC
// ---------------------------------------------------------------------------

MXTPU_API int MXGetGPUCount(int *out) {
  PREP;
  PyObject *r = call("get_gpu_count", nullptr);
  return ri(r, out);
}

MXTPU_API int MXGetGPUMemoryInformation64(int dev, uint64_t *free_mem,
                                          uint64_t *total_mem) {
  PREP;
  PyObject *a = Py_BuildValue("(i)", dev);
  PyObject *r = call("get_gpu_memory_info", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  *free_mem = (uint64_t)PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 0));
  *total_mem = (uint64_t)PyLong_AsUnsignedLongLong(PyTuple_GetItem(r, 1));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXGetGPUMemoryInformation(int dev, int *free_mem,
                                        int *total_mem) {
  uint64_t f = 0, t = 0;
  if (MXGetGPUMemoryInformation64(dev, &f, &t)) return -1;
  *free_mem = (int)(f >> 20);   // MiB, like the reference's int variant
  *total_mem = (int)(t >> 20);
  return 0;
}

MXTPU_API int MXSetNumOMPThreads(int thread_num) {
  PREP;
  PyObject *a = Py_BuildValue("(i)", thread_num);
  PyObject *r = call("set_num_omp_threads", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXEngineSetBulkSize(int bulk_size, int *prev_bulk_size) {
  PREP;
  PyObject *a = Py_BuildValue("(i)", bulk_size);
  PyObject *r = call("engine_set_bulk_size", a); Py_DECREF(a);
  return ri(r, prev_bulk_size);
}

MXTPU_API int MXNotifyShutdown() {
  PREP;
  PyObject *r = call("notify_shutdown", nullptr);
  return rv(r);
}

struct LibFeature {
  const char *name;
  bool enabled;
};

MXTPU_API int MXLibInfoFeatures(const struct LibFeature **lib_features,
                                size_t *size) {
  PREP;
  PyObject *r = call("libinfo_features", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  thread_local std::vector<std::string> names;
  thread_local std::vector<LibFeature> feats;
  Py_ssize_t n = PyList_Size(r);
  names.clear();
  feats.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *t = PyList_GetItem(r, i);
    const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(t, 0));
    names.emplace_back(c ? c : "");
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *t = PyList_GetItem(r, i);
    feats.push_back({names[i].c_str(),
                     PyLong_AsLong(PyTuple_GetItem(t, 1)) != 0});
  }
  Py_DECREF(r);
  *lib_features = feats.data();
  *size = (size_t)n;
  return 0;
}

MXTPU_API int MXRandomSeedContext(int seed, int dev_type, int dev_id) {
  PREP;
  PyObject *a = Py_BuildValue("(iii)", seed, dev_type, dev_id);
  PyObject *r = call("random_seed_context", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXGenBackendSubgraph(SymbolHandle sym, const char *backend,
                                   SymbolHandle *out) {
  PREP;
  PyObject *a = Py_BuildValue("(Os)", H(sym), backend);
  PyObject *r = call("gen_backend_subgraph", a); Py_DECREF(a);
  return rh(r, out);
}

MXTPU_API int MXListFunctions(mx_uint *out_size, void ***out_array) {
  PREP;
  PyObject *r = call("list_functions", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  Py_ssize_t n = PyList_Size(r);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(r, i);
    Py_INCREF(o);
    g_handle_store.push_back(o);  // FunctionHandle = op-name string
  }
  Py_DECREF(r);
  *out_size = (mx_uint)n;
  *out_array = g_handle_store.data();
  return 0;
}

MXTPU_API int MXGetFunction(const char *name, void **out) {
  ScopedGIL gil;
  *out = PyUnicode_FromString(name);
  return 0;
}

MXTPU_API int MXFuncGetInfo(void *fun, const char **name,
                            const char **description, mx_uint *num_args,
                            const char ***arg_names,
                            const char ***arg_type_infos,
                            const char ***arg_descriptions,
                            const char ***return_type) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(fun));
  PyObject *r = call("func_get_info", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  thread_local std::string s_name, s_desc;
  const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(r, 0));
  s_name = c ? c : "";
  c = PyUnicode_AsUTF8(PyTuple_GetItem(r, 1));
  s_desc = c ? c : "";
  Py_DECREF(r);
  *name = s_name.c_str();
  *description = s_desc.c_str();
  *num_args = 0;
  *arg_names = nullptr;
  *arg_type_infos = nullptr;
  *arg_descriptions = nullptr;
  if (return_type) *return_type = nullptr;
  return 0;
}

MXTPU_API int MXFuncDescribe(void *fun, mx_uint *num_use_vars,
                             mx_uint *num_scalars, mx_uint *num_mutate_vars,
                             int *type_mask) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(fun));
  PyObject *r = call("func_describe", a); Py_DECREF(a);
  if (!r) { set_error(py_error()); return -1; }
  *num_use_vars = (mx_uint)PyLong_AsLong(PyTuple_GetItem(r, 0));
  *num_scalars = (mx_uint)PyLong_AsLong(PyTuple_GetItem(r, 1));
  *num_mutate_vars = (mx_uint)PyLong_AsLong(PyTuple_GetItem(r, 2));
  *type_mask = (int)PyLong_AsLong(PyTuple_GetItem(r, 3));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXFuncInvoke(void *fun, NDArrayHandle *use_vars,
                           mx_float *scalar_args,
                           NDArrayHandle *mutate_vars) {
  PREP;
  mx_uint n_use = 0, n_scalar = 0, n_mut = 0;
  int mask = 0;
  if (MXFuncDescribe(fun, &n_use, &n_scalar, &n_mut, &mask)) return -1;
  (void)scalar_args;
  PyObject *use = handle_list(use_vars, n_use);
  PyObject *scal = PyList_New(0);
  PyObject *mut = handle_list(mutate_vars, n_mut);
  PyObject *a = Py_BuildValue("(ONNN)", H(fun), use, scal, mut);
  PyObject *r = call("func_invoke", a); Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXFuncInvokeEx(void *fun, NDArrayHandle *use_vars,
                             mx_float *scalar_args,
                             NDArrayHandle *mutate_vars, int num_params,
                             char **param_keys, char **param_vals) {
  (void)num_params; (void)param_keys; (void)param_vals;
  return MXFuncInvoke(fun, use_vars, scalar_args, mutate_vars);
}

MXTPU_API int MXQuantizeSymbol(SymbolHandle sym, SymbolHandle *ret_sym,
                               const mx_uint num_excluded,
                               const char **excluded_op_names,
                               const mx_uint num_offline,
                               const char **offline_params,
                               const char *quantized_dtype,
                               const bool calib_quantize) {
  (void)calib_quantize;
  PREP;
  PyObject *ex = str_list(excluded_op_names, num_excluded);
  PyObject *off = str_list(offline_params, num_offline);
  PyObject *a = Py_BuildValue("(ONNs)", H(sym), ex, off, quantized_dtype);
  PyObject *r = call("quantize_symbol", a); Py_DECREF(a);
  return rh(r, ret_sym);
}

MXTPU_API int MXSetCalibTableToQuantizedSymbol(
    SymbolHandle qsym, const mx_uint num_layers, const char **layer_names,
    const float *low_quantiles, const float *high_quantiles,
    SymbolHandle *ret_sym) {
  PREP;
  PyObject *names = str_list(layer_names, num_layers);
  PyObject *lows = PyList_New(num_layers);
  PyObject *highs = PyList_New(num_layers);
  for (mx_uint i = 0; i < num_layers; ++i) {
    PyList_SetItem(lows, i, PyFloat_FromDouble(low_quantiles[i]));
    PyList_SetItem(highs, i, PyFloat_FromDouble(high_quantiles[i]));
  }
  PyObject *a = Py_BuildValue("(ONNN)", H(qsym), names, lows, highs);
  PyObject *r = call("set_calib_table", a); Py_DECREF(a);
  return rh(r, ret_sym);
}

// RTC: CUDA-source runtime compilation has no TPU backend; these report
// the same build-feature error a non-CUDA reference build raises, and
// MXRtcCudaModuleCreate routes to mx.rtc (PallasModule is the supported
// runtime-compile path).

static int rtc_unsupported() {
  PREP;
  PyObject *r = call("rtc_legacy", PyTuple_New(0));
  return rv(r);  // always raises with the guidance message
}

MXTPU_API int MXRtcCreate(char *name, mx_uint num_input, mx_uint num_output,
                          char **input_names, char **output_names,
                          NDArrayHandle *inputs, NDArrayHandle *outputs,
                          char *kernel, void **out) {
  (void)name; (void)num_input; (void)num_output; (void)input_names;
  (void)output_names; (void)inputs; (void)outputs; (void)kernel; (void)out;
  return rtc_unsupported();
}

MXTPU_API int MXRtcPush(void *handle, mx_uint num_input, mx_uint num_output,
                        NDArrayHandle *inputs, NDArrayHandle *outputs,
                        mx_uint gridDimX, mx_uint gridDimY, mx_uint gridDimZ,
                        mx_uint blockDimX, mx_uint blockDimY,
                        mx_uint blockDimZ) {
  (void)handle; (void)num_input; (void)num_output; (void)inputs;
  (void)outputs; (void)gridDimX; (void)gridDimY; (void)gridDimZ;
  (void)blockDimX; (void)blockDimY; (void)blockDimZ;
  return rtc_unsupported();
}

MXTPU_API int MXRtcFree(void *handle) {
  if (handle) { ScopedGIL gil; Py_DECREF(H(handle)); }
  return 0;
}

MXTPU_API int MXRtcCudaModuleCreate(const char *source, int num_options,
                                    const char **options, int num_exports,
                                    const char **exports, void **out) {
  PREP;
  PyObject *opt = str_list(options, num_options);
  PyObject *exp = str_list(exports, num_exports);
  PyObject *a = Py_BuildValue("(sNN)", source, opt, exp);
  PyObject *r = call("rtc_cuda_module_create", a); Py_DECREF(a);
  return rh(r, out);  // raises: CUDA RTC unavailable, use PallasModule
}

MXTPU_API int MXRtcCudaModuleFree(void *handle) {
  if (handle) { ScopedGIL gil; Py_DECREF(H(handle)); }
  return 0;
}

MXTPU_API int MXRtcCudaKernelCreate(void *handle, const char *name,
                                    int num_args, int *is_ndarray,
                                    int *is_const, int *arg_types,
                                    void **out) {
  (void)handle; (void)name; (void)num_args; (void)is_ndarray;
  (void)is_const; (void)arg_types; (void)out;
  return rtc_unsupported();
}

MXTPU_API int MXRtcCudaKernelFree(void *handle) {
  if (handle) { ScopedGIL gil; Py_DECREF(H(handle)); }
  return 0;
}

MXTPU_API int MXRtcCudaKernelCall(void *handle, int dev_id, void **args,
                                  mx_uint grid_dim_x, mx_uint grid_dim_y,
                                  mx_uint grid_dim_z, mx_uint block_dim_x,
                                  mx_uint block_dim_y, mx_uint block_dim_z,
                                  mx_uint shared_mem) {
  (void)handle; (void)dev_id; (void)args; (void)grid_dim_x;
  (void)grid_dim_y; (void)grid_dim_z; (void)block_dim_x;
  (void)block_dim_y; (void)block_dim_z; (void)shared_mem;
  return rtc_unsupported();
}

MXTPU_API int MXSymbolGetInputSymbols(SymbolHandle sym,
                                      SymbolHandle **input_symbols,
                                      int *input_size) {
  PREP;
  PyObject *a = Py_BuildValue("(O)", H(sym));
  PyObject *r = call("symbol_get_input_symbols", a); Py_DECREF(a);
  mx_uint n = 0;
  if (rhl(r, &n, reinterpret_cast<NDArrayHandle **>(input_symbols)))
    return -1;
  *input_size = (int)n;
  return 0;
}

// ---------------------------------------------------------------------------
// C-callback custom operators + autograd functions
// (ref: include/mxnet/c_api.h:2459 MXCustomOpRegister / :2468
//  MXCustomFunctionRecord; src/operator/custom/custom.cc tag protocol,
//  src/c_api/c_api_function.cc). These are THE two functions a non-Python
//  language binding needs to define ops: the frontend supplies C function
//  pointers (prop creator -> prop callbacks -> operator callbacks), the
//  runtime calls them with NDArray handles. Here the callbacks plug into
//  the Python Custom-op host (mxnet_tpu/operator.py) through a tiny
//  embedded extension module `_mxtpu_chost` the bridge adapter consumes —
//  the callbacks themselves drive the SAME flat C API to do their math.
// ---------------------------------------------------------------------------

extern "C" {
struct MXCallbackList {
  int num_callbacks;
  int (**callbacks)(void);
  void **contexts;
};
}

typedef int (*CustomOpPropCreator)(const char *, const int, const char **,
                                   const char **, MXCallbackList *);
typedef int (*CustomOpFBFunc)(int, void **, int *, const int *, const int,
                              void *);
typedef int (*CustomOpDelFunc)(void *);
typedef int (*CustomOpListFunc)(char ***, void *);
typedef int (*CustomOpInferShapeFunc)(int, int *, unsigned **, void *);
typedef int (*CustomOpInferTypeFunc)(int, int *, void *);
typedef int (*CustomOpCreateFunc)(const char *, int, unsigned **,
                                  const int *, const int *,
                                  MXCallbackList *, void *);
typedef int (*CustomFunctionBwdFunc)(int, int, void **, const int *,
                                     const int, void *);

namespace {

// enum values mirror include/mxnet/c_api.h
enum { kCustomOpDelete = 0, kCustomOpForward = 1, kCustomOpBackward = 2 };
enum {
  kCustomOpPropDelete = 0,
  kCustomOpPropListArguments = 1,
  kCustomOpPropListOutputs = 2,
  kCustomOpPropListAuxiliaryStates = 3,
  kCustomOpPropInferShape = 4,
  kCustomOpPropDeclareBackwardDependency = 5,
  kCustomOpPropCreateOperator = 6,
  kCustomOpPropInferType = 7
};
enum { kCustomFunctionBackward = 0, kCustomFunctionDelete = 1 };

std::mutex g_cop_mu;
std::map<std::string, CustomOpPropCreator> g_cop_creators;
std::map<long, MXCallbackList> g_cop_lists;  // props, operators, functions
long g_cop_next = 1;

long stash_cblist(const MXCallbackList &cb) {
  std::lock_guard<std::mutex> lk(g_cop_mu);
  long id = g_cop_next++;
  g_cop_lists[id] = cb;  // struct copy; the frontend owns the arrays and
  return id;             // keeps them alive while the op exists (same
}                        // contract as the reference runtime)

MXCallbackList *get_cblist(long id) {
  std::lock_guard<std::mutex> lk(g_cop_mu);
  auto it = g_cop_lists.find(id);
  return it == g_cop_lists.end() ? nullptr : &it->second;
}

bool has_cb(const MXCallbackList *l, int i) {
  return l != nullptr && i < l->num_callbacks && l->callbacks[i] != nullptr;
}

#define CHOST_GET(idvar)                                                   \
  MXCallbackList *cb = get_cblist(idvar);                                  \
  if (cb == nullptr) {                                                     \
    PyErr_SetString(PyExc_KeyError, "unknown custom-op callback handle");  \
    return nullptr;                                                        \
  }

PyObject *chost_has_creator(PyObject *, PyObject *args) {
  const char *op_type;
  if (!PyArg_ParseTuple(args, "s", &op_type)) return nullptr;
  std::lock_guard<std::mutex> lk(g_cop_mu);
  return PyBool_FromLong(g_cop_creators.count(op_type) ? 1 : 0);
}

PyObject *chost_create_prop(PyObject *, PyObject *args) {
  const char *op_type;
  PyObject *keys, *vals;
  if (!PyArg_ParseTuple(args, "sOO", &op_type, &keys, &vals)) return nullptr;
  CustomOpPropCreator creator = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_cop_mu);
    auto it = g_cop_creators.find(op_type);
    if (it != g_cop_creators.end()) creator = it->second;
  }
  if (creator == nullptr) {
    PyErr_Format(PyExc_KeyError, "no C creator registered for %s", op_type);
    return nullptr;
  }
  Py_ssize_t n = PyList_Size(keys);
  std::vector<std::string> ks, vs;
  std::vector<const char *> kp, vp;
  for (Py_ssize_t i = 0; i < n; ++i) {
    ks.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(keys, i)));
    vs.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(vals, i)));
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    kp.push_back(ks[i].c_str());
    vp.push_back(vs[i].c_str());
  }
  MXCallbackList cb{0, nullptr, nullptr};
  if (!creator(op_type, (int)n, kp.data(), vp.data(), &cb)) {
    PyErr_Format(PyExc_RuntimeError, "C prop creator for %s failed",
                 op_type);
    return nullptr;
  }
  return PyLong_FromLong(stash_cblist(cb));
}

PyObject *chost_prop_list(PyObject *, PyObject *args) {
  long id;
  int which;
  if (!PyArg_ParseTuple(args, "li", &id, &which)) return nullptr;
  CHOST_GET(id);
  char **names = nullptr;
  if (!has_cb(cb, which)) return PyList_New(0);
  if (!((CustomOpListFunc)cb->callbacks[which])(&names,
                                                cb->contexts[which])) {
    PyErr_SetString(PyExc_RuntimeError, "custom-op list callback failed");
    return nullptr;
  }
  PyObject *out = PyList_New(0);
  for (char **p = names; p != nullptr && *p != nullptr; ++p) {
    PyObject *s = PyUnicode_FromString(*p);
    PyList_Append(out, s);
    Py_DECREF(s);
  }
  return out;
}

PyObject *chost_prop_infer_shape(PyObject *, PyObject *args) {
  long id;
  int n_out, n_aux;
  PyObject *in_shapes;
  if (!PyArg_ParseTuple(args, "lOii", &id, &in_shapes, &n_out, &n_aux))
    return nullptr;
  CHOST_GET(id);
  if (!has_cb(cb, kCustomOpPropInferShape)) Py_RETURN_NONE;
  int n_in = (int)PyList_Size(in_shapes);
  int total = n_in + n_out + n_aux;
  std::vector<int> ndims(total, 0);
  std::vector<std::vector<unsigned>> store(total);
  std::vector<unsigned *> ptrs(total, nullptr);
  for (int i = 0; i < n_in; ++i) {
    PyObject *s = PyList_GetItem(in_shapes, i);
    Py_ssize_t d = PyList_Size(s);
    ndims[i] = (int)d;
    store[i].resize(d);
    for (Py_ssize_t j = 0; j < d; ++j)
      store[i][j] = (unsigned)PyLong_AsUnsignedLong(PyList_GetItem(s, j));
    ptrs[i] = store[i].data();
  }
  if (!((CustomOpInferShapeFunc)cb->callbacks[kCustomOpPropInferShape])(
          total, ndims.data(), ptrs.data(),
          cb->contexts[kCustomOpPropInferShape])) {
    PyErr_SetString(PyExc_RuntimeError, "custom-op infer_shape failed");
    return nullptr;
  }
  PyObject *out = PyList_New(total);  // copy out IMMEDIATELY: the pointers
  for (int i = 0; i < total; ++i) {   // target callee-owned storage
    PyObject *s = PyList_New(ndims[i]);
    for (int j = 0; j < ndims[i]; ++j)
      PyList_SetItem(s, j, PyLong_FromUnsignedLong(ptrs[i][j]));
    PyList_SetItem(out, i, s);
  }
  return out;
}

PyObject *chost_prop_infer_type(PyObject *, PyObject *args) {
  long id;
  int n_out, n_aux;
  PyObject *in_types;
  if (!PyArg_ParseTuple(args, "lOii", &id, &in_types, &n_out, &n_aux))
    return nullptr;
  CHOST_GET(id);
  if (!has_cb(cb, kCustomOpPropInferType)) Py_RETURN_NONE;
  int n_in = (int)PyList_Size(in_types);
  int total = n_in + n_out + n_aux;
  std::vector<int> types(total, -1);
  for (int i = 0; i < n_in; ++i)
    types[i] = (int)PyLong_AsLong(PyList_GetItem(in_types, i));
  if (!((CustomOpInferTypeFunc)cb->callbacks[kCustomOpPropInferType])(
          total, types.data(), cb->contexts[kCustomOpPropInferType])) {
    PyErr_SetString(PyExc_RuntimeError, "custom-op infer_type failed");
    return nullptr;
  }
  PyObject *out = PyList_New(total);
  for (int i = 0; i < total; ++i)
    PyList_SetItem(out, i, PyLong_FromLong(types[i]));
  return out;
}

PyObject *chost_prop_create_operator(PyObject *, PyObject *args) {
  long id;
  const char *ctx;
  PyObject *shapes, *dtypes;
  if (!PyArg_ParseTuple(args, "lsOO", &id, &ctx, &shapes, &dtypes))
    return nullptr;
  CHOST_GET(id);
  if (!has_cb(cb, kCustomOpPropCreateOperator)) {
    PyErr_SetString(PyExc_RuntimeError,
                    "custom-op prop has no create_operator callback");
    return nullptr;
  }
  int n = (int)PyList_Size(shapes);
  std::vector<int> ndims(n), dts(n);
  std::vector<std::vector<unsigned>> store(n);
  std::vector<unsigned *> ptrs(n);
  for (int i = 0; i < n; ++i) {
    PyObject *s = PyList_GetItem(shapes, i);
    Py_ssize_t d = PyList_Size(s);
    ndims[i] = (int)d;
    store[i].resize(d);
    for (Py_ssize_t j = 0; j < d; ++j)
      store[i][j] = (unsigned)PyLong_AsUnsignedLong(PyList_GetItem(s, j));
    ptrs[i] = store[i].data();
    dts[i] = (int)PyLong_AsLong(PyList_GetItem(dtypes, i));
  }
  MXCallbackList op{0, nullptr, nullptr};
  if (!((CustomOpCreateFunc)cb->callbacks[kCustomOpPropCreateOperator])(
          ctx, n, ptrs.data(), ndims.data(), dts.data(), &op,
          cb->contexts[kCustomOpPropCreateOperator])) {
    PyErr_SetString(PyExc_RuntimeError, "custom-op create_operator failed");
    return nullptr;
  }
  return PyLong_FromLong(stash_cblist(op));
}

PyObject *chost_op_call(PyObject *, PyObject *args) {
  long id;
  int which, is_train;
  PyObject *handles, *tags, *reqs;
  if (!PyArg_ParseTuple(args, "liOOOi", &id, &which, &handles, &tags, &reqs,
                        &is_train))
    return nullptr;
  CHOST_GET(id);
  if (!has_cb(cb, which)) {
    PyErr_SetString(PyExc_RuntimeError, "custom op callback missing");
    return nullptr;
  }
  int n = (int)PyList_Size(handles);
  std::vector<void *> ptrs(n);
  std::vector<int> tg(n);
  for (int i = 0; i < n; ++i) {
    ptrs[i] = PyList_GetItem(handles, i);  // NDArrayHandle == PyObject*
    tg[i] = (int)PyLong_AsLong(PyList_GetItem(tags, i));
  }
  int m = (int)PyList_Size(reqs);
  std::vector<int> rq(m);
  for (int i = 0; i < m; ++i)
    rq[i] = (int)PyLong_AsLong(PyList_GetItem(reqs, i));
  if (!((CustomOpFBFunc)cb->callbacks[which])(n, ptrs.data(), tg.data(),
                                              rq.data(), is_train,
                                              cb->contexts[which])) {
    PyErr_SetString(PyExc_RuntimeError, "custom op callback failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject *chost_func_backward(PyObject *, PyObject *args) {
  long id;
  int n_ograds, n_igrads, is_train;
  PyObject *handles, *reqs;
  if (!PyArg_ParseTuple(args, "liiOOi", &id, &n_ograds, &n_igrads, &handles,
                        &reqs, &is_train))
    return nullptr;
  CHOST_GET(id);
  if (!has_cb(cb, kCustomFunctionBackward)) {
    PyErr_SetString(PyExc_RuntimeError, "custom function has no backward");
    return nullptr;
  }
  int n = (int)PyList_Size(handles);
  std::vector<void *> ptrs(n);
  for (int i = 0; i < n; ++i) ptrs[i] = PyList_GetItem(handles, i);
  int m = (int)PyList_Size(reqs);
  std::vector<int> rq(m);
  for (int i = 0; i < m; ++i)
    rq[i] = (int)PyLong_AsLong(PyList_GetItem(reqs, i));
  if (!((CustomFunctionBwdFunc)cb->callbacks[kCustomFunctionBackward])(
          n_ograds, n_igrads, ptrs.data(), rq.data(), is_train,
          cb->contexts[kCustomFunctionBackward])) {
    PyErr_SetString(PyExc_RuntimeError, "custom function backward failed");
    return nullptr;
  }
  Py_RETURN_NONE;
}

PyObject *chost_release(PyObject *, PyObject *args) {
  long id;
  int del_index;
  if (!PyArg_ParseTuple(args, "li", &id, &del_index)) return nullptr;
  MXCallbackList cb{0, nullptr, nullptr};
  {
    std::lock_guard<std::mutex> lk(g_cop_mu);
    auto it = g_cop_lists.find(id);
    if (it == g_cop_lists.end()) Py_RETURN_NONE;
    cb = it->second;
    g_cop_lists.erase(it);
  }
  if (del_index >= 0 && has_cb(&cb, del_index))
    ((CustomOpDelFunc)cb.callbacks[del_index])(cb.contexts[del_index]);
  Py_RETURN_NONE;
}

PyMethodDef g_chost_methods[] = {
    {"has_creator", chost_has_creator, METH_VARARGS, nullptr},
    {"create_prop", chost_create_prop, METH_VARARGS, nullptr},
    {"prop_list", chost_prop_list, METH_VARARGS, nullptr},
    {"prop_infer_shape", chost_prop_infer_shape, METH_VARARGS, nullptr},
    {"prop_infer_type", chost_prop_infer_type, METH_VARARGS, nullptr},
    {"prop_create_operator", chost_prop_create_operator, METH_VARARGS,
     nullptr},
    {"op_call", chost_op_call, METH_VARARGS, nullptr},
    {"func_backward", chost_func_backward, METH_VARARGS, nullptr},
    {"release", chost_release, METH_VARARGS, nullptr},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef g_chost_module = {PyModuleDef_HEAD_INIT, "_mxtpu_chost",
                              "C custom-op callback host", -1,
                              g_chost_methods,
                              nullptr, nullptr, nullptr, nullptr};

// the interpreter may predate this library (ctypes-loaded into a live
// python process) so AppendInittab is not an option: create the module
// lazily and plant it in sys.modules for the bridge adapter to import
void ensure_chost() {
  PyObject *mods = PyImport_GetModuleDict();
  if (PyDict_GetItemString(mods, "_mxtpu_chost") != nullptr) return;
  PyObject *m = PyModule_Create(&g_chost_module);
  if (m != nullptr) {
    PyDict_SetItemString(mods, "_mxtpu_chost", m);
    Py_DECREF(m);
  }
}

}  // namespace

MXTPU_API int MXCustomOpRegister(const char *op_type,
                                 CustomOpPropCreator creator) {
  PREP;
  {
    std::lock_guard<std::mutex> lk(g_cop_mu);
    g_cop_creators[op_type] = creator;
  }
  ensure_chost();
  PyObject *a = Py_BuildValue("(s)", op_type);
  PyObject *r = call("custom_c_op_register", a);
  Py_DECREF(a);
  return rv(r);
}

MXTPU_API int MXCustomFunctionRecord(int num_inputs, NDArrayHandle *inputs,
                                     int num_outputs,
                                     NDArrayHandle *outputs,
                                     MXCallbackList *callbacks) {
  PREP;
  ensure_chost();
  long id = stash_cblist(*callbacks);
  PyObject *a = Py_BuildValue(
      "(NNl)", handle_list(inputs, (mx_uint)num_inputs),
      handle_list(outputs, (mx_uint)num_outputs), id);
  PyObject *r = call("custom_function_record", a);
  Py_DECREF(a);
  if (r == nullptr) {
    // failed record (e.g. not recording): drop the stashed entry — the
    // frontend retains ownership of its callbacks, so no delete fires
    std::lock_guard<std::mutex> lk(g_cop_mu);
    g_cop_lists.erase(id);
  }
  return rv(r);
}
