// General C API: NDArray lifecycle, operator invocation, symbol
// composition, executor, autograd, kvstore.
//
// Reference: include/mxnet/c_api.h (198 functions) + src/c_api/*.cc.
// TPU-native design: like c_predict_api.cc, the runtime IS the
// Python/JAX stack, so this library embeds CPython and drives
// mxnet_tpu.c_api_bridge. Handles crossing the boundary are PyObject*
// (ref-counted via MXNDArrayFree etc.); signatures, shape encodings,
// last-error contract and return-code conventions match the reference so
// existing c_api consumers (and future language bindings) port by
// relinking.
//
// Build: make -C src  (libmxtpu_capi.so, links libpython3.12)

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <mutex>
#include <string>
#include <vector>

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *NDArrayHandle;
typedef void *SymbolHandle;
typedef void *ExecutorHandle;
typedef void *KVStoreHandle;

#define MXTPU_API extern "C" __attribute__((visibility("default")))

namespace {

thread_local std::string g_last_error;
void set_error(const std::string &msg) { g_last_error = msg; }

std::once_flag g_init_flag;

void ensure_interpreter() {
  std::call_once(g_init_flag, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      PyEval_SaveThread();
    }
  });
}

class ScopedGIL {
 public:
  ScopedGIL() : state_(PyGILState_Ensure()) {}
  ~ScopedGIL() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

std::string py_error() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  std::string msg = "python error";
  if (value) {
    PyObject *s = PyObject_Str(value);
    if (s) {
      const char *c = PyUnicode_AsUTF8(s);
      if (c) msg = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
  return msg;
}

PyObject *bridge() {
  const char *home = getenv("MXTPU_HOME");
  if (home != nullptr) {
    PyObject *sys_path = PySys_GetObject("path");
    if (sys_path != nullptr) {
      PyObject *p = PyUnicode_FromString(home);
      bool found = false;
      for (Py_ssize_t i = 0; i < PyList_Size(sys_path); ++i) {
        PyObject *item = PyList_GetItem(sys_path, i);
        if (item && PyUnicode_Compare(item, p) == 0) { found = true; break; }
      }
      if (!found) PyList_Insert(sys_path, 0, p);
      Py_DECREF(p);
    }
  }
  return PyImport_ImportModule("mxnet_tpu.c_api_bridge");
}

// call bridge.<name>(*args); steals nothing, returns new ref or nullptr
PyObject *call(const char *name, PyObject *args) {
  PyObject *mod = bridge();
  if (!mod) return nullptr;
  PyObject *fn = PyObject_GetAttrString(mod, name);
  Py_DECREF(mod);
  if (!fn) return nullptr;
  PyObject *out = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  return out;
}

PyObject *uint_list(const mx_uint *data, mx_uint n) {
  PyObject *lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyLong_FromUnsignedLong(data[i]));
  return lst;
}

PyObject *str_list(const char **data, mx_uint n) {
  PyObject *lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyUnicode_FromString(data[i]));
  return lst;
}

PyObject *handle_list(void *const *handles, mx_uint n) {
  PyObject *lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i) {
    PyObject *o = static_cast<PyObject *>(handles[i]);
    Py_INCREF(o);
    PyList_SetItem(lst, i, o);
  }
  return lst;
}

// per-thread string/shape storage for pointer-returning getters (the
// reference stores these in thread-local Ret entries likewise)
thread_local std::vector<std::string> g_str_store;
thread_local std::vector<const char *> g_cstr_store;
thread_local std::vector<mx_uint> g_shape_store;
thread_local std::vector<void *> g_handle_store;

int fill_strs(PyObject *lst, mx_uint *out_n, const char ***out) {
  Py_ssize_t n = PyList_Size(lst);
  g_str_store.clear();
  g_cstr_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char *c = PyUnicode_AsUTF8(PyList_GetItem(lst, i));
    g_str_store.emplace_back(c ? c : "");
  }
  for (auto &s : g_str_store) g_cstr_store.push_back(s.c_str());
  *out_n = static_cast<mx_uint>(n);
  *out = g_cstr_store.data();
  return 0;
}

int fill_handles(PyObject *lst, mx_uint *out_n, NDArrayHandle **out) {
  Py_ssize_t n = PyList_Size(lst);
  g_handle_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *o = PyList_GetItem(lst, i);
    Py_INCREF(o);  // caller owns via MXNDArrayFree
    g_handle_store.push_back(o);
  }
  *out_n = static_cast<mx_uint>(n);
  *out = g_handle_store.data();
  return 0;
}

}  // namespace

// ---------------------------------------------------------------------------
// misc
// ---------------------------------------------------------------------------

MXTPU_API const char *MXGetLastError() { return g_last_error.c_str(); }

MXTPU_API int MXGetVersion(int *out) {
  *out = 10500;
  return 0;
}

MXTPU_API int MXRandomSeed(int seed) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(i)", seed);
  PyObject *r = call("random_seed", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayWaitAll() {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *r = call("ndarray_wait_all", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

// ---------------------------------------------------------------------------
// NDArray
// ---------------------------------------------------------------------------

MXTPU_API int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim,
                                int dev_type, int dev_id,
                                int delay_alloc, int dtype,
                                NDArrayHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *shp = uint_list(shape, ndim);
  PyObject *args = Py_BuildValue("(Oiii)", shp, dtype, dev_type, dev_id);
  Py_DECREF(shp);
  PyObject *r = call("ndarray_create", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXNDArrayCreate(const mx_uint *shape, mx_uint ndim,
                              int dev_type, int dev_id, int delay_alloc,
                              NDArrayHandle *out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc, 0,
                           out);
}

MXTPU_API int MXNDArrayCreateNone(NDArrayHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *r = call("ndarray_create_none", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXNDArrayFree(NDArrayHandle handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

MXTPU_API int MXNDArraySyncCopyFromCPU(NDArrayHandle handle,
                                       const void *data, size_t size) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(OKK)", static_cast<PyObject *>(handle),
                                 (unsigned long long)(uintptr_t)data,
                                 (unsigned long long)size);
  PyObject *r = call("ndarray_sync_copy_from_cpu", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void *data,
                                     size_t size) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(OKK)", static_cast<PyObject *>(handle),
                                 (unsigned long long)(uintptr_t)data,
                                 (unsigned long long)size);
  PyObject *r = call("ndarray_sync_copy_to_cpu", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetShape(NDArrayHandle handle, mx_uint *out_dim,
                                const mx_uint **out_pdata) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(handle));
  PyObject *r = call("ndarray_shape", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_ssize_t n = PyList_Size(r);
  g_shape_store.clear();
  for (Py_ssize_t i = 0; i < n; ++i)
    g_shape_store.push_back(
        (mx_uint)PyLong_AsUnsignedLong(PyList_GetItem(r, i)));
  Py_DECREF(r);
  *out_dim = static_cast<mx_uint>(n);
  *out_pdata = g_shape_store.data();
  return 0;
}

MXTPU_API int MXNDArrayGetDType(NDArrayHandle handle, int *out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(handle));
  PyObject *r = call("ndarray_dtype", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArraySlice(NDArrayHandle handle, mx_uint begin,
                             mx_uint end, NDArrayHandle *out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(OII)", static_cast<PyObject *>(handle),
                                 begin, end);
  PyObject *r = call("ndarray_slice", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXNDArrayAt(NDArrayHandle handle, mx_uint idx,
                          NDArrayHandle *out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(OI)", static_cast<PyObject *>(handle),
                                 idx);
  PyObject *r = call("ndarray_at", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXNDArrayReshape(NDArrayHandle handle, int ndim,
                               const int *dims, NDArrayHandle *out) {
  ScopedGIL gil;
  PyObject *shp = PyList_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyList_SetItem(shp, i, PyLong_FromLong(dims[i]));
  PyObject *args = Py_BuildValue("(ON)", static_cast<PyObject *>(handle),
                                 shp);
  PyObject *r = call("ndarray_reshape", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXNDArraySave(const char *fname, mx_uint num_args,
                            NDArrayHandle *args_h, const char **keys) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *arrays = handle_list(args_h, num_args);
  PyObject *names = keys ? str_list(keys, num_args) : PyList_New(0);
  PyObject *args = Py_BuildValue("(sNN)", fname, arrays, names);
  PyObject *r = call("ndarray_save", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayLoad(const char *fname, mx_uint *out_size,
                            NDArrayHandle **out_arr,
                            mx_uint *out_name_size,
                            const char ***out_names) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(s)", fname);
  PyObject *r = call("ndarray_load", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  PyObject *names = PyTuple_GetItem(r, 0);
  PyObject *arrays = PyTuple_GetItem(r, 1);
  fill_strs(names, out_name_size, out_names);
  fill_handles(arrays, out_size, out_arr);
  Py_DECREF(r);
  return 0;
}

// ---------------------------------------------------------------------------
// operators
// ---------------------------------------------------------------------------

MXTPU_API int MXListAllOpNames(mx_uint *out_size, const char ***out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *r = call("list_all_op_names", nullptr);
  if (!r) { set_error(py_error()); return -1; }
  fill_strs(r, out_size, out);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXImperativeInvoke(const char *op_name, int num_inputs,
                                 NDArrayHandle *inputs, int *num_outputs,
                                 NDArrayHandle **outputs, int num_params,
                                 const char **param_keys,
                                 const char **param_vals) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *ins = handle_list(inputs, num_inputs);
  PyObject *keys = str_list(param_keys, num_params);
  PyObject *vals = str_list(param_vals, num_params);
  // reference contract: *num_outputs > 0 means the caller preallocated
  // output arrays — results are written into them in place
  bool prealloc = *num_outputs > 0 && *outputs != nullptr;
  PyObject *outs = prealloc ? handle_list(*outputs, *num_outputs)
                            : (Py_INCREF(Py_None), Py_None);
  PyObject *args = Py_BuildValue("(sNNNN)", op_name, ins, keys, vals,
                                 outs);
  PyObject *r = call("imperative_invoke", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  if (!prealloc) {
    mx_uint n = 0;
    fill_handles(r, &n, outputs);
    *num_outputs = static_cast<int>(n);
  }
  Py_DECREF(r);
  return 0;
}

// ---------------------------------------------------------------------------
// symbol
// ---------------------------------------------------------------------------

MXTPU_API int MXSymbolCreateVariable(const char *name, SymbolHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(s)", name);
  PyObject *r = call("symbol_create_variable", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolFree(SymbolHandle handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

MXTPU_API int MXSymbolCreateAtomicSymbol(const char *op_name,
                                         mx_uint num_param,
                                         const char **keys,
                                         const char **vals,
                                         SymbolHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *k = str_list(keys, num_param);
  PyObject *v = str_list(vals, num_param);
  PyObject *empty1 = PyList_New(0);
  PyObject *empty2 = PyList_New(0);
  PyObject *args = Py_BuildValue("(sNNNNs)", op_name, k, v, empty1,
                                 empty2, "");
  PyObject *r = call("symbol_create_atomic", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

// compose an atomic symbol with inputs: the CreateAtomicSymbol+Compose
// two-step every reference language binding uses. Positional args only —
// keyword composition (keys != NULL) is rejected loudly rather than
// silently wiring inputs into the wrong slots.
MXTPU_API int MXSymbolCompose(SymbolHandle sym, const char *name,
                              mx_uint num_args, const char **keys,
                              SymbolHandle *args_h) {
  ensure_interpreter();
  ScopedGIL gil;
  if (keys != nullptr) {
    // silent positional wiring under keyword intent would transpose
    // input roles — refuse loudly instead
    set_error("MXSymbolCompose: keyword composition is not supported; "
              "pass inputs positionally (keys must be NULL)");
    return -1;
  }
  PyObject *ins = handle_list(args_h, num_args);
  PyObject *args = Py_BuildValue("(OsN)", static_cast<PyObject *>(sym),
                                 name ? name : "", ins);
  PyObject *r = call("symbol_compose", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXSymbolCreateAtomicSymbolEx(const char *op_name,
                                           mx_uint num_param,
                                           const char **keys,
                                           const char **vals,
                                           mx_uint num_inputs,
                                           SymbolHandle *inputs,
                                           const char *name,
                                           SymbolHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *k = str_list(keys, num_param);
  PyObject *v = str_list(vals, num_param);
  PyObject *ins = handle_list(inputs, num_inputs);
  PyObject *in_names = PyList_New(0);
  PyObject *args = Py_BuildValue("(sNNNNs)", op_name, k, v, ins, in_names,
                                 name ? name : "");
  PyObject *r = call("symbol_create_atomic", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolCreateFromJSON(const char *json, SymbolHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(s)", json);
  PyObject *r = call("symbol_from_json", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXSymbolSaveToJSON(SymbolHandle sym, const char **out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(sym));
  PyObject *r = call("symbol_to_json", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  g_str_store.clear();
  const char *c = PyUnicode_AsUTF8(r);
  g_str_store.emplace_back(c ? c : "");
  Py_DECREF(r);
  *out = g_str_store.back().c_str();
  return 0;
}

static int list_via(const char *fn, SymbolHandle sym, mx_uint *out_size,
                    const char ***out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(sym));
  PyObject *r = call(fn, args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  fill_strs(r, out_size, out);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXSymbolListArguments(SymbolHandle sym, mx_uint *out_size,
                                    const char ***out) {
  return list_via("symbol_list_arguments", sym, out_size, out);
}

MXTPU_API int MXSymbolListOutputs(SymbolHandle sym, mx_uint *out_size,
                                  const char ***out) {
  return list_via("symbol_list_outputs", sym, out_size, out);
}

MXTPU_API int MXSymbolListAuxiliaryStates(SymbolHandle sym,
                                          mx_uint *out_size,
                                          const char ***out) {
  return list_via("symbol_list_aux", sym, out_size, out);
}

namespace {
// thread-local CSR-style shape storage for MXSymbolInferShape (the
// reference's per-thread MXAPIThreadLocalEntry layout)
struct ShapeSet {
  std::vector<mx_uint> ndim;
  std::vector<std::vector<mx_uint>> shapes;
  std::vector<const mx_uint *> ptrs;
};
thread_local ShapeSet g_in_shapes, g_out_shapes, g_aux_shapes;

void fill_shapeset(PyObject *list_of_shapes, ShapeSet *ss, mx_uint *size,
                   const mx_uint **ndim_out,
                   const mx_uint ***data_out) {
  Py_ssize_t n = PyList_Size(list_of_shapes);
  ss->ndim.clear();
  ss->shapes.assign(n, {});
  ss->ptrs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject *shp = PyList_GetItem(list_of_shapes, i);
    Py_ssize_t d = PySequence_Size(shp);
    ss->ndim.push_back(static_cast<mx_uint>(d));
    for (Py_ssize_t j = 0; j < d; ++j) {
      PyObject *v = PySequence_GetItem(shp, j);
      ss->shapes[i].push_back((mx_uint)PyLong_AsUnsignedLong(v));
      Py_DECREF(v);
    }
  }
  for (auto &s : ss->shapes) ss->ptrs.push_back(s.data());
  *size = static_cast<mx_uint>(n);
  *ndim_out = ss->ndim.data();
  *data_out = ss->ptrs.data();
}
}  // namespace

MXTPU_API int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args,
                                 const char **keys,
                                 const mx_uint *arg_ind_ptr,
                                 const mx_uint *arg_shape_data,
                                 mx_uint *in_shape_size,
                                 const mx_uint **in_shape_ndim,
                                 const mx_uint ***in_shape_data,
                                 mx_uint *out_shape_size,
                                 const mx_uint **out_shape_ndim,
                                 const mx_uint ***out_shape_data,
                                 mx_uint *aux_shape_size,
                                 const mx_uint **aux_shape_ndim,
                                 const mx_uint ***aux_shape_data,
                                 int *complete) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *names = str_list(keys, num_args);
  PyObject *shapes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    mx_uint lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject *shp = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(shp, j - lo,
                     PyLong_FromUnsignedLong(arg_shape_data[j]));
    PyList_SetItem(shapes, i, shp);
  }
  PyObject *args = Py_BuildValue("(ONN)", static_cast<PyObject *>(sym),
                                 names, shapes);
  PyObject *r = call("symbol_infer_shape", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  fill_shapeset(PyTuple_GetItem(r, 0), &g_in_shapes, in_shape_size,
                in_shape_ndim, in_shape_data);
  fill_shapeset(PyTuple_GetItem(r, 1), &g_out_shapes, out_shape_size,
                out_shape_ndim, out_shape_data);
  fill_shapeset(PyTuple_GetItem(r, 2), &g_aux_shapes, aux_shape_size,
                aux_shape_ndim, aux_shape_data);
  if (complete)
    *complete = (int)PyLong_AsLong(PyTuple_GetItem(r, 3));
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXSymbolGetAtomicSymbolInfo(const char *op_name,
                                          const char **name,
                                          const char **description,
                                          const char **signature) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(s)", op_name);
  PyObject *r = call("symbol_get_atomic_symbol_info", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  g_str_store.clear();
  for (int i = 0; i < 3; ++i) {
    const char *c = PyUnicode_AsUTF8(PyTuple_GetItem(r, i));
    g_str_store.emplace_back(c ? c : "");
  }
  Py_DECREF(r);
  *name = g_str_store[0].c_str();
  *description = g_str_store[1].c_str();
  *signature = g_str_store[2].c_str();
  return 0;
}

// ---------------------------------------------------------------------------
// executor
// ---------------------------------------------------------------------------

MXTPU_API int MXExecutorBind(SymbolHandle sym, mx_uint num_args,
                             const char **arg_names, NDArrayHandle *args_h,
                             mx_uint num_grads, const char **grad_names,
                             NDArrayHandle *grads_h, mx_uint num_aux,
                             const char **aux_names, NDArrayHandle *aux_h,
                             ExecutorHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *a = handle_list(args_h, num_args);
  PyObject *an = str_list(arg_names, num_args);
  PyObject *g = num_grads ? handle_list(grads_h, num_grads)
                          : PyList_New(0);
  PyObject *gn = num_grads ? str_list(grad_names, num_grads)
                           : PyList_New(0);
  PyObject *x = num_aux ? handle_list(aux_h, num_aux) : PyList_New(0);
  PyObject *xn = num_aux ? str_list(aux_names, num_aux) : PyList_New(0);
  PyObject *args = Py_BuildValue("(ONNNNNN)",
                                 static_cast<PyObject *>(sym), a, an, g,
                                 gn, x, xn);
  PyObject *r = call("executor_bind", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXExecutorFree(ExecutorHandle handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

MXTPU_API int MXExecutorForward(ExecutorHandle handle, int is_train) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(Oi)", static_cast<PyObject *>(handle),
                                 is_train);
  PyObject *r = call("executor_forward", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXExecutorBackward(ExecutorHandle handle, mx_uint num_grads,
                                 NDArrayHandle *grads_h) {
  ScopedGIL gil;
  PyObject *g = num_grads ? handle_list(grads_h, num_grads)
                          : PyList_New(0);
  PyObject *args = Py_BuildValue("(ON)", static_cast<PyObject *>(handle),
                                 g);
  PyObject *r = call("executor_backward", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXExecutorOutputs(ExecutorHandle handle, mx_uint *out_size,
                                NDArrayHandle **out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(handle));
  PyObject *r = call("executor_outputs", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  fill_handles(r, out_size, out);
  Py_DECREF(r);
  return 0;
}

// ---------------------------------------------------------------------------
// autograd
// ---------------------------------------------------------------------------

MXTPU_API int MXAutogradSetIsRecording(int is_recording, int *prev) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(i)", is_recording);
  PyObject *r = call("autograd_set_recording", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  if (prev) *prev = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXAutogradSetIsTraining(int is_training, int *prev) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(i)", is_training);
  PyObject *r = call("autograd_set_training", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  if (prev) *prev = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXAutogradMarkVariables(mx_uint num, NDArrayHandle *vars) {
  ScopedGIL gil;
  PyObject *lst = handle_list(vars, num);
  PyObject *args = Py_BuildValue("(N)", lst);
  PyObject *r = call("autograd_mark_variables", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXAutogradBackward(mx_uint num, NDArrayHandle *outputs,
                                 NDArrayHandle *head_grads,
                                 int retain_graph) {
  ScopedGIL gil;
  PyObject *lst = handle_list(outputs, num);
  PyObject *heads = head_grads ? handle_list(head_grads, num)
                               : (Py_INCREF(Py_None), Py_None);
  PyObject *args = Py_BuildValue("(NNi)", lst, heads, retain_graph);
  PyObject *r = call("autograd_backward", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle *out) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(handle));
  PyObject *r = call("autograd_get_grad", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

// ---------------------------------------------------------------------------
// kvstore
// ---------------------------------------------------------------------------

MXTPU_API int MXKVStoreCreate(const char *type, KVStoreHandle *out) {
  ensure_interpreter();
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(s)", type ? type : "local");
  PyObject *r = call("kvstore_create", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *out = r;
  return 0;
}

MXTPU_API int MXKVStoreFree(KVStoreHandle handle) {
  if (!handle) return 0;
  ScopedGIL gil;
  Py_DECREF(static_cast<PyObject *>(handle));
  return 0;
}

static int kv_op(const char *fn, KVStoreHandle kv, mx_uint num,
                 const char **keys, NDArrayHandle *vals) {
  ScopedGIL gil;
  PyObject *k = str_list(keys, num);
  PyObject *v = handle_list(vals, num);
  PyObject *args = Py_BuildValue("(ONN)", static_cast<PyObject *>(kv), k,
                                 v);
  PyObject *r = call(fn, args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStoreInitEx(KVStoreHandle kv, mx_uint num,
                              const char **keys, NDArrayHandle *vals) {
  return kv_op("kvstore_init", kv, num, keys, vals);
}

MXTPU_API int MXKVStorePushEx(KVStoreHandle kv, mx_uint num,
                              const char **keys, NDArrayHandle *vals,
                              int priority) {
  return kv_op("kvstore_push", kv, num, keys, vals);
}

MXTPU_API int MXKVStorePullEx(KVStoreHandle kv, mx_uint num,
                              const char **keys, NDArrayHandle *outs,
                              int priority) {
  return kv_op("kvstore_pull", kv, num, keys, outs);
}

MXTPU_API int MXKVStoreGetRank(KVStoreHandle kv, int *rank) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(kv));
  PyObject *r = call("kvstore_rank", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *rank = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}

MXTPU_API int MXKVStoreGetGroupSize(KVStoreHandle kv, int *size) {
  ScopedGIL gil;
  PyObject *args = Py_BuildValue("(O)", static_cast<PyObject *>(kv));
  PyObject *r = call("kvstore_size", args);
  Py_DECREF(args);
  if (!r) { set_error(py_error()); return -1; }
  *size = (int)PyLong_AsLong(r);
  Py_DECREF(r);
  return 0;
}
