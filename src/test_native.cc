// Native-runtime unit tests (the tests/cpp + googletest analog; plain
// assert-based to avoid a test-framework dependency).
//
// Covers the C ABIs of src/engine.cc (var/version dependency engine:
// writer exclusivity, reader concurrency, FIFO ordering per var,
// WaitForVar versions, WaitAll) and src/recordio.cc (writer/reader
// round-trip, seek/tell, pipeline sharding).
//
// Build + run:  make -C src test

#include <atomic>
#include <cassert>
#include <chrono>
#include <mutex>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void *mxtpu_engine_create(int num_workers);
void mxtpu_engine_destroy(void *e);
void *mxtpu_engine_new_var(void *e);
void mxtpu_engine_push(void *e, void (*fn)(void *), void *arg, void **reads,
                       int n_reads, void **writes, int n_writes);
void mxtpu_engine_wait_var(void *e, void *v, uint64_t version);
void mxtpu_engine_wait_all(void *e);
uint64_t mxtpu_engine_var_version(void *e, void *v);

void *recio_writer_open(const char *path);
int recio_writer_write(void *handle, const char *data, uint64_t len);
void recio_writer_close(void *handle);
void *recio_reader_open(const char *path);
int64_t recio_reader_next(void *handle);
int recio_reader_seek(void *handle, int64_t pos);
int64_t recio_reader_tell(void *handle);
void recio_reader_close(void *handle);
const char *recio_reader_data(void *handle);
}

namespace {

struct Ctx {
  std::atomic<int> counter{0};
  std::atomic<int> concurrent_readers{0};
  std::atomic<int> max_concurrent_readers{0};
  std::atomic<bool> writer_active{false};
  std::atomic<bool> overlap_violation{false};
  std::vector<int> order;
  std::mutex order_mu;
};

Ctx g_ctx;

void reader_task(void *) {
  int now = ++g_ctx.concurrent_readers;
  int prev = g_ctx.max_concurrent_readers.load();
  while (now > prev &&
         !g_ctx.max_concurrent_readers.compare_exchange_weak(prev, now)) {
  }
  if (g_ctx.writer_active.load()) g_ctx.overlap_violation = true;
  std::this_thread::sleep_for(std::chrono::milliseconds(20));
  --g_ctx.concurrent_readers;
  ++g_ctx.counter;
}

void writer_task(void *) {
  if (g_ctx.writer_active.exchange(true)) g_ctx.overlap_violation = true;
  if (g_ctx.concurrent_readers.load() > 0) g_ctx.overlap_violation = true;
  std::this_thread::sleep_for(std::chrono::milliseconds(10));
  g_ctx.writer_active = false;
  ++g_ctx.counter;
}

void ordered_task(void *arg) {
  std::lock_guard<std::mutex> lk(g_ctx.order_mu);
  g_ctx.order.push_back(static_cast<int>(
      reinterpret_cast<intptr_t>(arg)));
}

void test_engine_readers_concurrent_writers_exclusive() {
  void *e = mxtpu_engine_create(4);
  void *v = mxtpu_engine_new_var(e);
  void *reads[1] = {v};
  void *writes[1] = {v};
  // 4 readers (may overlap), one writer, 4 more readers
  for (int i = 0; i < 4; ++i)
    mxtpu_engine_push(e, reader_task, nullptr, reads, 1, nullptr, 0);
  mxtpu_engine_push(e, writer_task, nullptr, nullptr, 0, writes, 1);
  for (int i = 0; i < 4; ++i)
    mxtpu_engine_push(e, reader_task, nullptr, reads, 1, nullptr, 0);
  mxtpu_engine_wait_all(e);
  assert(g_ctx.counter.load() == 9);
  assert(!g_ctx.overlap_violation.load());
  assert(g_ctx.max_concurrent_readers.load() >= 2 &&
         "readers never ran concurrently");
  // the single write bumped the version exactly once
  assert(mxtpu_engine_var_version(e, v) == 1);
  mxtpu_engine_destroy(e);
  std::printf("engine concurrency/exclusivity OK (max readers=%d)\n",
              g_ctx.max_concurrent_readers.load());
}

void test_engine_write_order_and_wait_version() {
  void *e = mxtpu_engine_create(4);
  void *v = mxtpu_engine_new_var(e);
  void *writes[1] = {v};
  for (intptr_t i = 0; i < 16; ++i)
    mxtpu_engine_push(e, ordered_task, reinterpret_cast<void *>(i),
                      nullptr, 0, writes, 1);
  mxtpu_engine_wait_var(e, v, 16);  // wait for the 16th write version
  assert(mxtpu_engine_var_version(e, v) == 16);
  {
    std::lock_guard<std::mutex> lk(g_ctx.order_mu);
    assert(g_ctx.order.size() == 16);
    for (int i = 0; i < 16; ++i) assert(g_ctx.order[i] == i &&
                                        "writes ran out of order");
  }
  mxtpu_engine_destroy(e);
  std::printf("engine write ordering + wait_for_var(version) OK\n");
}

void test_recordio_roundtrip() {
  const char *path = "/tmp/mxtpu_test_native.rec";
  void *w = recio_writer_open(path);
  assert(w);
  std::vector<std::string> recs = {"hello", "", "world!",
                                   std::string(1000, 'x')};
  for (auto &r : recs)
    assert(recio_writer_write(w, r.data(), r.size()) == 0);
  recio_writer_close(w);

  void *r = recio_reader_open(path);
  assert(r);
  std::vector<int64_t> positions;
  for (auto &want : recs) {
    positions.push_back(recio_reader_tell(r));
    int64_t n = recio_reader_next(r);
    assert(n == static_cast<int64_t>(want.size()));
    assert(std::memcmp(recio_reader_data(r), want.data(), n) == 0);
  }
  assert(recio_reader_next(r) < 0);  // EOF
  // seek back to record 2
  assert(recio_reader_seek(r, positions[2]) == 0);
  int64_t n = recio_reader_next(r);
  assert(n == 6 && std::memcmp(recio_reader_data(r), "world!", 6) == 0);
  recio_reader_close(r);
  std::remove(path);
  std::printf("recordio roundtrip + seek OK\n");
}

}  // namespace

int main() {
  test_engine_readers_concurrent_writers_exclusive();
  test_engine_write_order_and_wait_version();
  test_recordio_roundtrip();
  std::printf("ALL NATIVE TESTS PASSED\n");
  return 0;
}
