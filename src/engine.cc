// Native dependency engine: async task scheduler ordered by variable
// read/write sets.
//
// TPU-native role: XLA's async dispatch owns device-side ordering, so this
// engine schedules the HOST side of the framework — IO pipelines, batch
// assembly, checkpoint writes, callback fan-out — with the same contract as
// the reference's core scheduler (include/mxnet/engine.h: NewVariable,
// PushAsync(read_vars, write_vars), WaitForVar, WaitForAll; version-counted
// vars as in src/engine/threaded_engine.h ThreadedVar). Fresh
// implementation: a single MPMC ready-queue + per-var FIFO waiters, with
// sequential-write/concurrent-read admission (readers admitted together,
// writers exclusive).
//
// Exposed over a C ABI for ctypes. Tasks are C function pointers
// (fn(void* arg)); the python wrapper passes trampolines for host work.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using TaskFn = void (*)(void*);

struct Opr;

struct Var {
  std::mutex mu;
  // queue of pending ops on this var, in program order
  struct Waiter {
    Opr* opr;
    bool is_write;
  };
  std::deque<Waiter> queue;
  int active_readers = 0;
  bool active_writer = false;
  uint64_t version = 0;
  std::condition_variable cv;  // for WaitForVar
};

struct Opr {
  TaskFn fn = nullptr;
  void* arg = nullptr;
  std::vector<Var*> reads;
  std::vector<Var*> writes;
  std::atomic<int> pending{0};  // vars not yet granted
};

class Engine {
 public:
  explicit Engine(int num_workers) : stop_(false), inflight_(0) {
    for (int i = 0; i < num_workers; ++i)
      workers_.emplace_back([this] { WorkerLoop(); });
  }

  ~Engine() {
    {
      std::unique_lock<std::mutex> lk(qmu_);
      stop_ = true;
      qcv_.notify_all();
    }
    for (auto& t : workers_) t.join();
    for (auto* v : vars_) delete v;
  }

  Var* NewVar() {
    auto* v = new Var();
    std::unique_lock<std::mutex> lk(vars_mu_);
    vars_.push_back(v);
    return v;
  }

  void Push(TaskFn fn, void* arg, Var** reads, int n_reads, Var** writes,
            int n_writes) {
    auto* opr = new Opr();
    opr->fn = fn;
    opr->arg = arg;
    opr->reads.assign(reads, reads + n_reads);
    opr->writes.assign(writes, writes + n_writes);
    inflight_.fetch_add(1);
    int deps = static_cast<int>(opr->reads.size() + opr->writes.size());
    if (deps == 0) {
      Ready(opr);
      return;
    }
    opr->pending.store(deps);
    // enqueue on each var; grant immediately where possible
    for (Var* v : opr->reads) Enqueue(v, opr, false);
    for (Var* v : opr->writes) Enqueue(v, opr, true);
  }

  void WaitForVar(Var* v, uint64_t version_at_least) {
    std::unique_lock<std::mutex> lk(v->mu);
    v->cv.wait(lk, [v, version_at_least] {
      return v->queue.empty() && !v->active_writer &&
             v->active_readers == 0 && v->version >= version_at_least;
    });
  }

  void WaitAll() {
    std::unique_lock<std::mutex> lk(done_mu_);
    done_cv_.wait(lk, [this] { return inflight_.load() == 0; });
  }

  uint64_t Version(Var* v) {
    std::unique_lock<std::mutex> lk(v->mu);
    return v->version;
  }

 private:
  void Enqueue(Var* v, Opr* opr, bool is_write) {
    bool granted = false;
    {
      std::unique_lock<std::mutex> lk(v->mu);
      if (v->queue.empty() && !v->active_writer &&
          (!is_write ? true : v->active_readers == 0)) {
        // immediate admission
        if (is_write)
          v->active_writer = true;
        else
          v->active_readers += 1;
        granted = true;
      } else {
        v->queue.push_back({opr, is_write});
      }
    }
    if (granted) Granted(opr);
  }

  void Granted(Opr* opr) {
    if (opr->pending.fetch_sub(1) == 1) Ready(opr);
  }

  void Ready(Opr* opr) {
    std::unique_lock<std::mutex> lk(qmu_);
    ready_.push_back(opr);
    qcv_.notify_one();
  }

  void Release(Var* v, bool was_write) {
    std::vector<Opr*> to_grant;
    {
      std::unique_lock<std::mutex> lk(v->mu);
      if (was_write) {
        v->active_writer = false;
        v->version += 1;
      } else {
        v->active_readers -= 1;
      }
      // admit next waiters: either one writer, or a run of readers
      while (!v->queue.empty()) {
        auto& w = v->queue.front();
        if (w.is_write) {
          if (v->active_readers == 0 && !v->active_writer) {
            v->active_writer = true;
            to_grant.push_back(w.opr);
            v->queue.pop_front();
          }
          break;
        }
        if (v->active_writer) break;
        v->active_readers += 1;
        to_grant.push_back(w.opr);
        v->queue.pop_front();
      }
      v->cv.notify_all();
    }
    for (Opr* o : to_grant) Granted(o);
  }

  void WorkerLoop() {
    while (true) {
      Opr* opr = nullptr;
      {
        std::unique_lock<std::mutex> lk(qmu_);
        qcv_.wait(lk, [this] { return stop_ || !ready_.empty(); });
        if (stop_ && ready_.empty()) return;
        opr = ready_.front();
        ready_.pop_front();
      }
      if (opr->fn) opr->fn(opr->arg);
      for (Var* v : opr->reads) Release(v, false);
      for (Var* v : opr->writes) Release(v, true);
      delete opr;
      if (inflight_.fetch_sub(1) == 1) {
        std::unique_lock<std::mutex> lk(done_mu_);
        done_cv_.notify_all();
      }
    }
  }

  std::vector<std::thread> workers_;
  std::deque<Opr*> ready_;
  std::mutex qmu_;
  std::condition_variable qcv_;
  bool stop_;
  std::atomic<int> inflight_;
  std::mutex done_mu_;
  std::condition_variable done_cv_;
  std::mutex vars_mu_;
  std::vector<Var*> vars_;
};

}  // namespace

extern "C" {

void* mxtpu_engine_create(int num_workers) {
  return new Engine(num_workers < 1 ? 1 : num_workers);
}

void mxtpu_engine_destroy(void* e) { delete static_cast<Engine*>(e); }

void* mxtpu_engine_new_var(void* e) {
  return static_cast<Engine*>(e)->NewVar();
}

void mxtpu_engine_push(void* e, void (*fn)(void*), void* arg, void** reads,
                       int n_reads, void** writes, int n_writes) {
  static_cast<Engine*>(e)->Push(fn, arg,
                                reinterpret_cast<Var**>(reads), n_reads,
                                reinterpret_cast<Var**>(writes), n_writes);
}

void mxtpu_engine_wait_var(void* e, void* v, uint64_t version) {
  static_cast<Engine*>(e)->WaitForVar(static_cast<Var*>(v), version);
}

void mxtpu_engine_wait_all(void* e) { static_cast<Engine*>(e)->WaitAll(); }

uint64_t mxtpu_engine_var_version(void* e, void* v) {
  return static_cast<Engine*>(e)->Version(static_cast<Var*>(v));
}

}  // extern "C"
