// Native RecordIO reader/writer + threaded prefetching record pipeline.
//
// TPU-native replacement for the reference's dmlc-core RecordIO
// (3rdparty/dmlc-core, consumed by src/io/iter_image_recordio_2.cc) and the
// threaded-iter machinery: a compact C++ library exposed over a C ABI and
// bound with ctypes (no pybind11 in this image).
//
// Format (wire-compatible with the reference so existing .rec datasets and
// im2rec output work):
//   [uint32 magic = 0xced7230a][uint32 lrec][payload][pad to 4B]
//   lrec: upper 3 bits = continuation flag (0 = whole record), lower 29 bits
//   = payload length. Multi-part records (cflag 1/2/3) are reassembled.
//
// The pipeline: N reader threads pull record offsets from a shared cursor,
// read + (optionally) shuffle within a window, and push length-prefixed
// records into a bounded ring buffer the python side drains in batches —
// the PrefetcherIter/ThreadedIter analog without GIL involvement.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;

inline uint32_t EncodeLRec(uint32_t cflag, uint32_t len) {
  return (cflag << 29u) | (len & ((1u << 29u) - 1u));
}
inline uint32_t DecodeFlag(uint32_t lrec) { return lrec >> 29u; }
inline uint32_t DecodeLen(uint32_t lrec) { return lrec & ((1u << 29u) - 1u); }

struct Writer {
  FILE* fp = nullptr;
};

struct Reader {
  FILE* fp = nullptr;
  std::vector<char> buf;
};

struct Record {
  std::vector<char> data;
  bool bad = false;  // read failed for this index (tombstone: skipped in
                     // order, so a corrupt record can't stall the window)
};

}  // namespace

extern "C" {

// ---------------------------------------------------------------- writer --
void* recio_writer_open(const char* path) {
  FILE* fp = std::fopen(path, "wb");
  if (!fp) return nullptr;
  auto* w = new Writer();
  w->fp = fp;
  return w;
}

int recio_writer_write(void* handle, const char* data, uint64_t len) {
  auto* w = static_cast<Writer*>(handle);
  uint32_t magic = kMagic;
  uint32_t lrec = EncodeLRec(0, static_cast<uint32_t>(len));
  if (std::fwrite(&magic, 4, 1, w->fp) != 1) return -1;
  if (std::fwrite(&lrec, 4, 1, w->fp) != 1) return -1;
  if (len && std::fwrite(data, 1, len, w->fp) != len) return -1;
  uint32_t pad = (4 - (len & 3u)) & 3u;
  static const char zeros[4] = {0, 0, 0, 0};
  if (pad && std::fwrite(zeros, 1, pad, w->fp) != pad) return -1;
  return 0;
}

int64_t recio_writer_tell(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  return std::ftell(w->fp);
}

void recio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  if (w->fp) std::fclose(w->fp);
  delete w;
}

// ---------------------------------------------------------------- reader --
void* recio_reader_open(const char* path) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return nullptr;
  auto* r = new Reader();
  r->fp = fp;
  return r;
}

// Reads the next logical record into an internal buffer; returns length or
// -1 at EOF / error. Reassembles continuation parts.
int64_t recio_reader_next(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  r->buf.clear();
  while (true) {
    uint32_t magic = 0, lrec = 0;
    if (std::fread(&magic, 4, 1, r->fp) != 1) return -1;
    if (magic != kMagic) return -1;
    if (std::fread(&lrec, 4, 1, r->fp) != 1) return -1;
    uint32_t len = DecodeLen(lrec);
    uint32_t flag = DecodeFlag(lrec);
    size_t off = r->buf.size();
    r->buf.resize(off + len);
    if (len && std::fread(r->buf.data() + off, 1, len, r->fp) != len)
      return -1;
    uint32_t pad = (4 - (len & 3u)) & 3u;
    if (pad) std::fseek(r->fp, pad, SEEK_CUR);
    if (flag == 0 || flag == 3) break;  // whole record or last part
  }
  return static_cast<int64_t>(r->buf.size());
}

const char* recio_reader_data(void* handle) {
  return static_cast<Reader*>(handle)->buf.data();
}

int recio_reader_seek(void* handle, int64_t pos) {
  return std::fseek(static_cast<Reader*>(handle)->fp, pos, SEEK_SET);
}

int64_t recio_reader_tell(void* handle) {
  return std::ftell(static_cast<Reader*>(handle)->fp);
}

void recio_reader_close(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  if (r->fp) std::fclose(r->fp);
  delete r;
}

// -------------------------------------------------------------- pipeline --
// Threaded prefetcher: worker threads read records sequentially partitioned
// by (part_index, num_parts) for distributed sharding (ref:
// iter_image_recordio_2.cc part_index/num_parts) and fill a bounded
// REORDER buffer keyed by record index. Records are delivered to the
// consumer in submission (index) order, not completion order — with
// num_threads > 1 a bare FIFO queue interleaved batches (labels came back
// permuted), which broke every consumer that pairs records with external
// state (ref: ThreadedIter preserves order for the same reason).

struct Pipeline {
  std::string path;
  std::vector<int64_t> offsets;  // record start offsets (shard-local)
  std::map<size_t, Record> reorder;  // index -> record, delivered in order
  size_t next_emit = 0;              // next index the consumer gets
  std::mutex mu;
  std::condition_variable cv_push, cv_pop;
  size_t capacity = 256;  // producer window: [next_emit, next_emit + cap)
  std::atomic<size_t> cursor{0};
  size_t active_workers = 0;
  std::atomic<bool> done{false};
  std::atomic<bool> stop{false};
  std::vector<std::thread> workers;
  int num_threads = 1;
  bool shuffle = false;
  uint64_t seed = 0;
  int epoch = 0;
};

static int BuildIndex(Pipeline* p, int part_index, int num_parts) {
  FILE* fp = std::fopen(p->path.c_str(), "rb");
  if (!fp) return -1;
  std::vector<int64_t> all;
  int64_t pos = 0;
  while (true) {
    uint32_t magic = 0, lrec = 0;
    if (std::fread(&magic, 4, 1, fp) != 1) break;
    if (magic != kMagic) break;
    if (std::fread(&lrec, 4, 1, fp) != 1) break;
    uint32_t len = DecodeLen(lrec);
    uint32_t flag = DecodeFlag(lrec);
    uint32_t pad = (4 - (len & 3u)) & 3u;
    if (flag == 0) all.push_back(pos);  // only whole-record heads
    std::fseek(fp, len + pad, SEEK_CUR);
    pos = std::ftell(fp);
  }
  std::fclose(fp);
  // contiguous shard for this worker (ref: part_index/num_parts sharding)
  size_t n = all.size();
  size_t per = (n + num_parts - 1) / num_parts;
  size_t lo = per * part_index;
  size_t hi = lo + per < n ? lo + per : n;
  for (size_t i = lo; i < hi; ++i) p->offsets.push_back(all[i]);
  return static_cast<int>(p->offsets.size());
}

static void ShuffleOffsets(Pipeline* p) {
  // Fisher-Yates with a splitmix64 stream seeded by (seed, epoch)
  uint64_t x = p->seed + 0x9e3779b97f4a7c15ull * (p->epoch + 1);
  auto next = [&x]() {
    x += 0x9e3779b97f4a7c15ull;
    uint64_t z = x;
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ull;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebull;
    return z ^ (z >> 31);
  };
  for (size_t i = p->offsets.size(); i > 1; --i) {
    size_t j = static_cast<size_t>(next() % i);
    std::swap(p->offsets[i - 1], p->offsets[j]);
  }
}

static void WorkerLoop(Pipeline* p) {
  FILE* fp = std::fopen(p->path.c_str(), "rb");
  while (fp && !p->stop.load()) {
    size_t i = p->cursor.fetch_add(1);
    if (i >= p->offsets.size()) break;
    // a failed read becomes a TOMBSTONE, not a silent worker exit: a
    // claimed index must always reach the reorder buffer, or next_emit
    // would stall and every window-blocked sibling deadlock with it
    Record rec;
    std::fseek(fp, p->offsets[i], SEEK_SET);
    uint32_t magic = 0, lrec = 0;
    if (std::fread(&magic, 4, 1, fp) == 1 && magic == kMagic &&
        std::fread(&lrec, 4, 1, fp) == 1) {
      uint32_t len = DecodeLen(lrec);
      rec.data.resize(len);
      if (len && std::fread(rec.data.data(), 1, len, fp) != len)
        rec.bad = true;
    } else {
      rec.bad = true;
    }
    if (rec.bad) rec.data.clear();
    std::unique_lock<std::mutex> lk(p->mu);
    // admit only indices inside the reorder window: the worker holding
    // next_emit always fits (next_emit < next_emit + capacity), so the
    // consumer can always advance — no producer/consumer deadlock
    p->cv_push.wait(lk, [p, i] {
      return i < p->next_emit + p->capacity || p->stop.load();
    });
    if (p->stop.load()) break;
    p->reorder.emplace(i, std::move(rec));
    p->cv_pop.notify_all();
  }
  if (fp) std::fclose(fp);
  // last worker out marks done; wake BOTH sides (a window-blocked sibling
  // must re-check, not sleep through the shutdown)
  std::unique_lock<std::mutex> lk(p->mu);
  if (--p->active_workers == 0) p->done.store(true);
  p->cv_pop.notify_all();
  p->cv_push.notify_all();
}

void* recio_pipeline_create(const char* path, int num_threads,
                            int part_index, int num_parts, int shuffle,
                            uint64_t seed) {
  auto* p = new Pipeline();
  p->path = path;
  p->shuffle = shuffle != 0;
  p->seed = seed;
  if (BuildIndex(p, part_index, num_parts) < 0) {
    delete p;
    return nullptr;
  }
  if (p->shuffle) ShuffleOffsets(p);
  p->num_threads = num_threads < 1 ? 1 : num_threads;
  p->active_workers = static_cast<size_t>(p->num_threads);
  for (int i = 0; i < p->num_threads; ++i)
    p->workers.emplace_back(WorkerLoop, p);
  return p;
}

int64_t recio_pipeline_size(void* handle) {
  return static_cast<Pipeline*>(handle)->offsets.size();
}

// Pops the next record IN SUBMISSION ORDER; returns length (copied into
// out, up to cap bytes) or -1 when the epoch is exhausted.
int64_t recio_pipeline_next(void* handle, char* out, int64_t cap) {
  auto* p = static_cast<Pipeline*>(handle);
  Record rec;
  {
    std::unique_lock<std::mutex> lk(p->mu);
    for (;;) {
      auto it = p->reorder.find(p->next_emit);
      if (it != p->reorder.end()) {
        bool bad = it->second.bad;
        if (!bad) rec = std::move(it->second);
        p->reorder.erase(it);
        ++p->next_emit;
        p->cv_push.notify_all();
        if (bad) continue;  // tombstone: record lost to a read error —
                            // skip it, stay ordered
        break;
      }
      if (p->stop.load()) return -1;
      if (p->done.load()) {
        if (p->reorder.empty()) return -1;
        // catastrophic worker loss (e.g. its fopen failed): indices it
        // claimed never arrived — skip to the next record that did
        p->next_emit = p->reorder.begin()->first;
        continue;
      }
      p->cv_pop.wait(lk);
    }
  }
  int64_t n = static_cast<int64_t>(rec.data.size());
  if (n > cap) n = cap;
  std::memcpy(out, rec.data.data(), n);
  return static_cast<int64_t>(rec.data.size());
}

void recio_pipeline_reset(void* handle) {
  auto* p = static_cast<Pipeline*>(handle);
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->stop.store(true);
    p->cv_push.notify_all();
    p->cv_pop.notify_all();
  }
  for (auto& t : p->workers) t.join();
  p->workers.clear();
  p->reorder.clear();
  p->next_emit = 0;
  p->cursor.store(0);
  p->done.store(false);
  p->stop.store(false);
  p->epoch += 1;
  if (p->shuffle) ShuffleOffsets(p);
  p->active_workers = static_cast<size_t>(p->num_threads);
  for (int i = 0; i < p->num_threads; ++i)
    p->workers.emplace_back(WorkerLoop, p);
}

void recio_pipeline_destroy(void* handle) {
  auto* p = static_cast<Pipeline*>(handle);
  {
    std::unique_lock<std::mutex> lk(p->mu);
    p->stop.store(true);
    p->cv_push.notify_all();
    p->cv_pop.notify_all();
  }
  for (auto& t : p->workers) t.join();
  delete p;
}

}  // extern "C"
