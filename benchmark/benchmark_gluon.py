"""Model-zoo throughput benchmark
(ref: benchmark/python/gluon/benchmark_gluon.py — per-model inference and
training img/s across the vision zoo)."""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np


def score(model_name, batch_size, image_shape, n_iter, train):
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.gluon.model_zoo.vision import get_model
    from mxnet_tpu.parallel import SPMDTrainer
    from mxnet_tpu.ndarray.ndarray import from_jax

    mx.random.seed(0)
    net = get_model(model_name)
    net.initialize(mx.init.Xavier())
    rs = np.random.RandomState(0)
    data = jnp.asarray(rs.randn(batch_size, *image_shape)
                       .astype(np.float32))

    if train:
        label = jnp.asarray(rs.randint(0, 1000, batch_size)
                            .astype(np.float32))
        tr = SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(), mesh=None,
                         optimizer="sgd",
                         optimizer_params={"learning_rate": 0.01})
        float(tr.step(data, label))  # compile
        t0 = time.time()
        for _ in range(n_iter - 1):
            tr.step(data, label)
        float(tr.step(data, label))
    else:
        with autograd.pause():
            net._imperative_call(from_jax(data[:1]))  # resolve shapes
        params = [p for _, p in sorted(net.collect_params().items())]
        pa = tuple(p._data._data for p in params)

        def fwd(pa, x):
            orig = []
            for p, a in zip(params, pa):
                orig.append(p._data._data)
                p._data._data = a
            try:
                with autograd.pause():
                    return net._imperative_call(from_jax(x))._data
            finally:
                for p, o in zip(params, orig):
                    p._data._data = o

        jf = jax.jit(fwd)
        float(jf(pa, data).sum())  # compile
        t0 = time.time()
        for _ in range(n_iter - 1):
            out = jf(pa, data)
        float(jf(pa, data).sum())
    dt = time.time() - t0
    return batch_size * n_iter / dt


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--models", default="resnet18_v1,mobilenet_v2_1_0")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--num-iters", type=int, default=10)
    ap.add_argument("--mode", choices=["inference", "training", "both"],
                    default="both")
    args = ap.parse_args()
    shape = tuple(int(x) for x in args.image_shape.split(","))
    for name in args.models.split(","):
        if args.mode in ("inference", "both"):
            ips = score(name, args.batch_size, shape, args.num_iters, False)
            print(f"{name} inference: {ips:.1f} img/s "
                  f"(batch {args.batch_size})", flush=True)
        if args.mode in ("training", "both"):
            ips = score(name, args.batch_size, shape, args.num_iters, True)
            print(f"{name} training: {ips:.1f} img/s "
                  f"(batch {args.batch_size})", flush=True)


if __name__ == "__main__":
    main()
