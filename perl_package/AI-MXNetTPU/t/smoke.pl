#!/usr/bin/perl
# Smoke drive of AI::MXNetTPU (run by tests/test_perl_binding.py):
#   1. NDArray + imperative invoke
#   2. C-callback custom op (registered from this module's XS glue)
#   3. LeNet predict through the predict API
# argv: <model-prefix> (files <prefix>-symbol.json / <prefix>-0000.params)
use strict;
use warnings;
use AI::MXNetTPU;

sub approx {
    my ($got, $want, $what) = @_;
    die "$what: size @{[scalar @$got]} vs @{[scalar @$want]}\n"
        unless @$got == @$want;
    for my $i (0 .. $#$want) {
        die "$what\[$i]: $got->[$i] vs $want->[$i]\n"
            if abs($got->[$i] - $want->[$i]) > 1e-4 * (1 + abs($want->[$i]));
    }
}

# -- 1. imperative ---------------------------------------------------------
my $a = AI::MXNetTPU::nd_create([2, 3]);
AI::MXNetTPU::nd_set($a, [1, 2, 3, 4, 5, 6]);
my $b = AI::MXNetTPU::nd_create([2, 3]);
AI::MXNetTPU::nd_set($b, [10, 20, 30, 40, 50, 60]);
my $sum = AI::MXNetTPU::invoke("broadcast_add", [$a, $b], [], [])->[0];
approx(AI::MXNetTPU::nd_values($sum), [11, 22, 33, 44, 55, 66], "add");
my $scaled = AI::MXNetTPU::invoke("_mul_scalar", [$a], ["scalar"], ["2.5"])->[0];
approx(AI::MXNetTPU::nd_values($scaled), [2.5, 5, 7.5, 10, 12.5, 15], "mul_scalar");
print "perl imperative ok\n";

# -- 2. C-callback custom op ------------------------------------------------
AI::MXNetTPU::register_sqr_op();
my $sq = AI::MXNetTPU::invoke("Custom", [$a], ["op_type"], ["perl_sqr"])->[0];
approx(AI::MXNetTPU::nd_values($sq), [1, 4, 9, 16, 25, 36], "custom sqr");
print "perl custom op ok\n";

# -- 3. LeNet predict -------------------------------------------------------
my $prefix = $ARGV[0] or die "usage: smoke.pl <model-prefix>\n";
open my $jf, '<', "$prefix-symbol.json" or die "no symbol json: $!";
my $json = do { local $/; <$jf> };
close $jf;
open my $pf, '<:raw', "$prefix-0000.params" or die "no params: $!";
my $params = do { local $/; <$pf> };
close $pf;

my $pred = AI::MXNetTPU::pred_create($json, $params, "data", [1, 1, 28, 28]);
my @img = map { ($_ % 7) / 7.0 } 0 .. 28 * 28 - 1;
AI::MXNetTPU::pred_set_input($pred, "data", \@img);
AI::MXNetTPU::pred_forward($pred);
my $out = AI::MXNetTPU::pred_output($pred, 0);
die "lenet: expected 10 logits, got @{[scalar @$out]}\n" unless @$out == 10;
my $finite = 1;
for (@$out) { $finite = 0 if $_ != $_; }
die "lenet: NaN logits\n" unless $finite;
AI::MXNetTPU::pred_free($pred);
printf "perl lenet predict ok: [%s]\n", join(", ", map { sprintf "%.3f", $_ } @$out);
AI::MXNetTPU::nd_free($_) for ($a, $b, $sum, $scaled, $sq);
print "PERL_BINDING_OK\n";
