#!/usr/bin/env perl
# MNIST-style digit classification trained END TO END from Perl through
# the idiomatic NDArray API (generated op methods + autograd + in-place
# sgd_update) — the reference's Perl frontend trains the same way over
# libmxnet (ref: perl-package/AI-MXNet/examples/mnist.pl).
#
# Data is the zero-egress stand-in the repo's CTC example uses: 3x5
# digit glyphs rendered into an 8x8 image with noise and a random
# offset, flattened to 64 features. An MLP (64 -> 48 relu -> 10) must
# reach >90% held-out accuracy in a couple hundred SGD steps; random is
# 10%.
#
# Usage: perl -Mblib t/train_mnist.pl [iters]
use strict;
use warnings;
use FindBin;
use lib "$FindBin::Bin/../lib";
use AI::MXNetTPU;
use AI::MXNetTPU::NDArray;
use AI::MXNetTPU::AutoGrad qw(record);

srand(7);

my $ITERS = $ARGV[0] || 220;
my $BATCH = 64;
my $LR    = 0.2;
my $MOM   = 0.9;

# ---- glyph data ----------------------------------------------------------
my %GLYPH = (
    0 => ['111', '101', '101', '101', '111'],
    1 => ['010', '110', '010', '010', '111'],
    2 => ['111', '001', '111', '100', '111'],
    3 => ['111', '001', '111', '001', '111'],
    4 => ['101', '101', '111', '001', '001'],
    5 => ['111', '100', '111', '001', '111'],
    6 => ['111', '100', '111', '101', '111'],
    7 => ['111', '001', '010', '010', '010'],
    8 => ['111', '101', '111', '101', '111'],
    9 => ['111', '101', '111', '001', '111'],
);

sub make_batch {
    my ($n) = @_;
    my (@x, @y);
    for (1 .. $n) {
        my $d  = int(rand(10));
        my $r0 = int(rand(2));       # vertical offset
        my $c0 = int(rand(3));       # horizontal offset
        my @img = map { 0.3 * rand() } 1 .. 64;
        my $rows = $GLYPH{$d};
        for my $r (0 .. 4) {
            my @bits = split //, $rows->[$r];
            for my $c (0 .. 2) {
                $img[($r0 + $r) * 8 + $c0 + $c] += 1.0 if $bits[$c];
            }
        }
        push @x, @img;
        push @y, $d;
    }
    return (\@x, \@y);
}

# ---- model ---------------------------------------------------------------
my $HID = 48;
my $lim1 = sqrt(6.0 / (64 + $HID));
my $lim2 = sqrt(6.0 / ($HID + 10));
my $w1 = AI::MXNetTPU::NDArray->uniform([$HID, 64], -$lim1, $lim1);
my $b1 = AI::MXNetTPU::NDArray->zeros([$HID]);
my $w2 = AI::MXNetTPU::NDArray->uniform([10, $HID], -$lim2, $lim2);
my $b2 = AI::MXNetTPU::NDArray->zeros([10]);
my @params = ($w1, $b1, $w2, $b2);
$_->attach_grad for @params;
# momentum buffers, updated in place alongside the weights (keyed by
# refaddr — hash keys would otherwise stringify the NDArray)
use Scalar::Util qw(refaddr);
my %mom = map { refaddr($_) => AI::MXNetTPU::NDArray->zeros($_->shape) }
    @params;

printf "perl frontend: %d generated op methods\n",
    $AI::MXNetTPU::NDArray::NUM_GENERATED_OPS;

sub forward {
    my ($x) = @_;
    return $x->FullyConnected($w1, $b1, num_hidden => $HID)
             ->Activation(act_type => 'relu')
             ->FullyConnected($w2, $b2, num_hidden => 10);
}

# ---- training loop -------------------------------------------------------
for my $it (0 .. $ITERS - 1) {
    my ($xv, $yv) = make_batch($BATCH);
    my $x = AI::MXNetTPU::NDArray->new([$BATCH, 64], $xv);
    my $y = AI::MXNetTPU::NDArray->new([$BATCH], $yv);

    my $loss = record {
        my $logp = forward($x)->log_softmax(axis => -1);
        ($logp->pick($y, axis => 1)->mean * -1.0);
    };
    $loss->backward;
    AI::MXNetTPU::NDArray->invoke_into(
        'sgd_mom_update', [$_, $_->grad, $mom{refaddr($_)}],
        [$_, $mom{refaddr($_)}],
        lr => $LR, momentum => $MOM, wd => 0)
        for @params;

    printf "iter %d loss %.4f\n", $it, $loss->asscalar
        if $it % 40 == 0 || $it == $ITERS - 1;
}

# ---- held-out evaluation -------------------------------------------------
my ($xv, $yv) = make_batch(256);
my $x   = AI::MXNetTPU::NDArray->new([256, 64], $xv);
my $hit = 0;
my $pred = forward($x)->argmax(axis => 1)->aslist;
for my $i (0 .. 255) {
    ++$hit if $pred->[$i] == $yv->[$i];
}
printf "test accuracy: %.3f\n", $hit / 256;
exit($hit / 256 >= 0.9 ? 0 : 1);
