package AI::MXNetTPU::NDArray;

# Idiomatic Perl NDArray over the mxnet_tpu flat C API.
#
# The operator surface is NOT hand-written: at load time the module asks
# the runtime for every registered atomic-symbol creator
# (MXSymbolListAtomicSymbolCreators) and installs one method per op —
# the same codegen pattern the reference Perl frontend uses to build
# AI::MXNet::NDArray's method table from libmxnet
# (ref: perl-package/AI-MXNet/lib/AI/MXNet.pm function generation).
# So $x->FullyConnected($w, $b, num_hidden => 10), $x->log_softmax,
# $x->argmax(axis => 1), ... all exist without any per-op Perl code.
#
# Overloaded arithmetic (+ - * /) dispatches to broadcast_* for
# NDArray-NDArray and _*_scalar for NDArray-number, autograd
# (attach_grad/grad/backward) rides the C API's tape, and in-place
# optimizer steps go through the preallocated-output invoke
# (AI::MXNetTPU::invoke_into), which is how sgd_update mutates a weight
# instead of allocating a new one.

use strict;
use warnings;
use Scalar::Util qw(blessed);
use AI::MXNetTPU ();

our $VERSION = '0.02';

use overload
    '+'  => \&_op_add,
    '-'  => \&_op_sub,
    '*'  => \&_op_mul,
    '/'  => \&_op_div,
    '""' => \&_op_str,
    '==' => \&_op_eq;

# ---- lifecycle -----------------------------------------------------------

sub _wrap {
    my ($h, $own) = @_;
    return bless { h => $h, own => defined $own ? $own : 1 },
        __PACKAGE__;
}

sub handle { $_[0]{h} }

sub new {
    my ($class, $shape, $values) = @_;
    my $h = AI::MXNetTPU::nd_create($shape);
    my $self = _wrap($h);
    $self->set($values) if $values;
    return $self;
}

sub zeros {
    my ($class, $shape) = @_;
    return $class->invoke('_zeros', [], shape => _shape_str($shape));
}

sub ones {
    my ($class, $shape) = @_;
    return $class->invoke('_ones', [], shape => _shape_str($shape));
}

sub uniform {
    my ($class, $shape, $lo, $hi) = @_;
    my $n = 1;
    $n *= $_ for @$shape;
    my @v = map { $lo + rand() * ($hi - $lo) } 1 .. $n;
    return $class->new($shape, \@v);
}

sub DESTROY {
    my ($self) = @_;
    AI::MXNetTPU::nd_free($self->{h}) if $self->{own};
}

# ---- data access ---------------------------------------------------------

sub set    { AI::MXNetTPU::nd_set($_[0]{h}, $_[1]); $_[0] }
sub shape  { AI::MXNetTPU::nd_shape($_[0]{h}) }
sub aslist { AI::MXNetTPU::nd_values($_[0]{h}) }
sub asscalar { AI::MXNetTPU::nd_values($_[0]{h})->[0] }

sub _op_eq {
    my ($self, $other) = @_;
    return 0 unless blessed($other) && $other->isa(__PACKAGE__);
    my ($sa, $sb) = ($self->shape, $other->shape);
    return 0 unless @$sa == @$sb;
    $sa->[$_] == $sb->[$_] or return 0 for 0 .. $#$sa;
    my ($va, $vb) = ($self->aslist, $other->aslist);
    $va->[$_] == $vb->[$_] or return 0 for 0 .. $#$va;
    return 1;
}

sub _op_str {
    my ($self) = @_;
    my $v = $self->aslist;
    my $body = join(', ', map { sprintf('%.4g', $_) }
                    @$v > 8 ? @{$v}[0 .. 7] : @$v);
    $body .= ', ...' if @$v > 8;
    return "[$body]";
}

# ---- autograd ------------------------------------------------------------

sub attach_grad {
    my ($self) = @_;
    AI::MXNetTPU::mark_variables([$self->{h}]);
    return $self;
}

sub grad {
    my ($self) = @_;
    my $g = AI::MXNetTPU::nd_grad($self->{h});
    # MXNDArrayGetGrad returns a NEW handle reference each call; own it
    # so DESTROY releases it (own=0 here would leak one ref per call)
    return $g ? _wrap($g, 1) : undef;
}

sub backward { AI::MXNetTPU::backward($_[0]{h}); $_[0] }

# ---- operator invocation -------------------------------------------------

# trailing comma so a 1-d shape parses as a tuple, not a bare int
sub _shape_str { '(' . join(',', @{$_[0]}) . ',)' }

# functional form: AI::MXNetTPU::NDArray->invoke($op, [@ndarray_args],
# %kwargs) — also what every generated method calls into.
sub invoke {
    my ($class, $op, $ins, %kw) = @_;
    my @handles = map { blessed($_) ? $_->{h} : $_ } @$ins;
    my (@keys, @vals);
    for my $k (sort keys %kw) {
        push @keys, $k;
        push @vals, "$kw{$k}";
    }
    my $outs = AI::MXNetTPU::invoke($op, \@handles, \@keys, \@vals);
    my @wrapped = map { _wrap($_) } @$outs;
    return wantarray ? @wrapped : $wrapped[0];
}

# in-place form: results land in the given output NDArrays (preallocated
# -output contract of MXImperativeInvoke) — optimizer updates use this.
sub invoke_into {
    my ($class, $op, $ins, $outs, %kw) = @_;
    my @ih = map { blessed($_) ? $_->{h} : $_ } @$ins;
    my @oh = map { blessed($_) ? $_->{h} : $_ } @$outs;
    my (@keys, @vals);
    for my $k (sort keys %kw) {
        push @keys, $k;
        push @vals, "$kw{$k}";
    }
    AI::MXNetTPU::invoke_into($op, \@ih, \@keys, \@vals, \@oh);
    return wantarray ? @$outs : $outs->[0];
}

# ---- overloaded arithmetic ----------------------------------------------

sub _binop {
    my ($bcast, $scalar, $rscalar) = @_;
    return sub {
        my ($self, $other, $swap) = @_;
        if (blessed($other) && $other->isa(__PACKAGE__)) {
            my ($a, $b) = $swap ? ($other, $self) : ($self, $other);
            return __PACKAGE__->invoke($bcast, [$a, $b]);
        }
        return __PACKAGE__->invoke($swap ? $rscalar : $scalar, [$self],
                                   scalar => $other);
    };
}

{
    no warnings 'once';
    *_op_add = _binop('broadcast_add', '_plus_scalar', '_plus_scalar');
    *_op_sub = _binop('broadcast_sub', '_minus_scalar', '_rminus_scalar');
    *_op_mul = _binop('broadcast_mul', '_mul_scalar', '_mul_scalar');
    *_op_div = _binop('broadcast_div', '_div_scalar', '_rdiv_scalar');
}

# ---- generated op methods ------------------------------------------------

my %RESERVED = map { $_ => 1 }
    qw(new zeros ones uniform set shape aslist asscalar attach_grad grad
       backward handle invoke invoke_into DESTROY AUTOLOAD BEGIN import);

sub _install_op_methods {
    my $names = AI::MXNetTPU::list_op_names();
    my $installed = 0;
    for my $op (@$names) {
        next unless $op =~ /^[A-Za-z_][A-Za-z0-9_]*$/;
        next if $RESERVED{$op} || __PACKAGE__->can($op);
        no strict 'refs';
        *{__PACKAGE__ . '::' . $op} = sub {
            my $self = shift;
            my @ins = ($self);
            # leading NDArray positionals are further op inputs; the
            # remainder is key => value op params
            push @ins, shift
                while @_ && blessed($_[0]) && $_[0]->isa(__PACKAGE__);
            return __PACKAGE__->invoke($op, \@ins, @_);
        };
        ++$installed;
    }
    return $installed;
}

our $NUM_GENERATED_OPS = _install_op_methods();

1;
__END__

=head1 NAME

AI::MXNetTPU::NDArray - idiomatic NDArray API with generated operators

=head1 SYNOPSIS

  use AI::MXNetTPU::NDArray;

  my $x = AI::MXNetTPU::NDArray->new([2, 3], [1 .. 6]);
  my $w = AI::MXNetTPU::NDArray->uniform([4, 3], -0.1, 0.1);
  $w->attach_grad;

  AI::MXNetTPU::autograd_recording(1);
  my $y = $x->FullyConnected($w, num_hidden => 4, no_bias => 1)
            ->Activation(act_type => 'relu')
            ->sum;
  AI::MXNetTPU::autograd_recording(0);
  $y->backward;
  print $w->grad, "\n";

  # in-place optimizer step
  AI::MXNetTPU::NDArray->invoke_into('sgd_update', [$w, $w->grad], [$w],
                                     lr => 0.1, wd => 0);

=cut
