package AI::MXNetTPU::AutoGrad;

# Autograd scoping for the Perl frontend: record { ... } runs the block
# with the C API tape recording in training mode and restores the
# previous state even if the block dies (ref: the reference Perl
# frontend's AI::MXNet::AutoGrad record/pause scopes over
# MXAutogradSetIsRecording/MXAutogradSetIsTraining).

use strict;
use warnings;
use Exporter 'import';
use AI::MXNetTPU ();

our @EXPORT_OK = qw(record pause);

sub _scoped {
    my ($rec, $train, $code) = @_;
    my $pr = AI::MXNetTPU::autograd_recording($rec);
    my $pt = AI::MXNetTPU::autograd_training($train);
    my @out = eval { $code->() };
    my $err = $@;
    AI::MXNetTPU::autograd_recording($pr);
    AI::MXNetTPU::autograd_training($pt);
    die $err if $err;
    return wantarray ? @out : $out[0];
}

sub record (&) { _scoped(1, 1, $_[0]) }
sub pause  (&) { _scoped(0, 0, $_[0]) }

1;
