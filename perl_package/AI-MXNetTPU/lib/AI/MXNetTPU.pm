package AI::MXNetTPU;

# Minimal Perl frontend over the mxnet_tpu flat C API (ref: the
# reference's perl-package/AI-MXNet over libmxnet's identical ABI).
# Proves the C surface hosts a non-C++ language binding: NDArray
# lifecycle, imperative operator invocation, the predict API, and a
# C-callback custom operator (MXCustomOpRegister).

use strict;
use warnings;

our $VERSION = '0.01';

require XSLoader;
XSLoader::load('AI::MXNetTPU', $VERSION);

1;
__END__

=head1 NAME

AI::MXNetTPU - minimal Perl binding over the mxnet_tpu C API

=head1 SYNOPSIS

  use AI::MXNetTPU;
  my $h = AI::MXNetTPU::nd_create([2, 2]);
  AI::MXNetTPU::nd_set($h, [1, 2, 3, 4]);
  my $out = AI::MXNetTPU::invoke("broadcast_mul", [$h, $h], [], [])->[0];
  my $vals = AI::MXNetTPU::nd_values($out);   # [1, 4, 9, 16]

=cut
