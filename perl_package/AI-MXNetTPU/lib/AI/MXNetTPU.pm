package AI::MXNetTPU;

# Perl frontend over the mxnet_tpu flat C API (ref: the reference's
# perl-package/AI-MXNet over libmxnet's identical ABI). This module is
# the low-level XS surface (NDArray lifecycle, imperative invoke incl.
# preallocated outputs, autograd tape control, op enumeration, the
# predict API, and a C-callback custom operator); the idiomatic API
# lives in AI::MXNetTPU::NDArray (operator methods GENERATED from
# MXSymbolListAtomicSymbolCreators, overloaded arithmetic, autograd)
# and AI::MXNetTPU::AutoGrad (record/pause scopes) — deep enough to
# train a net end to end from Perl (t/train_mnist.pl).

use strict;
use warnings;

our $VERSION = '0.01';

require XSLoader;
XSLoader::load('AI::MXNetTPU', $VERSION);

1;
__END__

=head1 NAME

AI::MXNetTPU - minimal Perl binding over the mxnet_tpu C API

=head1 SYNOPSIS

  use AI::MXNetTPU;
  my $h = AI::MXNetTPU::nd_create([2, 2]);
  AI::MXNetTPU::nd_set($h, [1, 2, 3, 4]);
  my $out = AI::MXNetTPU::invoke("broadcast_mul", [$h, $h], [], [])->[0];
  my $vals = AI::MXNetTPU::nd_values($out);   # [1, 4, 9, 16]

=cut
