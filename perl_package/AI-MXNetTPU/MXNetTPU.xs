/* AI::MXNetTPU — minimal Perl XS binding over the flat C API
 * (ref: perl-package/AI-MXNet — the reference ships a full Perl frontend
 * over the same libmxnet C ABI; this module proves the same portability
 * claim for libmxtpu_capi/libmxtpu_predict: NDArray lifecycle,
 * imperative invoke, the predict API, and a C-callback custom op
 * registered through MXCustomOpRegister).
 *
 * Everything below talks ONLY to the flat C API — no Python, no
 * mxnet_tpu internals.
 */
#define PERL_NO_GET_CONTEXT
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

#include <dlfcn.h>
#include <stdlib.h>
#include <string.h>

typedef unsigned int mx_uint;
typedef void *NDArrayHandle;
typedef void *PredictorHandle;

struct MXCallbackList {
  int num_callbacks;
  int (**callbacks)(void);
  void **contexts;
};

/* c_api surface used (signatures: include/mxnet/c_api.h contract) */
extern const char *MXGetLastError(void);
extern int MXNDArrayCreateEx(const mx_uint *, mx_uint, int, int, int, int,
                             NDArrayHandle *);
extern int MXNDArrayFree(NDArrayHandle);
extern int MXNDArraySyncCopyFromCPU(NDArrayHandle, const void *, size_t);
extern int MXNDArraySyncCopyToCPU(NDArrayHandle, void *, size_t);
extern int MXNDArrayGetShape(NDArrayHandle, mx_uint *, const mx_uint **);
extern int MXImperativeInvoke(const char *, int, NDArrayHandle *, int *,
                              NDArrayHandle **, int, const char **,
                              const char **);
extern int MXCustomOpRegister(const char *, int (*)(const char *, int,
                                                    const char **,
                                                    const char **,
                                                    struct MXCallbackList *));
/* op enumeration — the codegen source for the idiomatic NDArray API
 * (ref: the reference Perl frontend generates its method table from
 * MXSymbolListAtomicSymbolCreators at load time) */
extern int MXSymbolListAtomicSymbolCreators(mx_uint *, void ***);
extern int MXSymbolGetAtomicSymbolName(void *, const char **);
/* autograd */
extern int MXAutogradSetIsRecording(int, int *);
extern int MXAutogradSetIsTraining(int, int *);
extern int MXAutogradMarkVariables(mx_uint, NDArrayHandle *);
extern int MXAutogradBackward(mx_uint, NDArrayHandle *, NDArrayHandle *,
                              int);
extern int MXNDArrayGetGrad(NDArrayHandle, NDArrayHandle *);
/* c_predict surface */
extern int MXPredCreate(const char *, const void *, int, int, int, mx_uint,
                        const char **, const mx_uint *, const mx_uint *,
                        PredictorHandle *);
extern int MXPredSetInput(PredictorHandle, const char *, const float *,
                          mx_uint);
extern int MXPredForward(PredictorHandle);
extern int MXPredGetOutputShape(PredictorHandle, mx_uint, mx_uint **,
                                mx_uint *);
extern int MXPredGetOutput(PredictorHandle, mx_uint, float *, mx_uint);
extern int MXPredFree(PredictorHandle);

/* ---- helpers ---------------------------------------------------------- */

static void croak_on(pTHX_ int rc, const char *what) {
  if (rc != 0) croak("%s failed: %s", what, MXGetLastError());
}

/* copy an AV of IV handles into a malloc'd array (caller frees); the
 * terminating extra slot keeps zero-length allocations valid. Returns
 * NULL on OOM (no croak: call sites holding other allocations must be
 * able to free them first). */
static NDArrayHandle *av_to_handles(pTHX_ AV *av) {
  size_t n = av_count(av), i;
  NDArrayHandle *h =
      (NDArrayHandle *)malloc((n + 1) * sizeof(NDArrayHandle));
  if (h == NULL) return NULL;
  for (i = 0; i < n; ++i) {
    SV **e = av_fetch(av, i, 0);
    h[i] = e ? INT2PTR(NDArrayHandle, SvIV(*e)) : NULL;
  }
  return h;
}

/* single-allocation sites only: croaks on OOM (nothing else to free) */
static size_t av_to_floats(pTHX_ AV *av, float **out) {
  size_t n = av_count(av);
  float *buf = (float *)malloc((n + 1) * sizeof(float));
  size_t i;
  if (buf == NULL) croak("av_to_floats: out of memory (%lu floats)",
                         (unsigned long)n);
  for (i = 0; i < n; ++i) {
    SV **e = av_fetch(av, i, 0);
    buf[i] = e ? (float)SvNV(*e) : 0.0f;
  }
  *out = buf;
  return n;
}

/* ---- demo custom op: perl_sqr (x -> x*x, dx = 2*x*gy) ----------------- */
/* the callbacks do their math through the SAME flat C API, like any
 * frontend-supplied custom op (ref custom.cc tag protocol:
 * fwd ptrs = in(0)+out(1)+aux(4); bwd = ograd(3)+in(0)+out(1)+igrad(2)) */

static float *read_handle(void *h, size_t *out_n) {
  mx_uint ndim = 0;
  const mx_uint *shape = NULL;
  size_t n = 1, i;
  float *buf;
  if (MXNDArrayGetShape(h, &ndim, &shape) != 0) return NULL;
  for (i = 0; i < ndim; ++i) n *= shape[i];
  buf = (float *)malloc(n * sizeof(float));
  if (buf == NULL) return NULL;
  if (MXNDArraySyncCopyToCPU(h, buf, n) != 0) { free(buf); return NULL; }
  *out_n = n;
  return buf;
}

static int sqr_forward(int size, void **ptrs, int *tags, const int *reqs,
                       int is_train, void *state) {
  void *in = NULL, *out = NULL;
  size_t n = 0, i;
  float *x;
  int k;
  (void)reqs; (void)is_train; (void)state;
  for (k = 0; k < size; ++k) {
    if (tags[k] == 0 && in == NULL) in = ptrs[k];
    if (tags[k] == 1 && out == NULL) out = ptrs[k];
  }
  x = read_handle(in, &n);
  if (x == NULL) return 0;
  for (i = 0; i < n; ++i) x[i] *= x[i];
  k = MXNDArraySyncCopyFromCPU(out, x, n) == 0;
  free(x);
  return k;
}

static int sqr_backward(int size, void **ptrs, int *tags, const int *reqs,
                        int is_train, void *state) {
  void *og = NULL, *in = NULL, *ig = NULL;
  size_t n = 0, m = 0, i;
  float *gy, *x;
  int k;
  (void)reqs; (void)is_train; (void)state;
  for (k = 0; k < size; ++k) {
    if (tags[k] == 3 && og == NULL) og = ptrs[k];
    if (tags[k] == 0 && in == NULL) in = ptrs[k];
    if (tags[k] == 2 && ig == NULL) ig = ptrs[k];
  }
  gy = read_handle(og, &n);
  x = read_handle(in, &m);
  if (gy == NULL || x == NULL || n != m) { free(gy); free(x); return 0; }
  for (i = 0; i < n; ++i) x[i] = 2.0f * x[i] * gy[i];
  k = MXNDArraySyncCopyFromCPU(ig, x, n) == 0;
  free(gy);
  free(x);
  return k;
}

static int sqr_del(void *state) { (void)state; return 1; }

static int sqr_list_args(char ***out, void *state) {
  static char *names[] = {(char *)"data", NULL};
  (void)state;
  *out = names;
  return 1;
}

static int sqr_list_outs(char ***out, void *state) {
  static char *names[] = {(char *)"output", NULL};
  (void)state;
  *out = names;
  return 1;
}

static int sqr_list_aux(char ***out, void *state) {
  static char *names[] = {NULL};
  (void)state;
  *out = names;
  return 1;
}

static int sqr_infer_shape(int num_tensor, int *ndims, unsigned **shapes,
                           void *state) {
  (void)num_tensor; (void)state;
  ndims[1] = ndims[0];
  shapes[1] = shapes[0];
  return 1;
}

static int sqr_create_operator(const char *ctx, int num_inputs,
                               unsigned **shapes, const int *ndims,
                               const int *dtypes,
                               struct MXCallbackList *ret, void *state) {
  static int (*cbs[3])(void);
  static void *ctxs[3] = {NULL, NULL, NULL};
  (void)ctx; (void)num_inputs; (void)shapes; (void)ndims; (void)dtypes;
  (void)state;
  cbs[0] = (int (*)(void))sqr_del;
  cbs[1] = (int (*)(void))sqr_forward;
  cbs[2] = (int (*)(void))sqr_backward;
  ret->num_callbacks = 3;
  ret->callbacks = cbs;
  ret->contexts = ctxs;
  return 1;
}

static int sqr_creator(const char *op_type, int num_kwargs,
                       const char **keys, const char **vals,
                       struct MXCallbackList *ret) {
  static int (*cbs[7])(void);
  static void *ctxs[7];
  (void)op_type; (void)num_kwargs; (void)keys; (void)vals;
  memset(ctxs, 0, sizeof(ctxs));
  cbs[0] = (int (*)(void))sqr_del;           /* kCustomOpPropDelete */
  cbs[1] = (int (*)(void))sqr_list_args;     /* ListArguments */
  cbs[2] = (int (*)(void))sqr_list_outs;     /* ListOutputs */
  cbs[3] = (int (*)(void))sqr_list_aux;      /* ListAuxiliaryStates */
  cbs[4] = (int (*)(void))sqr_infer_shape;   /* InferShape */
  cbs[5] = NULL;                             /* DeclareBackwardDependency */
  cbs[6] = (int (*)(void))sqr_create_operator;
  ret->num_callbacks = 7;
  ret->callbacks = cbs;
  ret->contexts = ctxs;
  return 1;
}

MODULE = AI::MXNetTPU  PACKAGE = AI::MXNetTPU

PROTOTYPES: DISABLE

BOOT:
{
  /* perl dlopens this module RTLD_LOCAL, which would leave the embedded
   * CPython's symbols invisible to numpy/jax C extensions (they expect
   * libpython symbols to be global, manylinux-style). Re-promote it. */
  void *h = dlopen("libpython3.12.so.1.0", RTLD_NOW | RTLD_GLOBAL);
  if (h == NULL) dlopen("libpython3.12.so", RTLD_NOW | RTLD_GLOBAL);
}

const char *
last_error()
  CODE:
    RETVAL = MXGetLastError();
  OUTPUT:
    RETVAL

IV
nd_create(shape_av)
    AV *shape_av
  CODE:
  {
    size_t ndim = av_count(shape_av), i;
    mx_uint shape[8];
    NDArrayHandle h = NULL;
    if (ndim > 8) croak("nd_create: at most 8 dimensions supported");
    for (i = 0; i < ndim && i < 8; ++i) {
      SV **e = av_fetch(shape_av, i, 0);
      shape[i] = e ? (mx_uint)SvUV(*e) : 0;
    }
    croak_on(aTHX_ MXNDArrayCreateEx(shape, (mx_uint)ndim, 1, 0, 0, 0, &h),
             "MXNDArrayCreateEx");
    RETVAL = PTR2IV(h);
  }
  OUTPUT:
    RETVAL

void
nd_free(h)
    IV h
  CODE:
    MXNDArrayFree(INT2PTR(NDArrayHandle, h));

void
nd_set(h, values_av)
    IV h
    AV *values_av
  CODE:
  {
    float *buf;
    size_t n = av_to_floats(aTHX_ values_av, &buf);
    int rc = MXNDArraySyncCopyFromCPU(INT2PTR(NDArrayHandle, h), buf, n);
    free(buf);
    croak_on(aTHX_ rc, "MXNDArraySyncCopyFromCPU");
  }

AV *
nd_shape(h)
    IV h
  CODE:
  {
    mx_uint ndim = 0;
    const mx_uint *shape = NULL;
    size_t i;
    croak_on(aTHX_ MXNDArrayGetShape(INT2PTR(NDArrayHandle, h), &ndim,
                                     &shape),
             "MXNDArrayGetShape");
    RETVAL = newAV();
    sv_2mortal((SV *)RETVAL);
    for (i = 0; i < ndim; ++i) av_push(RETVAL, newSVuv(shape[i]));
  }
  OUTPUT:
    RETVAL

AV *
nd_values(h)
    IV h
  CODE:
  {
    mx_uint ndim = 0;
    const mx_uint *shape = NULL;
    size_t n = 1, i;
    float *buf;
    croak_on(aTHX_ MXNDArrayGetShape(INT2PTR(NDArrayHandle, h), &ndim,
                                     &shape),
             "MXNDArrayGetShape");
    for (i = 0; i < ndim; ++i) n *= shape[i];
    buf = (float *)malloc(n * sizeof(float));
    if (buf == NULL) croak("nd_values: out of memory");
    if (MXNDArraySyncCopyToCPU(INT2PTR(NDArrayHandle, h), buf, n) != 0) {
      free(buf);
      croak("MXNDArraySyncCopyToCPU failed: %s", MXGetLastError());
    }
    RETVAL = newAV();
    sv_2mortal((SV *)RETVAL);
    for (i = 0; i < n; ++i) av_push(RETVAL, newSVnv(buf[i]));
    free(buf);
  }
  OUTPUT:
    RETVAL

AV *
invoke(op, in_av, key_av, val_av)
    const char *op
    AV *in_av
    AV *key_av
    AV *val_av
  CODE:
  {
    size_t n_in = av_count(in_av), n_p = av_count(key_av), i;
    NDArrayHandle *ins = av_to_handles(aTHX_ in_av);
    const char **keys = (const char **)malloc((n_p + 1) * sizeof(char *));
    const char **vals = (const char **)malloc((n_p + 1) * sizeof(char *));
    NDArrayHandle *outs = NULL;
    int n_out = 0, rc;
    if (ins == NULL || keys == NULL || vals == NULL) {
      free(ins); free(keys); free(vals);
      croak("invoke: out of memory");
    }
    for (i = 0; i < n_p; ++i) {
      SV **k = av_fetch(key_av, i, 0);
      SV **v = av_fetch(val_av, i, 0);
      keys[i] = k ? SvPV_nolen(*k) : "";
      vals[i] = v ? SvPV_nolen(*v) : "";
    }
    rc = MXImperativeInvoke(op, (int)n_in, ins, &n_out, &outs,
                            (int)n_p, keys, vals);
    free(ins);
    free(keys);
    free(vals);
    croak_on(aTHX_ rc, "MXImperativeInvoke");
    RETVAL = newAV();
    sv_2mortal((SV *)RETVAL);
    for (i = 0; i < (size_t)n_out; ++i)
      av_push(RETVAL, newSViv(PTR2IV(outs[i])));
  }
  OUTPUT:
    RETVAL

void
register_sqr_op()
  CODE:
    croak_on(aTHX_ MXCustomOpRegister("perl_sqr", sqr_creator),
             "MXCustomOpRegister");

AV *
list_op_names()
  CODE:
  {
    mx_uint n = 0, i;
    void **creators = NULL;
    croak_on(aTHX_ MXSymbolListAtomicSymbolCreators(&n, &creators),
             "MXSymbolListAtomicSymbolCreators");
    RETVAL = newAV();
    sv_2mortal((SV *)RETVAL);
    for (i = 0; i < n; ++i) {
      const char *name = NULL;
      if (MXSymbolGetAtomicSymbolName(creators[i], &name) == 0 && name)
        av_push(RETVAL, newSVpv(name, 0));
    }
  }
  OUTPUT:
    RETVAL

AV *
invoke_into(op, in_av, key_av, val_av, out_av)
    const char *op
    AV *in_av
    AV *key_av
    AV *val_av
    AV *out_av
  CODE:
  {
    size_t n_in = av_count(in_av), n_p = av_count(key_av);
    size_t n_out_req = av_count(out_av), i;
    NDArrayHandle *ins, *outs;
    const char **keys, **vals;
    int n_out = (int)n_out_req, rc;
    if (n_out_req == 0)
      croak("invoke_into: out_av is empty — the preallocated-output "
            "contract requires n_out > 0");
    ins = av_to_handles(aTHX_ in_av);
    outs = av_to_handles(aTHX_ out_av);
    keys = (const char **)malloc((n_p + 1) * sizeof(char *));
    vals = (const char **)malloc((n_p + 1) * sizeof(char *));
    if (ins == NULL || outs == NULL || keys == NULL || vals == NULL) {
      free(ins); free(outs); free(keys); free(vals);
      croak("invoke_into: out of memory");
    }
    for (i = 0; i < n_p; ++i) {
      SV **k = av_fetch(key_av, i, 0);
      SV **v = av_fetch(val_av, i, 0);
      keys[i] = k ? SvPV_nolen(*k) : "";
      vals[i] = v ? SvPV_nolen(*v) : "";
    }
    rc = MXImperativeInvoke(op, (int)n_in, ins, &n_out, &outs,
                            (int)n_p, keys, vals);
    free(ins);
    free(keys);
    free(vals);
    if (rc != 0) free(outs);
    croak_on(aTHX_ rc, "MXImperativeInvoke");
    RETVAL = newAV();
    sv_2mortal((SV *)RETVAL);
    for (i = 0; i < (size_t)n_out; ++i)
      av_push(RETVAL, newSViv(PTR2IV(outs[i])));
    free(outs);
  }
  OUTPUT:
    RETVAL

IV
autograd_recording(flag)
    IV flag
  CODE:
  {
    int prev = 0;
    croak_on(aTHX_ MXAutogradSetIsRecording((int)flag, &prev),
             "MXAutogradSetIsRecording");
    RETVAL = prev;
  }
  OUTPUT:
    RETVAL

IV
autograd_training(flag)
    IV flag
  CODE:
  {
    int prev = 0;
    croak_on(aTHX_ MXAutogradSetIsTraining((int)flag, &prev),
             "MXAutogradSetIsTraining");
    RETVAL = prev;
  }
  OUTPUT:
    RETVAL

void
mark_variables(av)
    AV *av
  CODE:
  {
    size_t n = av_count(av);
    NDArrayHandle *vars = av_to_handles(aTHX_ av);
    int rc;
    if (vars == NULL) croak("mark_variables: out of memory");
    rc = MXAutogradMarkVariables((mx_uint)n, vars);
    free(vars);
    croak_on(aTHX_ rc, "MXAutogradMarkVariables");
  }

void
backward(h)
    IV h
  CODE:
  {
    NDArrayHandle out = INT2PTR(NDArrayHandle, h);
    croak_on(aTHX_ MXAutogradBackward(1, &out, NULL, 0),
             "MXAutogradBackward");
  }

IV
nd_grad(h)
    IV h
  CODE:
  {
    NDArrayHandle g = NULL;
    croak_on(aTHX_ MXNDArrayGetGrad(INT2PTR(NDArrayHandle, h), &g),
             "MXNDArrayGetGrad");
    RETVAL = PTR2IV(g);
  }
  OUTPUT:
    RETVAL

IV
pred_create(sym_json, params_sv, input_name, shape_av)
    const char *sym_json
    SV *params_sv
    const char *input_name
    AV *shape_av
  CODE:
  {
    STRLEN plen;
    const char *pbytes = SvPV(params_sv, plen);
    size_t ndim = av_count(shape_av), i;
    mx_uint sdata[8];
    mx_uint indptr[2];
    const char *keys[1];
    PredictorHandle h = NULL;
    if (ndim > 8) croak("pred_create: at most 8 dimensions supported");
    for (i = 0; i < ndim && i < 8; ++i) {
      SV **e = av_fetch(shape_av, i, 0);
      sdata[i] = e ? (mx_uint)SvUV(*e) : 0;
    }
    indptr[0] = 0;
    indptr[1] = (mx_uint)ndim;
    keys[0] = input_name;
    croak_on(aTHX_ MXPredCreate(sym_json, pbytes, (int)plen, 1, 0, 1, keys,
                                indptr, sdata, &h),
             "MXPredCreate");
    RETVAL = PTR2IV(h);
  }
  OUTPUT:
    RETVAL

void
pred_set_input(h, name, values_av)
    IV h
    const char *name
    AV *values_av
  CODE:
  {
    float *buf;
    size_t n = av_to_floats(aTHX_ values_av, &buf);
    int rc = MXPredSetInput(INT2PTR(PredictorHandle, h), name, buf,
                            (mx_uint)n);
    free(buf);
    croak_on(aTHX_ rc, "MXPredSetInput");
  }

void
pred_forward(h)
    IV h
  CODE:
    croak_on(aTHX_ MXPredForward(INT2PTR(PredictorHandle, h)),
             "MXPredForward");

AV *
pred_output(h, index)
    IV h
    UV index
  CODE:
  {
    mx_uint *shape = NULL;
    mx_uint ndim = 0;
    size_t n = 1, i;
    float *buf;
    croak_on(aTHX_ MXPredGetOutputShape(INT2PTR(PredictorHandle, h),
                                        (mx_uint)index, &shape, &ndim),
             "MXPredGetOutputShape");
    for (i = 0; i < ndim; ++i) n *= shape[i];
    buf = (float *)malloc(n * sizeof(float));
    if (buf == NULL) croak("pred_output: out of memory");
    if (MXPredGetOutput(INT2PTR(PredictorHandle, h), (mx_uint)index, buf,
                        (mx_uint)n) != 0) {
      free(buf);
      croak("MXPredGetOutput failed: %s", MXGetLastError());
    }
    RETVAL = newAV();
    sv_2mortal((SV *)RETVAL);
    for (i = 0; i < n; ++i) av_push(RETVAL, newSVnv(buf[i]));
    free(buf);
  }
  OUTPUT:
    RETVAL

void
pred_free(h)
    IV h
  CODE:
    MXPredFree(INT2PTR(PredictorHandle, h));
