"""CNN sentence classification (ref: example/cnn_text_classification/
text_cnn.py — Kim 2014's multi-width conv + max-over-time architecture,
there built as a symbolic graph with explicit Convolution/Pooling nodes).

Rebuilt TPU-first: one Gluon HybridBlock whose parallel filter branches
(widths 3/4/5) run as Conv1D over the embedded token sequence and reduce
with a global max — the whole model compiles to a single XLA program, so
the branch convs fuse and batch onto the MXU instead of dispatching as
separate graph nodes. NWC layout (channels-last is TPU-native).

Data: the reference trains on the Movie Review polarity set (rt-polarity
files downloaded in data_helpers.py — zero-egress here), so sentences
are synthesized over a vocabulary in which some "words" carry sentiment:
a sentence is positive iff it contains more positive- than negative-class
tokens, forcing the convs to learn keyword detectors and the max-pool to
aggregate them, which is exactly the mechanism Kim's architecture tests.

Run: python examples/cnn_text_classification/text_cnn.py --iters 120
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

VOCAB = 500
SEQ_LEN = 32
POS_WORDS = np.arange(10, 40)     # "good", "great", ...
NEG_WORDS = np.arange(40, 70)     # "bad", "awful", ...


def make_batch(rs, batch):
    """Sentences of neutral tokens with planted sentiment keywords."""
    x = rs.randint(70, VOCAB, (batch, SEQ_LEN))
    y = np.zeros(batch, np.float32)
    for b in range(batch):
        n_pos = rs.randint(0, 4)
        n_neg = rs.randint(0, 4)
        if n_pos == n_neg:          # break ties decisively
            n_pos += 1
        pos = rs.choice(SEQ_LEN, n_pos + n_neg, replace=False)
        x[b, pos[:n_pos]] = rs.choice(POS_WORDS, n_pos)
        x[b, pos[n_pos:]] = rs.choice(NEG_WORDS, n_neg)
        y[b] = 1.0 if n_pos > n_neg else 0.0
    return x.astype(np.float32), y


def build_net(embed_dim=32, num_filters=32, widths=(3, 4, 5),
              dropout=0.5):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib.nn import HybridConcurrent

    net = nn.HybridSequential(prefix="")
    net.add(nn.Embedding(VOCAB, embed_dim))
    branches = HybridConcurrent(axis=-1, prefix="branches_")
    for w in widths:
        b = nn.HybridSequential(prefix=f"w{w}_")
        # NWC: (batch, seq, embed) straight out of the Embedding —
        # no transpose between embedding and conv
        b.add(nn.Conv1D(num_filters, w, layout="NWC",
                        in_channels=embed_dim, activation="relu"))
        b.add(nn.GlobalMaxPool1D(layout="NWC"))
        b.add(nn.Flatten())
        branches.add(b)
    net.add(branches)
    net.add(nn.Dropout(dropout))
    net.add(nn.Dense(2))
    return net


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=120)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import loss as gloss

    np.random.seed(0)
    mx.random.seed(0)
    rs = np.random.RandomState(7)

    net = build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    lossfn = gloss.SoftmaxCrossEntropyLoss()

    for it in range(args.iters):
        x, y = make_batch(rs, args.batch_size)
        with autograd.record():
            out = net(mx.nd.array(x))
            L = lossfn(out, mx.nd.array(y))
        L.backward()
        trainer.step(args.batch_size)
        if it % 20 == 0 or it == args.iters - 1:
            print(f"iter {it} loss {float(L.mean().asnumpy()):.4f}",
                  flush=True)

    # held-out accuracy (inference mode: dropout off outside record())
    xte, yte = make_batch(np.random.RandomState(999), 512)
    pred = net(mx.nd.array(xte)).asnumpy().argmax(axis=1)
    acc = float((pred == yte).mean())
    print(f"test accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
