"""Neural style transfer (ref: example/neural-style/): optimize the INPUT
image so its deep features match a content image while its feature Gram
matrices match a style image. Exercises gradients with respect to data
(attach_grad on a non-parameter array) through a conv feature extractor.
Synthetic content/style images keep it zero-egress.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def synthetic_images(size=64, seed=0):
    rs = np.random.RandomState(seed)
    # content: centered bright square; style: diagonal stripes
    content = rs.rand(1, 3, size, size).astype(np.float32) * 0.1
    content[:, :, size // 4:3 * size // 4, size // 4:3 * size // 4] = 0.9
    idx = np.arange(size)
    stripes = (((idx[:, None] + idx[None, :]) // 8) % 2).astype(np.float32)
    style = np.broadcast_to(stripes, (1, 3, size, size)).copy()
    return content, style


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--lr", type=float, default=20.0)
    ap.add_argument("--style-weight", type=float, default=1.0)
    ap.add_argument("--content-weight", type=float, default=1.0)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, nd
    from mxnet_tpu.gluon import nn

    mx.random.seed(0)

    # fixed random feature extractor (stand-in for the reference's VGG)
    feat = nn.Sequential()
    feat.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
             nn.Conv2D(16, 3, strides=2, padding=1, activation="relu"),
             nn.Conv2D(16, 3, padding=1, activation="relu"))
    feat.initialize(mx.init.Xavier())

    def gram(f):
        n, c = f.shape[0], f.shape[1]
        flat = f.reshape((n, c, -1))
        return nd.batch_dot(flat, flat.transpose(axes=(0, 2, 1))) \
            / float(flat.shape[2])

    content_np, style_np = synthetic_images()
    with autograd.pause():
        content_feat = feat(nd.array(content_np))
        style_gram = gram(feat(nd.array(style_np)))

    img = nd.array(np.random.RandomState(1)
                   .rand(*content_np.shape).astype(np.float32))
    img.attach_grad()

    losses = []
    for it in range(args.iters):
        with autograd.record():
            f = feat(img)
            content_loss = ((f - content_feat) ** 2).mean()
            style_loss = ((gram(f) - style_gram) ** 2).mean()
            loss = args.content_weight * content_loss \
                + args.style_weight * style_loss
        loss.backward()
        # plain gradient descent on the image itself
        img = nd.array(img.asnumpy() - args.lr * img.grad.asnumpy())
        img.attach_grad()
        losses.append(float(loss.asnumpy()))
        if it % 10 == 0 or it == args.iters - 1:
            print(f"iter {it}: loss {losses[-1]:.5f}")
    assert losses[-1] < losses[0], "style optimization failed to descend"
    print("done")


if __name__ == "__main__":
    main()
