"""Tiny SSD (ref: example/ssd/): single-scale anchor head over a small
conv backbone, trained with MultiBoxTarget targets and decoded with
MultiBoxDetection. Synthetic colored-square dataset (zero-egress)."""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def synthetic_batch(rs, batch, size=32):
    """Images each containing one bright square; label its box."""
    data = rs.rand(batch, 3, size, size).astype(np.float32) * 0.1
    labels = np.zeros((batch, 1, 5), np.float32)
    for i in range(batch):
        w = rs.randint(8, 16)
        x0 = rs.randint(0, size - w)
        y0 = rs.randint(0, size - w)
        data[i, :, y0:y0 + w, x0:x0 + w] = 1.0
        labels[i, 0] = [0, x0 / size, y0 / size, (x0 + w) / size,
                        (y0 + w) / size]
    return data, labels


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch-size", type=int, default=8)
    ap.add_argument("--iters", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn, loss as gloss

    mx.random.seed(0)
    num_classes = 1  # square vs background
    sizes, ratios = (0.3, 0.45), (1.0, 2.0)
    num_anchors = len(sizes) + len(ratios) - 1

    backbone = nn.HybridSequential()
    backbone.add(nn.Conv2D(16, 3, 2, 1, activation="relu"),
                 nn.Conv2D(32, 3, 2, 1, activation="relu"))  # 32 -> 8
    cls_head = nn.Conv2D(num_anchors * (num_classes + 1), 3, padding=1)
    box_head = nn.Conv2D(num_anchors * 4, 3, padding=1)
    for blk in (backbone, cls_head, box_head):
        blk.initialize(mx.init.Xavier())

    cls_loss = gloss.SoftmaxCrossEntropyLoss()
    l1_loss = gloss.L1Loss()
    params = {}
    for blk in (backbone, cls_head, box_head):
        params.update(blk.collect_params())
    trainer = gluon.Trainer(params, "sgd", {"learning_rate": args.lr})

    rs = np.random.RandomState(0)
    losses = []
    for it in range(args.iters):
        data_np, labels_np = synthetic_batch(rs, args.batch_size)
        x = nd.array(data_np)
        labels = nd.array(labels_np)
        with autograd.record():
            feat = backbone(x)
            anchors = nd.contrib.MultiBoxPrior(feat, sizes=sizes,
                                               ratios=ratios)
            B = args.batch_size
            N = anchors.shape[1] if anchors.ndim == 3 else \
                anchors.size // 4
            cp = cls_head(feat).reshape((B, num_anchors *
                                         (num_classes + 1), -1))
            cp = cp.reshape((B, num_classes + 1, -1))
            bp = box_head(feat).reshape((B, -1))
            with autograd.pause():
                bt, bm, ct = nd.contrib.MultiBoxTarget(
                    anchors.reshape((1, -1, 4)), labels, cp)
            l_cls = cls_loss(nd.transpose(cp, axes=(0, 2, 1)), ct)
            l_box = l1_loss(bp * bm, bt)
            loss = (l_cls.mean() + l_box.mean())
        loss.backward()
        trainer.step(B)
        losses.append(float(loss.asscalar()))
        print(f"iter {it}: loss={losses[-1]:.4f}", flush=True)

    assert losses[-1] < losses[0], losses
    # inference: decode + NMS
    feat = backbone(x)
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=sizes, ratios=ratios)
    cp = cls_head(feat).reshape((args.batch_size, num_classes + 1, -1))
    cp = nd.softmax(cp, axis=1)
    bp = box_head(feat).reshape((args.batch_size, -1))
    det = nd.contrib.MultiBoxDetection(cp, bp,
                                       anchors.reshape((1, -1, 4)))
    print("detections:", det.shape, "kept:",
          int((det.asnumpy()[:, :, 0] >= 0).sum()), flush=True)
    print("ssd training loop done")


if __name__ == "__main__":
    main()
