"""BASELINE config #5: model-parallel matrix factorization
(ref: example/model-parallel/matrix_factorization/{model.py,train.py} —
group2ctx splits the embedding halves across devices).

TPU-native: instead of ctx_group device assignment, the two embedding
tables are SHARDED across the mesh with a ShardingPlan (user embedding
split over axis 'mp' rows, item embedding likewise); the train step is one
pjit program and GSPMD places the per-shard gathers + collectives.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--num-users", type=int, default=5000)
    ap.add_argument("--num-items", type=int, default=2000)
    ap.add_argument("--factors", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=512)
    ap.add_argument("--steps", type=int, default=100)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel import make_mesh

    n_dev = len(jax.devices())
    mp = 2 if n_dev % 2 == 0 else 1
    dp = n_dev // mp
    mesh = make_mesh({"dp": dp, "mp": mp})
    print(f"mesh: dp={dp} mp={mp}")

    rs = np.random.RandomState(0)
    u_true = rs.randn(args.num_users, 8).astype(np.float32)
    i_true = rs.randn(args.num_items, 8).astype(np.float32)

    users = rs.randint(0, args.num_users, 200000).astype(np.int32)
    items = rs.randint(0, args.num_items, 200000).astype(np.int32)
    ratings = np.sum(u_true[users] * i_true[items], axis=1).astype(np.float32)

    key = jax.random.PRNGKey(0)
    k1, k2 = jax.random.split(key)
    params = {
        "user_embed": jax.device_put(
            jax.random.normal(k1, (args.num_users, args.factors)) * 0.01,
            NamedSharding(mesh, P("mp", None))),   # rows sharded: model parallel
        "item_embed": jax.device_put(
            jax.random.normal(k2, (args.num_items, args.factors)) * 0.01,
            NamedSharding(mesh, P("mp", None))),
    }
    batch_sharding = NamedSharding(mesh, P("dp"))

    def loss_fn(p, u, i, r):
        ue = p["user_embed"][u]
        ie = p["item_embed"][i]
        pred = jnp.sum(ue * ie, axis=1)
        return jnp.mean(jnp.square(pred - r))

    @jax.jit
    def step(p, u, i, r):
        loss, grads = jax.value_and_grad(loss_fn)(p, u, i, r)
        new_p = jax.tree_util.tree_map(
            lambda w, g: w - args.lr * g, p, grads)
        return loss, new_p

    t0 = time.time()
    for s in range(args.steps):
        b0 = (s * args.batch_size) % (len(users) - args.batch_size)
        u = jax.device_put(users[b0:b0 + args.batch_size], batch_sharding)
        i = jax.device_put(items[b0:b0 + args.batch_size], batch_sharding)
        r = jax.device_put(ratings[b0:b0 + args.batch_size], batch_sharding)
        loss, params = step(params, u, i, r)
        if s % 20 == 0:
            print(f"step {s}: mse {float(loss):.4f}")
    print(f"final mse {float(loss):.4f} "
          f"({args.steps * args.batch_size / (time.time() - t0):.0f} "
          "samples/s)")


if __name__ == "__main__":
    main()
