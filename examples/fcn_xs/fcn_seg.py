"""Fully-convolutional semantic segmentation, FCN-8s style (ref:
example/fcn-xs/ — VGG backbone with fcn32s/fcn16s/fcn8s heads whose
Deconvolution layers upsample coarse score maps and fuse skip
connections; rebuilt TPU-first: a compact NHWC conv backbone, NHWC
Conv2DTranspose upsampling (channel-last end to end — no layout
transposes anywhere), per-pixel softmax loss, all in one XLA program).

Data (zero-egress Pascal-VOC stand-in): images contain 1-3 axis-aligned
shapes (squares / circles / crosses) over textured noise; the label map
marks each pixel with its shape class (0 = background). The smoke bar
is mean IoU over the foreground classes — the metric of the task.

Run: python examples/fcn_xs/fcn_seg.py --iters 150
"""
import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np

SIZE = 32
N_CLS = 4   # background + square + disk + cross


def make_batch(rs, n):
    x = rs.rand(n, SIZE, SIZE, 3).astype(np.float32) * 0.4
    y = np.zeros((n, SIZE, SIZE), np.int64)
    yy, xx = np.mgrid[0:SIZE, 0:SIZE]
    for i in range(n):
        for _ in range(rs.randint(1, 4)):
            cls = rs.randint(1, N_CLS)
            r = rs.randint(4, 8)
            cy, cx = rs.randint(r, SIZE - r, 2)
            if cls == 1:       # square
                m = (abs(yy - cy) <= r) & (abs(xx - cx) <= r)
            elif cls == 2:     # disk
                m = (yy - cy) ** 2 + (xx - cx) ** 2 <= r * r
            else:              # cross
                m = ((abs(yy - cy) <= 2) & (abs(xx - cx) <= r)) | \
                    ((abs(xx - cx) <= 2) & (abs(yy - cy) <= r))
            # class-tinted appearance (jittered): the net segments by
            # color family AND shape, like real FCN classes
            base = np.zeros(3)
            base[cls - 1] = 1.0
            color = base * (0.6 + 0.4 * rs.rand()) + rs.rand(3) * 0.15
            x[i][m] = x[i][m] * 0.3 + color
            y[i][m] = cls
    return x, y


def build_net():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    class FCN(nn.HybridBlock):
        """conv x2 -> pool -> conv x2 -> pool -> conv (score) ->
        2x deconv + skip-fuse -> 2x deconv to full resolution: the
        fcn8s pattern (coarse semantics + fine skip detail)."""

        def __init__(self):
            super().__init__()
            args = dict(layout="NHWC", activation="relu", padding=1)
            self.c1a = nn.Conv2D(24, 3, in_channels=3, **args)
            self.c1b = nn.Conv2D(24, 3, in_channels=24, **args)
            self.p1 = nn.MaxPool2D(2, layout="NHWC")        # 32 -> 16
            self.c2a = nn.Conv2D(48, 3, in_channels=24, **args)
            self.c2b = nn.Conv2D(48, 3, in_channels=48, **args)
            self.p2 = nn.MaxPool2D(2, layout="NHWC")        # 16 -> 8
            self.score = nn.Conv2D(N_CLS, 1, layout="NHWC",
                                   in_channels=48)
            self.skip = nn.Conv2D(N_CLS, 1, layout="NHWC",
                                  in_channels=24)
            self.up2 = nn.Conv2DTranspose(N_CLS, 4, strides=2,
                                          padding=1, layout="NHWC",
                                          in_channels=N_CLS)  # 8 -> 16
            self.up4 = nn.Conv2DTranspose(N_CLS, 4, strides=2,
                                          padding=1, layout="NHWC",
                                          in_channels=N_CLS)  # 16 -> 32

        def hybrid_forward(self, F, x):
            h1 = self.p1(self.c1b(self.c1a(x)))      # (B,16,16,24)
            h2 = self.p2(self.c2b(self.c2a(h1)))     # (B,8,8,48)
            s2 = self.up2(self.score(h2))            # (B,16,16,C)
            s2 = s2 + self.skip(h1)                  # fuse skip scores
            return self.up4(s2)                      # (B,32,32,C)

    return FCN()


def mean_iou(pred, y):
    ious = []
    for c in range(1, N_CLS):
        inter = float(((pred == c) & (y == c)).sum())
        union = float(((pred == c) | (y == c)).sum())
        if union > 0:
            ious.append(inter / union)
    return float(np.mean(ious)) if ious else 0.0


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=150)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    rs = np.random.RandomState(0)
    mx.random.seed(0)
    net = build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss(axis=-1)

    for it in range(args.iters):
        x, y = make_batch(rs, args.batch_size)
        with autograd.record():
            logits = net(mx.nd.array(x))             # (B,H,W,C)
            L = ce(logits.reshape((-1, N_CLS)),
                   mx.nd.array(y.reshape(-1).astype(np.float32)))
        L.backward()
        trainer.step(args.batch_size)
        if it % 25 == 0 or it == args.iters - 1:
            print(f"iter {it} loss {float(L.mean().asnumpy()):.4f}",
                  flush=True)

    x, y = make_batch(np.random.RandomState(99), 64)
    pred = net(mx.nd.array(x)).asnumpy().argmax(axis=-1)
    acc = float((pred == y).mean())
    print(f"pixel accuracy {acc:.3f} mean IoU: {mean_iou(pred, y):.3f}")


if __name__ == "__main__":
    main()
