"""BASELINE config #1: LeNet on MNIST via the Module API
(ref: example/image-classification/train_mnist.py).

Uses real MNIST idx files when present under --data-dir, else a synthetic
stand-in (zero-egress environment).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.io import NDArrayIter, MNISTIter
from mxnet_tpu.module import Module


def lenet_symbol():
    """LeNet-5 graph (ref: example/image-classification/symbols/lenet.py)."""
    data = sym.var("data")
    c1 = sym.Convolution(data, sym.var("conv1_weight"), sym.var("conv1_bias"),
                         kernel=(5, 5), num_filter=20, name="conv1")
    p1 = sym.Pooling(sym.Activation(c1, act_type="tanh"), kernel=(2, 2),
                     pool_type="max", stride=(2, 2))
    c2 = sym.Convolution(p1, sym.var("conv2_weight"), sym.var("conv2_bias"),
                         kernel=(5, 5), num_filter=50, name="conv2")
    p2 = sym.Pooling(sym.Activation(c2, act_type="tanh"), kernel=(2, 2),
                     pool_type="max", stride=(2, 2))
    f1 = sym.FullyConnected(sym.flatten(p2), sym.var("fc1_weight"),
                            sym.var("fc1_bias"), num_hidden=500, name="fc1")
    f2 = sym.FullyConnected(sym.Activation(f1, act_type="tanh"),
                            sym.var("fc2_weight"), sym.var("fc2_bias"),
                            num_hidden=10, name="fc2")
    return sym.SoftmaxOutput(f2, sym.var("softmax_label"), name="softmax")


def get_iters(data_dir, batch_size):
    train_img = os.path.join(data_dir, "train-images-idx3-ubyte")
    if os.path.exists(train_img) or os.path.exists(train_img + ".gz"):
        train = MNISTIter(image=train_img,
                          label=os.path.join(data_dir,
                                             "train-labels-idx1-ubyte"),
                          batch_size=batch_size, shuffle=True)
        val = MNISTIter(image=os.path.join(data_dir, "t10k-images-idx3-ubyte"),
                        label=os.path.join(data_dir, "t10k-labels-idx1-ubyte"),
                        batch_size=batch_size, shuffle=False)
        return train, val
    # synthetic stand-in: 10 gaussian digit prototypes
    rs = np.random.RandomState(0)
    protos = rs.rand(10, 1, 28, 28).astype(np.float32)
    y = rs.randint(0, 10, 2048)
    x = protos[y] + 0.1 * rs.randn(2048, 1, 28, 28).astype(np.float32)
    train = NDArrayIter(x[:1792], y[:1792].astype(np.float32),
                        batch_size, shuffle=True)
    val = NDArrayIter(x[1792:], y[1792:].astype(np.float32), batch_size)
    return train, val


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-dir", default=os.path.expanduser(
        "~/.mxnet/datasets/mnist"))
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.1)
    ap.add_argument("--num-epochs", type=int, default=3)
    ap.add_argument("--ctx", default="cpu", choices=["cpu", "tpu", "gpu"])
    args = ap.parse_args()

    import logging
    logging.basicConfig(level=logging.INFO)
    ctx = {"cpu": mx.cpu, "tpu": mx.tpu, "gpu": mx.gpu}[args.ctx]()
    train, val = get_iters(args.data_dir, args.batch_size)
    mod = Module(lenet_symbol(), context=ctx)
    mod.fit(train, eval_data=val, num_epoch=args.num_epochs,
            optimizer="sgd", initializer=mx.init.Xavier(),
            optimizer_params={"learning_rate": args.lr, "momentum": 0.9},
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 20))
    print("final accuracy:", dict(mod.score(val, "acc")))


if __name__ == "__main__":
    main()
