"""BASELINE config #2: ImageNet-class training
(ref: example/image-classification/train_imagenet.py).

Data comes from RecordIO shards through ImageRecordIter (the reference's
path), or --benchmark 1 uses synthetic batches (the reference's
train_imagenet --benchmark flag) so throughput is measurable without the
dataset. Training runs the fused SPMD path: the whole
fwd+bwd+SGD-momentum step is one XLA program over the device mesh, with
ImageRecordIter sharding by part_index/num_parts for multi-host.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def get_net(network, classes=1000):
    from mxnet_tpu.gluon.model_zoo import vision as models
    factory = {
        "resnet-18": models.resnet18_v1, "resnet-34": models.resnet34_v1,
        "resnet-50": models.resnet50_v1, "resnet-101": models.resnet101_v1,
        "resnet-152": models.resnet152_v1, "vgg-16": models.vgg16,
        "mobilenet-v2": models.mobilenet_v2_1_0,
        "inception-v3": models.inception_v3,
    }
    if network not in factory:
        raise SystemExit(f"unknown network {network}; have "
                         f"{sorted(factory)}")
    return factory[network](classes=classes)


def synthetic_batches(batch_size, image_shape, num_batches):
    rs = np.random.RandomState(0)
    data = rs.randn(batch_size, *image_shape).astype(np.float32)
    label = rs.randint(0, 1000, batch_size).astype(np.float32)
    for _ in range(num_batches):
        yield data, label


def rec_batches(args):
    from mxnet_tpu.io import ImageRecordIter
    c, h, w = args.image_shape
    it = ImageRecordIter(
        path_imgrec=args.data_train, data_shape=(c, h, w),
        batch_size=args.batch_size, shuffle=True,
        rand_crop=True, rand_mirror=True, resize=max(h, w) + 32,
        mean_r=123.68, mean_g=116.779, mean_b=103.939,
        std_r=58.393, std_g=57.12, std_b=57.375,
        preprocess_threads=args.data_nthreads,
        part_index=args.part_index, num_parts=args.num_parts)
    for batch in it:
        yield batch.data[0].asnumpy(), batch.label[0].asnumpy()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--network", default="resnet-50")
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--num-epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--mom", type=float, default=0.9)
    ap.add_argument("--wd", type=float, default=1e-4)
    ap.add_argument("--data-train", default=None,
                    help="RecordIO file (im2rec output)")
    ap.add_argument("--data-nthreads", type=int, default=4)
    ap.add_argument("--image-shape", default="3,224,224")
    ap.add_argument("--benchmark", type=int, default=0,
                    help="1: synthetic data, report img/s only")
    ap.add_argument("--num-batches", type=int, default=20,
                    help="batches per epoch in benchmark mode")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--kv-store", default="device",
                    help="device|dist_sync (dist uses MXTPU_* env)")
    ap.add_argument("--part-index", type=int, default=0)
    ap.add_argument("--num-parts", type=int, default=1)
    ap.add_argument("--disp-batches", type=int, default=10)
    ap.add_argument("--model-prefix", default=None)
    args = ap.parse_args()
    args.image_shape = tuple(int(x) for x in args.image_shape.split(","))

    if args.kv_store.startswith("dist"):
        from mxnet_tpu.kvstore_server import init_distributed
        init_distributed()

    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import loss as gloss
    from mxnet_tpu.parallel import SPMDTrainer, auto_mesh

    mx.random.seed(0)
    net = get_net(args.network)
    net.initialize(mx.init.Xavier())
    mesh = auto_mesh()
    trainer = SPMDTrainer(
        net, gloss.SoftmaxCrossEntropyLoss(), mesh=mesh, optimizer="sgd",
        optimizer_params={"learning_rate": args.lr, "momentum": args.mom,
                          "wd": args.wd},
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else None)

    for epoch in range(args.num_epochs):
        batches = synthetic_batches(args.batch_size, args.image_shape,
                                    args.num_batches) \
            if args.benchmark or not args.data_train else rec_batches(args)
        t0 = time.time()
        n_img = 0
        for i, (data, label) in enumerate(batches):
            loss = trainer.step(jnp.asarray(data), jnp.asarray(label))
            n_img += len(data)
            if (i + 1) % args.disp_batches == 0:
                dt = time.time() - t0
                print(f"epoch {epoch} batch {i + 1}: "
                      f"loss={float(loss):.3f} {n_img / dt:.1f} img/s",
                      flush=True)
        dt = time.time() - t0
        print(f"epoch {epoch}: {n_img} images in {dt:.1f}s "
              f"({n_img / dt:.1f} img/s)", flush=True)
        if args.model_prefix:
            net.export(args.model_prefix, epoch=epoch)


if __name__ == "__main__":
    main()
