"""DCGAN (ref: example/gan/dcgan.py — Conv2DTranspose generator vs conv
discriminator, alternating SGD updates). Synthetic 32x32 data by default
(zero-egress); the training loop, losses, and update pattern match the
reference."""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def build_nets(ngf=16, ndf=16, nc=3, nz=16):
    from mxnet_tpu.gluon import nn

    netG = nn.HybridSequential()
    netG.add(
        nn.Conv2DTranspose(ngf * 4, 4, 1, 0, use_bias=False),   # 1 -> 4
        nn.BatchNorm(), nn.Activation("relu"),
        nn.Conv2DTranspose(ngf * 2, 4, 2, 1, use_bias=False),   # 4 -> 8
        nn.BatchNorm(), nn.Activation("relu"),
        nn.Conv2DTranspose(ngf, 4, 2, 1, use_bias=False),       # 8 -> 16
        nn.BatchNorm(), nn.Activation("relu"),
        nn.Conv2DTranspose(nc, 4, 2, 1, use_bias=False),        # 16 -> 32
        nn.Activation("tanh"))

    netD = nn.HybridSequential()
    netD.add(
        nn.Conv2D(ndf, 4, 2, 1, use_bias=False),
        nn.LeakyReLU(0.2),
        nn.Conv2D(ndf * 2, 4, 2, 1, use_bias=False),
        nn.BatchNorm(), nn.LeakyReLU(0.2),
        nn.Conv2D(ndf * 4, 4, 2, 1, use_bias=False),
        nn.BatchNorm(), nn.LeakyReLU(0.2),
        nn.Conv2D(1, 4, 1, 0, use_bias=False))
    return netG, netD


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--nz", type=int, default=16)
    ap.add_argument("--iters", type=int, default=6)
    ap.add_argument("--lr", type=float, default=0.0002)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import loss as gloss

    mx.random.seed(0)
    netG, netD = build_nets(nz=args.nz)
    netG.initialize(mx.init.Normal(0.02))
    netD.initialize(mx.init.Normal(0.02))
    loss_fn = gloss.SigmoidBinaryCrossEntropyLoss()
    trainerG = gluon.Trainer(netG.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})
    trainerD = gluon.Trainer(netD.collect_params(), "adam",
                             {"learning_rate": args.lr, "beta1": 0.5})

    rs = np.random.RandomState(0)
    real_label = nd.ones((args.batch_size,))
    fake_label = nd.zeros((args.batch_size,))
    for it in range(args.iters):
        real = nd.array(rs.randn(args.batch_size, 3, 32, 32)
                        .astype(np.float32).clip(-1, 1))
        noise = nd.array(rs.randn(args.batch_size, args.nz, 1, 1)
                         .astype(np.float32))
        # D step: maximize log D(x) + log(1 - D(G(z)))
        with autograd.record():
            out_real = netD(real).reshape((-1,))
            fake = netG(noise)
            out_fake = netD(fake.detach()).reshape((-1,))
            errD = loss_fn(out_real, real_label) + \
                loss_fn(out_fake, fake_label)
        errD.backward()
        trainerD.step(args.batch_size)
        # G step: maximize log D(G(z))
        with autograd.record():
            out = netD(netG(noise)).reshape((-1,))
            errG = loss_fn(out, real_label)
        errG.backward()
        trainerG.step(args.batch_size)
        print(f"iter {it}: errD={float(errD.mean().asscalar()):.4f} "
              f"errG={float(errG.mean().asscalar()):.4f}", flush=True)
    print("dcgan training loop done")


if __name__ == "__main__":
    main()
