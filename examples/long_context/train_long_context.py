"""Long-context transformer LM training with sequence parallelism.

The first-class long-context recipe: a decoder-only transformer whose
attention runs as RING ATTENTION over the mesh's `sp` axis
(mxnet_tpu.parallel.ring_attention — the blockwise k/v rotation over ICI;
per-device working set is T/n so sequences n× longer than one chip's
memory fit), composed with data parallelism on `dp`. The whole train
step is ONE pjit-compiled program: XLA inserts the gradient psum over
`dp` and the ring ppermutes over `sp`.

Run (virtual 8-device mesh on CPU):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 JAX_PLATFORMS=cpu \
      python examples/long_context/train_long_context.py

On a real TPU slice the same code scales across chips — only the mesh
shape changes (ref counterpart: example/gluon/word_language_model + the
reference's dist kvstore, re-designed SPMD-first).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def build_params(rng, vocab, d_model, n_heads, d_ff, n_layers):
    import jax
    import jax.numpy as jnp
    keys = jax.random.split(rng, 2 + 4 * n_layers)
    s = 0.02
    params = {
        "embed": jax.random.normal(keys[0], (vocab, d_model)) * s,
        "out": jax.random.normal(keys[1], (d_model, vocab)) * s,
        "layers": [],
    }
    for i in range(n_layers):
        k = keys[2 + 4 * i: 6 + 4 * i]
        params["layers"].append({
            "qkv": jax.random.normal(k[0], (d_model, 3 * d_model)) * s,
            "proj": jax.random.normal(k[1], (d_model, d_model)) * s,
            "ff1": jax.random.normal(k[2], (d_model, d_ff)) * s,
            "ff2": jax.random.normal(k[3], (d_ff, d_model)) * s,
        })
    return params


def forward(params, tokens, mesh, n_heads, sp_axis="sp"):
    """tokens (B, T) int32 -> logits (B, T, V); attention over the ring."""
    import jax.numpy as jnp
    from mxnet_tpu.parallel import ring_attention

    x = params["embed"][tokens]  # (B, T, D)
    B, T, D = x.shape
    H, hd = n_heads, D // n_heads
    for layer in params["layers"]:
        # pre-norm
        h = x / (jnp.sqrt(jnp.mean(jnp.square(x), axis=-1,
                                   keepdims=True)) + 1e-6)
        qkv = h @ layer["qkv"]
        q, k, v = jnp.split(qkv, 3, axis=-1)
        q = q.reshape(B, T, H, hd)
        k = k.reshape(B, T, H, hd)
        v = v.reshape(B, T, H, hd)
        att = ring_attention(q, k, v, mesh, axis=sp_axis, causal=True)
        x = x + att.reshape(B, T, D) @ layer["proj"]
        h = x / (jnp.sqrt(jnp.mean(jnp.square(x), axis=-1,
                                   keepdims=True)) + 1e-6)
        x = x + jnp.maximum(h @ layer["ff1"], 0.0) @ layer["ff2"]
    return x @ params["out"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=2)
    ap.add_argument("--sp", type=int, default=4)
    ap.add_argument("--seq-len", type=int, default=256)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--d-model", type=int, default=64)
    ap.add_argument("--n-heads", type=int, default=4)
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--vocab", type=int, default=257)
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=3e-3)
    args = ap.parse_args()

    from mxnet_tpu.util import honor_platform_env
    honor_platform_env()  # JAX_PLATFORMS=cpu + virtual devices work
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from mxnet_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": args.dp, "sp": args.sp})
    print(f"mesh: dp={args.dp} x sp={args.sp} over "
          f"{args.dp * args.sp} devices; seq {args.seq_len} "
          f"({args.seq_len // args.sp}/device)")

    rng = jax.random.PRNGKey(0)
    params = build_params(rng, args.vocab, args.d_model, args.n_heads,
                          4 * args.d_model, args.layers)

    # synthetic LEARNABLE task: a FIXED set of period-P sequences — the
    # model memorizes the patterns' bigrams and long-range structure;
    # loss drops toward zero while every attention step runs as a ring
    # over `sp` (the long-range retrieval machinery under test)
    rs = np.random.RandomState(0)
    period = 16
    pat = rs.randint(1, args.vocab, (args.batch, period))
    reps = (args.seq_len + period) // period + 1
    fixed = np.tile(pat, (1, reps))[:, :args.seq_len + 1]

    def batch():
        return fixed[:, :-1].astype(np.int32), fixed[:, 1:].astype(np.int32)

    def loss_fn(p, tokens, targets):
        logits = forward(p, tokens, mesh, args.n_heads)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None],
                                   axis=-1)[..., 0]
        return jnp.mean(nll)

    @jax.jit
    def step(p, m, v, t, tokens, targets):
        loss, grads = jax.value_and_grad(loss_fn)(p, tokens, targets)
        # inline Adam — the update fuses into the same XLA program as the
        # ring-attention forward/backward (one dispatch per step)
        b1, b2, eps = 0.9, 0.999, 1e-8
        m = jax.tree_util.tree_map(lambda a, g: b1 * a + (1 - b1) * g,
                                   m, grads)
        v = jax.tree_util.tree_map(
            lambda a, g: b2 * a + (1 - b2) * jnp.square(g), v, grads)
        lr_t = args.lr * jnp.sqrt(1 - b2 ** t) / (1 - b1 ** t)
        new_p = jax.tree_util.tree_map(
            lambda w, mi, vi: w - lr_t * mi / (jnp.sqrt(vi) + eps),
            p, m, v)
        return new_p, m, v, loss

    # shard: batch over dp, sequence over sp; params replicated
    data_sh = NamedSharding(mesh, P("dp", "sp"))
    params = jax.device_put(params, NamedSharding(mesh, P()))
    zeros = lambda: jax.tree_util.tree_map(jnp.zeros_like, params)
    m_state, v_state = zeros(), zeros()

    first = last = None
    for i in range(args.steps):
        toks, tgts = batch()
        toks = jax.device_put(jnp.asarray(toks), data_sh)
        tgts = jax.device_put(jnp.asarray(tgts), data_sh)
        params, m_state, v_state, loss = step(params, m_state, v_state,
                                              i + 1, toks, tgts)
        last = float(loss)
        first = first if first is not None else last
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i}: loss {last:.4f}")
    print(f"done (loss {first:.3f} -> {last:.3f})")


if __name__ == "__main__":
    main()
