/* Standalone consumer of libmxtpu_predict.so — no Python host process. */
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *PredictorHandle;

extern const char *MXGetLastError();
extern int MXPredCreate(const char *, const void *, int, int, int, mx_uint,
                        const char **, const mx_uint *, const mx_uint *,
                        PredictorHandle *);
extern int MXPredSetInput(PredictorHandle, const char *, const mx_float *, mx_uint);
extern int MXPredForward(PredictorHandle);
extern int MXPredGetOutputShape(PredictorHandle, mx_uint, mx_uint **, mx_uint *);
extern int MXPredGetOutput(PredictorHandle, mx_uint, mx_float *, mx_uint);
extern int MXPredFree(PredictorHandle);

static char *read_file(const char *path, long *size) {
  FILE *f = fopen(path, "rb");
  if (!f) { fprintf(stderr, "cannot open %s\n", path); exit(1); }
  fseek(f, 0, SEEK_END); *size = ftell(f); fseek(f, 0, SEEK_SET);
  char *buf = malloc(*size + 1);
  if (fread(buf, 1, *size, f) != (size_t)*size) exit(1);
  buf[*size] = 0; fclose(f);
  return buf;
}

int main(int argc, char **argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s model-symbol.json model-0000.params\n", argv[0]);
    return 2;
  }
  long sym_size, param_size;
  char *sym = read_file(argv[1], &sym_size);
  char *params = read_file(argv[2], &param_size);
  const char *keys[] = {"data"};
  mx_uint indptr[] = {0, 2};
  mx_uint shape[] = {2, 8};
  PredictorHandle h;
  if (MXPredCreate(sym, params, (int)param_size, 1, 0, 1, keys, indptr, shape, &h)) {
    fprintf(stderr, "create failed: %s\n", MXGetLastError()); return 1;
  }
  mx_float input[16];
  for (int i = 0; i < 16; i++) input[i] = (mx_float)i * 0.1f;
  if (MXPredSetInput(h, "data", input, 16)) { fprintf(stderr, "%s\n", MXGetLastError()); return 1; }
  if (MXPredForward(h)) { fprintf(stderr, "forward failed: %s\n", MXGetLastError()); return 1; }
  mx_uint *oshape, ondim;
  if (MXPredGetOutputShape(h, 0, &oshape, &ondim)) return 1;
  mx_uint total = 1;
  printf("output shape: ");
  for (mx_uint i = 0; i < ondim; i++) { printf("%u ", oshape[i]); total *= oshape[i]; }
  printf("\n");
  mx_float *out = malloc(total * sizeof(mx_float));
  if (MXPredGetOutput(h, 0, out, total)) return 1;
  printf("out[0..3]: %f %f %f %f\n", out[0], out[1], out[2], out[3]);
  MXPredFree(h);
  printf("STANDALONE_OK\n");
  return 0;
}
