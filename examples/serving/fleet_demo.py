"""Fleet serving demo: publish, hot-swap under load, roll back.

The full registry-driven serving story on one machine:

1. **publish v1**: train-ish a tiny CNN, ``registry.publish`` it with its
   closed signature set (atomic: staged dir + SHA-256 manifest + DONE +
   CURRENT pointer flip),
2. **serve it** with a :class:`~mxnet_tpu.serving.FleetServer` (resolves
   CURRENT, verifies integrity, warms every signature; with
   ``MXTPU_COMPILE_CACHE`` set, a restart of this script recompiles
   nothing),
3. **publish v2** (updated weights) and export the warm replica's AOT
   bundle for it, so the deploy needs zero fresh compiles,
4. **hot-swap under load**: concurrent clients hammer the server while
   ``server.deploy(v2)`` warms v2 in the background and flips — the
   printed version-tag timeline shows the atomic cutover (tags are
   monotone in dispatch order; zero errors),
5. **roll back** to v1 with one call.

Smoke run (CPU, CI)::

    JAX_PLATFORMS=cpu python examples/serving/fleet_demo.py --smoke
"""
import argparse
import os
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.serving import FleetServer, ModelRegistry

SHAPE = (3, 16, 16)


def build_net(seed):
    mx.random.seed(seed)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3))
    net.add(gluon.nn.GlobalAvgPool2D())
    net.add(gluon.nn.Flatten())
    net.add(gluon.nn.Dense(10, in_units=8))
    net.initialize(mx.init.Xavier())
    with mx.autograd.pause():
        net(nd.ones((1,) + SHAPE))
    return net


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--registry", default=None,
                   help="registry root (default: a temp dir)")
    p.add_argument("--requests", type=int, default=200,
                   help="client requests driven across the hot swap")
    p.add_argument("--clients", type=int, default=3)
    p.add_argument("--smoke", action="store_true",
                   help="assert the swap invariants and exit (CI)")
    args = p.parse_args()

    root = args.registry or os.path.join(
        tempfile.mkdtemp(prefix="fleet_demo_"), "registry")
    registry = ModelRegistry(root)
    sig = {"bucket_shapes": [list(SHAPE)], "dtype": "float32"}

    # 1. publish v1 and serve it
    v1 = registry.publish("demo_cnn", net=build_net(1), signature=sig)
    print(f"published {v1} -> CURRENT={registry.current('demo_cnn')}")
    server = FleetServer(registry, "demo_cnn", max_batch_size=8,
                         max_queue_latency_ms=2.0).start()
    print(f"serving {server.active_version} "
          f"(warm signatures: {server.cache.cache_info().currsize})")

    # 2. publish v2 + the warm replica's AOT bundle for it (same
    #    architecture -> same executables: the deploy below compiles 0)
    v2 = registry.publish("demo_cnn", net=build_net(2), signature=sig)
    n_aot = server.publish_aot(version=v2)
    print(f"published {v2} with {n_aot} AOT executables from the warm "
          "replica")

    # 3. concurrent load across the swap, collecting the tag timeline
    item = np.random.RandomState(0).rand(*SHAPE).astype(np.float32)
    timeline, errors = [], []
    lock = threading.Lock()
    remaining = [args.requests]

    def client():
        while True:
            with lock:
                if remaining[0] <= 0:
                    return
                remaining[0] -= 1
            try:
                fut = server.submit(item)
                fut.result(timeout=30)
                with lock:
                    timeline.append((fut.dispatch_seq, fut.version))
            except Exception as e:  # any shed/error during swap is a bug
                with lock:
                    errors.append(repr(e))

    threads = [threading.Thread(target=client)
               for _ in range(args.clients)]
    for t in threads:
        t.start()
    time.sleep(0.05)  # let v1 traffic flow first
    report = server.deploy(v2)
    for t in threads:
        t.join()

    # 4. the timeline: monotone version tags in dispatch order
    timeline.sort()
    versions = [v for _, v in timeline]
    flip = versions.index(v2) if v2 in versions else len(versions)
    print(f"deploy: {report['previous']} -> {report['version']} "
          f"(warm {report['warm_s']:.2f}s, {report['compiles']} fresh "
          f"compiles, aot_loaded {report['aot_loaded']}, drain "
          f"{report['drain_s']:.3f}s)")
    print(f"timeline: {len(timeline)} requests, {len(errors)} errors, "
          f"{flip} on {v1} then {len(versions) - flip} on {v2}")
    shown = versions[max(0, flip - 3):flip + 3]
    print(f"around the flip: ...{shown}...")

    # 5. rollback is one call
    back = server.rollback()
    print(f"rolled back -> serving {back['version']}")
    server.stop(drain=True)

    if args.smoke:
        assert not errors, errors[:3]
        assert versions and flip > 0, versions  # some v1 traffic happened
        assert all(v == v1 for v in versions[:flip])
        assert all(v == v2 for v in versions[flip:])
        assert report["aot_loaded"] > 0 and report["compiles"] == 0, report
        assert back["version"] == v1
        print("SMOKE OK")


if __name__ == "__main__":
    main()
