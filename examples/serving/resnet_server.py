"""Serve a model-zoo ResNet with the dynamic-batching ModelServer.

Demonstrates the full serving story:

1. load a model (zoo architecture here; ``--prefix`` serves an exported
   ``HybridBlock.export`` checkpoint via ``ModelServer.load`` instead),
2. warm the compiled-signature cache so first traffic never compiles,
3. drive concurrent clients through the batcher,
4. dump the metrics plane (Prometheus text + JSON),
5. run until SIGTERM, drain in-flight work, exit resumable (code 75) —
   the same relauncher contract as a preempted training job.

Smoke run (CPU, tiny synthetic model)::

    JAX_PLATFORMS=cpu python examples/serving/resnet_server.py \
        --smoke --requests 64

Real run (serves resnet18_v1 until SIGTERM)::

    python examples/serving/resnet_server.py --model resnet18_v1
"""
import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu.serving import ModelServer, QueueFull


def build_net(args):
    if args.prefix:
        return None  # ModelServer.load handles it
    if args.smoke:
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3))
        net.add(gluon.nn.GlobalAvgPool2D())
        net.add(gluon.nn.Flatten())
        net.add(gluon.nn.Dense(10, in_units=8))
    else:
        from mxnet_tpu.gluon.model_zoo.vision import get_model
        net = get_model(args.model)
    net.initialize(mx.init.Xavier())
    with mx.autograd.pause():
        net(nd.ones((1, 3, args.size, args.size)))
    return net


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18_v1")
    p.add_argument("--prefix", default=None,
                   help="serve an exported checkpoint (prefix-symbol.json "
                        "+ prefix-0000.params) instead of a zoo model")
    p.add_argument("--size", type=int, default=32)
    p.add_argument("--max-batch", type=int, default=16)
    p.add_argument("--max-latency-ms", type=float, default=5.0)
    p.add_argument("--requests", type=int, default=128,
                   help="synthetic client requests to drive before serving")
    p.add_argument("--smoke", action="store_true",
                   help="tiny CNN + exit after the synthetic clients "
                        "(CI-friendly; no signal wait)")
    args = p.parse_args()

    shape = (3, args.size, args.size)
    mx.random.seed(0)
    if args.prefix:
        server = ModelServer.load(args.prefix, bucket_shapes=[shape],
                                  max_batch_size=args.max_batch,
                                  max_queue_latency_ms=args.max_latency_ms,
                                  name=args.prefix)
    else:
        server = ModelServer(build_net(args), bucket_shapes=[shape],
                             max_batch_size=args.max_batch,
                             max_queue_latency_ms=args.max_latency_ms,
                             name=args.model)
    server.start()
    t0 = time.time()
    n = server.warmup()
    print(f"warmup: {n} signatures compiled in {time.time() - t0:.1f}s")

    # synthetic concurrent clients
    rs = np.random.RandomState(0)
    items = [rs.rand(*shape).astype(np.float32)
             for _ in range(args.requests)]
    results, rejected = [None] * len(items), [0]

    def client(i):
        try:
            results[i] = server.submit(items[i]).result(timeout=60)
        except QueueFull:
            rejected[0] += 1

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(len(items))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    done = sum(r is not None for r in results)
    print(f"served {done}/{len(items)} requests "
          f"({rejected[0]} shed with QueueFull)")
    print("--- metrics (prometheus) ---")
    print(server.metrics_text())

    if args.smoke:
        server.stop(drain=True)
        m = server.metrics_json()
        assert m["responses_total"] == done and done > 0
        print("SMOKE OK", m["latency_ms"]["total"])
        return
    print("serving until SIGTERM (kill -TERM %d) ..." % os.getpid())
    server.serve_forever()  # drains, then exits with the resumable code


if __name__ == "__main__":
    sys.exit(main())
