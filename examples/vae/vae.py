"""Variational autoencoder (ref: example/vae/VAE_example.ipynb — MLP
encoder/decoder VAE on MNIST with the classic ELBO; rebuilt TPU-first:
the reparameterization sample draws from mx.random INSIDE
autograd.record, so the pathwise gradient flows through mu/sigma
exactly as the reference's sample_normal node does).

Surfaces exercised: stochastic nodes under the tape (reparameterization
trick), a composite loss (Bernoulli reconstruction + analytic KL), and
generation from the prior at the end.

Run: python examples/vae/vae.py --iters 200
"""
import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))
sys.path.insert(0, os.path.join(_HERE, ".."))  # examples/_digits.py

import numpy as np

from _digits import digit_batch

SIZE = 10
DIM = SIZE * SIZE


def make_batch(rs, n):
    x, _ = digit_batch(rs, n, SIZE, noise=0.0, jitter=3)
    return x.reshape(n, DIM)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--batch-size", type=int, default=128)
    ap.add_argument("--latent", type=int, default=8)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    rs = np.random.RandomState(0)
    mx.random.seed(0)

    class VAE(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            self.enc = nn.Dense(args.hidden, activation="tanh")
            self.mu = nn.Dense(args.latent)
            self.logvar = nn.Dense(args.latent)
            self.dec1 = nn.Dense(args.hidden, activation="tanh")
            self.dec2 = nn.Dense(DIM)

        def decode(self, F, z):
            return self.dec2(self.dec1(z))

        def hybrid_forward(self, F, x):
            h = self.enc(x)
            mu, logvar = self.mu(h), self.logvar(h)
            # reparameterization: z = mu + sigma * eps, eps ~ N(0, 1) —
            # the random draw happens under the tape; gradients flow
            # through mu/logvar pathwise. Shape follows mu so any batch
            # size works (the net is never hybridized here).
            eps = F.random.normal(0, 1, shape=mu.shape)
            z = mu + F.exp(0.5 * logvar) * eps
            return self.decode(F, z), mu, logvar

    net = VAE()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    first = last = None
    for it in range(args.iters):
        x = nd.array(make_batch(rs, args.batch_size))
        with autograd.record():
            logits, mu, logvar = net(x)
            # Bernoulli reconstruction (logits) + analytic KL(q || N(0,1))
            rec = nd.op.relu(logits) - logits * x + \
                nd.op.Activation(nd.op.abs(logits) * -1.0,
                                 act_type="softrelu")
            rec = rec.sum(axis=1)
            kl = 0.5 * (nd.op.exp(logvar) + mu * mu - 1.0 - logvar) \
                .sum(axis=1)
            loss = (rec + kl).mean()
        loss.backward()
        trainer.step(args.batch_size)
        last = float(loss.asnumpy())
        first = first if first is not None else last
        if it % 40 == 0 or it == args.iters - 1:
            print(f"iter {it} elbo-loss {last:.2f}", flush=True)

    # generate from the prior and score how digit-like samples are:
    # fraction of mass inside the glyph grid (5x3 region) vs outside
    z = nd.array(np.random.RandomState(5).randn(64, args.latent)
                 .astype(np.float32))
    gen = 1.0 / (1.0 + np.exp(-net.decode(None, z).asnumpy()))
    on = (gen > 0.5).mean()
    print(f"first-loss {first:.2f} final-loss {last:.2f} "
          f"gen-on-fraction {on:.3f}")


if __name__ == "__main__":
    main()
