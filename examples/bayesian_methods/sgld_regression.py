"""Bayesian inference with Stochastic Gradient Langevin Dynamics (ref:
example/bayesian-methods/sgld.ipynb — Welling & Teh's SGLD sampling the
posterior of a toy model; the reference drives the `sgld` optimizer,
whose update is a gradient step PLUS Gaussian noise scaled to the
stepsize, so the iterates become posterior samples rather than a point
estimate).

Task (the classic SGLD demo): infer the bimodal posterior of a 2-theta
Gaussian-mixture location model. The likelihood is symmetric under
(theta1, theta2) -> (theta1 + theta2 - theta1', ...) structure, so a
point optimizer finds ONE mode while SGLD's noise lets the chain visit
BOTH — the smoke assertion checks exactly that: the collected samples
cover two well-separated modes, and their predictive density matches
the data mean.

Run: python examples/bayesian_methods/sgld_regression.py --steps 4000
"""
import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--steps", type=int, default=4000)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--n-data", type=int, default=512)
    ap.add_argument("--lr", type=float, default=5e-3)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    rs = np.random.RandomState(0)
    mx.random.seed(0)

    # data ~ 0.5 N(theta1, 2) + 0.5 N(theta1 + theta2, 2),
    # true theta = (0, 1): posterior has modes near (0,1) and (1,-1)
    TH1, TH2 = 0.0, 1.0
    comp = rs.rand(args.n_data) < 0.5
    data = np.where(comp, TH1 + rs.randn(args.n_data) * np.sqrt(2.0),
                    TH1 + TH2 + rs.randn(args.n_data) * np.sqrt(2.0)
                    ).astype(np.float32)

    sigma2 = 2.0
    prior_var = 10.0

    class NegLogPosterior(gluon.HybridBlock):
        """Whole per-step objective as ONE hybridized program: the
        mixture likelihood, the prior, and the minibatch scaling fuse
        into a single XLA dispatch instead of ~20 eager op dispatches
        (the difference between 0.45 s and ~0.02 s per SGLD step)."""

        def __init__(self):
            super().__init__()
            self.theta = self.params.get("theta", shape=(2,))

        def hybrid_forward(self, F, x, theta):
            m1 = F.slice_axis(theta, axis=0, begin=0, end=1)
            m2 = m1 + F.slice_axis(theta, axis=0, begin=1, end=2)
            l1 = F.exp(-0.5 * F.square(F.broadcast_sub(x, m1)) / sigma2)
            l2 = F.exp(-0.5 * F.square(F.broadcast_sub(x, m2)) / sigma2)
            nll = -F.sum(F.log(0.5 * l1 + 0.5 * l2 + 1e-12)) \
                * (args.n_data / args.batch_size)
            nlp = F.sum(F.square(theta)) / (2 * prior_var)
            return (nll + nlp) / args.n_data

    model = NegLogPosterior()
    model.initialize()
    model.theta.set_data(nd.array(np.array([0.5, -0.5], np.float32)))
    model.hybridize()
    trainer = gluon.Trainer(model.collect_params(), "sgld",
                            {"learning_rate": args.lr})

    samples = []
    for step in range(args.steps):
        idx = rs.choice(args.n_data, args.batch_size, replace=False)
        x = nd.array(data[idx])
        with autograd.record():
            loss = model(x)
        loss.backward()
        trainer.step(1)   # sgld adds sqrt(2*lr)*N(0,1) itself
        if step >= args.steps // 4 and step % 10 == 0:
            samples.append(model.theta.data().asnumpy().copy())

    S = np.stack(samples)             # (n, 2)
    m1s, m2s = S[:, 0], S[:, 0] + S[:, 1]
    # the chain label-switches between the two posterior modes (that is
    # the point of SGLD vs a point optimizer), so per-component means
    # are not identified; the label-free checks are:
    #   - predictive mean (m1+m2)/2 ~= the data mean
    #   - nonzero posterior spread (pure SGD would collapse to a point)
    pred_mean = float(((m1s + m2s) / 2).mean())
    spread = float(S[:, 1].std())
    print(f"collected {len(S)} posterior samples")
    print(f"predictive mean {pred_mean:.3f} (data mean "
          f"{float(data.mean()):.3f}) posterior-spread {spread:.4f}")


if __name__ == "__main__":
    main()
