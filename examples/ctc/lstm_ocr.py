"""LSTM + CTC sequence recognition (ref: example/ctc/lstm_ocr_train.py —
captcha OCR with warpctc; rebuilt TPU-first: gluon LSTM over column
features + gluon.loss.CTCLoss, whole model trained as one XLA program).

The task mirrors the reference's OCR setting without its captcha PIL
dependency: each sample renders a variable-length digit string into a
(width x height) strip using fixed 3x5 glyph bitmaps plus noise; the
model reads pixel columns as a sequence, an LSTM encodes them, and CTC
aligns the unsegmented column sequence to the digit labels
(blank = last class, the reference's warpctc convention).

Run: python examples/ctc/lstm_ocr.py --iters 60
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

# 3x5 digit glyphs (rows x cols), enough structure to be learnable
_GLYPHS = {
    0: ["111", "101", "101", "101", "111"],
    1: ["010", "110", "010", "010", "111"],
    2: ["111", "001", "111", "100", "111"],
    3: ["111", "001", "111", "001", "111"],
    4: ["101", "101", "111", "001", "001"],
    5: ["111", "100", "111", "001", "111"],
    6: ["111", "100", "111", "101", "111"],
    7: ["111", "001", "010", "010", "010"],
    8: ["111", "101", "111", "101", "111"],
    9: ["111", "101", "111", "001", "111"],
}
GLYPH_W, GLYPH_H, GAP = 3, 5, 2
NUM_CLASSES = 10  # digits; CTC blank rides as class 10


def render_batch(rs, batch, min_len, max_len, width):
    """(batch, width, GLYPH_H) strips + padded labels + label lengths."""
    x = rs.rand(batch, width, GLYPH_H).astype(np.float32) * 0.3
    labels = np.full((batch, max_len), -1, np.float32)
    lens = np.zeros(batch, np.float32)
    for b in range(batch):
        n = rs.randint(min_len, max_len + 1)
        digits = rs.randint(0, 10, n)
        labels[b, :n] = digits
        lens[b] = n
        col = rs.randint(0, GAP + 1)
        for d in digits:
            for r, row in enumerate(_GLYPHS[int(d)]):
                for c, bit in enumerate(row):
                    if bit == "1" and col + c < width:
                        x[b, col + c, r] += 1.0
            col += GLYPH_W + rs.randint(1, GAP + 1)
    return x, labels, lens


def ctc_greedy_decode(logits):
    """argmax -> collapse repeats -> drop blanks (ref: ctc_metrics.py)."""
    best = logits.argmax(axis=-1)  # (B, T)
    out = []
    for seq in best:
        prev, dec = -1, []
        for s in seq:
            if s != prev and s != NUM_CLASSES:
                dec.append(int(s))
            prev = s
        out.append(dec)
    return out


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=60)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=96)
    ap.add_argument("--width", type=int, default=20)
    ap.add_argument("--max-len", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.005)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn, rnn

    rs = np.random.RandomState(0)
    mx.random.seed(0)

    class OCRNet(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            self.lstm = rnn.LSTM(args.hidden, num_layers=2,
                                 layout="NTC", bidirectional=True)
            self.head = nn.Dense(NUM_CLASSES + 1, flatten=False)

        def hybrid_forward(self, F, x):
            return self.head(self.lstm(x))  # (B, T, classes+blank)

    net = OCRNet()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    # layout NTC: (batch, seq, alphabet); blank label = last class
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")

    for it in range(args.iters):
        x, labels, lens = render_batch(rs, args.batch_size, 1,
                                       args.max_len, args.width)
        with autograd.record():
            logits = net(mx.nd.array(x))
            loss = ctc(logits, mx.nd.array(labels), None,
                       mx.nd.array(lens))
        loss.backward()
        trainer.step(args.batch_size)
        if it % 10 == 0 or it == args.iters - 1:
            print(f"iter {it} ctc-loss "
                  f"{float(loss.mean().asnumpy()):.4f}", flush=True)

    # evaluate: exact-sequence accuracy via greedy decode
    x, labels, lens = render_batch(rs, 256, 1, args.max_len, args.width)
    decoded = ctc_greedy_decode(net(mx.nd.array(x)).asnumpy())
    correct = sum(
        dec == [int(v) for v in row[:int(n)]]
        for dec, row, n in zip(decoded, labels, lens))
    acc = correct / len(decoded)
    print(f"sequence accuracy: {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
