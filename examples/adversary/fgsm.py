"""Adversarial examples via FGSM (ref: example/adversary/adversary_generation.ipynb
— train an MNIST net, then perturb inputs along the SIGN of the input
gradient and watch accuracy collapse; rebuilt TPU-first with Gluon +
autograd).

What this exercises that the other examples don't: gradients with
respect to INPUTS (x.attach_grad() on a non-parameter array — the
autograd tape treats data and parameters uniformly, like the
reference's mark_variables on the data blob), and using those
gradients OUTSIDE the training loop.

Run: python examples/adversary/fgsm.py --iters 120
"""
import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))
sys.path.insert(0, os.path.join(_HERE, ".."))  # examples/_digits.py

import numpy as np

from _digits import digit_batch

SIZE = 10


def make_batch(rs, n):
    x, y = digit_batch(rs, n, SIZE, noise=0.2, jitter=3)
    return x[..., None], y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=120)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--epsilon", type=float, default=0.25)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    rs = np.random.RandomState(0)
    mx.random.seed(0)
    net = nn.HybridSequential(prefix="")
    net.add(nn.Conv2D(16, 3, padding=1, layout="NHWC", in_channels=1,
                      activation="relu"))
    net.add(nn.MaxPool2D(2, layout="NHWC"))
    net.add(nn.Flatten())
    net.add(nn.Dense(64, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 2e-3})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    for it in range(args.iters):
        x, y = make_batch(rs, args.batch_size)
        with autograd.record():
            L = ce(net(mx.nd.array(x)), mx.nd.array(y))
        L.backward()
        trainer.step(args.batch_size)
        if it % 30 == 0 or it == args.iters - 1:
            print(f"iter {it} loss {float(L.mean().asnumpy()):.4f}",
                  flush=True)

    # ---- FGSM: x_adv = x + eps * sign(dL/dx) --------------------------
    xte, yte = make_batch(np.random.RandomState(9), 512)
    xa = mx.nd.array(xte)
    xa.attach_grad()            # input gradients, not parameter ones
    with autograd.record():
        L = ce(net(xa), mx.nd.array(yte))
    L.backward()
    gsign = np.sign(xa.grad.asnumpy())
    x_adv = np.clip(xte + args.epsilon * gsign, 0, 1.4)

    clean = net(mx.nd.array(xte)).asnumpy().argmax(axis=1)
    adv = net(mx.nd.array(x_adv)).asnumpy().argmax(axis=1)
    acc_clean = float((clean == yte).mean())
    acc_adv = float((adv == yte).mean())
    print(f"clean accuracy {acc_clean:.3f} "
          f"adversarial accuracy: {acc_adv:.3f} (eps={args.epsilon})")


if __name__ == "__main__":
    main()
