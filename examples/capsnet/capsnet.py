"""CapsNet with dynamic routing-by-agreement (ref: example/capsnet/
capsulenet.py — Sabour et al.'s conv -> PrimaryCaps -> DigitCaps with 3
routing iterations and the margin loss; rebuilt TPU-first: the routing
loop is a FIXED 3-iteration python loop inside hybrid_forward, so it
unrolls into one XLA program — batch_dot drives the capsule transform
on the MXU and there is no dynamic control flow to block compilation).

Data: the shared glyph-digit renderer at 16x16 (zero-egress MNIST
stand-in). The smoke bar is classification accuracy from the capsule
LENGTHS — the architecture's defining readout (class = longest digit
capsule).

Run: python examples/capsnet/capsnet.py --iters 150
"""
import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))
sys.path.insert(0, os.path.join(_HERE, ".."))  # examples/_digits.py

import numpy as np

from _digits import digit_batch

SIZE = 16
N_CLS = 10
PRIM_CAPS = 4 * 4 * 8   # 4x4 spatial x 8 capsule channels
PRIM_DIM = 8
DIGIT_DIM = 16
ROUTING_ITERS = 3


def make_batch(rs, n):
    x, y = digit_batch(rs, n, SIZE, noise=0.2, jitter=5, scale=2)
    return x[..., None], y


def build_net():
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    class CapsNet(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            self.conv1 = nn.Conv2D(32, 5, layout="NHWC", in_channels=1,
                                   activation="relu")      # 16 -> 12
            # primary capsules: conv to 4x4 x (8 caps x 8 dim)
            self.prim = nn.Conv2D(PRIM_DIM * 8, 5, strides=2,
                                  layout="NHWC", in_channels=32)  # -> 4x4
            # the capsule transform W: (i, j*d_out, d_in)
            self.W = self.params.get(
                "caps_weight", shape=(PRIM_CAPS, N_CLS * DIGIT_DIM,
                                      PRIM_DIM))

        @staticmethod
        def squash(F, s, axis):
            n2 = F.sum(F.square(s), axis=axis, keepdims=True)
            return F.broadcast_mul(
                s, n2 / (1.0 + n2) / F.sqrt(n2 + 1e-9))

        def hybrid_forward(self, F, x, caps_weight):
            h = self.prim(self.conv1(x))                 # (B,4,4,64)
            u = F.reshape(h, shape=(0, -1, PRIM_DIM))    # (B,128,8)
            u = self.squash(F, u, axis=2)
            # u_hat[b,i,j*d] = W[i,:,:] @ u[b,i,:]  via batch_dot over i
            uT = F.transpose(u, axes=(1, 2, 0))          # (128,8,B)
            uh = F.batch_dot(caps_weight, uT)            # (128,160,B)
            uh = F.transpose(uh, axes=(2, 0, 1))
            u_hat = F.reshape(uh, shape=(0, PRIM_CAPS, N_CLS,
                                         DIGIT_DIM))    # (B,128,10,16)
            # routing by agreement: logits b over (i, j), fixed 3 iters
            b = F.sum(u_hat * 0.0, axis=3)               # (B,128,10)
            for it in range(ROUTING_ITERS):
                c = F.softmax(b, axis=2)
                s = F.sum(F.broadcast_mul(
                    u_hat, F.expand_dims(c, axis=3)), axis=1)
                v = self.squash(F, s, axis=2)            # (B,10,16)
                if it < ROUTING_ITERS - 1:
                    b = b + F.sum(F.broadcast_mul(
                        u_hat, F.expand_dims(v, axis=1)), axis=3)
            return F.sqrt(F.sum(F.square(v), axis=2) + 1e-9)  # lengths

    return CapsNet()


def margin_loss(nd, lengths, y_onehot):
    pos = nd.op.relu(0.9 - lengths) ** 2
    neg = nd.op.relu(lengths - 0.1) ** 2
    L = y_onehot * pos + 0.5 * (1.0 - y_onehot) * neg
    return L.sum(axis=1).mean()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=150)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    rs = np.random.RandomState(0)
    mx.random.seed(0)
    net = build_net()
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    for it in range(args.iters):
        x, y = make_batch(rs, args.batch_size)
        yh = nd.op.one_hot(nd.array(y.astype(np.float32)), depth=N_CLS)
        with autograd.record():
            lengths = net(nd.array(x))
            L = margin_loss(nd, lengths, yh)
        L.backward()
        trainer.step(args.batch_size)
        if it % 25 == 0 or it == args.iters - 1:
            print(f"iter {it} margin-loss {float(L.asnumpy()):.4f}",
                  flush=True)

    x, y = make_batch(np.random.RandomState(99), 512)
    pred = net(nd.array(x)).asnumpy().argmax(axis=1)
    print(f"capsule-length accuracy: {float((pred == y).mean()):.3f}")


if __name__ == "__main__":
    main()
