"""Word embeddings with NCE loss (ref: example/nce-loss/wordvec.py +
nce.py — word2vec with noise-contrastive estimation over a Zipfian noise
distribution, rebuilt TPU-first).

Instead of a full-vocab softmax (O(V) logits per position), NCE scores
the true context word against k noise words drawn from the unigram^0.75
distribution — the reference samples negatives on the data-iter thread;
here `mx.nd.random` zipfian sampling runs on host and the whole scoring
step (two embedding gathers + dot products + logistic loss) compiles to
one XLA program.

The synthetic corpus has planted co-occurrence structure (words 2i and
2i+1 always appear adjacent), so success = partner words having the most
similar embeddings.

Run: python examples/nce_loss/wordvec_nce.py --iters 300
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_corpus(rs, vocab, n_tokens):
    """Zipf-distributed word pairs: word 2i is always followed by 2i+1."""
    n_pairs = vocab // 2
    ranks = np.arange(1, n_pairs + 1, dtype=np.float64)
    probs = (1.0 / ranks) / (1.0 / ranks).sum()
    pairs = rs.choice(n_pairs, size=n_tokens // 2, p=probs)
    corpus = np.empty(n_tokens, np.int64)
    corpus[0::2] = pairs * 2
    corpus[1::2] = pairs * 2 + 1
    return corpus


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--vocab", type=int, default=200)
    ap.add_argument("--dim", type=int, default=32)
    ap.add_argument("--negatives", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    rs = np.random.RandomState(0)
    mx.random.seed(0)
    corpus = make_corpus(rs, args.vocab, 40000)

    class NCEWordVec(nn.HybridBlock):
        """Center/context embedding tables + NCE logistic scoring
        (ref: nce-loss/nce.py nce_loss — the LogisticRegressionOutput
        over true-vs-noise dot products)."""

        def __init__(self):
            super().__init__()
            self.emb_in = nn.Embedding(args.vocab, args.dim)
            self.emb_out = nn.Embedding(args.vocab, args.dim)

        def hybrid_forward(self, F, center, context, negatives):
            v_c = self.emb_in(center)                   # (B, D)
            u_pos = self.emb_out(context)               # (B, D)
            u_neg = self.emb_out(negatives)             # (B, K, D)
            pos_logit = F.sum(v_c * u_pos, axis=-1)     # (B,)
            neg_logit = F.batch_dot(
                u_neg, F.expand_dims(v_c, axis=2)).reshape((0, -1))
            # NCE objective: true pair -> 1, noise pairs -> 0, in the
            # overflow-safe softplus form: -log sigmoid(x) = softplus(-x)
            pos_loss = F.Activation(-pos_logit, act_type="softrelu")
            neg_loss = F.sum(F.Activation(neg_logit, act_type="softrelu"),
                             axis=1)
            return pos_loss + neg_loss

    net = NCEWordVec()
    net.initialize(mx.init.Xavier(magnitude=1.0))
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})

    # noise distribution ~ unigram^0.75 (the word2vec/reference choice)
    counts = np.bincount(corpus, minlength=args.vocab).astype(np.float64)
    noise_p = counts ** 0.75
    noise_p /= noise_p.sum()

    positions = rs.randint(0, len(corpus) - 1, size=(args.iters,
                                                     args.batch_size))
    for it in range(args.iters):
        pos = positions[it]
        center = corpus[pos]
        context = corpus[pos + 1 - 2 * (pos % 2)]  # the pair partner
        negs = rs.choice(args.vocab, size=(args.batch_size,
                                           args.negatives), p=noise_p)
        with autograd.record():
            loss = net(mx.nd.array(center), mx.nd.array(context),
                       mx.nd.array(negs))
        loss.backward()
        trainer.step(args.batch_size)
        if it % 50 == 0 or it == args.iters - 1:
            print(f"iter {it} nce-loss "
                  f"{float(loss.mean().asnumpy()):.4f}", flush=True)

    # evaluation: the model scores pairs as emb_in[center] . emb_out[ctx];
    # success = word w's best-scoring context is its planted partner
    emb_i = net.emb_in.weight.data().asnumpy()
    emb_o = net.emb_out.weight.data().asnumpy()
    sims = emb_i @ emb_o.T
    np.fill_diagonal(sims, -np.inf)
    # restrict to the head of the Zipf (tail words barely occur)
    head = 40
    hits = sum(sims[w].argmax() == w + 1 - 2 * (w % 2)
               for w in range(head))
    acc = hits / head
    print(f"pair-retrieval accuracy (top-{head} words): {acc:.4f}")
    return acc


if __name__ == "__main__":
    main()
