"""Shared synthetic-digit assets for the example trees (the zero-egress
MNIST stand-in): 3x5 glyph bitmaps plus a stamp helper. One definition
so a glyph or jitter fix reaches every example at once."""
import numpy as np

GLYPHS = {
    0: ["111", "101", "101", "101", "111"],
    1: ["010", "110", "010", "010", "111"],
    2: ["111", "001", "111", "100", "111"],
    3: ["111", "001", "111", "001", "111"],
    4: ["101", "101", "111", "001", "001"],
    5: ["111", "100", "111", "001", "111"],
    6: ["111", "100", "111", "101", "111"],
    7: ["111", "001", "010", "010", "010"],
    8: ["111", "101", "111", "101", "111"],
    9: ["111", "101", "111", "001", "111"],
}
GLYPH_H, GLYPH_W = 5, 3


def stamp(img, digit, r0, c0, value=1.0, scale=1):
    """Add glyph ``digit`` into 2-d ``img`` at (r0, c0), each glyph cell
    drawn as a ``scale`` x ``scale`` block."""
    for r, row in enumerate(GLYPHS[int(digit)]):
        for c, bit in enumerate(row):
            if bit == "1":
                img[r0 + scale * r:r0 + scale * (r + 1),
                    c0 + scale * c:c0 + scale * (c + 1)] += value
    return img


def digit_batch(rs, n, size, noise=0.2, jitter=3, scale=1):
    """(n, size, size) noisy images each holding one jittered digit."""
    y = rs.randint(0, 10, n)
    x = rs.rand(n, size, size).astype(np.float32) * noise
    hi = max(size - GLYPH_H * scale, 1)
    wi = max(size - GLYPH_W * scale, 1)
    for i, d in enumerate(y):
        stamp(x[i], d, rs.randint(0, min(jitter, hi)),
              rs.randint(0, min(jitter, wi)), scale=scale)
    return x, y
