"""Named-entity recognition with a BiLSTM tagger (ref:
example/named_entity_recognition/ — the reference trains an LSTM
sequence labeler over word vectors with a Softmax per token; rebuilt
TPU-first: embedding + bidirectional lax.scan LSTM + per-token Dense in
ONE compiled program, per-token masked cross-entropy for variable-length
sentences).

Data (zero-egress CoNLL stand-in): sentences over a synthetic
vocabulary where entity mentions are 1-3 token spans drawn from
per-type word families (PER/LOC/ORG), each preceded by a type-biased
trigger word ("mr", "in", "at", ...) — so correct tagging requires
CONTEXT (the BiLSTM), not per-token lookup: family words are shared
across types and only the trigger disambiguates. Tags are BIO over 3
entity types (7 classes).

Run: python examples/named_entity_recognition/ner_bilstm.py --iters 120
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

VOCAB = 300
SEQ = 20
# tag set: O, B-PER, I-PER, B-LOC, I-LOC, B-ORG, I-ORG
N_TAGS = 7
PAD = -1

# word families: ids 50-79 are AMBIGUOUS entity words usable by any type
ENTITY_WORDS = np.arange(50, 80)
# triggers force the type of the FOLLOWING span: context is required
TRIGGERS = {1: 10, 3: 11, 5: 12}   # B-PER <- "mr", B-LOC <- "in", B-ORG <- "at"


def make_batch(rs, n):
    x = rs.randint(100, VOCAB, (n, SEQ))
    y = np.zeros((n, SEQ), np.int64)        # O
    lens = np.full(n, SEQ, np.float32)
    for b in range(n):
        lens[b] = rs.randint(SEQ - 6, SEQ + 1)
        x[b, int(lens[b]):] = 0
        y[b, int(lens[b]):] = PAD
        for _ in range(rs.randint(1, 4)):
            btag = int(rs.choice([1, 3, 5]))
            span = rs.randint(1, 4)
            pos = rs.randint(0, int(lens[b]) - span - 1)
            x[b, pos] = TRIGGERS[btag]
            x[b, pos + 1:pos + 1 + span] = rs.choice(ENTITY_WORDS, span)
            y[b, pos + 1] = btag
            y[b, pos + 2:pos + 1 + span] = btag + 1   # I- tag
    return x.astype(np.float32), y


def build_net(hidden, embed):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn, rnn

    class Tagger(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(VOCAB, embed)
            self.lstm = rnn.LSTM(hidden, num_layers=1, layout="NTC",
                                 bidirectional=True)
            self.head = nn.Dense(N_TAGS, flatten=False)

        def hybrid_forward(self, F, tokens):
            return self.head(self.lstm(self.emb(tokens)))

    return Tagger()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=120)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--embed", type=int, default=32)
    ap.add_argument("--lr", type=float, default=0.005)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    rs = np.random.RandomState(0)
    mx.random.seed(0)
    net = build_net(args.hidden, args.embed)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    for it in range(args.iters):
        x, y = make_batch(rs, args.batch_size)
        mask = (y != PAD).astype(np.float32)
        ysafe = np.where(y == PAD, 0, y).astype(np.float32)
        with autograd.record():
            logits = net(mx.nd.array(x))
            # per-token CE, masked mean over real tokens
            L = ce(logits.reshape((-1, N_TAGS)),
                   mx.nd.array(ysafe.reshape(-1)),
                   mx.nd.array(mask.reshape(-1, 1)))
            L = L.sum() / max(mask.sum(), 1.0)
        L.backward()
        trainer.step(1)
        if it % 20 == 0 or it == args.iters - 1:
            print(f"iter {it} loss {float(L.asnumpy()):.4f}", flush=True)

    # held-out entity-token F1 (micro, over non-O tags)
    x, y = make_batch(np.random.RandomState(99), 256)
    pred = net(mx.nd.array(x)).asnumpy().argmax(axis=-1)
    mask = y != PAD
    tp = int(((pred == y) & (y > 0) & mask).sum())
    fp = int(((pred > 0) & (pred != y) & mask).sum())
    fn = int(((y > 0) & (pred != y) & mask).sum())
    prec = tp / max(tp + fp, 1)
    rec = tp / max(tp + fn, 1)
    f1 = 2 * prec * rec / max(prec + rec, 1e-9)
    acc = float((pred[mask] == y[mask]).mean())
    print(f"token accuracy {acc:.3f} entity F1: {f1:.3f}")


if __name__ == "__main__":
    main()
