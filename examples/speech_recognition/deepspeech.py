"""DeepSpeech-lite speech recognition: conv spectrogram stem + stacked
bidirectional GRU + CTC, trained over LENGTH BUCKETS
(ref: example/speech_recognition/arch_deepspeech.py — conv front-end over
spectrograms, stacked BiGRU, warpctc head — driven by
stt_bucketing_module.py's BucketingModule so each utterance-length bucket
gets its own unrolled graph with SHARED parameters).

TPU-first rebuild: the network is one Gluon HybridBlock (the RNN is a
lax.scan inside, so no per-length unrolling is needed); bucketing
survives as the COMPILATION strategy — utterances are grouped into a
small set of padded time lengths, each bucket shape compiles ONCE to its
own XLA program (static shapes are what the MXU needs), and all programs
share the same parameter arrays, exactly the BucketingModule contract.
CTC consumes per-utterance frame counts so padding frames don't train.

Data (zero-egress stand-in for the reference's LibriSpeech wavs): each
"utterance" is a phoneme sequence rendered as a (time, freq) spectrogram
— phoneme p excites frequency band p (+harmonic) for a variable 4-7
frame duration over noise; utterance lengths vary, exercising the
buckets. The conv stem downsamples time 2x, the BiGRUs see context, CTC
aligns the unsegmented frames to the phoneme labels.

Run: python examples/speech_recognition/deepspeech.py --iters 90
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

N_FREQ = 24          # spectrogram frequency bins
N_PHON = 10          # phoneme classes; CTC blank rides as class N_PHON
BUCKETS = (32, 48, 64)   # padded time lengths (frames)
MAX_LABEL = 8


def render_utterance(rs, min_phon=2, max_phon=MAX_LABEL):
    """One spectrogram: per-phoneme frequency bands over noise."""
    n = rs.randint(min_phon, max_phon + 1)
    phons = rs.randint(0, N_PHON, n)
    frames = []
    for p in phons:
        dur = rs.randint(4, 8)
        f = rs.rand(dur, N_FREQ).astype(np.float32) * 0.3
        band = 2 * int(p)
        f[:, band:band + 2] += 1.0          # fundamental
        f[:, (band + N_PHON) % N_FREQ] += 0.5   # harmonic
        frames.append(f)
    return np.concatenate(frames, axis=0), phons


def make_bucketed_batch(rs, batch):
    """Render a batch, pad each utterance to its bucket, return one
    (bucket_len, x, labels, frame_lens, label_lens) group per bucket."""
    groups = {}
    for _ in range(batch):
        spec, phons = render_utterance(rs)
        t = len(spec)
        bucket = next(b for b in BUCKETS if b >= t)
        groups.setdefault(bucket, []).append((spec, phons))
    out = []
    for bucket, samples in sorted(groups.items()):
        x = np.zeros((len(samples), bucket, N_FREQ, 1), np.float32)
        labels = np.full((len(samples), MAX_LABEL), -1, np.float32)
        flens = np.zeros(len(samples), np.float32)
        llens = np.zeros(len(samples), np.float32)
        for i, (spec, phons) in enumerate(samples):
            x[i, :len(spec), :, 0] = spec
            labels[i, :len(phons)] = phons
            flens[i] = len(spec) // 2    # conv stem downsamples time 2x
            llens[i] = len(phons)
        out.append((bucket, x, labels, flens, llens))
    return out


def ctc_greedy_decode(logits, frame_lens):
    best = logits.argmax(axis=-1)
    out = []
    for seq, T in zip(best, frame_lens):
        prev, dec = -1, []
        for s in seq[: int(T)]:
            if s != prev and s != N_PHON:
                dec.append(int(s))
            prev = s
        out.append(dec)
    return out


def build_net(hidden):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn, rnn

    class DeepSpeechLite(nn.HybridBlock):
        """conv (time-stride 2) -> 2x BiGRU -> per-frame phoneme logits."""

        def __init__(self):
            super().__init__()
            # NHWC: (batch, time, freq, channel) — channels-last conv
            self.conv = nn.Conv2D(16, (5, 3), strides=(2, 1),
                                  padding=(2, 1), layout="NHWC",
                                  in_channels=1, activation="relu")
            self.gru = rnn.GRU(hidden, num_layers=2, layout="NTC",
                               bidirectional=True)
            self.head = nn.Dense(N_PHON + 1, flatten=False)

        def hybrid_forward(self, F, x):
            h = self.conv(x)                       # (B, T/2, F, 16)
            h = h.reshape((0, 0, -3))              # (B, T/2, F*16)
            return self.head(self.gru(h))          # (B, T/2, classes+1)

    return DeepSpeechLite()


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=90)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--hidden", type=int, default=96)
    ap.add_argument("--lr", type=float, default=0.004)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    rs = np.random.RandomState(0)
    mx.random.seed(0)

    net = build_net(args.hidden)
    net.initialize(mx.init.Xavier())
    net.hybridize()    # one compiled program per bucket shape
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ctc = gluon.loss.CTCLoss(layout="NTC", label_layout="NT")

    for it in range(args.iters):
        # every bucket in the batch trains (shared params, per-bucket
        # programs) — the BucketingModule pattern
        tot, n = 0.0, 0
        for bucket, x, labels, flens, llens in \
                make_bucketed_batch(rs, args.batch_size):
            with autograd.record():
                logits = net(mx.nd.array(x))
                loss = ctc(logits, mx.nd.array(labels),
                           mx.nd.array(flens), mx.nd.array(llens))
            loss.backward()
            trainer.step(len(x))
            tot += float(loss.sum().asnumpy())
            n += len(x)
        if it % 10 == 0 or it == args.iters - 1:
            print(f"iter {it} ctc-loss {tot / n:.4f}", flush=True)

    # per-utterance phoneme error rate on held-out utterances
    test_rs = np.random.RandomState(999)
    errs = tot_ph = 0
    for bucket, x, labels, flens, llens in \
            make_bucketed_batch(test_rs, 64):
        dec = ctc_greedy_decode(net(mx.nd.array(x)).asnumpy(), flens)
        for d, lab, n_lab in zip(dec, labels, llens):
            ref = [int(v) for v in lab[: int(n_lab)]]
            # edit distance
            dp = np.arange(len(ref) + 1, dtype=np.int32)
            for i, c in enumerate(d, 1):
                prev, dp[0] = dp[0], i
                for j, r in enumerate(ref, 1):
                    prev, dp[j] = dp[j], min(dp[j] + 1, dp[j - 1] + 1,
                                             prev + (c != r))
            errs += int(dp[len(ref)])
            tot_ph += int(n_lab)
    print(f"phoneme error rate: {errs / max(tot_ph, 1):.3f}")


if __name__ == "__main__":
    main()
