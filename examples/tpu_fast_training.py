"""The TPU fast-training recipe, end to end.

Puts the round-2 performance machinery together on a ResNet-style
workload (ref: example/image-classification/train_imagenet.py, rebuilt
around what actually makes a TPU busy):

1. NHWC model layout (channels-last is the TPU conv layout),
2. ``SPMDTrainer.run_steps`` — K training steps fused into ONE XLA
   dispatch (lax.scan), amortizing per-dispatch host overhead and letting
   XLA overlap the optimizer update of step i with the forward of i+1,
3. ``io.DeviceStagingIter`` — async host->device staging one batch ahead,
4. optional activation remat (``remat=True`` / MXNET_BACKWARD_DO_MIRROR)
   for models that don't fit otherwise,
5. async checkpoints (``fault.CheckpointManager(async_write=True)``).

Run (any backend; on a virtual mesh use JAX_PLATFORMS=cpu with
--xla_force_host_platform_device_count=8):

    python examples/tpu_fast_training.py --batch-size 64 --fused-steps 4
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".."))

import numpy as np

from mxnet_tpu.util import honor_platform_env
honor_platform_env()  # respect JAX_PLATFORMS even under a sitecustomize

import mxnet_tpu as mx
from mxnet_tpu import fault, gluon, nd
from mxnet_tpu.gluon import loss as gloss
from mxnet_tpu.gluon.model_zoo.vision import get_model
from mxnet_tpu.io import DeviceStagingIter, NDArrayIter
from mxnet_tpu.parallel import SPMDTrainer


def synthetic_imagenet(n, image_size, classes, layout, seed=0):
    rs = np.random.RandomState(seed)
    shape = (n, image_size, image_size, 3) if layout == "NHWC" \
        else (n, 3, image_size, image_size)
    return (rs.rand(*shape).astype(np.float32),
            rs.randint(0, classes, n).astype(np.float32))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet18_v1")
    ap.add_argument("--layout", default="NHWC")
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--fused-steps", type=int, default=4)
    ap.add_argument("--image-size", type=int, default=64)
    ap.add_argument("--num-batches", type=int, default=16)
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    ap.add_argument("--remat", action="store_true",
                    help="recompute activations in backward "
                         "(MXNET_BACKWARD_DO_MIRROR)")
    ap.add_argument("--ckpt-dir", default=None)
    ap.add_argument("--ckpt-every", type=int, default=8,
                    help="checkpoint every N outer batches")
    args = ap.parse_args()

    import jax.numpy as jnp
    mx.random.seed(0)
    net = get_model(args.model, layout=args.layout, classes=100)
    net.initialize(mx.init.Xavier())
    trainer = SPMDTrainer(
        net, gloss.SoftmaxCrossEntropyLoss(), mesh=None, optimizer="sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9},
        dtype=jnp.bfloat16 if args.dtype == "bfloat16" else None,
        remat=args.remat)

    K, B = args.fused_steps, args.batch_size
    X, Y = synthetic_imagenet(args.num_batches * K * B, args.image_size,
                              100, args.layout)
    # host iter -> async device staging one batch ahead
    it = DeviceStagingIter(NDArrayIter(X, Y, batch_size=K * B))

    cm = fault.CheckpointManager(args.ckpt_dir, async_write=True) \
        if args.ckpt_dir else None

    t0 = time.time()
    nstep = 0
    for i, batch in enumerate(it):
        data = batch.data[0].reshape((K, B) + batch.data[0].shape[1:])
        label = batch.label[0].reshape((K, B))
        losses = trainer.run_steps(data, label)  # ONE dispatch, K steps
        nstep += K
        if i % 4 == 0:
            print(f"batch {i}: loss {float(np.asarray(losses)[-1]):.3f}",
                  flush=True)
        if cm is not None and i % args.ckpt_every == \
                args.ckpt_every - 1:
            cm.save(nstep, net=net)  # file IO overlaps training
    dt = time.time() - t0
    print(f"{nstep} steps, {nstep * B / dt:.0f} img/s "
          f"({dt / nstep * 1000:.1f} ms/step incl. first compile)")
    if cm is not None:
        cm.wait()
        print("checkpoints:", cm.steps())


if __name__ == "__main__":
    main()
