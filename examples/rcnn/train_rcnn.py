"""Tiny Faster R-CNN (ref: example/rcnn/): RPN head producing proposals
through the `Proposal` op, ROIPooling over the backbone features, and a
small ROI classification head. Synthetic bright-square dataset
(zero-egress). Demonstrates the full two-stage detection pipeline the
reference's rcnn example runs (train_end2end.py) on the new
Proposal/ROIPooling ops.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def synthetic_batch(rs, batch, size=64):
    """Images with one bright square; label = class (1) + corner box."""
    data = rs.rand(batch, 3, size, size).astype(np.float32) * 0.1
    boxes = np.zeros((batch, 4), np.float32)
    for i in range(batch):
        w = rs.randint(16, 32)
        x0 = rs.randint(0, size - w)
        y0 = rs.randint(0, size - w)
        data[i, :, y0:y0 + w, x0:x0 + w] = 1.0
        boxes[i] = [x0, y0, x0 + w - 1, y0 + w - 1]
    return data, boxes


def iou_targets(rois, gt_box):
    """Label each roi 1 if IoU with the single gt box > 0.3 else 0."""
    x1 = np.maximum(rois[:, 1], gt_box[0])
    y1 = np.maximum(rois[:, 2], gt_box[1])
    x2 = np.minimum(rois[:, 3], gt_box[2])
    y2 = np.minimum(rois[:, 4], gt_box[3])
    inter = np.maximum(x2 - x1 + 1, 0) * np.maximum(y2 - y1 + 1, 0)
    a1 = (rois[:, 3] - rois[:, 1] + 1) * (rois[:, 4] - rois[:, 2] + 1)
    a2 = (gt_box[2] - gt_box[0] + 1) * (gt_box[3] - gt_box[1] + 1)
    return (inter / (a1 + a2 - inter) > 0.3).astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch-size", type=int, default=4)
    ap.add_argument("--iters", type=int, default=10)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--num-rois", type=int, default=16)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn, loss as gloss

    mx.random.seed(0)
    rs = np.random.RandomState(0)
    stride = 16
    scales, ratios = (1.5, 2.0), (1.0,)
    A = len(scales) * len(ratios)

    backbone = nn.HybridSequential()
    backbone.add(nn.Conv2D(16, 3, strides=2, padding=1, activation="relu"),
                 nn.Conv2D(32, 3, strides=2, padding=1, activation="relu"),
                 nn.Conv2D(32, 3, strides=2, padding=1, activation="relu"),
                 nn.Conv2D(32, 3, strides=2, padding=1, activation="relu"))
    rpn_cls = nn.Conv2D(2 * A, 1)
    rpn_bbox = nn.Conv2D(4 * A, 1)
    roi_head = nn.HybridSequential()
    roi_head.add(nn.Dense(64, activation="relu"), nn.Dense(2))
    for blk in (backbone, rpn_cls, rpn_bbox, roi_head):
        blk.initialize(mx.init.Xavier())

    params = {}
    for blk in (backbone, rpn_cls, rpn_bbox, roi_head):
        params.update(blk.collect_params())
    trainer = gluon.Trainer(params, "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    ce = gloss.SoftmaxCrossEntropyLoss()

    for it in range(args.iters):
        data_np, gt = synthetic_batch(rs, args.batch_size)
        data = nd.array(data_np)
        im_info = nd.array(np.tile([[64.0, 64.0, 1.0]],
                                   (args.batch_size, 1)).astype(np.float32))
        with autograd.record():
            feat = backbone(data)
            cls_score = rpn_cls(feat)
            # softmax over the (bg, fg) anchor pair for the Proposal op
            cls_prob = nd.softmax(
                cls_score.reshape((args.batch_size, 2, -1)), axis=1) \
                .reshape(cls_score.shape)
            bbox_pred = rpn_bbox(feat)
            with autograd.pause():
                rois = nd.contrib.MultiProposal(
                    cls_prob, bbox_pred, im_info,
                    rpn_pre_nms_top_n=64, rpn_post_nms_top_n=args.num_rois,
                    threshold=0.7, rpn_min_size=8, scales=scales,
                    ratios=ratios, feature_stride=stride)
                roi_np = rois.asnumpy()
                labels = np.concatenate(
                    [iou_targets(roi_np[i * args.num_rois:
                                        (i + 1) * args.num_rois], gt[i])
                     for i in range(args.batch_size)])
            pooled = nd.ROIPooling(feat, rois, pooled_size=(3, 3),
                                   spatial_scale=1.0 / stride)
            logits = roi_head(pooled.reshape((pooled.shape[0], -1)))
            loss = ce(logits, nd.array(labels)).mean()
        loss.backward()
        trainer.step(args.batch_size)
        acc = (logits.asnumpy().argmax(axis=1) == labels).mean()
        print(f"iter {it}: roi-cls loss {float(loss.asnumpy()):.4f} "
              f"acc {acc:.3f}")
    print("done")


if __name__ == "__main__":
    main()
