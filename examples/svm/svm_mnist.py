"""SVM digit classification via the SVMOutput head (ref:
example/svm_mnist/svm_mnist.py — an MLP whose output layer is SVMOutput,
trained with the squared hinge loss instead of softmax; same surface
here: the SVMOutput op's backward IS the hinge gradient, so the example
exercises an op-defined loss rather than a Gluon loss object).

Run: python examples/svm/svm_mnist.py --iters 200
"""
import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))
sys.path.insert(0, os.path.join(_HERE, ".."))  # examples/_digits.py

import numpy as np

from _digits import digit_batch

SIZE = 10


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--linear", action="store_true",
                    help="L1 hinge instead of squared hinge")
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn

    rs = np.random.RandomState(0)
    mx.random.seed(0)
    net = nn.HybridSequential(prefix="")
    net.add(nn.Dense(128, activation="relu"))
    net.add(nn.Dense(10))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})

    for it in range(args.iters):
        x, y = digit_batch(rs, args.batch_size, SIZE, noise=0.2,
                           jitter=3)
        xa = nd.array(x.reshape(args.batch_size, -1))
        ya = nd.array(y.astype(np.float32))
        with autograd.record():
            scores = net(xa)
            # SVMOutput: forward passes scores through; backward is the
            # (squared) hinge gradient at margin 1 — the op IS the loss
            out = nd.op.SVMOutput(scores, ya, margin=1.0,
                                  regularization_coefficient=1.0,
                                  use_linear=args.linear)
        out.backward()
        trainer.step(args.batch_size)
        if it % 40 == 0 or it == args.iters - 1:
            hinge = float(nd.op.relu(
                1.0 - (scores - scores.max(axis=1, keepdims=True))
            ).mean().asnumpy())
            print(f"iter {it} (proxy margin stat {hinge:.3f})", flush=True)

    x, y = digit_batch(np.random.RandomState(99), 512, SIZE, noise=0.2,
                       jitter=3)
    pred = net(nd.array(x.reshape(512, -1))).asnumpy().argmax(axis=1)
    print(f"svm accuracy: {float((pred == y).mean()):.3f}")


if __name__ == "__main__":
    main()
