"""Recommender: matrix factorization with large sparse embeddings
(ref: example/recommenders/matrix_fact.py — MovieLens MF; rebuilt
TPU-first over synthetic interactions with planted low-rank structure).

What it exercises beyond the basic MF example (examples/model_parallel):
- REAL vocab sizes (default 100k users x 50k items) where dense
  gradient updates would touch 150k rows per step for a 4k-row batch —
  Embedding(sparse_grad=True) produces row_sparse gradients and the
  lazy Adam update (ref: optimizer_op.cc AdamUpdateRspImpl) rewrites
  state ONLY for touched rows.
- rating prediction = dot(user_vec, item_vec) + user/item biases,
  trained with L2 loss against the planted factors + noise.

Success = held-out RMSE approaching the injected noise floor.

Run: python examples/recommenders/matrix_fact_sparse.py --iters 200
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=200)
    ap.add_argument("--batch-size", type=int, default=4096)
    ap.add_argument("--users", type=int, default=100000)
    ap.add_argument("--items", type=int, default=50000)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--true-rank", type=int, default=4)
    ap.add_argument("--noise", type=float, default=0.1)
    ap.add_argument("--lr", type=float, default=0.02)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    rs = np.random.RandomState(0)
    mx.random.seed(0)

    # planted low-rank rating structure (the "true" preferences)
    u_true = rs.randn(args.users, args.true_rank).astype(np.float32) * 0.7
    i_true = rs.randn(args.items, args.true_rank).astype(np.float32) * 0.7

    def sample_batch(n):
        u = rs.randint(0, args.users, n)
        i = rs.randint(0, args.items, n)
        r = (u_true[u] * i_true[i]).sum(1) + \
            rs.randn(n).astype(np.float32) * args.noise
        return u, i, r.astype(np.float32)

    class MFNet(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            # sparse_grad: backward emits row_sparse grads so the
            # optimizer touches only the batch's rows
            self.user_emb = nn.Embedding(args.users, args.dim,
                                         sparse_grad=True)
            self.item_emb = nn.Embedding(args.items, args.dim,
                                         sparse_grad=True)
            self.user_b = nn.Embedding(args.users, 1, sparse_grad=True)
            self.item_b = nn.Embedding(args.items, 1, sparse_grad=True)

        def hybrid_forward(self, F, user, item):
            p = F.sum(self.user_emb(user) * self.item_emb(item), axis=-1)
            return p + self.user_b(user).reshape((-1,)) + \
                self.item_b(item).reshape((-1,))

    net = MFNet()
    net.initialize(mx.init.Normal(0.1))
    # lazy Adam: m/v state advances only on touched rows
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr, "lazy_update": True})
    l2 = gluon.loss.L2Loss()

    for it in range(args.iters):
        u, i, r = sample_batch(args.batch_size)
        with autograd.record():
            pred = net(mx.nd.array(u), mx.nd.array(i))
            loss = l2(pred, mx.nd.array(r))
        loss.backward()
        # proof the sparse path is live: grads really are row_sparse
        if it == 0:
            g = net.user_emb.weight.grad()
            assert getattr(g, "stype", "default") == "row_sparse", g
            print(f"user_emb grad stype={g.stype}, "
                  f"touched rows={g._indices.shape[0]} of {args.users}")
        trainer.step(args.batch_size)
        if it % 40 == 0 or it == args.iters - 1:
            print(f"iter {it} l2-loss "
                  f"{float(loss.mean().asnumpy()):.4f}", flush=True)

    u, i, r = sample_batch(8192)
    pred = net(mx.nd.array(u), mx.nd.array(i)).asnumpy()
    rmse = float(np.sqrt(np.mean((pred - r) ** 2)))
    print(f"held-out RMSE: {rmse:.4f} (noise floor {args.noise})")
    return rmse


if __name__ == "__main__":
    main()
