"""Resilient training demo: FitLoop + chaos injection.

Trains a tiny MLP regression with periodic verified checkpoints, then (on
request) injects a failure and shows the recovery path. Run it twice with
--chaos kill@N to watch the second invocation resume from the checkpoint
and finish on the exact fault-free loss trajectory:

    python resilient_fit.py --ckpt-dir /tmp/resilient --chaos kill@12
    python resilient_fit.py --ckpt-dir /tmp/resilient          # resumes

SIGTERM (or --chaos preempt@N) exits with the resumable code (75) after a
final checkpoint; a NaN injection (--chaos nan_grad@N) is skipped by the
sentinel and training re-converges. See docs/fault.md.
"""
import argparse
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import mxnet_tpu as mx
from mxnet_tpu import fit, gluon, io, nd
from mxnet_tpu.contrib import chaos


def build(args):
    mx.random.seed(0)
    np.random.seed(0)
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(1))
    net.initialize(mx.init.Xavier())
    with mx.autograd.pause():
        net(nd.ones((1, 8)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.05, "momentum": 0.9},
                            kvstore=None)
    rs = np.random.RandomState(7)
    X = rs.randn(512, 8).astype(np.float32)
    Y = (X @ rs.randn(8, 1)).astype(np.float32)
    itr = io.NDArrayIter(X, Y, batch_size=args.batch_size, shuffle=True,
                         seed=13)  # seeded: resume replays exact batches
    loss_fn = gluon.loss.L2Loss()
    return fit.FitLoop(net, trainer, loss_fn, itr, ckpt_dir=args.ckpt_dir,
                       ckpt_every=args.ckpt_every)


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--ckpt-dir", default=None,
                    help="checkpoint directory (enables resume)")
    ap.add_argument("--ckpt-every", type=int, default=4)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--chaos", default=None,
                    help="fault plan, e.g. kill@12 / nan_grad@5 / "
                         "preempt@10 / ckpt_corrupt@latest,kv_flake:0.1")
    args = ap.parse_args(argv)

    if args.chaos:
        chaos.install(args.chaos)
    loop = build(args)
    try:
        result = loop.fit(epochs=args.epochs)
    except chaos.ChaosKilled as e:
        print(f"killed by chaos: {e}; rerun to resume from the last "
              "verified checkpoint", file=sys.stderr)
        return 1
    for i, l in enumerate(result.losses):
        print(f"iter {result.step - len(result.losses) + i} loss {l:.5f}"
              + (" (skipped: non-finite)" if
                 (result.step - len(result.losses) + i)
                 in result.skipped_steps else ""))
    print(f"done: steps={result.step} resumed_from={result.resumed_from} "
          f"skipped={result.skipped_steps} loss_scale={result.loss_scale}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
