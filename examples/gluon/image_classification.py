"""BASELINE config #2: Gluon model-zoo ResNet training with hybridize()
(ref: example/gluon/image_classification.py).

--spmd uses the fused SPMDTrainer path (one XLA program per step) over a
data-parallel mesh; default path is the classic Gluon loop
(autograd.record + Trainer.step).
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn, loss as gloss
from mxnet_tpu.gluon.model_zoo.vision import get_model


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="resnet50_v1")
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--image-size", type=int, default=224)
    ap.add_argument("--num-steps", type=int, default=20)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--spmd", action="store_true",
                    help="fused SPMD train step over the device mesh")
    ap.add_argument("--bf16", action="store_true")
    args = ap.parse_args()

    mx.random.seed(0)
    net = get_model(args.model)
    net.initialize(mx.init.Xavier())
    net.hybridize()

    rs = np.random.RandomState(0)
    data = rs.randn(args.batch_size, 3, args.image_size,
                    args.image_size).astype(np.float32)
    label = rs.randint(0, 1000, args.batch_size).astype(np.float32)
    lossfn = gloss.SoftmaxCrossEntropyLoss()

    if args.spmd:
        import jax.numpy as jnp
        from mxnet_tpu.parallel import SPMDTrainer, auto_mesh
        mesh = auto_mesh(prefer=("dp",)) if mx.num_tpus() > 1 else None
        trainer = SPMDTrainer(net, lossfn, mesh=mesh, optimizer="sgd",
                              optimizer_params={"learning_rate": args.lr,
                                                "momentum": 0.9},
                              dtype=jnp.bfloat16 if args.bf16 else None)
        step = lambda: trainer.step(nd.array(data), nd.array(label))
    else:
        trainer = gluon.Trainer(net.collect_params(), "sgd",
                                {"learning_rate": args.lr, "momentum": 0.9},
                                kvstore="device")

        def step():
            with autograd.record():
                loss = lossfn(net(nd.array(data)), nd.array(label))
            loss.backward()
            trainer.step(args.batch_size)
            return loss.mean()

    print("compiling...")
    loss = step()
    loss.wait_to_read() if hasattr(loss, "wait_to_read") else None
    t0 = time.perf_counter()
    for i in range(args.num_steps):
        loss = step()
    (loss.wait_to_read() if hasattr(loss, "wait_to_read")
     else loss.block_until_ready())
    dt = time.perf_counter() - t0
    print(f"{args.model}: {args.batch_size * args.num_steps / dt:.1f} img/s "
          f"(loss={float(loss if not hasattr(loss, 'asscalar') else loss.asscalar()):.3f})")


if __name__ == "__main__":
    main()
