"""Distributed Gluon training with the dist_sync kvstore
(ref: example/distributed_training/cifar10_dist.py — Gluon net +
`dist_sync` kvstore; each worker trains on its shard and gradients are
summed across workers every step).

Launch (N local processes; ssh/manual for real clusters):

    python tools/launch.py -n 2 --launcher local \
        python examples/distributed_training/cifar10_dist.py --epochs 1

Data: CIFAR-10 RecordIO via --data-train (im2rec output, sharded with
part_index/num_parts = rank/world); falls back to a synthetic set in the
zero-egress environment.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def synthetic_batches(batch, n_batches, rank):
    rs = np.random.RandomState(100 + rank)
    for _ in range(n_batches):
        yield (rs.randn(batch, 3, 32, 32).astype(np.float32),
               rs.randint(0, 10, batch).astype(np.float32))


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch-size", type=int, default=64,
                    help="per-worker batch size")
    ap.add_argument("--epochs", type=int, default=1)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--data-train", default=None)
    ap.add_argument("--num-batches", type=int, default=8,
                    help="synthetic batches per epoch")
    args = ap.parse_args()

    from mxnet_tpu.kvstore_server import init_distributed
    init_distributed()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn, loss as gloss

    kv = mx.kv.create("dist_sync")
    rank, nw = kv.rank, kv.num_workers
    print(f"worker {rank}/{nw} up", flush=True)

    mx.random.seed(42 + rank)
    net = nn.HybridSequential()
    net.add(nn.Conv2D(16, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2, 2),
            nn.Conv2D(32, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2, 2),
            nn.Flatten(), nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    lossfn = gloss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr}, kvstore=kv)

    for epoch in range(args.epochs):
        if args.data_train:
            from mxnet_tpu.io import ImageRecordIter
            it = ImageRecordIter(path_imgrec=args.data_train,
                                 data_shape=(3, 32, 32),
                                 batch_size=args.batch_size, shuffle=True,
                                 rand_mirror=True, part_index=rank,
                                 num_parts=nw)
            batches = ((b.data[0].asnumpy(), b.label[0].asnumpy())
                       for b in it)
        else:
            batches = synthetic_batches(args.batch_size, args.num_batches,
                                        rank)
        t0 = time.time()
        total, n = 0.0, 0
        for data, label in batches:
            x, y = nd.array(data), nd.array(label)
            with autograd.record():
                loss = lossfn(net(x), y)
            loss.backward()
            trainer.step(args.batch_size)
            total += float(loss.mean().asscalar())
            n += 1
        kv.barrier()
        print(f"worker {rank}: epoch {epoch} loss {total / max(n, 1):.4f} "
              f"({time.time() - t0:.1f}s, {n} batches)", flush=True)
    print(f"worker {rank}: DONE", flush=True)


if __name__ == "__main__":
    main()
