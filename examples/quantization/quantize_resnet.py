"""Model-scale int8 accuracy evidence: train a real model-zoo ResNet-18
to convergence, quantize it with calibrated per-channel int8
(quantize_net: BN fold -> int8 weights -> calibrated activation
scales), and report top-1 accuracy of float vs int8 on held-out data —
the number the reference treats as the POINT of the quantization flow
(ref: python/mxnet/contrib/quantization.py quantize_model +
example/quantization/imagenet_inference.py's accuracy comparison).

Data (zero-egress ImageNet stand-in): a 10-class TEXTURE dataset —
oriented sinusoidal gratings at class-specific (orientation, frequency,
color) with per-sample phase/contrast jitter and additive noise. Unlike
uniform noise, activations have the skewed, layer-dependent
distributions that make calibration non-trivial, which is what the
entropy/KL calibrator exists for.

Run: python examples/quantization/quantize_resnet.py --iters 150
(prints a final line: "top1 float <a> int8 <b> delta <d>")
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

CLASSES = 10
SIZE = 40


def make_batch(rs, n, size=SIZE, classes=CLASSES):
    """Oriented-grating textures: class k -> angle k*18deg, frequency
    2+(k%3), color channel k%3. Parameterized by image size so the
    chip-scale accuracy tool (tools/accuracy_int8_resnet50.py) measures
    the SAME task definition at 224px."""
    y = rs.randint(0, classes, n)
    yy, xx = np.mgrid[0:size, 0:size].astype(np.float32) / size
    x = rs.rand(n, size, size, 3).astype(np.float32) * 0.35
    for i, c in enumerate(y):
        ang = c * np.pi / classes
        freq = 2.0 + (c % 3) * 2.0
        phase = rs.rand() * 2 * np.pi
        contrast = 0.7 + 0.6 * rs.rand()
        wave = np.sin(2 * np.pi * freq *
                      (np.cos(ang) * xx + np.sin(ang) * yy) + phase)
        x[i, :, :, c % 3] += contrast * (wave * 0.5 + 0.5)
    return x, y.astype(np.float32)


def top1(net, mx, batches):
    hit = tot = 0
    for x, y in batches:
        pred = net(mx.nd.array(x)).asnumpy().argmax(axis=1)
        hit += int((pred == y).sum())
        tot += len(y)
    return hit / tot


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=150)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--calib-mode", default="entropy",
                    choices=["naive", "entropy"])
    ap.add_argument("--calib-batches", type=int, default=8)
    ap.add_argument("--lr", type=float, default=0.1)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    from mxnet_tpu.contrib.quantization import quantize_net

    rs = np.random.RandomState(0)
    mx.random.seed(0)

    net = resnet18_v1(classes=CLASSES, layout="NHWC")
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": args.lr, "momentum": 0.9})
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()

    for it in range(args.iters):
        x, y = make_batch(rs, args.batch_size)
        with autograd.record():
            L = lossfn(net(mx.nd.array(x)), mx.nd.array(y))
        L.backward()
        trainer.step(args.batch_size)
        if it % 25 == 0 or it == args.iters - 1:
            print(f"iter {it} loss {float(L.mean().asnumpy()):.4f}",
                  flush=True)

    test_rs = np.random.RandomState(999)
    test_batches = [make_batch(test_rs, 128) for _ in range(8)]
    acc_f = top1(net, mx, test_batches)

    # calibration set: held-out TRAINING-distribution batches, committed
    # by seed (the reference calibrates on a training subset too)
    calib_rs = np.random.RandomState(555)
    calib = [mx.nd.array(make_batch(calib_rs, args.batch_size)[0])
             for _ in range(args.calib_batches)]
    qnet = quantize_net(net, calib, calib_mode=args.calib_mode,
                        exclude=())
    acc_q = top1(qnet, mx, test_batches)

    delta = acc_f - acc_q
    print(f"top1 float {acc_f:.4f} int8 {acc_q:.4f} delta {delta:.4f}")


if __name__ == "__main__":
    main()
