"""INT8 quantization flow (ref: example/quantization/imagenet_gen_qsym.py):
load an fp32 model, quantize weights to int8 (dynamic/naive mode), emit
the quantized symbol + params, and compare outputs against fp32."""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--hidden", type=int, default=64)
    ap.add_argument("--batch-size", type=int, default=32)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import nd, sym
    from mxnet_tpu.contrib.quantization import quantize_model
    from mxnet_tpu.symbol.executor import eval_symbol

    rs = np.random.RandomState(0)

    # an fp32 MLP with random ("pretrained") weights
    data = sym.var("data")
    fc1 = sym.FullyConnected(data, sym.var("fc1_weight"),
                             sym.var("fc1_bias"), num_hidden=args.hidden,
                             name="fc1")
    act = sym.Activation(fc1, act_type="relu")
    fc2 = sym.FullyConnected(act, sym.var("fc2_weight"),
                             sym.var("fc2_bias"), num_hidden=10, name="fc2")
    net = sym.softmax(fc2, axis=-1)

    arg_params = {
        "fc1_weight": nd.array(rs.randn(args.hidden, 20)
                               .astype(np.float32) * 0.2),
        "fc1_bias": nd.array(np.zeros(args.hidden, np.float32)),
        "fc2_weight": nd.array(rs.randn(10, args.hidden)
                               .astype(np.float32) * 0.2),
        "fc2_bias": nd.array(np.zeros(10, np.float32)),
    }

    qsym, qargs, qaux = quantize_model(net, arg_params, {},
                                       calib_mode="naive")

    x = nd.array(rs.randn(args.batch_size, 20).astype(np.float32))
    fp32_out = eval_symbol(net, ["data"], [x], arg_params)
    int8_out = eval_symbol(qsym, ["data"], [x], qargs)
    fp32_out = (fp32_out[0] if isinstance(fp32_out, list)
                else fp32_out).asnumpy()
    int8_out = (int8_out[0] if isinstance(int8_out, list)
                else int8_out).asnumpy()
    agree = (fp32_out.argmax(1) == int8_out.argmax(1)).mean()
    err = np.abs(fp32_out - int8_out).max()
    print(f"top-1 agreement fp32 vs int8: {agree:.2%}  "
          f"max abs err: {err:.4f}")
    assert agree > 0.9, "int8 model diverged from fp32"
    print("quantization flow done")


if __name__ == "__main__":
    main()
