"""End-to-end Gluon int8 quantization (ref: example/quantization/
imagenet_gen_qsym.py + imagenet_inference.py, Gluon-surface analog):
train a small convnet to convergence, quantize it with calibration
(fold BN -> per-channel int8 weights -> calibrated activation scales),
and report the int8-vs-float accuracy delta and output agreement.

Run: python examples/quantization/quantize_gluon.py [--calib-mode entropy]
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def make_data(rs, n, classes=4, size=12):
    """A learnable synthetic task: class = dominant color channel+quadrant."""
    y = rs.randint(0, classes, n)
    x = rs.rand(n, size, size, 3).astype(np.float32) * 0.4
    for i, c in enumerate(y):
        ch, quad = c % 3, c // 3
        h = slice(0, size // 2) if quad == 0 else slice(size // 2, size)
        x[i, h, :, ch] += 1.0
    return x, y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--calib-mode", default="naive",
                    choices=["naive", "entropy"])
    ap.add_argument("--epochs", type=int, default=40)
    ap.add_argument("--batch-size", type=int, default=64)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.contrib.quantization import quantize_net

    rs = np.random.RandomState(0)
    mx.random.seed(0)

    net = nn.HybridSequential(prefix="")
    net.add(nn.Conv2D(16, 3, padding=1, use_bias=False, in_channels=3,
                      layout="NHWC"))
    net.add(nn.BatchNorm(axis=-1))
    net.add(nn.Activation("relu"))
    net.add(nn.Conv2D(32, 3, padding=1, strides=2, use_bias=False,
                      in_channels=16, layout="NHWC"))
    net.add(nn.BatchNorm(axis=-1))
    net.add(nn.Activation("relu"))
    net.add(nn.GlobalAvgPool2D(layout="NHWC"))
    net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())

    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.3, "momentum": 0.9})
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
    for ep in range(args.epochs):
        x, y = make_data(rs, args.batch_size)
        with autograd.record():
            loss = lossfn(net(mx.nd.array(x)), mx.nd.array(y))
        loss.backward()
        trainer.step(args.batch_size)
    print(f"final train loss: {float(loss.mean().asnumpy()):.4f}")

    xt, yt = make_data(rs, 1024)
    float_out = net(mx.nd.array(xt)).asnumpy()
    float_acc = (float_out.argmax(1) == yt).mean()

    calib = [make_data(rs, args.batch_size)[0] for _ in range(8)]
    qnet = quantize_net(net, calib, calib_mode=args.calib_mode)
    qnet.hybridize()
    int8_out = qnet(mx.nd.array(xt)).asnumpy()
    int8_acc = (int8_out.argmax(1) == yt).mean()
    agree = (int8_out.argmax(1) == float_out.argmax(1)).mean()

    print(f"float32 top-1: {float_acc:.4f}")
    print(f"int8    top-1: {int8_acc:.4f}  (delta {float_acc - int8_acc:+.4f})")
    print(f"argmax agreement: {agree:.4f}")
    assert abs(float_acc - int8_acc) <= 0.01, "int8 accuracy delta >1%"
    assert agree >= 0.98
    print("quantize_gluon done")


if __name__ == "__main__":
    main()
