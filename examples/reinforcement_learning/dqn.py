"""Deep Q-Network on a Catch environment: imperative rollouts, replay
buffer, target network (ref: example/reinforcement-learning/dqn/ —
dqn_demo.py's Atari DQN loop with its replay memory and target-network
sync; the env here is the classic 'Catch' falling-ball task instead of
an emulator, keeping the example zero-egress and CI-fast).

What this exercises that the supervised examples don't: EAGER
interleaving of environment steps and network forwards (rollouts can't
be one fused program — actions feed back into env state on the host),
a replay buffer decorrelating updates, a frozen target network copied
parameter-by-parameter every N steps (the reference's
qnet.copy_params_to(target)), epsilon-greedy exploration driven by
mx.random, and a TD(0) regression loss built from pick() on the taken
actions — while the TRAINING step itself still runs as one compiled
program per batch (hybridized net, static replay-batch shape).

Env: WxH grid; a ball falls one row per step from a random column; the
paddle on the bottom row moves {left, stay, right}. Reward +1 if caught,
-1 if missed, 0 otherwise. Optimal policy is exact; DQN should reach
~1.0 mean reward.

Run: python examples/reinforcement_learning/dqn.py --episodes 300
"""
import argparse
import os
import sys
from collections import deque

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

W, H = 6, 6
N_ACT = 3   # left, stay, right


class Catch:
    def __init__(self, rs):
        self.rs = rs

    def reset(self):
        self.ball_c = int(self.rs.randint(0, W))
        self.ball_r = 0
        self.paddle = W // 2
        return self._obs()

    def _obs(self):
        o = np.zeros((H, W), np.float32)
        o[self.ball_r, self.ball_c] = 1.0
        o[H - 1, self.paddle] = 0.5
        return o.ravel()

    def step(self, a):
        self.paddle = int(np.clip(self.paddle + (a - 1), 0, W - 1))
        self.ball_r += 1
        if self.ball_r == H - 1:
            r = 1.0 if self.paddle == self.ball_c else -1.0
            return self._obs(), r, True
        return self._obs(), 0.0, False


def build_qnet():
    from mxnet_tpu.gluon import nn
    net = nn.HybridSequential(prefix="")
    net.add(nn.Dense(64, activation="relu"))
    net.add(nn.Dense(64, activation="relu"))
    net.add(nn.Dense(N_ACT))
    return net


def copy_params(src, dst):
    """Target-network sync (ref: dqn_demo.py copyTargetQNetwork).

    The two nets are structurally identical but carry different name
    prefixes, so parameters are aligned by sorted-name ORDER, not by
    name equality."""
    s = src.collect_params()
    d = dst.collect_params()
    for (ks, ps), (kd, pd) in zip(sorted(s.items()), sorted(d.items())):
        pd.set_data(ps.data())


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--episodes", type=int, default=300)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--replay", type=int, default=4000)
    ap.add_argument("--gamma", type=float, default=0.9)
    ap.add_argument("--lr", type=float, default=1e-3)
    ap.add_argument("--target-sync", type=int, default=25)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd

    rs = np.random.RandomState(0)
    mx.random.seed(0)
    env = Catch(rs)

    qnet, target = build_qnet(), build_qnet()
    qnet.initialize(mx.init.Xavier())
    target.initialize(mx.init.Xavier())
    # materialize shapes, then hard-sync the target
    qnet(nd.array(np.zeros((1, W * H), np.float32)))
    target(nd.array(np.zeros((1, W * H), np.float32)))
    copy_params(qnet, target)
    qnet.hybridize()
    target.hybridize()

    trainer = gluon.Trainer(qnet.collect_params(), "adam",
                            {"learning_rate": args.lr})
    lossfn = gluon.loss.HuberLoss()
    buf = deque(maxlen=args.replay)
    rewards = deque(maxlen=50)

    for ep in range(args.episodes):
        eps = max(0.05, 1.0 - ep / (args.episodes * 0.6))
        s = env.reset()
        done, total = False, 0.0
        while not done:
            if rs.rand() < eps:
                a = int(rs.randint(N_ACT))
            else:   # imperative single-state forward (eager rollout)
                a = int(qnet(nd.array(s[None])).asnumpy().argmax())
            s2, r, done = env.step(a)
            buf.append((s, a, r, s2, done))
            s, total = s2, total + r
        rewards.append(total)

        if len(buf) >= args.batch_size:
            idx = rs.choice(len(buf), args.batch_size, replace=False)
            S, A, R, S2, D = (np.asarray(v, np.float32) for v in
                              zip(*[buf[i] for i in idx]))
            # TD target from the FROZEN network
            q2 = target(nd.array(S2)).asnumpy().max(axis=1)
            y = R + args.gamma * q2 * (1.0 - D)
            with autograd.record():
                q = qnet(nd.array(S))
                qa = nd.op.pick(q, nd.array(A), axis=1)
                L = lossfn(qa, nd.array(y))
            L.backward()
            trainer.step(args.batch_size)

        if ep % args.target_sync == 0:
            copy_params(qnet, target)
        if ep % 25 == 0 or ep == args.episodes - 1:
            print(f"episode {ep} eps {eps:.2f} "
                  f"mean-reward {np.mean(rewards):.3f}", flush=True)

    # greedy evaluation
    wins = 0
    for _ in range(100):
        s, done = env.reset(), False
        while not done:
            a = int(qnet(nd.array(s[None])).asnumpy().argmax())
            s, r, done = env.step(a)
        wins += r > 0
    print(f"greedy catch rate: {wins / 100:.2f}")


if __name__ == "__main__":
    main()
