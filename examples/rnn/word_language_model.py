"""BASELINE config #3: LSTM word language model
(ref: example/rnn/word_lm/train.py; cuDNN RNN -> fused lax.scan RNN).

Trains on a local text corpus when given, else a synthetic char-level
corpus. Embedding -> multi-layer fused LSTM -> tied-vocab decoder.
"""
import argparse
import math
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn, rnn, loss as gloss


class RNNModel(gluon.HybridBlock):
    """(ref: example/rnn/word_lm/model.py RNNModel)"""

    def __init__(self, vocab_size, num_embed, num_hidden, num_layers,
                 dropout=0.2, **kwargs):
        super().__init__(**kwargs)
        with self.name_scope():
            self.drop = nn.Dropout(dropout)
            self.encoder = nn.Embedding(vocab_size, num_embed)
            self.rnn = rnn.LSTM(num_hidden, num_layers, dropout=dropout,
                                input_size=num_embed)
            self.decoder = nn.Dense(vocab_size, in_units=num_hidden,
                                    flatten=False)
            self.num_hidden = num_hidden

    def _imperative_call(self, inputs, hidden=None):
        emb = self.drop(self.encoder(inputs))
        if hidden is None:
            output = self.rnn(emb)
            new_hidden = None
        else:
            output, new_hidden = self.rnn(emb, hidden)
        output = self.drop(output)
        decoded = self.decoder(output)
        if hidden is None:
            return decoded
        return decoded, new_hidden

    def begin_state(self, batch_size):
        return self.rnn.begin_state(batch_size)


def batchify(ids, batch_size, seq_len):
    n = (len(ids) - 1) // (batch_size * seq_len) * batch_size * seq_len
    x = ids[:n].reshape(batch_size, -1).T  # (T_total, N)
    y = ids[1:n + 1].reshape(batch_size, -1).T
    for t0 in range(0, x.shape[0] - seq_len + 1, seq_len):
        yield x[t0:t0 + seq_len], y[t0:t0 + seq_len]


def load_corpus(path):
    if path and os.path.exists(path):
        with open(path) as f:
            text = f.read()
        vocab = sorted(set(text.split()))
        stoi = {w: i for i, w in enumerate(vocab)}
        ids = np.array([stoi[w] for w in text.split()], np.int32)
        return ids, len(vocab)
    rs = np.random.RandomState(0)
    # synthetic markov-ish corpus: next token depends on current
    V = 200
    trans = rs.randint(0, V, (V, 4))
    ids = [0]
    for _ in range(60000):
        ids.append(trans[ids[-1], rs.randint(0, 4)])
    return np.array(ids, np.int32), V


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data", default=None, help="path to corpus txt")
    ap.add_argument("--emsize", type=int, default=128)
    ap.add_argument("--nhid", type=int, default=256)
    ap.add_argument("--nlayers", type=int, default=2)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--bptt", type=int, default=35)
    ap.add_argument("--lr", type=float, default=1.0)
    ap.add_argument("--clip", type=float, default=0.25)
    ap.add_argument("--epochs", type=int, default=2)
    args = ap.parse_args()

    ids, vocab = load_corpus(args.data)
    print(f"corpus: {len(ids)} tokens, vocab {vocab}")
    model = RNNModel(vocab, args.emsize, args.nhid, args.nlayers)
    model.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(model.collect_params(), "sgd",
                            {"learning_rate": args.lr}, kvstore=None)
    lossfn = gloss.SoftmaxCrossEntropyLoss()

    for epoch in range(args.epochs):
        total_loss, n_batches = 0.0, 0
        hidden = model.begin_state(args.batch_size)
        t0 = time.time()
        for x, y in batchify(ids, args.batch_size, args.bptt):
            xb = nd.array(x, dtype="int32")
            yb = nd.array(y.reshape(-1).astype(np.float32))
            hidden = [h.detach() for h in hidden]
            with autograd.record():
                out, hidden = model._imperative_call(xb, hidden)
                loss = lossfn(out.reshape((-1, vocab)), yb)
            loss.backward()
            grads = [p.grad() for p in model.collect_params().values()
                     if p.grad_req != "null"]
            gluon.utils.clip_global_norm(grads, args.clip * args.batch_size)
            trainer.step(args.batch_size)
            total_loss += float(loss.mean().asscalar())
            n_batches += 1
        ppl = math.exp(total_loss / n_batches)
        wps = n_batches * args.batch_size * args.bptt / (time.time() - t0)
        print(f"epoch {epoch}: ppl {ppl:.2f}, {wps:.0f} words/s")


if __name__ == "__main__":
    main()
