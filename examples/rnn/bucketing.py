"""Bucketed LSTM language model via BucketingModule
(ref: example/rnn/bucketing/lstm_bucketing.py: variable-length sentences
bucketed by length, one shared-parameter executor per bucket).

Synthetic corpus by default (zero-egress); pass --corpus for a text file
(one sentence per line, whitespace-tokenized).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def synthetic_sentences(n=200, vocab=50, seed=0):
    rs = np.random.RandomState(seed)
    return [list(rs.randint(1, vocab, rs.randint(3, 30)))
            for _ in range(n)], vocab


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--num-hidden", type=int, default=32)
    ap.add_argument("--num-embed", type=int, default=32)
    ap.add_argument("--batch-size", type=int, default=16)
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--buckets", default="10,20,30")
    ap.add_argument("--corpus", default=None)
    args = ap.parse_args()
    buckets = [int(b) for b in args.buckets.split(",")]

    import mxnet_tpu as mx
    from mxnet_tpu import sym
    from mxnet_tpu.rnn import BucketSentenceIter
    from mxnet_tpu.module import BucketingModule

    if args.corpus:
        with open(args.corpus) as f:
            tokens = [l.split() for l in f if l.strip()]
        vocab_map = {w: i + 1 for i, w in
                     enumerate(sorted({w for l in tokens for w in l}))}
        sentences = [[vocab_map[w] for w in l] for l in tokens]
        vocab = len(vocab_map) + 1
    else:
        sentences, vocab = synthetic_sentences()

    train = BucketSentenceIter(sentences, args.batch_size, buckets=buckets)

    def sym_gen(seq_len):
        data = sym.var("data")
        label = sym.var("softmax_label")
        embed = sym.Embedding(data, sym.var("embed_weight"),
                              input_dim=vocab, output_dim=args.num_embed,
                              name="embed")
        from mxnet_tpu.ops.rnn_op import rnn_param_size
        psize = rnn_param_size(num_layers=1, input_size=args.num_embed,
                               state_size=args.num_hidden,
                               bidirectional=False, mode="lstm")
        out = sym.RNN(sym.transpose(embed, axes=(1, 0, 2)),
                      sym.var("rnn_params", shape=(psize,)),
                      sym.var("rnn_state", shape=(1, args.batch_size,
                                                  args.num_hidden)),
                      sym.var("rnn_state_cell",
                              shape=(1, args.batch_size, args.num_hidden)),
                      state_size=args.num_hidden, num_layers=1,
                      mode="lstm", name="lstm")
        out = sym.reshape(sym.transpose(out, axes=(1, 0, 2)),
                          shape=(-1, args.num_hidden))
        pred = sym.FullyConnected(out, sym.var("fc_weight"),
                                  sym.var("fc_bias"), num_hidden=vocab,
                                  name="pred")
        label_flat = sym.reshape(label, shape=(-1,))
        return (sym.SoftmaxOutput(pred, label_flat, use_ignore=True,
                                 ignore_label=-1, name="softmax"),
                ("data",), ("softmax_label",))

    mod = BucketingModule(sym_gen,
                          default_bucket_key=train.default_bucket_key)
    mod.fit(train, num_epoch=args.epochs, optimizer="sgd",
            optimizer_params={"learning_rate": 0.05},
            eval_metric=mx.metric.Perplexity(ignore_label=-1),
            batch_end_callback=mx.callback.Speedometer(args.batch_size, 5))
    print("bucketing training done")


if __name__ == "__main__":
    main()
