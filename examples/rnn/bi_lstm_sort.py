"""Bidirectional-LSTM sequence sorting (ref: example/bi-lstm-sort/): the
classic seq2seq-free toy — feed a sequence of random tokens, predict the
same tokens in sorted order position-by-position through a BiLSTM.
Exercises the bidirectional fused RNN path end to end.
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np


def batches(rs, n_batches, batch, seq_len, vocab):
    for _ in range(n_batches):
        x = rs.randint(1, vocab, (batch, seq_len))
        y = np.sort(x, axis=1)
        yield x.astype(np.float32), y.astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--batch-size", type=int, default=32)
    ap.add_argument("--seq-len", type=int, default=8)
    ap.add_argument("--vocab", type=int, default=16)
    ap.add_argument("--num-hidden", type=int, default=64)
    ap.add_argument("--iters", type=int, default=60)
    ap.add_argument("--lr", type=float, default=0.01)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn, rnn, loss as gloss

    mx.random.seed(0)
    rs = np.random.RandomState(0)

    net = nn.Sequential()
    net.add(nn.Embedding(args.vocab, 32),
            rnn.LSTM(args.num_hidden, num_layers=1, bidirectional=True,
                     layout="NTC"),
            nn.Dense(args.vocab, flatten=False))
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ce = gloss.SoftmaxCrossEntropyLoss()

    accs = []
    for it, (x, y) in enumerate(
            batches(rs, args.iters, args.batch_size, args.seq_len,
                    args.vocab)):
        xb, yb = nd.array(x), nd.array(y)
        with autograd.record():
            logits = net(xb)  # (N, T, vocab)
            loss = ce(logits.reshape((-1, args.vocab)),
                      yb.reshape((-1,))).mean()
        loss.backward()
        trainer.step(args.batch_size)
        if it % 10 == 0 or it == args.iters - 1:
            pred = logits.asnumpy().argmax(axis=-1)
            acc = (pred == y).mean()
            accs.append(acc)
            print(f"iter {it}: loss {float(loss.asnumpy()):.4f} "
                  f"token-acc {acc:.3f}")
    assert accs[-1] > accs[0], "no learning progress"
    print("done")


if __name__ == "__main__":
    main()
