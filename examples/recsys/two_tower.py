"""Two-tower recommender on the sparse embedding plane.

The graded recsys recipe for the sharded giant-embedding subsystem
(parallel/embedding_plane.py; ref: the reference's recommender story —
row_sparse embeddings trained against server-sharded tables with
KVStore::PullRowSparse, served as lookup traffic):

- ONE row-sharded embedding table holds both vocabularies (item rows
  offset by the user count — the single-hash-table discipline), trained
  through the plane's mask-packed row-sparse path: each step touches only
  the batch's unique rows, per-rank Adam state materializes lazily at
  exactly 1/world of the unsharded bytes (printed from the ledger, not
  estimated).
- The dense tower is a dot-product two-tower head (user-vec and item-vec
  each projected, then an inner product — the shape that can actually
  express the planted low-rank preference structure). It trains through
  an ordinary Trainer in the SAME loop as the plane — the composition
  the ZeRO plane's sparse raise points at. The block takes the
  concatenated [user ++ item] batch and slices inside, so the exported
  symbol stays single-input for the serving tier.
- Serving: the trained table + tower publish as ONE registry version
  (serving/lookup.py publish_embedding); a LookupFleet answers
  embedding-lookup and dense-tower requests from the artifact, and the
  closed-loop lookup QPS is printed.

Success = held-out eval loss falls decisively, per-rank bytes land at
1/world, the fleet serves lookups bitwise equal to the trained table.

Run: python examples/recsys/two_tower.py --smoke
"""
import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))
# the explicit opt-in the plane requires (a typo'd value still raises)
os.environ.setdefault("MXTPU_SPARSE_PLANE", "on")

import numpy as np


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    # None defaults so --smoke only fills in what the caller left unset
    ap.add_argument("--iters", type=int, default=None)
    ap.add_argument("--batch-size", type=int, default=None)
    ap.add_argument("--users", type=int, default=None)
    ap.add_argument("--items", type=int, default=None)
    ap.add_argument("--dim", type=int, default=None)
    ap.add_argument("--proj", type=int, default=16,
                    help="two-tower projection width")
    ap.add_argument("--world", type=int, default=4)
    ap.add_argument("--true-rank", type=int, default=4)
    ap.add_argument("--lr", type=float, default=0.05)
    ap.add_argument("--init-scale", type=float, default=0.3)
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--serve-seconds", type=float, default=None)
    ap.add_argument("--registry", default=None,
                    help="registry root (default: a temp dir)")
    ap.add_argument("--smoke", action="store_true",
                    help="tiny sizes for CI")
    args = ap.parse_args()
    small = dict(users=128, items=128, dim=8, iters=150, batch_size=128,
                 serve_seconds=0.5)
    full = dict(users=4096, items=4096, dim=16, iters=400, batch_size=256,
                serve_seconds=1.0)
    for k, v in (small if args.smoke else full).items():
        if getattr(args, k) is None:
            setattr(args, k, v)

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    from mxnet_tpu.gluon import nn
    from mxnet_tpu import optimizer as opt_mod
    from mxnet_tpu.parallel.embedding_plane import EmbeddingPlane
    from mxnet_tpu.serving import LookupFleet, ModelRegistry
    from mxnet_tpu.serving.lookup import publish_embedding

    rs = np.random.RandomState(0)
    mx.random.seed(0)

    # one table, both towers: item rows live at [users, users+items),
    # padded so the contiguous row partition divides the world
    vocab = args.users + args.items
    rows = ((vocab + args.world - 1) // args.world) * args.world
    plane = EmbeddingPlane("two_tower", rows=rows, dim=args.dim,
                           world=args.world,
                           optimizer=opt_mod.Adam(learning_rate=args.lr),
                           init_scale=args.init_scale)

    # planted low-rank preference structure (the "true" taste factors)
    u_true = rs.randn(args.users, args.true_rank).astype(np.float32) * 0.7
    i_true = rs.randn(args.items, args.true_rank).astype(np.float32) * 0.7

    def sample_batch(n):
        u = rs.randint(0, args.users, n)
        i = rs.randint(0, args.items, n)
        r = (u_true[u] * i_true[i]).sum(1).astype(np.float32)
        return u, i, r

    class TwoTower(nn.HybridBlock):
        """score = <P_u uvec, P_i ivec>; input is [uvec ++ ivec] so the
        exported symbol is single-input for the lookup replicas."""

        def __init__(self, dim, proj):
            super().__init__()
            self._dim = dim
            self.u = nn.Dense(proj, in_units=dim)
            self.i = nn.Dense(proj, in_units=dim)

        def hybrid_forward(self, F, x):
            uv = F.slice_axis(x, axis=1, begin=0, end=self._dim)
            iv = F.slice_axis(x, axis=1, begin=self._dim, end=2 * self._dim)
            return F.sum(self.u(uv) * self.i(iv), axis=-1)

    tower = TwoTower(args.dim, args.proj)
    tower.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(tower.collect_params(), "adam",
                            {"learning_rate": args.lr})
    l2 = gluon.loss.L2Loss()

    # fixed held-out batch: the learning bar is measured on it, not on
    # the (noisy) per-iteration training batches
    eu, ei, er = sample_batch(512)

    def eval_loss():
        with autograd.pause():
            x = nd.concat(plane.lookup(eu), plane.lookup(ei + args.users),
                          dim=1)
            pred = tower(x)
            return float(l2(pred, nd.array(er)).mean().asnumpy())

    eval_first = eval_loss()
    t0 = None
    for k in range(args.iters):
        u, i, r = sample_batch(args.batch_size)
        iv_ids = i + args.users
        uvec = plane.lookup(u)
        ivec = plane.lookup(iv_ids)
        uvec.attach_grad()
        ivec.attach_grad()
        with autograd.record():
            pred = tower(nd.concat(uvec, ivec, dim=1))
            loss = l2(pred, nd.array(r)).mean()
        loss.backward()
        trainer.step(args.batch_size)
        # ONE sharded row-sparse push for both towers' rows (dedup +
        # segment-sum inside the plane merges duplicate ids)
        plane.step(np.concatenate([u, iv_ids]),
                   nd.concat(uvec.grad, ivec.grad, dim=0))
        if k % 10 == 0 or k == args.iters - 1:
            print(f"iter {k} loss {float(loss.asnumpy()):.5f}")
        if k == 0:
            t0 = time.perf_counter()  # warm steps only: compiles excluded
    warm = max(args.iters - 1, 1)
    examples_per_s = warm * args.batch_size / max(
        time.perf_counter() - t0, 1e-9)
    eval_last = eval_loss()
    print(f"train examples/s: {examples_per_s:.1f}")
    print(f"eval loss {eval_first:.5f} -> {eval_last:.5f}")

    # the 1/world ledger pin: every rank was touched, so each holds its
    # shard + full per-row Adam state — queried from the ledger
    per_rank = [plane.rank_bytes(rk) for rk in range(args.world)]
    unsharded = 3 * rows * args.dim * 4  # f32 params + Adam mean/var
    ok = per_rank == [unsharded // args.world] * args.world
    print(f"per-rank embedding bytes: {per_rank} "
          f"(1/{args.world} of {unsharded}: {ok})")

    # serve: publish table + tower as one version, answer lookups from it
    reg_root = args.registry or tempfile.mkdtemp(prefix="two_tower_reg_")
    reg = ModelRegistry(reg_root)
    version = publish_embedding(
        reg, "two_tower", plane, tower,
        signature={"bucket_shapes": [[2 * args.dim]], "dtype": "float32"})
    fleet = LookupFleet(reg, "two_tower", replicas=args.replicas,
                        version=version)
    table = plane.todense()
    deadline = time.perf_counter() + args.serve_seconds
    parity = True
    while time.perf_counter() < deadline:
        ids = rs.randint(0, rows, 32)
        got = fleet.lookup(ids)
        parity = parity and bool((got == table[ids]).all())
    m = fleet.metrics_json()
    print(f"lookup QPS: {m['lookup_qps']:.1f} "
          f"(requests {m['requests']}, replicas {m['replicas']})")
    print(f"served-table parity: {parity}")

    plane.close()
    assert eval_last < 0.6 * eval_first, (eval_first, eval_last)
    assert ok and parity
    print("TWO_TOWER OK")


if __name__ == "__main__":
    main()
