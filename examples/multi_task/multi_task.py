"""Multi-task learning: one trunk, two heads, jointly trained
(ref: example/multi-task/multi-task-learning.ipynb — MNIST digit class +
odd/even parity sharing a conv trunk; rebuilt TPU-first with the same
structure over generated glyph images).

The two losses are weighted and summed; both heads backpropagate into
the shared trunk in ONE fused backward. Per-task accuracies are tracked
with separate mx.metric.Accuracy instances, the reference's multi-output
metric pattern.

Run: python examples/multi_task/multi_task.py --iters 150
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

_GLYPHS = {
    0: ["111", "101", "101", "101", "111"],
    1: ["010", "110", "010", "010", "111"],
    2: ["111", "001", "111", "100", "111"],
    3: ["111", "001", "111", "001", "111"],
    4: ["101", "101", "111", "001", "001"],
    5: ["111", "100", "111", "001", "111"],
    6: ["111", "100", "111", "101", "111"],
    7: ["111", "001", "010", "010", "010"],
    8: ["111", "101", "111", "101", "111"],
    9: ["111", "101", "111", "001", "111"],
}


def render_digits(rs, n, size=12):
    x = rs.rand(n, size, size, 1).astype(np.float32) * 0.3
    y = rs.randint(0, 10, n)
    for i, d in enumerate(y):
        r0 = rs.randint(0, size - 5)
        c0 = rs.randint(0, size - 3)
        for r, row in enumerate(_GLYPHS[int(d)]):
            for c, bit in enumerate(row):
                if bit == "1":
                    x[i, r0 + r, c0 + c, 0] += 1.0
    return x, y.astype(np.float32), (y % 2).astype(np.float32)


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=150)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--parity-weight", type=float, default=0.3)
    ap.add_argument("--lr", type=float, default=0.003)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    rs = np.random.RandomState(0)
    mx.random.seed(0)

    class MultiTaskNet(nn.HybridBlock):
        def __init__(self):
            super().__init__()
            self.trunk = nn.HybridSequential(prefix="")
            self.trunk.add(nn.Conv2D(16, 3, padding=1, layout="NHWC",
                                     in_channels=1, activation="relu"))
            self.trunk.add(nn.MaxPool2D(2, 2, layout="NHWC"))
            self.trunk.add(nn.Conv2D(32, 3, padding=1, layout="NHWC",
                                     in_channels=16, activation="relu"))
            self.trunk.add(nn.MaxPool2D(2, 2, layout="NHWC"))
            self.trunk.add(nn.Flatten())
            self.trunk.add(nn.Dense(64, activation="relu"))
            self.digit_head = nn.Dense(10)
            self.parity_head = nn.Dense(2)

        def hybrid_forward(self, F, x):
            z = self.trunk(x)
            return self.digit_head(z), self.parity_head(z)

    net = MultiTaskNet()
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    sce = gluon.loss.SoftmaxCrossEntropyLoss()
    acc_digit = mx.metric.Accuracy(name="digit")
    acc_parity = mx.metric.Accuracy(name="parity")

    for it in range(args.iters):
        x, y_digit, y_parity = render_digits(rs, args.batch_size)
        with autograd.record():
            out_d, out_p = net(mx.nd.array(x))
            loss = sce(out_d, mx.nd.array(y_digit)) + \
                args.parity_weight * sce(out_p, mx.nd.array(y_parity))
        loss.backward()
        trainer.step(args.batch_size)
        if it % 25 == 0 or it == args.iters - 1:
            print(f"iter {it} joint-loss "
                  f"{float(loss.mean().asnumpy()):.4f}", flush=True)

    x, y_digit, y_parity = render_digits(rs, 512)
    out_d, out_p = net(mx.nd.array(x))
    acc_digit.update([mx.nd.array(y_digit)], [out_d])
    acc_parity.update([mx.nd.array(y_parity)], [out_p])
    _, ad = acc_digit.get()
    _, ap_ = acc_parity.get()
    print(f"digit accuracy: {ad:.4f}   parity accuracy: {ap_:.4f}")
    return ad, ap_


if __name__ == "__main__":
    main()
