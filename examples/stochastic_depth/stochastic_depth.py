"""Stochastic-depth ResNet training (ref: example/stochastic-depth/
sd_cifar10.py — Huang et al.: each residual block's BRANCH is dropped
with a depth-dependent probability during training and always kept at
inference, shrinking the expected depth and regularizing).

TPU-first construction: the per-sample drop gate IS a Dropout op on a
(B,1,1,1) ones tensor — Dropout already implements inverted scaling
(kept values are divided by the survival probability) and the
training/inference switch, so the whole block stays one fused XLA
program in both modes with no python-side randomness or control flow.
The linear-decay survival schedule p_l = 1 - l/L * (1 - p_L) follows
the paper (and the reference example).

Run: python examples/stochastic_depth/stochastic_depth.py --iters 150
"""
import argparse
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.join(_HERE, "..", ".."))

import numpy as np

SIZE = 16
N_CLS = 4


def make_batch(rs, n):
    """Color-texture classes (gratings), small enough for CI."""
    y = rs.randint(0, N_CLS, n)
    yy, xx = np.mgrid[0:SIZE, 0:SIZE].astype(np.float32) / SIZE
    x = rs.rand(n, SIZE, SIZE, 3).astype(np.float32) * 0.35
    for i, c in enumerate(y):
        ang = c * np.pi / N_CLS
        wave = np.sin(2 * np.pi * 3.0 *
                      (np.cos(ang) * xx + np.sin(ang) * yy)
                      + rs.rand() * 6.28)
        x[i, :, :, c % 3] += (wave * 0.5 + 0.5)
    return x, y.astype(np.float32)


def build_net(n_blocks, final_survival):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    class SDBlock(nn.HybridBlock):
        """Residual block whose branch survives with probability p:
        out = x + gate * branch(x), gate = Dropout(ones)(p_drop) —
        0 or 1/p per SAMPLE in training, exactly 1 at inference."""

        def __init__(self, channels, survival):
            super().__init__()
            self._drop = 1.0 - float(survival)
            args = dict(layout="NHWC", padding=1, in_channels=channels)
            self.c1 = nn.Conv2D(channels, 3, activation="relu", **args)
            self.c2 = nn.Conv2D(channels, 3, **args)

        def hybrid_forward(self, F, x):
            branch = self.c2(self.c1(x))
            ones = F.mean(x, axis=(1, 2, 3), keepdims=True) * 0.0 + 1.0
            gate = F.Dropout(ones, p=self._drop, mode="training")
            return F.Activation(x + F.broadcast_mul(gate, branch),
                                act_type="relu")

    net = nn.HybridSequential(prefix="")
    net.add(nn.Conv2D(32, 3, padding=1, layout="NHWC", in_channels=3,
                      activation="relu"))
    for l in range(n_blocks):
        # linear decay: early blocks survive more
        survival = 1.0 - (l + 1) / n_blocks * (1.0 - final_survival)
        net.add(SDBlock(32, survival))
    net.add(nn.GlobalAvgPool2D(layout="NHWC"))
    net.add(nn.Dense(N_CLS))
    return net


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=150)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--blocks", type=int, default=6)
    ap.add_argument("--final-survival", type=float, default=0.5)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon

    rs = np.random.RandomState(0)
    mx.random.seed(0)
    net = build_net(args.blocks, args.final_survival)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()

    for it in range(args.iters):
        x, y = make_batch(rs, args.batch_size)
        with autograd.record():
            L = ce(net(mx.nd.array(x)), mx.nd.array(y))
        L.backward()
        trainer.step(args.batch_size)
        if it % 25 == 0 or it == args.iters - 1:
            print(f"iter {it} loss {float(L.mean().asnumpy()):.4f}",
                  flush=True)

    # training forwards are stochastic (blocks drop), inference ones are
    # deterministic (every block kept) — the mode contract of the paper
    x, y = make_batch(np.random.RandomState(99), 256)
    xa = mx.nd.array(x)
    with autograd.record():
        t1 = net(xa).asnumpy()
        t2 = net(xa).asnumpy()
    stochastic = float(np.abs(t1 - t2).max())
    i1 = net(xa).asnumpy()
    i2 = net(xa).asnumpy()
    deterministic = float(np.abs(i1 - i2).max())
    # the bit-identical contract is asserted HERE on the raw values, not
    # on rounded output downstream
    assert deterministic == 0.0, deterministic
    acc = float((i1.argmax(axis=1) == y).mean())
    print(f"train-mode variation {stochastic:.4f} "
          f"infer-mode variation {deterministic:.17g} "
          f"accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
