"""Convolutional autoencoder (ref: example/autoencoder/ — the reference
trains stacked autoencoders on MNIST with a reconstruction objective;
rebuilt TPU-first as a single Gluon encoder-decoder compiled to one XLA
program, with Conv2DTranspose upsampling instead of the reference's
fully-connected stacks).

Data: the glyph-digit renderer the repo's other vision examples use
(zero-egress MNIST stand-in). The smoke bar is the autoencoder's
defining property: reconstruction error collapses vs the input variance
AND the 16-d bottleneck stays linearly separable by digit class (a
linear probe trained on frozen codes beats chance by a wide margin).

Run: python examples/autoencoder/conv_autoencoder.py --iters 150
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

_GLYPHS = {
    0: ["111", "101", "101", "101", "111"],
    1: ["010", "110", "010", "010", "111"],
    2: ["111", "001", "111", "100", "111"],
    3: ["111", "001", "111", "001", "111"],
    4: ["101", "101", "111", "001", "001"],
    5: ["111", "100", "111", "001", "111"],
    6: ["111", "100", "111", "101", "111"],
    7: ["111", "001", "010", "010", "010"],
    8: ["111", "101", "111", "101", "111"],
    9: ["111", "101", "111", "001", "111"],
}
SIZE = 16


def make_batch(rs, n):
    y = rs.randint(0, 10, n)
    x = rs.rand(n, SIZE, SIZE, 1).astype(np.float32) * 0.15
    for i, d in enumerate(y):
        r0, c0 = rs.randint(0, 4, 2)
        for r, row in enumerate(_GLYPHS[int(d)]):
            for c, bit in enumerate(row):
                if bit == "1":
                    # 2x2 blocks so the glyph survives stride-2 encoding
                    x[i, r0 + 2 * r:r0 + 2 * r + 2,
                      c0 + 2 * c:c0 + 2 * c + 2, 0] += 1.0
    return np.clip(x, 0, 1.2), y


def build_nets(code_dim):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    enc = nn.HybridSequential(prefix="enc_")
    enc.add(nn.Conv2D(16, 3, strides=2, padding=1, layout="NHWC",
                      in_channels=1, activation="relu"))   # 16 -> 8
    enc.add(nn.Conv2D(32, 3, strides=2, padding=1, layout="NHWC",
                      in_channels=16, activation="relu"))  # 8 -> 4
    enc.add(nn.Flatten())
    enc.add(nn.Dense(code_dim))

    dec = nn.HybridSequential(prefix="dec_")
    dec.add(nn.Dense(4 * 4 * 32, activation="relu"))
    dec.add(nn.HybridLambda(
        lambda F, h: F.reshape(h, shape=(-1, 4, 4, 32))))
    dec.add(nn.Conv2DTranspose(16, 4, strides=2, padding=1,
                               layout="NHWC", in_channels=32,
                               activation="relu"))         # 4 -> 8
    dec.add(nn.Conv2DTranspose(1, 4, strides=2, padding=1,
                               layout="NHWC", in_channels=16))  # 8 -> 16
    return enc, dec


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--iters", type=int, default=150)
    ap.add_argument("--batch-size", type=int, default=64)
    ap.add_argument("--code-dim", type=int, default=16)
    ap.add_argument("--lr", type=float, default=2e-3)
    args = ap.parse_args()

    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon
    from mxnet_tpu.gluon import nn

    rs = np.random.RandomState(0)
    mx.random.seed(0)
    enc, dec = build_nets(args.code_dim)
    net = nn.HybridSequential(prefix="")
    net.add(enc)
    net.add(dec)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": args.lr})
    l2 = gluon.loss.L2Loss()

    baseline_var = None
    for it in range(args.iters):
        x, _ = make_batch(rs, args.batch_size)
        xa = mx.nd.array(x)
        if baseline_var is None:
            baseline_var = float(((x - x.mean()) ** 2).mean())
        with autograd.record():
            L = l2(net(xa), xa)
        L.backward()
        trainer.step(args.batch_size)
        if it % 25 == 0 or it == args.iters - 1:
            print(f"iter {it} recon-mse "
                  f"{2 * float(L.mean().asnumpy()):.5f} "
                  f"(input var {baseline_var:.4f})", flush=True)

    # linear probe on frozen codes: the bottleneck must organize digits
    xtr, ytr = make_batch(np.random.RandomState(7), 1024)
    xte, yte = make_batch(np.random.RandomState(8), 512)
    ztr = enc(mx.nd.array(xtr)).asnumpy()
    zte = enc(mx.nd.array(xte)).asnumpy()
    probe = nn.Dense(10)
    probe.initialize(mx.init.Xavier())
    ptr = gluon.Trainer(probe.collect_params(), "adam",
                        {"learning_rate": 0.01})
    ce = gluon.loss.SoftmaxCrossEntropyLoss()
    for _ in range(250):
        with autograd.record():
            L = ce(probe(mx.nd.array(ztr)),
                   mx.nd.array(ytr.astype(np.float32)))
        L.backward()
        ptr.step(len(ztr))
    acc = float((probe(mx.nd.array(zte)).asnumpy().argmax(axis=1)
                 == yte).mean())
    x, _ = make_batch(np.random.RandomState(9), 256)
    mse = float(((net(mx.nd.array(x)).asnumpy() - x) ** 2).mean())
    print(f"final recon-mse {mse:.5f} input-var {baseline_var:.4f} "
          f"probe accuracy: {acc:.3f}")


if __name__ == "__main__":
    main()
