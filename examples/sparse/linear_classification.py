"""BASELINE config #4: sparse linear classification with a distributed
kvstore (ref: example/sparse/linear_classification/train.py — csr data,
row_sparse weight, kvstore dist_sync push/pull + row_sparse_pull).
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu import kvstore as kv_mod
from mxnet_tpu.ndarray import sparse


def synthetic_libsvm(num_samples=4096, num_features=10000, nnz=32, seed=0):
    """Sparse binary classification data (stand-in for kdda/avazu)."""
    rs = np.random.RandomState(seed)
    w_true = rs.randn(num_features).astype(np.float32) * \
        (rs.rand(num_features) < 0.05)
    rows = []
    labels = []
    for _ in range(num_samples):
        idx = rs.choice(num_features, nnz, replace=False)
        val = rs.randn(nnz).astype(np.float32)
        score = float(w_true[idx] @ val)
        rows.append((idx, val))
        labels.append(1.0 if score > 0 else 0.0)
    return rows, np.array(labels, np.float32)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--kvstore", default="dist_tpu_sync")
    ap.add_argument("--num-features", type=int, default=10000)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--shard-table", action="store_true",
                    help="shard the weight table across local devices "
                    "(lowers MXNET_KVSTORE_BIGARRAY_BOUND so this table "
                    "qualifies; ref: kvstore_dist_server.h:331)")
    args = ap.parse_args()

    if args.shard_table:
        os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"] = "4096"

    rows, labels = synthetic_libsvm(num_features=args.num_features)
    kv = kv_mod.create(args.kvstore)
    print(f"kvstore type={kv.type} rank={kv.rank}/{kv.num_workers}")

    # weight lives in the store; workers row_sparse_pull only touched rows
    weight = nd.zeros((args.num_features, 1))
    kv.init("weight", weight)
    if args.shard_table:
        shards = kv._store["weight"]._data.addressable_shards
        print(f"weight table sharded over {len(shards)} devices "
              f"({shards[0].data.shape[0]} rows each)")
    # server-side additive update (the kvstore_dist_server ApplyUpdates
    # analog): pushed values are deltas merged into the stored weight
    kv.set_updater(lambda key, delta, stored:
                   stored._rebind((stored + delta)._data))

    n = len(labels)
    steps = 0
    for epoch in range(args.epochs):
        t0 = time.time()
        correct = 0
        for b0 in range(0, n - args.batch_size + 1, args.batch_size):
            batch = rows[b0:b0 + args.batch_size]
            y = labels[b0:b0 + args.batch_size]
            # active rows of this batch
            all_idx = np.unique(np.concatenate([idx for idx, _ in batch]))
            rid = nd.array(all_idx, dtype="int64")
            w_rows = nd.zeros((len(all_idx), 1))
            kv.row_sparse_pull("weight", out=w_rows, row_ids=rid)
            remap = {int(i): k for k, i in enumerate(all_idx)}

            # dense-per-batch computation over the active feature subspace
            X = np.zeros((len(batch), len(all_idx)), np.float32)
            for r, (idx, val) in enumerate(batch):
                for i, v in zip(idx, val):
                    X[r, remap[int(i)]] = v
            Xn = nd.array(X)
            yn = nd.array(y)
            w_rows.attach_grad()
            with autograd.record():
                logits = nd.op.dot(Xn, w_rows).reshape((-1,))
                loss = nd.op.relu(logits) - logits * yn + \
                    nd.op.Activation(-nd.op.abs(logits), act_type="softrelu")
                loss = loss.mean()
            loss.backward()
            # push row_sparse gradient for the touched rows only
            grad_rows = w_rows.grad
            scatter = sparse.RowSparseNDArray(
                (grad_rows * args.lr * -1.0)._data, rid._data,
                (args.num_features, 1))
            # apply: pull full rows, add update, push back via updater
            updated = w_rows - args.lr * grad_rows
            dense_update = nd.zeros((args.num_features, 1))
            dense_update[rid] = updated - w_rows
            kv.push("weight", dense_update)
            pred = (logits.asnumpy() > 0).astype(np.float32)
            correct += int((pred == y).sum())
            steps += 1
        acc = correct / (steps and (n // args.batch_size) * args.batch_size)
        print(f"epoch {epoch}: accuracy {correct / ((n // args.batch_size) * args.batch_size):.3f} "
              f"({time.time() - t0:.1f}s)")


if __name__ == "__main__":
    main()
