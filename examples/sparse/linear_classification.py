"""BASELINE config #4: sparse linear classification with a distributed
kvstore (ref: example/sparse/linear_classification/train.py — csr data,
row_sparse weight, kvstore dist_sync push/pull + row_sparse_pull).

The whole batch path is sparse end-to-end, like the reference:
- each batch is a CSRNDArray (never densified),
- only the batch's touched weight rows move: row_sparse_pull refreshes
  them from the store (O(batch nnz) traffic),
- the forward is the on-device csr×dense dot kernel (ops/sparse_ops.py),
- the backward delivers a row_sparse gradient for only the touched rows,
- the kvstore push ships those compact rows and the store applies a
  lazy SGD step to them.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "..", ".."))

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu import kvstore as kv_mod
from mxnet_tpu.ndarray import sparse


def synthetic_libsvm(num_samples=4096, num_features=10000, nnz=32, seed=0):
    """Sparse binary classification data (stand-in for kdda/avazu),
    already in csr coordinate form (vectorized, no per-row python work)."""
    rs = np.random.RandomState(seed)
    w_true = rs.randn(num_features).astype(np.float32) * \
        (rs.rand(num_features) < 0.05)
    cols = np.stack([rs.choice(num_features, nnz, replace=False)
                     for _ in range(num_samples)])       # (N, nnz)
    vals = rs.randn(num_samples, nnz).astype(np.float32)
    scores = (w_true[cols] * vals).sum(axis=1)
    labels = (scores > 0).astype(np.float32)
    return cols, vals, labels


def batch_csr(cols, vals, num_features):
    """Build one batch's CSRNDArray from its (rows, nnz) coordinate block."""
    b, nnz = cols.shape
    indptr = np.arange(b + 1, dtype=np.int32) * nnz
    return sparse.CSRNDArray(vals.reshape(-1), cols.reshape(-1).astype(np.int32),
                             indptr, (b, num_features))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--kvstore", default="dist_tpu_sync")
    ap.add_argument("--num-features", type=int, default=10000)
    ap.add_argument("--batch-size", type=int, default=256)
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--lr", type=float, default=0.5)
    ap.add_argument("--shard-table", action="store_true",
                    help="shard the weight table across local devices "
                    "(lowers MXNET_KVSTORE_BIGARRAY_BOUND so this table "
                    "qualifies; ref: kvstore_dist_server.h:331)")
    args = ap.parse_args()

    if args.shard_table:
        os.environ["MXNET_KVSTORE_BIGARRAY_BOUND"] = "4096"

    cols, vals, labels = synthetic_libsvm(num_features=args.num_features)
    kv = kv_mod.create(args.kvstore)
    print(f"kvstore type={kv.type} rank={kv.rank}/{kv.num_workers}")

    # weight lives in the store; the updater is a lazy SGD on pushed rows
    # (the kvstore_dist_server ApplyUpdates analog)
    weight = nd.zeros((args.num_features, 1))
    kv.init("weight", weight)
    if args.shard_table:
        shards = kv._store["weight"]._data.addressable_shards
        print(f"weight table sharded over {len(shards)} devices "
              f"({shards[0].data.shape[0]} rows each)")
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=args.lr, lazy_update=True))

    n = len(labels)
    batches = (n // args.batch_size) * args.batch_size
    # local working copy of the table; per batch, only the touched rows are
    # refreshed from the store via row_sparse_pull (O(batch nnz) traffic,
    # like the reference's sparse weight pull)
    w = nd.zeros((args.num_features, 1))
    for epoch in range(args.epochs):
        t0 = time.time()
        correct = 0
        for b0 in range(0, n - args.batch_size + 1, args.batch_size):
            sl = slice(b0, b0 + args.batch_size)
            X = batch_csr(cols[sl], vals[sl], args.num_features)
            yn = nd.array(labels[sl])

            rid = nd.array(np.unique(cols[sl]), dtype="int64")
            rows = sparse.zeros("row_sparse", (args.num_features, 1))
            kv.row_sparse_pull("weight", out=rows, row_ids=rid)
            w[rid] = rows.data
            w.attach_grad(stype="row_sparse")
            with autograd.record():
                # on-device csr×dense dot — no densification anywhere
                logits = sparse.dot(X, w).reshape((-1,))
                loss = nd.op.relu(logits) - logits * yn + \
                    nd.op.Activation(-nd.op.abs(logits), act_type="softrelu")
                loss = loss.mean()
            loss.backward()
            # w.grad is row_sparse: only this batch's features are present
            kv.push("weight", w.grad)
            pred = (logits.asnumpy() > 0).astype(np.float32)
            correct += int((pred == labels[sl]).sum())
        print(f"epoch {epoch}: accuracy {correct / batches:.3f} "
              f"({time.time() - t0:.1f}s)")


if __name__ == "__main__":
    main()
