"""Self-healing fleet supervisor (parallel/supervisor.py + tools/launch.py
--supervise): the table-driven escalation-ladder proofs over the pure
``decide`` function, capacity models, the supervisor-consumable
flight-record schema (stable ``absent_rank``/``hung_since`` + parse
helper, pinned against a PR 12-layout fixture AND a live ``_dump_flight``
round-trip), the launcher exit-code taxonomy, the crash-loop/budget
termination drill (jax-free stub workers — bounded, never an infinite
relaunch), and the acceptance chaos soak: a real supervised 2-worker
fleet surviving a scripted rank kill, hung collective and graceful
resize with zero human intervention, the union-of-trained-samples and
loss-trajectory contracts intact.

Marker ``supervisor``."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import supervisor as sv
from mxnet_tpu.telemetry import collective as coll

pytestmark = pytest.mark.supervisor

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE = os.path.join(ROOT, "tests", "data",
                       "coll_flight_pr12_fixture.json")

# every decide() call below pins the knobs explicitly so the table is
# hermetic to the environment
KNOBS = dict(max_restarts=8, crash_window_s=300.0, crash_limit=3)


def _ev(kind, rank=None, t=0.0, ranks=None):
    e = {"kind": kind, "rank": rank, "time": t}
    if ranks is not None:
        e["ranks"] = ranks
    return e


# ----------------------------------------------------------- env knobs

def test_supervise_knobs_strict_parse(monkeypatch):
    monkeypatch.setenv("MXTPU_SUPERVISE_MAX_RESTARTS", "5")
    assert sv.supervise_max_restarts() == 5
    monkeypatch.setenv("MXTPU_SUPERVISE_CRASH_WINDOW_S", "12.5")
    assert sv.supervise_crash_window_s() == 12.5
    monkeypatch.setenv("MXTPU_SUPERVISE_CRASH_LIMIT", "2")
    assert sv.supervise_crash_limit() == 2
    for name, fn in (
            ("MXTPU_SUPERVISE_MAX_RESTARTS", sv.supervise_max_restarts),
            ("MXTPU_SUPERVISE_CRASH_WINDOW_S",
             sv.supervise_crash_window_s),
            ("MXTPU_SUPERVISE_CRASH_LIMIT", sv.supervise_crash_limit)):
        monkeypatch.setenv(name, "yolo")
        with pytest.raises(MXNetError, match=name):
            fn()
        monkeypatch.delenv(name)
    monkeypatch.setenv("MXTPU_SUPERVISE_MAX_RESTARTS", "-1")
    with pytest.raises(MXNetError, match="MXTPU_SUPERVISE_MAX_RESTARTS"):
        sv.supervise_max_restarts()
    monkeypatch.setenv("MXTPU_SUPERVISE_CRASH_LIMIT", "0")
    with pytest.raises(MXNetError, match="MXTPU_SUPERVISE_CRASH_LIMIT"):
        sv.supervise_crash_limit()


def test_classify_exit_taxonomy():
    assert sv.classify_exit(0) == "ok"
    assert sv.classify_exit(75) == "resumable"
    assert sv.classify_exit(-9) == "signal"
    assert sv.classify_exit(-15) == "signal"
    assert sv.classify_exit(1) == "fatal"
    assert sv.classify_exit(137) == "fatal"
    with pytest.raises(MXNetError):
        sv.classify_exit(None)


# -------------------------------------- the escalation ladder, by table

LADDER = [
    # (id, events, world, knob overrides, expected action subset)
    ("flake_retries",
     [_ev("flake", 0)], 2, {}, {"op": "retry"}),
    ("flake_even_after_incidents",
     [_ev("crash", 1, 0.0), _ev("flake", 0, 1.0)], 2, {},
     {"op": "retry"}),
    ("single_crash_shrinks",
     [_ev("crash", 1, 0.0)], 2, {},
     {"op": "shrink", "world": 1, "lost": [1]}),
    ("hang_shrinks_absent_rank",
     [_ev("hang", 0, 0.0, ranks=[0])], 3, {},
     {"op": "shrink", "world": 2, "lost": [0]}),
    ("multi_rank_death_shrinks_by_all",
     [_ev("crash", 0, 0.0, ranks=[0, 2])], 4, {},
     {"op": "shrink", "world": 2, "lost": [0, 2]}),
    ("whole_group_death_relaunches_at_floor",
     [_ev("crash", 0, 0.0, ranks=[0, 1])], 2, {},
     {"op": "shrink", "world": 1}),
    ("resumable_resumes_same_world",
     [_ev("resumable")], 2, {}, {"op": "resume", "world": 2}),
    ("crash_loop_excludes_slot",
     [_ev("crash", 1, t) for t in (0.0, 10.0, 20.0)], 2,
     {"crash_limit": 3}, {"op": "exclude", "rank": 1, "world": 1}),
    ("crash_loop_window_expired_shrinks",
     [_ev("crash", 1, t) for t in (0.0, 10.0, 1000.0)], 2,
     {"crash_limit": 3, "crash_window_s": 300.0},
     {"op": "shrink", "world": 1}),
    ("crashes_of_different_ranks_shrink",
     [_ev("crash", 0, 0.0), _ev("crash", 1, 10.0)], 2,
     {"crash_limit": 2}, {"op": "shrink", "world": 1}),
    ("budget_exhausted_fails",
     [_ev("crash", 1, float(t)) for t in range(4)], 2,
     {"max_restarts": 3, "crash_limit": 99}, {"op": "fail"}),
    ("budget_counts_resumables",
     [_ev("resumable"), _ev("resumable"), _ev("resumable")], 2,
     {"max_restarts": 2}, {"op": "fail"}),
    ("budget_ignores_flakes",
     [_ev("flake", 0, float(t)) for t in range(10)] +
     [_ev("crash", 1, 11.0)], 2,
     {"max_restarts": 1}, {"op": "shrink", "world": 1}),
    ("budget_outranks_crash_loop",
     [_ev("crash", 1, float(t)) for t in range(5)], 3,
     {"max_restarts": 2, "crash_limit": 3}, {"op": "fail"}),
    ("exclude_below_floor_fails",
     [_ev("crash", 0, t) for t in (0.0, 1.0, 2.0)], 1,
     {"crash_limit": 3}, {"op": "fail"}),
]


@pytest.mark.parametrize("events,world,over,want",
                         [c[1:] for c in LADDER],
                         ids=[c[0] for c in LADDER])
def test_decide_ladder(events, world, over, want):
    got = sv.decide(events, world=world, floor=1, **{**KNOBS, **over})
    for k, v in want.items():
        assert got[k] == v, (got, want)


def test_decide_rejects_garbage():
    with pytest.raises(MXNetError, match="empty"):
        sv.decide([], world=2, **KNOBS)
    with pytest.raises(MXNetError, match="unknown event kind"):
        sv.decide([_ev("meteor", 0)], world=2, **KNOBS)


# ------------------------------------------------------ capacity models

def test_capacity_models():
    s = sv.StaticCapacity(4)
    assert s.available(0.0) == s.available(1e9) == 4
    m = sv.SpotCapacityModel(3, recovery_s=10.0)
    assert m.available(0.0) == 3
    m.note_lost(2, 100.0)
    assert m.available(105.0) == 1   # both slots still out
    assert m.available(110.0) == 3   # recovered
    m.note_lost(1, 200.0)
    assert m.available(205.0) == 2
    with pytest.raises(MXNetError):
        sv.SpotCapacityModel(0)


# ------------------------------- flight-record schema (supervisor view)

def test_parse_flight_record_pr12_fixture():
    """The PR 12 on-disk layout (no ``hung_since``) keeps parsing: old
    dumps on a crashed fleet's disk must stay supervisor-readable."""
    rec = coll.parse_flight_record(FIXTURE)
    assert rec["absent_rank"] == 0
    assert rec["rank"] == 1 and rec["pid"] == 41873
    assert rec["hung_since"] is None          # pre-PR-17 record
    assert rec["hung"][0]["seq"] == 7


def test_parse_flight_record_rejects_non_flight(tmp_path):
    p = tmp_path / "coll_flight_bogus.json"
    p.write_text(json.dumps({"reason": "oom"}))
    with pytest.raises(MXNetError, match="not 'hung_collective'"):
        coll.parse_flight_record(str(p))
    p.write_text("{not json")
    with pytest.raises(MXNetError, match="unreadable"):
        coll.parse_flight_record(str(p))


def test_live_dump_roundtrips_through_parser(monkeypatch, tmp_path):
    """Producer<->consumer pin: a record written by the REAL
    ``_dump_flight`` carries top-level ``absent_rank`` + ``hung_since``
    and round-trips through ``parse_flight_record`` — schema drift on
    either side fails here."""
    monkeypatch.setenv("MXTPU_MEM_DUMP_DIR", str(tmp_path))
    t = time.perf_counter() - 7.0
    path = coll.ledger._dump_flight(
        [{"kind": "push", "key": "_gbkt_0", "seq": 3, "bytes": 64,
          "rank": 1, "waiting_for": 0, "t_enter": t}], 5.0)
    rec = coll.parse_flight_record(path)
    assert rec["absent_rank"] == 0
    assert rec["hung_since"] == pytest.approx(coll.ledger.epoch_of(t))
    assert rec["hung"][0]["waiting_for_rank"] == 0

    seen = set()
    recs = coll.scan_flight_records(str(tmp_path), seen)
    assert [r["path"] for r in recs] == [path] and path in seen
    assert coll.scan_flight_records(str(tmp_path), seen) == []  # consumed


# --------------------------------------- launcher exit-code taxonomy

def _load_launch():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "launch_mod", os.path.join(ROOT, "tools", "launch.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_launch_wait_group_taxonomy():
    """Satellite: _wait_group distinguishes resumable / fatal / signal.
    A resumable exit must NOT fail-fast-kill the draining peers; a
    fatal one must. The group verdict carries the distinction."""
    launch = _load_launch()
    assert launch._classify_exit(0) == "ok"
    assert launch._classify_exit(75) == "resumable"
    assert launch._classify_exit(-9) == "signal"
    assert launch._classify_exit(3) == "fatal"

    def popen(code, delay=0.0):
        return subprocess.Popen(
            [sys.executable, "-c",
             f"import time,sys; time.sleep({delay}); sys.exit({code})"])

    # all ok -> 0
    assert launch._wait_group([(0, popen(0)), (1, popen(0))]) == 0
    # one resumable + one slow-ok: peers NOT killed, verdict = 75
    slow = popen(0, delay=1.0)
    assert launch._wait_group([(0, popen(75)), (1, slow)]) == 75
    assert slow.returncode == 0, "draining peer was killed"
    # fatal kills the group and wins over a resumable
    hang = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(60)"])
    rc = launch._wait_group([(0, popen(3)), (1, popen(75)), (2, hang)])
    assert rc == 3
    assert hang.wait(timeout=10) != 0, "fatal death did not kill peers"


# ------------------------- crash loop + budget: bounded, loud, bundled

def test_supervisor_crash_loop_budget_terminates(tmp_path, capsys):
    """A fleet whose workers ALWAYS crash must terminate by the ladder
    (shrink -> crash-loop exclude/floor -> budget fail), never relaunch
    forever, and leave the forensic bundle. Stub workers are jax-free:
    the whole drill is seconds."""
    spawned = []

    def spawn(world, gen, extra):
        spawned.append((gen, world))
        return {r: subprocess.Popen([sys.executable, "-c",
                                     "import sys; sys.exit(3)"])
                for r in range(world)}

    from mxnet_tpu.telemetry import default_registry
    before = getattr(default_registry().get(
        "mxtpu_supervisor_restarts_total"), "value", 0)
    sup = sv.Supervisor(spawn, 2, state_dir=str(tmp_path),
                        dump_dir=str(tmp_path / "dumps"),
                        max_restarts=3, crash_window_s=300.0,
                        crash_limit=3, term_grace_s=0.5, floor=1)
    t0 = time.monotonic()
    rc = sup.run()
    assert rc == 1
    assert time.monotonic() - t0 < 60
    # bounded: every failure relaunch is budgeted and every grow needs
    # a preceding shrink, so generations <= 2*budget + 2 — never an
    # infinite relaunch loop
    assert len(spawned) <= 2 * 3 + 2
    assert sup.restarts <= 3
    after = default_registry().get("mxtpu_supervisor_restarts_total")
    assert after is not None and after.value - before == sup.restarts

    out = capsys.readouterr().out
    summary = json.loads(out.split("SUPERVISOR_SUMMARY ", 1)[1])
    assert summary["ok"] is False
    assert [e["kind"] for e in summary["events"]].count("crash") >= 2
    bundle = summary["forensics"]
    assert bundle and os.path.isdir(bundle)
    with open(os.path.join(bundle, "events.json")) as f:
        dumped = json.load(f)
    assert dumped["summary"]["reason"]
    assert os.path.exists(os.path.join(bundle, "manifest.json")) or \
        os.path.exists(os.path.join(bundle, "MANIFEST.txt"))


def test_supervisor_excludes_crash_looping_slot(tmp_path):
    """Rung 3 in-process: when one slot crash-loops while the rest of
    the fleet is healthy, the supervisor EXCLUDES it and continues
    smaller instead of burning the whole budget on it."""
    # slot 1 crashes whenever it exists (a bad host); every other rank
    # is healthy: drains resumable on SIGTERM, finishes clean otherwise.
    # With the default StaticCapacity the supervisor grows straight
    # back after the first shrink — putting the bad slot back in play,
    # which is exactly what the crash-loop rung must then stop.
    crash = "import time,sys; time.sleep(0.1); sys.exit(3)"
    healthy = ("import signal,sys,time;"
               "signal.signal(signal.SIGTERM, lambda *a: sys.exit(75));"
               "time.sleep(1.5); sys.exit(0)")

    def spawn(world, gen, extra):
        return {r: subprocess.Popen(
                    [sys.executable, "-c", crash if r == 1 else healthy])
                for r in range(world)}

    sup = sv.Supervisor(spawn, 2, state_dir=str(tmp_path),
                        dump_dir=str(tmp_path / "dumps"),
                        max_restarts=8, crash_window_s=300.0,
                        crash_limit=2, term_grace_s=2.0, floor=1)
    rc = sup.run()
    assert rc == 0
    kinds = [e["kind"] for e in sup.events]
    assert all(k == "crash" for k in kinds) and len(kinds) == 2
    assert sup.excluded == [1], (sup.excluded, sup.events)
    # after the exclusion the fleet ran (and finished) at world 1
    assert sup.generations[-1]["world"] == 1
    assert sup.generations[-1]["outcome"] == "done"
    assert sup.grows >= 1


# ----------------------------------------------- the chaos soak (tentpole)

def test_selfheal_chaos_soak(tmp_path):
    """Acceptance: a supervised 2-worker fleet survives three scripted
    chaos events — rank kill, hung collective (kv_hang + watchdog
    flight record), graceful resize — with ZERO human intervention:
    auto-shrink to the survivor, auto-grow back when the spot capacity
    model recovers, run to completion. The union of trained samples
    equals the no-failure stream exactly and the per-step summed loss
    trajectory matches a never-failed fixed-global-batch reference.
    ``restarts`` in the supervisor summary equals the injected event
    count (grows are free)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "selfheal_worker",
        os.path.join(ROOT, "tests", "dist", "selfheal_worker.py"))
    sw = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sw)

    out = str(tmp_path)
    dumps = os.path.join(out, "dumps")
    os.makedirs(dumps)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one cpu device per process
    env.pop("MXTPU_CHAOS", None)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "MXTPU_ZERO": "1",
        "MXTPU_OPTIMIZER_AGGREGATION": "8",
        "MXTPU_COLL_TIMEOUT_S": "1",
        "MXTPU_MEM_DUMP_DIR": dumps,
        "MXTPU_COORD_TIMEOUT_MS": "20000",
        "MXTPU_SUPERVISE_MAX_RESTARTS": "6",
        "SELFHEAL_OUT_DIR": out,
        "SELFHEAL_TARGET": "2",
        "SELFHEAL_STEP_SLEEP_MS": "500",
        "SELFHEAL_EVENTS": json.dumps({
            "0": {"kind": "kill", "rank": 1, "offset": 2},
            "2": {"kind": "kv_hang", "rank": 0, "offset": 2},
            "4": {"kind": "resize", "world": 2, "offset": 2},
        }),
    })
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", "2", "--launcher", "local",
         "--coordinator", "127.0.0.1:12700",
         "--supervise",
         "--supervise-ckpt", os.path.join(out, "ckpt_r0"),
         "--supervise-dir", out,
         "--supervise-grace", "2", "--supervise-recovery", "2.5",
         sys.executable,
         os.path.join(ROOT, "tests", "dist", "selfheal_worker.py")],
        capture_output=True, text=True, timeout=240, env=env, cwd=ROOT)
    text = proc.stdout + proc.stderr
    assert proc.returncode == 0, text[-4000:]

    summary = json.loads(
        text.split("SUPERVISOR_SUMMARY ", 1)[1].split("\n", 1)[0])
    assert summary["ok"] is True
    # mxtpu_supervisor_restarts_total == injected chaos events
    assert summary["restarts"] == 3, summary
    assert [e["kind"] for e in summary["events"]] == \
        ["crash", "hang", "resumable"], summary["events"]
    # each shrink was followed by a capacity-driven grow back to target
    assert summary["grows"] == 2, summary
    assert summary["final_world"] == 2
    assert summary["excluded"] == []
    # the hang event named the withholding rank from the flight record
    hang = summary["events"][1]
    assert hang["ranks"] == [0], hang

    # ---- never-failed reference: world 1, same fixed global batch G
    # and sum loss -> world-independent trajectory
    import mxnet_tpu as mx
    from mxnet_tpu import fit, gluon, io
    for k in ("MXTPU_ZERO", "MXTPU_ZERO_WORLD", "MXTPU_ELASTIC"):
        os.environ.pop(k, None)
    X, Y = sw.make_data()
    mx.random.seed(0)
    net = gluon.nn.Dense(1, in_units=3)
    net.initialize(mx.init.Constant(0.25))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=None)
    it = io.NDArrayIter(X, Y, batch_size=sw.G, shuffle=True, seed=sw.SEED)
    loop = fit.FitLoop(net, tr, lambda o, y: ((o - y) ** 2).sum(), it,
                       ckpt_dir=None, heartbeat=False, seed=sw.SEED)
    ref = loop.fit(epochs=sw.EPOCHS, batch_size=sw.G)
    total_steps = (sw.N // sw.G) * sw.EPOCHS
    assert ref.step == total_steps

    ref_stream = []
    rit = io.NDArrayIter(X, Y, batch_size=sw.G, shuffle=True, seed=sw.SEED)
    for ep in range(sw.EPOCHS):
        rit.set_epoch(ep)
        for bt in rit:
            ref_stream += sw.batch_ids(bt.data[0].asnumpy())

    # ---- union proof: every step's ids, across all ranks of all
    # generations, equals the no-failure stream — zero dup, zero drop
    consumed = []
    per_step = {}
    logs = [n for n in os.listdir(out) if n.startswith("steps_r")]
    assert logs, text[-2000:]
    for name in logs:
        with open(os.path.join(out, name)) as f:
            for line in f:
                rec = json.loads(line)
                consumed += rec["ids"]
                per_step[rec["step"]] = \
                    per_step.get(rec["step"], 0.0) + rec["loss"]
    assert sorted(consumed) == sorted(ref_stream)
    assert len(consumed) == len(ref_stream) == sw.N * sw.EPOCHS

    # ---- trajectory contract: per-step summed loss across however
    # many ranks trained that step == the never-failed reference
    assert sorted(per_step) == list(range(total_steps))
    np.testing.assert_allclose(
        [per_step[s] for s in range(total_steps)], ref.losses,
        rtol=1e-4, atol=1e-6)

    # ---- final weights from the last generation agree with reference
    dec = json.JSONDecoder()
    done = [dec.raw_decode(chunk.lstrip())[0]
            for chunk in text.split("SELFHEAL_DONE ")[1:]]
    final_gen = max(d["gen"] for d in done)
    finals = [d for d in done if d["gen"] == final_gen]
    assert sorted(d["rank"] for d in finals) == [0, 1]
    for d in finals:
        np.testing.assert_allclose(
            np.asarray(d["weight"]),
            net.weight.data().asnumpy().ravel(), rtol=1e-5, atol=1e-7)

    # the hung-collective evidence is on disk: at least one flight
    # record in the dump dir names rank 0 absent
    recs = coll.scan_flight_records(dumps)
    assert any(r["absent_rank"] == 0 for r in recs), recs
