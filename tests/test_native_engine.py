"""Native dependency-engine tests (model: tests/cpp/engine/
threaded_engine_test.cc — randomized dependency workloads verified against
expected ordering)."""
import random
import threading
import time

import pytest

from mxnet_tpu.engine import NativeEngine
from mxnet_tpu.io.record_io import native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native lib not built")


def test_write_write_ordering():
    eng = NativeEngine(num_workers=4)
    v = eng.new_var()
    log = []
    lock = threading.Lock()
    for i in range(50):
        def fn(i=i):
            with lock:
                log.append(i)
        eng.push(fn, write_vars=[v])
    eng.wait_all()
    assert log == list(range(50)), "writes on one var must serialize in order"
    assert eng.var_version(v) == 50
    eng.close()


def test_readers_between_writes():
    eng = NativeEngine(num_workers=4)
    v = eng.new_var()
    state = {"x": 0}
    seen = []
    lock = threading.Lock()

    def writer(val):
        def fn():
            time.sleep(0.001)
            state["x"] = val
        return fn

    def reader():
        def fn():
            with lock:
                seen.append(state["x"])
        return fn

    eng.push(writer(1), write_vars=[v])
    for _ in range(8):
        eng.push(reader(), read_vars=[v])
    eng.push(writer(2), write_vars=[v])
    for _ in range(8):
        eng.push(reader(), read_vars=[v])
    eng.wait_all()
    assert seen[:8] == [1] * 8
    assert seen[8:] == [2] * 8
    eng.close()


def test_independent_vars_run_concurrently():
    eng = NativeEngine(num_workers=4)
    vs = [eng.new_var() for _ in range(4)]
    barrier = threading.Barrier(4, timeout=5)
    ok = []

    def fn():
        barrier.wait()  # passes only if 4 tasks run concurrently
        ok.append(1)

    for v in vs:
        eng.push(fn, write_vars=[v])
    eng.wait_all()
    assert len(ok) == 4
    eng.close()


def test_randomized_dependency_chains():
    """Random ops over random var subsets; verify per-var write order and
    read-after-write visibility (the threaded_engine_test.cc pattern)."""
    eng = NativeEngine(num_workers=8)
    rng = random.Random(0)
    n_vars = 6
    vars_ = [eng.new_var() for _ in range(n_vars)]
    counters = [0] * n_vars
    observed = []
    lock = threading.Lock()

    expected = [0] * n_vars
    for _ in range(200):
        k = rng.randint(1, 3)
        targets = rng.sample(range(n_vars), k)
        if rng.random() < 0.5:
            def fn(ts=tuple(targets)):
                with lock:
                    for t in ts:
                        counters[t] += 1
            eng.push(fn, write_vars=[vars_[t] for t in targets])
            for t in targets:
                expected[t] += 1
        else:
            def fn(ts=tuple(targets)):
                with lock:
                    observed.append(tuple(counters[t] for t in ts))
            eng.push(fn, read_vars=[vars_[t] for t in targets])
    eng.wait_all()
    assert counters == expected
    eng.close()


def test_wait_for_var_version():
    eng = NativeEngine(num_workers=2)
    v = eng.new_var()
    for i in range(10):
        eng.push(lambda: time.sleep(0.001), write_vars=[v])
    eng.wait_for_var(v, version=10)
    assert eng.var_version(v) == 10
    eng.close()
