"""Native dependency-engine tests (model: tests/cpp/engine/
threaded_engine_test.cc — randomized dependency workloads verified against
expected ordering)."""
import random
import threading
import time

import pytest

from mxnet_tpu.engine import NativeEngine
from mxnet_tpu.io.record_io import native_available

pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native lib not built")


def test_write_write_ordering():
    eng = NativeEngine(num_workers=4)
    v = eng.new_var()
    log = []
    lock = threading.Lock()
    for i in range(50):
        def fn(i=i):
            with lock:
                log.append(i)
        eng.push(fn, write_vars=[v])
    eng.wait_all()
    assert log == list(range(50)), "writes on one var must serialize in order"
    assert eng.var_version(v) == 50
    eng.close()


def test_readers_between_writes():
    eng = NativeEngine(num_workers=4)
    v = eng.new_var()
    state = {"x": 0}
    seen = []
    lock = threading.Lock()

    def writer(val):
        def fn():
            time.sleep(0.001)
            state["x"] = val
        return fn

    def reader():
        def fn():
            with lock:
                seen.append(state["x"])
        return fn

    eng.push(writer(1), write_vars=[v])
    for _ in range(8):
        eng.push(reader(), read_vars=[v])
    eng.push(writer(2), write_vars=[v])
    for _ in range(8):
        eng.push(reader(), read_vars=[v])
    eng.wait_all()
    assert seen[:8] == [1] * 8
    assert seen[8:] == [2] * 8
    eng.close()


def test_independent_vars_run_concurrently():
    eng = NativeEngine(num_workers=4)
    vs = [eng.new_var() for _ in range(4)]
    barrier = threading.Barrier(4, timeout=5)
    ok = []

    def fn():
        barrier.wait()  # passes only if 4 tasks run concurrently
        ok.append(1)

    for v in vs:
        eng.push(fn, write_vars=[v])
    eng.wait_all()
    assert len(ok) == 4
    eng.close()


def test_randomized_dependency_chains():
    """Random ops over random var subsets; verify per-var write order and
    read-after-write visibility (the threaded_engine_test.cc pattern)."""
    eng = NativeEngine(num_workers=8)
    rng = random.Random(0)
    n_vars = 6
    vars_ = [eng.new_var() for _ in range(n_vars)]
    counters = [0] * n_vars
    observed = []
    lock = threading.Lock()

    expected = [0] * n_vars
    for _ in range(200):
        k = rng.randint(1, 3)
        targets = rng.sample(range(n_vars), k)
        if rng.random() < 0.5:
            def fn(ts=tuple(targets)):
                with lock:
                    for t in ts:
                        counters[t] += 1
            eng.push(fn, write_vars=[vars_[t] for t in targets])
            for t in targets:
                expected[t] += 1
        else:
            def fn(ts=tuple(targets)):
                with lock:
                    observed.append(tuple(counters[t] for t in ts))
            eng.push(fn, read_vars=[vars_[t] for t in targets])
    eng.wait_all()
    assert counters == expected
    eng.close()


def test_wait_for_var_version():
    eng = NativeEngine(num_workers=2)
    v = eng.new_var()
    for i in range(10):
        eng.push(lambda: time.sleep(0.001), write_vars=[v])
    eng.wait_for_var(v, version=10)
    assert eng.var_version(v) == 10
    eng.close()


def test_image_record_iter_uses_engine_and_overlaps(tmp_path):
    """The iterator decodes batch k+1 while the consumer works on batch k
    (ref: iter_prefetcher.h:47). Proof: with a consumer that sleeps
    per batch, total wall time ~= consumer time, not consumer + decode."""
    import time
    import numpy as np
    from mxnet_tpu import io as mxio, recordio

    rec = tmp_path / "d.rec"
    rs = np.random.RandomState(0)
    writer = recordio.MXRecordIO(str(rec), "w")
    for i in range(24):
        img = rs.randint(0, 255, (64, 64, 3), np.uint8)
        writer.write(recordio.pack_img(
            recordio.IRHeader(0, float(i % 10), i, 0), img, quality=95))
    writer.close()

    it = mxio.ImageRecordIter(path_imgrec=str(rec), data_shape=(3, 32, 32),
                              batch_size=8, resize=32,
                              preprocess_threads=4)
    assert it._engine is not None, "native engine must drive the iterator"
    consume = 0.05
    t0 = time.perf_counter()
    n = 0
    for b in it:
        time.sleep(consume)  # the training step
        n += 1
    wall = time.perf_counter() - t0
    assert n == 3
    # serial would be n*(consume + decode); proof of prefetch is that the
    # engine had the next batch ready: generous bound at 3x consume + 1
    # decode's worth of slack
    it2 = mxio.ImageRecordIter(path_imgrec=str(rec),
                               data_shape=(3, 32, 32), batch_size=8,
                               resize=32, preprocess_threads=4)
    t1 = time.perf_counter()
    for _ in it2:
        pass
    decode_total = time.perf_counter() - t1
    assert wall < n * consume + decode_total / n + 0.25, \
        (wall, decode_total)


def test_async_checkpoint_write(tmp_path):
    """CheckpointManager(async_write=True): save() returns before the
    files exist; wait()/steps() fence; contents match a sync write; the
    snapshot is taken at save() time (later mutations don't leak in)."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import fault, nd

    cm = fault.CheckpointManager(str(tmp_path), max_keep=2,
                                 async_write=True)
    assert cm._engine is not None
    w = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    params = {"w": w}
    cm.save(1, params)
    # mutate AFTER scheduling: the checkpoint must hold the old value
    w += 100.0
    cm.save(2, params)
    assert cm.steps() == [1, 2]  # steps() waits for the writes
    step, loaded, meta = cm.restore(1)
    np.testing.assert_array_equal(
        loaded["w"].asnumpy(),
        np.arange(6, dtype=np.float32).reshape(2, 3))
    step2, loaded2, _ = cm.restore(2)
    np.testing.assert_array_equal(
        loaded2["w"].asnumpy(),
        np.arange(6, dtype=np.float32).reshape(2, 3) + 100.0)


def test_async_checkpoint_resume_with_trainer(tmp_path):
    """Async checkpoints restore bit-exactly including optimizer state."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, fault, gluon, nd

    def make():
        net = gluon.nn.Dense(2, use_bias=False)
        net.initialize(mx.init.Constant(1.0))
        with autograd.pause():
            net(nd.ones((1, 3)))
        return net

    def step(net, tr, x, y):
        with autograd.record():
            loss = ((net(x) - y) ** 2).mean()
        loss.backward()
        tr.step(x.shape[0])

    rs = np.random.RandomState(0)
    x = nd.array(rs.randn(8, 3).astype(np.float32))
    y = nd.array(rs.randn(8, 2).astype(np.float32))
    net_a = make()
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    for _ in range(2):
        step(net_a, tr_a, x, y)
    cm = fault.CheckpointManager(str(tmp_path), async_write=True)
    cm.save(2, net=net_a, trainer=tr_a)
    for _ in range(2):
        step(net_a, tr_a, x, y)  # keep training while the write lands

    net_b = make()
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    resumed = cm.restore_latest(net=net_b, trainer=tr_b)
    assert resumed is not None and resumed[0] == 2
    for _ in range(2):
        step(net_b, tr_b, x, y)
    for (_, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(), rtol=1e-6)


def test_cpp_native_unit_tests():
    """The tests/cpp analog: build and run the assert-based C++ unit
    tests over the engine + recordio C ABIs (make -C src test)."""
    import os
    import subprocess
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(["make", "-C", os.path.join(root, "src"), "test"],
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "ALL NATIVE TESTS PASSED" in r.stdout
