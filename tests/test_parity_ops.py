"""Reference-inventory parity ops (ops/parity_ops.py): init ops reachable
from symbol graphs, the _random_*_like family, _grad_add,
_contrib_div_sqrt_dim, and the csr-container registry identities.
Ref: src/operator/tensor/init_op.cc, src/operator/random/sample_op.cc:210,
src/operator/tensor/elemwise_binary_op_basic.cc:105,
src/operator/contrib/transformer.cc:33."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def test_init_ops_imperative():
    assert nd.op.zeros(shape=(2, 3)).asnumpy().sum() == 0
    assert nd.op.ones(shape=(4,)).asnumpy().sum() == 4
    np.testing.assert_allclose(nd.op.full(shape=(2, 2), value=7).asnumpy(),
                               np.full((2, 2), 7.0))
    np.testing.assert_allclose(nd.op.eye(N=3).asnumpy(), np.eye(3))
    np.testing.assert_allclose(nd.op.arange(start=2.0, stop=6.0).asnumpy(),
                               np.arange(2.0, 6.0))
    # reference arange(stop-only) form
    np.testing.assert_allclose(nd.op.arange(start=4.0).asnumpy(),
                               np.arange(4.0))


def test_init_ops_symbolic():
    # sym.zeros exists and evaluates inside a graph (the VERDICT item)
    z = sym.zeros(shape=(2, 2))
    out = (z + 1.0).eval()
    np.testing.assert_allclose(out[0].asnumpy(), np.ones((2, 2)))
    e = sym.eye(N=3)
    np.testing.assert_allclose(e.eval()[0].asnumpy(), np.eye(3))


@pytest.mark.parametrize("op,params", [
    ("_random_uniform_like", dict(low=-1.0, high=1.0)),
    ("_random_normal_like", dict(loc=0.0, scale=2.0)),
    ("_random_exponential_like", dict(lam=2.0)),
    ("_random_gamma_like", dict(alpha=2.0, beta=1.0)),
    ("_random_poisson_like", dict(lam=3.0)),
    ("_random_negative_binomial_like", dict(k=3, p=0.5)),
    ("_random_generalized_negative_binomial_like", dict(mu=2.0, alpha=0.3)),
])
def test_random_like_family(op, params):
    x = nd.zeros((200, 5))
    fn = getattr(nd.op, op)
    out = fn(x, **params)
    assert out.shape == x.shape
    vals = out.asnumpy()
    assert np.isfinite(vals).all()
    # distribution sanity (loose, seeded by the global stream)
    if op == "_random_uniform_like":
        assert -1.0 <= vals.min() and vals.max() <= 1.0
    if op == "_random_exponential_like":
        assert vals.min() >= 0 and abs(vals.mean() - 0.5) < 0.15
    if op == "_random_poisson_like":
        assert abs(vals.mean() - 3.0) < 0.5
    # also exposed under mx.nd.random (reference namespace routing)
    assert hasattr(nd.random, op[len("_random_"):])


def test_grad_add_and_div_sqrt_dim():
    a, b = nd.array(np.ones((2, 2))), nd.array(np.full((2, 2), 2.0))
    np.testing.assert_allclose(nd.op._grad_add(a, b).asnumpy(), 3.0)
    x = nd.array(np.ones((2, 16), np.float32))
    np.testing.assert_allclose(nd.op._contrib_div_sqrt_dim(x).asnumpy(),
                               0.25, rtol=1e-6)


def test_sample_unique_zipfian():
    s, c = nd.op._sample_unique_zipfian(range_max=10000, shape=(64,))
    sv = s.asnumpy()
    assert sv.shape == (64,) and (sv >= 0).all() and (sv < 10000).all()
    # zipfian mass concentrates at small ids
    assert np.median(sv) < 1000
    assert (c.asnumpy() > 0).all()


def test_container_ops_registered_and_dispatch():
    from mxnet_tpu.ndarray import sparse
    from mxnet_tpu.ops import registry as reg
    for name in ("_contrib_edge_id", "_contrib_getnnz", "_sparse_retain",
                 "_contrib_dgl_adjacency", "_contrib_dgl_subgraph",
                 "_contrib_dgl_csr_neighbor_uniform_sample",
                 "_contrib_dgl_csr_neighbor_non_uniform_sample",
                 "_contrib_dgl_graph_compact"):
        assert name in reg.list_ops()
    csr = sparse.csr_matrix(np.eye(5, dtype=np.float32))
    assert int(nd.op._contrib_getnnz(csr).asnumpy()) == 5
    # dense invocation errors with guidance rather than silently wrong
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        nd.op._contrib_getnnz(nd.ones((3, 3)))
    # retain through the registry identity
    rsp = sparse.row_sparse_array(np.diag([1.0, 2.0, 3.0]).astype(np.float32))
    out = nd.op._sparse_retain(rsp, nd.array(np.array([0.0, 2.0])))
    assert sorted(np.asarray(out._indices)) == [0, 2]


def test_alias_names_exist():
    from mxnet_tpu.ops import registry as reg
    for n in ("_histogram", "_ravel_multi_index", "_unravel_index"):
        assert n in reg.list_ops()
