"""tools/ scripts (ref: tools/parse_log.py, tools/bandwidth/measure.py)."""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LOG = """INFO Epoch[0] Batch [20] Speed: 1234.5 samples/sec
INFO Epoch[0] Train-accuracy=0.61
INFO Epoch[0] Time cost=12.3
INFO Epoch[0] Validation-accuracy=0.58
INFO Epoch[1] Batch [20] Speed: 1300.0 samples/sec
INFO Epoch[1] Batch [40] Speed: 1310.0 samples/sec
INFO Epoch[1] Train-cross-entropy=1.9
INFO Epoch[1] Train-accuracy=0.72
INFO Epoch[1] Validation-accuracy=0.69
INFO Epoch[1] Time cost=11.9
"""


def _run_parse(tmp_path, *args):
    log = tmp_path / "train.log"
    log.write_text(LOG)
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "parse_log.py"),
         str(log), *args], capture_output=True, text=True, timeout=60)


def test_parse_log_markdown(tmp_path):
    r = _run_parse(tmp_path)
    assert r.returncode == 0, r.stderr
    assert "train-accuracy" in r.stdout
    assert "1305.0" in r.stdout  # averaged speedometer lines
    assert "12.3" in r.stdout    # time cost


def test_parse_log_json_and_metric(tmp_path):
    r = _run_parse(tmp_path, "--format", "json")
    rows = json.loads(r.stdout)
    assert rows[1]["train"]["cross-entropy"] == "1.9"
    assert rows[1]["speed"] == pytest.approx(1305.0)
    r = _run_parse(tmp_path, "--metric", "cross-entropy")
    assert "train-cross-entropy" in r.stdout


def test_bandwidth_model_shapes():
    """The gradient-shaped workload sweep runs on the test mesh and
    reports per-tensor metadata (measure.py's real-model mode)."""
    from tools.bandwidth import _model_grad_shapes, _measure_shapes
    from mxnet_tpu.parallel import make_mesh
    shapes = _model_grad_shapes("alexnet")
    assert len(shapes) >= 10  # conv + fc params
    mesh = make_mesh({"dp": 8})
    bw, mb = _measure_shapes(mesh, "dp", shapes[:4], iters=2)
    assert bw > 0 and mb > 0


# ---------------------------------------------------------------------------
# tools/trace_report.py — offline chrome-trace reader (autotune PR)
# ---------------------------------------------------------------------------

def _synthetic_trace():
    """Two steps with nested spans + an autotune probe/decision, in
    chrome-trace object format (ts/dur in us)."""
    ev = []

    def span(name, cat, ts, dur, tid=1):
        ev.append({"name": name, "cat": cat, "ph": "X", "ts": ts,
                   "dur": dur, "pid": 7, "tid": tid, "args": {}})

    ev.append({"name": "step:0", "cat": "step", "ph": "i", "ts": 1000,
               "pid": 7, "tid": 1, "s": "t", "args": {}})
    span("fwd_bwd", "compute", 1000, 900)
    span("bucket0", "comm_overlapped", 1400, 400)   # nested in compute
    span("allreduce", "comm", 1950, 50)
    ev.append({"name": "step:1", "cat": "step", "ph": "i", "ts": 2000,
               "pid": 7, "tid": 1, "s": "t", "args": {}})
    span("fwd_bwd", "compute", 2000, 800)
    span("probe:overlap=1", "autotune", 2000, 900)
    # a warmup probe span (measured=False): the tuner excluded it from
    # its scores, the offline reader must too
    ev.append({"name": "probe:overlap=1", "cat": "autotune", "ph": "X",
               "ts": 900, "dur": 5000, "pid": 7, "tid": 1,
               "args": {"measured": False}})
    ev.append({"name": 'autotune:lock {"chosen": {"overlap": 1}}',
               "cat": "autotune", "ph": "i", "ts": 2950, "pid": 7,
               "tid": 1, "s": "t", "args": {}})
    return {"traceEvents": ev}


def _run_trace_report(tmp_path, payload, *args):
    trace = tmp_path / "trace.json"
    trace.write_text(json.dumps(payload))
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_report.py"),
         str(trace), *args], capture_output=True, text=True, timeout=60)


def test_trace_report_exclusive_nesting_and_decision(tmp_path):
    r = _run_trace_report(tmp_path, _synthetic_trace(), "--json")
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout)
    steps = {row["step"]: row for row in out["steps"]}
    assert set(steps) == {"0", "1"}
    # EXCLUSIVE accounting: the 400us comm_overlapped span nested inside
    # the 900us compute span is charged once — compute keeps 500us
    assert steps["0"]["segments"]["comm_overlapped"] == 400.0
    assert steps["0"]["segments"]["compute"] == 500.0
    assert steps["0"]["segments"]["comm"] == 50.0
    # the tuner's footprint survives the round trip
    assert out["autotune"]["probes"]["overlap=1"]["steps"] == 1
    assert out["autotune"]["decision"] == {"chosen": {"overlap": 1}}


def test_trace_report_kv_spans_under_overlap_charge_overlapped(tmp_path):
    """kvstore wire spans (cat 'comm') nested inside a comm_overlapped
    bracket are HIDDEN communication: live, the overlap scheduler charges
    the whole launch to comm_overlapped and the kv tracer spans never
    touch the breakdown — so the offline reconstruction must relabel
    them, or the innermost-span rule would report hidden comm as exposed,
    the exact inversion of what the run measured."""
    ev = {"traceEvents": [
        {"name": "step:0", "cat": "step", "ph": "i", "ts": 1000,
         "pid": 7, "tid": 1, "s": "t", "args": {}},
        {"name": "fwd_bwd", "cat": "compute", "ph": "X", "ts": 1000,
         "dur": 900, "pid": 7, "tid": 1, "args": {}},
        {"name": "bucket0", "cat": "comm_overlapped", "ph": "X",
         "ts": 1200, "dur": 500, "pid": 7, "tid": 1, "args": {}},
        {"name": "kv_push:_gbkt0", "cat": "comm", "ph": "X", "ts": 1210,
         "dur": 240, "pid": 7, "tid": 1, "args": {}},
        {"name": "kv_pull:_gbkt0", "cat": "comm", "ph": "X", "ts": 1455,
         "dur": 230, "pid": 7, "tid": 1, "args": {}},
        # an exposed straggler AFTER backward keeps its own category
        {"name": "kv_push:3", "cat": "comm", "ph": "X", "ts": 1910,
         "dur": 60, "pid": 7, "tid": 1, "args": {}},
    ]}
    r = _run_trace_report(tmp_path, ev, "--json")
    assert r.returncode == 0, r.stderr
    segs = json.loads(r.stdout)["steps"][0]["segments"]
    # the whole 500us launch bracket (30us overhead + 470us wire) is
    # overlapped; only the straggler stays exposed comm
    assert segs["comm_overlapped"] == 500.0
    assert segs["comm"] == 60.0
    assert segs["compute"] == 400.0


def test_trace_report_human_table(tmp_path):
    r = _run_trace_report(tmp_path, _synthetic_trace())
    assert r.returncode == 0, r.stderr
    assert "comm_overlapped" in r.stdout and "share" in r.stdout
    assert "autotune decision" in r.stdout
    # bad input: clean error, distinct exit code, nothing on stdout
    bad = tmp_path / "bad.json"
    bad.write_text("not json")
    r2 = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_report.py"),
         str(bad)], capture_output=True, text=True, timeout=60)
    assert r2.returncode == 2 and "trace_report" in r2.stderr


def test_trace_report_reads_live_fit_dump(tmp_path):
    """End-to-end: a traced FitLoop run with the autotuner on dumps a
    chrome trace that the offline tool reads back — per-step segment
    rows, probe spans, and the lock decision."""
    import numpy as np
    import mxnet_tpu as mx
    from mxnet_tpu import gluon, io as mxio, telemetry
    from mxnet_tpu import kvstore as kv_mod
    from mxnet_tpu.fit import FitLoop
    from mxnet_tpu.telemetry import dump_chrome_trace

    mx.random.seed(0)
    rs = np.random.RandomState(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(16, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    it = mxio.NDArrayIter(rs.randn(96, 16).astype(np.float32),
                          rs.randint(0, 4, (96,)).astype(np.float32),
                          batch_size=16)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01},
                            kvstore=kv_mod.create("device"))
    os.environ["MXTPU_AUTOTUNE"] = "on,probe=1,warmup=0,knobs=overlap"
    telemetry.enable()
    try:
        FitLoop(net, trainer, gluon.loss.SoftmaxCrossEntropyLoss(), it,
                ckpt_dir=None).fit(epochs=1)
        trace = tmp_path / "live.json"
        dump_chrome_trace(str(trace))
    finally:
        telemetry.disable()
        telemetry.tracer.clear()
        os.environ.pop("MXTPU_AUTOTUNE", None)
    r = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "trace_report.py"),
         str(trace), "--json"], capture_output=True, text=True, timeout=60)
    assert r.returncode == 0, r.stderr
    out = json.loads(r.stdout)
    assert len(out["steps"]) >= 6
    assert any("compute" in row["segments"] for row in out["steps"])
    assert out["autotune"]["probes"], "probe spans missing from trace"
    assert out["autotune"]["decision"] is not None
    assert out["autotune"]["decision"]["chosen"]["overlap"] in (0, 1)
