"""tools/ scripts (ref: tools/parse_log.py, tools/bandwidth/measure.py)."""
import json
import os
import subprocess
import sys

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

LOG = """INFO Epoch[0] Batch [20] Speed: 1234.5 samples/sec
INFO Epoch[0] Train-accuracy=0.61
INFO Epoch[0] Time cost=12.3
INFO Epoch[0] Validation-accuracy=0.58
INFO Epoch[1] Batch [20] Speed: 1300.0 samples/sec
INFO Epoch[1] Batch [40] Speed: 1310.0 samples/sec
INFO Epoch[1] Train-cross-entropy=1.9
INFO Epoch[1] Train-accuracy=0.72
INFO Epoch[1] Validation-accuracy=0.69
INFO Epoch[1] Time cost=11.9
"""


def _run_parse(tmp_path, *args):
    log = tmp_path / "train.log"
    log.write_text(LOG)
    return subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "parse_log.py"),
         str(log), *args], capture_output=True, text=True, timeout=60)


def test_parse_log_markdown(tmp_path):
    r = _run_parse(tmp_path)
    assert r.returncode == 0, r.stderr
    assert "train-accuracy" in r.stdout
    assert "1305.0" in r.stdout  # averaged speedometer lines
    assert "12.3" in r.stdout    # time cost


def test_parse_log_json_and_metric(tmp_path):
    r = _run_parse(tmp_path, "--format", "json")
    rows = json.loads(r.stdout)
    assert rows[1]["train"]["cross-entropy"] == "1.9"
    assert rows[1]["speed"] == pytest.approx(1305.0)
    r = _run_parse(tmp_path, "--metric", "cross-entropy")
    assert "train-cross-entropy" in r.stdout


def test_bandwidth_model_shapes():
    """The gradient-shaped workload sweep runs on the test mesh and
    reports per-tensor metadata (measure.py's real-model mode)."""
    from tools.bandwidth import _model_grad_shapes, _measure_shapes
    from mxnet_tpu.parallel import make_mesh
    shapes = _model_grad_shapes("alexnet")
    assert len(shapes) >= 10  # conv + fc params
    mesh = make_mesh({"dp": 8})
    bw, mb = _measure_shapes(mesh, "dp", shapes[:4], iters=2)
    assert bw > 0 and mb > 0
