"""The Perl binding (perl_package/AI-MXNetTPU) — VERDICT r3 directive #4:
prove the flat C API hosts a NON-C++ language binding. Builds the XS
module with ExtUtils::MakeMaker, exports a LeNet from the Python side,
then drives imperative invoke + a C-callback custom op + LeNet predict
from Perl (ref: the reference's perl-package/AI-MXNet over the same ABI).
"""
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "perl_package", "AI-MXNetTPU")


@pytest.fixture(scope="module")
def native_libs():
    for name in ("libmxtpu_capi.so", "libmxtpu_predict.so"):
        lib = os.path.join(ROOT, "src", name)
        if not os.path.exists(lib):
            subprocess.run(["make", "-C", os.path.join(ROOT, "src"), name],
                           check=False, capture_output=True, timeout=300)
        if not os.path.exists(lib):
            pytest.skip(f"{name} not built")
    return True


@pytest.fixture(scope="module")
def perl():
    exe = shutil.which("perl")
    if exe is None:
        pytest.skip("perl not on PATH")
    probe = subprocess.run(
        [exe, "-MExtUtils::MakeMaker", "-e", "1"], capture_output=True)
    if probe.returncode != 0:
        pytest.skip("ExtUtils::MakeMaker unavailable")
    return exe


@pytest.fixture(scope="module")
def built_module(perl, native_libs):
    env = dict(os.environ)
    gen = subprocess.run([perl, "Makefile.PL"], cwd=PKG,
                         capture_output=True, text=True, timeout=120,
                         env=env)
    assert gen.returncode == 0, gen.stderr + gen.stdout
    build = subprocess.run(["make"], cwd=PKG, capture_output=True,
                           text=True, timeout=300, env=env)
    assert build.returncode == 0, build.stderr[-3000:] + build.stdout[-1500:]
    return PKG


@pytest.fixture(scope="module")
def lenet_model(tmp_path_factory):
    """Export a LeNet (conv-pool-conv-pool-fc-fc, the classic 28x28
    digit net) for the Perl predict leg."""
    import mxnet_tpu as mx
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(6, kernel_size=5, padding=2, activation="tanh"),
            nn.AvgPool2D(pool_size=2, strides=2),
            nn.Conv2D(16, kernel_size=5, activation="tanh"),
            nn.AvgPool2D(pool_size=2, strides=2),
            nn.Flatten(),
            nn.Dense(120, activation="tanh"),
            nn.Dense(84, activation="tanh"),
            nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = mx.nd.array(np.random.RandomState(0)
                    .rand(1, 1, 28, 28).astype(np.float32))
    with autograd.pause():
        y = net(x)
    d = tmp_path_factory.mktemp("perl_lenet")
    prefix = str(d / "lenet")
    net.export(prefix)
    return prefix


def test_perl_binding_end_to_end(perl, built_module, lenet_model):
    env = dict(os.environ)
    env["MXTPU_HOME"] = ROOT
    env.setdefault("JAX_PLATFORMS", "cpu")
    run = subprocess.run(
        [perl, "-Mblib", os.path.join(PKG, "t", "smoke.pl"), lenet_model],
        cwd=PKG, capture_output=True, text=True, timeout=600, env=env)
    out = run.stdout + run.stderr
    assert run.returncode == 0, out[-4000:]
    assert "perl imperative ok" in out
    assert "perl custom op ok" in out
    assert "perl lenet predict ok" in out
    assert "PERL_BINDING_OK" in out


def test_perl_ndarray_api(perl, built_module):
    """The idiomatic surface: generated op methods (codegen from
    MXSymbolListAtomicSymbolCreators), overloaded arithmetic, autograd
    record/backward with an analytically-known gradient."""
    script = r"""
use strict; use warnings;
use AI::MXNetTPU;
use AI::MXNetTPU::NDArray;
use AI::MXNetTPU::AutoGrad qw(record);

die "too few generated ops" if $AI::MXNetTPU::NDArray::NUM_GENERATED_OPS < 300;

my $a = AI::MXNetTPU::NDArray->new([2, 2], [1, 2, 3, 4]);
my $b = AI::MXNetTPU::NDArray->new([2, 2], [10, 20, 30, 40]);
my $s = ($a + $b)->aslist;
die "add @$s" unless "@$s" eq "11 22 33 44";
my $m = ($a * 2)->aslist;
die "mul_scalar @$m" unless "@$m" eq "2 4 6 8";
my $r = (1 / $a)->aslist;   # swapped scalar op -> _rdiv_scalar
die "rdiv @$r" unless abs($r->[1] - 0.5) < 1e-6;
# generated method with kwargs
my $sm = $a->sum(axis => '(1,)')->aslist;
die "sum @$sm" unless "@$sm" eq "3 7";

# autograd: d/dx sum(x*x) = 2x
my $x = AI::MXNetTPU::NDArray->new([3], [1, 2, 3])->attach_grad;
my $y = record { ($x * $x)->sum };
$y->backward;
my $g = $x->grad->aslist;
die "grad @$g" unless "@$g" eq "2 4 6";
print "PERL_NDARRAY_OK\n";
"""
    env = dict(os.environ)
    env["MXTPU_HOME"] = ROOT
    env.setdefault("JAX_PLATFORMS", "cpu")
    run = subprocess.run([perl, "-Mblib", "-e", script], cwd=PKG,
                         capture_output=True, text=True, timeout=600,
                         env=env)
    out = run.stdout + run.stderr
    assert run.returncode == 0, out[-4000:]
    assert "PERL_NDARRAY_OK" in out


def test_perl_mnist_training_converges(perl, built_module):
    """VERDICT r4 directive #5: a SECOND-LANGUAGE training loop — MLP on
    glyph digits trained purely from Perl (generated FullyConnected /
    Activation / log_softmax / pick methods, autograd record/backward,
    in-place sgd_mom_update through preallocated-output invoke) must
    converge; the script exits nonzero below 90% held-out accuracy."""
    env = dict(os.environ)
    env["MXTPU_HOME"] = ROOT
    env.setdefault("JAX_PLATFORMS", "cpu")
    run = subprocess.run(
        [perl, "-Mblib", os.path.join(PKG, "t", "train_mnist.pl")],
        cwd=PKG, capture_output=True, text=True, timeout=600, env=env)
    out = run.stdout + run.stderr
    assert run.returncode == 0, out[-4000:]
    assert "test accuracy" in out
