"""tools/im2rec.py round trip (ref: tools/im2rec.py + test_recordio)."""
import os
import subprocess
import sys

import numpy as np
import pytest

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_im2rec_roundtrip(tmp_path):
    cv2 = pytest.importorskip("cv2")
    rs = np.random.RandomState(0)
    for cls in ("cat", "dog"):
        os.makedirs(tmp_path / "imgs" / cls)
        for i in range(3):
            cv2.imwrite(str(tmp_path / "imgs" / cls / f"{i}.jpg"),
                        rs.randint(0, 255, (16, 16, 3), np.uint8))
    prefix = str(tmp_path / "data")
    r = subprocess.run([sys.executable,
                        os.path.join(_ROOT, "tools", "im2rec.py"),
                        prefix, str(tmp_path / "imgs")],
                       capture_output=True, text=True, cwd=_ROOT)
    assert r.returncode == 0, r.stderr[-500:]
    assert os.path.exists(prefix + ".lst")
    assert os.path.exists(prefix + ".rec")
    from mxnet_tpu.io import ImageRecordIter
    it = ImageRecordIter(path_imgrec=prefix + ".rec",
                         data_shape=(3, 8, 8), batch_size=6, resize=8)
    b = next(it)
    assert b.data[0].shape == (6, 3, 8, 8)
    labels = sorted(set(b.label[0].asnumpy().tolist()))
    assert labels == [0.0, 1.0]
    it.close()
