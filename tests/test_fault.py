"""Failure-detection + checkpoint/restart tests (SURVEY §5.3 analog of
tests around ps-lite GetDeadNodes / model_backwards_compatibility_check)."""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault, gluon, nd, autograd
from mxnet_tpu.base import MXNetError


def test_heartbeat_and_dead_nodes(tmp_path):
    d = str(tmp_path)
    hb0 = fault.Heartbeat(d, rank=0, interval=0.2)
    hb1 = fault.Heartbeat(d, rank=1, interval=0.2)
    with hb0, hb1:
        time.sleep(0.5)
        assert fault.dead_nodes(d, timeout=5.0) == []
    # stop rank 1's beats and backdate its file -> reported dead
    os.utime(os.path.join(d, "heartbeat-1"),
             (time.time() - 100, time.time() - 100))
    # utime doesn't change the content; rewrite with an old stamp instead
    with open(os.path.join(d, "heartbeat-1"), "w") as f:
        f.write(str(time.time() - 100))
    assert fault.dead_nodes(d, timeout=30.0) == [1]
    assert fault.dead_nodes(d, timeout=1000.0) == []


def test_heartbeat_read_write_race(tmp_path):
    """dead_nodes must never see a half-written stamp: hammer beat() and
    dead_nodes() concurrently — with non-atomic writes the reader catches
    a truncated file, parses the stamp as 0 and reports the rank dead."""
    d = str(tmp_path)
    hb = fault.Heartbeat(d, rank=0, interval=10.0)
    hb.beat()
    stop = [False]
    import threading

    def writer():
        while not stop[0]:
            hb.beat()

    t = threading.Thread(target=writer)
    t.start()
    try:
        for _ in range(2000):
            assert fault.dead_nodes(d, timeout=30.0) == []
    finally:
        stop[0] = True
        t.join()


def test_is_recovery_env(monkeypatch):
    monkeypatch.delenv("MXNET_IS_RECOVERY", raising=False)
    assert not fault.is_recovery()
    monkeypatch.setenv("MXNET_IS_RECOVERY", "1")
    assert fault.is_recovery()


@pytest.mark.parametrize("raw", ["0", "", "false", "False"])
def test_is_recovery_falsy_spellings(monkeypatch, raw):
    """Routed through the declared bool registry (graftcheck GC-E01): the
    old direct read treated the literal string "False" as TRUTHY, so a
    relauncher exporting MXNET_IS_RECOVERY=False sent a fresh node down
    the restore-from-checkpoint path."""
    monkeypatch.setenv("MXNET_IS_RECOVERY", raw)
    assert not fault.is_recovery(), f"raw={raw!r} must read as falsy"


def _make_net():
    net = gluon.nn.Dense(2, use_bias=False)
    net.initialize(mx.init.Constant(1.0))
    with autograd.pause():
        net(nd.ones((1, 3)))
    return net


def _step(net, trainer, x, y):
    with autograd.record():
        loss = ((net(x) - y) ** 2).mean()
    loss.backward()
    trainer.step(x.shape[0])
    return float(loss.asnumpy())


def test_checkpoint_resume_exact(tmp_path):
    """A killed-and-restarted run resumes bit-identically from the
    checkpoint (momentum state included)."""
    rs = np.random.RandomState(0)
    x = nd.array(rs.randn(8, 3).astype(np.float32))
    y = nd.array(rs.randn(8, 2).astype(np.float32))

    # run A: 4 steps straight through
    net_a = _make_net()
    tr_a = gluon.Trainer(net_a.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    for _ in range(4):
        _step(net_a, tr_a, x, y)

    # run B: 2 steps, checkpoint, "crash", restore into fresh objects,
    # 2 more steps
    cm = fault.CheckpointManager(str(tmp_path), max_keep=2)
    net_b = _make_net()
    tr_b = gluon.Trainer(net_b.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    for _ in range(2):
        _step(net_b, tr_b, x, y)
    cm.save(2, net=net_b, trainer=tr_b)

    net_c = _make_net()
    tr_c = gluon.Trainer(net_c.collect_params(), "sgd",
                         {"learning_rate": 0.1, "momentum": 0.9})
    resumed = cm.restore_latest(net=net_c, trainer=tr_c)
    assert resumed is not None and resumed[0] == 2
    for _ in range(2):
        _step(net_c, tr_c, x, y)

    for (ka, pa), (kc, pc) in zip(sorted(net_a.collect_params().items()),
                                  sorted(net_c.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pc.data().asnumpy(), rtol=1e-6)


def test_checkpoint_prune_and_incomplete(tmp_path):
    cm = fault.CheckpointManager(str(tmp_path), max_keep=2)
    net = _make_net()
    params = {k: p.data() for k, p in net.collect_params().items()}
    for s in (1, 2, 3):
        cm.save(s, params)
    assert cm.steps() == [2, 3]  # pruned to max_keep
    # partially-written checkpoint (no DONE) is invisible
    broken = os.path.join(str(tmp_path), "ckpt-9")
    os.makedirs(broken)
    assert cm.latest() == 3
    import pytest
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        cm.restore(9)


def test_fresh_start_returns_none(tmp_path):
    cm = fault.CheckpointManager(str(tmp_path))
    assert cm.restore_latest() is None


def _params_of(net):
    return {k: p.data() for k, p in net.collect_params().items()}


def test_crash_mid_write_is_never_restored(tmp_path, monkeypatch):
    """A writer killed between the params write and the DONE marker leaves
    a checkpoint that is never listed nor restored (atomic tmp+rename)."""
    cm = fault.CheckpointManager(str(tmp_path))
    params = _params_of(_make_net())
    cm.save(1, params)
    with monkeypatch.context() as m:
        def die(*a, **k):
            raise RuntimeError("simulated crash mid-write")
        m.setattr(fault.json, "dump", die)  # fires before manifest/DONE
        with pytest.raises(RuntimeError):
            cm.save(2, params)
    assert cm.steps() == [1]
    restored = cm.restore_latest()
    assert restored is not None and restored[0] == 1


def test_truncated_params_with_forged_done_is_quarantined(tmp_path):
    """DONE claims complete but the payload is truncated: the manifest
    check catches it, restore_latest quarantines ckpt-2 -> ckpt-2.bad and
    falls back to the previous verified checkpoint."""
    cm = fault.CheckpointManager(str(tmp_path), max_keep=3)
    net = _make_net()
    cm.save(1, net=net)
    net.weight.set_data(nd.ones((2, 3)) * 7)
    cm.save(2, net=net)
    p2 = os.path.join(str(tmp_path), "ckpt-2", "params")
    with open(p2, "rb") as f:
        blob = f.read()
    with open(p2, "wb") as f:
        f.write(blob[:len(blob) // 2])  # truncate, DONE stays forged
    with pytest.raises(fault.CheckpointCorruptError):
        cm.verify(2)
    restored = cm.restore_latest()
    assert restored is not None and restored[0] == 1
    np.testing.assert_allclose(restored[1]["weight"].asnumpy(),
                               np.ones((2, 3)), rtol=1e-6)
    assert os.path.isdir(os.path.join(str(tmp_path), "ckpt-2.bad"))
    assert cm.steps() == [1]


def test_corrupt_in_place_detected_and_all_bad_returns_none(tmp_path):
    """Same-size byte flips (no truncation) still fail the sha256 check;
    when every checkpoint is corrupt, restore_latest quarantines them all
    and reports a fresh start instead of restoring garbage."""
    from mxnet_tpu.contrib import chaos
    cm = fault.CheckpointManager(str(tmp_path), max_keep=3)
    params = _params_of(_make_net())
    for s in (1, 2):
        cm.save(s, params)
        chaos.corrupt_file(os.path.join(str(tmp_path), f"ckpt-{s}",
                                        "params"))
    assert cm.restore_latest() is None
    assert cm.steps() == []
    assert os.path.isdir(os.path.join(str(tmp_path), "ckpt-1.bad"))
    assert os.path.isdir(os.path.join(str(tmp_path), "ckpt-2.bad"))


def test_restore_strict_both_directions(tmp_path):
    """Checkpoint keys missing from the net already raise; net parameters
    absent from the checkpoint must be loud too (they would silently keep
    their current values) unless allow_missing=True."""
    cm = fault.CheckpointManager(str(tmp_path))
    slim = _make_net()  # Dense(2, use_bias=False): weight only
    cm.save(1, net=slim)

    wide = gluon.nn.Dense(2)  # weight + bias
    wide.initialize(mx.init.Constant(0.5))
    with autograd.pause():
        wide(nd.ones((1, 3)))
    wide.bias.set_data(nd.ones((2,)) * 0.5)  # bias init default is zeros
    before = {k: p.data().asnumpy().copy()
              for k, p in wide.collect_params().items()}
    with pytest.raises(MXNetError, match="absent from checkpoint"):
        cm.restore(1, net=wide)
    for k, p in wide.collect_params().items():
        np.testing.assert_array_equal(
            p.data().asnumpy(), before[k],
            err_msg="a failed restore must leave the net untouched")
    # opt-out accepts the partial restore: weight loaded, bias kept
    cm.restore(1, net=wide, allow_missing=True)
    np.testing.assert_allclose(wide.weight.data().asnumpy(),
                               np.ones((2, 3)), rtol=1e-6)
    np.testing.assert_allclose(wide.bias.data().asnumpy(),
                               np.full((2,), 0.5), rtol=1e-6)

    # the pre-existing direction: checkpoint key unknown to the net
    cm.save(2, net=wide)
    with pytest.raises(MXNetError, match="not found in net"):
        cm.restore(2, net=_make_net())


def test_async_save_then_hard_exit_is_complete_or_absent(tmp_path):
    """An async save() followed by immediate process death must leave
    either a fully verified checkpoint or nothing restorable — never a
    half-written one that restore would trust."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = (
        "import os, sys\n"
        f"sys.path.insert(0, {repo!r})\n"
        "os.environ.setdefault('JAX_PLATFORMS', 'cpu')\n"
        "from mxnet_tpu import fault, nd\n"
        "cm = fault.CheckpointManager(sys.argv[1], async_write=True)\n"
        "cm.save(1, {'w': nd.ones((128, 128))})\n"
        "os._exit(0)\n"  # die with the write possibly in flight
    )
    r = subprocess.run([sys.executable, "-c", script, str(tmp_path)],
                       capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, r.stderr[-2000:]
    cm = fault.CheckpointManager(str(tmp_path))
    steps = cm.steps()
    assert steps in ([], [1])
    for s in steps:
        cm.verify(s)  # whatever survived must verify end to end
        restored = cm.restore(s)
        np.testing.assert_allclose(restored[1]["w"].asnumpy(),
                                   np.ones((128, 128)))


def test_heartbeat_restart_and_numeric_order(tmp_path):
    d = str(tmp_path)
    hb = fault.Heartbeat(d, rank=0, interval=0.2)
    hb.start()
    hb.stop()
    hb.start()  # restart must resume beating
    time.sleep(0.6)
    hb.stop()
    with open(os.path.join(d, "heartbeat-0")) as f:
        last = float(f.read())
    assert time.time() - last < 5.0, "no beats after restart"
    # numeric rank ordering with >= 10 ranks
    for r in (0, 2, 10, 11, 1):
        with open(os.path.join(d, f"heartbeat-{r}"), "w") as f:
            f.write(str(time.time() - 100))
    assert fault.dead_nodes(d, timeout=30.0) == [0, 1, 2, 10, 11]


def test_trainer_param_order_stable_across_name_counter():
    """Positional optimizer-state indices (checkpoint slots, kvstore keys)
    derive from the Trainer's parameter order, and gluon layer names embed
    a process-global counter: ``dense10_*`` < ``dense8_*`` under a plain
    lexicographic sort, so a run checkpointed at one counter value and
    resumed at another bound restored momentum to the WRONG parameters
    (kill/resume straddling the dense9 -> dense10 boundary). The order
    must be numeric-aware and therefore identical for structurally equal
    nets regardless of where the counter sits."""
    def order(p1, p2):
        net = gluon.nn.HybridSequential()
        net.add(gluon.nn.Dense(8, activation="relu", prefix=p1),
                gluon.nn.Dense(1, prefix=p2))
        net.initialize(mx.init.Xavier())
        with autograd.pause():
            net(nd.ones((1, 4)))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9},
                           kvstore=None)
        return [tuple(p.data().shape) for p in tr._params]

    straddling = order("dense9_", "dense10_")
    plain = order("dense11_", "dense12_")
    assert straddling == plain, (straddling, plain)
