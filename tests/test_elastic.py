"""Elastic world-size training (parallel/elastic.py): topology records
in every checkpoint, resize@N[:M] chaos, cross-world resume (re-formed
group, re-derived ZeRO partition, re-split seeded data stream, reset
comm state), named-error raise paths, and the NDArrayIter shard-union
proofs — no duplicated, no dropped sample across 1→2, 2→3 and 4→2.

Marker ``elastic`` (tier-1-safe: CPU, simulated worlds in-process; the
real 2↔3-process drill lives in tests/dist/elastic_worker.py)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault, fit, gluon, io, nd
from mxnet_tpu import kvstore as kvs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import chaos
from mxnet_tpu.parallel import elastic

pytestmark = pytest.mark.elastic

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ----------------------------------------------------------- env parsing

def test_elastic_flag_strict_parse(monkeypatch):
    monkeypatch.delenv("MXTPU_ELASTIC", raising=False)
    assert elastic.elastic_enabled() is False
    for v in ("on", "1", "true"):
        monkeypatch.setenv("MXTPU_ELASTIC", v)
        assert elastic.elastic_enabled() is True
    for v in ("off", "0", "false", ""):
        monkeypatch.setenv("MXTPU_ELASTIC", v)
        assert elastic.elastic_enabled() is False
    monkeypatch.setenv("MXTPU_ELASTIC", "yolo")
    with pytest.raises(MXNetError, match="MXTPU_ELASTIC"):
        elastic.elastic_enabled()


def test_resize_grammar():
    plan = chaos.ChaosPlan("resize@5:3")
    assert plan._resize == {5: 3}
    plan = chaos.ChaosPlan("resize@7")
    assert plan._resize == {7: None}
    for bad in ("resize", "resize@x", "resize@5:0", "resize@5:x",
                "resize:0.5@5"):
        with pytest.raises(MXNetError):
            chaos.ChaosPlan(bad)


def test_resize_target_consume_once():
    plan = chaos.ChaosPlan("resize@2:4")
    plan.begin_step(1)
    assert plan.resize_target() is None
    plan.begin_step(2)
    assert plan.resize_target() == {"world": 4}
    assert plan.resize_target() is None  # consumed
    assert plan.injected["resize"] == 1


# ------------------------------------------------- NDArrayIter sharding

def _id_data(n):
    """Feature value IS the sample id — batches become traceable."""
    return np.arange(n, dtype=np.float32).reshape(n, 1)


def _ids(batch):
    return batch.data[0].asnumpy().ravel().astype(int).tolist()


def test_ndarrayiter_shard_basics():
    n, G, P = 48, 12, 3
    b = G // P
    its = [io.NDArrayIter(_id_data(n), batch_size=b, shuffle=True,
                          seed=9, num_parts=P, part_index=r)
           for r in range(P)]
    ref = io.NDArrayIter(_id_data(n), batch_size=G, shuffle=True, seed=9)
    ref_steps = [_ids(bt) for bt in ref]
    streams = [[_ids(bt) for bt in it] for it in its]
    # every rank steps the same count (no desync on data), and the
    # rank-order concatenation of each local step IS the unsharded
    # global batch, elementwise
    assert len({len(s) for s in streams}) == 1
    assert len(streams[0]) == len(ref_steps) == n // G
    for t, window in enumerate(ref_steps):
        got = sum((streams[r][t] for r in range(P)), [])
        assert got == window
    for it in its:
        assert it.getpad() == 0
    with pytest.raises(MXNetError):
        io.NDArrayIter(_id_data(8), batch_size=2, num_parts=2,
                       part_index=2)


def test_ndarrayiter_world1_unchanged():
    """num_parts=1 must be byte-identical to the historical iterator."""
    a = io.NDArrayIter(_id_data(10), batch_size=4, shuffle=True, seed=3)
    b = io.NDArrayIter(_id_data(10), batch_size=4, shuffle=True, seed=3,
                       num_parts=1, part_index=0)
    sa = [( _ids(x), x.pad) for x in a]
    sb = [( _ids(x), x.pad) for x in b]
    assert sa == sb and sa[-1][1] == 2  # wraparound pad preserved


def test_set_position_rejects_midgroup_offset():
    it = io.NDArrayIter(_id_data(48), batch_size=4, shuffle=True, seed=1,
                        num_parts=3, part_index=0)
    with pytest.raises(MXNetError, match="set_position"):
        it.set_position(0, 10)  # not a multiple of 12
    it.set_position(0, 24)  # group boundary: fine
    assert _ids(next(it)) == _ids_of_order(48, 1, 0)[24:28]


def _ids_of_order(n, seed, epoch):
    return np.random.RandomState(seed + epoch).permutation(n).tolist()


@pytest.mark.parametrize("w_from,w_to", [(1, 2), (2, 3), (4, 2)])
def test_iter_resplit_union_exact(w_from, w_to):
    """THE re-split proof: k global steps at world N, then the recorded
    global position re-split across world M — the union of every rank's
    stream (pre + post) equals the never-resized stream exactly: zero
    duplicated, zero dropped samples."""
    n, G, k, seed = 48, 12, 2, 11
    order = _ids_of_order(n, seed, 0)
    pre, post = [], []
    for r in range(w_from):
        it = io.NDArrayIter(_id_data(n), batch_size=G // w_from,
                            shuffle=True, seed=seed,
                            num_parts=w_from, part_index=r)
        for _t in range(k):
            pre.append(_ids(next(it)))
    for r in range(w_to):
        it = io.NDArrayIter(_id_data(n), batch_size=G // w_to,
                            shuffle=True, seed=seed,
                            num_parts=w_to, part_index=r)
        it.set_position(0, k * G)  # the checkpointed global position
        post.append([_ids(bt) for bt in it])
    consumed = sum(pre, []) + sum((sum(s, []) for s in post), [])
    # multiset equality with the full no-resize stream: exact coverage
    assert sorted(consumed) == sorted(order)
    assert len(consumed) == len(set(consumed)) == n
    # and the pre-resize half is exactly the stream's first k*G samples
    assert sorted(sum(pre, [])) == sorted(order[:k * G])


def test_resplit_batches_math():
    topo = {"num_parts": 2, "batch_size": 6, "global_samples": 24}
    cur = {"num_parts": 3, "batch_size": 4}
    assert elastic.resplit_batches(topo, cur, restored_batches=2) == 2
    # unchanged layout: the restored local count passes through
    same = {"num_parts": 2, "batch_size": 6, "global_samples": 24}
    assert elastic.resplit_batches(
        same, {"num_parts": 2, "batch_size": 6}, 2) == 2
    # a position that does not split over the new stride raises
    bad = {"num_parts": 2, "batch_size": 5, "global_samples": 10}
    with pytest.raises(elastic.TopologyMismatchError, match="split"):
        elastic.resplit_batches(bad, {"num_parts": 3, "batch_size": 4}, 1)
    with pytest.raises(elastic.TopologyMismatchError, match="no global"):
        elastic.resplit_batches({"num_parts": 2, "batch_size": 6},
                                {"num_parts": 3, "batch_size": 4}, 1)


# ------------------------------------------------------ fit-chain pieces

def _zero_env(monkeypatch, world, elastic_on=False):
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "8")
    if world:
        monkeypatch.setenv("MXTPU_ZERO", "1")
        monkeypatch.setenv("MXTPU_ZERO_WORLD", str(world))
    else:
        monkeypatch.delenv("MXTPU_ZERO", raising=False)
        monkeypatch.delenv("MXTPU_ZERO_WORLD", raising=False)
    if elastic_on:
        monkeypatch.setenv("MXTPU_ELASTIC", "on")
    else:
        monkeypatch.delenv("MXTPU_ELASTIC", raising=False)


def _build(monkeypatch, world, ckpt_dir, elastic_on=False):
    """Deterministic momentum-SGD FitLoop under simulated-world ZeRO
    (the test_zero kill/resume recipe, grown a world knob)."""
    _zero_env(monkeypatch, world, elastic_on)
    mx.random.seed(0)
    net = gluon.nn.Dense(2, in_units=3)
    net.initialize(mx.init.Constant(0.5))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=kvs.create("local"))
    rs = np.random.RandomState(0)
    it = io.NDArrayIter(rs.rand(24, 3).astype(np.float32),
                        rs.rand(24, 2).astype(np.float32), batch_size=4,
                        shuffle=True, seed=7)
    loss = lambda out, y: ((out - y) ** 2).mean()
    return net, fit.FitLoop(net, tr, loss, it, ckpt_dir=ckpt_dir,
                            ckpt_every=2, async_ckpt=False,
                            heartbeat=False, seed=7)


def test_topology_record_in_meta(monkeypatch, tmp_path):
    """Every checkpoint's meta.json carries the topology record: world,
    shard layout, the world-independent global sample position, and the
    portable-states marker."""
    ck = str(tmp_path / "ck")
    _, loop = _build(monkeypatch, 2, ck)
    loop.fit(epochs=1)
    with open(os.path.join(ck, "ckpt-4", "meta.json")) as f:
        meta = json.load(f)
    topo = meta["topology"]
    assert topo["world"] == 2 and topo["rank"] == 0
    assert topo["distributed"] is False
    assert topo["num_parts"] == 1 and topo["part_index"] == 0
    assert topo["batch_size"] == 4
    assert topo["global_samples"] == topo["batches"] * 4
    assert topo["portable_states"] is True
    assert "resize_to" not in topo


def test_simulated_resize_e2e(monkeypatch, tmp_path):
    """THE acceptance chain, in-process: a world-2 run hit by
    resize@3:3 writes a final verified checkpoint (resize_to=3) and
    exits resumable; the world-3 relaunch re-forms the (simulated)
    group, re-derives the ZeRO partition at world 3 and reproduces the
    always-at-world-3 run's loss trajectory from the resize point
    BITWISE — the ZeRO parity discipline across worlds."""
    # always-at-new-size reference
    net_ref, loop_ref = _build(monkeypatch, 3, str(tmp_path / "ref"))
    res_ref = loop_ref.fit(epochs=2)
    assert res_ref.step == 12 and res_ref.elastic is None

    ck = str(tmp_path / "ck")
    chaos.install("resize@3:3")
    _, loop_a = _build(monkeypatch, 2, ck)
    with pytest.raises(SystemExit) as ei:
        loop_a.fit(epochs=2)
    assert ei.value.code == fit.resumable_exit_code() == 75
    assert chaos.active().injected["resize"] == 1
    chaos.uninstall()

    cm = fault.CheckpointManager(ck)
    assert cm.latest() == 3, "final checkpoint at the resize step"
    cm.verify(3)
    with open(os.path.join(ck, "ckpt-3", "meta.json")) as f:
        topo = json.load(f)["topology"]
    assert topo["world"] == 2 and topo["resize_to"] == 3

    # the relaunch harness honors resize_to: come back at world 3
    net_b, loop_b = _build(monkeypatch, 3, ck, elastic_on=True)
    res_b = loop_b.fit(epochs=2)
    assert res_b.resumed_from == 3 and res_b.step == 12
    assert res_b.elastic == {"from_world": 2, "world": 3, "rank": 0,
                             "members": [0, 1, 2], "resize_to": 3}
    assert res_b.zero and res_b.zero["world"] == 3
    # post-resize trajectory == the always-at-3 run's, bitwise
    np.testing.assert_array_equal(res_b.losses, res_ref.losses[3:])
    np.testing.assert_array_equal(net_b.weight.data().asnumpy(),
                                  net_ref.weight.data().asnumpy())


def test_same_world_batch_change_resplits(monkeypatch, tmp_path):
    """Review regression: a SAME-world resume whose data layout changed
    (here per-rank batch size 4 -> 6) must re-split from the recorded
    global sample position — replaying the raw local batch count would
    duplicate samples — and a position that does not divide the new
    stride raises instead of mis-positioning."""
    _zero_env(monkeypatch, 0)
    n = 24
    X = _id_data(n)
    Y = np.zeros((n, 1), np.float32)

    seen = []

    def build(bs, record=False):
        mx.random.seed(0)
        net = gluon.nn.Dense(1, in_units=1)
        net.initialize(mx.init.Constant(0.5))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.01}, kvstore=None)

        class Rec(io.NDArrayIter):
            def getdata(self):
                out = super().getdata()
                if record:
                    seen.append(out[0].asnumpy().ravel().astype(int)
                                .tolist())
                return out
        it = Rec(X, Y, batch_size=bs, shuffle=True, seed=5,
                 last_batch_handle="discard")
        loss = lambda o, y: ((o - y) ** 2).mean()
        return fit.FitLoop(net, tr, loss, it,
                           ckpt_dir=str(tmp_path / "ck"), ckpt_every=100,
                           async_ckpt=False, heartbeat=False, seed=5)

    chaos.install("kill@3")
    with pytest.raises(chaos.ChaosKilled):
        build(4).fit(epochs=1)  # ckpt? none yet — kill leaves nothing
    chaos.uninstall()
    # write the checkpoint via resize instead (graceful, ckpt at step 3)
    chaos.install("resize@3")
    with pytest.raises(SystemExit):
        build(4).fit(epochs=1)
    chaos.uninstall()

    # batch 8: 12 % 8 != 0 -> named error, never a silent mis-split
    # (checked FIRST: the successful resume below writes a newer
    # end-of-epoch checkpoint whose position trivially divides)
    with pytest.raises(elastic.TopologyMismatchError, match="split"):
        build(8).fit(epochs=1)

    # batch 6: global position 3*4=12 divides the new stride -> the
    # resume fast-forwards 2 local batches and trains order[12:] once
    res = build(6, record=True).fit(epochs=1)
    assert res.resumed_from == 3 and res.step == 3 + 2
    order = _ids_of_order(n, 5, 0)
    # set_position fast-forward is O(1): NO replay fetches — every
    # fetched batch is a trained one, and they are exactly order[12:]
    trained = sum(seen, [])
    assert trained == order[12:]


def test_cross_world_resume_requires_elastic_on(monkeypatch, tmp_path):
    """A world change without MXTPU_ELASTIC=on raises the named error
    (never a silent mis-split resume), and the intact checkpoint is NOT
    quarantined — an operator decision, not corruption."""
    ck = str(tmp_path / "ck")
    _, loop_a = _build(monkeypatch, 2, ck)
    loop_a.fit(epochs=1)
    _, loop_b = _build(monkeypatch, 3, ck, elastic_on=False)
    with pytest.raises(elastic.TopologyMismatchError,
                       match="MXTPU_ELASTIC"):
        loop_b.fit(epochs=2)
    assert os.path.isdir(os.path.join(ck, "ckpt-6"))
    assert not os.path.isdir(os.path.join(ck, "ckpt-6.bad"))
    # same world: resumes exactly as before, no elastic summary
    _, loop_c = _build(monkeypatch, 2, ck)
    res_c = loop_c.fit(epochs=2)
    assert res_c.resumed_from == 6 and res_c.elastic is None


def test_nonportable_sharded_artifact_rejected(monkeypatch, tmp_path):
    """Satellite acceptance: a checkpoint whose trainer states are NOT
    in the gather-on-save portable format must raise across a world
    change — even with MXTPU_ELASTIC=on — before any state loads."""
    monkeypatch.setenv("MXTPU_ELASTIC", "on")
    cm = fault.CheckpointManager(str(tmp_path / "ck"))
    cm.save(2, params={"w": nd.ones((2, 2))},
            extra={"topology": {"world": 2, "rank": 0, "num_parts": 1,
                                "part_index": 0, "batch_size": 4,
                                "global_samples": 8,
                                "portable_states": False}})
    cur = {"world": 3, "rank": 0, "distributed": False, "num_parts": 1,
           "part_index": 0, "batch_size": 4}
    guard = lambda meta: elastic.check_restore(meta.get("topology"), cur)
    with pytest.raises(elastic.TopologyMismatchError,
                       match="NON-portable"):
        cm.restore(2, meta_check=guard)
    with pytest.raises(elastic.TopologyMismatchError):
        cm.restore_latest(meta_check=guard)
    # rejected, not quarantined — and same-world restore still works
    assert cm.latest() == 2
    same = dict(cur, world=2)
    step, params, _meta = cm.restore(
        2, meta_check=lambda m: elastic.check_restore(
            m.get("topology"), same))
    assert step == 2 and "w" in params


def test_resize_without_ckpt_dir_raises(monkeypatch):
    chaos.install("resize@0:2")
    _, loop = _build(monkeypatch, 0, None)
    with pytest.raises(MXNetError, match="checkpoint dir"):
        loop.fit(epochs=1)
    chaos.uninstall()


def test_comm_state_reset_on_resize():
    from mxnet_tpu.telemetry import collective as coll
    from mxnet_tpu.telemetry.tracer import tracer as tr
    coll.ledger.clock_offset_ms = 123.0
    tr.clock_offset_ms = 123.0
    elastic.reset_comm_state()
    assert coll.ledger.clock_offset_ms == 0.0
    assert tr.clock_offset_ms == 0.0
    assert coll.health_summary().get("checks", 0) in (0, None)


def test_reform_group_simulated(monkeypatch):
    monkeypatch.setenv("MXTPU_ZERO_WORLD", "4")
    cur = elastic.current_topology()
    assert cur["world"] == 4 and not cur["distributed"]
    out = elastic.reform_group(cur)
    assert out == {"reformed": True, "members": [0, 1, 2, 3]}


# ----------------------------------------------- the 2->3-process drill

def test_elastic_two_to_three_process_drill(monkeypatch, tmp_path):
    """Acceptance, real process groups: a 2-rank dist_sync + ZeRO run is
    resized at step 3 by chaos ``resize@3:3`` (final checkpoint, exit
    75), relaunched as a 3-rank group that re-forms through the
    coordination service and re-splits the seeded stream — the summed
    post-resize loss trajectory matches an in-process never-resized
    reference (fixed global batch G, sum loss: the update is (1/G)·Σ∇
    at any world), the final weights agree, and the union of every
    rank's consumed samples across the resize equals the no-resize
    stream exactly (zero duplicated, zero dropped)."""
    import importlib.util
    import subprocess
    import sys
    spec = importlib.util.spec_from_file_location(
        "elastic_worker",
        os.path.join(ROOT, "tests", "dist", "elastic_worker.py"))
    worker = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(worker)
    make_data, EPOCHS, G, N, RESIZE_AT, SEED = (
        worker.make_data, worker.EPOCHS, worker.G, worker.N,
        worker.RESIZE_AT, worker.SEED)

    out = str(tmp_path)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one cpu device per process
    env.update({"JAX_PLATFORMS": "cpu",
                "ELASTIC_OUT_DIR": out,
                "MXTPU_ZERO": "1",
                "MXTPU_OPTIMIZER_AGGREGATION": "8"})

    def launch(n, port, phase_env):
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
             "-n", str(n), "--launcher", "local",
             "--coordinator", f"127.0.0.1:{port}",
             sys.executable,
             os.path.join(ROOT, "tests", "dist", "elastic_worker.py")],
            capture_output=True, text=True, timeout=300,
            env={**env, **phase_env}, cwd=ROOT)
        assert proc.returncode == 0, \
            (proc.stdout + proc.stderr)[-3000:]
        return proc.stdout + proc.stderr

    def markers(text, marker):
        # ranks share one stdout pipe: a peer's line can land between a
        # print's text and its newline, so parse each marker's JSON with
        # raw_decode (stops at the object end) instead of by line
        dec = json.JSONDecoder()
        return [dec.raw_decode(chunk.lstrip())[0]
                for chunk in text.split(marker + " ")[1:]]

    out_pre = launch(2, 12483, {"ELASTIC_PHASE": "pre",
                                "MXTPU_CHAOS": f"resize@{RESIZE_AT}:3"})
    pre = markers(out_pre, "ELASTIC_PRE")
    assert sorted(p["rank"] for p in pre) == [0, 1], out_pre[-2000:]

    out_post = launch(3, 12484, {"ELASTIC_PHASE": "post",
                                 "MXTPU_ELASTIC": "on"})
    post = markers(out_post, "ELASTIC_POST")
    assert sorted(p["rank"] for p in post) == [0, 1, 2], out_post[-2000:]
    for p in post:
        assert p["elastic"]["from_world"] == 2
        assert p["elastic"]["world"] == 3
        assert p["step"] == (N // G) * EPOCHS

    # in-process never-resized reference: world 1, full stream, same
    # fixed global batch and sum loss
    for k in ("MXTPU_ZERO", "MXTPU_ZERO_WORLD", "MXTPU_ELASTIC"):
        monkeypatch.delenv(k, raising=False)
    X, Y = make_data()
    mx.random.seed(0)
    net = gluon.nn.Dense(1, in_units=3)
    net.initialize(mx.init.Constant(0.25))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.05, "momentum": 0.9},
                       kvstore=None)
    it = io.NDArrayIter(X, Y, batch_size=G, shuffle=True, seed=SEED)
    loop = fit.FitLoop(net, tr, lambda o, y: ((o - y) ** 2).sum(), it,
                       ckpt_dir=None, heartbeat=False, seed=SEED)
    ref = loop.fit(epochs=EPOCHS, batch_size=G)
    assert ref.step == (N // G) * EPOCHS

    # post-resize loss trajectory: sum of the 3 ranks' local sum-losses
    # per step == the reference's full-batch loss from the resize point
    summed = np.sum([p["losses"] for p in sorted(post,
                                                 key=lambda p: p["rank"])],
                    axis=0)
    np.testing.assert_allclose(summed, ref.losses[RESIZE_AT:],
                               rtol=1e-4, atol=1e-6)
    for p in post:  # weights replicated: every rank must agree with ref
        np.testing.assert_allclose(
            np.asarray(p["weight"]),
            net.weight.data().asnumpy().ravel(), rtol=1e-5, atol=1e-7)

    # union proof across the resize: trained samples (2-rank prefix +
    # 3-rank suffix) == the no-resize stream, zero dup / zero dropped
    ref_stream = []
    rit = io.NDArrayIter(X, Y, batch_size=G, shuffle=True, seed=SEED)
    for ep in range(EPOCHS):
        rit.set_epoch(ep)
        for bt in rit:
            ref_stream += [int(round(float(v) * N))
                           for v in bt.data[0].asnumpy()[:, 0]]
    consumed = []
    for p in pre + post:
        for ids in p["trained_ids"]:
            consumed += ids
    assert sorted(consumed) == sorted(ref_stream)
    assert len(consumed) == len(ref_stream) == N * EPOCHS


# --------------------------------------- run-report topology fingerprint

def test_run_report_world_fingerprint(monkeypatch, tmp_path):
    from mxnet_tpu.telemetry import run_report as rr
    monkeypatch.delenv("MXTPU_ZERO_WORLD", raising=False)
    res = fit.FitResult(status="done", step=2, epoch=1,
                        losses=[1.0, 0.5])
    p1 = rr.build_payload(res)
    assert p1["fingerprint"]["world_size"] == 1
    monkeypatch.setenv("MXTPU_ZERO_WORLD", "3")
    p3 = rr.build_payload(res)
    assert p3["fingerprint"]["world_size"] == 3

    from tools import run_compare as rc
    out = rc.compare(p1, p3, fence_pct=5.0)
    assert out["topology_diff"] == {"baseline_world": 1,
                                    "candidate_world": 3}
    out_same = rc.compare(p3, p3, fence_pct=5.0)
    assert out_same["topology_diff"] is None


def test_run_compare_flags_cross_topology_text(monkeypatch, tmp_path,
                                               capsys):
    from mxnet_tpu.telemetry import run_report as rr
    from tools import run_compare as rc
    res = fit.FitResult(status="done", step=2, epoch=1, losses=[1.0, 0.5])
    monkeypatch.setenv("MXTPU_RUN_REPORT_DIR", str(tmp_path))
    monkeypatch.delenv("MXTPU_ZERO_WORLD", raising=False)
    a = rr.write_run_report(res)
    monkeypatch.setenv("MXTPU_ZERO_WORLD", "2")
    b = rr.write_run_report(res)
    rcode = rc.main([a, b])
    out = capsys.readouterr().out
    assert rcode == 0  # flagged, not failed: same metrics
    assert "CROSS-TOPOLOGY" in out
    assert "world 1" in out and "world 2" in out
