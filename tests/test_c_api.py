"""General C API (ref: include/mxnet/c_api.h — NDArray lifecycle,
operator invocation, symbol compose, executor, autograd, kvstore).
Driven through src/libmxtpu_capi.so via ctypes the way a language
binding would."""
import ctypes
import os

import numpy as np
import pytest

import mxnet_tpu as mx  # ensures the interpreter owns jax/config first

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src", "libmxtpu_capi.so")

u = ctypes.c_uint
cp = ctypes.POINTER


@pytest.fixture(scope="module")
def lib():
    if not os.path.exists(_LIB_PATH):
        import subprocess
        subprocess.run(["make", "-C", os.path.dirname(_LIB_PATH),
                        "libmxtpu_capi.so"],
                       check=False, capture_output=True, timeout=180)
    if not os.path.exists(_LIB_PATH):
        pytest.skip("libmxtpu_capi.so not built (make -C src)")
    lib = ctypes.CDLL(_LIB_PATH)
    lib.MXGetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.MXGetLastError().decode()


def _make_nd(lib, arr):
    arr = np.ascontiguousarray(arr, np.float32)
    shape = (u * arr.ndim)(*arr.shape)
    h = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayCreateEx(shape, u(arr.ndim), 1, 0, 0, 0,
                                      ctypes.byref(h)))
    _check(lib, lib.MXNDArraySyncCopyFromCPU(
        h, arr.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_size_t(arr.size)))
    return h


def _vp(h):
    """Indexing a POINTER(c_void_p) yields a plain int; re-wrap so ctypes
    passes a full 64-bit pointer (no argtypes declared)."""
    return h if isinstance(h, ctypes.c_void_p) else ctypes.c_void_p(h)


def _to_np(lib, h):
    h = _vp(h)
    ndim = u()
    pdata = cp(u)()
    _check(lib, lib.MXNDArrayGetShape(h, ctypes.byref(ndim),
                                      ctypes.byref(pdata)))
    shape = tuple(pdata[i] for i in range(ndim.value))
    out = np.zeros(shape, np.float32)
    _check(lib, lib.MXNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_size_t(out.size)))
    return out


def test_version_and_op_listing(lib):
    v = ctypes.c_int()
    _check(lib, lib.MXGetVersion(ctypes.byref(v)))
    assert v.value == 10500
    n = u()
    names = cp(ctypes.c_char_p)()
    _check(lib, lib.MXListAllOpNames(ctypes.byref(n), ctypes.byref(names)))
    all_names = {names[i].decode() for i in range(n.value)}
    assert n.value >= 400
    assert {"dot", "Convolution", "sgd_update"} <= all_names


def test_ndarray_roundtrip_and_shape(lib):
    x = np.arange(12, dtype=np.float32).reshape(3, 4)
    h = _make_nd(lib, x)
    np.testing.assert_array_equal(_to_np(lib, h), x)
    dt = ctypes.c_int()
    _check(lib, lib.MXNDArrayGetDType(h, ctypes.byref(dt)))
    assert dt.value == 0  # float32
    # slice + at + reshape
    s = ctypes.c_void_p()
    _check(lib, lib.MXNDArraySlice(h, u(1), u(3), ctypes.byref(s)))
    np.testing.assert_array_equal(_to_np(lib, s), x[1:3])
    a = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayAt(h, u(2), ctypes.byref(a)))
    np.testing.assert_array_equal(_to_np(lib, a), x[2])
    r = ctypes.c_void_p()
    dims = (ctypes.c_int * 2)(4, 3)
    _check(lib, lib.MXNDArrayReshape(h, 2, dims, ctypes.byref(r)))
    np.testing.assert_array_equal(_to_np(lib, r), x.reshape(4, 3))
    for hh in (s, a, r, h):
        _check(lib, lib.MXNDArrayFree(hh))


def test_imperative_invoke_dot(lib):
    a = _make_nd(lib, np.random.RandomState(0).randn(3, 4))
    b = _make_nd(lib, np.random.RandomState(1).randn(4, 5))
    ins = (ctypes.c_void_p * 2)(a, b)
    n_out = ctypes.c_int()
    outs = cp(ctypes.c_void_p)()
    _check(lib, lib.MXImperativeInvoke(
        b"dot", 2, ins, ctypes.byref(n_out), ctypes.byref(outs), 0,
        None, None))
    assert n_out.value == 1
    got = _to_np(lib, outs[0])
    want = _to_np(lib, a) @ _to_np(lib, b)
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_imperative_invoke_with_params(lib):
    x = _make_nd(lib, np.random.RandomState(2).randn(2, 6))
    ins = (ctypes.c_void_p * 1)(x)
    n_out = ctypes.c_int()
    outs = cp(ctypes.c_void_p)()
    keys = (ctypes.c_char_p * 1)(b"shape")
    vals = (ctypes.c_char_p * 1)(b"(3, 4)")
    _check(lib, lib.MXImperativeInvoke(
        b"Reshape", 1, ins, ctypes.byref(n_out), ctypes.byref(outs), 1,
        keys, vals))
    assert _to_np(lib, outs[0]).shape == (3, 4)


def test_ndarray_save_load(lib, tmp_path):
    f = str(tmp_path / "arrs.nd").encode()
    a = _make_nd(lib, np.ones((2, 2), np.float32))
    handles = (ctypes.c_void_p * 1)(a)
    keys = (ctypes.c_char_p * 1)(b"w")
    _check(lib, lib.MXNDArraySave(f, u(1), handles, keys))
    n = u()
    arrs = cp(ctypes.c_void_p)()
    nn = u()
    names = cp(ctypes.c_char_p)()
    _check(lib, lib.MXNDArrayLoad(f, ctypes.byref(n), ctypes.byref(arrs),
                                  ctypes.byref(nn), ctypes.byref(names)))
    assert n.value == 1 and nn.value == 1
    assert names[0] == b"w"
    np.testing.assert_array_equal(_to_np(lib, arrs[0]), np.ones((2, 2)))


def test_symbol_compose_infer_and_json(lib):
    data = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"data", ctypes.byref(data)))
    w = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"w", ctypes.byref(w)))
    fc = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 2)(b"num_hidden", b"no_bias")
    vals = (ctypes.c_char_p * 2)(b"4", b"true")
    inputs = (ctypes.c_void_p * 2)(data, w)
    _check(lib, lib.MXSymbolCreateAtomicSymbolEx(
        b"FullyConnected", u(2), keys, vals, u(2), inputs, b"fc",
        ctypes.byref(fc)))
    n = u()
    names = cp(ctypes.c_char_p)()
    _check(lib, lib.MXSymbolListArguments(fc, ctypes.byref(n),
                                          ctypes.byref(names)))
    args = [names[i].decode() for i in range(n.value)]
    assert args == ["data", "w"]
    js = ctypes.c_char_p()
    _check(lib, lib.MXSymbolSaveToJSON(fc, ctypes.byref(js)))
    restored = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateFromJSON(js.value,
                                           ctypes.byref(restored)))
    _check(lib, lib.MXSymbolListArguments(restored, ctypes.byref(n),
                                          ctypes.byref(names)))
    assert [names[i].decode() for i in range(n.value)] == ["data", "w"]


def test_symbol_atomic_info(lib):
    name = ctypes.c_char_p()
    desc = ctypes.c_char_p()
    sig = ctypes.c_char_p()
    _check(lib, lib.MXSymbolGetAtomicSymbolInfo(
        b"Convolution", ctypes.byref(name), ctypes.byref(desc),
        ctypes.byref(sig)))
    assert b"kernel" in sig.value
    assert b"Parameters" in desc.value


def test_executor_forward_backward(lib):
    # y = FC(x, w); dy/dw via the C autograd-free executor path
    data = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"data", ctypes.byref(data)))
    w = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"w", ctypes.byref(w)))
    fc = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 2)(b"num_hidden", b"no_bias")
    vals = (ctypes.c_char_p * 2)(b"2", b"true")
    inputs = (ctypes.c_void_p * 2)(data, w)
    _check(lib, lib.MXSymbolCreateAtomicSymbolEx(
        b"FullyConnected", u(2), keys, vals, u(2), inputs, b"fc",
        ctypes.byref(fc)))
    rs = np.random.RandomState(3)
    xv = rs.randn(4, 3).astype(np.float32)
    wv = rs.randn(2, 3).astype(np.float32)
    xh, wh = _make_nd(lib, xv), _make_nd(lib, wv)
    gw = _make_nd(lib, np.zeros((2, 3), np.float32))
    arg_names = (ctypes.c_char_p * 2)(b"data", b"w")
    arg_h = (ctypes.c_void_p * 2)(xh, wh)
    grad_names = (ctypes.c_char_p * 1)(b"w")
    grad_h = (ctypes.c_void_p * 1)(gw)
    ex = ctypes.c_void_p()
    _check(lib, lib.MXExecutorBind(fc, u(2), arg_names, arg_h, u(1),
                                   grad_names, grad_h, u(0), None, None,
                                   ctypes.byref(ex)))
    _check(lib, lib.MXExecutorForward(ex, 1))
    _check(lib, lib.MXExecutorBackward(ex, u(0), None))
    n = u()
    outs = cp(ctypes.c_void_p)()
    _check(lib, lib.MXExecutorOutputs(ex, ctypes.byref(n),
                                      ctypes.byref(outs)))
    assert n.value == 1
    np.testing.assert_allclose(_to_np(lib, outs[0]), xv @ wv.T,
                               rtol=1e-5)
    # d(sum out)/dw = ones(4,2).T @ x
    np.testing.assert_allclose(_to_np(lib, gw),
                               np.ones((4, 2)).T @ xv, rtol=1e-5)


def test_autograd_through_c(lib):
    x = _make_nd(lib, np.array([1.0, 2.0, 3.0], np.float32))
    marks = (ctypes.c_void_p * 1)(x)
    _check(lib, lib.MXAutogradMarkVariables(u(1), marks))
    prev = ctypes.c_int()
    _check(lib, lib.MXAutogradSetIsRecording(1, ctypes.byref(prev)))
    ins = (ctypes.c_void_p * 1)(x)
    n_out = ctypes.c_int()
    outs = cp(ctypes.c_void_p)()
    _check(lib, lib.MXImperativeInvoke(b"square", 1, ins,
                                       ctypes.byref(n_out),
                                       ctypes.byref(outs), 0, None, None))
    y = _vp(outs[0])
    _check(lib, lib.MXAutogradSetIsRecording(0, ctypes.byref(prev)))
    heads = (ctypes.c_void_p * 1)(y)
    _check(lib, lib.MXAutogradBackward(u(1), heads, None, 0))
    g = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayGetGrad(x, ctypes.byref(g)))
    np.testing.assert_allclose(_to_np(lib, g), [2.0, 4.0, 6.0],
                               rtol=1e-6)


def test_kvstore_through_c(lib):
    kv = ctypes.c_void_p()
    _check(lib, lib.MXKVStoreCreate(b"local", ctypes.byref(kv)))
    rank = ctypes.c_int()
    size = ctypes.c_int()
    _check(lib, lib.MXKVStoreGetRank(kv, ctypes.byref(rank)))
    _check(lib, lib.MXKVStoreGetGroupSize(kv, ctypes.byref(size)))
    assert rank.value == 0 and size.value == 1
    w = _make_nd(lib, np.zeros((3,), np.float32))
    keys = (ctypes.c_char_p * 1)(b"k0")
    vals = (ctypes.c_void_p * 1)(w)
    _check(lib, lib.MXKVStoreInitEx(kv, u(1), keys, vals))
    g = _make_nd(lib, np.array([1.0, 2.0, 3.0], np.float32))
    gv = (ctypes.c_void_p * 1)(g)
    _check(lib, lib.MXKVStorePushEx(kv, u(1), keys, gv, 0))
    out = _make_nd(lib, np.zeros((3,), np.float32))
    ov = (ctypes.c_void_p * 1)(out)
    _check(lib, lib.MXKVStorePullEx(kv, u(1), keys, ov, 0))
    np.testing.assert_allclose(_to_np(lib, out), [1.0, 2.0, 3.0])


def test_error_contract(lib):
    h = ctypes.c_void_p()
    rc = lib.MXSymbolCreateVariable(None, ctypes.byref(h))
    # creating an op that doesn't exist must fail with a message
    bad = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 0)()
    vals = (ctypes.c_char_p * 0)()
    rc = lib.MXSymbolCreateAtomicSymbol(b"NoSuchOp", u(0), keys, vals,
                                        ctypes.byref(bad))
    assert rc != 0
    assert b"NoSuchOp" in lib.MXGetLastError()


def test_symbol_compose_two_step(lib):
    """The reference's canonical CreateAtomicSymbol + Compose path."""
    fc = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 1)(b"num_hidden")
    vals = (ctypes.c_char_p * 1)(b"3")
    _check(lib, lib.MXSymbolCreateAtomicSymbol(b"FullyConnected", u(1),
                                               keys, vals,
                                               ctypes.byref(fc)))
    data = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"data", ctypes.byref(data)))
    w = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"w", ctypes.byref(w)))
    b = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"b", ctypes.byref(b)))
    args = (ctypes.c_void_p * 3)(data, w, b)
    _check(lib, lib.MXSymbolCompose(fc, b"fc1", u(3), None, args))
    n = u()
    names = cp(ctypes.c_char_p)()
    _check(lib, lib.MXSymbolListArguments(fc, ctypes.byref(n),
                                          ctypes.byref(names)))
    assert [names[i].decode() for i in range(n.value)] == \
        ["data", "w", "b"]


def test_symbol_compose_keywords(lib):
    """Keyword composition (keys != NULL): inputs bind argument slots by
    NAME, in any order; unbound slots auto-create variables (ref: nnvm
    Symbol::Compose kwargs path — Scala/R bindings compose this way)."""
    fc = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 1)(b"num_hidden")
    vals = (ctypes.c_char_p * 1)(b"3")
    _check(lib, lib.MXSymbolCreateAtomicSymbol(b"FullyConnected", u(1),
                                               keys, vals,
                                               ctypes.byref(fc)))
    data = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"x", ctypes.byref(data)))
    w = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"myw", ctypes.byref(w)))
    # supply weight and data OUT OF ORDER by keyword; bias auto-creates
    in_keys = (ctypes.c_char_p * 2)(b"weight", b"data")
    args = (ctypes.c_void_p * 2)(w, data)
    _check(lib, lib.MXSymbolCompose(fc, b"fck", u(2), in_keys, args))
    n = u()
    names = cp(ctypes.c_char_p)()
    _check(lib, lib.MXSymbolListArguments(fc, ctypes.byref(n),
                                          ctypes.byref(names)))
    got = [names[i].decode() for i in range(n.value)]
    assert got == ["x", "myw", "fck_bias"], got
    # the no_bias-gated variadic slot is keyword-addressable too
    fcb = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateAtomicSymbol(b"FullyConnected", u(1),
                                               keys, vals,
                                               ctypes.byref(fcb)))
    d2 = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"x2", ctypes.byref(d2)))
    bias = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"myb", ctypes.byref(bias)))
    kb = (ctypes.c_char_p * 2)(b"bias", b"data")
    ab = (ctypes.c_void_p * 2)(bias, d2)
    _check(lib, lib.MXSymbolCompose(fcb, b"fcb", u(2), kb, ab))
    _check(lib, lib.MXSymbolListArguments(fcb, ctypes.byref(n),
                                          ctypes.byref(names)))
    got = [names[i].decode() for i in range(n.value)]
    assert got == ["x2", "fcb_weight", "myb"], got

    # a bogus keyword must error loudly, naming the op's real arguments
    bogus = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateAtomicSymbol(b"FullyConnected", u(1),
                                               keys, vals,
                                               ctypes.byref(bogus)))
    bad_keys = (ctypes.c_char_p * 1)(b"nonsense")
    bad_args = (ctypes.c_void_p * 1)(data)
    rc = lib.MXSymbolCompose(bogus, b"fbad", u(1), bad_keys, bad_args)
    assert rc != 0
    assert b"no input named" in lib.MXGetLastError()


def test_symbol_infer_shape(lib):
    data = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"data", ctypes.byref(data)))
    w = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"w", ctypes.byref(w)))
    fc = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 2)(b"num_hidden", b"no_bias")
    vals = (ctypes.c_char_p * 2)(b"7", b"true")
    inputs = (ctypes.c_void_p * 2)(data, w)
    _check(lib, lib.MXSymbolCreateAtomicSymbolEx(
        b"FullyConnected", u(2), keys, vals, u(2), inputs, b"fc",
        ctypes.byref(fc)))
    arg_keys = (ctypes.c_char_p * 1)(b"data")
    ind_ptr = (u * 2)(0, 2)
    shape_data = (u * 2)(5, 3)
    in_n, out_n, aux_n = u(), u(), u()
    in_ndim = cp(u)()
    out_ndim = cp(u)()
    aux_ndim = cp(u)()
    in_data = cp(cp(u))()
    out_data = cp(cp(u))()
    aux_data = cp(cp(u))()
    complete = ctypes.c_int()
    _check(lib, lib.MXSymbolInferShape(
        fc, u(1), arg_keys, ind_ptr, shape_data,
        ctypes.byref(in_n), ctypes.byref(in_ndim), ctypes.byref(in_data),
        ctypes.byref(out_n), ctypes.byref(out_ndim),
        ctypes.byref(out_data),
        ctypes.byref(aux_n), ctypes.byref(aux_ndim),
        ctypes.byref(aux_data), ctypes.byref(complete)))
    assert complete.value == 1
    assert out_n.value == 1 and out_ndim[0] == 2
    assert [out_data[0][i] for i in range(2)] == [5, 7]
    # the weight's inferred shape comes back in the arg shapes
    args_got = {}
    for i in range(in_n.value):
        args_got[i] = [in_data[i][j] for j in range(in_ndim[i])]
    assert [7, 3] in args_got.values()


def test_autograd_head_grads_and_retain(lib):
    x = _make_nd(lib, np.array([1.0, 2.0], np.float32))
    marks = (ctypes.c_void_p * 1)(x)
    _check(lib, lib.MXAutogradMarkVariables(u(1), marks))
    prev = ctypes.c_int()
    _check(lib, lib.MXAutogradSetIsRecording(1, ctypes.byref(prev)))
    ins = (ctypes.c_void_p * 1)(x)
    n_out = ctypes.c_int(0)
    outs = cp(ctypes.c_void_p)()
    _check(lib, lib.MXImperativeInvoke(b"square", 1, ins,
                                       ctypes.byref(n_out),
                                       ctypes.byref(outs), 0, None, None))
    y = _vp(outs[0])
    _check(lib, lib.MXAutogradSetIsRecording(0, ctypes.byref(prev)))
    heads = (ctypes.c_void_p * 1)(y)
    hg = _make_nd(lib, np.array([0.5, 0.5], np.float32))
    hgs = (ctypes.c_void_p * 1)(hg)
    _check(lib, lib.MXAutogradBackward(u(1), heads, hgs, 0))
    g = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayGetGrad(x, ctypes.byref(g)))
    # d(x^2) * 0.5 head grad = x
    np.testing.assert_allclose(_to_np(lib, g), [1.0, 2.0], rtol=1e-6)


def test_imperative_invoke_preallocated_output(lib):
    x = _make_nd(lib, np.array([1.0, 4.0, 9.0], np.float32))
    out = _make_nd(lib, np.zeros(3, np.float32))
    ins = (ctypes.c_void_p * 1)(x)
    outs_arr = (ctypes.c_void_p * 1)(out)
    outs_ptr = ctypes.cast(outs_arr, cp(ctypes.c_void_p))
    n_out = ctypes.c_int(1)
    _check(lib, lib.MXImperativeInvoke(b"sqrt", 1, ins,
                                       ctypes.byref(n_out),
                                       ctypes.byref(outs_ptr), 0, None,
                                       None))
    # result written into the caller's array in place
    np.testing.assert_allclose(_to_np(lib, out), [1.0, 2.0, 3.0],
                               rtol=1e-6)


def test_param_parsing_none_and_nested(lib):
    # "(0, None)" must parse to (0, None) — slice-style params
    x = _make_nd(lib, np.arange(12, dtype=np.float32).reshape(3, 4))
    ins = (ctypes.c_void_p * 1)(x)
    n_out = ctypes.c_int(0)
    outs = cp(ctypes.c_void_p)()
    keys = (ctypes.c_char_p * 2)(b"begin", b"end")
    vals = (ctypes.c_char_p * 2)(b"(1, None)", b"(None, None)")
    _check(lib, lib.MXImperativeInvoke(b"crop", 1, ins,
                                       ctypes.byref(n_out),
                                       ctypes.byref(outs), 2, keys, vals))
    got = _to_np(lib, outs[0])
    np.testing.assert_array_equal(
        got, np.arange(12, dtype=np.float32).reshape(3, 4)[1:])
