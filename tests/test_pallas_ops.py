"""Attention kernel ops: flash attention + interleaved self-attention
matmuls (ref: src/operator/contrib/transformer.cc MKL/interleaved helpers;
the flash kernel is the TPU-native replacement for fused attention).

Cross-checked against plain jnp einsum attention.
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import assert_almost_equal

RS = np.random.RandomState(5)


def _plain_attention(q, k, v, causal=False, scale=None):
    # q,k,v: (B, T, H, D)
    B, T, H, D = q.shape
    s = scale if scale is not None else 1.0 / np.sqrt(D)
    logits = np.einsum("bthd,bshd->bhts", q, k) * s
    if causal:
        mask = np.tril(np.ones((T, T), bool))
        logits = np.where(mask, logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhts,bshd->bthd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_matches_plain(causal):
    B, T, H, D = 2, 32, 2, 8
    q = RS.randn(B, T, H, D).astype(np.float32)
    k = RS.randn(B, T, H, D).astype(np.float32)
    v = RS.randn(B, T, H, D).astype(np.float32)
    out = nd.imperative_invoke(
        "_contrib_flash_attention",
        (nd.array(q), nd.array(k), nd.array(v)), {"causal": causal})
    want = _plain_attention(q, k, v, causal=causal)
    assert_almost_equal(out.asnumpy(), want, rtol=2e-3, atol=2e-3)


def test_flash_attention_gradients_match_plain():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas_kernels import flash_attention as \
        _flash_attention

    B, T, H, D = 1, 16, 2, 4
    q = jnp.asarray(RS.randn(B, T, H, D).astype(np.float32))
    k = jnp.asarray(RS.randn(B, T, H, D).astype(np.float32))
    v = jnp.asarray(RS.randn(B, T, H, D).astype(np.float32))

    def plain(q, k, v):
        s = 1.0 / np.sqrt(D)
        logits = jnp.einsum("bthd,bshd->bhts", q, k) * s
        p = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("bhts,bshd->bthd", p, v).sum()

    g_plain = jax.grad(plain, argnums=(0, 1, 2))(q, k, v)
    g_flash = jax.grad(lambda q, k, v:
                       _flash_attention(q, k, v).sum(),
                       argnums=(0, 1, 2))(q, k, v)
    for gp, gf in zip(g_plain, g_flash):
        assert_almost_equal(np.asarray(gf), np.asarray(gp), rtol=5e-3,
                            atol=5e-3)


def test_interleaved_matmul_selfatt_roundtrip():
    """qk produces (H*B, T, T) attention logits from packed qkv; valatt
    applies attention weights to the packed values — together they form
    standard self-attention (ref: transformer.cc interleaved layout
    (T, B, 3*H*D))."""
    T, B, H, D = 8, 2, 2, 4
    qkv = RS.randn(T, B, 3 * H * D).astype(np.float32)
    att = nd.imperative_invoke(
        "_contrib_interleaved_matmul_selfatt_qk",
        (nd.array(qkv),), {"heads": H}).asnumpy()
    assert att.shape == (B * H, T, T)
    # reference computation from the packed layout
    proj = qkv.reshape(T, B, H, 3, D)
    q, k, v = proj[..., 0, :], proj[..., 1, :], proj[..., 2, :]
    scale = 1.0 / np.sqrt(D)
    want = np.einsum("tbhd,sbhd->bhts", q * scale, k).reshape(B * H, T, T)
    assert_almost_equal(att, want, rtol=1e-4, atol=1e-5)

    weights = np.exp(att) / np.exp(att).sum(-1, keepdims=True)
    out = nd.imperative_invoke(
        "_contrib_interleaved_matmul_selfatt_valatt",
        (nd.array(qkv), nd.array(weights.astype(np.float32))),
        {"heads": H}).asnumpy()
    want_out = np.einsum("bhts,sbhd->tbhd",
                         weights.reshape(B, H, T, T), v)
    assert_almost_equal(out, want_out.reshape(T, B, H * D), rtol=1e-4,
                        atol=1e-5)
