"""CTCLoss vs torch.nn.functional.ctc_loss as numerical oracle
(model: tests/python/unittest/test_operator.py check_ctc_loss, which checks
against a numpy forward-algorithm implementation)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.test_utils import assert_almost_equal

torch = pytest.importorskip("torch")


def torch_ctc(acts, labels, data_len, label_len, blank):
    lp = torch.log_softmax(torch.tensor(acts, dtype=torch.float32), dim=-1)
    lp.requires_grad_(True)
    loss = torch.nn.functional.ctc_loss(
        lp, torch.tensor(labels, dtype=torch.long),
        torch.tensor(data_len, dtype=torch.long),
        torch.tensor(label_len, dtype=torch.long),
        blank=blank, reduction="none", zero_infinity=False)
    return loss.detach().numpy()


def test_ctc_loss_matches_torch_blank_first():
    rs = np.random.RandomState(0)
    T, B, A, L = 20, 4, 6, 5
    acts = rs.randn(T, B, A).astype(np.float32)
    # blank_label='first': blank id 0, labels in 1..A-1, padding 0
    label_len = np.array([5, 3, 4, 1], dtype=np.int32)
    labels = np.zeros((B, L), dtype=np.int32)
    for b in range(B):
        labels[b, :label_len[b]] = rs.randint(1, A, size=label_len[b])
    data_len = np.full((B,), T, dtype=np.int32)

    out = nd.CTCLoss(nd.array(acts), nd.array(labels)).asnumpy()
    ref = torch_ctc(acts, labels, data_len, label_len, blank=0)
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


def test_ctc_loss_variable_lengths_blank_last():
    rs = np.random.RandomState(1)
    T, B, A, L = 15, 3, 5, 4
    acts = rs.randn(T, B, A).astype(np.float32)
    data_len = np.array([15, 10, 8], dtype=np.int32)
    label_len = np.array([4, 2, 3], dtype=np.int32)
    labels = np.full((B, L), -1, dtype=np.int32)
    for b in range(B):
        labels[b, :label_len[b]] = rs.randint(0, A - 1, size=label_len[b])

    out = nd.CTCLoss(nd.array(acts), nd.array(labels),
                     nd.array(data_len), nd.array(label_len),
                     use_data_lengths=True, use_label_lengths=True,
                     blank_label="last").asnumpy()
    ref = torch_ctc(acts, np.where(labels < 0, 0, labels), data_len,
                    label_len, blank=A - 1)
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


def test_ctc_loss_gradient_matches_torch():
    rs = np.random.RandomState(2)
    T, B, A, L = 12, 2, 5, 3
    acts = rs.randn(T, B, A).astype(np.float32)
    labels = np.array([[1, 2, 1], [3, 1, 0]], dtype=np.int32)
    label_len = np.array([3, 2], dtype=np.int64)
    data_len = np.full((B,), T, dtype=np.int64)

    x = nd.array(acts)
    x.attach_grad()
    with autograd.record():
        loss = nd.CTCLoss(x, nd.array(labels))
        total = nd.sum(loss)
    total.backward()

    t = torch.tensor(acts, requires_grad=True)
    lp = torch.log_softmax(t, dim=-1)
    tl = torch.nn.functional.ctc_loss(
        lp, torch.tensor(labels, dtype=torch.long),
        torch.tensor(data_len), torch.tensor(label_len),
        blank=0, reduction="sum", zero_infinity=False)
    tl.backward()
    assert_almost_equal(x.grad.asnumpy(), t.grad.numpy(),
                        rtol=1e-3, atol=1e-4)


def test_gluon_ctc_loss_layout():
    rs = np.random.RandomState(3)
    from mxnet_tpu.gluon.loss import CTCLoss
    B, T, A = 2, 10, 5
    acts_ntc = rs.randn(B, T, A).astype(np.float32)
    labels = np.array([[0, 1, 2], [2, 3, -1]], dtype=np.int32)
    loss_fn = CTCLoss(layout="NTC", label_layout="NT")
    out = loss_fn(nd.array(acts_ntc), nd.array(labels)).asnumpy()
    ref = torch_ctc(np.swapaxes(acts_ntc, 0, 1),
                    np.where(labels < 0, 0, labels),
                    np.full((B,), T, dtype=np.int32),
                    np.array([3, 2], dtype=np.int32), blank=A - 1)
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


def test_ctc_loss_mid_row_padding_is_packed():
    # padding embedded mid-row must be removed, not treated as labels
    # (ref: ctc_loss.cc LabelTensorToPackedVector)
    rs = np.random.RandomState(4)
    T, B, A = 10, 1, 4
    acts = rs.randn(T, B, A).astype(np.float32)
    out = nd.CTCLoss(nd.array(acts),
                     nd.array(np.array([[1, 0, 2]], dtype=np.int32))).asnumpy()
    ref = torch_ctc(acts, np.array([[1, 2]], dtype=np.int32),
                    np.array([T]), np.array([2]), blank=0)
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-4)


def test_ctc_loss_invalid_blank_label_raises():
    import pytest
    from mxnet_tpu.base import MXNetError
    acts = np.zeros((4, 1, 3), dtype=np.float32)
    labels = np.array([[1, 2]], dtype=np.int32)
    with pytest.raises(MXNetError, match="blank_label"):
        nd.CTCLoss(nd.array(acts), nd.array(labels), blank_label="First")
