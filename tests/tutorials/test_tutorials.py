"""Execute every python code block in docs/tutorial.md (the analog of the
reference's tests/tutorials/test_tutorials.py CI gate): docs that rot
fail the suite."""
import os
import re
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

DOCS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "docs")


def _python_blocks(md_path):
    text = open(md_path).read()
    return re.findall(r"```python\n(.*?)```", text, re.DOTALL)


def test_tutorial_snippets_run():
    blocks = _python_blocks(os.path.join(DOCS, "tutorial.md"))
    assert len(blocks) >= 6, "tutorial lost its code blocks"
    ns = {}
    for i, block in enumerate(blocks):
        try:
            exec(compile(block, f"tutorial.md[block {i}]", "exec"), ns)
        except Exception as e:
            raise AssertionError(
                f"tutorial block {i} failed: {e}\n---\n{block}") from e


def test_api_doc_names_exist():
    """Every `mx.<name>` surface the API overview mentions must resolve."""
    import mxnet_tpu as mx
    text = open(os.path.join(DOCS, "api.md")).read()
    for dotted in set(re.findall(r"`mx\.([a-z_]+(?:\.[a-z_]+)?)`", text)):
        obj = mx
        for part in dotted.split("."):
            assert hasattr(obj, part), f"api.md mentions missing mx.{dotted}"
            obj = getattr(obj, part)
