"""Unified telemetry subsystem: tracer, chrome-trace export + strict
validator, shared metrics registry, per-step breakdown, profiler facade.

Marker ``telemetry`` — tier-1-safe: CPU, in-process, no sockets.
"""
import json
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon, io as mxio, nd, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.telemetry.tracer import Tracer
from mxnet_tpu.telemetry import (chrome_trace_events, dump_chrome_trace,
                                 validate_chrome_trace, MetricsRegistry)

pytestmark = pytest.mark.telemetry


@pytest.fixture(autouse=True)
def _clean_tracer():
    """Every test starts with the shared tracer off and empty."""
    from mxnet_tpu.telemetry.tracer import tracer
    tracer.disable()
    tracer.clear()
    tracer.set_categories(None)
    yield
    tracer.disable()
    tracer.clear()
    tracer.set_categories(None)


# ---------------------------------------------------------------------------
# tracer core
# ---------------------------------------------------------------------------

def test_tracer_off_by_default_records_nothing():
    tr = Tracer()
    with tr.span("s", "compute"):
        pass
    tr.record("x", "compute", 0.0, 1.0)
    tr.instant("i")
    tr.counter_event("c", 1.0)
    assert tr.events() == []
    assert not tr.enabled


def test_tracer_ring_buffer_bounded_and_counts_drops():
    tr = Tracer(ring=8)
    tr.enable()
    t0 = time.perf_counter()
    for i in range(20):
        tr.record(f"s{i}", "compute", t0, t0 + 1e-6)
    evs = tr.events()
    assert len(evs) == 8
    assert evs[0]["name"] == "s12"  # oldest evicted
    assert tr.dropped == 12


def test_tracer_category_filter_and_pause():
    tr = Tracer()
    tr.enable()
    tr.set_categories({"comm"})
    t0 = time.perf_counter()
    tr.record("keep", "comm", t0, t0 + 1e-6)
    tr.record("drop", "compute", t0, t0 + 1e-6)
    assert [e["name"] for e in tr.events()] == ["keep"]
    tr.set_categories(None)
    tr.pause()
    tr.record("paused", "comm", t0, t0 + 1e-6)
    tr.resume()
    tr.record("resumed", "comm", t0, t0 + 1e-6)
    assert [e["name"] for e in tr.events()] == ["keep", "resumed"]


def test_mxtpu_profile_grammar():
    tr = Tracer()
    tr.configure("on,ring=128,cat=comm|data_wait")
    assert tr.enabled
    assert tr.ring_capacity == 128
    assert tr.wants("comm") and not tr.wants("compute")
    tr.configure("off")
    assert not tr.enabled
    # a modifiers-only spec implies 'on': asking for a trace file and
    # getting silence would be the silent-measure-nothing failure
    tr2 = Tracer()
    tr2.configure("cat=comm")
    assert tr2.enabled
    for bad in ("bogus", "ring=x", "cat=", "file=", "wat=1"):
        with pytest.raises(MXNetError):
            Tracer().configure(bad)


def test_tracing_off_overhead_under_one_percent():
    """The off path must cost <1% on a tight step loop (one flag check,
    no clock reads, no allocation).

    Measurement discipline for a shared CI box: A/B-timing two ~1ms
    loops flakes on scheduler noise alone, so measure the two quantities
    the claim is actually about — the per-iteration cost of a disabled
    span (min over reps) and the per-iteration cost of the step body —
    and bound their ratio. The disabled span is ~0.5µs and the body
    ~1ms, so the 1% bound has ~20x headroom."""
    tr = Tracer()  # disabled
    a = np.random.RandomState(0).rand(256, 256)

    def per_iter(body, n, reps=5):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            for _ in range(n):
                body()
            best = min(best, (time.perf_counter() - t0) / n)
        return best

    def noop_span():
        with tr.span("step", "compute"):
            pass

    def step_body():
        a @ a

    noop_span(), step_body()  # warm
    span_cost = per_iter(noop_span, 20000)
    body_cost = per_iter(step_body, 50)
    assert span_cost < 0.01 * body_cost, \
        (f"tracing-off span costs {span_cost * 1e9:.0f}ns = "
         f"{span_cost / body_cost:.3%} of a {body_cost * 1e6:.0f}us step")


# ---------------------------------------------------------------------------
# chrome trace export + strict validator
# ---------------------------------------------------------------------------

def _span_ev(name, ts, dur, tid=0, pid=0):
    return {"name": name, "cat": "t", "ph": "X", "ts": float(ts),
            "dur": float(dur), "pid": pid, "tid": tid}


def test_exporter_output_passes_validator(tmp_path):
    telemetry.enable()
    with telemetry.span("outer", "compute"):
        with telemetry.span("inner", "comm"):
            time.sleep(0.001)
    telemetry.instant("mark")
    telemetry.counter_event("queue_depth", 3)
    telemetry.disable()
    path = str(tmp_path / "trace.json")
    payload = dump_chrome_trace(path)
    events = validate_chrome_trace(payload)
    names = {e["name"] for e in events}
    assert {"outer", "inner", "mark", "queue_depth",
            "process_name"} <= names
    with open(path) as f:
        validate_chrome_trace(f.read())  # the file round-trips too


def test_validator_rejects_malformed_traces():
    ok = {"traceEvents": [_span_ev("a", 0, 10)]}
    validate_chrome_trace(ok)
    with pytest.raises(ValueError, match="not valid JSON"):
        validate_chrome_trace("{nope")
    with pytest.raises(ValueError, match="traceEvents"):
        validate_chrome_trace({"events": []})
    with pytest.raises(ValueError, match="missing keys"):
        validate_chrome_trace({"traceEvents": [{"name": "x", "ph": "X"}]})
    with pytest.raises(ValueError, match="unknown phase"):
        validate_chrome_trace({"traceEvents": [
            dict(_span_ev("a", 0, 1), ph="Z")]})
    with pytest.raises(ValueError, match="numeric"):
        validate_chrome_trace({"traceEvents": [
            dict(_span_ev("a", 0, 1), ts="soon")]})
    with pytest.raises(ValueError, match="dur"):
        validate_chrome_trace({"traceEvents": [
            {"name": "a", "cat": "t", "ph": "X", "ts": 0.0,
             "pid": 0, "tid": 0}]})
    with pytest.raises(ValueError, match="negative dur"):
        validate_chrome_trace({"traceEvents": [_span_ev("a", 0, -1)]})
    with pytest.raises(ValueError, match="no events"):
        validate_chrome_trace({"traceEvents": []})


def test_validator_enforces_per_thread_nesting():
    # proper nesting and disjoint siblings pass
    validate_chrome_trace({"traceEvents": [
        _span_ev("parent", 0, 100), _span_ev("child", 10, 20),
        _span_ev("sibling", 40, 20), _span_ev("next", 200, 50)]})
    # partial overlap on ONE thread is broken instrumentation
    with pytest.raises(ValueError, match="partially overlaps"):
        validate_chrome_trace({"traceEvents": [
            _span_ev("a", 0, 100), _span_ev("b", 50, 100)]})
    # the same overlap on DIFFERENT threads is fine
    validate_chrome_trace({"traceEvents": [
        _span_ev("a", 0, 100, tid=1), _span_ev("b", 50, 100, tid=2)]})


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

def test_registry_counter_gauge_histogram_and_render():
    reg = MetricsRegistry()
    c = reg.counter("mxtpu_t_total", "things", label="kind")
    c.inc(2, label_value="a")
    c.inc(1, label_value="b")
    g = reg.gauge("mxtpu_t_depth", "depth")
    g.set(4)
    g.inc()
    h = reg.histogram("mxtpu_t_ms", "latency")
    for v in (1.0, 2.0, 100.0):
        h.observe(v)
    text = reg.render_prometheus()
    assert 'mxtpu_t_total{kind="a"} 2' in text
    assert "mxtpu_t_depth 5" in text
    assert "mxtpu_t_ms_count 3" in text
    out = reg.render_json()
    assert out["mxtpu_t_total"] == {"total": 3, "by_label": {"a": 2, "b": 1}}
    assert out["mxtpu_t_depth"] == 5
    assert out["mxtpu_t_ms"]["count"] == 3
    # same name returns the same object; a different kind raises
    assert reg.counter("mxtpu_t_total") is c
    with pytest.raises(ValueError, match="already registered"):
        reg.gauge("mxtpu_t_total")


def test_registry_callback_gauge_polls_at_export():
    reg = MetricsRegistry()
    box = [1.0]
    reg.callback_gauge("mxtpu_t_live", lambda: box[0], "live")
    assert "mxtpu_t_live 1" in reg.render_prometheus()
    box[0] = 7.0
    assert "mxtpu_t_live 7" in reg.render_prometheus()


def test_default_registry_absorbs_cachedop_cache_traffic():
    """Order-independent since the retired-counts fix: the exported
    totals are MONOTONE (a dying cache folds its counters into a retired
    accumulator), so a cyclic-GC pass collecting earlier tests' caches
    between the two reads can no longer make the sum go DOWN — the exact
    mechanism of the test_env_flags+test_telemetry pair-order flake."""
    reg = telemetry.default_registry()
    before = reg.render_json().get("mxtpu_cachedop_cache_misses", 0)
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    net.hybridize()
    net(nd.ones((2, 8)))  # miss (fresh signature)
    net(nd.ones((2, 8)))  # hit
    after = reg.render_json()
    assert after["mxtpu_cachedop_cache_misses"] > before
    assert after["mxtpu_cachedop_cache_hits"] >= 1


def test_cachedop_cache_gauges_survive_cache_death():
    """Regression for the pair-order flake: deleting a cache (and
    forcing the cyclic GC that used to subtract its whole history from
    the live-sum gauge) must keep hits/misses monotone; only currsize —
    true occupancy — may drop."""
    import gc

    from mxnet_tpu.cached_op import SignatureLRU
    reg = telemetry.default_registry()
    cache = SignatureLRU(maxsize=8)
    for i in range(5):
        cache.get_or_build(("k", i), lambda: object())
    cache.get_or_build(("k", 0), lambda: object())  # a hit
    j1 = reg.render_json()
    del cache
    gc.collect()
    j2 = reg.render_json()
    for field in ("mxtpu_cachedop_cache_misses",
                  "mxtpu_cachedop_cache_hits"):
        assert j2[field] >= j1[field], (field, j1[field], j2[field])
    assert j2["mxtpu_cachedop_cache_currsize"] <= \
        j1["mxtpu_cachedop_cache_currsize"]
    # clear() must retire, not erase: an explicit cache reset is the
    # other path that used to subtract history from the live sum
    cache2 = SignatureLRU(maxsize=8)
    cache2.get_or_build(("c2", 1), lambda: object())
    j3 = reg.render_json()
    cache2.clear()
    j4 = reg.render_json()
    assert j4["mxtpu_cachedop_cache_misses"] >= \
        j3["mxtpu_cachedop_cache_misses"]
    assert cache2.cache_info().misses == 0  # per-cache view does reset


def test_default_registry_absorbs_trainer_dispatch_counts():
    reg = telemetry.default_registry()
    before = reg.render_json().get("mxtpu_update_dispatches_total", 0)
    p = gluon.Parameter("telemetry_p", shape=(4, 2))
    p.initialize(mx.init.Constant(1.0))
    tr = gluon.Trainer([p], "sgd", {"learning_rate": 0.1}, kvstore=None)
    p._grad._rebind(nd.ones((4, 2))._data)
    p._fresh_grad = True
    tr.step(1)
    after = reg.render_json()["mxtpu_update_dispatches_total"]
    assert after >= before + 1


def test_default_registry_counts_kv_retries():
    from mxnet_tpu import kvstore as kv_mod
    reg = telemetry.default_registry()
    before = reg.render_json().get("mxtpu_kv_retries_total", {})
    before_n = before.get("total", before) if isinstance(before, dict) \
        else before
    state = {"n": 0}

    def flaky():
        state["n"] += 1
        if state["n"] < 3:
            raise kv_mod.TransientKVError("injected")
        return "ok"

    assert kv_mod._retry_op("push", flaky) == "ok"
    after = reg.render_json()["mxtpu_kv_retries_total"]
    assert after["total"] == (before_n or 0) + 2
    assert after["by_label"].get("push", 0) >= 2


def test_default_registry_counts_chaos_injections():
    from mxnet_tpu.contrib.chaos import ChaosPlan
    reg = telemetry.default_registry()
    before = reg.render_json().get("mxtpu_chaos_injections_total", {})
    before_n = before.get("total", 0) if isinstance(before, dict) else 0
    plan = ChaosPlan("kv_flake:1.0", seed=0)
    with pytest.raises(Exception):
        plan.kv_maybe_fail("push", "w")
    after = reg.render_json()["mxtpu_chaos_injections_total"]
    assert after["total"] == before_n + 1
    assert after["by_label"].get("kv_flake", 0) >= 1


def test_default_registry_observes_xla_compiles():
    reg = telemetry.default_registry()
    import jax
    import jax.numpy as jnp
    before = reg.render_json().get("mxtpu_xla_compile_total", 0)
    # a fresh jaxpr forces a backend compile
    jax.jit(lambda x: x * 3.14159 + before)(jnp.ones(7)).block_until_ready()
    after = reg.render_json()
    assert after["mxtpu_xla_compile_total"] >= before + 1
    assert after["mxtpu_xla_compile_seconds_total"] >= 0


def test_serving_metrics_ride_shared_registry_types():
    from mxnet_tpu.serving import metrics as sm
    from mxnet_tpu.telemetry import registry as tr_reg
    assert sm.Counter is tr_reg.Counter
    assert sm.Gauge is tr_reg.Gauge
    assert sm.LatencyHistogram is tr_reg.Histogram


# ---------------------------------------------------------------------------
# step breakdown + FitLoop e2e
# ---------------------------------------------------------------------------

def _fit_run(n_steps=3, batch=32, stage=True, loss_scale=1.0):
    from mxnet_tpu.fit import FitLoop
    from mxnet_tpu.io.staging import DeviceStagingIter
    rs = np.random.RandomState(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    data = rs.randn(n_steps * batch, 16).astype(np.float32)
    label = rs.randint(0, 4, (n_steps * batch,)).astype(np.float32)
    it = mxio.NDArrayIter(data, label, batch_size=batch)
    if stage:
        it = DeviceStagingIter(it)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    loop = FitLoop(net, trainer, loss_fn, it, ckpt_dir=None,
                   loss_scale=loss_scale)
    return loop.fit(epochs=1)


def test_fitloop_three_steps_covers_categories_and_wall_clock(tmp_path):
    telemetry.enable()
    try:
        result = _fit_run(n_steps=3)
    finally:
        telemetry.disable()
    # >= 5 distinct span categories in the trace
    cats = {e.get("cat") for e in telemetry.tracer.events()}
    assert {"data_wait", "h2d", "compute", "optimizer", "comm"} <= cats, cats
    # the trace is strict-validator clean
    payload = dump_chrome_trace(str(tmp_path / "fit_trace.json"))
    validate_chrome_trace(payload)
    # per-step segment sums within 20% of measured wall-clock step time
    bd = result.step_breakdown
    assert bd is not None and bd["steps"] == 3
    assert 0.8 <= bd["accounted_frac"] <= 1.0 + 1e-6, bd
    for rec in bd["per_step"]:
        accounted = sum(v for k, v in rec.items() if k != "wall")
        assert accounted >= 0.8 * rec["wall"], rec
        assert accounted <= rec["wall"] * 1.2 + 1e-6, rec


def test_fitloop_breakdown_collected_even_with_tracer_off():
    result = _fit_run(n_steps=2)
    bd = result.step_breakdown
    assert bd is not None and bd["steps"] == 2
    assert bd["shares"].get("compute", 0) > 0
    # but nothing landed in the (disabled) tracer ring
    assert telemetry.tracer.events() == []


def test_input_bound_detector_logs_one_line_diagnosis(caplog):
    from mxnet_tpu.fit import FitLoop

    class SlowIter(mxio.NDArrayIter):
        def next(self):
            time.sleep(0.05)  # dominates the tiny model's step time
            return super().next()

    rs = np.random.RandomState(0)
    net = gluon.nn.Dense(2)
    net.initialize(mx.init.Xavier())
    it = SlowIter(rs.randn(8, 4).astype(np.float32),
                  rs.randint(0, 2, (8,)).astype(np.float32), batch_size=4)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    import logging
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.telemetry"):
        result = FitLoop(net, trainer, loss_fn, it,
                         ckpt_dir=None).fit(epochs=1)
    assert result.step_breakdown["diagnoses"], "detector never fired"
    assert any("data_wait" in r.message and "input-bound" in r.message
               for r in caplog.records)


def test_breakdown_exclusive_time_accounting():
    from mxnet_tpu.telemetry.step_breakdown import StepBreakdown, segment
    bd = StepBreakdown(bound_frac=0).install()
    try:
        bd.begin_step(0)
        with segment("data_wait"):
            time.sleep(0.02)
            with segment("h2d"):
                time.sleep(0.01)
        rec = bd.end_step()
    finally:
        bd.uninstall()
    # h2d charged once, to the inner bracket; data_wait keeps only its
    # exclusive share
    assert rec["h2d"] >= 0.009
    assert rec["data_wait"] >= 0.015
    assert rec["data_wait"] + rec["h2d"] <= rec["wall"] + 1e-3


# ---------------------------------------------------------------------------
# profiler facade (MXNet API over the tracer)
# ---------------------------------------------------------------------------

def test_profiler_facade_round_trip(tmp_path):
    from mxnet_tpu import profiler
    f = str(tmp_path / "prof.json")
    profiler.set_config(filename=f, aggregate_stats=True)
    profiler.set_state("run")
    with profiler.Task("unit_step"):
        time.sleep(0.001)
    (nd.ones((4, 4)) * 2).asnumpy()  # operator span via op dispatch
    profiler.set_state("stop")
    profiler.dump()
    with open(f) as fh:
        events = validate_chrome_trace(fh.read())
    assert any(e["name"] == "unit_step" for e in events)
    table = profiler.dumps()
    assert "Total(ms)" in table and "unit_step" in table
    # events() keeps the historical shape (ph + args always present)
    evs = profiler.events("task")
    assert evs and evs[0]["ph"] == "X" and isinstance(evs[0]["args"], dict)


def test_bench_scan_folds_step_breakdown_extra_row():
    import bench
    bench._EXTRAS.clear()
    row = {"step_breakdown": {"steps": 3, "shares": {"compute": 0.9}}}
    stdout = "TRAIN_IPS 123.0\nEXTRA_ROW " + json.dumps(row) + "\n"
    value = bench._scan_child_stdout(stdout, "TRAIN_IPS")
    assert value == 123.0
    assert bench._EXTRAS["step_breakdown"]["shares"]["compute"] == 0.9
    bench._EXTRAS.clear()
