"""C-callback custom operators through the flat C ABI
(ref: include/mxnet/c_api.h:2459 MXCustomOpRegister / :2468
MXCustomFunctionRecord; tag protocol src/operator/custom/custom.cc).

Driven via ctypes CFUNCTYPE exactly the way a non-Python language
binding supplies callbacks: the callbacks themselves only use the flat
C API (MXNDArrayGetShape / SyncCopyToCPU / SyncCopyFromCPU) to do their
math — no mxnet_tpu Python objects are touched inside them.
"""
import ctypes
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd

_LIB_PATH = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "src", "libmxtpu_capi.so")

u = ctypes.c_uint
cp = ctypes.POINTER

# enum values (include/mxnet/c_api.h)
K_OP_DELETE, K_OP_FORWARD, K_OP_BACKWARD = 0, 1, 2


class MXCallbackList(ctypes.Structure):
    _fields_ = [("num_callbacks", ctypes.c_int),
                ("callbacks", cp(ctypes.CFUNCTYPE(ctypes.c_int))),
                ("contexts", cp(ctypes.c_void_p))]


GENERIC = ctypes.CFUNCTYPE(ctypes.c_int)
CREATOR = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
    cp(ctypes.c_char_p), cp(ctypes.c_char_p), cp(MXCallbackList))
FBFUNC = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, cp(ctypes.c_void_p), cp(ctypes.c_int),
    cp(ctypes.c_int), ctypes.c_int, ctypes.c_void_p)
LISTFUNC = ctypes.CFUNCTYPE(
    ctypes.c_int, cp(cp(ctypes.c_char_p)), ctypes.c_void_p)
SHAPEFUNC = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, cp(ctypes.c_int), cp(cp(u)),
    ctypes.c_void_p)
CREATEFUNC = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_char_p, ctypes.c_int, cp(cp(u)),
    cp(ctypes.c_int), cp(ctypes.c_int), cp(MXCallbackList),
    ctypes.c_void_p)
FUNCBWD = ctypes.CFUNCTYPE(
    ctypes.c_int, ctypes.c_int, ctypes.c_int, cp(ctypes.c_void_p),
    cp(ctypes.c_int), ctypes.c_int, ctypes.c_void_p)

_KEEP = []  # every callback object must outlive the test module


@pytest.fixture(scope="module")
def lib():
    if not os.path.exists(_LIB_PATH):
        import subprocess
        subprocess.run(["make", "-C", os.path.dirname(_LIB_PATH),
                        "libmxtpu_capi.so"],
                       check=False, capture_output=True, timeout=180)
    if not os.path.exists(_LIB_PATH):
        pytest.skip("libmxtpu_capi.so not built (make -C src)")
    lib = ctypes.CDLL(_LIB_PATH)
    lib.MXGetLastError.restype = ctypes.c_char_p
    return lib


def _check(lib, rc):
    assert rc == 0, lib.MXGetLastError().decode()


def _handle_np(lib, h):
    """Read an NDArrayHandle into numpy using ONLY the C API."""
    h = ctypes.c_void_p(h) if not isinstance(h, ctypes.c_void_p) else h
    ndim = u()
    pdata = cp(u)()
    _check(lib, lib.MXNDArrayGetShape(h, ctypes.byref(ndim),
                                      ctypes.byref(pdata)))
    shape = tuple(pdata[i] for i in range(ndim.value))
    out = np.empty(shape, np.float32)
    _check(lib, lib.MXNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_size_t(out.size)))
    return out


def _write_handle(lib, h, arr):
    h = ctypes.c_void_p(h) if not isinstance(h, ctypes.c_void_p) else h
    arr = np.ascontiguousarray(arr, np.float32)
    _check(lib, lib.MXNDArraySyncCopyFromCPU(
        h, arr.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_size_t(arr.size)))


def _cb_list(pairs):
    """Build an MXCallbackList from [(CFUNCTYPE instance)] (contexts 0)."""
    n = len(pairs)
    arr = (ctypes.CFUNCTYPE(ctypes.c_int) * n)(
        *[ctypes.cast(p, GENERIC) for p in pairs])
    ctxs = (ctypes.c_void_p * n)(*([None] * n))
    cb = MXCallbackList(n, ctypes.cast(arr, cp(GENERIC)), ctxs)
    _KEEP.extend([pairs, arr, ctxs, cb])
    return cb


def _register_csqr(lib):
    """x -> x*x with backward 2*x*gy, all through C callbacks."""

    @FBFUNC
    def forward(size, ptrs, tags, reqs, is_train, _state):
        ins = [ptrs[i] for i in range(size) if tags[i] == 0]
        outs = [ptrs[i] for i in range(size) if tags[i] == 1]
        x = _handle_np(lib, ins[0])
        _write_handle(lib, outs[0], x * x)
        return 1

    @FBFUNC
    def backward(size, ptrs, tags, reqs, is_train, _state):
        ogs = [ptrs[i] for i in range(size) if tags[i] == 3]
        ins = [ptrs[i] for i in range(size) if tags[i] == 0]
        igs = [ptrs[i] for i in range(size) if tags[i] == 2]
        gy = _handle_np(lib, ogs[0])
        x = _handle_np(lib, ins[0])
        _write_handle(lib, igs[0], 2.0 * x * gy)
        return 1

    @GENERIC
    def op_delete():
        return 1

    @CREATEFUNC
    def create_operator(ctx, num_in, shapes, ndims, dtypes, ret, _state):
        ret[0] = _cb_list([op_delete, forward, backward])
        return 1

    @LISTFUNC
    def list_arguments(out, _state):
        names = (ctypes.c_char_p * 2)(b"data", None)
        _KEEP.append(names)
        out[0] = names
        return 1

    @LISTFUNC
    def list_outputs(out, _state):
        names = (ctypes.c_char_p * 2)(b"output", None)
        _KEEP.append(names)
        out[0] = names
        return 1

    @LISTFUNC
    def list_aux(out, _state):
        names = (ctypes.c_char_p * 1)(None)
        _KEEP.append(names)
        out[0] = names
        return 1

    @SHAPEFUNC
    def infer_shape(num_tensor, ndims, shapes, _state):
        # one input, one output, zero aux: output shape = input shape
        ndims[1] = ndims[0]
        shapes[1] = shapes[0]
        return 1

    @GENERIC
    def prop_delete():
        return 1

    @CREATOR
    def creator(op_type, num_kwargs, keys, vals, ret):
        ret[0] = _cb_list([
            prop_delete, list_arguments, list_outputs, list_aux,
            infer_shape, GENERIC(), create_operator])
        return 1

    _KEEP.append(creator)
    _check(lib, lib.MXCustomOpRegister(b"csqr", creator))


def test_custom_op_register_forward_backward(lib):
    _register_csqr(lib)
    x_np = np.array([[1.0, -2.0], [3.0, 0.5]], np.float32)
    x = nd.array(x_np)
    x.attach_grad()
    with autograd.record():
        y = nd.Custom(x, op_type="csqr")
        s = (y * 2).sum()
    np.testing.assert_allclose(y.asnumpy(), x_np * x_np, rtol=1e-6)
    s.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x_np * 2.0, rtol=1e-6)


def test_custom_op_symbolic(lib):
    """The C-registered op also composes into symbol graphs."""
    _register_csqr(lib)
    data = mx.sym.var("data")
    y = mx.sym.Custom(data, op_type="csqr")
    ex = y.bind(mx.cpu(), {"data": nd.array([[2.0, 3.0]])})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, [[4.0, 9.0]], rtol=1e-6)


def test_custom_function_record(lib):
    """MXCustomFunctionRecord: a C backward callback wired into the tape."""

    @FUNCBWD
    def func_backward(n_ograds, n_igrads, ptrs, reqs, is_train, _state):
        gy = _handle_np(lib, ptrs[0])  # ograds first ...
        _write_handle(lib, ptrs[n_ograds], 3.0 * gy)  # ... then igrads
        return 1

    @GENERIC
    def func_delete():
        return 1

    cb = _cb_list([func_backward, func_delete])

    x = nd.array(np.array([1.0, 2.0, 4.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = x * 3.0  # forward computed by the frontend itself
        # record: d(y)/d(x) is claimed by the C callback
        ins = (ctypes.c_void_p * 1)(ctypes.c_void_p(id(x)))
        outs = (ctypes.c_void_p * 1)(ctypes.c_void_p(id(y)))
        _check(lib, lib.MXCustomFunctionRecord(
            1, ins, 1, outs, ctypes.byref(cb)))
    y.backward()  # implicit ones cotangent, like the reference pattern
    np.testing.assert_allclose(x.grad.asnumpy(), [3.0, 3.0, 3.0])
