"""Round-3 C API families driven through ctypes, the way a language
binding would (ref: include/mxnet/c_api.h families that were absent in
round 2: symbol depth, DataIter, RecordIO, profiler, CachedOp, sparse
NDArray, SimpleBind/monitor, kvstore updater/row-sparse, misc)."""
import ctypes
import os

import numpy as np
import pytest

import mxnet_tpu as mx  # noqa: F401  (interpreter owns jax first)

from test_c_api import lib, _check, _make_nd, _to_np, _vp, u, cp  # noqa: F401

sz = ctypes.c_size_t


def _make_sym(lib, op=b"relu"):
    """data -> relu(data) symbol via the C API."""
    var = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateVariable(b"data", ctypes.byref(var)))
    out = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateAtomicSymbol(op, u(0), None, None,
                                               ctypes.byref(out)))
    args = (ctypes.c_void_p * 1)(var)
    _check(lib, lib.MXSymbolCompose(out, b"act0", u(1), None, args))
    return out


# -- symbol depth ----------------------------------------------------------

def test_symbol_copy_print_name(lib):
    s = _make_sym(lib)
    c = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCopy(s, ctypes.byref(c)))
    out = ctypes.c_char_p()
    _check(lib, lib.MXSymbolPrint(c, ctypes.byref(out)))
    assert b"act0" in out.value
    name = ctypes.c_char_p()
    ok = ctypes.c_int()
    _check(lib, lib.MXSymbolGetName(c, ctypes.byref(name), ctypes.byref(ok)))
    assert ok.value == 1 and name.value == b"act0"


def test_symbol_attr_roundtrip(lib):
    s = _make_sym(lib)
    _check(lib, lib.MXSymbolSetAttr(s, b"lr_mult", b"2.0"))
    val = ctypes.c_char_p()
    ok = ctypes.c_int()
    _check(lib, lib.MXSymbolGetAttr(s, b"lr_mult", ctypes.byref(val),
                                    ctypes.byref(ok)))
    assert ok.value == 1 and val.value == b"2.0"
    n = u()
    pairs = cp(ctypes.c_char_p)()
    _check(lib, lib.MXSymbolListAttrShallow(s, ctypes.byref(n),
                                            ctypes.byref(pairs)))
    flat = [pairs[i] for i in range(n.value * 2)]
    assert b"lr_mult" in flat and b"2.0" in flat


def test_symbol_file_roundtrip(lib, tmp_path):
    s = _make_sym(lib)
    path = str(tmp_path / "sym.json").encode()
    _check(lib, lib.MXSymbolSaveToFile(s, path))
    loaded = ctypes.c_void_p()
    _check(lib, lib.MXSymbolCreateFromFile(path, ctypes.byref(loaded)))
    n = u()
    names = cp(ctypes.c_char_p)()
    _check(lib, lib.MXSymbolListArguments(loaded, ctypes.byref(n),
                                          ctypes.byref(names)))
    assert [names[i] for i in range(n.value)] == [b"data"]


def test_symbol_internals_outputs_children(lib):
    s = _make_sym(lib)
    nout = u()
    _check(lib, lib.MXSymbolGetNumOutputs(s, ctypes.byref(nout)))
    assert nout.value == 1
    internals = ctypes.c_void_p()
    _check(lib, lib.MXSymbolGetInternals(s, ctypes.byref(internals)))
    out0 = ctypes.c_void_p()
    _check(lib, lib.MXSymbolGetOutput(s, u(0), ctypes.byref(out0)))
    kids = ctypes.c_void_p()
    _check(lib, lib.MXSymbolGetChildren(s, ctypes.byref(kids)))
    inputs = cp(ctypes.c_void_p)()
    n_in = ctypes.c_int()
    _check(lib, lib.MXSymbolGetInputSymbols(s, ctypes.byref(inputs),
                                            ctypes.byref(n_in)))
    assert n_in.value == 1


def test_symbol_infer_type(lib):
    s = _make_sym(lib)
    keys = (ctypes.c_char_p * 1)(b"data")
    types = (ctypes.c_int * 1)(0)  # float32
    n_in, n_out, n_aux = u(), u(), u()
    t_in, t_out, t_aux = cp(ctypes.c_int)(), cp(ctypes.c_int)(), \
        cp(ctypes.c_int)()
    complete = ctypes.c_int()
    _check(lib, lib.MXSymbolInferType(s, u(1), keys, types,
                                      ctypes.byref(n_in), ctypes.byref(t_in),
                                      ctypes.byref(n_out),
                                      ctypes.byref(t_out),
                                      ctypes.byref(n_aux),
                                      ctypes.byref(t_aux),
                                      ctypes.byref(complete)))
    assert complete.value == 1 and t_out[0] == 0


def test_symbol_creators_listing(lib):
    n = u()
    creators = cp(ctypes.c_void_p)()
    _check(lib, lib.MXSymbolListAtomicSymbolCreators(ctypes.byref(n),
                                                     ctypes.byref(creators)))
    assert n.value > 400
    name = ctypes.c_char_p()
    _check(lib, lib.MXSymbolGetAtomicSymbolName(_vp(creators[0]),
                                                ctypes.byref(name)))
    assert len(name.value) > 0


def test_symbol_grad_errors_like_reference(lib):
    s = _make_sym(lib)
    wrt = (ctypes.c_char_p * 1)(b"data")
    out = ctypes.c_void_p()
    rc = lib.MXSymbolGrad(s, u(1), wrt, ctypes.byref(out))
    assert rc != 0
    assert b"not implemented" in lib.MXGetLastError()


# -- DataIter --------------------------------------------------------------

def test_data_iter_family(lib, tmp_path):
    csv = tmp_path / "d.csv"
    data = np.arange(24, dtype=np.float32).reshape(8, 3)
    np.savetxt(csv, data, delimiter=",", fmt="%.1f")
    n = u()
    creators = cp(ctypes.c_void_p)()
    _check(lib, lib.MXListDataIters(ctypes.byref(n), ctypes.byref(creators)))
    names = {}
    for i in range(n.value):
        nm = ctypes.c_char_p()
        _check(lib, lib.MXSymbolGetAtomicSymbolName(_vp(creators[i]),
                                                    ctypes.byref(nm)))
        names[nm.value] = _vp(creators[i])
    assert b"CSVIter" in names
    # creator info
    nm, desc = ctypes.c_char_p(), ctypes.c_char_p()
    n_args = u()
    a_names, a_types, a_descs = (cp(ctypes.c_char_p)() for _ in range(3))
    _check(lib, lib.MXDataIterGetIterInfo(
        names[b"CSVIter"], ctypes.byref(nm), ctypes.byref(desc),
        ctypes.byref(n_args), ctypes.byref(a_names), ctypes.byref(a_types),
        ctypes.byref(a_descs)))
    assert nm.value == b"CSVIter"
    # create + iterate
    keys = (ctypes.c_char_p * 3)(b"data_csv", b"data_shape", b"batch_size")
    vals = (ctypes.c_char_p * 3)(str(csv).encode(), b"(3,)", b"4")
    it = ctypes.c_void_p()
    _check(lib, lib.MXDataIterCreateIter(names[b"CSVIter"], u(3), keys, vals,
                                         ctypes.byref(it)))
    _check(lib, lib.MXDataIterBeforeFirst(it))
    seen = 0
    has = ctypes.c_int(1)
    while True:
        _check(lib, lib.MXDataIterNext(it, ctypes.byref(has)))
        if not has.value:
            break
        batch = ctypes.c_void_p()
        _check(lib, lib.MXDataIterGetData(it, ctypes.byref(batch)))
        arr = _to_np(lib, batch)
        assert arr.shape == (4, 3)
        pad = ctypes.c_int()
        _check(lib, lib.MXDataIterGetPadNum(it, ctypes.byref(pad)))
        assert pad.value == 0
        seen += 1
    assert seen == 2
    _check(lib, lib.MXDataIterFree(it))


# -- RecordIO --------------------------------------------------------------

def test_recordio_roundtrip(lib, tmp_path):
    path = str(tmp_path / "t.rec").encode()
    w = ctypes.c_void_p()
    _check(lib, lib.MXRecordIOWriterCreate(path, ctypes.byref(w)))
    records = [b"hello", b"tpu" * 100, b"z"]
    for rec in records:
        _check(lib, lib.MXRecordIOWriterWriteRecord(w, rec, sz(len(rec))))
    pos = sz()
    _check(lib, lib.MXRecordIOWriterTell(w, ctypes.byref(pos)))
    assert pos.value > 0
    _check(lib, lib.MXRecordIOWriterFree(w))

    r = ctypes.c_void_p()
    _check(lib, lib.MXRecordIOReaderCreate(path, ctypes.byref(r)))
    got = []
    while True:
        buf = ctypes.c_char_p()
        size = sz()
        _check(lib, lib.MXRecordIOReaderReadRecord(r, ctypes.byref(buf),
                                                   ctypes.byref(size)))
        if not buf.value and size.value == 0:
            break
        got.append(ctypes.string_at(buf, size.value))
    assert got == records
    # seek back to start and re-read first record
    _check(lib, lib.MXRecordIOReaderSeek(r, sz(0)))
    buf = ctypes.c_char_p()
    size = sz()
    _check(lib, lib.MXRecordIOReaderReadRecord(r, ctypes.byref(buf),
                                               ctypes.byref(size)))
    assert ctypes.string_at(buf, size.value) == records[0]
    _check(lib, lib.MXRecordIOReaderFree(r))


# -- profiler --------------------------------------------------------------

def test_profiler_family(lib, tmp_path):
    fname = str(tmp_path / "prof.json").encode()
    keys = (ctypes.c_char_p * 2)(b"filename", b"aggregate_stats")
    vals = (ctypes.c_char_p * 2)(fname, b"True")
    _check(lib, lib.MXSetProfilerConfig(ctypes.c_int(2), keys, vals))
    _check(lib, lib.MXSetProfilerState(ctypes.c_int(1)))
    dom = ctypes.c_void_p()
    _check(lib, lib.MXProfileCreateDomain(b"test", ctypes.byref(dom)))
    task = ctypes.c_void_p()
    _check(lib, lib.MXProfileCreateTask(dom, b"step", ctypes.byref(task)))
    _check(lib, lib.MXProfileDurationStart(task))
    _check(lib, lib.MXProfileDurationStop(task))
    ctr = ctypes.c_void_p()
    _check(lib, lib.MXProfileCreateCounter(dom, b"items", ctypes.byref(ctr)))
    _check(lib, lib.MXProfileSetCounter(ctr, ctypes.c_uint64(5)))
    _check(lib, lib.MXProfileAdjustCounter(ctr, ctypes.c_int64(-2)))
    _check(lib, lib.MXProfileSetMarker(dom, b"mark", b"process"))
    out = ctypes.c_char_p()
    _check(lib, lib.MXAggregateProfileStatsPrint(ctypes.byref(out),
                                                 ctypes.c_int(0)))
    assert b"step" in out.value or b"test" in out.value
    _check(lib, lib.MXSetProfilerState(ctypes.c_int(0)))
    _check(lib, lib.MXProfileDestroyHandle(task))
    _check(lib, lib.MXProfileDestroyHandle(ctr))
    _check(lib, lib.MXProfileDestroyHandle(dom))


# -- CachedOp --------------------------------------------------------------

def test_cached_op_invoke(lib):
    s = _make_sym(lib)
    op = ctypes.c_void_p()
    _check(lib, lib.MXCreateCachedOp(s, ctypes.byref(op)))
    x = np.array([[-1.0, 2.0], [3.0, -4.0]], np.float32)
    h = _make_nd(lib, x)
    ins = (ctypes.c_void_p * 1)(h)
    n_out = ctypes.c_int(0)
    outs = cp(ctypes.c_void_p)()
    _check(lib, lib.MXInvokeCachedOp(op, ctypes.c_int(1), ins,
                                     ctypes.byref(n_out),
                                     ctypes.byref(outs)))
    assert n_out.value == 1
    np.testing.assert_allclose(_to_np(lib, outs[0]), np.maximum(x, 0))
    # second invoke reuses the bound executor (same shapes)
    _check(lib, lib.MXInvokeCachedOp(op, ctypes.c_int(1), ins,
                                     ctypes.byref(n_out),
                                     ctypes.byref(outs)))
    stypes = cp(ctypes.c_int)()
    _check(lib, lib.MXInvokeCachedOpEx(op, ctypes.c_int(1), ins,
                                       ctypes.byref(n_out),
                                       ctypes.byref(outs),
                                       ctypes.byref(stypes)))
    assert stypes[0] == 0
    _check(lib, lib.MXFreeCachedOp(op))


# -- sparse NDArray --------------------------------------------------------

def test_sparse_ndarray_family(lib):
    h = ctypes.c_void_p()
    shape = (u * 2)(6, 4)
    _check(lib, lib.MXNDArrayCreateSparseEx(
        ctypes.c_int(1), shape, u(2), 1, 0, 0, 0, u(1), None, None, None,
        ctypes.byref(h)))  # row_sparse zeros
    st = ctypes.c_int()
    _check(lib, lib.MXNDArrayGetStorageType(h, ctypes.byref(st)))
    assert st.value == 1
    _check(lib, lib.MXNDArraySyncCheckFormat(h, ctypes.c_bool(True)))
    data_h = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayGetDataNDArray(h, ctypes.byref(data_h)))
    aux_h = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayGetAuxNDArray(h, u(0), ctypes.byref(aux_h)))
    t = ctypes.c_int()
    _check(lib, lib.MXNDArrayGetAuxType(h, u(0), ctypes.byref(t)))
    assert t.value == 4  # int32 indices
    # dense arrays report default storage
    d = _make_nd(lib, np.ones((2, 2), np.float32))
    _check(lib, lib.MXNDArrayGetStorageType(d, ctypes.byref(st)))
    assert st.value == 0


# -- executor depth --------------------------------------------------------

def test_executor_simple_bind_and_monitor(lib):
    s = _make_sym(lib)
    shape_names = (ctypes.c_char_p * 1)(b"data")
    shape_idx = (u * 2)(0, 2)
    shape_data = (u * 2)(3, 4)
    n_args, n_aux = u(), u()
    in_args, arg_grads, aux = (cp(ctypes.c_void_p)() for _ in range(3))
    shared_len = ctypes.c_int(-1)
    upd_names = cp(ctypes.c_char_p)()
    upd_handles = cp(ctypes.c_void_p)()
    ex = ctypes.c_void_p()
    _check(lib, lib.MXExecutorSimpleBind(
        s, 1, 0, u(0), None, None, None,
        u(0), None, None,
        u(1), shape_names, shape_data, shape_idx,
        u(0), None, None, u(0), None, None, u(0), None,
        ctypes.byref(shared_len), None, None,
        ctypes.byref(upd_names), ctypes.byref(upd_handles),
        ctypes.byref(n_args), ctypes.byref(in_args),
        ctypes.byref(arg_grads), ctypes.byref(n_aux), ctypes.byref(aux),
        None, ctypes.byref(ex)))
    assert n_args.value == 1
    # write data, forward, check output via monitor callback
    x = np.random.randn(3, 4).astype(np.float32)
    _check(lib, lib.MXNDArraySyncCopyFromCPU(
        _vp(in_args[0]), x.ctypes.data_as(ctypes.c_void_p),
        ctypes.c_size_t(x.size)))
    seen = []
    CB = ctypes.CFUNCTYPE(None, ctypes.c_char_p, ctypes.c_void_p,
                          ctypes.c_void_p)

    def monitor(name, handle, _):
        seen.append((name, _to_np(lib, ctypes.c_void_p(handle))))
        lib.MXNDArrayFree(ctypes.c_void_p(handle))

    cb = CB(monitor)
    _check(lib, lib.MXExecutorSetMonitorCallback(ex, cb, None))
    _check(lib, lib.MXExecutorForward(ex, ctypes.c_int(0)))
    n_out = u()
    outs = cp(ctypes.c_void_p)()
    _check(lib, lib.MXExecutorOutputs(ex, ctypes.byref(n_out),
                                      ctypes.byref(outs)))
    np.testing.assert_allclose(_to_np(lib, outs[0]), np.maximum(x, 0),
                               rtol=1e-6)
    assert seen and seen[0][0] is not None
    pstr = ctypes.c_char_p()
    _check(lib, lib.MXExecutorPrint(ex, ctypes.byref(pstr)))
    assert b"Executor" in pstr.value
    opt_sym = ctypes.c_void_p()
    _check(lib, lib.MXExecutorGetOptimizedSymbol(ex, ctypes.byref(opt_sym)))
    # reshape to a new batch
    new_idx = (u * 2)(0, 2)
    new_data = (u * 2)(5, 4)
    r_args, r_grads, r_aux = (cp(ctypes.c_void_p)() for _ in range(3))
    rn_args, rn_aux = u(), u()
    new_ex = ctypes.c_void_p()
    _check(lib, lib.MXExecutorReshape(
        ctypes.c_int(0), ctypes.c_int(1), 1, 0, u(0), None, None, None,
        u(1), shape_names, new_data, new_idx,
        ctypes.byref(rn_args), ctypes.byref(r_args), ctypes.byref(r_grads),
        ctypes.byref(rn_aux), ctypes.byref(r_aux), ex,
        ctypes.byref(new_ex)))
    assert rn_args.value == 1


# -- kvstore depth ---------------------------------------------------------

def test_kvstore_int_keys_updater_barrier(lib):
    kv = ctypes.c_void_p()
    _check(lib, lib.MXKVStoreCreate(b"local", ctypes.byref(kv)))
    t = ctypes.c_char_p()
    _check(lib, lib.MXKVStoreGetType(kv, ctypes.byref(t)))
    assert t.value == b"local"
    init = np.zeros((4,), np.float32)
    h = _make_nd(lib, init)
    keys = (ctypes.c_int * 1)(7)
    vals = (ctypes.c_void_p * 1)(h)
    _check(lib, lib.MXKVStoreInit(kv, u(1), keys, vals))

    calls = []
    UPD = ctypes.CFUNCTYPE(None, ctypes.c_int, ctypes.c_void_p,
                           ctypes.c_void_p, ctypes.c_void_p)

    def updater(key, recv, local, _):
        grad = _to_np(lib, ctypes.c_void_p(recv))
        stored = _to_np(lib, ctypes.c_void_p(local))
        calls.append(key)
        new = (stored + 2 * grad).astype(np.float32)
        lib.MXNDArraySyncCopyFromCPU(
            ctypes.c_void_p(local), new.ctypes.data_as(ctypes.c_void_p),
            ctypes.c_size_t(new.size))

    cb = UPD(updater)
    _check(lib, lib.MXKVStoreSetUpdater(kv, cb, None))
    g = _make_nd(lib, np.ones((4,), np.float32))
    gvals = (ctypes.c_void_p * 1)(g)
    _check(lib, lib.MXKVStorePush(kv, u(1), keys, gvals, ctypes.c_int(0)))
    assert calls == [7]
    out = _make_nd(lib, np.zeros((4,), np.float32))
    ovals = (ctypes.c_void_p * 1)(out)
    _check(lib, lib.MXKVStorePull(kv, u(1), keys, ovals, ctypes.c_int(0)))
    np.testing.assert_allclose(_to_np(lib, out), 2 * np.ones(4), rtol=1e-6)
    _check(lib, lib.MXKVStoreBarrier(kv))
    ret = ctypes.c_int()
    _check(lib, lib.MXKVStoreIsWorkerNode(ctypes.byref(ret)))
    assert ret.value == 1
    dead = ctypes.c_int()
    _check(lib, lib.MXKVStoreGetNumDeadNode(kv, ctypes.c_int(0),
                                            ctypes.byref(dead),
                                            ctypes.c_int(1)))
    assert dead.value == 0
    _check(lib, lib.MXKVStoreSetBarrierBeforeExit(kv, ctypes.c_int(0)))
    _check(lib, lib.MXKVStoreFree(kv))


def test_kvstore_pull_row_sparse(lib):
    kv = ctypes.c_void_p()
    _check(lib, lib.MXKVStoreCreate(b"local", ctypes.byref(kv)))
    table = np.arange(12, dtype=np.float32).reshape(6, 2)
    _check(lib, lib.MXKVStoreInitEx(
        kv, u(1), (ctypes.c_char_p * 1)(b"emb"),
        (ctypes.c_void_p * 1)(_make_nd(lib, table))))
    rows = _make_nd(lib, np.array([1, 4], np.float32))
    out = _make_nd(lib, np.zeros((2, 2), np.float32))
    _check(lib, lib.MXKVStorePullRowSparseEx(
        kv, u(1), (ctypes.c_char_p * 1)(b"emb"),
        (ctypes.c_void_p * 1)(out), (ctypes.c_void_p * 1)(rows),
        ctypes.c_int(0)))
    np.testing.assert_allclose(_to_np(lib, out), table[[1, 4]], rtol=1e-6)


# -- NDArray depth ---------------------------------------------------------

def test_ndarray_extras(lib):
    x = np.random.randn(3, 4).astype(np.float32)
    h = _make_nd(lib, x)
    _check(lib, lib.MXNDArrayWaitToRead(h))
    _check(lib, lib.MXNDArrayWaitToWrite(h))
    dt, did = ctypes.c_int(), ctypes.c_int()
    _check(lib, lib.MXNDArrayGetContext(h, ctypes.byref(dt),
                                        ctypes.byref(did)))
    assert dt.value in (1, 2)
    ptr = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayGetData(h, ctypes.byref(ptr)))
    host = np.ctypeslib.as_array(
        ctypes.cast(ptr, cp(ctypes.c_float)), shape=(12,))
    np.testing.assert_allclose(host.reshape(3, 4), x, rtol=1e-6)
    det = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayDetach(h, ctypes.byref(det)))
    # reshape64
    dims = (ctypes.c_int64 * 2)(4, 3)
    r = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayReshape64(h, ctypes.c_int(2), dims,
                                       ctypes.c_bool(False),
                                       ctypes.byref(r)))
    assert _to_np(lib, r).shape == (4, 3)
    # raw bytes roundtrip
    size = sz()
    buf = ctypes.c_char_p()
    _check(lib, lib.MXNDArraySaveRawBytes(h, ctypes.byref(size),
                                          ctypes.byref(buf)))
    raw = ctypes.string_at(buf, size.value)
    h2 = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayLoadFromRawBytes(raw, sz(len(raw)),
                                              ctypes.byref(h2)))
    np.testing.assert_allclose(_to_np(lib, h2), x, rtol=1e-6)
    # dlpack roundtrip
    cap = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayToDLPack(h, ctypes.byref(cap)))
    h3 = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayFromDLPack(cap, ctypes.byref(h3)))
    np.testing.assert_allclose(_to_np(lib, h3), x, rtol=1e-6)
    _check(lib, lib.MXNDArrayCallDLPackDeleter(cap))


def test_ndarray_shared_mem(lib):
    x = np.random.randn(2, 3).astype(np.float32)
    h = _make_nd(lib, x)
    pid, sid = ctypes.c_int(), ctypes.c_int()
    _check(lib, lib.MXNDArrayGetSharedMemHandle(h, ctypes.byref(pid),
                                                ctypes.byref(sid)))
    shape = (u * 2)(2, 3)
    h2 = ctypes.c_void_p()
    _check(lib, lib.MXNDArrayCreateFromSharedMem(pid, sid, shape, u(2),
                                                 ctypes.c_int(0),
                                                 ctypes.byref(h2)))
    np.testing.assert_allclose(_to_np(lib, h2), x, rtol=1e-6)


# -- autograd depth + misc -------------------------------------------------

def test_autograd_backward_ex_with_variables(lib):
    h = _make_nd(lib, np.array([2.0, 3.0], np.float32))
    _check(lib, lib.MXNDArraySetGradState(h, ctypes.c_int(1)))
    st = ctypes.c_int()
    _check(lib, lib.MXNDArrayGetGradState(h, ctypes.byref(st)))
    assert st.value == 1
    prev = ctypes.c_int()
    _check(lib, lib.MXAutogradSetIsRecording(ctypes.c_int(1),
                                             ctypes.byref(prev)))
    rec = ctypes.c_bool()
    _check(lib, lib.MXAutogradIsRecording(ctypes.byref(rec)))
    assert rec.value
    n_out = ctypes.c_int(0)
    outs = cp(ctypes.c_void_p)()
    ins = (ctypes.c_void_p * 2)(h, h)
    _check(lib, lib.MXImperativeInvoke(b"elemwise_mul", ctypes.c_int(2),
                                       ins, ctypes.byref(n_out),
                                       ctypes.byref(outs), ctypes.c_int(0),
                                       None, None))
    y = ctypes.c_void_p(outs[0])
    _check(lib, lib.MXAutogradSetIsRecording(ctypes.c_int(0),
                                             ctypes.byref(prev)))
    grads = cp(ctypes.c_void_p)()
    stypes = cp(ctypes.c_int)()
    heads = (ctypes.c_void_p * 1)(y)
    variables = (ctypes.c_void_p * 1)(h)
    _check(lib, lib.MXAutogradBackwardEx(
        u(1), heads, None, u(1), variables, ctypes.c_int(0),
        ctypes.c_int(0), ctypes.c_int(1), ctypes.byref(grads),
        ctypes.byref(stypes)))
    np.testing.assert_allclose(_to_np(lib, grads[0]), [4.0, 6.0], rtol=1e-5)


def test_misc_family(lib):
    n = ctypes.c_int()
    _check(lib, lib.MXGetGPUCount(ctypes.byref(n)))
    assert n.value >= 0  # 0 on a CPU-only host (honest no-GPU signal)
    f64, t64 = ctypes.c_uint64(), ctypes.c_uint64()
    _check(lib, lib.MXGetGPUMemoryInformation64(0, ctypes.byref(f64),
                                                ctypes.byref(t64)))
    prev = ctypes.c_int()
    _check(lib, lib.MXEngineSetBulkSize(ctypes.c_int(16),
                                        ctypes.byref(prev)))
    _check(lib, lib.MXSetNumOMPThreads(ctypes.c_int(2)))

    class LibFeature(ctypes.Structure):
        _fields_ = [("name", ctypes.c_char_p), ("enabled", ctypes.c_bool)]

    feats = cp(LibFeature)()
    count = sz()
    _check(lib, lib.MXLibInfoFeatures(ctypes.byref(feats),
                                      ctypes.byref(count)))
    names = {feats[i].name for i in range(count.value)}
    assert b"TPU" in names or len(names) > 3
    _check(lib, lib.MXRandomSeedContext(ctypes.c_int(7), 1, 0))


def test_legacy_function_api(lib):
    n = u()
    funcs = cp(ctypes.c_void_p)()
    _check(lib, lib.MXListFunctions(ctypes.byref(n), ctypes.byref(funcs)))
    assert n.value > 400
    fh = ctypes.c_void_p()
    _check(lib, lib.MXGetFunction(b"relu", ctypes.byref(fh)))
    nu, nsc, nm = u(), u(), u()
    mask = ctypes.c_int()
    _check(lib, lib.MXFuncDescribe(fh, ctypes.byref(nu), ctypes.byref(nsc),
                                   ctypes.byref(nm), ctypes.byref(mask)))
    assert (nu.value, nm.value) == (1, 1)
    x = np.array([-1.0, 5.0], np.float32)
    src = _make_nd(lib, x)
    dst = _make_nd(lib, np.zeros(2, np.float32))
    _check(lib, lib.MXFuncInvoke(fh, (ctypes.c_void_p * 1)(src), None,
                                 (ctypes.c_void_p * 1)(dst)))
    np.testing.assert_allclose(_to_np(lib, dst), [0.0, 5.0], rtol=1e-6)


def test_rtc_error_contract(lib):
    out = ctypes.c_void_p()
    rc = lib.MXRtcCudaModuleCreate(b"__global__ void k(){}", ctypes.c_int(0),
                                   None, ctypes.c_int(0), None,
                                   ctypes.byref(out))
    assert rc != 0
    assert b"PallasModule" in lib.MXGetLastError()


def test_capi_coverage_gate(lib):
    """>=150/197 reference functions exported, absences documented."""
    import subprocess, sys, json, os
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", "capi_coverage.py")
    if not os.path.isdir("/root/reference"):
        pytest.skip("reference tree unavailable")
    res = subprocess.run([sys.executable, script, "--json"],
                         capture_output=True, text=True)
    report = json.loads(res.stdout[res.stdout.index("{"):])
    assert report["implemented"] >= 150
    assert report["missing_undocumented"] == []
