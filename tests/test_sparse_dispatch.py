"""Storage-type dispatch: the op layer actually speaks sparse.

Covers the FComputeEx analog (ops/sparse_ops.py + registry.stype_dispatch):
on-device csr dot kernels, row_sparse autograd gradients
(Embedding(sparse_grad=True), dot(csr, dense)), lazy optimizer updates,
kvstore row_sparse push, and the principled dense fallback.
Reference: src/operator/tensor/dot-inl.h, src/operator/tensor/indexing_op.cc,
src/operator/optimizer_op.cc row_sparse variants,
tests/python/unittest/test_sparse_operator.py.
"""
import warnings

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu import optimizer as opt
from mxnet_tpu.ndarray import sparse


def _random_csr(m, k, density=0.25, seed=0):
    rs = np.random.RandomState(seed)
    dense = rs.randn(m, k).astype(np.float32) * (rs.rand(m, k) < density)
    return sparse.csr_matrix(dense), dense


# ---------------------------------------------------------------------------
# csr dot kernels
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("m,k,n,density",
                         [(4, 7, 3, 0.3), (16, 33, 8, 0.1),
                          (8, 12, 1, 0.5), (5, 9, 4, 0.0),
                          (1, 64, 16, 0.9)])
def test_dot_csr_dense(m, k, n, density):
    csr, dense = _random_csr(m, k, density, seed=m + k)
    rhs = np.random.RandomState(1).randn(k, n).astype(np.float32)
    out = nd.dot(csr, nd.array(rhs))
    assert out.shape == (m, n)
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs,
                               rtol=1e-5, atol=1e-5)


def test_dot_csr_dense_vector_rhs():
    csr, dense = _random_csr(6, 10, 0.3)
    rhs = np.random.randn(10).astype(np.float32)
    out = nd.dot(csr, nd.array(rhs))
    assert out.shape == (6,)
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs, rtol=1e-5,
                               atol=1e-5)


def test_dot_csr_transpose_returns_row_sparse():
    csr, dense = _random_csr(6, 50, 0.1, seed=3)
    rhs = np.random.RandomState(2).randn(6, 4).astype(np.float32)
    out = nd.dot(csr, nd.array(rhs), transpose_a=True)
    assert out.stype == "row_sparse"
    # only touched columns appear as stored rows
    touched = np.unique(np.asarray(csr._indices))
    assert set(np.asarray(out._indices)) <= set(touched)
    np.testing.assert_allclose(out.todense().asnumpy(), dense.T @ rhs,
                               rtol=1e-5, atol=1e-5)


def test_sparse_dot_namespace():
    csr, dense = _random_csr(5, 8, 0.4)
    rhs = np.random.randn(8, 2).astype(np.float32)
    out = sparse.dot(csr, nd.array(rhs))
    np.testing.assert_allclose(out.asnumpy(), dense @ rhs, rtol=1e-5,
                               atol=1e-5)


# ---------------------------------------------------------------------------
# autograd: row_sparse gradients
# ---------------------------------------------------------------------------

def test_dot_csr_backward_row_sparse_grad():
    csr, dense = _random_csr(6, 30, 0.15, seed=5)
    w = nd.array(np.random.RandomState(3).randn(30, 4).astype(np.float32))
    w.attach_grad(stype="row_sparse")
    with autograd.record():
        y = nd.dot(csr, w)
        loss = (y * y).sum()
    loss.backward()
    g = w.grad
    assert isinstance(g, sparse.RowSparseNDArray)
    cot = 2 * (dense @ np.asarray(w._data))
    ref = dense.T @ cot
    np.testing.assert_allclose(g.todense().asnumpy(), ref, rtol=1e-4,
                               atol=1e-4)
    # untouched feature rows are not stored
    touched = np.unique(np.asarray(csr._indices))
    assert set(np.asarray(g._indices)) <= set(touched)


def test_dot_csr_backward_vector_rhs():
    # 1-D rhs: backward must mirror the squeeze (regression: (nnz, nnz) cot)
    csr, dense = _random_csr(8, 20, 0.2, seed=11)
    w = nd.array(np.random.RandomState(6).randn(20).astype(np.float32))
    w.attach_grad(stype="row_sparse")
    with autograd.record():
        y = nd.dot(csr, w)
        y.sum().backward()
    g = w.grad
    ref = dense.T @ np.ones(8, np.float32)
    np.testing.assert_allclose(g.todense().asnumpy(), ref, rtol=1e-5,
                               atol=1e-5)
    # no spurious padded row 0 with zero data in the compact grad
    touched = set(np.unique(np.asarray(csr._indices)))
    assert set(np.asarray(g._indices)) <= touched


def test_dot_csr_transpose_backward():
    # y = csr.T @ h: grad wrt h = csr @ cot (regression: silent zero grad)
    csr, dense = _random_csr(6, 15, 0.25, seed=12)
    h = nd.array(np.random.RandomState(7).randn(6, 3).astype(np.float32))
    h.attach_grad()
    with autograd.record():
        y = nd.dot(csr, h, transpose_a=True)
        loss = y.todense().sum()
    loss.backward()
    ref = dense @ np.ones((15, 3), np.float32)
    np.testing.assert_allclose(h.grad.asnumpy(), ref, rtol=1e-4, atol=1e-4)


def test_sparse_dispatch_out_kwarg():
    csr, dense = _random_csr(5, 9, 0.4, seed=13)
    rhs = np.random.randn(9, 2).astype(np.float32)
    buf = nd.zeros((5, 2))
    res = nd.op.dot(csr, nd.array(rhs), out=buf)
    assert res is buf
    np.testing.assert_allclose(buf.asnumpy(), dense @ rhs, rtol=1e-5,
                               atol=1e-5)
    # row_sparse result into a row_sparse out buffer
    rhs2 = np.random.randn(5, 2).astype(np.float32)
    rsp_buf = sparse.zeros("row_sparse", (9, 2))
    nd.op.dot(csr, nd.array(rhs2), transpose_a=True, out=rsp_buf)
    np.testing.assert_allclose(rsp_buf.todense().asnumpy(), dense.T @ rhs2,
                               rtol=1e-5, atol=1e-5)


def test_embedding_sparse_grad_row_sparse():
    W = np.random.RandomState(0).randn(40, 6).astype(np.float32)
    w = nd.array(W)
    w.attach_grad(stype="row_sparse")
    ids = np.array([[5, 9, 5], [17, 9, 0]], np.float32)
    with autograd.record():
        e = nd.Embedding(nd.array(ids), w, input_dim=40, output_dim=6,
                         sparse_grad=True)
        loss = e.sum()
    loss.backward()
    g = w.grad
    assert isinstance(g, sparse.RowSparseNDArray)
    assert sorted(np.asarray(g._indices)) == [0, 5, 9, 17]
    ref = np.zeros_like(W)
    for i in ids.reshape(-1).astype(int):
        ref[i] += 1.0
    np.testing.assert_allclose(g.todense().asnumpy(), ref, atol=1e-6)


def test_embedding_sparse_grad_dense_buffer_densifies():
    # dense grad buffer still receives the correct (densified) gradient
    w = nd.array(np.random.randn(20, 3).astype(np.float32))
    w.attach_grad()
    with autograd.record():
        e = nd.Embedding(nd.array(np.array([1.0, 3.0])), w, input_dim=20,
                         output_dim=3, sparse_grad=True)
        e.sum().backward()
    g = w.grad.asnumpy()
    assert g[1].sum() == pytest.approx(3.0)
    assert g[3].sum() == pytest.approx(3.0)
    assert np.abs(g[[0, 2, 4]]).sum() == 0.0


def test_grad_accumulation_sparse_plus_sparse():
    w = nd.array(np.zeros((30, 2), np.float32))
    w.attach_grad(stype="row_sparse")
    with autograd.record():
        e1 = nd.Embedding(nd.array(np.array([2.0])), w, input_dim=30,
                          output_dim=2, sparse_grad=True)
        e2 = nd.Embedding(nd.array(np.array([2.0, 7.0])), w, input_dim=30,
                          output_dim=2, sparse_grad=True)
        (e1.sum() + e2.sum()).backward()
    g = w.grad
    assert sorted(np.asarray(g._indices)) == [2, 7]
    dense = g.todense().asnumpy()
    assert dense[2].sum() == pytest.approx(4.0)  # 2 + 2
    assert dense[7].sum() == pytest.approx(2.0)


def test_grad_accumulation_sparse_plus_dense_densifies():
    w = nd.array(np.ones((10, 2), np.float32))
    w.attach_grad()  # dense buffer
    with autograd.record():
        e = nd.Embedding(nd.array(np.array([4.0])), w, input_dim=10,
                         output_dim=2, sparse_grad=True)
        dense_path = (w * 2.0).sum()
        (e.sum() + dense_path).backward()
    g = w.grad.asnumpy()
    assert g[4].sum() == pytest.approx(2 * 2 + 2)  # 2 from dense, 1+1 embed
    assert g[0].sum() == pytest.approx(4.0)


# ---------------------------------------------------------------------------
# lazy optimizer updates
# ---------------------------------------------------------------------------

def _rsp_grad(shape, rows, seed=0):
    rs = np.random.RandomState(seed)
    data = rs.randn(len(rows), *shape[1:]).astype(np.float32)
    import jax.numpy as jnp
    return sparse.RowSparseNDArray(jnp.asarray(data),
                                   np.asarray(rows, np.int32), shape)


@pytest.mark.parametrize("name,kwargs", [
    ("sgd", dict(learning_rate=0.1)),
    ("sgd", dict(learning_rate=0.1, momentum=0.9)),
    ("adam", dict(learning_rate=0.01)),
])
def test_lazy_update_touches_only_grad_rows(name, kwargs):
    w = nd.array(np.random.RandomState(1).randn(25, 4).astype(np.float32))
    o = opt.create(name, wd=0.01, **kwargs)
    state = o.create_state(0, w)
    g = _rsp_grad((25, 4), [3, 11, 19], seed=2)
    before = w.asnumpy().copy()
    o.update(0, w, g, state)
    after = w.asnumpy()
    untouched = [i for i in range(25) if i not in (3, 11, 19)]
    np.testing.assert_array_equal(after[untouched], before[untouched])
    assert not np.allclose(after[[3, 11, 19]], before[[3, 11, 19]])


def test_lazy_sgd_matches_dense_on_touched_rows():
    rows = [1, 6, 7]
    w1 = nd.array(np.random.RandomState(4).randn(10, 3).astype(np.float32))
    w2 = nd.array(w1.asnumpy())
    g = _rsp_grad((10, 3), rows, seed=5)
    o = opt.create("sgd", learning_rate=0.2, wd=0.1)
    o.update(0, w1, g, None)
    o2 = opt.create("sgd", learning_rate=0.2, wd=0.1, lazy_update=False)
    o2.update(0, w2, g, None)  # densified standard update
    np.testing.assert_allclose(w1.asnumpy()[rows], w2.asnumpy()[rows],
                               rtol=1e-5, atol=1e-6)


def test_lazy_update_convergence_logistic():
    """Sparse logistic regression with Adam lazy updates converges
    (the VERDICT 'done' criterion for the lazy_update path)."""
    rs = np.random.RandomState(0)
    n, d, nnz = 512, 400, 12
    w_true = rs.randn(d).astype(np.float32)
    cols = np.stack([rs.choice(d, nnz, replace=False) for _ in range(n)])
    vals = rs.randn(n, nnz).astype(np.float32)
    y = ((w_true[cols] * vals).sum(1) > 0).astype(np.float32)

    w = nd.zeros((d, 1))
    adam = opt.create("adam", learning_rate=0.05)
    state = adam.create_state(0, w)
    import jax.numpy as jnp
    bs = 64
    for epoch in range(6):
        correct = 0
        for b0 in range(0, n, bs):
            sl = slice(b0, b0 + bs)
            indptr = np.arange(bs + 1, dtype=np.int32) * nnz
            X = sparse.CSRNDArray(jnp.asarray(vals[sl].reshape(-1)),
                                  cols[sl].reshape(-1).astype(np.int32),
                                  indptr, (bs, d))
            yn = nd.array(y[sl])
            w.attach_grad(stype="row_sparse")
            with autograd.record():
                logits = sparse.dot(X, w).reshape((-1,))
                loss = (nd.op.relu(logits) - logits * yn +
                        nd.op.Activation(-nd.op.abs(logits),
                                         act_type="softrelu")).mean()
            loss.backward()
            adam.update(0, w, w.grad, state)
            correct += int(((logits.asnumpy() > 0) == y[sl]).sum())
    assert correct / n > 0.9


# ---------------------------------------------------------------------------
# kvstore row_sparse push
# ---------------------------------------------------------------------------

def test_kvstore_push_row_sparse():
    from mxnet_tpu import kvstore as kv_mod
    kv = kv_mod.create("local")
    kv.init("w", nd.zeros((12, 2)))
    kv.set_updater(lambda key, grad, stored:
                   stored.__setitem__(slice(None), (stored + grad.todense())
                                      if isinstance(grad,
                                                    sparse.RowSparseNDArray)
                                      else (stored + grad)))
    g1 = _rsp_grad((12, 2), [1, 5], seed=1)
    g2 = _rsp_grad((12, 2), [5, 9], seed=2)
    kv.push("w", [g1, g2])
    out = nd.zeros((12, 2))
    kv.pull("w", out=out)
    ref = g1.todense().asnumpy() + g2.todense().asnumpy()
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-6)


def test_kvstore_push_row_sparse_no_updater_replaces():
    # replace semantics, like the dense push path: the store becomes the
    # pushed value (untouched rows zero), not a mix with stale contents
    from mxnet_tpu import kvstore as kv_mod
    kv = kv_mod.create("local")
    kv.init("w", nd.array(np.ones((8, 2), np.float32)))
    g = _rsp_grad((8, 2), [2, 6], seed=3)
    kv.push("w", g)
    out = nd.zeros((8, 2))
    kv.pull("w", out=out)
    res = out.asnumpy()
    np.testing.assert_array_equal(res[[0, 1, 3, 4, 5, 7]],
                                  np.zeros((6, 2), np.float32))
    np.testing.assert_allclose(res[[2, 6]], np.asarray(g._data), atol=1e-6)


# ---------------------------------------------------------------------------
# other sparse kernels + fallback discipline
# ---------------------------------------------------------------------------

def test_elemwise_add_rsp_rsp():
    a = _rsp_grad((9, 3), [0, 4], seed=6)
    b = _rsp_grad((9, 3), [4, 8], seed=7)
    out = sparse.add(a, b)
    assert out.stype == "row_sparse"
    assert sorted(np.asarray(out._indices)) == [0, 4, 8]
    np.testing.assert_allclose(out.todense().asnumpy(),
                               a.todense().asnumpy() + b.todense().asnumpy(),
                               rtol=1e-6)


def test_mask_pack_roundtrip_preserves_zero_rows():
    # a pushed row whose gradient is exactly zero must survive the packed
    # reduce (lazy updates still apply wd/momentum to it)
    import jax.numpy as jnp
    data = np.array([[0.0, 0.0], [1.5, -2.0]], np.float32)
    rsp = sparse.RowSparseNDArray(jnp.asarray(data),
                                  np.array([3, 7], np.int32), (10, 2))
    packed = sparse.mask_pack(rsp)
    assert packed.shape == (10, 3)
    back = sparse.mask_unpack(packed, (10, 2))
    assert sorted(np.asarray(back._indices)) == [3, 7]
    np.testing.assert_allclose(back.todense().asnumpy(),
                               rsp.todense().asnumpy(), atol=1e-6)


@pytest.mark.parametrize("axis,keepdims", [(None, False), (0, False),
                                           (1, False), (1, True),
                                           ((0, 1), False)])
def test_sum_csr(axis, keepdims):
    csr, dense = _random_csr(7, 11, 0.3, seed=9)
    out = nd.op.sum(csr, axis=axis, keepdims=keepdims)
    ref = dense.sum(axis=axis, keepdims=keepdims)
    np.testing.assert_allclose(np.asarray(out.asnumpy()).reshape(ref.shape)
                               if hasattr(ref, "shape") else out.asnumpy(),
                               ref, rtol=1e-5, atol=1e-5)


def test_dense_fallback_warns_and_computes():
    from mxnet_tpu.ops import registry as reg
    reg._FALLBACK_WARNED.clear()
    csr, dense = _random_csr(5, 6, 0.4, seed=10)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        out = nd.op.tanh(csr)
    assert any("falling back to dense" in str(w.message) for w in caught)
    np.testing.assert_allclose(out.asnumpy(), np.tanh(dense), rtol=1e-5,
                               atol=1e-5)
    # warned once only
    with warnings.catch_warnings(record=True) as caught2:
        warnings.simplefilter("always")
        nd.op.tanh(csr)
    assert not any("falling back" in str(w.message) for w in caught2)


def test_gluon_embedding_sparse_grad_end_to_end():
    from mxnet_tpu import gluon
    layer = gluon.nn.Embedding(30, 4, sparse_grad=True)
    layer.initialize()
    x = nd.array(np.array([[1.0, 2.0], [2.0, 9.0]]))
    with autograd.record():
        out = layer(x)
        out.sum().backward()
    g = layer.weight.grad()
    assert isinstance(g, sparse.RowSparseNDArray)
    assert sorted(np.asarray(g._indices)) == [1, 2, 9]
    # trainer step consumes the sparse grad through the lazy path
    trainer = gluon.Trainer(layer.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    before = layer.weight.data().asnumpy().copy()
    trainer.step(1)
    after = layer.weight.data().asnumpy()
    untouched = [i for i in range(30) if i not in (1, 2, 9)]
    np.testing.assert_array_equal(after[untouched], before[untouched])
    assert not np.allclose(after[[1, 2, 9]], before[[1, 2, 9]])


# ---------------------------------------------------------------------------
# jit trace-path round-trips (the megastep discipline: row_sparse crosses
# into a jitted program as a FIXED-SHAPE dense packed buffer; nnz varies
# per step, the compiled program does not)
# ---------------------------------------------------------------------------

def test_mask_pack_is_a_fixed_shape_jit_boundary():
    """mask_pack -> jitted dense reduce -> mask_unpack: the row_sparse ->
    dense boundary inside a jitted program. The program traces ONCE for
    the dense packed shape while nnz varies per call, and the round-trip
    reassembles the union row set bitwise."""
    import jax
    import jax.numpy as jnp
    traces = []

    @jax.jit
    def reduce_two(a, b):
        traces.append(1)
        summed = a + b  # the dense cross-worker reduce
        mask = (summed[:, -1:] > 0).astype(a.dtype)
        return jnp.concatenate([summed[:, :-1], mask], axis=1)

    shape = (10, 3)
    for seed, (r1, r2) in enumerate([([1, 4], [4, 7]),
                                     ([0, 2, 9], [2]),
                                     ([5], [5])]):
        g1 = _rsp_grad(shape, r1, seed=20 + seed)
        g2 = _rsp_grad(shape, r2, seed=40 + seed)
        packed = reduce_two(sparse.mask_pack(g1)._data,
                            sparse.mask_pack(g2)._data)
        back = sparse.mask_unpack(nd.from_jax(packed), shape)
        assert sorted(np.asarray(back._indices)) == \
            sorted(set(r1) | set(r2))
        np.testing.assert_array_equal(
            back.todense().asnumpy(),
            g1.todense().asnumpy() + g2.todense().asnumpy())
    assert len(traces) == 1  # nnz varied three ways, the program replayed


def test_mask_pack_jit_reduce_keeps_cancelled_rows():
    """A row whose reduced gradient sums to exactly zero must survive the
    jitted reduce via the mask column (lazy updates still apply wd /
    momentum to every pushed row — dropping it would silently skip them)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def reduce_two(a, b):
        summed = a + b
        mask = (summed[:, -1:] > 0).astype(a.dtype)
        return jnp.concatenate([summed[:, :-1], mask], axis=1)

    shape = (8, 2)
    g1 = _rsp_grad(shape, [4], seed=3)
    g2 = sparse.RowSparseNDArray(-g1._data, np.array([4], np.int32), shape)
    packed = reduce_two(sparse.mask_pack(g1)._data,
                        sparse.mask_pack(g2)._data)
    back = sparse.mask_unpack(nd.from_jax(packed), shape)
    assert list(np.asarray(back._indices)) == [4]
    np.testing.assert_array_equal(np.asarray(back._data),
                                  np.zeros((1, 2), np.float32))


def test_autograd_row_sparse_grad_through_jitted_program_matches_eager():
    """End-to-end over the real autograd product: an Embedding
    sparse_grad backward's row_sparse gradient rides mask_pack through a
    jitted dense transform and unpacks to the same rows and values the
    eager dense path computes."""
    import jax

    W = np.random.RandomState(8).randn(20, 4).astype(np.float32)
    w = nd.array(W)
    w.attach_grad(stype="row_sparse")
    with autograd.record():
        e = nd.Embedding(nd.array(np.array([3.0, 11.0, 3.0])), w,
                         input_dim=20, output_dim=4, sparse_grad=True)
        (e * e).sum().backward()
    g = w.grad
    assert isinstance(g, sparse.RowSparseNDArray)

    @jax.jit
    def halve(packed):
        return packed.at[:, :-1].multiply(0.5)  # data halved, mask kept

    back = sparse.mask_unpack(
        nd.from_jax(halve(sparse.mask_pack(g)._data)), g.shape)
    assert sorted(np.asarray(back._indices)) == [3, 11]
    np.testing.assert_array_equal(back.todense().asnumpy(),
                                  g.todense().asnumpy() * 0.5)


def test_hybridize_sparse_grad_warns_but_correct():
    from mxnet_tpu import gluon
    layer = gluon.nn.Embedding(20, 3, sparse_grad=True)
    layer.initialize()
    layer.hybridize()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with autograd.record():
            out = layer(nd.array(np.array([4.0, 4.0])))
            out.sum().backward()
    assert any("row_sparse" in str(w.message) for w in caught)
    g = layer.weight.grad()
    assert isinstance(g, sparse.RowSparseNDArray)
    assert g.todense().asnumpy()[4].sum() == pytest.approx(6.0)
