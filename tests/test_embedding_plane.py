"""Sparse embedding plane (parallel/embedding_plane.py): row-wise table
sharding across a simulated world, fixed-shape mask-packed row-sparse
gradients through the row-gathered grouped update (optimizer/grouped.py
sparse_rows_update), lazily materialized 1/world per-rank optimizer
state pinned ledger-exact, kv_flake no-double-apply, sentinel skip +
rollback, and the registry lookup-serving tier (serving/lookup.py).

Marker ``sparse_plane`` (tier-1-safe: CPU, simulated worlds in-process;
the ledger is exact by construction there)."""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import gluon, nd
from mxnet_tpu import optimizer as opt_mod
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import chaos
from mxnet_tpu.optimizer import grouped as grouped_mod
from mxnet_tpu.parallel import embedding_plane as ep
from mxnet_tpu.telemetry import memory as mem

pytestmark = pytest.mark.sparse_plane


@pytest.fixture
def plane_on(monkeypatch):
    monkeypatch.setenv("MXTPU_SPARSE_PLANE", "on")


def _plane(name, rows=32, dim=4, world=4, opt=None, seed=0, **kw):
    opt = opt or opt_mod.Adam(learning_rate=0.05)
    return ep.EmbeddingPlane(name, rows=rows, dim=dim, world=world,
                             optimizer=opt, seed=seed, **kw), opt


def _steps(plane, n=4, rows=32, batch=6, dim=4, seed=1, ids_list=None):
    rs = np.random.RandomState(seed)
    for s in range(n):
        ids = (ids_list[s] if ids_list is not None
               else rs.randint(0, rows, size=batch))
        g = rs.randn(len(ids), dim).astype(np.float32)
        plane.step(ids, nd.array(g))


# ---------------------------------------------------------------------------
# env flags + pure partition/bucket helpers
# ---------------------------------------------------------------------------

def test_sparse_plane_flag_strict_parse(monkeypatch):
    for raw, want in (("on", True), ("1", True), ("true", True),
                      ("off", False), ("0", False), ("", False)):
        monkeypatch.setenv("MXTPU_SPARSE_PLANE", raw)
        assert ep.sparse_plane_requested() is want
    monkeypatch.delenv("MXTPU_SPARSE_PLANE", raising=False)
    assert ep.sparse_plane_requested() is False
    monkeypatch.setenv("MXTPU_SPARSE_PLANE", "yess")
    with pytest.raises(MXNetError, match="MXTPU_SPARSE_PLANE"):
        ep.sparse_plane_requested()


def test_sparse_max_rows_strict_parse(monkeypatch):
    monkeypatch.delenv("MXTPU_SPARSE_MAX_ROWS", raising=False)
    assert ep.sparse_max_rows() == 4096
    monkeypatch.setenv("MXTPU_SPARSE_MAX_ROWS", "64")
    assert ep.sparse_max_rows() == 64
    monkeypatch.setenv("MXTPU_SPARSE_MAX_ROWS", "four")
    with pytest.raises(MXNetError, match="MXTPU_SPARSE_MAX_ROWS"):
        ep.sparse_max_rows()
    monkeypatch.setenv("MXTPU_SPARSE_MAX_ROWS", "0")
    with pytest.raises(MXNetError, match="MXTPU_SPARSE_MAX_ROWS"):
        ep.sparse_max_rows()


def test_plane_requires_explicit_opt_in(monkeypatch):
    monkeypatch.delenv("MXTPU_SPARSE_PLANE", raising=False)
    with pytest.raises(MXNetError, match="MXTPU_SPARSE_PLANE"):
        ep.EmbeddingPlane("t", rows=8, dim=2, world=2,
                          optimizer=opt_mod.Adam())


def test_row_partition_contiguous_and_strict():
    assert ep.row_partition(12, 3) == [(0, 4), (4, 8), (8, 12)]
    assert ep.row_partition(8, 1) == [(0, 8)]
    with pytest.raises(MXNetError, match="divide the world"):
        ep.row_partition(10, 4)
    with pytest.raises(MXNetError):
        ep.row_partition(8, 0)


def test_row_bucket_policy(monkeypatch):
    monkeypatch.setenv("MXTPU_SPARSE_MAX_ROWS", "64")
    assert ep.row_bucket(1) == 8      # floor
    assert ep.row_bucket(8) == 8
    assert ep.row_bucket(9) == 16     # next pow2
    assert ep.row_bucket(33) == 64    # capped exactly at the ceiling
    with pytest.raises(MXNetError, match="MXTPU_SPARSE_MAX_ROWS"):
        ep.row_bucket(65)


# ---------------------------------------------------------------------------
# lookup + sharding invariants
# ---------------------------------------------------------------------------

def test_lookup_matches_todense(plane_on):
    plane, _ = _plane("t_lk", rows=64, dim=8, world=4)
    try:
        ids = np.array([0, 5, 5, 17, 63, 32, 16])  # dupes + shard edges
        out = plane.lookup(ids).asnumpy()
        np.testing.assert_array_equal(out, plane.todense()[ids])
        with pytest.raises(MXNetError, match="lookup ids outside"):
            plane.lookup(np.array([64]))
        with pytest.raises(MXNetError, match="lookup ids outside"):
            plane.lookup(np.array([-1]))
    finally:
        plane.close()


def test_init_is_world_invariant(plane_on):
    """The deterministic full-table init + pure contiguous split: every
    world size derives the SAME table bitwise (topology-portable)."""
    tables = []
    for world in (1, 2, 4):
        plane, _ = _plane(f"t_init{world}", rows=32, dim=4, world=world)
        tables.append(plane.todense())
        plane.close()
    np.testing.assert_array_equal(tables[0], tables[1])
    np.testing.assert_array_equal(tables[0], tables[2])


@pytest.mark.parametrize("mkopt", [
    lambda: opt_mod.Adam(learning_rate=0.05, wd=0.01),
    lambda: opt_mod.SGD(learning_rate=0.1, momentum=0.9),
    lambda: opt_mod.SGD(learning_rate=0.1, wd=0.01),
], ids=["adam", "sgd-mom", "sgd"])
def test_training_is_world_invariant_bitwise(plane_on, mkopt):
    """Tentpole acceptance: the sharded trajectory is BITWISE identical
    across world sizes — the shard update is the same rule-kernel math,
    only row ownership changes."""
    tables = []
    for world in (1, 2, 4):
        plane, _ = _plane(f"t_tw{world}", rows=32, dim=4, world=world,
                          opt=mkopt())
        _steps(plane, n=4)
        tables.append(plane.todense())
        plane.close()
    np.testing.assert_array_equal(tables[0], tables[1])
    np.testing.assert_array_equal(tables[0], tables[2])


def test_parity_vs_dense_gather_reference(plane_on):
    """Bitwise parity against an independent dense-gather reference: the
    full unsharded table stepped by the SAME grouped rule kernel on the
    gathered touched rows (gather -> kernel -> scatter, no plane, no
    sharding, no mask-pack)."""
    rows, dim, batch = 32, 4, 6
    plane, opt = _plane("t_par", rows=rows, dim=dim, world=4)
    ref_opt = opt_mod.Adam(learning_rate=0.05)
    kernel = grouped_mod._with_cast(
        grouped_mod._rule_for(ref_opt).make_kernel(ref_opt, True), False)
    kfn = jax.jit(kernel)
    ref = jnp.asarray(plane.todense())
    ref_state = (jnp.zeros((rows, dim), jnp.float32),
                 jnp.zeros((rows, dim), jnp.float32))
    try:
        rs = np.random.RandomState(1)
        import math
        for s in range(4):
            ids = rs.randint(0, rows, size=batch)
            g = rs.randn(batch, dim).astype(np.float32)
            plane.step(ids, nd.array(g))
            # the reference: same dedup + same segment-summed rows
            uids, inv = np.unique(ids, return_inverse=True)
            bucket = ep.row_bucket(len(uids))
            packed = ep._pack_fn(batch, bucket)(
                jnp.asarray(g), jnp.asarray(inv.astype(np.int32)))
            ref_opt._update_count(0)
            t = ref_opt._index_update_count[0]
            lr = ref_opt._get_lr(0) * math.sqrt(
                1 - ref_opt.beta2 ** t) / (1 - ref_opt.beta1 ** t)
            u = jnp.asarray(uids.astype(np.int32))
            gw = jnp.take(ref, u, axis=0)
            gs = tuple(jnp.take(a, u, axis=0) for a in ref_state)
            nw, ns = kfn(gw, packed[:len(uids)], gs,
                         jnp.asarray(lr, jnp.float32),
                         jnp.asarray(ref_opt._get_wd(0), jnp.float32),
                         jnp.asarray(ref_opt.rescale_grad, jnp.float32))
            ref = ref.at[u].set(nw)
            ref_state = tuple(a.at[u].set(b)
                              for a, b in zip(ref_state, ns))
        np.testing.assert_array_equal(plane.todense(), np.asarray(ref))
    finally:
        plane.close()


def test_step_touches_only_touched_rows(plane_on):
    plane, _ = _plane("t_touch", rows=32, dim=4, world=4)
    try:
        before = plane.todense().copy()
        ids = np.array([3, 17, 30])
        plane.step(ids, nd.array(np.ones((3, 4), np.float32)))
        after = plane.todense()
        untouched = [i for i in range(32) if i not in set(ids.tolist())]
        np.testing.assert_array_equal(after[untouched], before[untouched])
        assert not np.allclose(after[ids], before[ids])
    finally:
        plane.close()


# ---------------------------------------------------------------------------
# the ZeRO analog: 1/world ledger-exact per-rank bytes, lazy state
# ---------------------------------------------------------------------------

def test_rank_bytes_exactly_one_world(plane_on):
    """Acceptance bar: with every rank touched, each rank's params+state
    ledger bytes land at EXACTLY 1/world of the unsharded plane's."""
    rows, dim, world = 64, 8, 4
    cover = [np.arange(i, rows, 4) for i in range(4)]  # hits all rows
    p1, _ = _plane("t_b1", rows=rows, dim=dim, world=1)
    _steps(p1, n=4, rows=rows, dim=dim, ids_list=cover)
    unsharded = p1.rank_bytes(0)
    p1.close()
    # Adam on f32: params rows*dim*4, state (mean+var) twice that
    assert unsharded == 3 * rows * dim * 4
    p4, _ = _plane("t_b4", rows=rows, dim=dim, world=world)
    try:
        _steps(p4, n=4, rows=rows, dim=dim, ids_list=cover)
        per_rank = [p4.rank_bytes(r) for r in range(world)]
        assert per_rank == [unsharded // world] * world
        assert sum(per_rank) == unsharded
    finally:
        p4.close()


def test_state_is_lazy_per_rank(plane_on):
    """A rank whose rows were never touched holds params only — the
    reference's lazy row-sparse update discipline at shard granularity."""
    plane, _ = _plane("t_lazy", rows=64, dim=8, world=4)
    try:
        shard_bytes = 64 // 4 * 8 * 4
        assert [plane.rank_bytes(r) for r in range(4)] == [shard_bytes] * 4
        plane.step(np.array([0, 40]),  # ranks 0 and 2 only
                   nd.array(np.ones((2, 8), np.float32)))
        assert plane.rank_bytes(0) == 3 * shard_bytes
        assert plane.rank_bytes(2) == 3 * shard_bytes
        assert plane.rank_bytes(1) == shard_bytes  # untouched: no state
        assert plane.rank_bytes(3) == shard_bytes
        assert plane.describe()["ranks_with_state"] == 2
    finally:
        plane.close()


# ---------------------------------------------------------------------------
# retrace contract: warm steps replay, never recompile
# ---------------------------------------------------------------------------

def test_warm_steps_never_retrace_within_bucket(plane_on):
    plane, _ = _plane("t_warm", rows=32, dim=4, world=4)
    try:
        rs = np.random.RandomState(3)
        batch = 6
        ids = rs.randint(0, 32, size=batch)
        plane.step(ids, nd.array(rs.randn(batch, 4).astype(np.float32)))
        plane.lookup(ids)
        grouped_misses = grouped_mod._cache().cache_info().misses
        pack_size = ep._pack_fn.cache_info().currsize
        gather_size = ep._gather_fn.cache_info().currsize
        # warm steps: varying touched-row counts and rank subsets, same
        # batch size, all within the bucket -> zero new programs
        for n_unique in (1, 3, 6, 2, 5, 4):
            ids = np.resize(rs.choice(32, size=n_unique, replace=False),
                            batch)  # repeat ids up to the fixed batch
            plane.step(ids,
                       nd.array(rs.randn(batch, 4).astype(np.float32)))
            plane.lookup(ids)
        assert grouped_mod._cache().cache_info().misses == grouped_misses
        assert ep._pack_fn.cache_info().currsize == pack_size
        assert ep._gather_fn.cache_info().currsize == gather_size
    finally:
        plane.close()


# ---------------------------------------------------------------------------
# chaos: retried kv_flake never double-applies
# ---------------------------------------------------------------------------

@pytest.mark.chaos
def test_kv_flake_retry_never_double_applies(plane_on, monkeypatch):
    monkeypatch.setenv("MXNET_KV_RETRY_BASE_MS", "1")

    def run(name, spec):
        plan = None
        if spec:
            plan = chaos.ChaosPlan(spec, seed=7)
            chaos.install(plan)
        try:
            plane, _ = _plane(name, rows=32, dim=4, world=4)
            _steps(plane, n=4)
            out = plane.todense()
            plane.close()
        finally:
            if spec:
                chaos.uninstall()
        return out, plan

    clean, _ = run("t_cl", "")
    flaky, plan = run("t_fl", "kv_flake:0.3")
    assert plan.injected["kv_flake"] > 0
    np.testing.assert_array_equal(clean, flaky)


# ---------------------------------------------------------------------------
# sentinel skip + rollback
# ---------------------------------------------------------------------------

def test_sentinel_false_leaves_device_state_bitwise(plane_on):
    plane, opt = _plane("t_sent", rows=32, dim=4, world=4)
    led = mem.ledger()
    try:
        w0 = plane.todense().copy()
        base = led.live_bytes("optimizer", owner_prefix="state:emb")
        plane.step(np.array([1, 20]),
                   nd.array(np.ones((2, 4), np.float32)),
                   flag=jnp.asarray(False))
        # device half untouched; host half (count + lazily created
        # state arrays with their ledger bytes) pending rollback
        np.testing.assert_array_equal(plane.todense(), w0)
        assert led.live_bytes("optimizer",
                              owner_prefix="state:emb") > base
        assert opt._index_update_count[0] == 1
        plane.rollback_step()
        assert led.live_bytes("optimizer",
                              owner_prefix="state:emb") == base
        assert opt._index_update_count[0] == 0
        # the retried step is step 1 again (Adam bias correction replays)
        plane.step(np.array([1, 20]),
                   nd.array(np.ones((2, 4), np.float32)))
        assert opt._index_update_count[0] == 1
        assert not np.allclose(plane.todense(), w0)
    finally:
        plane.close()


def test_sentinel_true_applies(plane_on):
    plane, _ = _plane("t_sentok", rows=32, dim=4, world=2)
    try:
        w0 = plane.todense().copy()
        plane.step(np.array([1, 20]),
                   nd.array(np.ones((2, 4), np.float32)),
                   flag=jnp.asarray(True))
        assert not np.allclose(plane.todense(), w0)
    finally:
        plane.close()


def test_skipped_then_clean_matches_never_skipped(plane_on):
    """A sentinel-skipped + rolled-back step is indistinguishable from
    one that never ran: the subsequent trajectory is bitwise identical
    (the Trainer.rollback_step contract, row-sharded)."""
    def run(name, skip):
        plane, _ = _plane(name, rows=32, dim=4, world=4)
        rs = np.random.RandomState(5)
        for s in range(3):
            ids = rs.randint(0, 32, size=6)
            g = rs.randn(6, 4).astype(np.float32)
            if skip and s == 1:
                plane.step(ids, nd.array(g), flag=jnp.asarray(False))
                plane.rollback_step()
                continue
            if not skip and s == 1:
                continue  # the clean run never sees step 1's batch
            plane.step(ids, nd.array(g))
        out = plane.todense()
        plane.close()
        return out
    np.testing.assert_array_equal(run("t_sk", True), run("t_nk", False))


# ---------------------------------------------------------------------------
# grouped/zero dispatch seams (satellites 1 + 2)
# ---------------------------------------------------------------------------

def test_grouped_dense_raise_names_sparse_plane():
    """ONE documented raise for sparse storage in the fused dense path,
    and it names the MXTPU_SPARSE_PLANE opt-in (the doorway into
    sparse_rows_update)."""
    p = gluon.Parameter("emb_sp", shape=(8, 2), grad_stype="row_sparse")
    p.initialize(mx.init.One())

    class U:
        optimizer = opt_mod.Adam()
        states = {}

    with pytest.raises(MXNetError, match="MXTPU_SPARSE_PLANE"):
        grouped_mod.prepare_update(U(), [(0, p)])


def test_sparse_rows_update_rejects_unruled_optimizer(plane_on):
    class Weird(opt_mod.Optimizer):
        def create_state(self, index, weight):
            return None

        def update(self, index, weight, grad, state):
            pass

    with pytest.raises(MXNetError, match="no grouped-update rule"):
        ep.EmbeddingPlane("t_weird", rows=8, dim=2, world=2,
                          optimizer=Weird())


def test_zero_raise_names_embedding_plane(monkeypatch):
    """MXTPU_ZERO=1 with a sparse table in the Trainer: the creation
    raise points at the row-wise plane composition."""
    from mxnet_tpu import kvstore as kvs
    monkeypatch.setenv("MXTPU_ZERO", "1")
    monkeypatch.setenv("MXTPU_ZERO_WORLD", "2")
    p = gluon.Parameter("emb_z", shape=(8, 2), grad_stype="row_sparse")
    p.initialize(mx.init.One())
    tr = gluon.Trainer([p], "adam", {"learning_rate": 0.01},
                       kvstore=kvs.create("local"))
    from mxnet_tpu import autograd
    with autograd.record():
        e = nd.Embedding(nd.array(np.array([1.0])), p.data(), input_dim=8,
                         output_dim=2, sparse_grad=True)
        e.sum().backward()
    p._fresh_grad = True
    with pytest.raises(MXNetError, match="embedding_plane.EmbeddingPlane"):
        tr.step(1)


def test_dense_zero_composes_with_plane_in_one_loop(plane_on,
                                                    monkeypatch):
    """Satellite-2 regression: dense params ZeRO-sharded through the
    Trainer while the embedding table trains through the plane — one
    loop, two planes, both sharded, and the dense trajectory is bitwise
    the ZeRO-off trajectory."""
    from mxnet_tpu import kvstore as kvs

    def run(zero):
        if zero:
            monkeypatch.setenv("MXTPU_ZERO", "1")
            monkeypatch.setenv("MXTPU_ZERO_WORLD", "2")
        else:
            monkeypatch.delenv("MXTPU_ZERO", raising=False)
            monkeypatch.delenv("MXTPU_ZERO_WORLD", raising=False)
        tag = "zc" if zero else "nc"
        rs = np.random.RandomState(0)
        params = []
        for j in range(4):
            p = gluon.Parameter(f"{tag}{j}", shape=(4, 4))
            p.initialize(mx.init.Constant(0.0))
            p.set_data(nd.array(rs.randn(4, 4).astype(np.float32)))
            params.append(p)
        tr = gluon.Trainer(params, "adam", {"learning_rate": 0.01},
                           kvstore=kvs.create("local"))
        plane, _ = _plane(f"t_comp_{tag}", rows=16, dim=4, world=2)
        for _ in range(3):
            for p in params:
                g = nd.array(rs.randn(4, 4).astype(np.float32))
                p._grad._rebind(g._data)
                p._fresh_grad = True
            ids = rs.randint(0, 16, size=5)
            ge = rs.randn(5, 4).astype(np.float32)
            tr.step(4)
            plane.step(ids, nd.array(ge))
        dense = [p.data().asnumpy() for p in params]
        table = plane.todense()
        zero_on = bool(tr._zero)
        per_rank = [plane.rank_bytes(r) for r in range(2)]
        plane.close()
        return dense, table, zero_on, per_rank

    d_z, t_z, zon, per_rank = run(True)
    d_n, t_n, noff, _ = run(False)
    assert zon and not noff
    for a, b in zip(d_z, d_n):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(t_z, t_n)
    # both ranks touched (16 rows, 15 random draws): state everywhere
    assert per_rank[0] == per_rank[1] == 3 * 8 * 4 * 4


# ---------------------------------------------------------------------------
# serving: the registry lookup tier (serving/lookup.py)
# ---------------------------------------------------------------------------

def _tower(dim=8, seed=0):
    mx.random.seed(seed)
    net = gluon.nn.Dense(2, in_units=dim)
    net.initialize(mx.init.Xavier())
    with mx.autograd.pause():
        net(nd.ones((1, dim)))
    return net


@pytest.mark.serving
def test_lookup_serving_roundtrip(plane_on, tmp_path):
    from mxnet_tpu.serving import (LookupFleet, LookupReplica,
                                   ModelRegistry, publish_embedding)
    plane, _ = _plane("t_serve", rows=64, dim=8, world=4)
    try:
        _steps(plane, n=2, rows=64, dim=8)
        reg = ModelRegistry(str(tmp_path / "registry"))
        sig = {"bucket_shapes": [[8]], "dtype": "float32"}
        version = publish_embedding(reg, "two_tower", plane, _tower(),
                                    signature=sig)
        table = plane.todense()
        replica = LookupReplica(reg, "two_tower", version=version)
        assert (replica.rows, replica.dim, replica.world) == (64, 8, 4)
        ids = np.array([0, 17, 63, 17])
        np.testing.assert_array_equal(replica.lookup(ids), table[ids])
        # dense-tower + the combined recommend request
        out = replica.recommend(ids)
        assert out.shape == (4, 2)
        ref = replica.dense_tower(table[ids])
        np.testing.assert_array_equal(out, ref)
        # the fleet tier: round-robin spreads requests, metrics count
        fleet = LookupFleet(reg, "two_tower", replicas=2, version=version)
        for _ in range(6):
            fleet.lookup(ids)
        m = fleet.metrics_json()
        assert m["replicas"] == 2 and m["requests"] == 6
        assert m["lookup_qps"] > 0
        assert sorted(m["per_replica"].values()) == [3, 3]
        # the plane's metadata rode along in the manifest
        emb_meta = replica.resolved.manifest["metadata"]["embedding"]
        assert emb_meta["rows"] == 64 and emb_meta["world"] == 4
    finally:
        plane.close()


@pytest.mark.serving
def test_lookup_replica_requires_sidecar(plane_on, tmp_path):
    from mxnet_tpu.serving import LookupReplica, ModelRegistry
    reg = ModelRegistry(str(tmp_path / "registry"))
    reg.publish("plain", net=_tower(),
                signature={"bucket_shapes": [[8]], "dtype": "float32"})
    with pytest.raises(MXNetError, match="sidecar"):
        LookupReplica(reg, "plain")


# ---------------------------------------------------------------------------
# misc plane hygiene
# ---------------------------------------------------------------------------

def test_step_shape_mismatch_raises(plane_on):
    plane, _ = _plane("t_shape", rows=16, dim=4, world=2)
    try:
        with pytest.raises(MXNetError, match="gradient rows"):
            plane.step(np.array([1, 2, 3]),
                       nd.array(np.ones((2, 4), np.float32)))
    finally:
        plane.close()


def test_close_drops_ledger(plane_on):
    led = mem.ledger()
    plane, _ = _plane("t_close", rows=16, dim=4, world=2)
    plane.step(np.array([1]), nd.array(np.ones((1, 4), np.float32)))
    own = mem.plane_owner(0, 2, "t_close")
    assert led.live_bytes("params", owner_prefix=own) > 0
    plane.close()
    assert led.live_bytes("params", owner_prefix=own) == 0
    assert led.live_bytes(
        "optimizer",
        owner_prefix=mem.plane_owner(0, 2, "t_close", state=True)) == 0
