"""8-bit activation-residual training mode (MXNET_RESID_DTYPE, ops/resid8.py).

The mode stores backward residuals fp8: dx must stay EXACT for convs
(backward-input needs only weights), dW and BN param grads see only small
zero-mean rounding noise, and toggling the env flag must actually change
the compiled kernels (trace-time flags are part of every jit-cache key).
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.gluon import nn, loss as gloss

RS = np.random.RandomState(7)


@pytest.fixture
def fp8_mode():
    os.environ["MXNET_RESID_DTYPE"] = "fp8"
    try:
        yield
    finally:
        os.environ["MXNET_RESID_DTYPE"] = ""


def _convnet():
    np.random.seed(0)
    mx.random.seed(0)
    net = nn.HybridSequential(prefix="")
    net.add(nn.Conv2D(8, 3, padding=1, use_bias=False, in_channels=3,
                      layout="NHWC"))
    net.add(nn.BatchNorm(axis=-1))
    net.add(nn.Activation("relu"))
    net.add(nn.Conv2D(16, 3, padding=1, use_bias=False, in_channels=8,
                      layout="NHWC"))
    net.add(nn.BatchNorm(axis=-1))
    net.add(nn.Activation("relu"))
    net.add(nn.GlobalAvgPool2D(layout="NHWC"))
    net.add(nn.Dense(5))
    net.initialize(mx.init.Xavier())
    return net


def _grads(hybridize=False):
    x = np.random.RandomState(1).rand(8, 12, 12, 3).astype(np.float32)
    y = np.random.RandomState(2).randint(0, 5, 8).astype(np.float32)
    net = _convnet()
    if hybridize:
        net.hybridize()
    lossfn = gloss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        loss = lossfn(net(mx.nd.array(x)), mx.nd.array(y))
    loss.backward()
    grads = [p.grad().asnumpy()
             for _, p in sorted(net.collect_params().items())
             if p.grad_req != "null"]
    return float(loss.mean().asnumpy()), grads


def test_conv_dx_exact_dw_noisy():
    """dx needs only weights (exact); dW reads the fp8 input (small,
    nonzero rounding error) — the defining property of the mode."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import resid8

    x = jnp.asarray(RS.rand(2, 6, 6, 3).astype(np.float32))
    w = jnp.asarray((RS.rand(4, 3, 3, 3) - 0.5).astype(np.float32))
    dy = jnp.asarray(RS.rand(2, 6, 6, 4).astype(np.float32))

    def plain(d, ww):
        dn = jax.lax.conv_dimension_numbers(
            d.shape, ww.shape, ("NHWC", "OHWI", "NHWC"))
        return jax.lax.conv_general_dilated(
            d, ww, (1, 1), [(1, 1), (1, 1)], dimension_numbers=dn)

    def r8(d, ww):
        return resid8.conv_resid8(d, ww, (1, 1), (1, 1), (1, 1),
                                  ("NHWC", "OHWI", "NHWC"), 1,
                                  "float8_e4m3fn")

    _, vjp0 = jax.vjp(plain, x, w)
    _, vjp8 = jax.vjp(r8, x, w)
    (dx0, dw0), (dx8, dw8) = vjp0(dy), vjp8(dy)
    assert float(jnp.abs(dx0 - dx8).max()) == 0.0
    rel = float(jnp.abs(dw0 - dw8).max() / jnp.abs(dw0).max())
    assert 1e-5 < rel < 0.05, rel


def test_outlier_activations_saturate_not_nan():
    """|x| > fp8-max (448 for e4m3) must clamp, not overflow: XLA's
    f32->fp8 cast rounds out-of-range values to NaN (e4m3fn) / inf
    (e5m2), and one NaN residual poisons dW for the whole layer and
    zeroes relu grads (NaN > 0 is False). Regression for the round-4
    advisor finding."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import resid8

    for rdt in ("float8_e4m3fn", "float8_e5m2"):
        big = float(jnp.finfo(jnp.dtype(rdt)).max) * 4.0
        x = jnp.asarray(RS.rand(2, 6, 6, 3).astype(np.float32)) * big
        w = jnp.asarray((RS.rand(4, 3, 3, 3) - 0.5).astype(np.float32))
        dy = jnp.ones((2, 6, 6, 4), np.float32)

        # conv residual: dW must be finite and ~match the exact dW
        _, vjp8 = jax.vjp(
            lambda d, ww: resid8.conv_resid8(
                d, ww, (1, 1), (1, 1), (1, 1),
                ("NHWC", "OHWI", "NHWC"), 1, rdt), x, w)
        dx8, dw8 = vjp8(dy)
        assert np.isfinite(np.asarray(dw8)).all(), rdt
        assert np.isfinite(np.asarray(dx8)).all(), rdt

        # relu residual: grads where y > fp8-max must pass dy, not zero
        _, vr = jax.vjp(lambda v: resid8.relu_resid8(v, rdt),
                        jnp.full((8,), big, jnp.float32))
        assert np.asarray(vr(jnp.ones(8, np.float32))[0]).min() == 1.0

        # BN xhat residual (ops/nn.py fwd): xhat is normalized so its
        # max is ~sqrt(N) for a lone spike among N elements — use
        # N > fp8_max^2 per channel so the spike's xhat overflows fp8
        if rdt == "float8_e4m3fn":  # e5m2 max is 57344: N would be 3e9
            from mxnet_tpu.ops.nn import _make_bn_core
            core = _make_bn_core(rdt)
            xnp = np.zeros((1, 500, 500, 2), np.float32)  # N=250k > 448^2
            xnp[0, 0, 0, :] = 1e6
            xb = jnp.asarray(xnp)

            def f(d):
                out, _, _ = core(d, jnp.ones(2, jnp.float32),
                                 jnp.zeros(2, jnp.float32), 3, 1e-5)
                return out
            out, vb = jax.vjp(f, xb)
            # confirm the construction actually exceeds the fp8 range
            assert float(jnp.abs(out).max()) > 448.0
            assert np.isfinite(
                np.asarray(vb(jnp.ones_like(xb))[0])).all(), rdt


def test_relu_mask_from_fp8_copy():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import resid8

    x = jnp.asarray((RS.rand(64) - 0.5).astype(np.float32))
    dy = jnp.asarray(RS.rand(64).astype(np.float32))
    _, v0 = jax.vjp(lambda v: jnp.maximum(v, 0), x)
    _, v8 = jax.vjp(lambda v: resid8.relu_resid8(v, "float8_e4m3fn"), x)
    # mask survives the fp8 round-trip bit-exactly away from denormals
    assert float(jnp.abs(v0(dy)[0] - v8(dy)[0]).max()) == 0.0


def test_bn_core_fp8_residual_close():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.nn import _make_bn_core

    xb = jnp.asarray(RS.rand(8, 6, 6, 5).astype(np.float32) * 3 + 1)
    g32 = jnp.asarray(RS.rand(5).astype(np.float32) + 0.5)
    b32 = jnp.asarray(RS.rand(5).astype(np.float32))
    dyb = jnp.asarray((RS.rand(8, 6, 6, 5) - 0.5).astype(np.float32))

    def run(core):
        def f(d, g, b):
            out, _, _ = core(d, g, b, 3, 1e-5)
            return out
        _, vjp = jax.vjp(f, xb, g32, b32)
        return vjp(dyb)

    exact = run(_make_bn_core(None))
    quant = run(_make_bn_core("float8_e4m3fn"))
    for a, b in zip(exact, quant):
        rel = float(jnp.abs(a - b).max() / (jnp.abs(a).max() + 1e-9))
        assert rel < 0.05, rel


def test_net_grads_close_and_env_actually_switches(fp8_mode):
    """Whole-net grads under fp8 residuals stay within a few percent of
    exact AND genuinely differ (regression: trace-time env flags must be
    in the op/vjp jit-cache keys, else toggling is a silent no-op)."""
    os.environ["MXNET_RESID_DTYPE"] = ""
    l0, g0 = _grads()
    os.environ["MXNET_RESID_DTYPE"] = "fp8"
    l8, g8 = _grads()
    assert abs(l0 - l8) < 1e-4  # forward is untouched
    diffs = [np.abs(a - b).max() / max(np.abs(a).max(), 1e-6)
             for a, b in zip(g0, g8)]
    assert max(diffs) > 1e-5, "fp8 mode did not engage (stale jit cache?)"
    # compare only params with non-degenerate gradients: exact-zero
    # cancellation grads (e.g. conv bias feeding BN) have no meaningful
    # relative error
    for a, b in zip(g0, g8):
        if np.abs(a).max() > 1e-4:
            rel = np.abs(a - b).max() / np.abs(a).max()
            assert rel < 0.1, rel


def test_eager_hybrid_agree_under_fp8(fp8_mode):
    l_e, g_e = _grads(hybridize=False)
    l_h, g_h = _grads(hybridize=True)
    assert abs(l_e - l_h) < 1e-4
    for a, b in zip(g_e, g_h):
        assert np.abs(a - b).max() / max(np.abs(a).max(), 1e-6) < 2e-2


def test_training_converges_under_fp8(fp8_mode):
    from mxnet_tpu import gluon
    net = _convnet()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.3, "momentum": 0.9})
    lossfn = gloss.SoftmaxCrossEntropyLoss()

    def make_data(n):
        y = np.random.randint(0, 3, n)
        x = np.random.rand(n, 8, 8, 3).astype(np.float32) * 0.3
        for i, c in enumerate(y):
            x[i, :, :, c] += 1.0
        return x, y.astype(np.float32)

    first = last = None
    for _ in range(25):
        x, y = make_data(64)
        with autograd.record():
            loss = lossfn(net(mx.nd.array(x)), mx.nd.array(y))
        loss.backward()
        tr.step(64)
        last = float(loss.mean().asnumpy())
        first = first if first is not None else last
    assert last < first * 0.5, (first, last)


def test_spmd_trainer_under_fp8(fp8_mode):
    """The bench path: SPMDTrainer fused step with fp8 residuals."""
    import jax.numpy as jnp
    from mxnet_tpu.parallel import SPMDTrainer
    net = _convnet()
    tr = SPMDTrainer(net, gloss.SoftmaxCrossEntropyLoss(),
                     optimizer="sgd",
                     optimizer_params={"learning_rate": 0.1},
                     dtype=jnp.bfloat16)
    x = jnp.asarray(RS.rand(2, 8, 12, 12, 3).astype(np.float32))
    y = jnp.asarray(RS.randint(0, 5, (2, 8)).astype(np.float32))
    losses = tr.run_steps(x, y)
    assert np.isfinite(np.asarray(losses)).all()
