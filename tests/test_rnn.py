"""RNN tests (model: tests/python/unittest/test_gluon_rnn.py +
test_operator.py RNN consistency checks). The fused op is verified against
torch's LSTM/GRU/RNN with identical packed weights."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import rnn


def test_rnn_cell_forward():
    cell = rnn.RNNCell(8, input_size=4)
    cell.initialize()
    x = nd.ones((2, 4))
    states = cell.begin_state(batch_size=2)
    out, new_states = cell(x, states)
    assert out.shape == (2, 8)
    assert new_states[0].shape == (2, 8)


def test_lstm_cell_unroll():
    cell = rnn.LSTMCell(6, input_size=3)
    cell.initialize()
    x = nd.ones((2, 5, 3))  # NTC
    outputs, states = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outputs.shape == (2, 5, 6)
    assert len(states) == 2


def test_gru_cell():
    cell = rnn.GRUCell(6, input_size=3)
    cell.initialize()
    out, states = cell(nd.ones((2, 3)), cell.begin_state(2))
    assert out.shape == (2, 6)


def test_sequential_stack():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(8, input_size=4))
    stack.add(rnn.LSTMCell(8, input_size=8))
    stack.initialize()
    outputs, states = stack.unroll(3, nd.ones((2, 3, 4)), layout="NTC",
                                   merge_outputs=True)
    assert outputs.shape == (2, 3, 8)
    assert len(states) == 4


def test_fused_lstm_layer_shapes():
    layer = rnn.LSTM(16, num_layers=2, input_size=8)
    layer.initialize()
    x = nd.ones((5, 3, 8))  # TNC
    out = layer(x)
    assert out.shape == (5, 3, 16)
    states = layer.begin_state(batch_size=3)
    out, new_states = layer(x, states)
    assert out.shape == (5, 3, 16)
    assert new_states[0].shape == (2, 3, 16)
    assert new_states[1].shape == (2, 3, 16)


def test_fused_bidirectional():
    layer = rnn.LSTM(8, num_layers=1, bidirectional=True, input_size=4)
    layer.initialize()
    out = layer(nd.ones((6, 2, 4)))
    assert out.shape == (6, 2, 16)


def test_fused_lstm_matches_torch():
    torch = pytest.importorskip("torch")
    T, N, I, H = 5, 3, 4, 6
    rs = np.random.RandomState(0)
    x = rs.randn(T, N, I).astype(np.float32)

    t_lstm = torch.nn.LSTM(I, H, num_layers=1)
    layer = rnn.LSTM(H, num_layers=1, input_size=I)
    layer.initialize()
    # copy torch weights (torch gate order i,f,g,o matches ours)
    layer.l0_i2h_weight.set_data(nd.array(
        t_lstm.weight_ih_l0.detach().numpy()))
    layer.l0_h2h_weight.set_data(nd.array(
        t_lstm.weight_hh_l0.detach().numpy()))
    layer.l0_i2h_bias.set_data(nd.array(t_lstm.bias_ih_l0.detach().numpy()))
    layer.l0_h2h_bias.set_data(nd.array(t_lstm.bias_hh_l0.detach().numpy()))

    out = layer(nd.array(x))
    t_out, _ = t_lstm(torch.tensor(x))
    assert np.allclose(out.asnumpy(), t_out.detach().numpy(), atol=1e-5)


def test_fused_gru_matches_torch():
    torch = pytest.importorskip("torch")
    T, N, I, H = 4, 2, 3, 5
    rs = np.random.RandomState(1)
    x = rs.randn(T, N, I).astype(np.float32)
    t_gru = torch.nn.GRU(I, H, num_layers=1)
    layer = rnn.GRU(H, num_layers=1, input_size=I)
    layer.initialize()
    layer.l0_i2h_weight.set_data(nd.array(t_gru.weight_ih_l0.detach().numpy()))
    layer.l0_h2h_weight.set_data(nd.array(t_gru.weight_hh_l0.detach().numpy()))
    layer.l0_i2h_bias.set_data(nd.array(t_gru.bias_ih_l0.detach().numpy()))
    layer.l0_h2h_bias.set_data(nd.array(t_gru.bias_hh_l0.detach().numpy()))
    out = layer(nd.array(x))
    t_out, _ = t_gru(torch.tensor(x))
    assert np.allclose(out.asnumpy(), t_out.detach().numpy(), atol=1e-5)


def test_lstm_backward_and_training():
    """Tiny sequence task: LSTM learns to output the running sign."""
    layer = rnn.LSTM(8, input_size=1)
    out_layer = gluon.nn.Dense(1, flatten=False)
    net_params = layer.collect_params()
    net_params.update(out_layer.collect_params())
    layer.initialize()
    out_layer.initialize()

    rs = np.random.RandomState(0)
    X = rs.randn(6, 16, 1).astype(np.float32)  # T N C
    Y = (np.cumsum(X, axis=0) > 0).astype(np.float32)

    trainer = gluon.Trainer(net_params, "adam", {"learning_rate": 0.02},
                            kvstore=None)
    lossfn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    first = None
    for i in range(40):
        with autograd.record():
            h = layer(nd.array(X))
            pred = out_layer(h)
            loss = lossfn(pred, nd.array(Y))
        loss.backward()
        trainer.step(batch_size=16)
        cur = float(loss.mean().asscalar())
        if first is None:
            first = cur
    assert cur < first * 0.7, f"{first} -> {cur}"


def test_bidirectional_cell():
    cell = rnn.BidirectionalCell(rnn.LSTMCell(4, input_size=2),
                                 rnn.LSTMCell(4, input_size=2))
    cell.initialize()
    outputs, states = cell.unroll(3, nd.ones((2, 3, 2)), layout="NTC")
    assert len(outputs) == 3
    assert outputs[0].shape == (2, 8)
