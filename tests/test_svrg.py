"""SVRG optimization module (ref: python/mxnet/contrib/svrg_optimization/
+ tests/python/unittest/test_contrib_svrg_module.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import sym
from mxnet_tpu.contrib.svrg_optimization import SVRGModule
from mxnet_tpu.io import NDArrayIter


def _linreg_problem(seed=0, n=64, d=4):
    rs = np.random.RandomState(seed)
    x = rs.randn(n, d).astype(np.float32)
    w_true = rs.randn(d).astype(np.float32)
    y = (x @ w_true).astype(np.float32)
    return x, y


def _linreg_sym():
    data = sym.var("data")
    pred = sym.FullyConnected(data, sym.var("fc_weight"),
                              sym.var("fc_bias"), num_hidden=1, name="fc")
    return sym.LinearRegressionOutput(pred, sym.var("lin_label"),
                                      name="lin")


def test_svrg_module_converges():
    x, y = _linreg_problem()
    train = NDArrayIter(x, y.reshape(-1, 1), batch_size=16,
                        label_name="lin_label")
    mod = SVRGModule(_linreg_sym(), label_names=("lin_label",))
    mod.fit_svrg(train, num_epoch=20, optimizer="sgd",
                 optimizer_params={"learning_rate": 0.1})
    # final weights close to ground truth => small residual
    train.reset()
    total = 0.0
    n = 0
    for batch in train:
        mod.forward(batch, is_train=False)
        out = mod.get_outputs()[0].asnumpy()
        lbl = batch.label[0].asnumpy().reshape(out.shape)
        total += float(((out - lbl) ** 2).sum())
        n += out.size
    assert total / n < 0.05, total / n


def test_svrg_take_snapshot_stores_params():
    """take_snapshot captures the current parameters for the full-batch
    gradient correction term."""
    x, y = _linreg_problem(seed=1)
    train = NDArrayIter(x, y.reshape(-1, 1), batch_size=16,
                        label_name="lin_label")
    mod = SVRGModule(_linreg_sym(), label_names=("lin_label",))
    mod.bind(data_shapes=train.provide_data,
             label_shapes=train.provide_label)
    mod.init_params()
    mod.take_snapshot(train)
    assert mod._snapshot_params is not None
    arg, _ = mod.get_params()
    for k, v in mod._snapshot_params.items():
        np.testing.assert_allclose(np.asarray(v.asnumpy()),
                                   np.asarray(arg[k].asnumpy()))
