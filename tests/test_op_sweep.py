"""Registry-driven operator correctness sweep.

For every entry of tests/op_cases.py:CASES:
  - forward: run the op eagerly and cross-check against the numpy ref
  - gradient: autograd vs central finite differences (differentiable ops)
  - dtype sweep: f16/bf16/f64 runs stay close to the f32 result
  - edge shapes: size-0 and single-element inputs execute and keep shape
    semantics (elementwise-classed cases)

Model: tests/python/unittest/test_operator.py (the reference gates every
operator on check_numeric_gradient + numpy forward parity,
python/mxnet/test_utils.py:801).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.ops import registry as reg
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_backend_consistency,
                                  check_numeric_gradient)

from op_cases import CASES, Case


def _flat_cases():
    out = []
    for name, cases in sorted(CASES.items()):
        for i, c in enumerate(cases):
            out.append(pytest.param(name, c, id=f"{name}-{i}"))
    return out


ALL_CASES = _flat_cases()


def _run(name, case):
    nds = tuple(nd.array(a) for a in case.inputs)
    out = nd.imperative_invoke(name, nds, dict(case.params))
    return out


def _first(out, idx=0):
    if isinstance(out, (tuple, list)):
        return out[idx]
    return out


@pytest.mark.parametrize("name,case", ALL_CASES)
def test_forward(name, case):
    opdef = reg.get_op(name)  # raises if the table lists an unknown op
    out = _first(_run(name, case), case.out_index)
    got = out.asnumpy()
    assert np.isfinite(got.astype(np.float64)).all() or \
        not np.issubdtype(got.dtype, np.floating) or "nan" in name.lower()
    if case.ref is not None:
        want = case.ref(*case.inputs, **case.params)
        assert_almost_equal(got, np.asarray(want), rtol=case.rtol,
                            atol=case.atol, names=(name, "numpy"))


def _gradable(name, case):
    if case.grad is False:
        return False
    opdef = reg.get_op(name)
    if not opdef.differentiable:
        return False
    return all(np.issubdtype(a.dtype, np.floating) for a in case.inputs) \
        and len(case.inputs) > 0


GRAD_CASES = [p for p in ALL_CASES if _gradable(*p.values)]


@pytest.mark.parametrize("name,case",
                         [pytest.param(*p.values, id=p.id)
                          for p in GRAD_CASES])
def test_gradient(name, case):
    if case.grad_only is None:
        check_numeric_gradient(name, list(case.inputs), dict(case.params),
                               rtol=case.grad_rtol, atol=case.grad_atol)
        return
    # differentiate only the data inputs; index-like inputs (lengths,
    # positions) are closed over, not perturbed
    fixed = {i: nd.array(a) for i, a in enumerate(case.inputs)
             if i not in case.grad_only}
    order = list(case.grad_only)

    def fn(*diff_nds):
        full = []
        it = iter(diff_nds)
        for i in range(len(case.inputs)):
            full.append(fixed[i] if i in fixed else next(it))
        return nd.imperative_invoke(name, tuple(full), dict(case.params))

    check_numeric_gradient(fn, [case.inputs[i] for i in order],
                           rtol=case.grad_rtol, atol=case.grad_atol)


DTYPE_CASES = [p for p in ALL_CASES if p.values[1].dtype_sweep]


@pytest.mark.parametrize("name,case",
                         [pytest.param(*p.values, id=p.id)
                          for p in DTYPE_CASES])
def test_dtype_sweep(name, case):
    """The op must run in half/bfloat16/double and agree with f32 at the
    appropriate precision (the reference's GPU-vs-CPU dtype matrix,
    test_utils.py:1224 check_consistency)."""
    import jax.numpy as jnp
    base = _first(_run(name, case), case.out_index).asnumpy()
    sweeps = [("float64", 1e-4, 1e-5), ("float16", 2e-2, 2e-2),
              ("bfloat16", 8e-2, 8e-2)]
    for dt, rtol, atol in sweeps:
        ins = tuple(a.astype(dt) if np.issubdtype(a.dtype, np.floating)
                    else a for a in case.inputs)
        nds = tuple(nd.array(a, dtype=a.dtype) for a in ins)
        out = _first(nd.imperative_invoke(name, nds, dict(case.params)),
                     case.out_index)
        got = np.asarray(out.asnumpy(), dtype=np.float64)
        assert_almost_equal(got, base.astype(np.float64), rtol=rtol,
                            atol=atol, names=(f"{name}[{dt}]", "f32"))


@pytest.mark.parametrize("name,case", ALL_CASES)
def test_mode_consistency(name, case):
    """The whole sweep re-run under a second execution mode — jit vs
    disable_jit (op-by-op lowering), plus cpu-vs-accelerator when the
    default backend is not cpu. The reference's 'GPU suite = CPU suite
    re-imported' trick (tests/python/gpu/test_operator_gpu.py)."""
    check_backend_consistency(name, list(case.inputs), dict(case.params),
                              grad=_gradable(name, case) and
                              case.grad_only is None)


EDGE_CASES = [p for p in ALL_CASES if p.values[1].edge]


@pytest.mark.parametrize("name,case",
                         [pytest.param(*p.values, id=p.id)
                          for p in EDGE_CASES])
def test_edge_shapes(name, case):
    """Size-0 and 1-element inputs must execute with numpy-consistent
    result shapes (the reference's zero-size/edge-shape sweeps).

    Shapes keep the case's rank so axis-valued params stay valid."""
    rank = max(a.ndim for a in case.inputs)
    shapes = [(0,) + (2,) * (rank - 1), (1,) * rank,
              (2,) * (rank - 1) + (0,)]
    for shape in shapes:
        ins = tuple(np.ones(shape, a.dtype) for a in case.inputs)
        nds = tuple(nd.array(a) for a in ins)
        out = _first(nd.imperative_invoke(name, nds, dict(case.params)),
                     case.out_index)
        got = out.asnumpy()
        if case.ref is not None:
            want = np.asarray(case.ref(*ins, **case.params))
            assert got.shape == want.shape, \
                f"{name}{shape}: {got.shape} != {want.shape}"
