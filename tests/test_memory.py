"""Device-memory observability plane (telemetry/memory.py).

The live-byte ledger must be EXACT on CPU for every tracked category —
that is the property that lets tier-1 enforce memory accounting on a
backend that reports no ``memory_stats`` at all — and the surfaces built
on it (per-step watermarks in FitResult, the chrome-trace memory counter
track, OOM forensics dumps, serving per-model bytes) must agree with it
byte-for-byte.
"""
import gc
import glob
import json
import os

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon, io as mxio, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.contrib import chaos
from mxnet_tpu.fit import FitLoop
from mxnet_tpu.io.staging import DeviceStagingIter
from mxnet_tpu.optimizer import grouped as grouped_mod
from mxnet_tpu.telemetry import dump_chrome_trace, validate_chrome_trace
from mxnet_tpu.telemetry import memory as mem

pytestmark = pytest.mark.memory

LED = mem.ledger()


def _flush():
    """Collect pending garbage BEFORE baselining, so an earlier test's
    dying net can't subtract its bytes between our snapshots."""
    gc.collect()
    return {c: LED.live_bytes(c) for c in mem.CATEGORIES}


def _param_bytes(params):
    return sum(p.data().size * p.data()._data.dtype.itemsize
               for p in params)


def _make_params(rs, n=4, dtype="float32", size=16):
    params = []
    for j in range(n):
        p = gluon.Parameter(f"memtest{j}", shape=(size, j + 2), dtype=dtype)
        p.initialize(mx.init.Constant(0.0))
        p.set_data(nd.array(rs.randn(size, j + 2).astype(np.float32)))
        params.append(p)
    return params


def _set_grads(params, rs, poison=False):
    for p in params:
        g = rs.randn(*p.shape).astype(np.float32)
        if poison:
            g.flat[0] = np.nan
        garr = nd.array(g)
        if str(p.data().dtype) != "float32":
            garr = garr.astype(p.data().dtype)
        p._grad._rebind(garr._data)
        p._fresh_grad = True


def _mlp(width=32, out=8, materialize=False):
    net = gluon.nn.HybridSequential()  # CachedOp needs a HybridBlock
    net.add(gluon.nn.Dense(width, activation="relu"),
            gluon.nn.Dense(out))
    net.initialize(mx.init.Xavier())
    if materialize:  # CachedOp needs shapes known up front
        net(nd.array(np.zeros((1, 16), np.float32)))
    return net


def _fit(steps=4, batch=8, staging=True, tracer=False, **fit_kw):
    rs = np.random.RandomState(0)
    net = _mlp()
    data = rs.randn(steps * batch, 16).astype(np.float32)
    label = rs.randint(0, 8, (steps * batch,)).astype(np.float32)
    it = mxio.NDArrayIter(data, label, batch_size=batch)
    if staging:
        it = DeviceStagingIter(it)
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.01})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    was_on = telemetry.tracer.enabled
    if tracer:
        telemetry.tracer.clear()
        telemetry.enable()
    try:
        result = FitLoop(net, trainer, loss_fn, it,
                         ckpt_dir=None).fit(epochs=1, **fit_kw)
    finally:
        if tracer and not was_on:
            telemetry.disable()
    return result, net


# ---------------------------------------------------------------------------
# Ledger exactness per category
# ---------------------------------------------------------------------------

def test_params_and_grads_exact_then_freed():
    base = _flush()
    net = _mlp()
    x = nd.array(np.random.RandomState(0).randn(4, 16).astype(np.float32))
    net(x)  # deferred shapes materialize
    params = list(net.collect_params().values())
    expect = _param_bytes(params)
    assert LED.live_bytes("params") - base["params"] == expect
    assert LED.live_bytes("grads") - base["grads"] == expect
    del net, params
    gc.collect()
    assert LED.live_bytes("params") == base["params"]
    assert LED.live_bytes("grads") == base["grads"]


def test_grad_req_null_frees_grad_bytes():
    base = _flush()
    p = gluon.Parameter("memnull", shape=(32, 4))
    p.initialize(mx.init.One())
    nbytes = 32 * 4 * 4
    assert LED.live_bytes("grads") - base["grads"] == nbytes
    p.grad_req = "null"
    assert LED.live_bytes("grads") == base["grads"]
    assert LED.live_bytes("params") - base["params"] == nbytes


def test_cast_retracks_bytes():
    base = _flush()
    p = gluon.Parameter("memcast", shape=(64, 4))
    p.initialize(mx.init.One())
    assert LED.live_bytes("params") - base["params"] == 64 * 4 * 4
    p.cast("float16")
    assert LED.live_bytes("params") - base["params"] == 64 * 4 * 2
    assert LED.live_bytes("grads") - base["grads"] == 64 * 4 * 2


def test_optimizer_state_exact_and_rollback_frees():
    base = _flush()
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=3)
    tr = gluon.Trainer(params, "adam", {"learning_rate": 0.01},
                       kvstore=None)
    # poisoned first step: the fused sentinel declines the update and
    # rollback must also release the state objects it just materialized
    _set_grads(params, rs, poison=True)
    flag = tr.update_with_sentinel(1)
    assert flag is not None and not bool(jax.device_get(flag))
    tr.rollback_step()
    gc.collect()
    assert LED.live_bytes("optimizer") == base["optimizer"], \
        "sentinel-skipped step leaked optimizer-state accounting"
    # clean step: adam m+v, both f32 like the weights -> exactly 2x
    _set_grads(params, rs)
    flag = tr.update_with_sentinel(1)
    assert bool(jax.device_get(flag))
    assert LED.live_bytes("optimizer") - base["optimizer"] == \
        2 * _param_bytes(params)
    assert LED.live_bytes("masters") == base["masters"]  # f32: no masters


def test_masters_split_out_for_multi_precision():
    base = _flush()
    rs = np.random.RandomState(1)
    params = _make_params(rs, n=3, dtype="bfloat16")
    tr = gluon.Trainer(params, "sgd",
                       {"learning_rate": 0.01, "momentum": 0.9,
                        "multi_precision": True}, kvstore=None)
    _set_grads(params, rs)
    tr.update(1)
    n_elems = sum(int(np.prod(p.shape)) for p in params)
    # f32 master copy per param; momentum rides the master dtype (f32)
    assert LED.live_bytes("masters") - base["masters"] == 4 * n_elems
    assert LED.live_bytes("optimizer") - base["optimizer"] == 4 * n_elems
    del tr, params
    gc.collect()
    assert LED.live_bytes("masters") == base["masters"]
    assert LED.live_bytes("optimizer") == base["optimizer"]


def test_masters_split_survives_kvstore_updater_path():
    """The optimizer pickle round-trip (kvstore.set_optimizer) drops
    param_dict, so the kvstore updater calls with param unresolvable —
    the masters split must come from the WEIGHT the updater holds."""
    import pickle
    base = _flush()
    from mxnet_tpu import optimizer as opt_mod
    opt = opt_mod.create("sgd", learning_rate=0.01, momentum=0.9,
                         multi_precision=True)
    opt = pickle.loads(pickle.dumps(opt))  # param_dict pickles away
    up = opt_mod.get_updater(opt)
    w = nd.array(np.ones((16, 4), np.float32)).astype("bfloat16")
    g = nd.array(np.ones((16, 4), np.float32)).astype("bfloat16")
    up(0, g, w)
    n = 16 * 4
    assert LED.live_bytes("masters") - base["masters"] == 4 * n, \
        "masters split lost on the kvstore-updater (param-less) path"
    assert LED.live_bytes("optimizer") - base["optimizer"] == 4 * n


def test_set_states_drops_stale_indices():
    """Checkpoint restore replaces the state dict wholesale; an index the
    restored dict lacks must not keep phantom optimizer bytes."""
    import pickle
    base = _flush()
    rs = np.random.RandomState(7)
    params = _make_params(rs, n=3)
    tr = gluon.Trainer(params, "adam", {"learning_rate": 0.01},
                       kvstore=None)
    _set_grads(params, rs)
    tr.step(1)
    up = tr._updaters[0]
    assert LED.live_bytes("optimizer") - base["optimizer"] == \
        2 * _param_bytes(params)
    partial = {i: s for i, s in up.states.items() if i != 2}
    up.set_states(pickle.dumps(partial))
    assert LED.live_bytes("optimizer") - base["optimizer"] == \
        2 * _param_bytes(params[:2]), \
        "stale index 2 kept phantom optimizer bytes after restore"


def test_grouped_donation_does_not_double_count():
    """Repeated fused (donated-buffer) steps must leave every category
    flat: donation rebinds outputs over the same logical params/states,
    so the ledger totals may not creep."""
    base = _flush()
    rs = np.random.RandomState(2)
    params = _make_params(rs, n=5)
    tr = gluon.Trainer(params, "adam", {"learning_rate": 0.01},
                       kvstore=None)
    _set_grads(params, rs)
    tr.step(1)
    after_one = {c: LED.live_bytes(c) for c in ("params", "grads",
                                                "optimizer", "masters")}
    for _ in range(4):
        _set_grads(params, rs)
        tr.step(1)
    for cat, val in after_one.items():
        assert LED.live_bytes(cat) == val, \
            f"{cat} grew across donated steps"
    assert after_one["optimizer"] - base["optimizer"] == \
        2 * _param_bytes(params)


def test_grad_bucket_bytes_tracked_and_stable():
    base = _flush()
    from mxnet_tpu import kvstore as kvs
    rs = np.random.RandomState(3)
    params = _make_params(rs, n=4)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.01},
                       kvstore=kvs.create("device"))
    for _ in range(3):
        _set_grads(params, rs)
        tr.step(1)
        gc.collect()
    # all 4 f32 grads fit one 25MB bucket -> ONE flat wire buffer stays
    # resident in the store; transients freed with each split
    flat_bytes = sum(int(np.prod(p.shape)) * 4 for p in params)
    assert LED.live_bytes("grad_buckets") - base["grad_buckets"] == \
        flat_bytes
    del tr
    gc.collect()
    assert LED.live_bytes("grad_buckets") == base["grad_buckets"]


def test_staging_bytes_rise_and_fall():
    base = _flush()
    rs = np.random.RandomState(4)
    data = rs.randn(6 * 4, 8).astype(np.float32)
    label = rs.randint(0, 2, (6 * 4,)).astype(np.float32)
    it = DeviceStagingIter(mxio.NDArrayIter(data, label, batch_size=4),
                           depth=2)
    batch_bytes = 4 * 8 * 4 + 4 * 4  # data + label per batch
    first = it.next()
    # depth=2: after serving one batch, 3 are staged ahead (depth+1)
    assert LED.live_bytes("staging") - base["staging"] == 3 * batch_bytes
    for _ in range(5):
        it.next()
    with pytest.raises(StopIteration):
        it.next()
    assert LED.live_bytes("staging") == base["staging"]
    # abandoned mid-epoch: reset + GC must not leak either
    it.reset()
    it.next()
    assert LED.live_bytes("staging") > base["staging"]
    del it, first
    gc.collect()
    assert LED.live_bytes("staging") == base["staging"]


def test_staging_finalizer_never_reenters_ledger_accessor(monkeypatch):
    """The abandoned-iterator finalizer must run against the MemoryLedger
    captured at construction, NOT re-resolve it through memory.ledger():
    that accessor runs first-use metrics installation under plain
    (non-reentrant) locks, and weakref.finalize can fire synchronously on
    a thread holding them — self-deadlock (graftcheck GC-L03, the PR 8
    ledger-bug class generalized). Simulated here by making the accessor
    explosive after construction: the finalizer must still free the
    staged bytes without ever calling it."""
    base = _flush()
    rs = np.random.RandomState(5)
    data = rs.randn(4 * 4, 8).astype(np.float32)
    label = rs.randint(0, 2, (4 * 4,)).astype(np.float32)
    it = DeviceStagingIter(mxio.NDArrayIter(data, label, batch_size=4),
                           depth=1)
    it.next()
    assert LED.live_bytes("staging") > base["staging"]

    def boom():
        raise AssertionError("finalizer re-entered memory.ledger()")

    monkeypatch.setattr(mem, "ledger", boom)
    del it
    gc.collect()
    assert LED.live_bytes("staging") == base["staging"]


# ---------------------------------------------------------------------------
# FitResult + trace counter track (the acceptance criterion)
# ---------------------------------------------------------------------------

def test_fit_memory_summary_matches_trace_counters(tmp_path):
    result, _net = _fit(steps=4, tracer=True)
    payload = dump_chrome_trace(str(tmp_path / "mem_trace.json"))
    validate_chrome_trace(payload)  # counter events are validator-clean
    peaks = [int(e["args"]["value"]) for e in payload["traceEvents"]
             if e.get("ph") == "C" and e["name"] == "device_memory_peak"]
    assert result.memory is not None
    per_step = result.memory["per_step"]
    assert len(per_step) == 4 and len(peaks) == 4
    assert peaks == [r["peak_bytes"] for r in per_step], \
        "trace memory track disagrees with FitResult memory summary"
    assert result.memory["peak_bytes"] == max(peaks)
    # the stacked track carries real categories with real bytes
    mem_events = [e for e in payload["traceEvents"]
                  if e.get("ph") == "C" and e["name"] == "device_memory"]
    assert mem_events
    cats = set().union(*(e["args"].keys() for e in mem_events))
    assert cats <= set(mem.CATEGORIES)
    assert {"params", "grads"} <= cats
    assert result.memory["by_category"]["params"] > 0
    # every per-step record carries the watermark pair
    for rec in per_step:
        assert rec["peak_bytes"] >= rec["live_bytes"] - max(
            rec["delta_bytes"], 0)
        assert "delta_bytes" in rec


def test_fit_memory_ledger_is_exact_on_cpu():
    result, net = _fit(steps=3, staging=False)
    params = list(net.collect_params().values())
    expect = _param_bytes(params)
    by_cat = result.memory["by_category"]
    assert by_cat["params"] >= expect
    # cross-check against the backend where it reports (CPU: it doesn't,
    # and reconcile must say so instead of inventing numbers)
    rec = mem.reconcile()
    assert rec["ledger_bytes"] == LED.live_bytes()
    if rec["backend_bytes_in_use"] is None:
        assert rec["consistent"] is None
    else:
        assert rec["consistent"]


# ---------------------------------------------------------------------------
# Forensics: chaos mem_pressure, budget watermark, OOM guard
# ---------------------------------------------------------------------------

def _dumps_in(d):
    return sorted(glob.glob(os.path.join(str(d), "mem_forensics_*.json")))


def test_mem_pressure_chaos_dump_parses(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_MEM_DUMP_DIR", str(tmp_path))
    plan = chaos.install("mem_pressure@2")
    try:
        _fit(steps=4)
    finally:
        chaos.uninstall()
    assert plan.injected["mem_pressure"] == 1
    dumps = _dumps_in(tmp_path)
    assert len(dumps) == 1, "mem_pressure@2 must fire exactly once"
    blob = json.load(open(dumps[0]))
    assert blob["reason"] == "chaos_mem_pressure"
    assert blob["step"] == 2
    assert blob["live_bytes"] > 0
    ranked = [c["category"] for c in blob["categories"]]
    assert "params" in ranked and "grads" in ranked
    shares = [c["bytes"] for c in blob["categories"]]
    assert shares == sorted(shares, reverse=True), "categories not ranked"
    owners = [b["owner"] for b in blob["top_buffers"]]
    assert any("dense" in o for o in owners), \
        f"top buffers must name their owners, got {owners[:5]}"


def test_mem_pressure_explicit_bytes_no_fire_when_under(tmp_path,
                                                        monkeypatch):
    monkeypatch.setenv("MXTPU_MEM_DUMP_DIR", str(tmp_path))
    plan = chaos.install(f"mem_pressure@1:{1 << 40}")  # 1 TiB: never over
    try:
        _fit(steps=3)
    finally:
        chaos.uninstall()
    assert plan.injected["mem_pressure"] == 1  # consumed...
    assert _dumps_in(tmp_path) == []           # ...but under budget


def test_budget_watermark_dumps_once(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_MEM_DUMP_DIR", str(tmp_path))
    monkeypatch.setenv("MXTPU_MEM_BUDGET", "1")
    _fit(steps=4)
    dumps = _dumps_in(tmp_path)
    assert len(dumps) == 1, \
        "budget breach must dump on the rising edge only, not per step"
    blob = json.load(open(dumps[0]))
    assert blob["reason"] == "budget_exceeded"
    assert blob["budget_bytes"] == 1


def test_oom_guard_dumps_and_reraises(tmp_path, monkeypatch):
    monkeypatch.setenv("MXTPU_MEM_DUMP_DIR", str(tmp_path))
    with pytest.raises(MXNetError, match="RESOURCE_EXHAUSTED"):
        with mem.oom_guard():
            raise MXNetError(
                "RESOURCE_EXHAUSTED: Out of memory allocating 2GB")
    dumps = _dumps_in(tmp_path)
    assert len(dumps) == 1
    blob = json.load(open(dumps[0]))
    assert blob["reason"] == "resource_exhausted"
    assert "RESOURCE_EXHAUSTED" in blob["error"]
    # a benign error must NOT dump
    with pytest.raises(ValueError):
        with mem.oom_guard():
            raise ValueError("nope")
    assert len(_dumps_in(tmp_path)) == 1


def test_mem_pressure_grammar_errors():
    with pytest.raises(MXNetError):
        chaos.ChaosPlan("mem_pressure")  # no target
    with pytest.raises(MXNetError):
        chaos.ChaosPlan("mem_pressure:0.5@3")  # no probability allowed
    with pytest.raises(MXNetError):
        chaos.ChaosPlan("mem_pressure@x:y")  # bad ints


# ---------------------------------------------------------------------------
# Static per-program attribution
# ---------------------------------------------------------------------------

def test_cached_op_memory_analysis():
    from mxnet_tpu.cached_op import CachedOp
    net = _mlp(materialize=True)
    op = CachedOp(net)
    x = nd.array(np.random.RandomState(0).randn(4, 16).astype(np.float32))
    op(x)
    report = op.memory_analysis()
    assert len(report) == 1
    stats = next(iter(report.values()))
    assert stats["argument_bytes"] > 0
    assert stats["output_bytes"] > 0
    assert stats["temp_bytes"] >= 0
    # cached: second call returns the recorded stats without re-lowering
    assert op.memory_analysis() == report
    # recorded into the shared program registry -> registry gauges
    from mxnet_tpu.telemetry import default_registry
    g = default_registry().get("mxtpu_program_argument_bytes")
    assert g is not None and g.value > 0


def test_grouped_program_memory():
    rs = np.random.RandomState(5)
    params = _make_params(rs, n=3)
    tr = gluon.Trainer(params, "adam", {"learning_rate": 0.01},
                       kvstore=None)
    _set_grads(params, rs)
    tr.step(1)
    report = grouped_mod.program_memory()
    assert report, "fused bucket programs must be attributable"
    for stats in report.values():
        assert stats["argument_bytes"] > 0
        assert stats["temp_bytes"] >= 0
    ranked = mem.program_report()
    assert any(r["kind"] == "optimizer" for r in ranked)


# ---------------------------------------------------------------------------
# Registry gauges + serving bytes
# ---------------------------------------------------------------------------

def test_device_gauges_fall_back_to_ledger_on_cpu():
    from mxnet_tpu.telemetry import default_registry
    _flush()
    p = gluon.Parameter("memgauge", shape=(128, 8))
    p.initialize(mx.init.One())
    reg = default_registry()
    assert reg.get("mxtpu_device_bytes_in_use").value > 0, \
        "gauge still reads 0 on CPU — ledger fallback not wired"
    assert reg.get("mxtpu_device_peak_bytes").value >= \
        reg.get("mxtpu_device_bytes_in_use").value
    assert reg.get("mxtpu_mem_params_bytes").value >= 128 * 8 * 4


def test_serving_cache_bytes_rise_and_fall():
    from mxnet_tpu.serving import ModelServer
    base = _flush()
    net = _mlp(width=16, out=4, materialize=True)
    server = ModelServer(net, bucket_shapes=[(16,)], max_batch_size=2,
                        workers=1)
    try:
        cache = server._active.cache
        cache.warmup([(16,)], [1])
        assert server.metrics.render_json()["model_bytes"] == 0  # unrecorded
        report = cache.program_memory()
        assert report
        bytes_now = cache.memory_bytes()
        assert bytes_now > 0
        assert LED.live_bytes("serving_cache") - base["serving_cache"] == \
            bytes_now
        blob = server.metrics.render_json()
        assert blob["model_bytes"] == bytes_now
        text = server.metrics.render_prometheus()
        assert f"mxtpu_serve_model_bytes {bytes_now}" in text
    finally:
        server.stop(drain=False)
    del server, cache, net
    gc.collect()
    assert LED.live_bytes("serving_cache") == base["serving_cache"], \
        "drained model's cache bytes must fall with the cache"


def test_storage_memory_summary_bridges_ledger_and_backend():
    from mxnet_tpu import storage
    s = storage.memory_summary()
    assert s["ledger"]["live_bytes"] == LED.live_bytes()
    assert "by_category" in s["ledger"]
    assert isinstance(s["backend"], dict)
    assert set(s["reconcile"]) >= {"ledger_bytes", "backend_bytes_in_use",
                                   "consistent"}


# ---------------------------------------------------------------------------
# Offline trace report renders the memory track
# ---------------------------------------------------------------------------

def test_trace_report_memory_columns(tmp_path):
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "trace_report", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "trace_report.py"))
    trace_report = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(trace_report)

    result, _net = _fit(steps=3, tracer=True)
    path = str(tmp_path / "live.json")
    dump_chrome_trace(path)
    rows = trace_report.step_table(trace_report.load_events(path))
    mem_rows = [r for r in rows if "mem_peak_bytes" in r]
    assert len(mem_rows) >= 3
    expected = {r["step"]: r["peak_bytes"]
                for r in result.memory["per_step"]}
    for i, r in enumerate(mem_rows):
        if r["step"] in (str(k) for k in expected):
            assert r["mem_peak_bytes"] == expected[int(r["step"])]
        assert "mem_live_bytes" in r
        if i > 0:  # the first sampled window has no offline baseline
            assert "mem_delta_bytes" in r
    # table mode shows the columns; --json round-trips
    lines = trace_report._fmt_table(rows, 8)
    assert any("mem_peak_MB" in line for line in lines)
    assert trace_report.main([path, "--json"]) == 0


def test_trace_report_peak_only_window_has_no_bogus_delta():
    """A step window holding only a peak event (ring-drop boundary) must
    report the peak alone — not live=0 with a huge negative delta."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "trace_report2", os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "tools", "trace_report.py"))
    tr = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(tr)
    events = [
        {"name": "step:0", "ph": "i", "cat": "step", "ts": 0.0,
         "pid": 0, "tid": 0},
        {"name": "compute", "ph": "X", "cat": "compute", "ts": 1.0,
         "dur": 5.0, "pid": 0, "tid": 0},
        {"name": "device_memory", "ph": "C", "ts": 6.0, "pid": 0,
         "tid": 0, "args": {"params": 1000.0}},
        {"name": "device_memory_peak", "ph": "C", "ts": 6.5, "pid": 0,
         "tid": 0, "args": {"value": 1200.0}},
        {"name": "step:1", "ph": "i", "cat": "step", "ts": 10.0,
         "pid": 0, "tid": 0},
        {"name": "compute", "ph": "X", "cat": "compute", "ts": 11.0,
         "dur": 5.0, "pid": 0, "tid": 0},
        # ring drop ate step 1's device_memory sample; only peak survives
        {"name": "device_memory_peak", "ph": "C", "ts": 16.0, "pid": 0,
         "tid": 0, "args": {"value": 1300.0}},
    ]
    rows = tr.step_table(events)
    assert rows[0]["mem_peak_bytes"] == 1200
    assert rows[0]["mem_live_bytes"] == 1000
    assert rows[1]["mem_peak_bytes"] == 1300
    assert "mem_live_bytes" not in rows[1]
    assert "mem_delta_bytes" not in rows[1]
    # the table renderer handles the partial row
    assert any("mem_peak_MB" in line for line in tr._fmt_table(rows, 8))


def test_aot_bundle_bytes_ledgered(tmp_path):
    try:
        from jax.experimental.serialize_executable import serialize  # noqa
    except ImportError:
        pytest.skip("serialize_executable unavailable")
    from mxnet_tpu.cached_op import CachedOp
    base = _flush()
    net = _mlp(width=8, out=4, materialize=True)
    op = CachedOp(net)
    x = nd.array(np.zeros((2, 16), np.float32))
    op(x)
    path = str(tmp_path / "bundle.aot")
    assert op.aot_export(path) == 1
    op2 = CachedOp(net)
    assert op2.aot_load(path) == 1
    assert LED.live_bytes("aot_bundles") - base["aot_bundles"] > 0
    # the loaded executable itself attributes (Compiled stage) or is
    # skipped cleanly — either way memory_analysis must not raise
    op2.memory_analysis()
    del op, op2, net
    gc.collect()
    assert LED.live_bytes("aot_bundles") == base["aot_bundles"]
