"""Aggregated (multi-tensor) optimizer step: parity with the per-param
path, dispatch-count regression, sparse bypass, bucketed allreduce
(ref: optimizer_op.cc multi_sgd_update + MXNET_OPTIMIZER_AGGREGATION_SIZE;
DDP-style gradient bucketing for the allreduce side)."""
import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.optimizer import grouped as grouped_mod


def _make_params(rs, n=6, dtype="float32", shapes=None):
    params = []
    for j in range(n):
        shape = shapes[j] if shapes else (3, j + 2)
        p = gluon.Parameter(f"p{j}", shape=shape, dtype=dtype)
        p.initialize(mx.init.Constant(0.0))
        p.set_data(nd.array(rs.randn(*shape).astype(np.float32)))
        params.append(p)
    return params


def _set_grads(params, rs, poison_at=None):
    for k, p in enumerate(params):
        g = rs.randn(*p.shape).astype(np.float32)
        if poison_at is not None and k == poison_at:
            g[0, 0] = np.nan
        garr = nd.array(g)
        if str(p.data().dtype) != "float32":
            garr = garr.astype(p.data().dtype)
        p._grad._rebind(garr._data)
        p._fresh_grad = True


OPTS = [
    ("sgd", {"learning_rate": 0.1, "wd": 0.01}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01, "wd": 0.001}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01, "centered": True}),
]


def _run_steps(opt, kw, agg, monkeypatch, steps=3, dtype="float32", n=6,
               seed=0):
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", str(agg))
    rs = np.random.RandomState(seed)
    params = _make_params(rs, n=n, dtype=dtype)
    tr = gluon.Trainer(params, opt, dict(kw), kvstore=None)
    for _ in range(steps):
        _set_grads(params, rs)
        tr.step(4)
    return params, tr


@pytest.mark.parametrize("opt,kw", OPTS,
                         ids=[f"{o}-{'-'.join(k)}" for o, k in
                              [(o, list(kw)) for o, kw in OPTS]])
def test_aggregated_matches_per_param(opt, kw, monkeypatch):
    """Tentpole acceptance: 3 aggregated steps == 3 per-param steps to
    fp32 tolerance, for every grouped optimizer."""
    ref, tr_ref = _run_steps(opt, kw, 0, monkeypatch)
    got, tr_got = _run_steps(opt, kw, 4, monkeypatch)
    assert tr_ref.last_update_dispatches == len(ref)
    assert tr_got.last_update_dispatches == 2  # ceil(6/4) buckets
    for pr, pg in zip(ref, got):
        np.testing.assert_allclose(pr.data().asnumpy(), pg.data().asnumpy(),
                                   rtol=1e-5, atol=1e-6)
    # optimizer state must agree too (momentum/mean/var trajectories)
    for i in tr_ref._updaters[0].states:
        sr, sg = tr_ref._updaters[0].states[i], tr_got._updaters[0].states[i]
        flat_r = grouped_mod._flatten_inner(sr)
        flat_g = grouped_mod._flatten_inner(sg)
        for a, b in zip(flat_r, flat_g):
            np.testing.assert_allclose(a.asnumpy(), b.asnumpy(),
                                       rtol=1e-5, atol=1e-6)


def test_aggregated_multi_precision_parity(monkeypatch):
    """bf16 weights + multi_precision: the fused path must route through
    the same f32 master-weight math as Optimizer.update_multi_precision —
    master copies match to fp32 tolerance, weights bitwise as bf16."""
    kw = {"learning_rate": 0.05, "momentum": 0.9, "multi_precision": True}
    ref, tr_ref = _run_steps("sgd", kw, 0, monkeypatch, dtype="bfloat16")
    got, tr_got = _run_steps("sgd", kw, 3, monkeypatch, dtype="bfloat16")
    for i in range(len(ref)):
        w32_ref = tr_ref._updaters[0].states[i][1].asnumpy()
        w32_got = tr_got._updaters[0].states[i][1].asnumpy()
        np.testing.assert_allclose(w32_ref, w32_got, rtol=1e-6)
        np.testing.assert_array_equal(
            ref[i].data().astype("float32").asnumpy(),
            got[i].data().astype("float32").asnumpy())


def test_loss_scale_skip_step_parity(monkeypatch):
    """A non-finite step must be a perfect no-op under BOTH flows: the
    per-param path (host check, update never called) and the fused path
    (where-guard + rollback). Trajectories including a poisoned middle
    step stay identical — Adam's bias-correction counter included."""
    kw = {"learning_rate": 0.01}

    def run(agg):
        monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", str(agg))
        rs = np.random.RandomState(3)
        params = _make_params(rs, n=5)
        tr = gluon.Trainer(params, "adam", dict(kw), kvstore=None)
        for step in range(3):
            _set_grads(params, rs, poison_at=2 if step == 1 else None)
            flag = tr.update_with_sentinel(4)
            if flag is not None:          # fused flow
                if not bool(jax.device_get(flag)):
                    tr.rollback_step()
                    for p in params:
                        p.zero_grad()
            else:                         # classic FitLoop flow
                finite = all(np.isfinite(p.grad().asnumpy()).all()
                             for p in params)
                if finite:
                    tr.update(4)
                else:
                    for p in params:
                        p.zero_grad()
        return params, tr

    ref, tr_ref = run(0)
    got, tr_got = run(4)
    assert tr_got._optimizer.num_update == tr_ref._optimizer.num_update == 2
    for pr, pg in zip(ref, got):
        np.testing.assert_allclose(pr.data().asnumpy(), pg.data().asnumpy(),
                                   rtol=1e-5, atol=1e-7)


def test_skipped_fused_step_creates_no_state(monkeypatch):
    """State creation is an observable side effect: when the FIRST step is
    skipped, rollback must also remove the freshly-created optimizer
    state, matching the per-param path where update never ran."""
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "4")
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=3)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1,
                                       "momentum": 0.9}, kvstore=None)
    _set_grads(params, rs, poison_at=0)
    flag = tr.update_with_sentinel(2)
    assert flag is not None and not bool(jax.device_get(flag))
    tr.rollback_step()
    assert not tr._updaters[0].states
    assert tr._optimizer.num_update == 0


def test_dispatch_count_regression(monkeypatch):
    """Acceptance: a 50-param model steps in O(buckets) compiled-call
    launches with aggregation on, O(params) with
    MXTPU_OPTIMIZER_AGGREGATION=0."""
    def one(agg):
        monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", str(agg))
        rs = np.random.RandomState(0)
        params = _make_params(rs, n=50, shapes=[(4, 4)] * 50)
        tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1,
                                           "momentum": 0.9}, kvstore=None)
        _set_grads(params, rs)
        tr.step(8)
        return tr.last_update_dispatches

    assert one(0) == 50                   # O(params)
    assert one(4) == 13                   # ceil(50/4) buckets
    assert one(64) == 1                   # one bucket covers everything
    assert one(1) == 50                   # degenerate cap still works


def test_signature_cache_no_per_step_recompile(monkeypatch):
    """Steady-state steps must HIT the signature cache (the CachedOp
    discipline): changing lr / rescale between steps may not mint new
    compiled programs."""
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "8")
    grouped_mod.clear_cache()
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=6)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1,
                                       "momentum": 0.9}, kvstore=None)
    _set_grads(params, rs)
    tr.step(4)
    misses0 = grouped_mod.cache_info().misses
    assert misses0 >= 1
    for step in range(4):
        tr.set_learning_rate(0.1 / (step + 2))  # scheduled-lr churn
        _set_grads(params, rs)
        tr.step(4 + step)                        # batch-size churn too
    info = grouped_mod.cache_info()
    assert info.misses == misses0, \
        "per-step lr/batch churn recompiled the bucket program"
    assert info.hits >= 4


def test_sparse_params_bypass_aggregation(monkeypatch):
    """Satellite: row_sparse-grad params must fall back to the per-param
    loop while dense neighbors still aggregate; _contains_sparse trainers
    work unchanged."""
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "4")
    rs = np.random.RandomState(0)
    dense = _make_params(rs, n=4)
    emb = gluon.Parameter("emb", shape=(10, 3), grad_stype="row_sparse")
    emb.initialize(mx.init.Constant(0.0))
    emb.set_data(nd.array(rs.randn(10, 3).astype(np.float32)))
    params = dense + [emb]
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1}, kvstore=None)
    _set_grads(dense, rs)
    from mxnet_tpu.ndarray import sparse as _sp
    rows = np.array([1, 4], dtype=np.int32)
    vals = rs.randn(2, 3).astype(np.float32)
    emb._grad._update(nd.array(vals)._data, nd.array(rows)._data)
    emb._fresh_grad = True
    w_emb = emb.data().asnumpy().copy()
    tr.step(2)
    # dense riders: 1 aggregated launch; sparse straggler: 1 per-param
    assert tr.last_update_dispatches == 2
    expect = w_emb.copy()
    expect[rows] -= 0.1 * (vals / 2.0)
    np.testing.assert_allclose(emb.data().asnumpy(), expect,
                               rtol=1e-5, atol=1e-6)


def test_grouped_update_asserts_dense_inputs(monkeypatch):
    """The grouped path refuses sparse inputs loudly instead of
    densifying them behind the caller's back."""
    rs = np.random.RandomState(0)
    emb = gluon.Parameter("emb", shape=(6, 2), grad_stype="row_sparse")
    emb.initialize(mx.init.Constant(0.0))
    tr = gluon.Trainer([emb], "sgd", {"learning_rate": 0.1}, kvstore=None)
    with pytest.raises(MXNetError, match="dense"):
        grouped_mod.grouped_update(tr._updaters[0], [(0, emb)], 4)
    # and the sentinel path reports ineligibility instead of raising
    assert not grouped_mod.eligible(tr._updaters[0], [(0, emb)])


def test_sentinel_unavailable_for_ungrouped_optimizer(monkeypatch):
    """update_with_sentinel returns None (caller falls back) for
    optimizers without a grouping rule — and applies nothing."""
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "4")
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=2)
    tr = gluon.Trainer(params, "ftrl", {"learning_rate": 0.1}, kvstore=None)
    _set_grads(params, rs)
    before = [p.data().asnumpy().copy() for p in params]
    assert tr.update_with_sentinel(2) is None
    for p, w in zip(params, before):
        np.testing.assert_array_equal(p.data().asnumpy(), w)
    assert all(p._fresh_grad for p in params), \
        "a declined sentinel call must leave the step fully pending"


def test_sentinel_declines_on_stale_without_raising(monkeypatch):
    """skip_nonfinite + a stale param + overflowing grads: the classic
    flow checks finiteness first and skips WITHOUT reaching the stale
    pre-scan, so the fused path must decline (None) rather than raise —
    the caller's fallback then reproduces the old ordering exactly."""
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "4")
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=3)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1}, kvstore=None)
    _set_grads(params, rs, poison_at=0)
    params[1]._fresh_grad = False  # unused-in-loss straggler
    before = [p.data().asnumpy().copy() for p in params]
    assert tr.update_with_sentinel(2) is None  # declined, nothing touched
    for p, w in zip(params, before):
        np.testing.assert_array_equal(p.data().asnumpy(), w)
    # the classic flow the caller falls back to: host check -> skip
    finite = all(np.isfinite(p.grad().asnumpy()).all() for p in params)
    assert not finite


def test_sentinel_covers_stale_grads(monkeypatch):
    """The fused flag must cover EVERY live grad — a stale NaN grad
    skipped under ignore_stale_grad still poisons the classic host check
    (FitLoop._grads_finite_flag iterates all non-null grads), so the
    fused path must skip the step identically."""
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "4")
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=3)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1}, kvstore=None)
    _set_grads(params, rs)
    # params[2] goes stale-with-NaN: fresh flag cleared, buffer poisoned
    bad = np.full(params[2].shape, np.nan, np.float32)
    params[2]._grad._rebind(nd.array(bad)._data)
    params[2]._fresh_grad = False
    before = [p.data().asnumpy().copy() for p in params[:2]]
    flag = tr.update_with_sentinel(2, ignore_stale_grad=True)
    assert flag is not None and not bool(jax.device_get(flag)), \
        "stale NaN grad must poison the fused flag like the host check"
    tr.rollback_step()
    for p, w in zip(params[:2], before):
        np.testing.assert_array_equal(p.data().asnumpy(), w)


def test_bucketed_allreduce_values_and_collective_count(monkeypatch):
    """Satellite: allreduce_grads issues one kvstore collective per
    bucket, values bit-preserved through flatten -> reduce -> split."""
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=7, shapes=[(8, j + 1) for j in range(7)])
    grads = [rs.randn(*p.shape).astype(np.float32) for p in params]

    def setg():
        for p, g in zip(params, grads):
            p._grad._rebind(nd.array(g)._data)
            p._fresh_grad = True

    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore="device")
    setg()
    tr.allreduce_grads()
    if tr._kvstore is None:
        pytest.skip("single-device backend: kvstore degraded to local")
    assert tr.last_allreduce_collectives == 1  # everything fits one bucket
    for p, g in zip(params, grads):
        np.testing.assert_allclose(p.grad().asnumpy(), g, rtol=1e-6)

    monkeypatch.setenv("MXTPU_GRAD_BUCKET_MB", "0")  # per-key fallback
    setg()
    tr.allreduce_grads()
    assert tr.last_allreduce_collectives == 7
    for p, g in zip(params, grads):
        np.testing.assert_allclose(p.grad().asnumpy(), g, rtol=1e-6)

    monkeypatch.setenv("MXTPU_GRAD_BUCKET_MB", "0.0001")  # ~100B buckets
    tr2 = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                        kvstore="device")
    setg()
    tr2.allreduce_grads()
    assert 1 < tr2.last_allreduce_collectives < 7
    for p, g in zip(params, grads):
        np.testing.assert_allclose(p.grad().asnumpy(), g, rtol=1e-6)


def test_bucketed_allreduce_mixed_dtype_and_sparse(monkeypatch):
    """dtype boundaries split buckets; row_sparse grads keep their
    per-key path alongside the bucketed dense ones."""
    rs = np.random.RandomState(1)
    p32 = _make_params(rs, n=2, shapes=[(4, 4), (4, 4)])
    p16 = []
    for j in range(2):
        p = gluon.Parameter(f"h{j}", shape=(4, 4), dtype="bfloat16")
        p.initialize(mx.init.Constant(0.0))
        p.set_data(nd.array(rs.randn(4, 4).astype(np.float32)))
        p16.append(p)
    params = [p32[0], p16[0], p32[1], p16[1]]  # interleave dtypes
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore="device")
    for p in params:
        g = nd.array(rs.randn(4, 4).astype(np.float32))
        if str(p.data().dtype) != "float32":
            g = g.astype(p.data().dtype)
        p._grad._rebind(g._data)
        p._fresh_grad = True
    tr.allreduce_grads()
    if tr._kvstore is None:
        pytest.skip("single-device backend: kvstore degraded to local")
    # interleaved dtypes force a bucket break at every boundary
    assert tr.last_allreduce_collectives == 4


@pytest.mark.parametrize("op,group,n_state", [
    ("multi_adam_update", 4, 2),
    ("multi_nag_mom_update", 3, 1),
    ("multi_rmsprop_update", 3, 1),
])
def test_multi_tensor_ops_match_singles(op, group, n_state):
    """The registered multi-tensor op surface (reference: the
    optimizer_op.cc multi_sgd family, extended beyond SGD) computes the
    same values as N single-tensor invocations."""
    single = {"multi_adam_update": "adam_update",
              "multi_nag_mom_update": "nag_mom_update",
              "multi_rmsprop_update": "rmsprop_update"}[op]
    rs = np.random.RandomState(0)
    n = 3
    packs = []
    for _ in range(n):
        w = nd.array(rs.randn(4).astype(np.float32))
        g = nd.array(rs.randn(4).astype(np.float32))
        states = [nd.zeros((4,)) for _ in range(n_state)]
        packs.append([w, g] + states)
    lrs = tuple(0.1 * (i + 1) for i in range(n))
    wds = tuple(0.01 * i for i in range(n))
    flat = [t.copy() for pack in packs for t in pack]
    outs = nd.imperative_invoke(op, tuple(flat),
                                {"lrs": lrs, "wds": wds, "num_weights": n})
    for i, pack in enumerate(packs):
        ref = nd.imperative_invoke(single, tuple(pack),
                                   {"lr": lrs[i], "wd": wds[i]})
        ref_w = ref[0] if isinstance(ref, (tuple, list)) else ref
        np.testing.assert_allclose(outs[i].asnumpy(), ref_w.asnumpy(),
                                   rtol=1e-6)


def test_fused_sentinel_through_fitloop(monkeypatch):
    """End to end: FitLoop rides the fused sentinel (one flag fetch, no
    per-grad host check) and still skips poisoned steps exactly."""
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "8")
    from mxnet_tpu import fit as fit_mod
    from mxnet_tpu.contrib import chaos
    from mxnet_tpu.io import NDArrayIter

    def build():
        mx.random.seed(0)
        net = gluon.nn.Dense(2, in_units=3)
        net.initialize(mx.init.Constant(0.5))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05}, kvstore=None)
        rs = np.random.RandomState(0)
        it = NDArrayIter(rs.rand(16, 3).astype(np.float32),
                         rs.rand(16, 2).astype(np.float32), batch_size=4)
        loss = lambda out, y: ((out - y) ** 2).mean()
        return net, fit_mod.FitLoop(net, tr, loss, it, ckpt_dir=None)

    chaos.install("nan_grad@1")
    net_a, loop_a = build()
    res = loop_a.fit(epochs=1)
    chaos.uninstall() if hasattr(chaos, "uninstall") else chaos.install("")
    assert res.skipped_steps == [1]
    assert np.isfinite(net_a.weight.data().asnumpy()).all()

    # the same run per-param must land on the identical trajectory
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "0")
    chaos.install("nan_grad@1")
    net_b, loop_b = build()
    res_b = loop_b.fit(epochs=1)
    chaos.install("")
    assert res_b.skipped_steps == [1]
    np.testing.assert_allclose(net_a.weight.data().asnumpy(),
                               net_b.weight.data().asnumpy(), rtol=1e-6)
    np.testing.assert_allclose(res.losses, res_b.losses, rtol=1e-6)


@pytest.mark.parametrize("agg", [4, 0])
def test_adam_resume_bitwise_matches_uninterrupted(monkeypatch, tmp_path,
                                                   agg):
    """Kill/resume parity for Adam (graftcheck-adjacent state audit, PR 9
    note): the bias-correction counter ``t`` rides the state pickle via
    Updater.COUNTS_KEY, so a restore continues the t sequence. Pre-fix,
    t restarted at 1 after load_states and the resumed trajectory
    diverged from the uninterrupted one on the very first step."""
    kw = {"learning_rate": 0.01, "wd": 0.001}
    steps_total, steps_before = 6, 3

    # uninterrupted reference: 6 steps, one trainer
    params_a, _ = _run_steps("adam", kw, agg, monkeypatch,
                             steps=steps_total, seed=7)

    # interrupted: 3 steps, save, then a FRESH trainer (fresh optimizer,
    # fresh updater — the process-restart stand-in) restores and resumes
    # on the same gradient stream
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", str(agg))
    rs = np.random.RandomState(7)
    params_b = _make_params(rs, n=6)
    tr = gluon.Trainer(params_b, "adam", dict(kw), kvstore=None)
    for _ in range(steps_before):
        _set_grads(params_b, rs)
        tr.step(4)
    fname = str(tmp_path / "trainer.states")
    tr.save_states(fname)
    saved_weights = [p.data().asnumpy().copy() for p in params_b]

    params_c = _make_params(np.random.RandomState(7), n=6)
    for p, w in zip(params_c, saved_weights):
        p.set_data(nd.array(w))
    tr2 = gluon.Trainer(params_c, "adam", dict(kw), kvstore=None)
    tr2.load_states(fname)
    # the counter must have resumed, not reset
    assert tr2._updaters[0].optimizer._index_update_count
    assert all(c == steps_before for c in
               tr2._updaters[0].optimizer._index_update_count.values())
    for _ in range(steps_total - steps_before):
        _set_grads(params_c, rs)
        tr2.step(4)

    for pa, pc in zip(params_a, params_c):
        assert np.array_equal(pa.data().asnumpy(), pc.data().asnumpy()), \
            f"{pa.name}: resumed Adam trajectory diverged (t not restored)"


def test_updater_states_roundtrip_accepts_legacy_pickle():
    """A pre-fix checkpoint (no reserved counter keys) must still load:
    counters then stay at their defaults exactly as before the fix."""
    import pickle
    from mxnet_tpu import optimizer as opt_mod
    up = opt_mod.get_updater(opt_mod.create("adam"))
    legacy = pickle.dumps({0: None, 1: None})
    up.set_states(legacy)
    assert set(up.states) == {0, 1}
    assert up.optimizer._index_update_count == {}
