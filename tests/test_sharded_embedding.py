"""Sharded embedding tables on the 8-device CPU mesh (VERDICT r1 item 4:
the reference's server-side row-sparse sharding,
kvstore_dist_server.h:331, redesigned as mesh-sharded jax Arrays).
"""
import numpy as np
import pytest

from mxnet_tpu.parallel.compat import HAVE_SHARD_MAP

if not HAVE_SHARD_MAP:  # pragma: no cover - depends on container jax
    pytest.skip("this jax build has neither jax.shard_map nor "
                "jax.experimental.shard_map (sharded tables need one)",
                allow_module_level=True)

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import parallel as par
from mxnet_tpu.parallel.sharded_embedding import (
    ShardedEmbedding, shard_table, sharded_lookup, sharded_scatter_add)

VOCAB, DIM = 64, 8


def _mesh():
    return par.make_mesh({"mp": 8})


def test_table_provably_sharded():
    mesh = _mesh()
    emb = ShardedEmbedding(VOCAB, DIM, mesh, axis="mp", seed=1)
    shards = emb.shards
    assert len(shards) == 8
    # each device holds a DISTINCT block of vocab/8 rows
    assert all(s.data.shape == (VOCAB // 8, DIM) for s in shards)
    datas = [np.asarray(s.data) for s in shards]
    full = np.asarray(emb.weight)
    for i, d in enumerate(datas):
        np.testing.assert_array_equal(d, full[i * 8:(i + 1) * 8])
    assert len({d.tobytes() for d in datas}) == 8, "shards are copies!"


def test_lookup_matches_replicated_take():
    import jax.numpy as jnp
    mesh = _mesh()
    rs = np.random.RandomState(0)
    table = jnp.asarray(rs.randn(VOCAB, DIM).astype(np.float32))
    sharded = shard_table(table, mesh, "mp")
    ids = jnp.asarray(rs.randint(0, VOCAB, (17,)).astype(np.int32))
    out = sharded_lookup(sharded, ids, mesh, "mp")
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(table)[np.asarray(ids)],
                               rtol=1e-6)


def test_lookup_gradient_is_row_sparse_scatter():
    import jax
    import jax.numpy as jnp
    mesh = _mesh()
    rs = np.random.RandomState(1)
    table = shard_table(
        jnp.asarray(rs.randn(VOCAB, DIM).astype(np.float32)), mesh, "mp")
    ids = jnp.asarray(np.array([3, 3, 60, 10], np.int32))
    cot = jnp.asarray(rs.randn(4, DIM).astype(np.float32))

    def f(t):
        return (sharded_lookup(t, ids, mesh, "mp") * cot).sum()

    g = jax.grad(f)(table)
    want = np.zeros((VOCAB, DIM), np.float32)
    for i, r in enumerate(np.asarray(ids)):
        want[r] += np.asarray(cot)[i]
    np.testing.assert_allclose(np.asarray(g), want, rtol=1e-5, atol=1e-6)


def test_scatter_add_updates_owned_rows_only():
    import jax.numpy as jnp
    mesh = _mesh()
    table = shard_table(jnp.zeros((VOCAB, DIM), jnp.float32), mesh, "mp")
    ids = jnp.asarray(np.array([0, 8, 63, 8], np.int32))
    rows = jnp.ones((4, DIM), jnp.float32)
    new = sharded_scatter_add(table, ids, rows, mesh, "mp")
    out = np.asarray(new)
    want = np.zeros((VOCAB, DIM), np.float32)
    want[0] += 1
    want[8] += 2  # duplicate id accumulates
    want[63] += 1
    np.testing.assert_array_equal(out, want)
    # still sharded after the update
    assert len(new.addressable_shards) == 8
    assert new.addressable_shards[0].data.shape == (VOCAB // 8, DIM)


def test_sharded_training_matches_replicated():
    """Convergence parity: an embedding classifier trained with the
    sharded table equals the same model trained with a replicated dense
    table (same data, same updates)."""
    import jax
    import jax.numpy as jnp
    mesh = _mesh()
    rs = np.random.RandomState(2)
    w0 = rs.randn(VOCAB, DIM).astype(np.float32) * 0.1
    proj = jnp.asarray(rs.randn(DIM, 1).astype(np.float32))
    emb = ShardedEmbedding(VOCAB, DIM, mesh, axis="mp")
    emb.weight = shard_table(jnp.asarray(w0), mesh, "mp")
    dense = jnp.asarray(w0)

    lr = 0.5
    losses_s, losses_d = [], []
    # fixed batch: the fit is learnable, so loss must drop
    ids = jnp.asarray(rs.randint(0, VOCAB, (16,)).astype(np.int32))
    y = jnp.asarray(rs.randn(16, 1).astype(np.float32))
    for step in range(10):

        def loss_sharded(t):
            out = sharded_lookup(t, ids, mesh, "mp") @ proj
            return ((out - y) ** 2).mean()

        def loss_dense(t):
            out = jnp.take(t, ids, axis=0) @ proj
            return ((out - y) ** 2).mean()

        ls, gs = jax.value_and_grad(loss_sharded)(emb.weight)
        ld, gd = jax.value_and_grad(loss_dense)(dense)
        # row-sparse apply on the sharded table; dense SGD on the other
        grad_rows = jnp.take(np.asarray(gd), ids, axis=0)  # rows of grad
        emb.weight = emb.weight - lr * gs
        dense = dense - lr * gd
        losses_s.append(float(ls))
        losses_d.append(float(ld))
    np.testing.assert_allclose(losses_s, losses_d, rtol=1e-4)
    np.testing.assert_allclose(np.asarray(emb.weight), np.asarray(dense),
                               rtol=1e-4, atol=1e-5)
    assert losses_s[-1] < losses_s[0]


def test_kvstore_shards_big_tables_and_row_sparse_pull(monkeypatch):
    """kv.init above MXNET_KVSTORE_BIGARRAY_BOUND stores the value SHARDED
    across local devices; row_sparse_pull gathers across shards; pushes
    through the updater keep the table sharded."""
    monkeypatch.setenv("MXNET_KVSTORE_BIGARRAY_BOUND", "256")
    from mxnet_tpu import kvstore as kv_mod
    from jax.sharding import NamedSharding
    kv = kv_mod.create("device")
    rs = np.random.RandomState(3)
    table = rs.randn(VOCAB, DIM).astype(np.float32)  # 512 elems >= bound
    kv.init("emb", nd.array(table))
    stored = kv._store["emb"]
    assert isinstance(stored._data.sharding, NamedSharding)
    assert len(stored._data.addressable_shards) == 8
    assert stored._data.addressable_shards[0].data.shape == (VOCAB // 8,
                                                             DIM)
    # row_sparse_pull returns exactly the requested rows
    rid = nd.array(np.array([1, 9, 33, 63]), dtype="int64")
    out = nd.zeros((4, DIM))
    kv.row_sparse_pull("emb", out=out, row_ids=rid)
    np.testing.assert_allclose(out.asnumpy(), table[[1, 9, 33, 63]],
                               rtol=1e-6)
    # additive push keeps the table sharded
    kv.set_updater(lambda k, delta, stored:
                   stored._rebind((stored + delta)._data))
    delta = np.zeros_like(table)
    delta[9] = 1.0
    kv.push("emb", nd.array(delta))
    stored = kv._store["emb"]
    assert isinstance(stored._data.sharding, NamedSharding), \
        "push dropped the sharding"
    out2 = nd.zeros((4, DIM))
    kv.row_sparse_pull("emb", out=out2, row_ids=rid)
    np.testing.assert_allclose(out2.asnumpy()[1], table[9] + 1.0,
                               rtol=1e-6)
    # small values stay unsharded
    kv.init("small", nd.zeros((4, 4)))
    assert not isinstance(kv._store["small"]._data.sharding,
                          NamedSharding) or \
        len(kv._store["small"]._data.sharding.mesh.devices.ravel()) == 1
