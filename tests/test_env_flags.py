"""Env-flag parity: every declared MXNET_* variable must have a real
consumer (VERDICT r1: 'a declared flag that is a no-op silently lies'),
and the newly wired flags must actually change behavior.
"""
import os
import subprocess

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu.base import env

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(ROOT, "mxnet_tpu")


def test_every_declared_env_var_has_a_consumer():
    undeclared = []
    for name, typ, value, doc in env.items():
        hits = subprocess.run(
            ["grep", "-rl", name, PKG, "--include=*.py"],
            capture_output=True, text=True).stdout.split()
        consumers = [h for h in hits if not h.endswith("base.py")]
        if not consumers:
            undeclared.append(name)
    assert not undeclared, \
        f"declared env vars with NO consumer (silent no-ops): {undeclared}"


def test_every_declared_env_var_is_documented():
    with open(os.path.join(ROOT, "docs", "env_vars.md")) as f:
        doc = f.read()
    missing = [name for name, *_ in env.items() if name not in doc]
    assert not missing, f"undocumented env vars: {missing}"


def test_safe_accumulation_is_in_jit_cache_key(monkeypatch):
    """MXNET_SAFE_ACCUMULATION is read at trace time, so it must be part
    of the op jit-cache key — otherwise toggling it after first compile
    would silently replay the old program. Verified structurally: the
    two modes occupy distinct cache entries and the safe program
    contains the f32 upcast."""
    from mxnet_tpu.ops import registry as reg
    opdef = reg.get_op("sum")
    opdef._jit_cache.clear()
    x = nd.array(np.full((64,), 0.5, np.float16), dtype="float16")
    monkeypatch.delenv("MXNET_SAFE_ACCUMULATION", raising=False)
    plain = nd.op.sum(x)
    keys_before = set(opdef._jit_cache)
    monkeypatch.setenv("MXNET_SAFE_ACCUMULATION", "1")
    safe = nd.op.sum(x)
    keys_after = set(opdef._jit_cache)
    assert len(keys_after) > len(keys_before), \
        "flag toggle did not create a new cache entry (stale program!)"
    assert safe.dtype == np.float16  # result dtype preserved
    assert float(safe.asnumpy()) == float(plain.asnumpy()) == 32.0
    # the flag must change the lowered program where it matters: jnp
    # reductions already accumulate f16 in f32 (jax's default upcast),
    # but norm/_square_sum square BEFORE reducing — the flag moves the
    # upcast ahead of the square (f16 squares overflow at |x| > 255).
    # This fails if norm ever stops threading _safe_acc.
    import jax
    import jax.numpy as jnp
    norm = reg.get_op("norm")
    xp = jnp.ones((8,), jnp.float16)
    on = str(jax.make_jaxpr(lambda a: norm.fn(a))(xp))
    assert on.index("convert_element_type") < on.index("square"), on
    monkeypatch.delenv("MXNET_SAFE_ACCUMULATION")
    off = str(jax.make_jaxpr(lambda a: norm.fn(a))(xp))
    assert off.index("square") < off.index("convert_element_type"), off
    # end-to-end: f16 squares of 300 overflow to inf without the flag
    big = np.full((16,), 300.0, np.float16)
    plain_n = float(nd.op.norm(nd.array(big, dtype="float16")).asnumpy())
    assert not np.isfinite(plain_n)
    monkeypatch.setenv("MXNET_SAFE_ACCUMULATION", "1")
    safe_n = float(nd.op.norm(nd.array(big, dtype="float16")).asnumpy())
    assert np.isfinite(safe_n) and abs(safe_n - 1200.0) < 2.0


def test_numpy_scalar_lr_stays_out_of_jit_cache_key():
    """An lr arriving as np.float32 (e.g. from a numpy-computing
    LRScheduler) must be treated as a weak dynamic scalar exactly like a
    python float — otherwise every step's lr bakes into the jit-cache
    key and recompiles (round-4 advisor finding: the isinstance check
    only accepted int/float)."""
    from mxnet_tpu.ops import registry as reg
    opdef = reg.get_op("sgd_update")
    opdef._jit_cache.clear()
    w, g = nd.ones((4,)), nd.ones((4,))
    nd.op.sgd_update(w, g, lr=0.1, wd=0.0)
    n1 = len(opdef._jit_cache)
    out2 = nd.op.sgd_update(w, g, lr=np.float32(0.2), wd=0.0)
    nd.op.sgd_update(w, g, lr=np.float64(0.3), wd=0.0)
    assert len(opdef._jit_cache) == n1, \
        "numpy-scalar lr created new jit-cache entries (recompile/step)"
    np.testing.assert_allclose(out2.asnumpy(), 1.0 - 0.2 * 1.0, rtol=1e-6)


def test_bulk_exec_flags_fall_back_to_imperative(monkeypatch):
    from mxnet_tpu import autograd, gluon
    net = gluon.nn.Dense(4)
    net.initialize()
    with autograd.pause():
        net(nd.ones((2, 3)))
    net.hybridize()
    out_bulk = net(nd.ones((2, 3))).asnumpy()
    monkeypatch.setenv("MXNET_EXEC_BULK_EXEC_INFERENCE", "0")
    net.hybridize()  # reset the cached op
    out_imp = net(nd.ones((2, 3))).asnumpy()
    np.testing.assert_allclose(out_bulk, out_imp, rtol=1e-6)
    # imperative path: no whole-graph cache entry was built
    assert net._cached_op is None or not net._cached_op._cache


def test_enforce_determinism_requires_seed(monkeypatch):
    import mxnet_tpu.random as mxrand
    from mxnet_tpu.base import MXNetError
    monkeypatch.setenv("MXNET_ENFORCE_DETERMINISM", "1")
    monkeypatch.setattr(mxrand, "_seed_value", None)
    with pytest.raises(MXNetError, match="DETERMINISM"):
        mxrand.np_rng()
    mx.random.seed(3)
    mxrand.np_rng()  # seeded: fine


def test_matmul_precision_flag_applies():
    # runs in a subprocess so the import-time hook sees the env
    code = ("import os; os.environ['MXNET_TPU_MATMUL_PRECISION']='highest';"
            "import mxnet_tpu, jax;"
            "assert jax.config.jax_default_matmul_precision == 'highest',"
            "jax.config.jax_default_matmul_precision;"
            "print('ok')")
    env2 = dict(os.environ)
    r = subprocess.run([os.sys.executable, "-c", code],
                       capture_output=True, text=True, env=env2, cwd=ROOT)
    assert "ok" in r.stdout, r.stdout + r.stderr


def test_update_on_kvstore_flag(monkeypatch):
    """MXNET_UPDATE_ON_KVSTORE=0 keeps the optimizer on the worker; the
    store only aggregates."""
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    out = mx.sym.FullyConnected(data, w, num_hidden=2, no_bias=True,
                                name="fc")
    monkeypatch.setenv("MXNET_UPDATE_ON_KVSTORE", "0")
    mod = mx.mod.Module(out, data_names=["data"], label_names=[])
    mod.bind(data_shapes=[("data", (4, 3))], label_shapes=None,
             for_training=True)
    mod.init_params(mx.init.Constant(0.5))
    mod.init_optimizer(kvstore="dist_tpu_sync",
                       optimizer_params={"learning_rate": 0.1,
                                         "rescale_grad": 1.0})
    if mod._kvstore is None:
        pytest.skip("dist kvstore unavailable")
    assert mod._update_on_kvstore is False
    from mxnet_tpu.io import DataBatch
    mod.forward(DataBatch([nd.ones((4, 3))], []))
    mod.backward(out_grads=nd.ones((4, 2)))
    before = mod._exec.arg_dict["w"].asnumpy().copy()
    mod.update()
    after = mod._exec.arg_dict["w"].asnumpy()
    assert not np.allclose(before, after), "local update must have run"
