"""Gluon tests (model: tests/python/unittest/test_gluon.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd, gluon
from mxnet_tpu.gluon import nn


def test_dense_forward():
    layer = nn.Dense(4, in_units=3)
    layer.initialize()
    x = nd.ones((2, 3))
    y = layer(x)
    assert y.shape == (2, 4)


def test_dense_deferred_init():
    layer = nn.Dense(4)
    layer.initialize()
    y = layer(nd.ones((2, 7)))
    assert y.shape == (2, 4)
    assert layer.weight.shape == (4, 7)


def test_sequential():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    y = net(nd.ones((4, 5)))
    assert y.shape == (4, 2)


def test_hybridize_matches_eager():
    mx.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net.initialize()
    x = nd.array(np.random.RandomState(0).randn(5, 10).astype(np.float32))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hybrid = net(x).asnumpy()
    assert np.allclose(y_eager, y_hybrid, atol=1e-5)
    # second call uses the cache
    y2 = net(x).asnumpy()
    assert np.allclose(y_eager, y2, atol=1e-5)


def test_hybrid_backward():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="tanh"), nn.Dense(1))
    net.initialize()
    x = nd.array(np.random.RandomState(1).randn(4, 6).astype(np.float32))

    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    eager_grads = {k: p.grad().asnumpy().copy()
                   for k, p in net.collect_params().items()}

    net.hybridize()
    with autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    for k, p in net.collect_params().items():
        assert np.allclose(eager_grads[k], p.grad().asnumpy(), atol=1e-4), k


def test_conv2d():
    layer = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3)
    layer.initialize()
    x = nd.ones((2, 3, 16, 16))
    y = layer(x)
    assert y.shape == (2, 8, 16, 16)


def test_conv2d_deferred():
    layer = nn.Conv2D(4, kernel_size=3)
    layer.initialize()
    y = layer(nd.ones((1, 5, 8, 8)))
    assert y.shape == (1, 4, 6, 6)
    assert layer.weight.shape == (4, 5, 3, 3)


def test_conv_vs_torch():
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 8, 8).astype(np.float32)
    w = rs.randn(6, 3, 3, 3).astype(np.float32)
    b = rs.randn(6).astype(np.float32)
    out = nd.op.Convolution(nd.array(x), nd.array(w), nd.array(b),
                            kernel=(3, 3), num_filter=6, stride=(2, 2),
                            pad=(1, 1)).asnumpy()
    tout = torch.nn.functional.conv2d(
        torch.tensor(x), torch.tensor(w), torch.tensor(b), stride=2,
        padding=1).numpy()
    assert np.allclose(out, tout, atol=1e-4)


def test_pooling_vs_torch():
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 9, 9).astype(np.float32)
    out = nd.op.Pooling(nd.array(x), kernel=(3, 3), pool_type="max",
                        stride=(2, 2)).asnumpy()
    tout = torch.nn.functional.max_pool2d(torch.tensor(x), 3, 2).numpy()
    assert np.allclose(out, tout, atol=1e-5)
    out = nd.op.Pooling(nd.array(x), kernel=(2, 2), pool_type="avg",
                        stride=(2, 2)).asnumpy()
    tout = torch.nn.functional.avg_pool2d(torch.tensor(x), 2, 2).numpy()
    assert np.allclose(out, tout, atol=1e-5)


def test_batchnorm_train_and_eval():
    layer = nn.BatchNorm(in_channels=4)
    layer.initialize()
    x = nd.array(np.random.RandomState(0).randn(8, 4, 5, 5).astype(np.float32) * 3 + 1)
    rm0 = layer.running_mean.data().asnumpy().copy()
    with autograd.record():
        y = layer(x)
    # batch-normalized output should be ~zero-mean/unit-var per channel
    yn = y.asnumpy()
    assert abs(yn.mean()) < 0.1
    assert abs(yn.std() - 1) < 0.1
    # moving stats moved toward batch stats
    rm1 = layer.running_mean.data().asnumpy()
    assert not np.allclose(rm0, rm1)
    # eval mode uses moving stats
    y_eval = layer(x)
    assert y_eval.shape == x.shape


def test_batchnorm_hybrid_state_update():
    layer = nn.BatchNorm(in_channels=3)
    layer.initialize()
    layer.hybridize()
    x = nd.array(np.random.RandomState(0).randn(4, 3, 2, 2).astype(np.float32) + 5)
    rm0 = layer.running_mean.data().asnumpy().copy()
    with autograd.record():
        layer(x)
    rm1 = layer.running_mean.data().asnumpy()
    assert not np.allclose(rm0, rm1), "hybridized BN must update moving stats"


def test_embedding():
    layer = nn.Embedding(10, 4)
    layer.initialize()
    y = layer(nd.array([1, 2, 3], dtype="int32"))
    assert y.shape == (3, 4)


def test_dropout_layer():
    layer = nn.Dropout(0.5)
    layer.initialize()
    x = nd.ones((100, 100))
    y = layer(x)
    assert (y.asnumpy() == 1).all()  # not training
    with autograd.record():
        y = layer(x)
    assert (y.asnumpy() == 0).any()


def test_trainer_sgd_step():
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(mx.init.Constant(1.0))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    x = nd.ones((4, 2))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(batch_size=4)
    # grad of sum(w.x) wrt w = sum over batch of x = [4,4]; /batch_size -> [1,1]
    w = net.weight.data().asnumpy()
    assert np.allclose(w, 1.0 - 0.1 * 1.0)


def test_mlp_regression_converges():
    rs = np.random.RandomState(0)
    X = rs.randn(128, 4).astype(np.float32)
    w_true = np.array([[1.0], [-2.0], [0.5], [3.0]], dtype=np.float32)
    Y = X @ w_true

    net = nn.Dense(1, in_units=4)
    net.initialize(mx.init.Normal(0.1))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1}, kvstore=None)
    l2 = gluon.loss.L2Loss()
    xs, ys = nd.array(X), nd.array(Y)
    for _ in range(100):
        with autograd.record():
            loss = l2(net(xs), ys)
        loss.backward()
        trainer.step(batch_size=128)
    final = float(loss.mean().asscalar())
    assert final < 1e-3, f"did not converge: {final}"
    assert np.allclose(net.weight.data().asnumpy().ravel(),
                       w_true.ravel(), atol=0.05)


def test_mlp_hybrid_adam_converges():
    rs = np.random.RandomState(1)
    X = rs.randn(256, 8).astype(np.float32)
    Y = (X[:, :1] > 0).astype(np.float32)

    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(1))
    net.initialize(mx.init.Xavier())
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01}, kvstore=None)
    lossfn = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    xs, ys = nd.array(X), nd.array(Y)
    first = None
    for i in range(60):
        with autograd.record():
            loss = lossfn(net(xs), ys)
        loss.backward()
        trainer.step(batch_size=256)
        if first is None:
            first = float(loss.mean().asscalar())
    last = float(loss.mean().asscalar())
    assert last < first * 0.5, f"{first} -> {last}"


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    f = str(tmp_path / "net.params")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net2.load_parameters(f)
    x = nd.ones((1, 3))
    assert np.allclose(net(x).asnumpy(), net2(x).asnumpy())


def test_collect_params_select():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    all_params = net.collect_params()
    weights = net.collect_params(".*weight")
    assert len(weights) == 2
    assert len(all_params) == 4


def test_losses_shapes():
    pred = nd.array(np.random.RandomState(0).randn(8, 5).astype(np.float32))
    label = nd.array([0.0, 1, 2, 3, 4, 0, 1, 2])
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label)
    assert l.shape == (8,)
    l1 = gluon.loss.L1Loss()(pred, pred * 0.5)
    assert l1.shape == (8,)
    h = gluon.loss.HuberLoss()(pred, pred * 0.9)
    assert h.shape == (8,)


def test_metric_accuracy():
    from mxnet_tpu import metric
    m = metric.Accuracy()
    pred = nd.array([[0.1, 0.9], [0.8, 0.2]])
    label = nd.array([1.0, 0.0])
    m.update([label], [pred])
    assert m.get()[1] == 1.0
    m2 = metric.create("acc")
    assert isinstance(m2, metric.Accuracy)
