"""Control-flow tests (model: tests/python/unittest/test_contrib_control_flow.py)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import nd, autograd
from mxnet_tpu.contrib import foreach, while_loop, cond


def test_foreach_cumsum():
    data = nd.array(np.arange(5).astype(np.float32))
    init = nd.zeros((1,))

    def body(item, state):
        new = state + item
        return new, new

    outs, final = foreach(body, data, init)
    assert outs.shape == (5, 1)
    assert outs.asnumpy().ravel().tolist() == [0, 1, 3, 6, 10]
    assert float(final.asscalar()) == 10


def test_foreach_rnn_like():
    T, N, H = 4, 2, 3
    x = nd.array(np.random.RandomState(0).randn(T, N, H).astype(np.float32))
    h0 = nd.zeros((N, H))
    w = nd.array(np.eye(H, dtype=np.float32) * 0.5)

    def body(xt, h):
        new_h = nd.op.tanh(xt + nd.op.dot(h, w))
        return new_h, new_h

    outs, hT = foreach(body, x, h0)
    assert outs.shape == (T, N, H)
    # manual replay
    h = np.zeros((N, H), np.float32)
    xs = x.asnumpy()
    for t in range(T):
        h = np.tanh(xs[t] + h @ (np.eye(H) * 0.5))
    assert np.allclose(hT.asnumpy(), h, atol=1e-5)


def test_foreach_backward():
    data = nd.array([1.0, 2.0, 3.0])
    data.attach_grad()

    def body(item, state):
        new = state + item * item
        return new, new

    with autograd.record():
        outs, final = foreach(body, data, nd.zeros((1,)))
        loss = final.sum()
    loss.backward()
    assert np.allclose(data.grad.asnumpy(), [2, 4, 6])


def test_while_loop():
    def cond_fn(i, s):
        return i < 5

    def func(i, s):
        return s + i, [i + 1, s + i]

    outs, (i, s) = while_loop(cond_fn, func,
                              [nd.array([0.0]), nd.array([0.0])],
                              max_iterations=10)
    assert float(i.asscalar()) == 5
    assert float(s.asscalar()) == 10  # 0+1+2+3+4
    assert outs.shape == (10, 1)


def test_while_loop_backward():
    x = nd.array([2.0])
    x.attach_grad()

    def cond_fn(i, s):
        return i < 3

    def func(i, s):
        return s, [i + 1, s * x]

    with autograd.record():
        _, (i, s) = while_loop(cond_fn, func,
                               [nd.array([0.0]), nd.array([1.0])],
                               max_iterations=5)
        loss = s.sum()  # s = x^3
    loss.backward()
    assert np.allclose(x.grad.asnumpy(), [12.0])  # 3x^2


def test_cond():
    a = nd.array([5.0])
    b = nd.array([3.0])
    out = cond(a > b, lambda: a * 2, lambda: b * 10)
    assert float(out.asscalar()) == 10.0
    out = cond(a < b, lambda: a * 2, lambda: b * 10)
    assert float(out.asscalar()) == 30.0
