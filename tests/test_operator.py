"""Broad operator correctness (model: tests/python/unittest/test_operator.py
— numpy cross-check + numeric gradient checking, SURVEY.md §4 strategy)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient, check_consistency)

RS = np.random.RandomState(7)


UNARY_CASES = [
    ("abs", np.abs, (-2, 2)), ("exp", np.exp, (-1, 1)),
    ("log", np.log, (0.1, 3)), ("sqrt", np.sqrt, (0.1, 4)),
    ("square", np.square, (-2, 2)), ("sin", np.sin, (-3, 3)),
    ("cos", np.cos, (-3, 3)), ("tanh", np.tanh, (-2, 2)),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), (-3, 3)),
    ("relu", lambda x: np.maximum(x, 0), (-2, 2)),
    ("floor", np.floor, (-3, 3)), ("ceil", np.ceil, (-3, 3)),
    ("log1p", np.log1p, (-0.5, 3)), ("expm1", np.expm1, (-1, 1)),
    ("rsqrt", lambda x: 1 / np.sqrt(x), (0.5, 4)),
    ("arctan", np.arctan, (-2, 2)), ("sign", np.sign, (-2, 2)),
    ("gammaln", None, (0.5, 4)), ("erf", None, (-2, 2)),
]


@pytest.mark.parametrize("name,npfn,rng", UNARY_CASES,
                         ids=[c[0] for c in UNARY_CASES])
def test_unary_vs_numpy(name, npfn, rng):
    x = RS.uniform(rng[0], rng[1], (3, 4)).astype(np.float32)
    out = getattr(nd.op, name)(nd.array(x)).asnumpy()
    if npfn is None:
        import scipy.special as sp
        npfn = {"gammaln": sp.gammaln, "erf": sp.erf}[name]
    assert_almost_equal(out, npfn(x).astype(np.float32), rtol=1e-4,
                        atol=1e-5)


BINARY_CASES = [
    ("broadcast_add", np.add), ("broadcast_sub", np.subtract),
    ("broadcast_mul", np.multiply), ("broadcast_div", np.divide),
    ("broadcast_maximum", np.maximum), ("broadcast_minimum", np.minimum),
    ("broadcast_power", np.power), ("broadcast_hypot", np.hypot),
]


@pytest.mark.parametrize("name,npfn", BINARY_CASES,
                         ids=[c[0] for c in BINARY_CASES])
def test_binary_vs_numpy(name, npfn):
    a = RS.uniform(0.5, 2, (2, 1, 4)).astype(np.float32)
    b = RS.uniform(0.5, 2, (1, 3, 4)).astype(np.float32)
    out = getattr(nd.op, name)(nd.array(a), nd.array(b)).asnumpy()
    assert_almost_equal(out, npfn(a, b).astype(np.float32), rtol=1e-4,
                        atol=1e-5)


GRAD_OPS = [
    ("sigmoid", {}), ("tanh", {}), ("exp", {}), ("square", {}),
    ("log_softmax", {"axis": -1}), ("softmax", {"axis": -1}),
    ("L2Normalization", {}), ("smooth_l1", {"scalar": 1.0}),
]


@pytest.mark.parametrize("name,params", GRAD_OPS,
                         ids=[c[0] for c in GRAD_OPS])
def test_numeric_gradient(name, params):
    x = RS.uniform(-1, 1, (3, 5)).astype(np.float32)
    check_numeric_gradient(name, [x], params, rtol=2e-2, atol=2e-3)


def test_fc_numeric_gradient():
    x = RS.randn(4, 6).astype(np.float32)
    w = RS.randn(3, 6).astype(np.float32)
    b = RS.randn(3).astype(np.float32)
    check_numeric_gradient(
        lambda x_, w_, b_: nd.op.FullyConnected(x_, w_, b_, num_hidden=3),
        [x, w, b], rtol=2e-2, atol=2e-3)


def test_conv_numeric_gradient():
    x = RS.randn(2, 2, 5, 5).astype(np.float32)
    w = RS.randn(3, 2, 3, 3).astype(np.float32)
    check_numeric_gradient(
        lambda x_, w_: nd.op.Convolution(x_, w_, kernel=(3, 3),
                                         num_filter=3, no_bias=True),
        [x, w], rtol=2e-2, atol=2e-3)


def test_batchnorm_numeric_gradient():
    x = RS.randn(4, 3, 2, 2).astype(np.float32)
    gamma = np.abs(RS.randn(3)).astype(np.float32) + 0.5
    beta = RS.randn(3).astype(np.float32)
    mm = np.zeros(3, np.float32)
    mv = np.ones(3, np.float32)

    def f(x_, g_, b_):
        out = nd.op.BatchNorm(x_, g_, b_, nd.array(mm), nd.array(mv),
                              fix_gamma=False, _training=True)
        return out[0]

    check_numeric_gradient(f, [x, gamma, beta], rtol=5e-2, atol=5e-3)


def test_consistency_across_dtypes():
    a = RS.randn(4, 4).astype(np.float32)
    check_consistency(lambda x: nd.op.softmax(x, axis=-1), [a])
    check_consistency(lambda x: nd.op.sum(x, axis=0), [a])


def test_deconvolution_vs_torch():
    torch = pytest.importorskip("torch")
    x = RS.randn(2, 3, 5, 5).astype(np.float32)
    w = RS.randn(3, 4, 3, 3).astype(np.float32)  # (in, out, kh, kw)
    out = nd.op.Deconvolution(nd.array(x), nd.array(w), kernel=(3, 3),
                              num_filter=4, stride=(2, 2),
                              pad=(1, 1), adj=(1, 1)).asnumpy()
    tout = torch.nn.functional.conv_transpose2d(
        torch.tensor(x), torch.tensor(w), stride=2, padding=1,
        output_padding=1).numpy()
    assert_almost_equal(out, tout, rtol=1e-3, atol=1e-4)


def test_embedding_grad_scatter():
    from mxnet_tpu import autograd
    w = nd.array(RS.randn(10, 4).astype(np.float32))
    w.attach_grad()
    idx = nd.array([1, 1, 3], dtype="int32")
    with autograd.record():
        out = nd.op.Embedding(idx, w, input_dim=10, output_dim=4)
        loss = out.sum()
    loss.backward()
    g = w.grad.asnumpy()
    assert np.allclose(g[1], 2.0)  # row 1 used twice
    assert np.allclose(g[3], 1.0)
    assert np.allclose(g[0], 0.0)


def test_layer_norm_matches_manual():
    x = RS.randn(4, 6).astype(np.float32)
    gamma = np.abs(RS.randn(6)).astype(np.float32)
    beta = RS.randn(6).astype(np.float32)
    out = nd.op.LayerNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                          axis=-1, eps=1e-5)[0].asnumpy()
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    ref = (x - mu) / np.sqrt(var + 1e-5) * gamma + beta
    assert_almost_equal(out, ref, rtol=1e-4, atol=1e-5)


def test_sequence_ops():
    x = nd.array(np.arange(24).reshape(4, 2, 3).astype(np.float32))
    slen = nd.array([2.0, 4.0])
    m = nd.op.SequenceMask(x, slen, use_sequence_length=True, value=-1)
    mn = m.asnumpy()
    assert (mn[2:, 0] == -1).all()
    assert (mn[:, 1] != -1).all()
    last = nd.op.SequenceLast(x, slen, use_sequence_length=True)
    assert np.allclose(last.asnumpy()[0], x.asnumpy()[1, 0])
    rev = nd.op.SequenceReverse(x, slen, use_sequence_length=True)
    assert np.allclose(rev.asnumpy()[0, 0], x.asnumpy()[1, 0])


def test_optimizer_ops_match_reference_math():
    w = np.array([1.0, 2.0], np.float32)
    g = np.array([0.1, -0.2], np.float32)
    out = nd.op.sgd_update(nd.array(w), nd.array(g), lr=0.1, wd=0.01)
    ref = w - 0.1 * (g + 0.01 * w)
    assert_almost_equal(out.asnumpy(), ref, rtol=1e-6, atol=1e-7)

    mom = np.array([0.5, 0.5], np.float32)
    new_w, new_m = nd.op.sgd_mom_update(nd.array(w), nd.array(g),
                                        nd.array(mom), lr=0.1, momentum=0.9)
    m_ref = 0.9 * mom - 0.1 * g
    assert_almost_equal(new_m.asnumpy(), m_ref, rtol=1e-6, atol=1e-7)
    assert_almost_equal(new_w.asnumpy(), w + m_ref, rtol=1e-6, atol=1e-7)


def test_topk_mask_and_ravel():
    a = nd.array([[1.0, 3.0, 2.0]])
    m = nd.op.topk(a, axis=1, k=2, ret_typ="mask")
    assert m.asnumpy().tolist() == [[0, 1, 1]]
    r = nd.op.ravel_multi_index(nd.array([[1.0], [2.0]]), shape=(3, 4))
    assert float(r.asnumpy()[0]) == 6
    u = nd.op.unravel_index(nd.array([6.0]), shape=(3, 4))
    assert u.asnumpy().ravel().tolist() == [1, 2]


def test_gradient_compression_roundtrip():
    from mxnet_tpu.gradient_compression import GradientCompression
    import jax.numpy as jnp
    g = jnp.asarray(RS.randn(1000).astype(np.float32))
    # threshold must bound |g| for error feedback to keep up (same
    # constraint as the reference's 2-bit scheme)
    thr = float(jnp.abs(g).max()) * 1.1
    gc = GradientCompression("2bit", threshold=thr)
    total = jnp.zeros_like(g)
    acc = jnp.zeros_like(g)
    # error feedback: accumulated compressed grads track accumulated truth
    for _ in range(200):
        out = gc.roundtrip("k", g)
        total = total + out
        acc = acc + g
    rel = float(jnp.abs(total - acc).mean() / jnp.abs(acc).mean())
    assert rel < 0.1, rel


def test_kvstore_compressed_push():
    from mxnet_tpu import kvstore as kv_mod
    kv = kv_mod.create("device")
    kv.init("w", nd.zeros((4,)))
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.push("w", nd.array([1.0, -1.0, 0.1, 0.0]))
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    assert out.asnumpy().tolist() == [0.5, -0.5, 0.0, 0.0]


def test_hard_sigmoid():
    x = np.array([[-5.0, 0.0, 1.0, 5.0]], np.float32)
    out = nd.hard_sigmoid(nd.array(x)).asnumpy()
    assert_almost_equal(out, np.clip(0.2 * x + 0.5, 0, 1))
    from mxnet_tpu.test_utils import check_numeric_gradient
    check_numeric_gradient("hard_sigmoid",
                           [np.array([[0.3, -0.8, 1.1]], np.float32)])


def test_batch_take():
    a = np.arange(12, dtype=np.float32).reshape(4, 3)
    idx = np.array([0, 2, 1, 0], np.float32)
    out = nd.batch_take(nd.array(a), nd.array(idx)).asnumpy()
    assert_almost_equal(out, a[np.arange(4), idx.astype(int)])


def test_svm_output_gradients():
    # ref: svm_output.cc L1_SVM/L2_SVM kernels
    d = np.array([[0.5, -0.2], [0.1, 0.8]], np.float32)
    l = np.array([0.0, 1.0], np.float32)

    def grads(use_linear):
        x = nd.array(d)
        x.attach_grad()
        with autograd.record():
            nd.SVMOutput(x, nd.array(l), use_linear=use_linear) \
                .sum().backward()
        return x.grad.asnumpy()

    l2 = grads(False)
    assert_almost_equal(l2, np.array([[-1.0, 1.6], [2.2, -0.4]],
                                     np.float32), rtol=1e-5)
    l1 = grads(True)
    assert_almost_equal(l1, np.array([[-1.0, 1.0], [1.0, -1.0]],
                                     np.float32), rtol=1e-5)
    # forward is identity
    assert_almost_equal(nd.SVMOutput(nd.array(d), nd.array(l)).asnumpy(), d)


def test_make_loss_gradient_normalization():
    d = np.array([[0.5, 0.0], [2.0, 0.1]], np.float32)

    def grad(norm, **kw):
        x = nd.array(d)
        x.attach_grad()
        with autograd.record():
            nd.MakeLoss(x, grad_scale=3.0, normalization=norm,
                        **kw).sum().backward()
        return x.grad.asnumpy()

    assert_almost_equal(grad("null"), np.full(d.shape, 3.0, np.float32))
    assert_almost_equal(grad("batch"), np.full(d.shape, 1.5, np.float32))
    # valid: 3 entries above 0.05 -> scale 3/3 = 1
    assert_almost_equal(grad("valid", valid_thresh=0.05),
                        np.full(d.shape, 1.0, np.float32))


def test_identity_attach_kl_sparse_reg():
    rs = np.random.RandomState(0)
    d = rs.uniform(0.2, 0.8, (6, 4)).astype(np.float32)
    x = nd.array(d)
    x.attach_grad()
    with autograd.record():
        out = nd.IdentityAttachKLSparseReg(x, sparseness_target=0.1,
                                           penalty=0.01)
        out.sum().backward()
    assert_almost_equal(out.asnumpy(), d)  # identity forward
    avg = d.mean(axis=0, keepdims=True)
    expected = 1.0 + 0.01 * (-(0.1 / avg) + 0.9 / (1 - avg))
    assert_almost_equal(x.grad.asnumpy(),
                        np.broadcast_to(expected, d.shape), rtol=1e-4)


def test_entropy_calibration_threshold_clips_outliers():
    from mxnet_tpu.contrib.quantization import (HistogramCollector,
                                                get_optimal_threshold)
    rs = np.random.RandomState(0)
    data = rs.randn(100000).astype(np.float32)
    data[:10] *= 100.0  # extreme outliers
    c = HistogramCollector()
    c.collect("x", data)
    hist, th = c.hists["x"]
    opt = get_optimal_threshold(hist, th)
    # KL threshold must clip far inside the outlier range but keep the
    # bulk of the gaussian
    assert 2.0 < opt < th * 0.5, (opt, th)


def test_quantize_model_entropy_mode():
    from mxnet_tpu import symbol as S
    from mxnet_tpu.symbol.symbol import create
    from mxnet_tpu.symbol.executor import eval_symbol
    from mxnet_tpu.contrib.quantization import quantize_model
    rs = np.random.RandomState(1)
    data = S.var("data")
    fc = create("FullyConnected", [data, S.var("w"), S.var("b")],
                {"num_hidden": 8}, name="fc1")
    out_sym = create("relu", [fc], {}, name="r")
    args = {"w": nd.array(rs.randn(8, 6).astype(np.float32) * 0.3),
            "b": nd.array(np.zeros(8, np.float32))}
    calib = [{"data": nd.array(rs.randn(16, 6).astype(np.float32))}
             for _ in range(3)]
    qsym, qargs, _ = quantize_model(out_sym, args, {},
                                    calib_mode="entropy",
                                    calib_data=calib)
    # calibrated ranges are baked into the quantize node
    qnodes = [n for n in qsym._topo()
              if n.op is not None and n.op.name == "_contrib_quantize_v2"]
    assert qnodes and "min_calib_range" in qnodes[0].attrs
    x = nd.array(rs.randn(4, 6).astype(np.float32))
    ref = eval_symbol(out_sym, ["data"], [x], args)
    got = eval_symbol(qsym, ["data"], [x], qargs)
    ref = (ref[0] if isinstance(ref, list) else ref).asnumpy()
    got = (got[0] if isinstance(got, list) else got).asnumpy()
    assert_almost_equal(got, ref, rtol=0.2, atol=0.1)


def test_entropy_calibration_small_tensor_still_clips():
    """Regression: bin floor below num_quantized_bins+2 emptied the KL
    candidate loop and entropy mode returned raw absmax."""
    from mxnet_tpu.contrib.quantization import (HistogramCollector,
                                                get_optimal_threshold)
    rs = np.random.RandomState(2)
    data = rs.randn(96).astype(np.float32)
    data[0] = 50.0  # extreme outlier
    c = HistogramCollector()
    c.collect("x", data)
    hist, th = c.hists["x"]
    opt = get_optimal_threshold(hist, th)
    assert opt < 25.0, opt  # must clip, not return absmax 50


def test_gradient_compression_wire_format():
    """pack/unpack roundtrip + the 16x wire-size claim: the payload that
    crosses the slow hop is 2-bit codes, 4 per byte
    (ref: gradient_compression.h:37-134 wire layout)."""
    from mxnet_tpu.gradient_compression import GradientCompression
    import jax.numpy as jnp
    gc = GradientCompression("2bit", threshold=0.5)
    g = jnp.asarray(RS.randn(103).astype(np.float32))  # non-multiple of 4
    packed, nelem = gc.compress_packed("k", g)
    assert nelem == 103
    assert packed.dtype == jnp.uint8
    assert packed.size == (103 + 3) // 4  # 16x smaller than f32 + padding
    dec = gc.decode_packed(np.asarray(packed), nelem, g.shape, g.dtype)
    # decoded == the dequantized values the roundtrip would produce
    gc2 = GradientCompression("2bit", threshold=0.5)
    want = gc2.roundtrip("k", g)
    np.testing.assert_array_equal(np.asarray(dec), np.asarray(want))
    # all three code values survive the wire
    q = gc.unpack(np.asarray(packed), nelem)
    assert set(np.unique(np.asarray(q))) <= {-1, 0, 1}
