"""Tier-1 static-analysis gate: graftcheck over mxnet_tpu/ + tools/ with
the checked-in baseline must report ZERO unsuppressed findings.

This is the mechanical replacement for the review passes PRs 5-9 burned
on the same bug families (RLock-under-GC-finalize, trace-impure code,
use-after-donate, silently-dead env typos, unledgered buffers): a PR that
reintroduces one fails here with the exact file:line and a fix hint.

To suppress a finding instead of fixing it, add its key to
``graftcheck_baseline.json`` WITH a written justification — unjustified
entries fail the baseline loader itself. See docs/static_analysis.md.
"""
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from tools.graftcheck import Baseline, SuiteConfig, run_suite  # noqa: E402
from tools.graftcheck.findings import RULES  # noqa: E402

BASELINE = os.path.join(ROOT, "graftcheck_baseline.json")


_MEMO = []


def _gate_result():
    if not _MEMO:  # one analysis run shared by the assertion tests
        baseline = Baseline.load(BASELINE)
        _MEMO.append((run_suite(
            SuiteConfig(root=ROOT, paths=["mxnet_tpu", "tools"],
                        baseline=baseline)), baseline))
    return _MEMO[0]


def test_gate_zero_unsuppressed_findings():
    result, _ = _gate_result()
    msg = "\n".join(f.render() for f in result.unsuppressed)
    assert not result.unsuppressed, (
        f"graftcheck found NEW unsuppressed findings:\n{msg}\n\n"
        "Fix them (preferred), or baseline with a written justification "
        "in graftcheck_baseline.json (docs/static_analysis.md).")


def test_gate_baseline_entries_all_fire_and_are_justified():
    """Every baseline entry must (a) carry a non-empty justification —
    enforced by the loader — and (b) still match a real finding: stale
    entries mean the hazard was fixed and the suppression must go."""
    result, baseline = _gate_result()
    assert all(j.strip() for j in baseline.entries.values())
    assert not result.stale_baseline, (
        f"stale baseline entries (fixed hazards — delete them): "
        f"{result.stale_baseline}")


def test_gate_known_rules_only():
    result, _ = _gate_result()
    for f in result.suppressed:
        assert f.rule in RULES


def test_cli_json_schema_and_exit_code_on_repo():
    """The CLI contract scripts outside pytest rely on: --json output is
    schema-stable and the exit code is 0 on a clean (baselined) tree."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.graftcheck", "--json",
         "mxnet_tpu", "tools"],
        capture_output=True, text=True, cwd=ROOT, timeout=600,
        env={**os.environ, "PYTHONPATH": ROOT})
    assert r.returncode == 0, r.stdout + r.stderr
    payload = json.loads(r.stdout)
    assert payload["version"] == 1
    assert payload["tool"] == "graftcheck"
    assert payload["findings"] == []
    assert isinstance(payload["counts"], dict)
    assert payload["suppressed"] >= 1          # the justified baseline
    assert payload["stale_baseline"] == []
