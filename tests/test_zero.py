"""ZeRO-1 sharded optimizer state (parallel/zero.py): trajectory parity
with the unsharded grouped path across optimizers/world sizes, global
sentinel + shard rollback, ~1/N ledger-enforced memory, topology-portable
gather-on-save checkpoints, chaos coverage of the sharded collectives,
and the multiprocess CPU-fallback protocol.

Marker ``zero`` (tier-1-safe: CPU, simulated worlds in-process; the one
real-group test is a 2-process subprocess on the coordination-service
fallback, same harness as test_dist_kvstore)."""
import os
import pickle
import subprocess
import sys

import numpy as np
import pytest

import jax

import mxnet_tpu as mx
from mxnet_tpu import nd, gluon
from mxnet_tpu import kvstore as kvs
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import zero as zero_mod

pytestmark = pytest.mark.zero

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _make_params(rs, n=6, dtype="float32", shapes=None, prefix="p"):
    params = []
    for j in range(n):
        shape = shapes[j] if shapes else (3, j + 2)
        p = gluon.Parameter(f"{prefix}{j}", shape=shape, dtype=dtype)
        p.initialize(mx.init.Constant(0.0))
        p.set_data(nd.array(rs.randn(*shape).astype(np.float32)))
        params.append(p)
    return params


def _set_grads(params, rs, poison_at=None):
    for k, p in enumerate(params):
        g = rs.randn(*p.shape).astype(np.float32)
        if poison_at is not None and k == poison_at:
            g[0, 0] = np.nan
        garr = nd.array(g)
        if str(p.data().dtype) != "float32":
            garr = garr.astype(p.data().dtype)
        p._grad._rebind(garr._data)
        p._fresh_grad = True


def _zero_env(monkeypatch, world):
    if world:
        monkeypatch.setenv("MXTPU_ZERO", "1")
        monkeypatch.setenv("MXTPU_ZERO_WORLD", str(world))
    else:
        monkeypatch.delenv("MXTPU_ZERO", raising=False)
        monkeypatch.delenv("MXTPU_ZERO_WORLD", raising=False)


OPTS = [
    ("sgd", {"learning_rate": 0.1, "wd": 0.01}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 0.01}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01, "wd": 0.001}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01, "centered": True}),
]


def _run_steps(opt, kw, world, monkeypatch, steps=3, dtype="float32", n=6,
               seed=0):
    _zero_env(monkeypatch, world)
    rs = np.random.RandomState(seed)
    params = _make_params(rs, n=n, dtype=dtype)
    tr = gluon.Trainer(params, opt, dict(kw), kvstore=kvs.create("local"))
    for _ in range(steps):
        _set_grads(params, rs)
        tr.step(4)
    return params, tr


@pytest.mark.parametrize("opt,kw", OPTS,
                         ids=[f"{o}-{'-'.join(k)}" for o, k in
                              [(o, list(kw)) for o, kw in OPTS]])
def test_zero_matches_unsharded(opt, kw, monkeypatch):
    """Tentpole acceptance: MXTPU_ZERO=1 reproduces the unsharded grouped
    trajectory BITWISE for every grouped optimizer, at world sizes 1, 2
    and 4 — the shard update is the same per-param kernel math, only the
    ownership (and therefore the comm pattern) changes."""
    ref, tr_ref = _run_steps(opt, kw, 0, monkeypatch)
    assert tr_ref._zero in (None, False)
    for world in (1, 2, 4):
        got, tr_got = _run_steps(opt, kw, world, monkeypatch)
        assert tr_got._zero.world == world
        assert tr_got.last_reduce_scatter_collectives >= 1
        assert tr_got.last_allgather_collectives >= 1
        assert tr_got.last_allreduce_collectives == 0
        for pr, pg in zip(ref, got):
            np.testing.assert_array_equal(pr.data().asnumpy(),
                                          pg.data().asnumpy())
        # state trajectories agree too, wherever the shard holds them
        su_ref, su_got = tr_ref._updaters[0], tr_got._updaters[0]
        assert set(su_got.states) == set(su_ref.states)
        from mxnet_tpu.optimizer import grouped as grouped_mod
        for i in su_ref.states:
            for a, b in zip(grouped_mod._flatten_inner(su_ref.states[i]),
                            grouped_mod._flatten_inner(su_got.states[i])):
                np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())


def test_zero_multi_precision_parity(monkeypatch):
    """bf16 + multi_precision under ZeRO: bitwise bf16 weights vs the
    unsharded grouped path, f32 masters materialized ONLY on the owning
    rank (shard-aware ledger owners prove the split)."""
    kw = {"learning_rate": 0.05, "momentum": 0.9, "multi_precision": True}
    ref, tr_ref = _run_steps("sgd", kw, 0, monkeypatch, dtype="bfloat16")
    got, tr_got = _run_steps("sgd", kw, 2, monkeypatch, dtype="bfloat16")
    for i in range(len(ref)):
        np.testing.assert_allclose(
            tr_ref._updaters[0].states[i][1].asnumpy(),
            tr_got._updaters[0].states[i][1].asnumpy(), rtol=1e-6)
        np.testing.assert_array_equal(
            ref[i].data().astype("float32").asnumpy(),
            got[i].data().astype("float32").asnumpy())
    from mxnet_tpu.telemetry import memory as mem
    led = mem.ledger()
    per_rank = [led.live_bytes("masters", owner_prefix=f"master:zr{r}/2:p")
                for r in range(2)]
    assert all(b > 0 for b in per_rank)


def test_zero_nan_skip_sentinel_parity(monkeypatch):
    """Global sentinel + shard rollback: a NaN-poisoned middle step is a
    perfect no-op under ZeRO exactly as under the unsharded fused path —
    Adam's bias-correction counter included."""
    def run(world):
        _zero_env(monkeypatch, world)
        rs = np.random.RandomState(3)
        params = _make_params(rs, n=5)
        tr = gluon.Trainer(params, "adam", {"learning_rate": 0.01},
                           kvstore=kvs.create("local"))
        for step in range(3):
            _set_grads(params, rs, poison_at=2 if step == 1 else None)
            tr.allreduce_grads()
            flag = tr.update_with_sentinel(4)
            assert flag is not None
            if not bool(jax.device_get(flag)):
                tr.rollback_step()
                for p in params:
                    p.zero_grad()
        return params, tr

    ref, tr_ref = run(0)
    got, tr_got = run(4)
    assert tr_got._optimizer.num_update == tr_ref._optimizer.num_update == 2
    for pr, pg in zip(ref, got):
        np.testing.assert_array_equal(pr.data().asnumpy(),
                                      pg.data().asnumpy())


def test_zero_skipped_first_step_creates_no_state(monkeypatch):
    """rollback_step must delete the shard-local states a skipped FIRST
    step materialized — and release their (shard-tagged) ledger bytes."""
    _zero_env(monkeypatch, 2)
    from mxnet_tpu.telemetry import memory as mem
    led = mem.ledger()
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=3, prefix="zskip")
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1,
                                       "momentum": 0.9},
                       kvstore=kvs.create("local"))
    _set_grads(params, rs, poison_at=0)
    tr.allreduce_grads()
    flag = tr.update_with_sentinel(2)
    assert flag is not None and not bool(jax.device_get(flag))
    tr.rollback_step()
    assert not tr._updaters[0].states
    assert tr._optimizer.num_update == 0
    for r in range(2):
        assert led.live_bytes("optimizer",
                              owner_prefix=f"state:zr{r}/2:zskip") == 0


def test_zero_fitloop_loss_scale_parity(monkeypatch):
    """End to end through FitLoop: ZeRO rides the fused sentinel, a
    chaos-poisoned step skips with loss-scale backoff, and the whole loss
    trajectory equals the unsharded run's."""
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "8")
    from mxnet_tpu import fit as fit_mod
    from mxnet_tpu.contrib import chaos
    from mxnet_tpu.io import NDArrayIter

    def build(world):
        _zero_env(monkeypatch, world)
        mx.random.seed(0)
        net = gluon.nn.Dense(2, in_units=3)
        net.initialize(mx.init.Constant(0.5))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05},
                           kvstore=kvs.create("local"))
        rs = np.random.RandomState(0)
        it = NDArrayIter(rs.rand(16, 3).astype(np.float32),
                         rs.rand(16, 2).astype(np.float32), batch_size=4)
        loss = lambda out, y: ((out - y) ** 2).mean()
        return net, fit_mod.FitLoop(net, tr, loss, it, ckpt_dir=None,
                                    loss_scale=128.0)

    chaos.install("nan_grad@1")
    net_a, loop_a = build(2)
    res_a = loop_a.fit(epochs=1)
    chaos.install("")
    assert res_a.skipped_steps == [1]
    assert res_a.loss_scale == 64.0
    assert res_a.zero and res_a.zero["world"] == 2

    chaos.install("nan_grad@1")
    net_b, loop_b = build(0)
    res_b = loop_b.fit(epochs=1)
    chaos.install("")
    assert res_b.skipped_steps == [1]
    assert res_b.zero is None
    np.testing.assert_allclose(res_a.losses, res_b.losses, rtol=1e-6)
    np.testing.assert_array_equal(net_a.weight.data().asnumpy(),
                                  net_b.weight.data().asnumpy())


def test_zero_ledger_one_over_n(monkeypatch):
    """Memory acceptance: per-rank optimizer+masters bytes == 1/N of the
    unsharded baseline for mp-Adam at N=4 (equal-sized params make the
    greedy partition exact; the ledger is exact by construction on CPU)."""
    from mxnet_tpu.telemetry import memory as mem
    led = mem.ledger()
    n, world = 8, 4

    def run(world_, prefix):
        _zero_env(monkeypatch, world_)
        rs = np.random.RandomState(0)
        params = _make_params(rs, n=n, dtype="bfloat16",
                              shapes=[(16, 16)] * n, prefix=prefix)
        tr = gluon.Trainer(params, "adam",
                           {"learning_rate": 1e-3,
                            "multi_precision": True},
                           kvstore=kvs.create("local"))
        _set_grads(params, rs)
        tr.step(4)
        return params, tr

    params_u, tr_u = run(0, "zubase")
    utok = tr_u._updaters[0]._mem_key
    unsharded = sum(
        led.live_bytes(c, owner_prefix=pref)
        for c, pref in (("optimizer", "state:zubase"),
                        ("masters", "master:zubase")))
    assert unsharded > 0
    params_z, tr_z = run(world, "zshard")
    for r in range(world):
        per_rank = (
            led.live_bytes("optimizer",
                           owner_prefix=f"state:zr{r}/{world}:zshard") +
            led.live_bytes("masters",
                           owner_prefix=f"master:zr{r}/{world}:zshard"))
        assert per_rank == unsharded // world, (r, per_rank, unsharded)
    # the bitwise trajectory is untouched by the sharding
    for pu, pz in zip(params_u, params_z):
        np.testing.assert_array_equal(
            pu.data().astype("float32").asnumpy(),
            pz.data().astype("float32").asnumpy())


def test_zero_checkpoint_topology_portable(monkeypatch, tmp_path):
    """A ZeRO-written trainer-state file restores into an unsharded run
    and vice versa (gather-on-save keeps one on-disk format), and the
    continued trajectories stay identical."""
    def build(world, seed=0):
        _zero_env(monkeypatch, world)
        rs = np.random.RandomState(seed)
        params = _make_params(rs, n=6)
        tr = gluon.Trainer(params, "adam", {"learning_rate": 0.01},
                           kvstore=kvs.create("local"))
        return params, tr, np.random.RandomState(seed + 1)

    # train 2 steps under ZeRO, save
    pz, tz, gz = build(2)
    for _ in range(2):
        _set_grads(pz, gz)
        tz.step(4)
    f_zero = str(tmp_path / "zero_states")
    tz.save_states(f_zero)
    # the on-disk format IS the ordinary unsharded dict (state slots for
    # every param, plus the reserved optimizer-counter keys every
    # checkpoint now carries so Adam's t survives kill/resume)
    from mxnet_tpu.optimizer.optimizer import Updater
    with open(f_zero, "rb") as f:
        blob = pickle.loads(f.read())
    counts = blob.pop(Updater.COUNTS_KEY)
    blob.pop(Updater.NUM_UPDATE_KEY)
    assert set(blob) == set(range(6))
    # gather merged every rank's counters, not just the last rank's
    assert set(counts) == set(range(6))
    assert all(c == 2 for c in counts.values())

    # same 2 steps unsharded, save
    pu, tu, gu = build(0)
    for _ in range(2):
        _set_grads(pu, gu)
        tu.step(4)
    f_plain = str(tmp_path / "plain_states")
    tu.save_states(f_plain)

    # cross-restore: zero-file -> unsharded trainer, plain-file -> zero
    pu2, tu2, gu2 = build(0)
    for p_src, p_dst in zip(pz, pu2):
        p_dst.set_data(p_src.data())
    tu2.load_states(f_zero)
    pz2, tz2, gz2 = build(2)
    for p_src, p_dst in zip(pu, pz2):
        p_dst.set_data(p_src.data())
    tz2.load_states(f_plain)
    # one more identical step each; both continuations must agree
    for params, tr, g in ((pu2, tu2, gu2), (pz2, tz2, gz2)):
        rs = np.random.RandomState(99)
        _set_grads(params, rs)
        tr.step(4)
    for a, b in zip(pu2, pz2):
        np.testing.assert_allclose(a.data().asnumpy(), b.data().asnumpy(),
                                   rtol=1e-6, atol=1e-7)


def test_zero_kill_resume_round_trip(monkeypatch, tmp_path):
    """Kill/resume parity at fixed N (chaos kill@3 + gather-on-save
    checkpoints): the resumed ZeRO run replays the fault-free trajectory."""
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "8")
    from mxnet_tpu import fit as fit_mod
    from mxnet_tpu.contrib import chaos
    from mxnet_tpu.io import NDArrayIter

    def build(world, ckpt_dir):
        _zero_env(monkeypatch, world)
        mx.random.seed(0)
        net = gluon.nn.Dense(2, in_units=3)
        net.initialize(mx.init.Constant(0.5))
        # momentum-SGD: stateful (the gathered shard state drives the
        # trajectory) AND exactly resumable — Adam's bias-correction
        # counter is not checkpointed, a pre-existing framework property
        # the unsharded kill/resume chaos tests share
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9},
                           kvstore=kvs.create("local"))
        rs = np.random.RandomState(0)
        it = NDArrayIter(rs.rand(24, 3).astype(np.float32),
                         rs.rand(24, 2).astype(np.float32), batch_size=4,
                         shuffle=True, seed=7)
        loss = lambda out, y: ((out - y) ** 2).mean()
        return net, fit_mod.FitLoop(net, tr, loss, it, ckpt_dir=ckpt_dir,
                                    ckpt_every=2, async_ckpt=False, seed=7)

    # uninterrupted reference (zero on)
    net_ref, loop_ref = build(2, str(tmp_path / "ref"))
    res_ref = loop_ref.fit(epochs=2)

    # killed at step 3, resumed from the gather-on-save checkpoint
    chaos.install("kill@3")
    net_a, loop_a = build(2, str(tmp_path / "killed"))
    with pytest.raises(chaos.ChaosKilled):
        loop_a.fit(epochs=2)
    chaos.install("")
    net_b, loop_b = build(2, str(tmp_path / "killed"))
    res_b = loop_b.fit(epochs=2)
    assert res_b.resumed_from == 2
    np.testing.assert_allclose(
        res_ref.losses[res_ref.step - len(res_b.losses):], res_b.losses,
        rtol=1e-6)
    np.testing.assert_allclose(net_ref.weight.data().asnumpy(),
                               net_b.weight.data().asnumpy(), rtol=1e-6)


def test_zero_flaky_reduce_scatter_retries_once_applied(monkeypatch):
    """Chaos regression: kv_flake makes reduce-scatter/allgather attempts
    raise TransientKVError; the retry loop must converge WITHOUT
    double-applying a shard update — trajectory identical to the clean
    run, faults actually injected."""
    monkeypatch.setenv("MXNET_KV_RETRY_MAX", "30")
    from mxnet_tpu.contrib import chaos

    def run(flake):
        _zero_env(monkeypatch, 2)
        if flake:
            chaos.install("kv_flake:0.4")
        rs = np.random.RandomState(0)
        params = _make_params(rs, n=5)
        tr = gluon.Trainer(params, "adam", {"learning_rate": 0.01},
                           kvstore=kvs.create("local"))
        for _ in range(3):
            _set_grads(params, rs)
            tr.step(4)
        plan = chaos.active()
        chaos.install("")
        return params, plan

    clean, _ = run(False)
    flaky, plan = run(True)
    assert plan.injected["kv_flake"] > 0, \
        "the plan never hit the sharded collectives"
    for a, b in zip(clean, flaky):
        np.testing.assert_array_equal(a.data().asnumpy(),
                                      b.data().asnumpy())


def test_zero_counters_and_metrics(monkeypatch):
    """Satellite: last_reduce_scatter/allgather counters and the
    mxtpu_zero_* registry metrics report the plane's activity."""
    from mxnet_tpu.telemetry import default_registry
    _zero_env(monkeypatch, 2)
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_MB", "0.0001")  # ~100B buckets
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=6, shapes=[(8, 4)] * 6)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore=kvs.create("local"))
    _set_grads(params, rs)
    tr.step(4)
    assert tr.last_reduce_scatter_collectives > 1  # tiny cap: >1 bucket
    assert tr.last_allgather_collectives == \
        tr.last_reduce_scatter_collectives
    assert tr.last_allreduce_collectives == 0
    text = default_registry().render_prometheus()
    assert "mxtpu_zero_reduce_scatter_collectives_total" in text
    assert "mxtpu_zero_allgather_collectives_total" in text
    assert "mxtpu_zero_world_size 2" in text


def test_zero_comm_spans_attributed(monkeypatch):
    """The sharded collectives emit kv_reduce_scatter/kv_allgather comm
    spans, so StepBreakdown/trace_report attribute the new wire time."""
    from mxnet_tpu.telemetry.tracer import tracer
    _zero_env(monkeypatch, 2)
    monkeypatch.setenv("MXTPU_PROFILE", "on")
    tracer.configure("on")
    try:
        rs = np.random.RandomState(0)
        params = _make_params(rs, n=4)
        tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                           kvstore=kvs.create("local"))
        _set_grads(params, rs)
        tr.step(4)
        names = [e.get("name", "") for e in tracer.events()]
    finally:
        tracer.configure("off")
    assert any(n.startswith("kv_reduce_scatter:_gbkt") for n in names)
    assert any(n.startswith("kv_allgather:_gbkt") for n in names)


def test_zero_strict_parse_and_guards(monkeypatch):
    """Typos and non-composable configs raise instead of silently
    training unsharded."""
    monkeypatch.setenv("MXTPU_ZERO", "bogus")
    with pytest.raises(MXNetError, match="MXTPU_ZERO"):
        zero_mod.zero_requested()
    monkeypatch.setenv("MXTPU_ZERO_WORLD", "-2")
    with pytest.raises(MXNetError, match="MXTPU_ZERO_WORLD"):
        zero_mod.simulated_world()
    monkeypatch.setenv("MXTPU_ZERO_WORLD", "four")
    with pytest.raises(MXNetError, match="integer"):
        zero_mod.simulated_world()
    _zero_env(monkeypatch, 2)
    rs = np.random.RandomState(0)
    # no store: the 'device' string degrades to no store on 1 device
    params = _make_params(rs, n=2)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1}, kvstore=None)
    _set_grads(params, rs)
    with pytest.raises(MXNetError, match="requires a kvstore"):
        tr.step(2)
    # non-grouped optimizer
    tr2 = gluon.Trainer(_make_params(rs, n=2), "ftrl",
                        {"learning_rate": 0.1}, kvstore=kvs.create("local"))
    with pytest.raises(MXNetError, match="grouped"):
        tr2._init_kvstore() or tr2._zero_plane()
    # aggregation off
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "0")
    tr3 = gluon.Trainer(_make_params(rs, n=2), "sgd",
                        {"learning_rate": 0.1}, kvstore=kvs.create("local"))
    with pytest.raises(MXNetError, match="AGGREGATION"):
        tr3._init_kvstore() or tr3._zero_plane()
    monkeypatch.delenv("MXTPU_OPTIMIZER_AGGREGATION", raising=False)
    # compression enabled AFTER the plane came up: the per-round check
    # refuses instead of silently skipping the compressor
    store = kvs.create("local")
    params4 = _make_params(rs, n=2, prefix="zc")
    tr4 = gluon.Trainer(params4, "sgd", {"learning_rate": 0.1},
                        kvstore=store)
    _set_grads(params4, rs)
    tr4.step(2)  # plane up, clean round
    store.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    _set_grads(params4, rs)
    with pytest.raises(MXNetError, match="compression"):
        tr4.step(2)


def test_zero_bare_update_refused(monkeypatch):
    """update() without a preceding reduce-scatter must raise under
    MXTPU_ZERO=1 — stepping every parameter would silently materialize
    full optimizer state (and, distributed, consume unreduced grads)."""
    _zero_env(monkeypatch, 2)
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=3)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore=kvs.create("local"))
    _set_grads(params, rs)
    with pytest.raises(MXNetError, match="reduce-scatter"):
        tr.update(4)
    assert not tr._updaters[0].states  # nothing materialized
    # the sanctioned sequence proceeds normally
    tr.allreduce_grads()
    tr.update(4)
    assert tr.last_reduce_scatter_collectives >= 1


def test_zero_composes_with_overlap(monkeypatch):
    """MXTPU_COMM_OVERLAP=on + MXTPU_ZERO=1: the overlap scope stays
    ACTIVE and drives the plane's reduce-scatter (grad-finality launch,
    rebinds at finalize) — and the step lands on the exact barrier-ZeRO
    trajectory. Raw grad injection never fires the autograd hook, so
    every bucket rides the finalize straggler path here; the real
    during-backward launches are covered by tests/test_zero_overlap.py."""
    _zero_env(monkeypatch, 2)

    def run(overlap):
        monkeypatch.setenv("MXTPU_COMM_OVERLAP", "on" if overlap else "off")
        rs = np.random.RandomState(0)
        params = _make_params(rs, n=4)
        tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                           kvstore=kvs.create("local"))
        for _ in range(2):
            with tr.overlap_scope() as scope:
                assert scope.active == overlap
            _set_grads(params, rs)
            tr.step(4)
            assert tr.last_reduce_scatter_collectives >= 1
            assert tr._zero_step is None  # consumed by the update
        return [p.data().asnumpy().copy() for p in params]

    barrier = run(False)
    overlapped = run(True)
    for a, b in zip(barrier, overlapped):
        np.testing.assert_array_equal(a, b)


def test_zero_stale_grad_declines_like_unsharded(monkeypatch):
    """Simulated worlds reproduce the fused path's decline-on-stale: the
    sentinel returns None, nothing is touched, and the caller's classic
    fallback flow (host check over locally-complete grads) is correct."""
    _zero_env(monkeypatch, 2)
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=3)
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.1},
                       kvstore=kvs.create("local"))
    _set_grads(params, rs)
    tr.allreduce_grads()
    params[1]._fresh_grad = False
    before = [p.data().asnumpy().copy() for p in params]
    assert tr.update_with_sentinel(2) is None
    for p, w in zip(params, before):
        np.testing.assert_array_equal(p.data().asnumpy(), w)


def test_zero_partition_deterministic_and_balanced():
    """The partition is a pure function of (order, shapes, world):
    byte-greedy, ties to the lowest rank, identical across calls."""
    rs = np.random.RandomState(0)
    params = _make_params(rs, n=8, shapes=[(16, 16)] * 8)
    a = zero_mod.partition(params, 4)
    b = zero_mod.partition(params, 4)
    assert a == b
    assert [a.count(r) for r in range(4)] == [2, 2, 2, 2]
    # bigger params spread first-fit: every rank gets load
    mixed = _make_params(rs, n=5, shapes=[(64, 64), (2, 2), (2, 2),
                                          (2, 2), (2, 2)], prefix="q")
    owners = zero_mod.partition(mixed, 2)
    assert owners[0] == 0 and set(owners[1:]) == {1}


def test_zero_multiprocess_cpu_fallback():
    """Acceptance: the REAL 2-process protocol over the jax.distributed
    coordination-service fallback — reduce-scatter of rank-distinct
    grads, 1/N state residency, gather-on-save format, shard re-derive
    on restore (tests/dist/zero_worker.py)."""
    n = 2
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)  # one cpu device per process
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "launch.py"),
         "-n", str(n), "--launcher", "local",
         "--coordinator", "127.0.0.1:12447",
         sys.executable,
         os.path.join(ROOT, "tests", "dist", "zero_worker.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=ROOT)
    out = proc.stdout + proc.stderr
    assert proc.returncode == 0, out[-3000:]
    for r in range(n):
        assert f"worker {r}/{n}: zero checks passed" in out, out[-3000:]
