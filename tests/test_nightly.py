"""Nightly-class tests (VERDICT r1 missing item 5):

- large-array indexing (ref: tests/nightly/test_large_array.py — >2^32
  element addressing): int64-offset correctness at a CI-friendly scale by
  default, the full >2^31-element case behind MXNET_TEST_LARGE_ARRAY=1
- model backward compatibility (ref: model_backwards_compatibility_check/)
  — golden artifacts in tests/data/ written by an earlier build MUST keep
  loading bit-exactly
- threaded-frontend stress (ref: test_tlocal_racecondition.py) — many
  python threads driving eager ops + autograd concurrently
"""
import os
import threading

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd

DATA = os.path.join(os.path.dirname(os.path.abspath(__file__)), "data")


# ---------------------------------------------------------------------------
# large arrays
# ---------------------------------------------------------------------------

def test_int64_index_arithmetic_moderate():
    """Indexing math must not truncate to int32 at any layer: gather at
    offsets beyond 2^24 (where f32 index math would lose precision) and
    near the int32 boundary of the flattened index space."""
    rows = 1 << 21  # 2M rows x 4 -> flat index space of 8M elements
    x = nd.array(np.broadcast_to(
        np.arange(rows, dtype=np.float32)[:, None], (rows, 4)).copy())
    idx = np.array([0, (1 << 19) - 1, (1 << 20) + 7, rows - 1], np.int64)
    got = nd.op.take(x, nd.array(idx, dtype="int64")).asnumpy()
    np.testing.assert_array_equal(got[:, 0], idx.astype(np.float32))


def test_large_flat_reduction_exact():
    """Summing 2^24 ones must be exactly 2^24 (f32 holds integers to
    2^24; accumulation-order bugs show up as off-by-thousands)."""
    n = 1 << 24
    total = float(nd.op.sum(nd.ones((n,), dtype="float32")).asnumpy())
    assert total == float(n), total


@pytest.mark.skipif(os.environ.get("MXNET_TEST_LARGE_ARRAY", "0") == "0",
                    reason="set MXNET_TEST_LARGE_ARRAY=1 (needs ~10GB)")
def test_beyond_int32_elements():
    """>2^31 elements end to end (the real nightly case)."""
    n = (1 << 31) + 8
    x = nd.ones((n,), dtype="int8")
    assert x.size == n
    s = int(nd.op.sum(x.astype("float32")).asnumpy())
    assert s == n
    # index past the int32 boundary
    val = x[n - 1].asnumpy()
    assert int(val) == 1


# ---------------------------------------------------------------------------
# backward compatibility (golden files from an earlier build)
# ---------------------------------------------------------------------------

def test_golden_nd_params_load():
    loaded = nd.load(os.path.join(DATA, "golden_params_v1.nd"))
    assert set(loaded) == {"w", "b"}
    assert loaded["w"].shape == (3, 4) and loaded["b"].shape == (4,)
    rs = np.random.RandomState(42)
    np.testing.assert_allclose(loaded["w"].asnumpy(),
                               rs.randn(3, 4).astype(np.float32),
                               rtol=1e-6)


def test_golden_sparse_load():
    from mxnet_tpu.ndarray import sparse
    loaded = nd.load(os.path.join(DATA, "golden_sparse_v1.nd"))
    arr = loaded[0]
    assert isinstance(arr, sparse.RowSparseNDArray)
    assert arr.shape == (6, 3)
    dense = arr.todense().asnumpy()
    assert (dense[[0, 2, 3, 5]] == 0).all()
    assert not (dense[[1, 4]] == 0).all()


def test_golden_symbol_and_module_checkpoint():
    sym = mx.sym.load(os.path.join(DATA, "golden_mlp_v1-symbol.json"))
    args = sym.list_arguments()
    assert "fc1_weight" in args and "softmax_label" in args
    params = nd.load(os.path.join(DATA, "golden_mlp_v1-0001.params"))
    arg_params = {k[4:]: v for k, v in params.items()
                  if k.startswith("arg:")}
    # bind and run the checkpointed net
    rs = np.random.RandomState(0)
    arg_params["data"] = nd.array(rs.randn(2, 5).astype(np.float32))
    arg_params["softmax_label"] = nd.zeros((2,))
    ex = sym.bind(mx.cpu(), args=arg_params)
    out = ex.forward(is_train=False)[0].asnumpy()
    assert out.shape == (2, 2)
    np.testing.assert_allclose(out.sum(1), 1.0, rtol=1e-5)  # softmax


def test_golden_gluon_parameters_load_bit_exact():
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(8, activation="relu"), gluon.nn.Dense(2))
    net.load_parameters(os.path.join(DATA, "golden_gluon_v1.params"))
    x = nd.array(np.linspace(-1, 1, 5, dtype=np.float32).reshape(1, 5))
    want = np.load(os.path.join(DATA, "golden_gluon_v1_out.npy"))
    np.testing.assert_allclose(net(x).asnumpy(), want, rtol=1e-6)


# ---------------------------------------------------------------------------
# threaded frontend stress
# ---------------------------------------------------------------------------

def test_threaded_eager_ops_stress():
    """N threads hammer the shared op registry/jit cache with eager ops;
    every thread must see its own correct results (the thread-local
    engine-state race test analog)."""
    errors = []

    def worker(seed):
        try:
            rs = np.random.RandomState(seed)
            for _ in range(30):
                a = rs.randn(16, 16).astype(np.float32)
                b = rs.randn(16, 16).astype(np.float32)
                got = nd.op.dot(nd.array(a), nd.array(b)).asnumpy()
                np.testing.assert_allclose(got, a @ b, rtol=1e-4,
                                           atol=1e-4)
        except Exception as e:  # pragma: no cover
            errors.append((seed, e))

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_threaded_autograd_training_stress():
    """Concurrent autograd tapes: recording state is thread-local, so
    parallel training loops must not corrupt each other's gradients."""
    errors = []

    def worker(seed):
        try:
            rs = np.random.RandomState(seed)
            w = nd.array(rs.randn(4, 4).astype(np.float32))
            w.attach_grad()
            for _ in range(10):
                x = nd.array(rs.randn(8, 4).astype(np.float32))
                with autograd.record():
                    loss = (nd.op.dot(x, w) ** 2).sum()
                loss.backward()
                want = 2 * x.asnumpy().T @ (x.asnumpy() @ w.asnumpy())
                np.testing.assert_allclose(w.grad.asnumpy(), want,
                                           rtol=1e-3, atol=1e-3)
        except Exception as e:  # pragma: no cover
            errors.append((seed, e))

    threads = [threading.Thread(target=worker, args=(s,))
               for s in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_threaded_hybridized_inference_stress():
    """One shared hybridized net served from many threads (the
    threaded-inference C API scenario): results must match the
    single-thread reference."""
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(32, activation="relu"), gluon.nn.Dense(4))
    net.initialize(mx.init.Xavier())
    with autograd.pause():
        net(nd.ones((1, 8)))
    net.hybridize()
    rs = np.random.RandomState(0)
    xs = [rs.randn(4, 8).astype(np.float32) for _ in range(12)]
    want = [net(nd.array(x)).asnumpy() for x in xs]
    errors = []

    def worker(i):
        try:
            for j in range(i, len(xs), 4):
                got = net(nd.array(xs[j])).asnumpy()
                np.testing.assert_allclose(got, want[j], rtol=1e-5,
                                           atol=1e-6)
        except Exception as e:  # pragma: no cover
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors
