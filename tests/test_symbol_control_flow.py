"""Symbol-level control flow (ref: src/operator/control_flow.cc —
_foreach:1255, _while_loop:1316, _cond) + graph-level sparse ops
(cast_storage/sparse_retain/_square_sum in sym.* graphs).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


def _run(out_sym, args, grads=None, out_grads=None, is_train=False):
    arg_nds = {k: nd.array(v) for k, v in args.items()}
    grad_nds = {k: nd.zeros(v.shape) for k, v in args.items()} \
        if grads else None
    ex = out_sym.bind(mx.cpu(), args=arg_nds, args_grad=grad_nds)
    outs = ex.forward(is_train=is_train or bool(grads))
    if grads:
        ex.backward(out_grads=out_grads)
        return [o.asnumpy() for o in outs], \
            {k: g.asnumpy() for k, g in ex.grad_dict.items()}
    return [o.asnumpy() for o in outs]


def test_sym_foreach_cumsum():
    data = sym.Variable("data")
    init = sym.Variable("init")

    def body(x, s):
        new_s = sym.elemwise_add(x, s)
        return new_s, new_s

    outs, final = sym.contrib.foreach(body, data, init)
    g = sym.Group([outs, final])
    rs = np.random.RandomState(0)
    d = rs.randn(5, 3).astype(np.float32)
    s0 = np.zeros(3, np.float32)
    res = _run(g, {"data": d, "init": s0})
    np.testing.assert_allclose(res[0], np.cumsum(d, axis=0), rtol=1e-5)
    np.testing.assert_allclose(res[1], d.sum(0), rtol=1e-5)


def test_sym_foreach_with_free_weight():
    """Weights used inside the body become ordinary graph arguments."""
    data = sym.Variable("data")
    init = sym.Variable("init")
    w = sym.Variable("w")

    def body(x, s):
        h = sym.broadcast_mul(x, w)
        new_s = sym.elemwise_add(h, s)
        return new_s, new_s

    outs, final = sym.contrib.foreach(body, data, init)
    assert "w" in sym.Group([outs]).list_arguments()
    rs = np.random.RandomState(1)
    d = rs.randn(4, 3).astype(np.float32)
    wv = rs.randn(3).astype(np.float32)
    res = _run(sym.Group([final]), {"data": d, "init": np.zeros(3, np.float32),
                                    "w": wv})
    np.testing.assert_allclose(res[0], (d * wv).sum(0), rtol=1e-5)


def test_sym_foreach_gradient():
    data = sym.Variable("data")
    init = sym.Variable("init")

    def body(x, s):
        new_s = sym.elemwise_add(sym.square(x), s)
        return new_s, new_s

    _, final = sym.contrib.foreach(body, data, init)
    rs = np.random.RandomState(2)
    d = rs.randn(4, 3).astype(np.float32)
    outs, grads = _run(final, {"data": d, "init": np.zeros(3, np.float32)},
                       grads=True, out_grads=nd.ones((3,)))
    # d(sum x^2)/dx = 2x
    np.testing.assert_allclose(grads["data"], 2 * d, rtol=1e-5)


def test_sym_while_loop_counts():
    """Run until i >= 4: buffered outputs + final loop vars."""
    i = sym.Variable("i")
    acc = sym.Variable("acc")

    def cond_fn(i, acc):
        return sym._internal._lesser_scalar(i, scalar=4.0)

    def func(i, acc):
        new_i = sym._internal._plus_scalar(i, scalar=1.0)
        new_acc = sym.elemwise_add(acc, new_i)
        return new_i, [new_i, new_acc]

    outs, finals = sym.contrib.while_loop(cond_fn, func, [i, acc],
                                          max_iterations=8)
    g = sym.Group([outs, *finals])
    res = _run(g, {"i": np.zeros((1,), np.float32),
                   "acc": np.zeros((1,), np.float32)})
    # steps produce i = 1..4, then predicate fails
    np.testing.assert_allclose(res[0][:4, 0], [1, 2, 3, 4])
    np.testing.assert_allclose(res[1], [4.0])
    np.testing.assert_allclose(res[2], [1 + 2 + 3 + 4.0])


def test_sym_cond_branches():
    pred = sym.Variable("p")
    x = sym.Variable("x")
    out = sym.contrib.cond(pred,
                           lambda a: sym.square(a),
                           lambda a: sym.negative(a), inputs=[x])
    xv = np.array([2.0, -3.0], np.float32)
    res_t = _run(out, {"p": np.array([1.0], np.float32), "x": xv})
    res_f = _run(out, {"p": np.array([0.0], np.float32), "x": xv})
    np.testing.assert_allclose(res_t[0], xv ** 2)
    np.testing.assert_allclose(res_f[0], -xv)


def test_sym_square_sum_and_sparse_ops_in_graph():
    x = sym.Variable("x")
    idx = sym.Variable("idx")
    ss = sym.op._square_sum(x, axis=(1,))
    cs = sym.op.cast_storage(x, stype="row_sparse")
    sr = sym.op.sparse_retain(x, idx)
    g = sym.Group([ss, cs, sr])
    rs = np.random.RandomState(3)
    xv = rs.randn(4, 3).astype(np.float32)
    res = _run(g, {"x": xv, "idx": np.array([0, 2], np.float32)})
    np.testing.assert_allclose(res[0], (xv ** 2).sum(1), rtol=1e-5)
    np.testing.assert_allclose(res[1], xv, rtol=1e-6)  # dense in-graph
    want = xv.copy()
    want[[1, 3]] = 0
    np.testing.assert_allclose(res[2], want, rtol=1e-6)


def test_sym_foreach_with_aux_state_op():
    """An op with auxiliary states (BatchNorm moving stats) inside the
    body: aux free variables must route through the executor's aux_map."""
    data = sym.Variable("data")          # (T, B, C)
    init = sym.Variable("init")
    gamma = sym.Variable("gamma")
    beta = sym.Variable("beta")

    def body(x, s):
        h = sym.BatchNorm(x, gamma, beta, use_global_stats=True,
                          fix_gamma=False, axis=1, name="bn")[0]
        return sym.elemwise_add(h, s), s

    outs, _ = sym.contrib.foreach(body, data, init)
    aux = sym.Group([outs]).list_auxiliary_states()
    assert any("moving_mean" in a for a in aux), aux
    rs = np.random.RandomState(4)
    T, B, C = 3, 2, 4
    d = rs.randn(T, B, C).astype(np.float32)
    arg_nds = {"data": nd.array(d), "init": nd.zeros((B, C)),
               "gamma": nd.ones((C,)), "beta": nd.zeros((C,))}
    aux_nds = {"bn_moving_mean": nd.zeros((C,)),
               "bn_moving_var": nd.ones((C,))}
    ex = sym.Group([outs]).bind(mx.cpu(), args=arg_nds,
                                aux_states=aux_nds)
    res = ex.forward(is_train=False)[0].asnumpy()
    # global stats mean=0 var=1 -> BN is ~identity (eps only)
    np.testing.assert_allclose(res, d / np.sqrt(1 + 1e-3), rtol=1e-4)


def test_sym_foreach_updates_moving_stats_in_training():
    """BatchNorm WITHOUT use_global_stats inside a foreach body: the
    moving stats must be updated by forward(is_train=True) — the loop
    carries them and the executor publishes the final values."""
    data = sym.Variable("data")
    init = sym.Variable("init")
    gamma = sym.Variable("gamma")
    beta = sym.Variable("beta")

    def body(x, s):
        h = sym.BatchNorm(x, gamma, beta, fix_gamma=False, axis=1,
                          momentum=0.5, name="bn")[0]
        return sym.elemwise_add(h, s), s

    outs, _ = sym.contrib.foreach(body, data, init)
    rs = np.random.RandomState(5)
    T, B, C = 3, 8, 2
    d = (rs.randn(T, B, C) * 2 + 5).astype(np.float32)
    arg_nds = {"data": nd.array(d), "init": nd.zeros((B, C)),
               "gamma": nd.ones((C,)), "beta": nd.zeros((C,))}
    aux_nds = {"bn_moving_mean": nd.zeros((C,)),
               "bn_moving_var": nd.ones((C,))}
    g = sym.Group([outs])
    ex = g.bind(mx.cpu(), args=arg_nds,
                args_grad={k: nd.zeros(v.shape) for k, v in arg_nds.items()},
                aux_states=aux_nds)
    ex.forward(is_train=True)
    ex.backward(out_grads=nd.ones((T, B, C)))
    mm = ex.aux_dict["bn_moving_mean"].asnumpy()
    mv = ex.aux_dict["bn_moving_var"].asnumpy()
    assert not np.allclose(mm, 0.0), "moving_mean never updated"
    assert not np.allclose(mv, 1.0), "moving_var never updated"
    # T momentum-0.5 updates of per-step batch means
    want_mm = np.zeros(C)
    want_mv = np.ones(C)
    for t in range(T):
        bm = d[t].mean(0)
        bv = d[t].var(0)
        want_mm = want_mm * 0.5 + bm * 0.5
        want_mv = want_mv * 0.5 + bv * 0.5
    np.testing.assert_allclose(mm, want_mm, rtol=1e-4)
    np.testing.assert_allclose(mv, want_mv, rtol=1e-4)


def test_cf_symbol_save_load_roundtrip():
    """tojson/load_json round-trips control-flow nodes (embedded
    subgraphs + typed attrs + aux markers)."""
    data = sym.Variable("data")
    init = sym.Variable("init")

    def body(x, s):
        new_s = sym.elemwise_add(x, s)
        return new_s, new_s

    outs, final = sym.contrib.foreach(body, data, init)
    g = sym.Group([outs, final])
    js = g.tojson()
    g2 = sym.load_json(js)
    assert g2.list_arguments() == g.list_arguments()
    rs = np.random.RandomState(6)
    d = rs.randn(4, 3).astype(np.float32)
    res = _run(g2, {"data": d, "init": np.zeros(3, np.float32)})
    np.testing.assert_allclose(res[0], np.cumsum(d, axis=0), rtol=1e-5)
    np.testing.assert_allclose(res[1], d.sum(0), rtol=1e-5)


def test_cf_op_imperative_invoke_raises():
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError, match="control-flow"):
        nd.imperative_invoke("_foreach", (nd.ones((2, 2)),), {})
