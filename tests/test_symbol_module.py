"""Symbol/Executor/Module tests (model: tests/python/unittest/test_symbol.py,
test_module.py, tests/python/train/test_mlp.py — BASELINE config #1 shape)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.io import NDArrayIter, DataBatch
from mxnet_tpu.module import Module, BucketingModule


def _mlp_symbol(num_hidden=32, num_classes=10):
    data = sym.var("data")
    net = sym.FullyConnected(data, name="fc1", num_hidden=num_hidden)
    net = sym.Activation(net, name="relu1", act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=num_classes)
    label = sym.var("softmax_label")
    return sym.SoftmaxOutput(net, label, name="softmax")


def test_symbol_compose_and_lists():
    s = _mlp_symbol()
    args = s.list_arguments()
    assert "data" in args and "softmax_label" in args
    # missing op inputs become auto-created variables, reference-style
    # (nnvm Symbol::Compose): fc1_weight/fc1_bias appear in arguments
    assert "fc1_weight" in args and "fc1_bias" in args
    # explicit weight vars
    data = sym.var("data")
    w = sym.var("w")
    out = sym.FullyConnected(data, w, num_hidden=4, no_bias=True)
    assert set(out.list_arguments()) == {"data", "w"}
    assert out.list_outputs()[0].endswith("_output")


def test_symbol_infer_shape():
    data = sym.var("data")
    w = sym.var("w")
    out = sym.FullyConnected(data, w, num_hidden=4, no_bias=True)
    arg_shapes, out_shapes, _ = out.infer_shape(data=(8, 16), w=(4, 16))
    assert out_shapes == [(8, 4)]
    assert arg_shapes[out.list_arguments().index("w")] == (4, 16)


def test_symbol_json_roundtrip(tmp_path):
    s = _mlp_symbol()
    f = str(tmp_path / "net-symbol.json")
    s.save(f)
    s2 = sym.load(f)
    assert s2.list_arguments() == s.list_arguments()
    assert s2.list_outputs() == s.list_outputs()


def test_executor_forward_backward():
    data = sym.var("data")
    w = sym.var("w")
    out = sym.FullyConnected(data, w, num_hidden=3, no_bias=True)
    loss = sym.sum(out)
    x = nd.ones((2, 5))
    wv = nd.ones((3, 5))
    ex = loss.bind(mx.cpu(), args={"data": x, "w": wv},
                   grad_req={"w": "write", "data": "null"})
    outs = ex.forward(is_train=True)
    assert float(outs[0].asscalar()) == 30.0
    ex.backward()
    assert np.allclose(ex.grad_dict["w"].asnumpy(), 2.0)


def test_executor_simple_bind():
    s = _mlp_symbol()
    # give weight vars explicit names via generated symbols
    data = sym.var("data")
    fc1_w = sym.var("fc1_weight")
    fc1_b = sym.var("fc1_bias")
    net = sym.FullyConnected(data, fc1_w, fc1_b, num_hidden=8)
    label = sym.var("softmax_label")
    net = sym.SoftmaxOutput(net, label)
    ex = net.simple_bind(mx.cpu(), data=(4, 6), softmax_label=(4,))
    assert ex.arg_dict["fc1_weight"].shape == (8, 6)
    ex.arg_dict["data"]._rebind(nd.ones((4, 6))._data)
    outs = ex.forward(is_train=False)
    assert outs[0].shape == (4, 8)


def _make_symbol_with_vars(num_hidden, num_classes):
    data = sym.var("data")
    w1, b1 = sym.var("fc1_weight"), sym.var("fc1_bias")
    h = sym.FullyConnected(data, w1, b1, num_hidden=num_hidden, name="fc1")
    h = sym.Activation(h, act_type="relu")
    w2, b2 = sym.var("fc2_weight"), sym.var("fc2_bias")
    h = sym.FullyConnected(h, w2, b2, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(h, sym.var("softmax_label"), name="softmax")


def _synthetic_mnist(n=512, d=16, classes=10, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d).astype(np.float32) * 3
    labels = rs.randint(0, classes, n)
    data = centers[labels] + rs.randn(n, d).astype(np.float32)
    return data, labels.astype(np.float32)


def test_module_train_converges():
    data, labels = _synthetic_mnist()
    train = NDArrayIter(data, labels, batch_size=64, shuffle=True)
    net = _make_symbol_with_vars(32, 10)
    mod = Module(net, context=mx.cpu())
    mod.fit(train, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            eval_metric="acc")
    score = mod.score(train, "acc")
    acc = dict(score)["accuracy"]
    assert acc > 0.85, f"module training failed to converge: acc={acc}"


def test_module_predict_and_checkpoint(tmp_path):
    data, labels = _synthetic_mnist(128)
    it = NDArrayIter(data, labels, batch_size=32)
    net = _make_symbol_with_vars(16, 10)
    mod = Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    preds = mod.predict(it)
    assert preds.shape == (128, 10)
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 3)
    mod2 = Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params()
    preds2 = mod2.predict(it)
    assert np.allclose(preds.asnumpy(), preds2.asnumpy(), atol=1e-5)


def test_module_batchnorm_aux_states():
    data = sym.var("data")
    g, b = sym.var("gamma"), sym.var("beta")
    out, _, _ = tuple(sym.BatchNorm(data, g, b, fix_gamma=False,
                                    name="bn"))[0:1] + (None, None)
    net = sym.Group([sym.BatchNorm(data, g, b, fix_gamma=False, name="bn2")[0]])
    assert "bn2_moving_mean" in net.list_auxiliary_states()
    assert "bn2_moving_var" in net.list_auxiliary_states()
    ex = net.simple_bind(mx.cpu(), data=(8, 4), gamma=(4,), beta=(4,))
    # init aux to identity transform
    ex.aux_dict["bn2_moving_var"]._rebind(nd.ones((4,))._data)
    ex.arg_dict["gamma"]._rebind(nd.ones((4,))._data)
    ex.arg_dict["data"]._rebind(
        nd.array(np.random.RandomState(0).randn(8, 4).astype(np.float32) + 7)._data)
    mm0 = ex.aux_dict["bn2_moving_mean"].asnumpy().copy()
    ex.forward(is_train=True)
    _ = ex.outputs
    mm1 = ex.aux_dict["bn2_moving_mean"].asnumpy()
    assert not np.allclose(mm0, mm1), "aux moving_mean should update in train"


def test_bucketing_module():
    def sym_gen(seq_len):
        # weight is bucket-independent (applied per time step); buckets
        # differ only in sequence length — the real RNN bucketing shape
        data = sym.var("data")
        w = sym.var("w")
        h = sym.FullyConnected(data, w, num_hidden=4, no_bias=True,
                               flatten=False)
        h = sym.reshape(h, shape=(-3, 4))
        out = sym.SoftmaxOutput(h, sym.var("softmax_label"))
        return out, ("data",), ("softmax_label",)

    bm = BucketingModule(sym_gen, default_bucket_key=8)
    bm.bind(data_shapes=[("data", (2, 8, 6))],
            label_shapes=[("softmax_label", (16,))])
    bm.init_params()
    bm.init_optimizer(optimizer="sgd")
    for key, n in [(8, 8), (4, 4), (8, 8)]:
        batch = DataBatch([nd.ones((2, n, 6))], [nd.zeros((2 * n,))],
                          bucket_key=key)
        bm.forward(batch, is_train=True)
        bm.backward()
        bm.update()
    # weights shared: bucket 4 and 8 use same param arrays
    w8 = bm._buckets[8]._exec.arg_dict["w"]
    w4 = bm._buckets[4]._exec.arg_dict["w"]
    assert w8 is w4


def test_grouped_symbol():
    a = sym.var("a")
    b = sym.var("b")
    g = sym.Group([a + b, a * b])
    ex = g.bind(mx.cpu(), args={"a": nd.array([2.0]), "b": nd.array([3.0])})
    outs = ex.forward()
    assert float(outs[0].asscalar()) == 5.0
    assert float(outs[1].asscalar()) == 6.0


def test_module_fit_with_monitor_and_callbacks():
    """The fit harness edge paths of reference test_module.py: monitor
    installed and fired, batch/epoch callbacks invoked with the right
    payloads, arg_params used to warm-start."""
    import mxnet_tpu as mx
    from mxnet_tpu import monitor as mon_mod
    rs = np.random.RandomState(0)
    X = rs.randn(64, 8).astype(np.float32)
    Y = (X.sum(1) > 0).astype(np.float32)

    data = mx.sym.Variable("data")
    w = mx.sym.Variable("fc_weight")
    fc = mx.sym.FullyConnected(data, w, mx.sym.Variable("fc_bias"),
                               num_hidden=2, name="fc")
    out = mx.sym.Softmax(fc, mx.sym.Variable("softmax_label"),
                         name="softmax")

    train_iter = mx.io.NDArrayIter(X, Y, batch_size=16)
    seen = {"batches": 0, "epochs": [], "monitor": 0}

    def stat(x):
        seen["monitor"] += 1
        return x.abs().mean()

    monitor = mx.Monitor(1, stat_func=stat, pattern=".*fc.*")

    def batch_cb(param):
        seen["batches"] += 1
        assert hasattr(param, "epoch") and hasattr(param, "nbatch")

    def epoch_cb(epoch, sym, arg_params, aux_params):
        seen["epochs"].append(epoch)
        assert "fc_weight" in arg_params

    mod = mx.mod.Module(out, data_names=["data"],
                        label_names=["softmax_label"])
    # warm start from explicit arg_params (the resume path)
    warm = {"fc_weight": mx.nd.zeros((2, 8)),
            "fc_bias": mx.nd.zeros((2,))}
    mod.fit(train_iter, num_epoch=2,
            arg_params=warm, allow_missing=True,
            initializer=mx.init.Xavier(),
            optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            batch_end_callback=batch_cb,
            epoch_end_callback=epoch_cb,
            monitor=monitor)
    assert seen["batches"] == 8  # 4 batches x 2 epochs
    assert seen["epochs"] == [0, 1]
    assert seen["monitor"] > 0, "installed monitor never fired"
    # metrics improve from the zero-init warm start
    m = mx.metric.Accuracy()
    mod.score(mx.io.NDArrayIter(X, Y, batch_size=16), m)
    assert m.get()[1] > 0.6


def test_executor_reshape_shares_parameters():
    """MXExecutorReshape semantics: reshaping to a new batch size keeps
    sharing the SAME parameter buffers (writes through one executor are
    visible in the other)."""
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    w = mx.sym.Variable("w")
    fc = mx.sym.FullyConnected(data, w, num_hidden=3, no_bias=True,
                               name="fc")
    warr = nd.array(np.ones((3, 4), np.float32))
    ex1 = fc.bind(mx.cpu(), args={"data": nd.zeros((2, 4)), "w": warr})
    ex2 = ex1.reshape(data=(5, 4))
    assert ex2.arg_dict["w"] is ex1.arg_dict["w"]
    # mutate the shared weight; both executors see it
    warr += 1.0
    o1 = ex1.forward(is_train=False, data=nd.ones((2, 4)))[0].asnumpy()
    o2 = ex2.forward(is_train=False, data=nd.ones((5, 4)))[0].asnumpy()
    np.testing.assert_allclose(o1[0], o2[0], rtol=1e-6)
    np.testing.assert_allclose(o1[0], np.full(3, 8.0), rtol=1e-6)


def test_bucketing_module_shares_parameters_across_buckets():
    """Switching buckets must reuse one parameter set (the shared_exec
    path): training on one bucket changes predictions on the other."""
    import mxnet_tpu as mx

    def gen(bucket_key):
        # time-axis bucketing: w is (2, 6) for EVERY bucket (applied per
        # step, flatten=False), so buckets can share one buffer
        data = mx.sym.Variable("data")
        w = mx.sym.Variable("w")
        fc = mx.sym.FullyConnected(data, w, num_hidden=2, no_bias=True,
                                   flatten=False, name="fc")
        return fc, ["data"], []

    mod = mx.mod.BucketingModule(gen, default_bucket_key=8)
    mod.bind(data_shapes=[("data", (4, 8, 6))], label_shapes=None,
             for_training=True)
    mod.init_params(mx.init.Constant(0.5))
    from mxnet_tpu.io import DataBatch
    mod.forward(DataBatch([nd.ones((4, 8, 6))], []), is_train=False)
    out8 = mod.get_outputs()[0].asnumpy()
    # switch to a shorter bucket: SAME param buffer, different shape
    mod.switch_bucket(4, [("data", (4, 4, 6))], None)
    assert mod._buckets[4]._exec.arg_dict["w"] is \
        mod._buckets[8]._exec.arg_dict["w"]
    mod.forward(DataBatch([nd.ones((4, 4, 6))], []), is_train=False)
    out4 = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(out8[0, 0], 0.5 * 6, rtol=1e-6)
    np.testing.assert_allclose(out4[0, 0], 0.5 * 6, rtol=1e-6)
    # a write through one bucket's buffer is visible in the other
    mod._buckets[8]._exec.arg_dict["w"] += 0.5
    mod.forward(DataBatch([nd.ones((4, 4, 6))], []), is_train=False)
    np.testing.assert_allclose(mod.get_outputs()[0].asnumpy()[0, 0],
                               1.0 * 6, rtol=1e-6)
