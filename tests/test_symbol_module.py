"""Symbol/Executor/Module tests (model: tests/python/unittest/test_symbol.py,
test_module.py, tests/python/train/test_mlp.py — BASELINE config #1 shape)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.io import NDArrayIter, DataBatch
from mxnet_tpu.module import Module, BucketingModule


def _mlp_symbol(num_hidden=32, num_classes=10):
    data = sym.var("data")
    net = sym.FullyConnected(data, name="fc1", num_hidden=num_hidden)
    net = sym.Activation(net, name="relu1", act_type="relu")
    net = sym.FullyConnected(net, name="fc2", num_hidden=num_classes)
    label = sym.var("softmax_label")
    return sym.SoftmaxOutput(net, label, name="softmax")


def test_symbol_compose_and_lists():
    s = _mlp_symbol()
    args = s.list_arguments()
    assert "data" in args and "softmax_label" in args
    assert "fc1_weight" not in args  # our sym ops don't auto-create weights
    # explicit weight vars
    data = sym.var("data")
    w = sym.var("w")
    out = sym.FullyConnected(data, w, num_hidden=4, no_bias=True)
    assert set(out.list_arguments()) == {"data", "w"}
    assert out.list_outputs()[0].endswith("_output")


def test_symbol_infer_shape():
    data = sym.var("data")
    w = sym.var("w")
    out = sym.FullyConnected(data, w, num_hidden=4, no_bias=True)
    arg_shapes, out_shapes, _ = out.infer_shape(data=(8, 16), w=(4, 16))
    assert out_shapes == [(8, 4)]
    assert arg_shapes[out.list_arguments().index("w")] == (4, 16)


def test_symbol_json_roundtrip(tmp_path):
    s = _mlp_symbol()
    f = str(tmp_path / "net-symbol.json")
    s.save(f)
    s2 = sym.load(f)
    assert s2.list_arguments() == s.list_arguments()
    assert s2.list_outputs() == s.list_outputs()


def test_executor_forward_backward():
    data = sym.var("data")
    w = sym.var("w")
    out = sym.FullyConnected(data, w, num_hidden=3, no_bias=True)
    loss = sym.sum(out)
    x = nd.ones((2, 5))
    wv = nd.ones((3, 5))
    ex = loss.bind(mx.cpu(), args={"data": x, "w": wv},
                   grad_req={"w": "write", "data": "null"})
    outs = ex.forward(is_train=True)
    assert float(outs[0].asscalar()) == 30.0
    ex.backward()
    assert np.allclose(ex.grad_dict["w"].asnumpy(), 2.0)


def test_executor_simple_bind():
    s = _mlp_symbol()
    # give weight vars explicit names via generated symbols
    data = sym.var("data")
    fc1_w = sym.var("fc1_weight")
    fc1_b = sym.var("fc1_bias")
    net = sym.FullyConnected(data, fc1_w, fc1_b, num_hidden=8)
    label = sym.var("softmax_label")
    net = sym.SoftmaxOutput(net, label)
    ex = net.simple_bind(mx.cpu(), data=(4, 6), softmax_label=(4,))
    assert ex.arg_dict["fc1_weight"].shape == (8, 6)
    ex.arg_dict["data"]._rebind(nd.ones((4, 6))._data)
    outs = ex.forward(is_train=False)
    assert outs[0].shape == (4, 8)


def _make_symbol_with_vars(num_hidden, num_classes):
    data = sym.var("data")
    w1, b1 = sym.var("fc1_weight"), sym.var("fc1_bias")
    h = sym.FullyConnected(data, w1, b1, num_hidden=num_hidden, name="fc1")
    h = sym.Activation(h, act_type="relu")
    w2, b2 = sym.var("fc2_weight"), sym.var("fc2_bias")
    h = sym.FullyConnected(h, w2, b2, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(h, sym.var("softmax_label"), name="softmax")


def _synthetic_mnist(n=512, d=16, classes=10, seed=0):
    rs = np.random.RandomState(seed)
    centers = rs.randn(classes, d).astype(np.float32) * 3
    labels = rs.randint(0, classes, n)
    data = centers[labels] + rs.randn(n, d).astype(np.float32)
    return data, labels.astype(np.float32)


def test_module_train_converges():
    data, labels = _synthetic_mnist()
    train = NDArrayIter(data, labels, batch_size=64, shuffle=True)
    net = _make_symbol_with_vars(32, 10)
    mod = Module(net, context=mx.cpu())
    mod.fit(train, num_epoch=5, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5},
            eval_metric="acc")
    score = mod.score(train, "acc")
    acc = dict(score)["accuracy"]
    assert acc > 0.85, f"module training failed to converge: acc={acc}"


def test_module_predict_and_checkpoint(tmp_path):
    data, labels = _synthetic_mnist(128)
    it = NDArrayIter(data, labels, batch_size=32)
    net = _make_symbol_with_vars(16, 10)
    mod = Module(net, context=mx.cpu())
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params()
    preds = mod.predict(it)
    assert preds.shape == (128, 10)
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 3)
    mod2 = Module.load(prefix, 3, context=mx.cpu())
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_params()
    preds2 = mod2.predict(it)
    assert np.allclose(preds.asnumpy(), preds2.asnumpy(), atol=1e-5)


def test_module_batchnorm_aux_states():
    data = sym.var("data")
    g, b = sym.var("gamma"), sym.var("beta")
    out, _, _ = tuple(sym.BatchNorm(data, g, b, fix_gamma=False,
                                    name="bn"))[0:1] + (None, None)
    net = sym.Group([sym.BatchNorm(data, g, b, fix_gamma=False, name="bn2")[0]])
    assert "bn2_moving_mean" in net.list_auxiliary_states()
    assert "bn2_moving_var" in net.list_auxiliary_states()
    ex = net.simple_bind(mx.cpu(), data=(8, 4), gamma=(4,), beta=(4,))
    # init aux to identity transform
    ex.aux_dict["bn2_moving_var"]._rebind(nd.ones((4,))._data)
    ex.arg_dict["gamma"]._rebind(nd.ones((4,))._data)
    ex.arg_dict["data"]._rebind(
        nd.array(np.random.RandomState(0).randn(8, 4).astype(np.float32) + 7)._data)
    mm0 = ex.aux_dict["bn2_moving_mean"].asnumpy().copy()
    ex.forward(is_train=True)
    _ = ex.outputs
    mm1 = ex.aux_dict["bn2_moving_mean"].asnumpy()
    assert not np.allclose(mm0, mm1), "aux moving_mean should update in train"


def test_bucketing_module():
    def sym_gen(seq_len):
        # weight is bucket-independent (applied per time step); buckets
        # differ only in sequence length — the real RNN bucketing shape
        data = sym.var("data")
        w = sym.var("w")
        h = sym.FullyConnected(data, w, num_hidden=4, no_bias=True,
                               flatten=False)
        h = sym.reshape(h, shape=(-3, 4))
        out = sym.SoftmaxOutput(h, sym.var("softmax_label"))
        return out, ("data",), ("softmax_label",)

    bm = BucketingModule(sym_gen, default_bucket_key=8)
    bm.bind(data_shapes=[("data", (2, 8, 6))],
            label_shapes=[("softmax_label", (16,))])
    bm.init_params()
    bm.init_optimizer(optimizer="sgd")
    for key, n in [(8, 8), (4, 4), (8, 8)]:
        batch = DataBatch([nd.ones((2, n, 6))], [nd.zeros((2 * n,))],
                          bucket_key=key)
        bm.forward(batch, is_train=True)
        bm.backward()
        bm.update()
    # weights shared: bucket 4 and 8 use same param arrays
    w8 = bm._buckets[8]._exec.arg_dict["w"]
    w4 = bm._buckets[4]._exec.arg_dict["w"]
    assert w8 is w4


def test_grouped_symbol():
    a = sym.var("a")
    b = sym.var("b")
    g = sym.Group([a + b, a * b])
    ex = g.bind(mx.cpu(), args={"a": nd.array([2.0]), "b": nd.array([3.0])})
    outs = ex.forward()
    assert float(outs[0].asscalar()) == 5.0
    assert float(outs[1].asscalar()) == 6.0
