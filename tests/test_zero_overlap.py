"""Overlapped ZeRO-1 (gluon/trainer.py + parallel/zero.py): the
grad-finality reduce-scatter and the per-bucket allgather prefetch must
reproduce the barrier plane's trajectory BITWISE for every grouped
optimizer — including a sentinel-declined (non-finite) step and a
kv_hang chaos step — while actually moving the collective launches into
the ``comm_overlapped`` breakdown segment, with the before/after run
reports grading in the improving direction through tools/run_compare.py.

Marker ``zero`` (tier-1-safe: CPU, simulated worlds in-process)."""
import json
import os
import sys

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, nd, gluon
from mxnet_tpu import kvstore as kvs
from mxnet_tpu import fit as fit_mod
from mxnet_tpu.io import NDArrayIter
from mxnet_tpu.contrib import chaos

from test_zero import OPTS, _zero_env

pytestmark = pytest.mark.zero

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _overlap_env(monkeypatch, world, overlap, bucket_mb="0.001"):
    _zero_env(monkeypatch, world)
    monkeypatch.setenv("MXTPU_COMM_OVERLAP", "on" if overlap else "off")
    monkeypatch.setenv("MXTPU_GRAD_BUCKET_MB", bucket_mb)
    monkeypatch.setenv("MXTPU_OPTIMIZER_AGGREGATION", "8")


def _build_net(width=16, out=4):
    mx.random.seed(0)
    net = gluon.nn.Sequential()
    net.add(gluon.nn.Dense(width, activation="relu"),
            gluon.nn.Dense(width, activation="relu"),
            gluon.nn.Dense(out))
    net.initialize(mx.init.Xavier())
    return net


def _train(opt, kw, overlap, monkeypatch, world=2, steps=4,
           chaos_spec=None):
    """Real-backward training loop (the autograd grad-ready hook fires
    per grad, so overlap launches during the reverse pass) returning the
    final weights + optimizer state for bitwise comparison."""
    _overlap_env(monkeypatch, world, overlap)
    net = _build_net()
    tr = gluon.Trainer(net.collect_params(), opt, dict(kw),
                       kvstore=kvs.create("local"))
    rs = np.random.RandomState(0)
    plan = None
    if chaos_spec:
        chaos.install(chaos_spec)
    try:
        for _ in range(steps):
            x = nd.array(rs.randn(8, 16).astype(np.float32))
            y = nd.array(rs.randn(8, 4).astype(np.float32))
            with autograd.record():
                loss = ((net(x) - y) ** 2).mean()
            with tr.overlap_scope() as scope:
                loss.backward()
            if overlap and scope.active:
                # the tentpole: collectives launched DURING backward,
                # before allreduce_grads/step ever ran
                assert tr.last_reduce_scatter_collectives >= 1
            tr.step(8)
        plan = chaos.active()
    finally:
        chaos.install("")
    weights = [p.data().asnumpy().copy()
               for p in net.collect_params().values()]

    def flat(sts):  # None (plain sgd) | array | tuple of arrays
        if sts is None:
            return []
        if isinstance(sts, (tuple, list)):
            return [np.asarray(s).copy() for s in sts]
        return [np.asarray(sts).copy()]

    states = {i: flat(sts)
              for i, sts in sorted(tr._updaters[0].states.items())}
    return weights, states, plan


@pytest.mark.parametrize("opt,kw", OPTS)
def test_zero_overlap_bitwise_parity(opt, kw, monkeypatch):
    """Overlapped ZeRO == barrier ZeRO, bitwise, for all six grouped
    optimizer configs: same buckets, same sums, same per-param counters
    — only the launch points move."""
    bw, bs, _ = _train(opt, kw, False, monkeypatch)
    ow, os_, _ = _train(opt, kw, True, monkeypatch)
    for a, b in zip(bw, ow):
        np.testing.assert_array_equal(a, b)
    assert sorted(bs) == sorted(os_)
    for i in bs:
        for a, b in zip(bs[i], os_[i]):
            np.testing.assert_array_equal(a, b)


def test_zero_overlap_kv_hang_chaos_parity(monkeypatch):
    """A kv_hang chaos step (rank 0 delays its collective mid-round)
    must not change the trajectory in either mode — the overlap launches
    ride the same chaos-wrapped kvstore entry points."""
    spec = "kv_hang:0@1:50"
    bw, _, plan_b = _train("adam", {"learning_rate": 0.01}, False,
                           monkeypatch, chaos_spec=spec)
    ow, _, plan_o = _train("adam", {"learning_rate": 0.01}, True,
                           monkeypatch, chaos_spec=spec)
    assert plan_b.injected["kv_hang"] >= 1
    assert plan_o.injected["kv_hang"] >= 1
    for a, b in zip(bw, ow):
        np.testing.assert_array_equal(a, b)


def _fit(monkeypatch, overlap, tmpdir=None, chaos_spec=None, steps=8,
         loss_scale=1.0, autotune=None):
    """One FitLoop run under simulated-world ZeRO; returns the
    FitResult (breakdown collection is on by default)."""
    _overlap_env(monkeypatch, 2, overlap)
    if tmpdir is not None:
        monkeypatch.setenv("MXTPU_RUN_REPORT_DIR", str(tmpdir))
    else:
        monkeypatch.delenv("MXTPU_RUN_REPORT_DIR", raising=False)
    if autotune is not None:
        monkeypatch.setenv("MXTPU_AUTOTUNE", autotune)
    else:
        monkeypatch.delenv("MXTPU_AUTOTUNE", raising=False)
    net = _build_net()
    tr = gluon.Trainer(net.collect_params(), "adam",
                       {"learning_rate": 1e-3}, kvstore=kvs.create("local"))
    rs = np.random.RandomState(0)
    it = NDArrayIter(rs.rand(steps * 4, 16).astype(np.float32),
                     rs.rand(steps * 4, 4).astype(np.float32),
                     batch_size=4)
    loss = lambda out, y: ((out - y) ** 2).mean()
    loop = fit_mod.FitLoop(net, tr, loss, it, ckpt_dir=None,
                           loss_scale=loss_scale)
    if chaos_spec:
        chaos.install(chaos_spec)
    try:
        res = loop.fit(epochs=1)
    finally:
        chaos.install("")
    res._weights = [p.data().asnumpy().copy()
                    for p in net.collect_params().values()]
    return res


def test_zero_overlap_nonfinite_step_parity(monkeypatch):
    """A chaos-poisoned (sentinel-declined) step under overlapped ZeRO:
    the poisoned step gets an inactive scope (no clean grads ship early),
    the global sentinel still skips it with loss-scale backoff, and the
    whole loss/weight trajectory equals the barrier plane's bitwise."""
    res_b = _fit(monkeypatch, False, chaos_spec="nan_grad@1",
                 loss_scale=128.0)
    res_o = _fit(monkeypatch, True, chaos_spec="nan_grad@1",
                 loss_scale=128.0)
    assert res_b.skipped_steps == [1]
    assert res_o.skipped_steps == [1]
    assert res_b.loss_scale == res_o.loss_scale == 64.0
    np.testing.assert_array_equal(res_b.losses, res_o.losses)
    for a, b in zip(res_b._weights, res_o._weights):
        np.testing.assert_array_equal(a, b)


def test_zero_overlap_moves_comm_share(monkeypatch):
    """The measured claim behind the knob: with overlap on, the exposed
    'comm' share of step time strictly drops vs barrier ZeRO and the
    moved time shows up in 'comm_overlapped' (total comm is attribution-
    conserved, not deleted)."""
    res_b = _fit(monkeypatch, False)
    res_o = _fit(monkeypatch, True)
    shares_b = res_b.step_breakdown["shares"]
    shares_o = res_o.step_breakdown["shares"]
    assert shares_o.get("comm_overlapped", 0.0) > 0.0
    assert shares_o.get("comm", 0.0) < shares_b.get("comm", 0.0)
    # trajectory unchanged while the attribution moved
    np.testing.assert_array_equal(res_b.losses, res_o.losses)


def test_zero_overlap_run_compare_direction(monkeypatch, tmp_path):
    """The CI hook: a barrier/overlap run-report pair diffs in the
    improving direction (comm_exposed_share shrinks, exit 0) and the
    reversed pair FAILS the gate naming comm_exposed_share — wired
    through tools/run_compare.py's real main()/exit codes."""
    sys.path.insert(0, os.path.join(ROOT, "tools"))
    try:
        import run_compare
    finally:
        sys.path.pop(0)
    # warm the compile caches so neither measured leg pays first-compile
    _fit(monkeypatch, False)
    _fit(monkeypatch, True)
    res_b = _fit(monkeypatch, False, tmpdir=tmp_path)
    res_o = _fit(monkeypatch, True, tmpdir=tmp_path)
    assert res_b.run_report and res_o.run_report
    a = run_compare.load_report(res_b.run_report)
    b = run_compare.load_report(res_o.run_report)
    verdict = run_compare.compare(a, b, fence_pct=50.0)
    assert "comm_exposed_share" in verdict["improved"]
    assert "comm_exposed_share" not in verdict["regressed"]
    # forward direction passes the gate on the comm metric; a huge fence
    # keeps unrelated step-time noise from muddying the exit code
    row = [r for r in verdict["metrics"]
           if r["metric"] == "comm_exposed_share"][0]
    assert row["verdict"] == "improved"
    # reversed pair: the regression must be caught and NAMED
    rc = run_compare.main([res_o.run_report, res_b.run_report,
                           "--fence", "50", "--json"])
    assert rc == 1
    reverse = run_compare.compare(b, a, fence_pct=50.0)
    assert "comm_exposed_share" in reverse["regressed"]


def test_zero_overlap_autotune_probes_knob(monkeypatch):
    """MXTPU_AUTOTUNE drives the overlap knob under ZeRO: the overlap
    candidate is probed (applicable — the plane no longer supersedes the
    knob), its exposed-comm share is recorded, the tuner locks, and the
    report says which comm plane it steered."""
    res = _fit(monkeypatch, False, steps=10,
               autotune="on,probe=2,warmup=1,knobs=overlap")
    rep = res.tuning_report
    assert rep is not None and rep["status"] == "locked"
    assert rep["zero"] is True
    assert "overlap" in rep["baseline"]
    cands = {c["label"]: c for c in rep["candidates"]}
    assert "overlap=1" in cands
    assert cands["overlap=1"]["comm_exposed_share"] is not None
    assert rep["chosen"]["overlap"] in (0, 1)


def test_zero_overlap_tile_layout():
    """The tiled psum_scatter padding rule (parallel/collectives.py):
    rank-major permutation, pad slots at index n, per-rank counts — and
    a host-side gather through the perm reproduces each rank's
    concatenated segments exactly (ragged, non-world-divisible parts)."""
    from mxnet_tpu.parallel.collectives import _tile_layout
    n = 11
    all_parts = [[(0, 3), (7, 9)],   # rank 0: 5 elements
                 [(3, 7)],           # rank 1: 4 elements
                 [(9, 11)]]          # rank 2: 2 elements
    counts, T, perm = _tile_layout(all_parts, n)
    assert counts == [5, 4, 2]
    assert T == 5
    assert perm.shape == (15,)
    local = np.arange(n, dtype=np.float64) * 10
    padded = np.concatenate([local, np.zeros(1)])
    wire = padded[perm]
    for r, ap in enumerate(all_parts):
        want = np.concatenate([local[lo:hi] for lo, hi in ap])
        got = wire[r * T:r * T + counts[r]]
        np.testing.assert_array_equal(got, want)
        # pad tail is zeros (the appended slot)
        np.testing.assert_array_equal(wire[r * T + counts[r]:(r + 1) * T],
                                      0.0)
    # the gate: wire cost world*T=15 vs allreduce ~2n=22 -> tiled wins
    assert len(all_parts) * T < 2 * n
    # degenerate ownership: one rank owns everything -> padding would
    # out-ship the allreduce, the gate must refuse
    counts1, T1, _ = _tile_layout([[(0, n)], [], []], n)
    assert counts1 == [n, 0, 0] and T1 == n
    assert not (3 * T1 < 2 * n)
