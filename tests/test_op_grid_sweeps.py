"""Parameter-space grids for the high-risk op families (VERDICT r3 #6).

Model: tests/python/unittest/test_operator.py — the reference sweeps
kernel/stride/pad/dilate/group combos for Convolution, pooling variants,
axis grids, and transpose combos for dot, each against a closed-form
reference. Every grid here checks >=10 configurations against a naive
numpy implementation; failures reproduce from the printed config (inputs
are seeded per-config).
"""
import itertools

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


# ---------------------------------------------------------------------------
# naive numpy references
# ---------------------------------------------------------------------------

def np_conv2d(x, w, b, stride, pad, dilate, groups):
    """x (N,C,H,W), w (O,C//g,kh,kw) -> (N,O,oh,ow); direct loops."""
    n, c, h, ww = x.shape
    o, cg, kh, kw = w.shape
    sh, sw = stride
    ph, pw = pad
    dh, dw = dilate
    xk = np.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)))
    ekh, ekw = (kh - 1) * dh + 1, (kw - 1) * dw + 1
    oh = (h + 2 * ph - ekh) // sh + 1
    ow = (ww + 2 * pw - ekw) // sw + 1
    out = np.zeros((n, o, oh, ow), np.float64)
    opg = o // groups
    for ni in range(n):
        for oi in range(o):
            g = oi // opg
            for yi in range(oh):
                for xi in range(ow):
                    patch = xk[ni, g * cg:(g + 1) * cg,
                               yi * sh:yi * sh + ekh:dh,
                               xi * sw:xi * sw + ekw:dw]
                    out[ni, oi, yi, xi] = np.sum(patch * w[oi])
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out


def np_pool2d(x, kernel, stride, pad, kind, count_include_pad):
    n, c, h, w = x.shape
    kh, kw = kernel
    sh, sw = stride
    ph, pw = pad
    fill = -np.inf if kind == "max" else 0.0
    xk = np.pad(x, ((0, 0), (0, 0), (ph, ph), (pw, pw)),
                constant_values=fill)
    oh = (h + 2 * ph - kh) // sh + 1
    ow = (w + 2 * pw - kw) // sw + 1
    out = np.zeros((n, c, oh, ow), np.float64)
    for yi in range(oh):
        for xi in range(ow):
            win = xk[:, :, yi * sh:yi * sh + kh, xi * sw:xi * sw + kw]
            if kind == "max":
                out[:, :, yi, xi] = win.max(axis=(2, 3))
            elif count_include_pad:
                out[:, :, yi, xi] = win.sum(axis=(2, 3)) / (kh * kw)
            else:
                # divide by the number of NON-pad elements in this window
                y0, x0 = yi * sh - ph, xi * sw - pw
                ny = min(y0 + kh, h) - max(y0, 0)
                nx = min(x0 + kw, w) - max(x0, 0)
                out[:, :, yi, xi] = win.sum(axis=(2, 3)) / (ny * nx)
    return out


# ---------------------------------------------------------------------------
# Convolution grid (ref: test_operator.py test_convolution_options)
# ---------------------------------------------------------------------------

CONV_GRID = [
    # (kernel, stride, pad, dilate, groups, layout)
    ((1, 1), (1, 1), (0, 0), (1, 1), 1, "NCHW"),
    ((3, 3), (1, 1), (0, 0), (1, 1), 1, "NCHW"),
    ((3, 3), (1, 1), (1, 1), (1, 1), 1, "NCHW"),
    ((3, 3), (2, 2), (1, 1), (1, 1), 1, "NCHW"),
    ((2, 4), (1, 2), (0, 1), (1, 1), 1, "NCHW"),
    ((3, 3), (1, 1), (2, 2), (2, 2), 1, "NCHW"),
    ((3, 3), (2, 1), (1, 0), (1, 2), 1, "NCHW"),
    ((3, 3), (1, 1), (1, 1), (1, 1), 2, "NCHW"),
    ((1, 1), (2, 2), (0, 0), (1, 1), 4, "NCHW"),
    ((3, 3), (1, 1), (1, 1), (1, 1), 1, "NHWC"),
    ((3, 3), (2, 2), (1, 1), (1, 1), 2, "NHWC"),
    ((5, 5), (2, 2), (2, 2), (1, 1), 1, "NHWC"),
    ((7, 7), (2, 2), (3, 3), (1, 1), 1, "NCHW"),
]


@pytest.mark.parametrize("kernel,stride,pad,dilate,groups,layout",
                         CONV_GRID)
def test_convolution_grid(kernel, stride, pad, dilate, groups, layout):
    rs = np.random.RandomState(hash((kernel, stride, pad, dilate, groups,
                                     layout)) % (2 ** 31))
    n, cin, h, w = 2, 4, 9, 10
    cout = 8 if groups == 4 else 6
    x = rs.randn(n, cin, h, w).astype(np.float32)
    wts = rs.randn(cout, cin // groups, *kernel).astype(np.float32)
    bias = rs.randn(cout).astype(np.float32)
    ref = np_conv2d(x.astype(np.float64), wts.astype(np.float64),
                    bias.astype(np.float64), stride, pad, dilate, groups)
    if layout == "NHWC":
        data = nd.array(np.transpose(x, (0, 2, 3, 1)))
        wz = nd.array(np.transpose(wts, (0, 2, 3, 1)))
    else:
        data = nd.array(x)
        wz = nd.array(wts)
    out = nd.op.Convolution(data, wz, nd.array(bias), kernel=kernel,
                            stride=stride, pad=pad, dilate=dilate,
                            num_filter=cout, num_group=groups,
                            layout=layout)
    got = out.asnumpy()
    if layout == "NHWC":
        got = np.transpose(got, (0, 3, 1, 2))
    np.testing.assert_allclose(got, ref, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# Pooling grid (ref: test_operator.py test_pooling_versions)
# ---------------------------------------------------------------------------

POOL_GRID = [
    # (kernel, stride, pad, type, global, count_include_pad)
    ((2, 2), (2, 2), (0, 0), "max", False, True),
    ((3, 3), (1, 1), (0, 0), "max", False, True),
    ((3, 3), (2, 2), (1, 1), "max", False, True),
    ((2, 3), (2, 1), (0, 1), "max", False, True),
    ((2, 2), (2, 2), (0, 0), "avg", False, True),
    ((3, 3), (2, 2), (1, 1), "avg", False, True),
    ((3, 3), (2, 2), (1, 1), "avg", False, False),
    ((2, 3), (1, 2), (1, 0), "avg", False, False),
    ((5, 5), (3, 3), (2, 2), "avg", False, True),
    ((0, 0), (1, 1), (0, 0), "max", True, True),
    ((0, 0), (1, 1), (0, 0), "avg", True, True),
]


@pytest.mark.parametrize("kernel,stride,pad,kind,global_pool,cip",
                         POOL_GRID)
def test_pooling_grid(kernel, stride, pad, kind, global_pool, cip):
    rs = np.random.RandomState(hash((kernel, stride, pad, kind,
                                     global_pool, cip)) % (2 ** 31))
    x = rs.randn(2, 3, 8, 9).astype(np.float32)
    if global_pool:
        ref = (x.max(axis=(2, 3), keepdims=True) if kind == "max"
               else x.mean(axis=(2, 3), keepdims=True))
        out = nd.op.Pooling(nd.array(x), pool_type=kind, global_pool=True)
    else:
        ref = np_pool2d(x.astype(np.float64), kernel, stride, pad, kind,
                        cip)
        out = nd.op.Pooling(nd.array(x), kernel=kernel, stride=stride,
                            pad=pad, pool_type=kind,
                            count_include_pad=cip)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# BatchNorm grid (ref: test_operator.py test_batchnorm_training)
# ---------------------------------------------------------------------------

BN_GRID = list(itertools.product([1, -1], [False, True], [False, True],
                                 [False, True]))  # axis, global, fix, train


@pytest.mark.parametrize("axis,use_global,fix_gamma,training", BN_GRID)
def test_batchnorm_grid(axis, use_global, fix_gamma, training):
    rs = np.random.RandomState(hash((axis, use_global, fix_gamma,
                                     training)) % (2 ** 31))
    x = (rs.randn(4, 3, 5, 6) * 2 + 1).astype(np.float32)
    cax = axis % x.ndim
    nch = x.shape[cax]
    gamma = (rs.rand(nch) + 0.5).astype(np.float32)
    beta = rs.randn(nch).astype(np.float32)
    mmean = rs.randn(nch).astype(np.float32)
    mvar = (rs.rand(nch) + 0.5).astype(np.float32)
    eps = 1e-3
    red = tuple(i for i in range(x.ndim) if i != cax)
    if training and not use_global:
        mean, var = x.mean(axis=red), x.var(axis=red)
    else:
        mean, var = mmean, mvar
    g = np.ones(nch) if fix_gamma else gamma
    bshape = tuple(nch if i == cax else 1 for i in range(x.ndim))
    ref = (x - mean.reshape(bshape)) / np.sqrt(
        var.reshape(bshape) + eps) * g.reshape(bshape) + beta.reshape(bshape)
    out = nd.op.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                          nd.array(mmean), nd.array(mvar), axis=axis,
                          eps=eps, fix_gamma=fix_gamma,
                          use_global_stats=use_global,
                          _training=training)
    got = out[0] if isinstance(out, (tuple, list)) else out
    np.testing.assert_allclose(got.asnumpy(), ref, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# take / gather_nd / scatter_nd grids (ref: test_operator.py test_take)
# ---------------------------------------------------------------------------

TAKE_GRID = list(itertools.product([0, 1, -1], ["clip", "wrap"]))


@pytest.mark.parametrize("axis,mode", TAKE_GRID)
def test_take_grid(axis, mode):
    rs = np.random.RandomState(hash((axis, mode)) % (2 ** 31))
    x = rs.randn(5, 6, 7).astype(np.float32)
    idx = rs.randint(-8, 12, (2, 3)).astype(np.float32)
    ref = np.take(x, idx.astype(np.int64), axis=axis, mode=mode)
    out = nd.op.take(nd.array(x), nd.array(idx), axis=axis, mode=mode)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)


@pytest.mark.parametrize("idx_shape,data_shape", [
    ((2, 3), (5, 6)),        # 2-D index into 2-D data
    ((1, 4), (7,)),          # 1-D gather
    ((3, 2), (4, 5, 6)),     # partial index, trailing slice
    ((2, 2, 2), (6, 6)),     # batched index grid
])
def test_gather_nd_grid(idx_shape, data_shape):
    rs = np.random.RandomState(hash((idx_shape, data_shape)) % (2 ** 31))
    data = rs.randn(*data_shape).astype(np.float32)
    m = idx_shape[0]
    idx = np.stack([rs.randint(0, data_shape[i], idx_shape[1:])
                    for i in range(m)]).astype(np.float32)
    ref = data[tuple(idx.astype(np.int64))]
    out = nd.op.gather_nd(nd.array(data), nd.array(idx))
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)


@pytest.mark.parametrize("m,shape", [(1, (6,)), (1, (6, 4)), (2, (4, 5)),
                                     (2, (4, 5, 3))])
def test_scatter_nd_grid(m, shape):
    rs = np.random.RandomState(hash((m, shape)) % (2 ** 31))
    k = 3
    idx = np.stack([rs.randint(0, shape[i], (k,))
                    for i in range(m)]).astype(np.float32)
    vals = rs.randn(k, *shape[m:]).astype(np.float32)
    ref = np.zeros(shape, np.float32)
    for j in range(k):
        ref[tuple(idx[:, j].astype(np.int64))] = vals[j]
    out = nd.op.scatter_nd(nd.array(vals), nd.array(idx), shape=shape)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-6)


# ---------------------------------------------------------------------------
# dot / batch_dot transpose grid (ref: test_operator.py test_dot)
# ---------------------------------------------------------------------------

DOT_GRID = list(itertools.product([False, True], [False, True],
                                  [(3, 4, 5), (1, 7, 2), (6, 6, 6)]))


@pytest.mark.parametrize("ta,tb,dims", DOT_GRID)
def test_dot_transpose_grid(ta, tb, dims):
    m, k, n = dims
    rs = np.random.RandomState(hash((ta, tb, dims)) % (2 ** 31))
    a = rs.randn(*((k, m) if ta else (m, k))).astype(np.float32)
    b = rs.randn(*((n, k) if tb else (k, n))).astype(np.float32)
    ref = (a.T if ta else a) @ (b.T if tb else b)
    out = nd.op.dot(nd.array(a), nd.array(b), transpose_a=ta,
                    transpose_b=tb)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("ta,tb", list(itertools.product([False, True],
                                                         repeat=2)))
def test_batch_dot_transpose_grid(ta, tb):
    rs = np.random.RandomState(hash((ta, tb)) % (2 ** 31))
    B, m, k, n = 3, 4, 5, 6
    a = rs.randn(*((B, k, m) if ta else (B, m, k))).astype(np.float32)
    b = rs.randn(*((B, n, k) if tb else (B, k, n))).astype(np.float32)
    ref = np.einsum("bij,bjk->bik",
                    np.transpose(a, (0, 2, 1)) if ta else a,
                    np.transpose(b, (0, 2, 1)) if tb else b)
    out = nd.op.batch_dot(nd.array(a), nd.array(b), transpose_a=ta,
                          transpose_b=tb)
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-4)
