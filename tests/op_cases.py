"""Registry-driven operator case table (model: the per-op tests of
tests/python/unittest/test_operator.py compressed into data).

Each entry maps a REGISTERED op name to one or more Cases. A Case drives
up to four checks in test_op_sweep.py:
  1. forward numpy cross-check (when ``ref`` is given),
  2. numeric-gradient check (autograd vs central differences) for
     differentiable ops with float inputs,
  3. dtype sweep (f32 result vs f16/bf16/f64 runs, loose tolerance),
  4. edge shapes (size-0 / 1-element) for elementwise-classed ops.

COVERED_ELSEWHERE lists registry ops whose fwd+bwd behavior is exercised
by a dedicated test file instead (kept exact: the coverage test greps the
file to prove the claim). test_op_coverage.py emits OP_COVERAGE.json.
"""
from __future__ import annotations

import numpy as np

__all__ = ["Case", "CASES", "COVERED_ELSEWHERE"]


class Case:
    def __init__(self, inputs, params=None, ref=None, grad=None,
                 rtol=1e-4, atol=1e-5, grad_rtol=2e-2, grad_atol=2e-3,
                 dtype_sweep=False, edge=False, out_index=0,
                 grad_only=None):
        self.inputs = inputs          # tuple of np arrays
        self.params = params or {}
        self.ref = ref                # callable(*inputs, **params) or None
        self.grad = grad              # None = auto (differentiable + float)
        self.rtol, self.atol = rtol, atol
        self.grad_rtol, self.grad_atol = grad_rtol, grad_atol
        self.dtype_sweep = dtype_sweep
        self.edge = edge              # also run on size-0 / scalar-ish input
        self.out_index = out_index    # which output the ref describes
        # indices of inputs to differentiate (None = all); index-like
        # inputs (lengths, positions) have no meaningful finite-difference
        self.grad_only = grad_only


def U(lo, hi, shape=(3, 4), seed=0, dtype=np.float32):
    return np.random.RandomState(seed).uniform(lo, hi, shape).astype(dtype)


def N(shape=(3, 4), seed=0, scale=1.0, dtype=np.float32):
    return (np.random.RandomState(seed).randn(*shape) * scale).astype(dtype)


def I(hi, shape=(3, 4), seed=0, dtype=np.int32):
    return np.random.RandomState(seed).randint(0, hi, shape).astype(dtype)


CASES = {}


def case(name, *cs):
    CASES[name] = list(cs)


# --------------------------------------------------------------------------
# elemwise: unary math
# --------------------------------------------------------------------------
import scipy.special as _sp

_UNARY = [
    # (name, numpy ref, (lo, hi), kwargs)
    ("abs", np.abs, (-2, 2), {}),
    ("arccos", np.arccos, (-0.9, 0.9), {}),
    ("arccosh", np.arccosh, (1.1, 3), {}),
    ("arcsin", np.arcsin, (-0.9, 0.9), {}),
    ("arcsinh", np.arcsinh, (-2, 2), {}),
    ("arctan", np.arctan, (-2, 2), {}),
    ("arctanh", np.arctanh, (-0.9, 0.9), {}),
    ("cbrt", np.cbrt, (0.2, 3), {}),
    ("ceil", np.ceil, (-3, 3), {"grad": False}),
    ("cos", np.cos, (-3, 3), {}),
    ("cosh", np.cosh, (-2, 2), {}),
    ("degrees", np.degrees, (-3, 3), {}),
    ("erf", _sp.erf, (-2, 2), {}),
    ("erfinv", _sp.erfinv, (-0.9, 0.9), {"grad_rtol": 5e-2}),
    ("exp", np.exp, (-1, 1), {}),
    ("expm1", np.expm1, (-1, 1), {}),
    ("fix", np.fix, (-3, 3), {"grad": False}),
    ("floor", np.floor, (-3, 3), {"grad": False}),
    ("gamma", _sp.gamma, (0.5, 3), {"grad_atol": 5e-3}),
    ("gammaln", _sp.gammaln, (0.5, 3), {"grad_atol": 5e-3}),
    ("log", np.log, (0.1, 3), {}),
    ("log10", np.log10, (0.1, 3), {}),
    ("log1p", np.log1p, (-0.5, 3), {}),
    ("log2", np.log2, (0.1, 3), {}),
    ("logical_not", lambda x: (x == 0).astype(np.float32), (-1, 1),
     {"grad": False}),
    ("negative", np.negative, (-2, 2), {}),
    ("radians", np.radians, (-90, 90), {}),
    ("rcbrt", lambda x: 1 / np.cbrt(x), (0.3, 3), {}),
    ("reciprocal", np.reciprocal, (0.3, 3), {}),
    ("relu", lambda x: np.maximum(x, 0), (-2, 2), {}),
    ("rint", np.rint, (-3, 3), {"grad": False}),
    ("round", lambda x: np.floor(x + 0.5) * (x >= 0) +
     np.ceil(x - 0.5) * (x < 0), (-3, 3), {"grad": False}),
    ("rsqrt", lambda x: 1 / np.sqrt(x), (0.3, 3), {}),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x)), (-3, 3), {}),
    ("sign", np.sign, (-2, 2), {"grad": False}),
    ("sin", np.sin, (-3, 3), {}),
    ("sinh", np.sinh, (-2, 2), {}),
    ("softsign", lambda x: x / (1 + np.abs(x)), (-2, 2), {}),
    ("sqrt", np.sqrt, (0.1, 4), {}),
    ("square", np.square, (-2, 2), {}),
    ("tan", np.tan, (-1, 1), {}),
    ("tanh", np.tanh, (-2, 2), {}),
    ("trunc", np.trunc, (-3, 3), {"grad": False}),
]

# rounding-family ops produce discrete outputs: a value that lands on a
# different side of an integer boundary after a low-precision cast changes
# the result by 1.0, so the close-to-f32 dtype sweep does not apply
_DISCRETE = {"ceil", "floor", "rint", "round", "trunc", "fix", "sign",
             "logical_not"}
for _name, _ref, _rng, _kw in _UNARY:
    case(_name, Case((U(_rng[0], _rng[1], seed=hash(_name) % 1000),),
                     ref=_ref, dtype_sweep=_name not in _DISCRETE,
                     edge=True, **_kw))

case("hard_sigmoid",
     Case((N(seed=3),), {"alpha": 0.2, "beta": 0.5},
          ref=lambda x, alpha, beta: np.clip(alpha * x + beta, 0, 1)))
case("smooth_l1",
     Case((N(seed=4),), {"scalar": 1.0},
          ref=lambda x, scalar: np.where(
              np.abs(x) < 1.0 / scalar**2,
              0.5 * (scalar * x) ** 2,
              np.abs(x) - 0.5 / scalar**2)))
case("BlockGrad", Case((N(seed=5),), ref=lambda x: x, grad=False))
case("_copy", Case((N(seed=6),), ref=lambda x: x, edge=True))
case("make_loss", Case((N(seed=7),), ref=lambda x: x))
case("ones_like", Case((N(seed=8),), ref=np.ones_like, grad=False))
case("zeros_like", Case((N(seed=9),), ref=np.zeros_like, grad=False))
case("shape_array",
     Case((N((2, 5), seed=10),), ref=lambda x: np.array([2, 5]),
          grad=False))
case("size_array",
     Case((N((2, 5), seed=11),), ref=lambda x: np.array([10]), grad=False))
case("Cast",
     Case((N(seed=12),), {"dtype": "float64"},
          ref=lambda x, dtype: x.astype(np.float64), grad=False))
case("amp_cast",
     Case((N(seed=13),), {"dtype": "float32"},
          ref=lambda x, dtype: x, grad=False))
case("gamma_sample_grad_dummy", Case((U(0.5, 2, seed=14),),
                                     ref=lambda x: x, grad=False))

# binary elemwise (also the operator aliases _plus/_minus/...)
_BIN = [
    ("_add", np.add, (0.5, 2)),
    ("_minus", np.subtract, (0.5, 2)),
    ("_mul", np.multiply, (0.5, 2)),
    ("_div", np.divide, (0.5, 2)),
    ("_mod", np.mod, (1.0, 5)),
    ("_power", np.power, (0.5, 2)),
    ("_hypot", np.hypot, (0.5, 2)),
    ("_maximum", np.maximum, (-2, 2)),
    ("_minimum", np.minimum, (-2, 2)),
    ("_scatter_elemwise_div", np.divide, (0.5, 2)),
]
for _name, _ref, _rng in _BIN:
    case(_name, Case((U(*_rng, seed=20), U(*_rng, seed=21)), ref=_ref,
                     dtype_sweep=True, edge=True,
                     grad=(None if _name != "_mod" else False)))

_CMP = [
    ("_equal", np.equal), ("_not_equal", np.not_equal),
    ("_greater", np.greater), ("_greater_equal", np.greater_equal),
    ("_lesser", np.less), ("_lesser_equal", np.less_equal),
    ("_logical_and", np.logical_and), ("_logical_or", np.logical_or),
    ("_logical_xor", np.logical_xor),
]
for _name, _ref in _CMP:
    _a, _b = I(3, seed=22).astype(np.float32), I(3, seed=23).astype(np.float32)
    case(_name, Case((_a, _b),
                     ref=lambda a, b, _f=_ref: _f(a, b).astype(np.float32),
                     grad=False))

# scalar forms incl. reverse variants
_SCALAR = [
    ("_plus_scalar", lambda x, s: x + s, (0.5, 2)),
    ("_minus_scalar", lambda x, s: x - s, (0.5, 2)),
    ("_rminus_scalar", lambda x, s: s - x, (0.5, 2)),
    ("_mul_scalar", lambda x, s: x * s, (0.5, 2)),
    ("_div_scalar", lambda x, s: x / s, (0.5, 2)),
    ("_rdiv_scalar", lambda x, s: s / x, (0.5, 2)),
    ("_mod_scalar", lambda x, s: np.mod(x, s), (1, 5)),
    ("_rmod_scalar", lambda x, s: np.mod(s, x), (1, 5)),
    ("_power_scalar", lambda x, s: x ** s, (0.5, 2)),
    ("_rpower_scalar", lambda x, s: s ** x, (0.5, 2)),
    ("_maximum_scalar", lambda x, s: np.maximum(x, s), (-2, 2)),
    ("_minimum_scalar", lambda x, s: np.minimum(x, s), (-2, 2)),
    ("_hypot_scalar", lambda x, s: np.hypot(x, s), (0.5, 2)),
    ("_scatter_plus_scalar", lambda x, s: x + s, (0.5, 2)),
    ("_scatter_minus_scalar", lambda x, s: x - s, (0.5, 2)),
]
for _name, _ref, _rng in _SCALAR:
    _grad = None if "_mod" not in _name else False
    case(_name, Case((U(*_rng, seed=25),), {"scalar": 1.5},
                     ref=lambda x, scalar, _f=_ref: _f(x, scalar),
                     grad=_grad))

_SCALAR_CMP = [
    ("_equal_scalar", np.equal), ("_not_equal_scalar", np.not_equal),
    ("_greater_scalar", np.greater),
    ("_greater_equal_scalar", np.greater_equal),
    ("_lesser_scalar", np.less), ("_lesser_equal_scalar", np.less_equal),
    ("_logical_and_scalar", np.logical_and),
    ("_logical_or_scalar", np.logical_or),
    ("_logical_xor_scalar", np.logical_xor),
]
for _name, _ref in _SCALAR_CMP:
    case(_name, Case((I(3, seed=26).astype(np.float32),), {"scalar": 1.0},
                     ref=lambda x, scalar, _f=_ref:
                     _f(x, scalar).astype(np.float32), grad=False))

case("amp_multicast",
     Case((N(seed=27), N(seed=28)), {"num_outputs": 2},
          ref=lambda a, b, num_outputs: a, grad=False))

# --------------------------------------------------------------------------
# broadcast
# --------------------------------------------------------------------------
_BCAST = [
    ("broadcast_add", np.add), ("broadcast_plus", np.add),
    ("broadcast_sub", np.subtract), ("broadcast_minus", np.subtract),
    ("broadcast_mul", np.multiply), ("broadcast_div", np.divide),
    ("broadcast_mod", np.mod), ("broadcast_power", np.power),
    ("broadcast_hypot", np.hypot),
    ("broadcast_maximum", np.maximum), ("broadcast_minimum", np.minimum),
]
for _name, _ref in _BCAST:
    _grad = None if _name != "broadcast_mod" else False
    case(_name, Case((U(0.5, 2, (2, 1, 4), seed=30),
                      U(0.5, 2, (1, 3, 4), seed=31)),
                     ref=_ref, dtype_sweep=True, grad=_grad))

_BCAST_CMP = [
    ("broadcast_equal", np.equal), ("broadcast_not_equal", np.not_equal),
    ("broadcast_greater", np.greater),
    ("broadcast_greater_equal", np.greater_equal),
    ("broadcast_lesser", np.less), ("broadcast_lesser_equal", np.less_equal),
    ("broadcast_logical_and", np.logical_and),
    ("broadcast_logical_or", np.logical_or),
    ("broadcast_logical_xor", np.logical_xor),
]
for _name, _ref in _BCAST_CMP:
    case(_name, Case((I(3, (2, 1), seed=32).astype(np.float32),
                      I(3, (2, 4), seed=33).astype(np.float32)),
                     ref=lambda a, b, _f=_ref: _f(a, b).astype(np.float32),
                     grad=False))

case("broadcast_axes",
     Case((N((2, 1, 3), seed=34),), {"axis": (1,), "size": (4,)},
          ref=lambda x, axis, size: np.broadcast_to(x, (2, 4, 3))))
case("broadcast_to",
     Case((N((2, 1, 3), seed=35),), {"shape": (2, 4, 3)},
          ref=lambda x, shape: np.broadcast_to(x, shape)))
case("broadcast_like",
     Case((N((2, 1), seed=36), N((2, 5), seed=37)),
          ref=lambda x, y: np.broadcast_to(x, y.shape)))

# --------------------------------------------------------------------------
# reduce
# --------------------------------------------------------------------------
case("sum",
     Case((N((3, 4), seed=40),), {"axis": (1,)},
          ref=lambda x, axis: x.sum(axis=axis), dtype_sweep=True,
          edge=True),
     Case((N((3, 4), seed=41),), {"axis": (0, 1), "keepdims": True},
          ref=lambda x, axis, keepdims: x.sum(axis=axis, keepdims=True)),
     Case((N((2, 3, 4), seed=42),), {"axis": (1,), "exclude": True},
          ref=lambda x, axis, exclude: x.sum(axis=(0, 2))))
case("mean",
     Case((N((3, 4), seed=43),), {"axis": (0,)},
          ref=lambda x, axis: x.mean(axis=axis)))
case("prod",
     Case((U(0.5, 1.5, (3, 4), seed=44),), {"axis": (1,)},
          ref=lambda x, axis: x.prod(axis=axis)))
case("nansum",
     Case((np.where(N((3, 4), seed=45) > 1, np.nan,
                    N((3, 4), seed=45)).astype(np.float32),),
          {"axis": (1,)}, ref=lambda x, axis: np.nansum(x, axis=axis),
          grad=False))
case("nanprod",
     Case((np.where(N((3, 4), seed=46) > 1, np.nan,
                    U(0.5, 1.5, (3, 4), seed=46)).astype(np.float32),),
          {"axis": (1,)}, ref=lambda x, axis: np.nanprod(x, axis=axis),
          grad=False))
case("max", Case((N((3, 4), seed=47),), {"axis": (1,)},
                 ref=lambda x, axis: x.max(axis=axis)))
case("min", Case((N((3, 4), seed=48),), {"axis": (1,)},
                 ref=lambda x, axis: x.min(axis=axis)))
case("norm",
     Case((N((3, 4), seed=49),), {},
          ref=lambda x: np.array(np.sqrt((x ** 2).sum()))),
     Case((N((3, 4), seed=50),), {"ord": 1, "axis": 1},
          ref=lambda x, ord, axis: np.abs(x).sum(axis=1)))
case("argmax",
     Case((N((3, 4), seed=51),), {"axis": 1},
          ref=lambda x, axis: x.argmax(axis=1).astype(np.float32),
          grad=False))
case("argmin",
     Case((N((3, 4), seed=52),), {"axis": 1},
          ref=lambda x, axis: x.argmin(axis=1).astype(np.float32),
          grad=False))
case("argmax_channel",
     Case((N((3, 4), seed=53),),
          ref=lambda x: x.argmax(axis=1).astype(np.float32), grad=False))

# --------------------------------------------------------------------------
# matrix
# --------------------------------------------------------------------------
case("dot",
     Case((N((3, 4), seed=60), N((4, 5), seed=61)),
          ref=lambda a, b: a @ b, dtype_sweep=True),
     Case((N((4, 3), seed=62), N((4, 5), seed=63)), {"transpose_a": True},
          ref=lambda a, b, transpose_a: a.T @ b))
case("batch_dot",
     Case((N((2, 3, 4), seed=64), N((2, 4, 5), seed=65)),
          ref=lambda a, b: np.einsum("bij,bjk->bik", a, b)))
case("matmul", Case((N((2, 3, 4), seed=66), N((4, 5), seed=67)),
                    ref=lambda a, b: a @ b))
case("Flatten", Case((N((2, 3, 4), seed=68),),
                     ref=lambda x: x.reshape(2, 12)))
case("Reshape",
     Case((N((2, 6), seed=69),), {"shape": (3, 4)},
          ref=lambda x, shape: x.reshape(shape)),
     Case((N((2, 6), seed=70),), {"shape": (-1, 3)},
          ref=lambda x, shape: x.reshape(-1, 3)))
case("transpose",
     Case((N((2, 3, 4), seed=71),), {"axes": (2, 0, 1)},
          ref=lambda x, axes: x.transpose(axes)),
     Case((N((2, 3), seed=72),), {}, ref=lambda x: x.T))
case("expand_dims", Case((N((2, 3), seed=73),), {"axis": 1},
                         ref=lambda x, axis: x[:, None, :]))
case("squeeze", Case((N((2, 1, 3), seed=74),), {"axis": 1},
                     ref=lambda x, axis: x.squeeze(1)))
case("Concat",
     Case((N((2, 3), seed=75), N((2, 4), seed=76)), {"dim": 1},
          ref=lambda a, b, dim: np.concatenate([a, b], axis=1)))
case("stack",
     Case((N((2, 3), seed=77), N((2, 3), seed=78)), {"axis": 1},
          ref=lambda a, b, axis: np.stack([a, b], axis=1)))
case("SliceChannel",
     Case((N((2, 6), seed=79),), {"num_outputs": 3, "axis": 1},
          ref=lambda x, num_outputs, axis: x[:, 0:2]))
case("_split_v2",
     Case((N((2, 6), seed=80),), {"sections": 2, "axis": 1},
          ref=lambda x, sections, axis: x[:, :3]))
case("slice_axis",
     Case((N((3, 6), seed=81),), {"axis": 1, "begin": 1, "end": 4},
          ref=lambda x, axis, begin, end: x[:, 1:4]))
case("crop",
     Case((N((3, 6), seed=82),), {"begin": (0, 1), "end": (2, 5)},
          ref=lambda x, begin, end: x[0:2, 1:5]))
case("slice_like",
     Case((N((4, 6), seed=83), N((2, 3), seed=84)),
          ref=lambda x, y: x[:2, :3]))
case("take",
     Case((N((5, 3), seed=85), np.array([0, 2, 4], np.int32)),
          ref=lambda x, i: x[i], dtype_sweep=True),
     # clip mode clamps out-of-range; wrap mode wraps negative/overflow
     Case((N((4, 2), seed=217), np.array([-1, 5, 3], np.int32)),
          {"mode": "clip"},
          ref=lambda x, i, mode: x[np.clip(i, 0, 3)]),
     Case((N((4, 2), seed=218), np.array([-1, 5, 3], np.int32)),
          {"mode": "wrap"},
          ref=lambda x, i, mode: x[i % 4]))
case("batch_take",
     Case((N((3, 4), seed=86), np.array([0, 2, 1], np.int32)),
          ref=lambda a, i: a[np.arange(3), i]))
case("pick",
     Case((N((3, 4), seed=87), np.array([0, 2, 1], np.float32)),
          {"axis": 1},
          ref=lambda x, i, axis: x[np.arange(3), i.astype(int)],
          grad_only=(0,)))
case("gather_nd",
     Case((N((3, 4), seed=88), np.array([[0, 2], [1, 3]], np.int32)),
          ref=lambda x, idx: x[idx[0], idx[1]]))
case("scatter_nd",
     Case((np.array([1.0, 2.0], np.float32),
           np.array([[0, 2], [1, 3]], np.int32)),
          {"shape": (3, 4)},
          ref=lambda d, idx, shape: _scatter_ref(d, idx, shape)))


def _scatter_ref(d, idx, shape):
    out = np.zeros(shape, np.float32)
    out[idx[0], idx[1]] = d
    return out


case("_scatter_set_nd",
     Case((np.zeros((3, 4), np.float32), np.array([1.0, 2.0], np.float32),
           np.array([[0, 2], [1, 3]], np.int32)),
          {"shape": (3, 4)},
          ref=lambda lhs, d, idx, shape: _scatter_ref(d, idx, shape),
          grad=False))
case("tile", Case((N((2, 3), seed=89),), {"reps": (2, 2)},
                  ref=lambda x, reps: np.tile(x, reps)))
case("repeat",
     Case((N((2, 3), seed=90),), {"repeats": 2, "axis": 1},
          ref=lambda x, repeats, axis: np.repeat(x, repeats, axis=1)),
     Case((N((2, 3), seed=91),), {"repeats": 2},
          ref=lambda x, repeats: np.repeat(x.reshape(-1), 2)))
case("flip", Case((N((2, 3), seed=92),), {"axis": 1},
                  ref=lambda x, axis: x[:, ::-1]))
case("reverse", Case((N((2, 3), seed=93),), {"axis": 1},
                     ref=lambda x, axis: x[:, ::-1]))
case("SwapAxis", Case((N((2, 3, 4), seed=94),), {"dim1": 0, "dim2": 2},
                      ref=lambda x, dim1, dim2: x.swapaxes(0, 2)))
case("moveaxis", Case((N((2, 3, 4), seed=95),),
                      {"source": 0, "destination": 2},
                      ref=lambda x, source, destination:
                      np.moveaxis(x, 0, 2)))
case("diag",
     Case((N((4, 4), seed=96),), {}, ref=lambda x: np.diag(x)),
     Case((np.arange(3, dtype=np.float32),), {},
          ref=lambda x: np.diag(x)))
case("one_hot",
     Case((np.array([0, 2, 1], np.int32),), {"depth": 4},
          ref=lambda i, depth: np.eye(4, dtype=np.float32)[i], grad=False))
case("where",
     Case((np.array([1, 0, 1], np.float32), N((3,), seed=97),
           N((3,), seed=98)),
          ref=lambda c, a, b: np.where(c != 0, a, b)))
case("clip",
     Case((N((3, 4), seed=99),), {"a_min": -0.5, "a_max": 0.5},
          ref=lambda x, a_min, a_max: np.clip(x, a_min, a_max)))
case("Pad",
     Case((N((2, 3, 4, 5), seed=100),),
          {"mode": "constant", "pad_width": (0, 0, 0, 0, 1, 1, 2, 2),
           "constant_value": 0.0},
          ref=lambda x, mode, pad_width, constant_value:
          np.pad(x, ((0, 0), (0, 0), (1, 1), (2, 2)))))
case("depth_to_space",
     Case((N((1, 4, 2, 3), seed=101),), {"block_size": 2},
          ref=lambda x, block_size: _d2s_ref(x, 2)))
case("space_to_depth",
     Case((N((1, 1, 4, 6), seed=102),), {"block_size": 2},
          ref=lambda x, block_size: _s2d_ref(x, 2)))


def _d2s_ref(x, b):
    n, c, h, w = x.shape
    y = x.reshape(n, b, b, c // (b * b), h, w)
    return y.transpose(0, 3, 4, 1, 5, 2).reshape(n, c // (b * b),
                                                 h * b, w * b)


def _s2d_ref(x, b):
    n, c, h, w = x.shape
    y = x.reshape(n, c, h // b, b, w // b, b)
    return y.transpose(0, 3, 5, 1, 2, 4).reshape(n, c * b * b,
                                                 h // b, w // b)


case("ravel_multi_index",
     Case((np.array([[0, 1], [2, 0]], np.float32),), {"shape": (3, 4)},
          ref=lambda d, shape: np.array([2.0, 4.0], np.float32),
          grad=False))
case("unravel_index",
     Case((np.array([2, 4], np.float32),), {"shape": (3, 4)},
          ref=lambda d, shape: np.array([[0, 1], [2, 0]], np.float32),
          grad=False))
case("reshape_like",
     Case((N((2, 6), seed=103), N((3, 4), seed=104)),
          ref=lambda x, y: x.reshape(3, 4)))
case("khatri_rao",
     Case((N((2, 3), seed=105), N((4, 3), seed=106)),
          ref=lambda a, b: np.vstack([np.kron(a[:, k], b[:, k])
                                      for k in range(3)]).T))

# --------------------------------------------------------------------------
# ordering
# --------------------------------------------------------------------------
case("sort", Case((N((3, 5), seed=110),), {"axis": 1},
                  ref=lambda x, axis: np.sort(x, axis=1)))
case("argsort",
     Case((N((3, 5), seed=111),), {"axis": 1},
          ref=lambda x, axis: np.argsort(x, axis=1).astype(np.float32),
          grad=False))
case("topk",
     Case((N((3, 5), seed=112),), {"axis": 1, "k": 2, "ret_typ": "value"},
          ref=lambda x, axis, k, ret_typ: np.sort(x, axis=1)[:, ::-1][:, :2],
          grad=False))

# --------------------------------------------------------------------------
# nn
# --------------------------------------------------------------------------
case("Activation",
     Case((N(seed=120),), {"act_type": "relu"},
          ref=lambda x, act_type: np.maximum(x, 0)),
     Case((N(seed=121),), {"act_type": "softrelu"},
          ref=lambda x, act_type: np.log1p(np.exp(x))))
case("LeakyReLU",
     Case((N(seed=122),), {"act_type": "leaky", "slope": 0.1},
          ref=lambda x, act_type, slope: np.where(x > 0, x, 0.1 * x)),
     Case((N(seed=123),), {"act_type": "elu", "slope": 1.0},
          ref=lambda x, act_type, slope: np.where(x > 0, x,
                                                  np.expm1(x))),
     # prelu: learned per-channel (here scalar) negative slope input
     Case((N((2, 4), seed=229), np.full((1,), 0.2, np.float32)),
          {"act_type": "prelu"},
          ref=lambda x, g, act_type: np.where(x > 0, x, 0.2 * x)))
case("FullyConnected",
     Case((N((4, 6), seed=124), N((3, 6), seed=125), N((3,), seed=126)),
          {"num_hidden": 3},
          ref=lambda x, w, b, num_hidden: x @ w.T + b, dtype_sweep=True))
case("Convolution",
     Case((N((2, 2, 5, 5), seed=127), N((3, 2, 3, 3), seed=128)),
          {"kernel": (3, 3), "num_filter": 3, "no_bias": True},
          ref=lambda x, w, **kw: _conv2d_ref(x, w), grad_rtol=4e-2),
     # stride 2 + padding 1
     Case((N((1, 2, 6, 6), seed=219), N((4, 2, 3, 3), seed=220)),
          {"kernel": (3, 3), "num_filter": 4, "no_bias": True,
           "stride": (2, 2), "pad": (1, 1)},
          ref=lambda x, w, **kw: _conv2d_ref(x, w, stride=2, pad=1),
          grad_rtol=4e-2),
     # grouped convolution (num_group=2)
     Case((N((1, 4, 5, 5), seed=221), N((4, 2, 3, 3), seed=222)),
          {"kernel": (3, 3), "num_filter": 4, "no_bias": True,
           "num_group": 2},
          ref=lambda x, w, **kw: np.concatenate(
              [_conv2d_ref(x[:, :2], w[:2]),
               _conv2d_ref(x[:, 2:], w[2:])], axis=1),
          grad_rtol=4e-2),
     # with bias
     Case((N((1, 2, 4, 4), seed=223), N((3, 2, 3, 3), seed=224),
           N((3,), seed=225)),
          {"kernel": (3, 3), "num_filter": 3},
          ref=lambda x, w, b, **kw:
          _conv2d_ref(x, w) + b.reshape(1, -1, 1, 1), grad_rtol=4e-2))


def _conv2d_ref(x, w, stride=1, pad=0):
    if pad:
        x = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    n, c, h, wd = x.shape
    f, _, kh, kw = w.shape
    oh = (h - kh) // stride + 1
    ow = (wd - kw) // stride + 1
    out = np.zeros((n, f, oh, ow), np.float32)
    for i in range(oh):
        for j in range(ow):
            patch = x[:, :, i * stride:i * stride + kh,
                      j * stride:j * stride + kw]
            out[:, :, i, j] = np.einsum("nchw,fchw->nf", patch, w)
    return out


case("Deconvolution",
     Case((N((1, 2, 3, 3), seed=129), N((2, 2, 2, 2), seed=130)),
          {"kernel": (2, 2), "num_filter": 2, "no_bias": True},
          ref=lambda x, w, **kw: _deconv2d_ref(x, w), grad_rtol=4e-2))


def _deconv2d_ref(x, w):
    # transposed convolution, stride 1: scatter each input pixel through
    # the kernel (w layout: (in_ch, out_ch, kh, kw))
    n, ci, h, wd = x.shape
    _, co, kh, kw = w.shape
    out = np.zeros((n, co, h + kh - 1, wd + kw - 1), np.float32)
    for i in range(h):
        for j in range(wd):
            out[:, :, i:i + kh, j:j + kw] += np.einsum(
                "nc,cfhw->nfhw", x[:, :, i, j], w)
    return out
case("Pooling",
     Case((N((2, 2, 4, 4), seed=131),),
          {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"},
          ref=lambda x, **kw: x.reshape(2, 2, 2, 2, 2, 2).max((3, 5))),
     Case((N((2, 2, 4, 4), seed=132),),
          {"kernel": (2, 2), "stride": (2, 2), "pool_type": "avg"},
          ref=lambda x, **kw: x.reshape(2, 2, 2, 2, 2, 2).mean((3, 5))),
     # global pooling ignores kernel
     Case((N((2, 3, 5, 5), seed=226),),
          {"kernel": (2, 2), "pool_type": "avg", "global_pool": True},
          ref=lambda x, **kw: x.mean((2, 3), keepdims=True)),
     # 'full' convention rounds the output size UP (ref: pooling-inl.h
     # pooling_convention=full)
     Case((N((1, 1, 5, 5), seed=227),),
          {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max",
           "pooling_convention": "full"},
          ref=lambda x, **kw: _pool_full_ref(x)),
     # sum pooling
     Case((N((1, 2, 4, 4), seed=228),),
          {"kernel": (2, 2), "stride": (2, 2), "pool_type": "sum"},
          ref=lambda x, **kw: x.reshape(1, 2, 2, 2, 2, 2).sum((3, 5))))


def _pool_full_ref(x):
    # 5x5, kernel 2, stride 2, full: out 3x3 (last window partial)
    out = np.full((1, 1, 3, 3), -np.inf, np.float32)
    for i in range(3):
        for j in range(3):
            out[0, 0, i, j] = x[0, 0, 2 * i:2 * i + 2,
                                2 * j:2 * j + 2].max()
    return out
case("softmax",
     Case((N((3, 5), seed=133),), {"axis": -1},
          ref=lambda x, axis: _softmax_ref(x), dtype_sweep=True),
     # masked softmax: positions >= length get exactly 0, a length-0 row
     # is all zeros (ref: softmax-inl.h use_length path)
     # float32 lengths so the numeric-gradient leg runs (grad_only skips
     # perturbing the length input; _length_mask casts internally)
     Case((N((3, 5), seed=230), np.array([3, 5, 0], np.float32)),
          {"axis": -1, "use_length": True},
          ref=lambda x, l, axis, use_length: _masked_softmax_ref(x, l),
          grad_only=(0,)),
     Case((N((3, 5), seed=231),), {"temperature": 2.0},
          ref=lambda x, temperature: _softmax_ref(x / 2.0)))
case("log_softmax",
     Case((N((3, 5), seed=134),), {"axis": -1},
          ref=lambda x, axis: np.log(_softmax_ref(x))))
case("softmin",
     Case((N((3, 5), seed=135),), {"axis": -1},
          ref=lambda x, axis: _softmax_ref(-x)))


def _masked_softmax_ref(x, lengths):
    out = np.zeros_like(x)
    for i, L in enumerate(lengths.astype(int)):
        if L > 0:
            out[i, :L] = _softmax_ref(x[i, :L].reshape(1, -1))
    return out
case("SoftmaxActivation",
     Case((N((3, 5), seed=136),), ref=lambda x: _softmax_ref(x)))


def _softmax_ref(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


# SoftmaxOutput: forward is softmax; backward is the fused (p - onehot)
# loss gradient by design (ref: softmax_output.cc), so finite differences
# of the forward do NOT apply.
case("Softmax",
     Case((N((4, 5), seed=137), np.array([0, 1, 2, 3], np.float32)),
          ref=lambda x, y: _softmax_ref(x), grad=False))
case("softmax_cross_entropy",
     Case((N((4, 5), seed=138), np.array([0, 1, 2, 3], np.float32)),
          ref=lambda x, y: np.array(
              -np.log(_softmax_ref(x))[np.arange(4),
                                       y.astype(int)].sum()),
          grad=False))
case("LayerNorm",
     Case((N((3, 5), seed=139), np.ones(5, np.float32),
           np.zeros(5, np.float32)),
          {"axis": -1},
          ref=lambda x, g, b, axis: (x - x.mean(-1, keepdims=True)) /
          np.sqrt(x.var(-1, keepdims=True) + 1e-5)))
case("InstanceNorm",
     Case((N((2, 3, 4), seed=140), np.ones(3, np.float32),
           np.zeros(3, np.float32)),
          ref=lambda x, g, b: (x - x.mean(-1, keepdims=True)) /
          np.sqrt(x.var(-1, keepdims=True) + 1e-3)))
case("L2Normalization",
     Case((N((3, 5), seed=141),),
          ref=lambda x: x / np.sqrt((x ** 2).sum(
              axis=tuple(range(1, x.ndim)), keepdims=True) + 1e-10)))
case("LRN", Case((N((2, 6, 3, 3), seed=142),), {"nsize": 3},
                 ref=lambda x, nsize: _lrn_ref(x, nsize),
                 grad_rtol=4e-2))


def _lrn_ref(x, nsize, alpha=1e-4, beta=0.75, knorm=2.0):
    # cross-channel local response norm; alpha is divided by nsize
    # (ref: lrn-inl.h  tmp_norm = knorm + alpha/nsize * sum(sq))
    n, c, h, w = x.shape
    half = nsize // 2
    sq = x ** 2
    denom = np.zeros_like(x)
    for ch in range(c):
        lo, hi = max(0, ch - half), min(c, ch + half + 1)
        denom[:, ch] = sq[:, lo:hi].sum(axis=1)
    return x / (knorm + alpha / nsize * denom) ** beta
case("Embedding",
     Case((np.array([0, 2, 1], np.int32), N((4, 5), seed=143)),
          {"input_dim": 4, "output_dim": 5},
          ref=lambda i, w, **kw: w[i]))
# Dropout is an rng op (takes a PRNG key input) — exercised through the
# gluon layer in tests/test_gluon.py instead of direct registry invoke
case("GridGenerator",
     Case((N((1, 6), seed=145),),
          {"transform_type": "affine", "target_shape": (2, 3)},
          grad=False))
case("UpSampling",
     Case((N((1, 2, 2, 2), seed=146),),
          {"scale": 2, "sample_type": "nearest"},
          ref=lambda x, **kw: x.repeat(2, 2).repeat(2, 3)))
case("SequenceMask",
     Case((N((4, 2, 3), seed=147), np.array([2, 4], np.float32)),
          {"use_sequence_length": True},
          ref=lambda x, l, **kw: _seqmask_ref(x, l), grad_only=(0,)))


def _seqmask_ref(x, lens, value=0.0):
    out = x.copy()
    for b, L in enumerate(lens.astype(int)):
        out[L:, b] = value
    return out


case("SequenceLast",
     Case((N((4, 2, 3), seed=148), np.array([2, 4], np.float32)),
          {"use_sequence_length": True},
          ref=lambda x, l, **kw: x[l.astype(int) - 1, np.arange(2)],
          grad_only=(0,)))
case("SequenceReverse",
     Case((N((4, 2, 3), seed=149), np.array([2, 4], np.float32)),
          {"use_sequence_length": True},
          ref=lambda x, l, **kw: _seqrev_ref(x, l), grad_only=(0,)))


def _seqrev_ref(x, lens):
    out = x.copy()
    for b, L in enumerate(lens.astype(int)):
        out[:L, b] = x[:L, b][::-1]
    return out


case("LinearRegressionOutput",
     Case((N((3, 4), seed=150), N((3, 4), seed=151)),
          ref=lambda x, y: x, grad=False))
case("MAERegressionOutput",
     Case((N((3, 4), seed=152), N((3, 4), seed=153)),
          ref=lambda x, y: x, grad=False))
case("LogisticRegressionOutput",
     Case((N((3, 4), seed=154), N((3, 4), seed=155)),
          ref=lambda x, y: 1 / (1 + np.exp(-x)), grad=False))
case("SVMOutput",
     Case((N((3, 4), seed=156), np.array([0, 1, 2], np.float32)),
          ref=lambda x, y: x, grad=False))
case("MakeLoss", Case((U(0.5, 2, seed=157),), ref=lambda x: x))
case("IdentityAttachKLSparseReg",
     Case((U(0.1, 0.9, seed=158),), ref=lambda x: x))
case("ElementWiseSum",
     Case((N(seed=159), N(seed=160), N(seed=161)),
          ref=lambda *xs: sum(xs)))
case("_rnn_param_concat",
     Case((N((2, 3), seed=162), N((4, 3), seed=163)), {"dim": 0},
          ref=lambda a, b, dim: np.concatenate(
              [a.reshape(-1), b.reshape(-1)])))
case("Crop",
     Case((N((1, 2, 5, 5), seed=164),), {"h_w": (3, 3)},
          ref=lambda x, h_w: x[:, :, :3, :3]))
case("_CrossDeviceCopy", Case((N(seed=165),), ref=lambda x: x))
case("_identity_with_attr_like_rhs",
     Case((N(seed=166), N(seed=167)), ref=lambda a, b: a))
case("_slice_assign",
     Case((np.zeros((3, 4), np.float32), np.ones((2, 2), np.float32)),
          {"begin": (0, 1), "end": (2, 3)},
          ref=lambda l, r, begin, end: _sa_ref(l, r)))


def _sa_ref(l, r):
    out = l.copy()
    out[0:2, 1:3] = r
    return out


case("_slice_assign_scalar",
     Case((np.zeros((3, 4), np.float32),),
          {"scalar": 5.0, "begin": (0, 1), "end": (2, 3)},
          ref=lambda l, scalar, begin, end: _sas_ref(l, 5.0)))


def _sas_ref(l, v):
    out = l.copy()
    out[0:2, 1:3] = v
    return out


case("BatchNorm",
     Case((N((4, 3, 2, 2), seed=168), np.ones(3, np.float32),
           np.zeros(3, np.float32), np.zeros(3, np.float32),
           np.ones(3, np.float32)),
          {"fix_gamma": False, "use_global_stats": True},
          ref=lambda x, g, b, mm, mv, **kw: x / np.sqrt(1 + 1e-3),
          grad=False),
     # train-mode stats on data with mean >> std: the shifted single-pass
     # variance must not cancel catastrophically (f32 E[x^2]-mean^2 would
     # return exactly 0 here)
     Case((N((64, 3, 4, 4), seed=169, scale=1.0).astype(np.float32)
           + 10000.0, np.ones(3, np.float32), np.zeros(3, np.float32),
           np.zeros(3, np.float32), np.ones(3, np.float32)),
          {"fix_gamma": False, "_training": True},
          ref=lambda x, g, b, mm, mv, **kw:
          x.var(axis=(0, 2, 3)).astype(np.float32),
          out_index=2, rtol=1e-2, atol=1e-3, grad=False))

# --------------------------------------------------------------------------
# linalg
# --------------------------------------------------------------------------
def _spd(n, seed):
    a = np.random.RandomState(seed).randn(n, n).astype(np.float32)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


case("_linalg_gemm",
     Case((N((3, 4), seed=170), N((4, 5), seed=171), N((3, 5), seed=172)),
          {"alpha": 2.0, "beta": 0.5},
          ref=lambda a, b, c, alpha, beta: alpha * (a @ b) + beta * c))
case("_linalg_gemm2",
     Case((N((3, 4), seed=173), N((4, 5), seed=174)), {"alpha": 1.5},
          ref=lambda a, b, alpha: alpha * (a @ b)))
case("_linalg_syrk",
     Case((N((3, 4), seed=175),), {"alpha": 1.0},
          ref=lambda a, alpha: a @ a.T))
case("_linalg_det",
     Case((_spd(3, 176),), ref=lambda a: np.array(np.linalg.det(a)),
          rtol=1e-3, grad_rtol=4e-2))
case("_linalg_slogdet",
     Case((_spd(3, 177),),
          ref=lambda a: np.array(np.linalg.slogdet(a)[0]), grad=False))
case("_linalg_inverse",
     Case((_spd(3, 178),), ref=np.linalg.inv, rtol=1e-3,
          grad_rtol=4e-2))
case("_linalg_potrf",
     Case((_spd(3, 179),), ref=np.linalg.cholesky, rtol=1e-3,
          grad_rtol=4e-2))
case("_linalg_potri",
     Case((np.linalg.cholesky(_spd(3, 180)).astype(np.float32),),
          ref=lambda l: np.linalg.inv(l @ l.T), rtol=1e-2,
          grad=False))
case("_linalg_trmm",
     Case((np.tril(N((3, 3), seed=181)).astype(np.float32),
           N((3, 4), seed=182)),
          ref=lambda a, b: a @ b))
case("_linalg_trsm",
     Case((np.tril(N((3, 3), seed=183) + 3 * np.eye(3,
                                                    dtype=np.float32)),
           N((3, 4), seed=184)),
          ref=lambda a, b: np.linalg.solve(a, b), rtol=1e-3))
case("_linalg_sumlogdiag",
     Case((_spd(3, 185),),
          ref=lambda a: np.array(np.log(np.diag(a)).sum())))
case("_linalg_extractdiag",
     Case((N((3, 3), seed=186),), ref=lambda a: np.diag(a)))
case("_linalg_makediag",
     Case((N((3,), seed=187),), ref=lambda a: np.diag(a)))
case("_linalg_extracttrian",
     Case((N((3, 3), seed=188),),
          ref=lambda a: a[np.tril_indices(3)]))
case("_linalg_maketrian",
     Case((N((6,), seed=189),), ref=lambda a: _maketrian_ref(a)))


def _maketrian_ref(a):
    out = np.zeros((3, 3), np.float32)
    out[np.tril_indices(3)] = a
    return out


case("_linalg_syevd", Case((_spd(3, 190),), grad=False))
case("_linalg_gelqf", Case((N((2, 4), seed=191),), grad=False))
case("histogram",
     Case((U(0, 10, (20,), seed=192),), {"bin_cnt": 5, "range": (0, 10)},
          ref=lambda x, bin_cnt, range:
          np.histogram(x, bins=5, range=(0, 10))[0].astype(np.float32),
          grad=False))
case("moments",
     Case((N((3, 4), seed=193),), {"axes": (1,)},
          ref=lambda x, axes: x.mean(axis=1)))

# --------------------------------------------------------------------------
# contrib
# --------------------------------------------------------------------------
case("_contrib_quadratic",
     Case((N(seed=200),), {"a": 2.0, "b": 3.0, "c": 1.0},
          ref=lambda x, a, b, c: a * x * x + b * x + c))
# gradientmultiplier: identity forward, backward scales the gradient by
# `scalar` ON PURPOSE — finite differences of the forward do not apply
case("_contrib_gradientmultiplier",
     Case((N(seed=201),), {"scalar": 2.0},
          ref=lambda x, scalar: x, grad=False))
case("_contrib_index_array",
     Case((N((2, 3), seed=202),),
          ref=lambda x: np.stack(np.meshgrid(np.arange(2), np.arange(3),
                                             indexing="ij"),
                                 -1).astype(np.int64),
          grad=False))
case("_contrib_index_copy",
     Case((np.zeros((4, 3), np.float32), np.array([1, 3], np.int32),
           np.ones((2, 3), np.float32)),
          ref=lambda o, i, n: _idxcopy_ref(o, i, n), grad=False))


def _idxcopy_ref(o, i, n):
    out = o.copy()
    out[i] = n
    return out


case("_contrib_boolean_mask",
     Case((N((4, 3), seed=203), np.array([1, 0, 1, 0], np.float32)),
          grad=False))
case("_contrib_box_iou",
     Case((np.array([[0, 0, 2, 2]], np.float32),
           np.array([[1, 1, 3, 3]], np.float32)),
          ref=lambda a, b, **kw: np.array([[1.0 / 7.0]], np.float32),
          grad=False))
case("_contrib_arange_like",
     Case((N((2, 3), seed=204),),
          ref=lambda x: np.arange(6, dtype=np.float32).reshape(2, 3),
          grad=False))
case("_contrib_count_sketch",
     Case((N((2, 8), seed=205), U(0, 4, (8,), seed=206),
           np.sign(N((8,), seed=207)).astype(np.float32)),
          {"out_dim": 4}, grad=False))
case("AdaptiveAvgPooling2D",
     Case((N((1, 2, 4, 4), seed=208),), {"output_size": (2, 2)},
          ref=lambda x, output_size:
          x.reshape(1, 2, 2, 2, 2, 2).mean((3, 5))))
case("BilinearResize2D",
     Case((N((1, 2, 3, 3), seed=209),), {"height": 6, "width": 6},
          grad_rtol=4e-2))
# _quantized_fc_static and the _contrib_quantized_* family are covered by
# tests/test_quantization_ops.py (int8 pipeline roundtrips)

case("ROIAlign",
     # one ROI covering the full 4x4 map, 2x2 output, aligned sampling:
     # gradient flows through bilinear weights (rois not differentiable)
     Case((N((1, 2, 4, 4), seed=216),
           np.array([[0, 0, 0, 3, 3]], np.float32)),
          {"pooled_size": (2, 2), "spatial_scale": 1.0,
           "sample_ratio": 1},
          grad_only=(0,), grad_rtol=4e-2))
case("MultiBoxPrior",
     Case((N((1, 3, 2, 2), seed=213),),
          {"sizes": (0.5,), "ratios": (1.0,)},
          ref=lambda x, sizes, ratios: _mbprior_ref(2, 2, 0.5),
          grad=False))


def _mbprior_ref(h, w, size):
    out = []
    for i in range(h):
        for j in range(w):
            cy, cx = (i + 0.5) / h, (j + 0.5) / w
            out.append([cx - size / 2, cy - size / 2,
                        cx + size / 2, cy + size / 2])
    return np.array(out, np.float32)[None]


case("_contrib_box_nms",
     Case((np.array([[[0, 0.9, 0, 0, 2, 2],
                      [0, 0.8, 0.1, 0.1, 2, 2],
                      [0, 0.7, 5, 5, 7, 7]]], np.float32),),
          {"overlap_thresh": 0.5},
          ref=lambda d, overlap_thresh: _nms_ref(d), grad=False))


def _nms_ref(d):
    # box 1 overlaps box 0 (IoU > 0.5) -> suppressed: the whole entry is
    # overwritten with -1 (ref: box_nms forward marks all fields)
    out = d.copy()
    out[0, 1, :] = -1
    return out


case("_rnn_state_zeros",
     Case((N((5, 2, 3), seed=214),), {"num_states": 1, "state_size": 4},
          ref=lambda x, num_states, state_size:
          np.zeros((1, 2, 4), np.float32), grad=False))
case("_state_zeros",
     Case((N((2, 5, 3), seed=215),), {"num_hidden": 4, "batch_axis": 0},
          ref=lambda x, num_hidden, batch_axis:
          np.zeros((2, 4), np.float32), grad=False))

# --------------------------------------------------------------------------
# creation / internal (forward-only sanity)
# --------------------------------------------------------------------------
case("_zeros_without_dtype",
     Case((), {"shape": (2, 3)},
          ref=lambda shape: np.zeros((2, 3), np.float32), grad=False))


# --------------------------------------------------------------------------
# ops proven by dedicated test files (file must mention the op)
# --------------------------------------------------------------------------
COVERED_ELSEWHERE = {
    # optimizer kernels: tests/test_optimizer_rules.py exercises every rule
    **{op: "tests/test_optimizer_rules.py" for op in [
        "sgd_update", "sgd_mom_update", "mp_sgd_update",
        "mp_sgd_mom_update", "adam_update", "nag_mom_update",
        "rmsprop_update", "rmspropalex_update", "ftrl_update",
        "ftml_update", "signsgd_update", "signum_update",
        "multi_sgd_update", "multi_sgd_mom_update", "multi_mp_sgd_update",
        "multi_mp_sgd_mom_update", "_adamw_update", "_mp_adamw_update",
        "_sparse_adagrad_update", "_contrib_group_adagrad_update"]},
    # aggregated multi-tensor update family (beyond SGD): parity vs the
    # single-tensor kernels in the aggregation suite
    **{op: "tests/test_optimizer_aggregation.py" for op in [
        "multi_adam_update", "multi_nag_mom_update",
        "multi_rmsprop_update"]},
    # random samplers: distribution tests
    **{op: "tests/test_operator_extended.py" for op in [
        "_random_uniform", "_random_normal", "_random_gamma",
        "_random_exponential", "_random_poisson",
        "_random_negative_binomial",
        "_random_generalized_negative_binomial", "_random_randint",
        "_sample_uniform", "_sample_normal", "_sample_gamma",
        "_sample_exponential", "_sample_poisson", "_sample_multinomial",
        "_shuffle", "sample_unique_zipfian"]},
    # image ops
    **{op: "tests/test_image_ops.py" for op in [
        "_image_adjust_lighting", "_image_flip_left_right",
        "_image_flip_top_bottom", "_image_normalize",
        "_image_random_brightness", "_image_random_color_jitter",
        "_image_random_contrast", "_image_random_flip_left_right",
        "_image_random_flip_top_bottom", "_image_random_hue",
        "_image_random_lighting", "_image_random_saturation",
        "_image_resize", "_image_to_tensor"]},
    # rcnn / detection
    **{op: "tests/test_rcnn_ops.py" for op in [
        "Proposal", "MultiProposal", "PSROIPooling",
        "DeformablePSROIPooling", "_contrib_bipartite_matching"]},
    # vision extras
    **{op: "tests/test_vision_ops.py" for op in [
        "Correlation", "DeformableConvolution", "_contrib_fft",
        "_contrib_ifft", "_contrib_count_sketch",
        "MultiBoxTarget", "MultiBoxDetection"]},
    "ROIPooling": "tests/test_rcnn_ops.py",
    "SpatialTransformer": "tests/test_operator_extended.py",
    "BilinearSampler": "tests/test_operator_extended.py",
    # rnn stack
    "RNN": "tests/test_rnn.py",
    # quantization
    **{op: "tests/test_quantization_ops.py" for op in [
        "_contrib_quantize", "_contrib_quantize_v2", "_contrib_dequantize",
        "_contrib_requantize", "_contrib_quantized_conv",
        "_contrib_quantized_fully_connected", "_contrib_quantized_pooling",
        "_contrib_quantized_concat", "_contrib_quantized_flatten",
        "_quantized_fc_static", "_quantize_static", "_quantized_conv_v2",
        "_quantized_dense_v2"]},
    # pallas attention kernels
    **{op: "tests/test_pallas_ops.py" for op in [
        "_contrib_flash_attention", "_contrib_interleaved_matmul_selfatt_qk",
        "_contrib_interleaved_matmul_selfatt_valatt"]},
    # pallas fused conv epilogues (fwd+grad parity, fallback, fold)
    **{op: "tests/test_fused_epilogue.py" for op in [
        "_contrib_fused_bn_relu", "_contrib_fused_bn_add_relu"]},
    # symbolic control flow + graph-level sparse ops
    **{op: "tests/test_symbol_control_flow.py" for op in [
        "_foreach", "_while_loop", "_cond", "cast_storage",
        "sparse_retain", "_square_sum"]},
    # DGL graph-sampling family (host-side csr algorithms)
    **{op: "tests/test_graph_ops.py" for op in [
        "_contrib_dgl_adjacency", "_contrib_dgl_subgraph",
        "_contrib_dgl_csr_neighbor_uniform_sample",
        "_contrib_dgl_csr_neighbor_non_uniform_sample",
        "_contrib_dgl_graph_compact", "_contrib_edge_id"]},
    # round-4 tail closure: init ops, sampler-_like family, lazy sparse
    # updates, sparse containers (VERDICT r3 directive #3)
    **{op: "tests/test_op_tail.py" for op in [
        "_zeros", "_ones", "_full", "_eye", "_arange", "_grad_add",
        "_contrib_div_sqrt_dim", "_random_uniform_like",
        "_random_normal_like", "_random_exponential_like",
        "_random_gamma_like", "_random_poisson_like",
        "_random_negative_binomial_like",
        "_random_generalized_negative_binomial_like",
        "_sparse_sgd_update", "_sparse_sgd_mom_update",
        "_sparse_adam_update", "_sparse_retain", "_contrib_getnnz"]},
    # misc dedicated files
    "CTCLoss": "tests/test_ctc.py",
    "Custom": "tests/test_custom_op.py",
    "_subgraph": "tests/test_subgraph.py",
    "_index": "tests/test_ndarray.py",
    "_index_assign": "tests/test_ndarray.py",
    "_index_assign_scalar": "tests/test_ndarray.py",
    "SyncBatchNorm": "tests/test_gluon_contrib.py",
    "Dropout": "tests/test_gluon.py",
}
