"""IO + RecordIO tests (model: tests/python/unittest/test_recordio.py,
test_io.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd
from mxnet_tpu import recordio
from mxnet_tpu.io import NDArrayIter, CSVIter, ResizeIter, PrefetchingIter
from mxnet_tpu.io.record_io import RecordPipeline, native_available


def test_ndarray_iter_basic():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = NDArrayIter(data, label, batch_size=4, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (4, 4)
    assert batches[2].pad == 2
    it.reset()
    assert len(list(it)) == 3


def test_ndarray_iter_discard_and_shuffle():
    data = np.arange(30).reshape(10, 3).astype(np.float32)
    it = NDArrayIter(data, None, batch_size=4, shuffle=True,
                     last_batch_handle="discard")
    batches = list(it)
    assert len(batches) == 2


def test_csv_iter(tmp_path):
    f = str(tmp_path / "data.csv")
    np.savetxt(f, np.arange(12).reshape(4, 3), delimiter=",")
    it = CSVIter(data_csv=f, data_shape=(3,), batch_size=2)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (2, 3)


def test_resize_iter():
    data = np.zeros((8, 2), np.float32)
    it = ResizeIter(NDArrayIter(data, None, batch_size=2), size=7)
    assert len(list(it)) == 7


def test_prefetching_iter():
    data = np.arange(16).reshape(8, 2).astype(np.float32)
    base = NDArrayIter(data, np.zeros(8, np.float32), batch_size=2)
    it = PrefetchingIter(base)
    batches = list(it)
    assert len(batches) == 4


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "test.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(20):
        w.write(f"record-{i}".encode() * (i + 1))
    w.close()
    r = recordio.MXRecordIO(path, "r")
    for i in range(20):
        rec = r.read()
        assert rec == f"record-{i}".encode() * (i + 1)
    assert r.read() is None
    r.close()


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "test.rec")
    idx_path = str(tmp_path / "test.idx")
    w = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(10):
        w.write_idx(i, f"item{i}".encode())
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, path, "r")
    assert r.read_idx(7) == b"item7"
    assert r.read_idx(2) == b"item2"
    assert len(r.keys) == 10
    r.close()


def test_pack_unpack():
    h = recordio.IRHeader(0, 3.0, 7, 0)
    s = recordio.pack(h, b"payload")
    h2, payload = recordio.unpack(s)
    assert payload == b"payload"
    assert h2.label == 3.0
    assert h2.id == 7
    # vector label
    h = recordio.IRHeader(0, [1.0, 2.0, 3.0], 1, 0)
    h2, payload = recordio.unpack(recordio.pack(h, b"x"))
    assert list(h2.label) == [1, 2, 3]


def test_pack_img_roundtrip():
    img = (np.random.RandomState(0).rand(4, 5, 3) * 255).astype(np.uint8)
    # png is lossless -> exact roundtrip; jpg would be approximate
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                          img_fmt=".png")
    h, img2 = recordio.unpack_img(s)
    assert np.array_equal(img, img2)
    s = recordio.pack_img(recordio.IRHeader(0, 1.0, 0, 0), img,
                          img_fmt=".jpg", quality=95)
    h, img3 = recordio.unpack_img(s)
    assert img3.shape == img.shape


@pytest.mark.skipif(not native_available(), reason="native lib not built")
def test_native_pipeline(tmp_path):
    path = str(tmp_path / "pipe.rec")
    w = recordio.MXRecordIO(path, "w")
    n = 100
    for i in range(n):
        w.write(struct_pack_i(i))
    w.close()
    pipe = RecordPipeline(path, num_threads=3)
    assert len(pipe) == n
    seen = set()
    while True:
        rec = pipe.next()
        if rec is None:
            break
        seen.add(int.from_bytes(rec[:4], "little"))
    assert seen == set(range(n))
    # reset -> second epoch works
    pipe.reset()
    count = 0
    while pipe.next() is not None:
        count += 1
    assert count == n
    pipe.close()


@pytest.mark.skipif(not native_available(), reason="native lib not built")
def test_native_pipeline_sharding(tmp_path):
    path = str(tmp_path / "shard.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(10):
        w.write(struct_pack_i(i))
    w.close()
    all_seen = set()
    for part in range(2):
        pipe = RecordPipeline(path, num_threads=1, part_index=part,
                              num_parts=2)
        while True:
            rec = pipe.next()
            if rec is None:
                break
            all_seen.add(int.from_bytes(rec[:4], "little"))
        pipe.close()
    assert all_seen == set(range(10))


def struct_pack_i(i):
    return i.to_bytes(4, "little") + b"data" * 10


def test_device_staging_iter():
    """DeviceStagingIter: batches come out device-committed one step
    ahead (the pinned-memory H2D staging analog, iter_prefetcher.h +
    pinned_memory_storage.h)."""
    import jax
    import numpy as np
    from mxnet_tpu.io import DeviceStagingIter, NDArrayIter

    rs = np.random.RandomState(0)
    X = rs.randn(40, 3).astype(np.float32)
    Y = rs.randint(0, 4, 40).astype(np.float32)
    base = NDArrayIter(X, Y, batch_size=8)
    it = DeviceStagingIter(base, depth=2)
    dev = jax.devices()[0]
    seen = []
    for batch in it:
        arr = batch.data[0]._data
        assert dev in arr.devices(), "batch not device-committed"
        seen.append(batch.data[0].asnumpy())
    assert len(seen) == 5
    np.testing.assert_allclose(np.concatenate(seen), X, rtol=1e-6)
    # reset replays from the start
    it.reset()
    first = next(it).data[0].asnumpy()
    np.testing.assert_allclose(first, X[:8], rtol=1e-6)
    # a trainer consumes staged batches end to end
    import mxnet_tpu as mx
    from mxnet_tpu import autograd, gluon, nd
    net = gluon.nn.Dense(4)
    net.initialize(mx.init.Xavier())
    with autograd.pause():
        net(nd.ones((1, 3)))
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    lossfn = gluon.loss.SoftmaxCrossEntropyLoss()
    it.reset()
    for batch in it:
        with autograd.record():
            l = lossfn(net(batch.data[0]), batch.label[0]).mean()
        l.backward()
        tr.step(8)
    assert np.isfinite(float(l.asnumpy()))


def test_device_staging_iter_ctx_matches_device():
    """Staged batches carry a Context matching the actual device, so
    ctx-driven scalar placement doesn't mix commitments."""
    import numpy as np
    from mxnet_tpu.io import DeviceStagingIter, NDArrayIter
    X = np.ones((8, 3), np.float32)
    it = DeviceStagingIter(NDArrayIter(X, None, batch_size=8))
    batch = next(it)
    d = batch.data[0]
    assert d.context.jax_device in d._data.devices(), \
        (d.context, d._data.devices())
    # mixed scalar arithmetic works (would raise on a ctx mismatch)
    out = (d / 2.0 + 1.0).asnumpy()
    np.testing.assert_allclose(out, 1.5)
