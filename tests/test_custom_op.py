"""Custom Python operator host tests.

Mirrors the reference's custom-op coverage
(ref: tests/python/unittest/test_operator.py test_custom_op — sqr op with
numeric gradient check, multi-output, aux states, Gluon/symbol use).
"""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd


@mx.operator.register("sqr")
class SqrProp(mx.operator.CustomOpProp):
    def __init__(self, scale="1.0"):
        super().__init__(need_top_grad=True)
        self.scale = float(scale)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return Sqr(self.scale)


class Sqr(mx.operator.CustomOp):
    def __init__(self, scale):
        self.scale = scale

    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0] * in_data[0] * self.scale)

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0],
                    2 * self.scale * in_data[0] * out_grad[0])


def test_custom_forward():
    x = mx.nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = mx.nd.Custom(x, op_type="sqr")
    np.testing.assert_allclose(y.asnumpy(), x.asnumpy() ** 2, rtol=1e-6)


def test_custom_param_kwarg():
    x = mx.nd.array(np.full((3,), 2.0, np.float32))
    y = mx.nd.Custom(x, op_type="sqr", scale=3.0)
    np.testing.assert_allclose(y.asnumpy(), 12.0 * np.ones(3), rtol=1e-6)


def test_custom_backward():
    x = mx.nd.array(np.array([1.0, -2.0, 3.0], np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="sqr")
        loss = y.sum()
    loss.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), 2 * x.asnumpy(), rtol=1e-6)


def test_custom_unregistered_raises():
    x = mx.nd.zeros((2,))
    with pytest.raises(mx.MXNetError):
        mx.nd.Custom(x, op_type="not_a_real_op")


@mx.operator.register("twosum")
class TwoSumProp(mx.operator.CustomOpProp):
    def list_arguments(self):
        return ["a", "b"]

    def list_outputs(self):
        return ["sum", "diff"]

    def infer_shape(self, in_shape):
        return in_shape, [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return TwoSum()


class TwoSum(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        self.assign(out_data[0], req[0], in_data[0] + in_data[1])
        self.assign(out_data[1], req[1], in_data[0] - in_data[1])

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0], out_grad[0] + out_grad[1])
        self.assign(in_grad[1], req[1], out_grad[0] - out_grad[1])


def test_custom_multi_io():
    a = mx.nd.array(np.array([1.0, 2.0], np.float32))
    b = mx.nd.array(np.array([10.0, 20.0], np.float32))
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        s, d = mx.nd.Custom(a, b, op_type="twosum")
        loss = (s * 2).sum() + d.sum()
    np.testing.assert_allclose(s.asnumpy(), [11.0, 22.0])
    np.testing.assert_allclose(d.asnumpy(), [-9.0, -18.0])
    loss.backward()
    np.testing.assert_allclose(a.grad.asnumpy(), [3.0, 3.0])
    np.testing.assert_allclose(b.grad.asnumpy(), [1.0, 1.0])


def test_custom_in_hybrid_block():
    class Net(mx.gluon.HybridBlock):
        def hybrid_forward(self, F, x):
            return F.Custom(x, op_type="sqr")

    for hybridize in (False, True):
        net = Net()
        if hybridize:
            net.hybridize()
        x = mx.nd.array(np.array([1.0, 2.0, 4.0], np.float32))
        y = net(x)
        np.testing.assert_allclose(y.asnumpy(), [1.0, 4.0, 16.0], rtol=1e-6)


def test_custom_symbolic():
    data = mx.sym.Variable("data")
    out = mx.sym.Custom(data, op_type="sqr", name="sq")
    ex = out.simple_bind(mx.cpu(), data=(2, 2))
    ex.forward(is_train=False, data=mx.nd.array(np.full((2, 2), 3.0)))
    np.testing.assert_allclose(ex.outputs[0].asnumpy(), np.full((2, 2), 9.0),
                               rtol=1e-6)


def test_custom_default_backward_zero():
    @mx.operator.register("fwdonly")
    class FwdOnlyProp(mx.operator.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return FwdOnly()

    class FwdOnly(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], in_data[0] * 5)

    x = mx.nd.array(np.ones((3,), np.float32))
    x.attach_grad()
    with autograd.record():
        y = mx.nd.Custom(x, op_type="fwdonly")
        y.sum().backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.zeros(3))
