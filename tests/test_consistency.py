"""Cross-dtype consistency sweep (ref: test_utils.py:1224
check_consistency — the same computation cross-checked over ctx/dtype
combos; the GPU suite re-runs the CPU suite this way by construction)."""
import numpy as np
import pytest

from mxnet_tpu import nd
from mxnet_tpu.test_utils import check_consistency


def _r(shape, seed=0, scale=1.0):
    return (np.random.RandomState(seed).randn(*shape) * scale) \
        .astype(np.float32)


CASES = [
    ("conv", lambda x, w: nd.Convolution(x, w, kernel=(3, 3), num_filter=8,
                                         pad=(1, 1), no_bias=True),
     [_r((2, 4, 8, 8)), _r((8, 4, 3, 3), 1, 0.3)]),
    ("fc", lambda x, w, b: nd.FullyConnected(x, w, b, num_hidden=8),
     [_r((4, 6)), _r((8, 6), 1, 0.3), _r((8,), 2, 0.1)]),
    ("pool", lambda x: nd.Pooling(x, kernel=(2, 2), stride=(2, 2),
                                  pool_type="avg"),
     [_r((2, 3, 8, 8))]),
    ("softmax", lambda x: nd.softmax(x, axis=-1), [_r((4, 10))]),
    ("layernorm", lambda x, g, b: nd.LayerNorm(x, g, b)[0],
     [_r((4, 8)), np.ones(8, np.float32), np.zeros(8, np.float32)]),
    ("dot", lambda a, b: nd.dot(a, b), [_r((4, 6)), _r((6, 3), 1)]),
    ("sort", lambda x: nd.sort(x, axis=-1), [_r((4, 10))]),
    ("norm", lambda x: nd.norm(x), [_r((4, 10))]),
    ("take", lambda x: nd.take(x, nd.array(np.array([0., 2., 1.]))),
     [_r((4, 5))]),
]


@pytest.mark.parametrize("name,fn,inputs", CASES,
                         ids=[c[0] for c in CASES])
def test_f32_f64_consistency(name, fn, inputs):
    check_consistency(fn, inputs, dtypes=(np.float32, np.float64),
                      rtol=1e-3, atol=1e-4)


def test_bf16_f32_consistency_looser():
    """bf16 runs of the same net must track f32 within bf16 precision."""
    import jax.numpy as jnp
    x = _r((2, 4, 8, 8), 3)
    w = _r((8, 4, 3, 3), 4, 0.3)
    f32 = nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                         num_filter=8, pad=(1, 1), no_bias=True).asnumpy()
    bf = nd.Convolution(nd.array(x).astype(jnp.bfloat16),
                        nd.array(w).astype(jnp.bfloat16), kernel=(3, 3),
                        num_filter=8, pad=(1, 1),
                        no_bias=True).astype(np.float32).asnumpy()
    np.testing.assert_allclose(f32, bf, rtol=5e-2, atol=5e-2)
