"""runtime.Features, SequentialModule, pallas flash attention numerics
(surfaces with no direct coverage)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import assert_almost_equal


def test_runtime_features():
    feats = mx.runtime.Features()
    assert len(feats) > 0
    assert feats.is_enabled("TPU") or feats.is_enabled("CPU")
    # feature flags the reference exposes must at least be queryable
    for name in ("CUDA", "CUDNN", "MKLDNN"):
        assert isinstance(feats.is_enabled(name), bool)


def test_flash_attention_matches_reference_softmax():
    import jax.numpy as jnp
    rs = np.random.RandomState(0)
    B, T, H, D = 2, 16, 2, 8
    q = rs.randn(B, T, H, D).astype(np.float32) * 0.5
    k = rs.randn(B, T, H, D).astype(np.float32) * 0.5
    v = rs.randn(B, T, H, D).astype(np.float32) * 0.5

    out = nd.contrib.flash_attention(nd.array(q), nd.array(k),
                                     nd.array(v)).asnumpy()

    def ref_attn(q, k, v, causal=False):
        scale = 1.0 / np.sqrt(D)
        o = np.zeros_like(q)
        for b in range(B):
            for h in range(H):
                logits = q[b, :, h] @ k[b, :, h].T * scale
                if causal:
                    mask = np.tril(np.ones((T, T), bool))
                    logits = np.where(mask, logits, -1e30)
                p = np.exp(logits - logits.max(axis=-1, keepdims=True))
                p /= p.sum(axis=-1, keepdims=True)
                o[b, :, h] = p @ v[b, :, h]
        return o

    assert_almost_equal(out, ref_attn(q, k, v), rtol=1e-4, atol=1e-4)
    out_c = nd.contrib.flash_attention(nd.array(q), nd.array(k),
                                       nd.array(v), causal=True).asnumpy()
    assert_almost_equal(out_c, ref_attn(q, k, v, causal=True),
                        rtol=1e-4, atol=1e-4)


def test_flash_attention_gradients():
    from mxnet_tpu import autograd
    rs = np.random.RandomState(1)
    q = nd.array(rs.randn(1, 8, 1, 4).astype(np.float32))
    k = nd.array(rs.randn(1, 8, 1, 4).astype(np.float32))
    v = nd.array(rs.randn(1, 8, 1, 4).astype(np.float32))
    for x in (q, k, v):
        x.attach_grad()
    with autograd.record():
        o = nd.contrib.flash_attention(q, k, v)
        (o * o).sum().backward()
    for x in (q, k, v):
        g = x.grad.asnumpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


def test_sequential_module():
    from mxnet_tpu.module import Module, SequentialModule
    from mxnet_tpu.io import NDArrayIter
    rs = np.random.RandomState(0)
    x = rs.randn(32, 6).astype(np.float32)
    w = rs.randn(6).astype(np.float32)
    y = (x @ w > 0).astype(np.float32)

    data = sym.var("data")
    net1 = sym.FullyConnected(data, sym.var("fc1_weight"),
                              sym.var("fc1_bias"), num_hidden=8,
                              name="fc1")
    net1 = sym.Activation(net1, act_type="relu")
    net2_in = sym.var("data")
    net2 = sym.FullyConnected(net2_in, sym.var("fc2_weight"),
                              sym.var("fc2_bias"), num_hidden=2,
                              name="fc2")
    net2 = sym.SoftmaxOutput(net2, sym.var("softmax_label"),
                             name="softmax")

    seq = SequentialModule()
    seq.add(Module(net1, label_names=[]))
    seq.add(Module(net2), take_labels=True, auto_wiring=True)

    train = NDArrayIter(x, y, batch_size=8, shuffle=False,
                        label_name="softmax_label")
    seq.fit(train, num_epoch=4, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1})
    score = dict(seq.score(train, "acc"))
    acc = score.get("accuracy", score.get("acc", 0))
    assert acc > 0.6, score


def test_storage_surface():
    """mx.storage: allocator observability over PJRT (the storage-manager
    introspection analog, pooled_storage_manager.h /
    MXGetGPUMemoryInformation64)."""
    import mxnet_tpu as mx
    from mxnet_tpu.base import MXNetError
    stats = mx.storage.memory_stats()
    assert isinstance(stats, dict)  # may be {} on host backends
    # branch on memory_info's OWN success condition
    if stats.get("bytes_limit") is not None and \
            stats.get("bytes_in_use") is not None:
        free, total = mx.storage.memory_info()
        assert 0 <= free <= total
    else:
        import pytest
        with pytest.raises(MXNetError):
            mx.storage.memory_info()
    mx.storage.empty_cache()  # never raises


def test_gpu_memory_info_parity_surface():
    """mx.context.gpu_memory_info maps to storage.memory_info (raises on
    backends without accounting, like the reference on CPU builds)."""
    import mxnet_tpu as mx
    from mxnet_tpu.base import MXNetError
    stats = mx.storage.memory_stats(mx.gpu(0))
    if stats.get("bytes_limit") is not None and \
            stats.get("bytes_in_use") is not None:
        free, total = mx.gpu_memory_info(0)
        assert 0 <= free <= total
    else:
        import pytest
        with pytest.raises(MXNetError):
            mx.gpu_memory_info(0)
